"""Response writers.

Reference parity: servlet/response/ (ResponseUtils version envelope,
BrokerStats for LOAD, PartitionLoadState for PARTITION_LOAD,
ClusterBrokerState for KAFKA_CLUSTER_STATE, OptimizationResult for
proposal-bearing endpoints). All JSON; the reference's plaintext variants
are served by the same dicts pretty-printed.
"""

from __future__ import annotations

import numpy as np

from ..analyzer.optimizer import OptimizerResult
from ..common.resources import Resource
from ..executor.admin import AdminBackend
from ..facade import OperationResult
from ..model.tensors import (
    ClusterMeta, ClusterTensors, broker_leader_counts, broker_load,
    broker_replica_counts, leader_bytes_in, potential_nw_out, replica_load,
)


def _num_cores(cpu_capacity_pct: float) -> int:
    """NumCore from the CPU capacity column. The reference carries an
    explicit core count from its BrokerCapacityConfigResolver; this model
    expresses CPU capacity in percent-of-machine (100.0 = the whole
    broker), so cores are DERIVED as capacity/100 — see docs/DESIGN.md
    ("LOAD response wire-format notes"). Zero capacity = zero cores (the
    floor of 1 applies only to brokers with SOME capacity, so dead-weight
    rows cannot inflate a mixed host's total)."""
    if cpu_capacity_pct <= 0:
        return 0
    return max(1, int(round(cpu_capacity_pct / 100.0)))

JSON_VERSION = 1


def envelope(payload: dict) -> dict:
    return {"version": JSON_VERSION, **payload}


def broker_capacities(admin, capacity_resolver) -> dict:
    """LOAD?capacity_only=true body: per-broker capacities straight from
    the capacity config — no metric model required (ParameterUtils
    capacityOnly excludes the time/model params)."""
    rows = []
    for bid in sorted(admin.alive_brokers()):
        caps = capacity_resolver.capacity_for(bid)
        rows.append({
            "Broker": bid,
            "DiskMB": round(float(caps[Resource.DISK]), 3),
            "CpuPct": round(float(caps[Resource.CPU]), 3),
            "NwInRate": round(float(caps[Resource.NW_IN]), 3),
            "NwOutRate": round(float(caps[Resource.NW_OUT]), 3),
            "DiskCapacityByLogdir":
                capacity_resolver.disk_capacity_by_logdir(bid),
            "Estimated": bool(getattr(capacity_resolver, "is_estimated",
                                      lambda _b: False)(bid)),
        })
    # capacity_only bypasses the model entirely (admin + capacity config
    # only), and the admin surface carries no host topology — host rows
    # exist on the model-backed LOAD path (broker_stats below).
    return envelope({"brokers": rows, "hosts": []})


def _host_name(meta: ClusterMeta, h: int) -> str:
    if 0 <= h < len(meta.host_names):
        return meta.host_names[h]
    return f"host-{h}"  # builder predates host topology / fixture default


def _host_rows(state: ClusterTensors, meta: ClusterMeta, loads, caps,
               replicas, leaders, pnw, lead_in, mask) -> list[dict]:
    """Per-host aggregate rows (BrokerStats.java host section /
    model/Host.java:275): every stat summed over the host's brokers,
    utilization pct over the host's summed capacity."""
    hosts = np.asarray(state.host)[mask]
    uniq, inv = np.unique(hosts, return_inverse=True)
    n = len(uniq)

    def by_host(col):
        return np.bincount(inv, weights=col, minlength=n)

    load = {r: by_host(loads[mask, int(r)]) for r in
            (Resource.DISK, Resource.CPU, Resource.NW_IN, Resource.NW_OUT)}
    disk_cap = by_host(caps[mask, int(Resource.DISK)])
    nw_in_cap = by_host(caps[mask, int(Resource.NW_IN)])
    nw_out_cap = by_host(caps[mask, int(Resource.NW_OUT)])
    h_pnw = by_host(np.asarray(pnw, dtype=np.float64)[mask])
    h_lead_in = by_host(np.asarray(lead_in, dtype=np.float64)[mask])
    h_replicas = by_host(np.asarray(replicas, dtype=np.float64)[mask])
    h_leaders = by_host(np.asarray(leaders, dtype=np.float64)[mask])
    with np.errstate(divide="ignore", invalid="ignore"):
        disk_pct = np.where(disk_cap > 0,
                            100.0 * load[Resource.DISK] / disk_cap, 0.0)
    return [{
        "Host": _host_name(meta, int(uniq[i])),
        "DiskMB": round(float(load[Resource.DISK][i]), 3),
        "DiskPct": round(float(disk_pct[i]), 3),
        "CpuPct": round(float(load[Resource.CPU][i]), 3),
        "LeaderNwInRate": round(float(h_lead_in[i]), 3),
        "FollowerNwInRate": round(
            float(load[Resource.NW_IN][i] - h_lead_in[i]), 3),
        "NwOutRate": round(float(load[Resource.NW_OUT][i]), 3),
        "PnwOutRate": round(float(h_pnw[i]), 3),
        "Replicas": int(h_replicas[i]),
        "Leaders": int(h_leaders[i]),
        "DiskCapacityMB": round(float(disk_cap[i]), 3),
        "NetworkInCapacity": round(float(nw_in_cap[i]), 3),
        "NetworkOutCapacity": round(float(nw_out_cap[i]), 3),
        "NumCore": sum(_num_cores(float(c))
                       for c in caps[mask, int(Resource.CPU)][inv == i]),
    } for i in range(n)]


def broker_stats(state: ClusterTensors, meta: ClusterMeta,
                 disk_info=None) -> dict:
    """LOAD endpoint body (response/stats/BrokerStats.java).
    ``disk_info`` = (logdirs_by_broker, capacity_resolver) adds per-logdir
    capacity + liveness per broker (populate_disk_info=true)."""
    from ..serving.journey import current_journey
    jny = current_journey()
    t0 = jny.now()
    loads = np.asarray(broker_load(state), dtype=np.float64)       # [B, R]
    caps = np.asarray(state.capacity, dtype=np.float64)
    with np.errstate(divide="ignore", invalid="ignore"):
        pct = np.where(caps > 0, 100.0 * loads / caps, 0.0)
    replicas = np.asarray(broker_replica_counts(state))
    leaders = np.asarray(broker_leader_counts(state))
    pnw = np.asarray(potential_nw_out(state))
    lead_in = np.asarray(leader_bytes_in(state), dtype=np.float64)
    states = np.asarray(state.broker_state)
    racks = np.asarray(state.rack)
    hosts = np.asarray(state.host)
    mask = np.asarray(state.broker_mask)
    from ..common.broker_state import BrokerState
    rows = []
    for i, bid in enumerate(meta.broker_ids):
        if not mask[i]:
            continue
        row = {
            "Broker": bid,
            "BrokerState": BrokerState(int(states[i])).name,
            "Rack": meta.rack_names[int(racks[i])],
            "Host": _host_name(meta, int(hosts[i])),
            "DiskMB": round(float(loads[i, Resource.DISK]), 3),
            "DiskPct": round(float(pct[i, Resource.DISK]), 3),
            "CpuPct": round(float(loads[i, Resource.CPU]), 3),
            # Reference wire format (BrokerStats.java): NW_IN is reported
            # split by replica role, not combined.
            "LeaderNwInRate": round(float(lead_in[i]), 3),
            "FollowerNwInRate": round(
                float(loads[i, Resource.NW_IN] - lead_in[i]), 3),
            "NwOutRate": round(float(loads[i, Resource.NW_OUT]), 3),
            "PnwOutRate": round(float(pnw[i]), 3),
            "Replicas": int(replicas[i]),
            "Leaders": int(leaders[i]),
            "DiskCapacityMB": round(float(caps[i, Resource.DISK]), 3),
            "NetworkInCapacity": round(float(caps[i, Resource.NW_IN]), 3),
            "NetworkOutCapacity": round(float(caps[i, Resource.NW_OUT]), 3),
            "NumCore": _num_cores(float(caps[i, Resource.CPU])),
        }
        if disk_info is not None:
            logdirs_by_broker, resolver = disk_info
            caps_by_dir = resolver.disk_capacity_by_logdir(bid) or {}
            alive_dirs = logdirs_by_broker.get(bid, {})
            row["DiskState"] = {
                d: {"DiskMB": round(float(caps_by_dir.get(d, 0.0)), 3),
                    "alive": bool(alive)}
                for d, alive in sorted(alive_dirs.items())} or {
                d: {"DiskMB": round(float(c), 3), "alive": True}
                for d, c in sorted(caps_by_dir.items())}
        rows.append(row)
    body = envelope({"brokers": rows,
                     "hosts": _host_rows(state, meta, loads, caps, replicas,
                                         leaders, pnw, lead_in, mask)})
    jny.add("render", jny.now() - t0, brokers=len(rows))
    return body


def partition_load(state: ClusterTensors, meta: ClusterMeta,
                   resource: str = "DISK", entries: int | None = None,
                   topic_rx: str | None = None,
                   partition_range: str | None = None,
                   brokerids: tuple[int, ...] = ()) -> dict:
    """PARTITION_LOAD body: partitions sorted by the requested resource,
    heaviest first (PartitionLoadState.java). ``topic_rx`` is a topic
    regex, ``partition_range`` a partition id or "start-end" range, and
    ``brokerids`` keeps only partitions with a replica on one of the
    brokers (ParameterUtils TOPIC/PARTITION/BROKER_ID params)."""
    from ..serving.journey import current_journey
    jny = current_journey()
    t0 = jny.now()
    aliases = {"NETWORK_INBOUND": "NW_IN", "NETWORK_OUTBOUND": "NW_OUT"}
    name = resource.upper()
    try:
        res = Resource[aliases.get(name, name)]
    except KeyError:
        from .parameters import ParameterParseError
        raise ParameterParseError(f"unknown resource {resource!r}")
    from .parameters import ParameterParseError
    rx = None
    if topic_rx:
        import re
        try:
            rx = re.compile(topic_rx)
        except re.error as e:
            raise ParameterParseError(f"bad topic regex {topic_rx!r}: {e}")
    p_lo = p_hi = None
    if partition_range:
        lo, sep, hi = partition_range.partition("-")
        try:
            p_lo = int(lo)
            p_hi = int(hi) if sep else p_lo
        except ValueError:
            raise ParameterParseError(
                f"bad partition range {partition_range!r} (want N or N-M)")
    want_brokers = {int(b) for b in brokerids}
    id_of = {bid: i for i, bid in enumerate(meta.broker_ids)}
    want_idx = {id_of[b] for b in want_brokers if b in id_of}
    per_slot = np.asarray(replica_load(state))          # [P, S, R]
    mask = np.asarray(state.partition_mask)
    leader_loads = np.asarray(state.leader_load)
    order = np.argsort(-leader_loads[:, res] * mask)
    assignment = np.asarray(state.assignment)
    leader_slot = np.asarray(state.leader_slot)
    records = []
    for p in order:
        if entries is not None and len(records) >= entries:
            break
        if not mask[p]:
            continue
        topic, part = meta.partition_index[int(p)]
        if rx is not None and not rx.fullmatch(topic):
            continue
        if p_lo is not None and not (p_lo <= part <= p_hi):
            continue
        if want_brokers and not any(int(b) in want_idx for b in assignment[p]
                                    if b >= 0):
            # Guard on the REQUESTED set: ids that don't resolve to model
            # brokers must filter everything out, not disable the filter.
            continue
        ls = int(leader_slot[p])
        leader_b = int(assignment[p, ls]) if 0 <= ls < assignment.shape[1] else -1
        followers = [int(meta.broker_ids[b]) for s, b in enumerate(assignment[p])
                     if b >= 0 and s != ls]
        records.append({
            "topic": topic, "partition": part,
            "leader": meta.broker_ids[leader_b] if leader_b >= 0 else -1,
            "followers": followers,
            "cpu": round(float(per_slot[p, :, Resource.CPU].sum()), 5),
            "disk": round(float(per_slot[p, :, Resource.DISK].sum()), 3),
            "networkInbound": round(float(per_slot[p, :, Resource.NW_IN].sum()), 3),
            "networkOutbound": round(float(per_slot[p, :, Resource.NW_OUT].sum()), 3),
        })
    body = envelope({"records": records})
    jny.add("render", jny.now() - t0, records=len(records))
    return body


def kafka_cluster_state(admin: AdminBackend, topic_filter: str = "") -> dict:
    """KAFKA_CLUSTER_STATE body (response/ClusterBrokerState.java): replica
    counts per broker + per-partition detail with URP/offline accounting."""
    parts = admin.describe_partitions()
    alive = admin.alive_brokers()
    replica_count: dict[int, int] = {}
    leader_count: dict[int, int] = {}
    out_of_sync: dict[str, list[int]] = {}
    offline: dict[str, list[int]] = {}
    partitions = []
    for (topic, p), st in sorted(parts.items()):
        if topic_filter and topic != topic_filter:
            continue
        for b in st.replicas:
            replica_count[b] = replica_count.get(b, 0) + 1
        if st.leader >= 0:
            leader_count[st.leader] = leader_count.get(st.leader, 0) + 1
        osr = [b for b in st.replicas if b not in st.isr]
        off = [b for b in st.replicas if b not in alive]
        key = f"{topic}-{p}"
        if osr:
            out_of_sync[key] = osr
        if off:
            offline[key] = off
        partitions.append({"topic": topic, "partition": p,
                           "leader": st.leader, "replicas": list(st.replicas),
                           "in-sync": list(st.isr), "out-of-sync": osr,
                           "offline": off})
    return envelope({
        "KafkaBrokerState": {
            "ReplicaCountByBrokerId": {str(b): c for b, c in sorted(replica_count.items())},
            "LeaderCountByBrokerId": {str(b): c for b, c in sorted(leader_count.items())},
            "OfflineReplicaCountByBrokerId": {},
            "IsController": {},
        },
        "KafkaPartitionState": {
            "offline": offline, "urp": out_of_sync,
            "with-offline-replicas": sorted(offline),
            "under-min-isr": [],
        },
        "partitions": partitions,
    })


_NON_VERBOSE_PROPOSAL_CAP = 1000


def _stats_dict(stats) -> dict:
    """ClusterModelStats → JSON (response/stats semantics)."""
    import numpy as np

    from ..common.resources import Resource
    util = {}
    for r in Resource:
        util[r.name] = {
            "avg": float(np.asarray(stats.utilization_avg)[int(r)]),
            "max": float(np.asarray(stats.utilization_max)[int(r)]),
            "min": float(np.asarray(stats.utilization_min)[int(r)]),
            "stdDev": float(np.asarray(stats.utilization_std)[int(r)]),
        }

    def four(a):
        avg, mx, mn, std = (float(x) for x in np.asarray(a))
        return {"avg": avg, "max": mx, "min": mn, "stdDev": std}

    return {"utilization": util,
            "potentialNwOut": four(stats.potential_nw_out_stats),
            "replicaCount": four(stats.replica_count_stats),
            "leaderCount": four(stats.leader_count_stats),
            "numAliveBrokers": int(stats.num_alive_brokers)}


def optimization_result(op: OperationResult, verbose: bool = False) -> dict:
    """Proposal-bearing POST/GET body (response/OptimizationResult.java:191).
    ``verbose`` lifts the proposal-list cap and adds before/after cluster
    stats (ParameterUtils verbose semantics)."""
    from ..serving.journey import current_journey
    jny = current_journey()
    body: dict = {"operation": op.operation, "dryrun": op.dryrun,
                  "executed": op.executed}
    with jny.seg("render"):
        r: OptimizerResult | None = op.optimizer_result
        if r is not None:
            s = r.summary()
            body["summary"] = s
            body["goalSummary"] = [
                {"goal": g.name,
                 "status": "FIXED" if g.succeeded else "VIOLATED",
                 "optimizationTimeMs": round(1000 * g.duration_s, 1)}
                for g in r.goal_results]
            if verbose:
                body["loadBeforeOptimization"] = _stats_dict(r.stats_before)
                body["loadAfterOptimization"] = _stats_dict(r.stats_after)
    with jny.seg("proposal_diff") as seg:
        proposals = list(op.proposals)
        body["numProposals"] = len(proposals)
        if not verbose and len(proposals) > _NON_VERBOSE_PROPOSAL_CAP:
            body["proposalsTruncated"] = True
            proposals = proposals[:_NON_VERBOSE_PROPOSAL_CAP]
        body["proposals"] = [
            {"topicPartition": {"topic": p.topic, "partition": p.partition},
             "oldLeader": p.old_leader,
             "oldReplicas": list(p.old_replicas),
             "newReplicas": list(p.new_replicas),
             "newLeader": p.new_leader}
            for p in proposals]
        seg.set(numProposals=len(proposals))
    body.update(op.extra)
    return envelope(body)
