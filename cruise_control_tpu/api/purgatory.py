"""Two-step review purgatory.

Reference parity: servlet/purgatory/Purgatory.java:42 + RequestInfo /
ReviewStatus — when ``two.step.verification.enabled``, POST requests are
parked PENDING_REVIEW; a reviewer approves or discards them via the REVIEW
endpoint, and an approved request is submitted by re-issuing it with its
``review_id``.
"""

from __future__ import annotations

import enum
import itertools
import threading
import time
from dataclasses import dataclass, field


class ReviewStatus(enum.Enum):
    PENDING_REVIEW = "PENDING_REVIEW"
    APPROVED = "APPROVED"
    SUBMITTED = "SUBMITTED"
    DISCARDED = "DISCARDED"


_VALID_TRANSITIONS = {
    ReviewStatus.PENDING_REVIEW: {ReviewStatus.APPROVED, ReviewStatus.DISCARDED},
    ReviewStatus.APPROVED: {ReviewStatus.SUBMITTED, ReviewStatus.DISCARDED},
    ReviewStatus.SUBMITTED: set(),
    ReviewStatus.DISCARDED: set(),
}


@dataclass
class RequestInfo:
    review_id: int
    endpoint: str
    query: str
    submitter: str = ""
    status: ReviewStatus = ReviewStatus.PENDING_REVIEW
    reason: str = ""
    submission_time_ms: int = field(
        default_factory=lambda: int(time.time() * 1000))

    def to_dict(self) -> dict:
        return {"Id": self.review_id, "EndPoint": self.endpoint,
                "Query": self.query, "Submitter": self.submitter,
                "Status": self.status.value, "Reason": self.reason,
                "SubmissionTimeMs": self.submission_time_ms}


class Purgatory:
    def __init__(self, retention_ms: int = 86_400_000):
        self._lock = threading.Lock()
        self._requests: dict[int, RequestInfo] = {}
        self._seq = itertools.count()
        self._retention_ms = retention_ms

    def add(self, endpoint: str, query: str, submitter: str = "") -> RequestInfo:
        with self._lock:
            self._expire_locked()
            info = RequestInfo(next(self._seq), endpoint, query, submitter)
            self._requests[info.review_id] = info
            return info

    def _expire_locked(self) -> None:
        now = int(time.time() * 1000)
        for rid in [r for r, info in self._requests.items()
                    if now - info.submission_time_ms > self._retention_ms]:
            del self._requests[rid]

    def _transition(self, review_id: int, to: ReviewStatus,
                    reason: str) -> RequestInfo:
        with self._lock:
            info = self._requests.get(review_id)
            if info is None:
                raise KeyError(f"unknown review id {review_id}")
            if to not in _VALID_TRANSITIONS[info.status]:
                raise ValueError(
                    f"invalid transition {info.status.value} -> {to.value}")
            info.status = to
            if reason:
                info.reason = reason
            return info

    def approve(self, review_id: int, reason: str = "") -> RequestInfo:
        return self._transition(review_id, ReviewStatus.APPROVED, reason)

    def discard(self, review_id: int, reason: str = "") -> RequestInfo:
        return self._transition(review_id, ReviewStatus.DISCARDED, reason)

    def submit(self, review_id: int, endpoint: str) -> RequestInfo:
        """Claim an APPROVED request for execution; validates the endpoint
        matches what was reviewed."""
        with self._lock:
            info = self._requests.get(review_id)
            if info is None:
                raise KeyError(f"unknown review id {review_id}")
            if info.endpoint != endpoint:
                raise ValueError(
                    f"review {review_id} is for {info.endpoint}, not {endpoint}")
            if info.status is not ReviewStatus.APPROVED:
                raise ValueError(
                    f"review {review_id} is {info.status.value}, not APPROVED")
            info.status = ReviewStatus.SUBMITTED
            return info

    def review_board(self) -> list[dict]:
        with self._lock:
            self._expire_locked()
            return [info.to_dict() for info in
                    sorted(self._requests.values(), key=lambda r: r.review_id)]
