"""Pluggable REST security.

Reference parity: cruise-control servlet/security/ — SecurityProvider SPI,
BasicSecurityProvider (file-based users with VIEWER/USER/ADMIN roles),
JwtAuthenticator (security/jwt/JwtAuthenticator.java:51, token validation +
role mapping; implemented here as stdlib HMAC-SHA256 JWS, no external jose
dependency), TrustedProxySecurityProvider
(security/trustedproxy/TrustedProxySecurityProvider.java:23 — authenticate
the proxy, trust its ``doAs`` user), and SPNEGO's principal-mapping shape
(spnego/SpnegoSecurityProvider.java:21) behind a pluggable validator since
no KDC exists in this environment.
"""

from __future__ import annotations

import base64
import binascii
import hashlib
import hmac
import json
import time
from dataclasses import dataclass
from typing import Callable, Mapping

from .endpoints import EndPoint, Role


@dataclass(frozen=True)
class Principal:
    name: str
    role: Role


class AuthenticationError(Exception):
    """401 — missing/invalid credentials."""


class AuthorizationError(Exception):
    """403 — authenticated but role below the endpoint's requirement."""


class SecurityProvider:
    """SPI: turn request headers into a Principal (or raise)."""

    def authenticate(self, headers: Mapping[str, str],
                     remote_addr: str = "") -> Principal:
        raise NotImplementedError

    def challenge(self) -> str:
        """WWW-Authenticate value advertised on 401 (a Kerberos client
        needs \"Negotiate\" or it never starts the handshake)."""
        return 'Basic realm="cruise-control"'

    def authorize(self, principal: Principal, endpoint: EndPoint) -> None:
        if principal.role < endpoint.required_role:
            raise AuthorizationError(
                f"{principal.name} (role {principal.role.name}) may not call "
                f"{endpoint.name} (requires {endpoint.required_role.name})")


class NoopSecurityProvider(SecurityProvider):
    """Security disabled: everyone is ADMIN."""

    def authenticate(self, headers, remote_addr="") -> Principal:
        return Principal("anonymous", Role.ADMIN)


def parse_credentials_file(text: str) -> dict[str, tuple[str, Role]]:
    """Jetty realm-properties format (BasicSecurityProvider):
    ``user: password, ROLE`` per line."""
    users: dict[str, tuple[str, Role]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        user, _, rest = line.partition(":")
        password, _, role = rest.partition(",")
        users[user.strip()] = (password.strip(),
                               Role[role.strip().upper() or "VIEWER"])
    return users


class BasicSecurityProvider(SecurityProvider):
    """HTTP Basic auth against a credentials file."""

    def __init__(self, credentials_file: str = "",
                 users: dict[str, tuple[str, Role]] | None = None):
        if users is not None:
            self._users = users
        elif credentials_file:
            with open(credentials_file) as f:
                self._users = parse_credentials_file(f.read())
        else:
            self._users = {}

    def authenticate(self, headers, remote_addr="") -> Principal:
        auth = headers.get("Authorization", "")
        if not auth.startswith("Basic "):
            raise AuthenticationError("missing Basic credentials")
        try:
            decoded = base64.b64decode(auth[6:]).decode()
            user, _, password = decoded.partition(":")
        except (binascii.Error, UnicodeDecodeError) as e:
            raise AuthenticationError(f"malformed Basic credentials: {e}")
        entry = self._users.get(user)
        if entry is None or not hmac.compare_digest(entry[0], password):
            raise AuthenticationError("bad username or password")
        return Principal(user, entry[1])


# ---- JWT (HS256, stdlib only) --------------------------------------------

def _b64url(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode()


def _b64url_decode(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def encode_jwt(claims: dict, secret: bytes) -> str:
    header = _b64url(json.dumps({"alg": "HS256", "typ": "JWT"}).encode())
    payload = _b64url(json.dumps(claims).encode())
    signing_input = f"{header}.{payload}".encode()
    sig = _b64url(hmac.new(secret, signing_input, hashlib.sha256).digest())
    return f"{header}.{payload}.{sig}"


def decode_jwt(token: str, secret: bytes | None = None,
               public_key_pem: bytes | None = None,
               expected_audiences: tuple[str, ...] = ()) -> dict:
    """Validate + decode a JWT. HS256 against ``secret``; RS256 against
    ``public_key_pem`` (JwtAuthenticator.java:51 verifies RS256 tokens with
    the certificate at jwt.auth.certificate.location — implemented via the
    cryptography package)."""
    try:
        header_b64, payload, sig = token.split(".")
    except ValueError:
        raise AuthenticationError("malformed JWT")
    signing_input = f"{header_b64}.{payload}".encode()
    try:
        header = json.loads(_b64url_decode(header_b64))
    except (ValueError, binascii.Error):
        raise AuthenticationError("malformed JWT header")
    if not isinstance(header, dict):
        raise AuthenticationError("malformed JWT header")
    alg = header.get("alg", "HS256")
    if alg == "HS256":
        if secret is None:
            raise AuthenticationError("HS256 token but no shared secret "
                                      "configured")
        expected = _b64url(hmac.new(secret, signing_input,
                                    hashlib.sha256).digest())
        if not hmac.compare_digest(expected, sig):
            raise AuthenticationError("bad JWT signature")
    elif alg == "RS256":
        if public_key_pem is None:
            raise AuthenticationError("RS256 token but no verification key "
                                      "configured (jwt.auth.certificate"
                                      ".location)")
        try:
            from cryptography.hazmat.primitives import hashes, serialization
            from cryptography.hazmat.primitives.asymmetric import padding
            try:
                key = serialization.load_pem_public_key(public_key_pem)
            except ValueError:
                from cryptography import x509
                key = x509.load_pem_x509_certificate(
                    public_key_pem).public_key()
            key.verify(_b64url_decode(sig), signing_input,
                       padding.PKCS1v15(), hashes.SHA256())
        except AuthenticationError:
            raise
        except Exception:  # noqa: BLE001 — any crypto failure is a 401
            raise AuthenticationError("bad JWT signature")
    else:
        raise AuthenticationError(f"unsupported JWT alg {alg!r}")
    try:
        claims = json.loads(_b64url_decode(payload))
    except (ValueError, binascii.Error):
        raise AuthenticationError("malformed JWT payload")
    exp = claims.get("exp")
    if exp is not None and time.time() > float(exp):
        raise AuthenticationError("expired JWT")
    if expected_audiences:
        aud = claims.get("aud")
        auds = {aud} if isinstance(aud, str) else set(aud or ())
        if not auds & set(expected_audiences):
            raise AuthenticationError("JWT audience not accepted")
    return claims


class JwtSecurityProvider(SecurityProvider):
    """Bearer-token auth (JwtAuthenticator.java:51): validates signature
    (HS256 shared secret or RS256 public key / certificate) + expiry +
    audience, maps the ``roles`` claim to the strongest known Role."""

    def __init__(self, secret: bytes | None = None, cookie_name: str = "",
                 principal_claim: str = "sub",
                 public_key_pem: bytes | None = None,
                 expected_audiences: tuple[str, ...] = ()):
        self._secret = secret
        self._cookie_name = cookie_name
        self._principal_claim = principal_claim
        self._public_key_pem = public_key_pem
        self._expected_audiences = tuple(expected_audiences)

    @classmethod
    def from_config(cls, cfg) -> "JwtSecurityProvider":
        """jwt.* config keys: certificate location (RS256), cookie name,
        expected audiences."""
        pem = None
        location = cfg.get("jwt.auth.certificate.location")
        if location:
            with open(location, "rb") as f:
                pem = f.read()
        return cls(cookie_name=cfg.get("jwt.cookie.name") or "",
                   public_key_pem=pem,
                   expected_audiences=tuple(
                       cfg.get_list("jwt.expected.audiences") or ()))

    def _token_from(self, headers: Mapping[str, str]) -> str:
        auth = headers.get("Authorization", "")
        if auth.startswith("Bearer "):
            return auth[7:]
        if self._cookie_name:
            for part in headers.get("Cookie", "").split(";"):
                name, _, value = part.strip().partition("=")
                if name == self._cookie_name:
                    return value
        raise AuthenticationError("missing Bearer token")

    def authenticate(self, headers, remote_addr="") -> Principal:
        claims = decode_jwt(self._token_from(headers), self._secret,
                            self._public_key_pem, self._expected_audiences)
        name = str(claims.get(self._principal_claim, "unknown"))
        roles = claims.get("roles", [])
        if isinstance(roles, str):
            roles = [roles]
        best = Role.VIEWER
        for r in roles:
            try:
                best = max(best, Role[str(r).upper()])
            except KeyError:
                continue
        return Principal(name, best)


class TrustedProxySecurityProvider(SecurityProvider):
    """Authenticate the proxy (by source address), then trust its ``doAs``
    query/header user (TrustedProxySecurityProvider.java:23). Role for the
    delegated user comes from an optional user→role map (default USER)."""

    DO_AS_HEADER = "X-Do-As"

    def __init__(self, trusted_proxies: set[str],
                 user_roles: Mapping[str, Role] | None = None):
        self._trusted = set(trusted_proxies)
        self._user_roles = dict(user_roles or {})

    def authenticate(self, headers, remote_addr="") -> Principal:
        if remote_addr not in self._trusted:
            raise AuthenticationError(f"{remote_addr} is not a trusted proxy")
        user = headers.get(self.DO_AS_HEADER, "")
        if not user:
            raise AuthenticationError("trusted proxy sent no delegated user")
        return Principal(user, self._user_roles.get(user, Role.USER))


class PrincipalValidatorSecurityProvider(SecurityProvider):
    """SPNEGO-shaped provider: an external validator (in the reference, the
    Kerberos GSS handshake) maps opaque credentials to a principal name;
    roles come from a user→role map."""

    def __init__(self, validator: Callable[[str], str | None],
                 user_roles: Mapping[str, Role] | None = None):
        self._validator = validator
        self._user_roles = dict(user_roles or {})

    def authenticate(self, headers, remote_addr="") -> Principal:
        token = headers.get("Authorization", "")
        name = self._validator(token)
        if not name:
            raise AuthenticationError("negotiation failed")
        # Strip the service/host parts of a Kerberos principal
        # (SpnegoSecurityProvider principal shortening).
        short = name.split("@")[0].split("/")[0]
        return Principal(short, self._user_roles.get(short, Role.USER))


class SpnegoSecurityProvider(PrincipalValidatorSecurityProvider):
    """Kerberos SPNEGO (security/spnego/SpnegoSecurityProvider.java:21):
    parses ``Authorization: Negotiate <base64 GSS token>`` and completes
    the GSS handshake via python-gssapi when installed (the KDC-side
    machinery the reference gets from Jetty/Hadoop auth). Without the
    ``gssapi`` package (not in this image) authentication fails loudly —
    never silently open."""

    def __init__(self, service_name: str = "HTTP",
                 principal: str | None = None,
                 keytab_file: str | None = None,
                 user_roles: Mapping[str, Role] | None = None):
        super().__init__(self._negotiate, user_roles)
        self._service_name = service_name
        # spnego.principal / spnego.keytab.file (WebServerConfig): the
        # acceptor identity and the keytab backing it.
        self._principal = principal
        self._keytab = keytab_file

    @classmethod
    def from_config(cls, cfg) -> "SpnegoSecurityProvider":
        return cls(principal=cfg.get("spnego.principal"),
                   keytab_file=cfg.get("spnego.keytab.file"))

    def challenge(self) -> str:
        return "Negotiate"

    def _acceptor_credentials(self, gssapi):
        name = None
        if self._principal:
            name = gssapi.Name(self._principal,
                               gssapi.NameType.kerberos_principal)
        store = {"keytab": self._keytab} if self._keytab else None
        if name is None and store is None:
            return None  # process default credentials
        return gssapi.Credentials(name=name, usage="accept", store=store)

    def _negotiate(self, auth_header: str) -> str | None:
        if not auth_header.startswith("Negotiate "):
            raise AuthenticationError("missing Negotiate token")
        try:
            import gssapi  # gated: not baked into this image
        except ImportError:
            raise AuthenticationError(
                "SPNEGO requires the python-gssapi package on the server")
        try:
            token = base64.b64decode(auth_header[len("Negotiate "):])
            ctx = gssapi.SecurityContext(
                creds=self._acceptor_credentials(gssapi), usage="accept")
            ctx.step(token)
            return str(ctx.initiator_name)
        except Exception as e:  # noqa: BLE001 — GSS failures are 401s
            raise AuthenticationError(f"SPNEGO negotiation failed: {e}")
