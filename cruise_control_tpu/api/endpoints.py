"""The REST endpoint taxonomy.

Reference parity: servlet/CruiseControlEndPoint.java:17-39 — the 23
endpoints with their HTTP methods, plus the VIEWER/USER/ADMIN role ladder
(security/UserPermissionsManager): VIEWER reads state, USER runs dry-run
analysis, ADMIN mutates the cluster.
"""

from __future__ import annotations

import enum


class Role(enum.IntEnum):
    VIEWER = 0
    USER = 1
    ADMIN = 2


class EndPoint(enum.Enum):
    """Value = (ordinal, method, role); the ordinal keeps members with the
    same (method, role) pair from aliasing."""

    # GET endpoints (CruiseControlEndPoint.java:18-28)
    BOOTSTRAP = (0, "GET", Role.USER)
    TRAIN = (1, "GET", Role.USER)
    LOAD = (2, "GET", Role.USER)
    PARTITION_LOAD = (3, "GET", Role.USER)
    PROPOSALS = (4, "GET", Role.USER)
    STATE = (5, "GET", Role.VIEWER)
    KAFKA_CLUSTER_STATE = (6, "GET", Role.VIEWER)
    USER_TASKS = (7, "GET", Role.USER)
    REVIEW_BOARD = (8, "GET", Role.USER)
    PERMISSIONS = (9, "GET", Role.VIEWER)
    # POST endpoints (:29-39)
    ADD_BROKER = (10, "POST", Role.ADMIN)
    REMOVE_BROKER = (11, "POST", Role.ADMIN)
    FIX_OFFLINE_REPLICAS = (12, "POST", Role.ADMIN)
    REBALANCE = (13, "POST", Role.ADMIN)
    STOP_PROPOSAL_EXECUTION = (14, "POST", Role.ADMIN)
    PAUSE_SAMPLING = (15, "POST", Role.ADMIN)
    RESUME_SAMPLING = (16, "POST", Role.ADMIN)
    DEMOTE_BROKER = (17, "POST", Role.ADMIN)
    ADMIN = (18, "POST", Role.ADMIN)
    REVIEW = (19, "POST", Role.ADMIN)
    TOPIC_CONFIGURATION = (20, "POST", Role.ADMIN)
    RIGHTSIZE = (21, "POST", Role.ADMIN)
    REMOVE_DISKS = (22, "POST", Role.ADMIN)
    # Fleet federation (no reference analogue: the reference is one
    # service per cluster; here one process serves many clusters and
    # this endpoint is the fleet-wide dashboard).
    FLEET = (23, "GET", Role.VIEWER)
    # Pipeline tracing (no reference analogue — the reference exposes JMX
    # sensors but no request-scoped causality): recent span trees from
    # utils.tracing, filterable by ?cluster= and ?operation=.
    TRACE = (24, "GET", Role.VIEWER)
    # Solver flight recorder (no reference analogue — the reference's
    # optimizer is host-side and debuggable in place; the donated
    # on-device megastep is not): recorded per-goal, per-dispatch search
    # telemetry from utils.flight_recorder, filterable by ?cluster= and
    # ?goal=.
    SOLVER = (25, "GET", Role.VIEWER)
    # On-demand device profiling (utils.profiling): jax.profiler trace
    # capture of live solves + the in-process op-class microbench. USER,
    # not VIEWER: a capture occupies the profiler gate and the microbench
    # occupies the device — both consume shared machine time.
    PROFILE = (26, "GET", Role.USER)
    # Futures engine (round 15, no reference analogue — the reference's
    # what-if is one dry run per request): evaluate a batch of sampled
    # candidate futures of the cluster in one megabatched solve and
    # return them ranked with score deltas vs the present. Async (202 +
    # User-Task-ID), dry-run only — a futures request can never execute
    # anything. USER like PROPOSALS/PROFILE: the batched solve consumes
    # shared device time even though the answer is viewer-safe.
    COMPARE_FUTURES = (27, "GET", Role.USER)
    # Heal ledger (round 16, no reference analogue — the reference's
    # AnomalyDetectorState shows per-anomaly status snapshots, not the
    # causal chain): correlated anomaly-lifecycle chains from
    # utils.heal_ledger — detection → notifier verdict → fix → solve
    # (flight-recorder pass ids) → execution → terminal outcome, with
    # per-phase durations. ``?cluster=`` routes to that cluster's
    # facade ledger; ``?anomaly_type=`` / ``?entries=`` filter.
    HEALS = (28, "GET", Role.VIEWER)
    # Predictive rebalancing (round 19, no reference analogue — the
    # reference is purely reactive): the facade's forecast engine state —
    # per-broker current-vs-projected loads with the confidence band,
    # horizon/fit geometry, and the predictive detector's lifecycle
    # counters (predictions made / confirmed / missed, hit rate).
    # ``?refresh=true`` fits a fresh forecast inline (explicit opt-in:
    # it is device work); the default serves the last cached fit.
    FORECAST = (29, "GET", Role.VIEWER)
    # Request journeys (no reference analogue — the reference exposes
    # per-endpoint latency sensors, not per-request attribution): the
    # facade's bounded ring of completed serving/journey.py records —
    # which segment (admission, cache, queue wait, model build, solve,
    # render, ...) each request's wall went to. ``?cluster=`` ROUTES to
    # that cluster's facade ring; ``?endpoint=`` / ``?entries=`` filter.
    JOURNEYS = (30, "GET", Role.VIEWER)
    # SLO engine (utils/slo.py): declarative objective registry state —
    # per-window burn rates, remaining error budget, burning verdicts —
    # plus the SLO-burn detector's raised/cleared lifecycle counters.
    # ``?cluster=`` ROUTES to that cluster's facade registry.
    SLO = (31, "GET", Role.VIEWER)
    # Red-team regression frontier (redteam/, round 22): the mined
    # worst-case scenario set with per-entry SLO margins, verdicts and
    # replay recipes, plus the forecaster blind-spot report. Each entry
    # replays via ``proposals?what_if=mined:<id>``.
    REDTEAM = (32, "GET", Role.VIEWER)

    @property
    def method(self) -> str:
        return self.value[1]

    @property
    def required_role(self) -> Role:
        return self.value[2]

    @property
    def path(self) -> str:
        return self.name.lower()


GET_ENDPOINTS = tuple(e for e in EndPoint if e.method == "GET")
POST_ENDPOINTS = tuple(e for e in EndPoint if e.method == "POST")

# POST endpoints subject to two-step review when the purgatory is enabled
# (Purgatory.java — GET endpoints and REVIEW itself are exempt).
REVIEWABLE_ENDPOINTS = tuple(e for e in POST_ENDPOINTS if e is not EndPoint.REVIEW)


def endpoint_for_path(path: str) -> EndPoint | None:
    try:
        return EndPoint[path.strip("/").upper()]
    except KeyError:
        return None
