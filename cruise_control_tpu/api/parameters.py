"""Query-parameter schemas + typed parsing.

Reference parity: servlet/parameters/ (one class per endpoint, ~15-25
params each) and ParameterUtils.java (central parsing). Collapsed to a
declarative schema per endpoint: name → coercion, with unknown-parameter
rejection exactly like ParameterUtils' UserRequestException.
"""

from __future__ import annotations

from typing import Any, Callable, Mapping

from .endpoints import EndPoint


class ParameterParseError(ValueError):
    """Maps to HTTP 400 (UserRequestException)."""


def _bool(v: str) -> bool:
    if v.lower() in ("true", "1", "yes"):
        return True
    if v.lower() in ("false", "0", "no"):
        return False
    raise ParameterParseError(f"not a boolean: {v!r}")


def _int(v: str) -> int:
    try:
        return int(v)
    except ValueError:
        raise ParameterParseError(f"not an integer: {v!r}")


def _long_ms(v: str) -> int:
    return _int(v)


def _float(v: str) -> float:
    try:
        return float(v)
    except ValueError:
        raise ParameterParseError(f"not a number: {v!r}")


def _str(v: str) -> str:
    return v


def _csv(v: str) -> tuple[str, ...]:
    return tuple(x for x in (s.strip() for s in v.split(",")) if x)


def _int_csv(v: str) -> tuple[int, ...]:
    return tuple(_int(x) for x in _csv(v))


def _broker_logdir_csv(v: str) -> dict[int, tuple[str, ...]]:
    """REMOVE_DISKS brokerid_and_logdirs: ``brokerid-logdir`` pairs."""
    out: dict[int, list[str]] = {}
    for item in _csv(v):
        broker, sep, logdir = item.partition("-")
        if not sep:
            raise ParameterParseError(
                f"expected brokerid-logdir pair, got {item!r}")
        out.setdefault(_int(broker), []).append(logdir)
    return {b: tuple(d) for b, d in out.items()}


_COMMON: dict[str, Callable[[str], Any]] = {
    "json": _bool, "verbose": _bool, "get_response_schema": _bool,
    "doas": _str, "reason": _str,
    # Fleet federation routing: which registered cluster the request
    # targets (fleet.registry). Absent = the process's default cluster,
    # so every single-cluster deployment is untouched.
    "cluster": _str,
}

_GOALS_PARAMS = {"goals": _csv, "allow_capacity_estimation": _bool,
                 "exclude_recently_demoted_brokers": _bool,
                 "exclude_recently_removed_brokers": _bool,
                 "use_ready_default_goals": _bool, "fast_mode": _bool}

_PROPOSAL_PARAMS = {**_GOALS_PARAMS, "ignore_proposal_cache": _bool,
                    "data_from": _str, "excluded_topics": _csv,
                    "kafka_assigner": _bool, "rebalance_disk": _bool}

# Digital-twin what-if replay (testing/simulator.py): a PROPOSALS request
# with what_if=<scenario> runs the named canonical scenario on a
# simulated twin and returns the scored trajectory — a time-dimension
# extension of the dry run; it never executes anything.
# what_if=random:<template>:<seed> replays a generator-sampled scenario
# (futures/generator.py) instead — same caps, same determinism contract.
_WHAT_IF_PARAMS = {"what_if": _str, "what_if_seed": _int,
                   "what_if_ticks": _int}

_EXECUTION_PARAMS = {
    "dryrun": _bool, "concurrent_partition_movements_per_broker": _int,
    "max_partition_movements_in_cluster": _int,
    "concurrent_intra_broker_partition_movements": _int,
    "concurrent_leader_movements": _int,
    "broker_concurrent_leader_movements": _int,
    "execution_progress_check_interval_ms": _long_ms,
    "skip_hard_goal_check": _bool, "replication_throttle": _int,
    "replica_movement_strategies": _csv, "review_id": _int,
    "stop_ongoing_execution": _bool}

SCHEMAS: dict[EndPoint, dict[str, Callable[[str], Any]]] = {
    EndPoint.BOOTSTRAP: {"start": _long_ms, "end": _long_ms,
                         "clearmetrics": _bool, "developer_mode": _bool},
    EndPoint.TRAIN: {"start": _long_ms, "end": _long_ms},
    EndPoint.LOAD: {"time": _long_ms, "start": _long_ms, "end": _long_ms,
                    "allow_capacity_estimation": _bool, "populate_disk_info": _bool,
                    "capacity_only": _bool},
    EndPoint.PARTITION_LOAD: {"resource": _str, "start": _long_ms, "end": _long_ms,
                              "entries": _int, "max_load": _bool, "avg_load": _bool,
                              "topic": _str, "partition": _str,
                              "min_valid_partition_ratio": _float,
                              "allow_capacity_estimation": _bool,
                              "brokerid": _int_csv},
    EndPoint.PROPOSALS: {**_PROPOSAL_PARAMS, **_WHAT_IF_PARAMS},
    EndPoint.STATE: {"substates": _csv, "super_verbose": _bool},
    EndPoint.KAFKA_CLUSTER_STATE: {"topic": _str},
    EndPoint.USER_TASKS: {"user_task_ids": _csv, "client_ids": _csv,
                          "endpoints": _csv, "types": _csv, "entries": _int,
                          "fetch_completed_task": _bool},
    EndPoint.REVIEW_BOARD: {"review_ids": _int_csv},
    EndPoint.PERMISSIONS: {},
    EndPoint.ADD_BROKER: {**_PROPOSAL_PARAMS, **_EXECUTION_PARAMS,
                          "brokerid": _int_csv, "throttle_added_broker": _bool},
    EndPoint.REMOVE_BROKER: {**_PROPOSAL_PARAMS, **_EXECUTION_PARAMS,
                             "brokerid": _int_csv, "throttle_removed_broker": _bool,
                             "destination_broker_ids": _int_csv},
    EndPoint.FIX_OFFLINE_REPLICAS: {**_PROPOSAL_PARAMS, **_EXECUTION_PARAMS},
    EndPoint.REBALANCE: {**_PROPOSAL_PARAMS, **_EXECUTION_PARAMS,
                         "destination_broker_ids": _int_csv,
                         "ignore_proposal_cache": _bool},
    EndPoint.STOP_PROPOSAL_EXECUTION: {"force_stop": _bool,
                                       "stop_external_agent": _bool,
                                       "review_id": _int},
    EndPoint.PAUSE_SAMPLING: {"review_id": _int},
    EndPoint.RESUME_SAMPLING: {"review_id": _int},
    EndPoint.DEMOTE_BROKER: {**_EXECUTION_PARAMS, "brokerid": _int_csv,
                             "skip_urp_demotion": _bool,
                             "exclude_follower_demotion": _bool},
    EndPoint.ADMIN: {"disable_self_healing_for": _csv,
                     "enable_self_healing_for": _csv,
                     "disable_concurrency_adjuster_for": _csv,
                     "enable_concurrency_adjuster_for": _csv,
                     "min_isr_based_concurrency_adjustment": _bool,
                     "concurrent_partition_movements_per_broker": _int,
                     "concurrent_intra_broker_partition_movements": _int,
                     "concurrent_leader_movements": _int,
                     "drop_recently_removed_brokers": _int_csv,
                     "drop_recently_demoted_brokers": _int_csv,
                     "review_id": _int},
    EndPoint.REVIEW: {"approve": _int_csv, "discard": _int_csv},
    EndPoint.TOPIC_CONFIGURATION: {**_EXECUTION_PARAMS, "topic": _str,
                                   "replication_factor": _int,
                                   "skip_rack_awareness_check": _bool},
    EndPoint.RIGHTSIZE: {"numbrokerstoadd": _int, "partition_count": _int,
                         "topic": _str, "review_id": _int},
    EndPoint.REMOVE_DISKS: {**_EXECUTION_PARAMS,
                            "brokerid_and_logdirs": _broker_logdir_csv},
    EndPoint.FLEET: {},
    # cluster (in _COMMON) filters by the trace's recorded cluster label
    # rather than routing; operation filters by runnable name
    # (rebalance/proposals/sampling/execution/...).
    EndPoint.TRACE: {"operation": _str, "entries": _int},
    # cluster (in _COMMON) filters by the pass's recorded cluster label
    # (same no-route semantics as TRACE); goal trims each pass to one
    # goal's record.
    EndPoint.SOLVER: {"goal": _str, "entries": _int},
    # cluster (in _COMMON) ROUTES to that cluster's facade ledger (each
    # facade journals its own heals on its own clock); anomaly_type
    # filters chains; entries bounds the response.
    EndPoint.HEALS: {"anomaly_type": _str, "entries": _int},
    # duration_s > 0 = jax.profiler capture window; microbench=true = the
    # in-process op-class while_loop marginals instead (brokers/
    # partitions/iters size it).
    EndPoint.PROFILE: {"duration_s": _float, "microbench": _bool,
                       "brokers": _int, "partitions": _int, "iters": _int},
    # Futures engine (futures/evaluator.py): templates picks the sampled
    # scenario templates (default: all), num_futures how many candidates
    # (capped by futures.max.count), seed the base generator seed, ticks
    # the advance horizon (capped by futures.max.ticks).
    EndPoint.COMPARE_FUTURES: {"templates": _csv, "num_futures": _int,
                               "seed": _int, "ticks": _int,
                               "include_present": _bool},
    # Predictive rebalancing (forecast/engine.py): refresh=true fits a
    # fresh forecast inline (device work, explicit opt-in); default
    # serves the engine's last cached projection. cluster (in _COMMON)
    # ROUTES to that cluster's facade engine.
    EndPoint.FORECAST: {"refresh": _bool},
    # cluster (in _COMMON) ROUTES to that cluster's facade journey ring;
    # endpoint filters by the journey's endpoint name; entries bounds
    # the response (newest first).
    EndPoint.JOURNEYS: {"endpoint": _str, "entries": _int},
    # cluster (in _COMMON) ROUTES to that cluster's facade SLO registry;
    # objective trims the body to one objective's evaluation.
    EndPoint.SLO: {"objective": _str},
    # Red-team frontier (redteam/): entries bounds the frontier list
    # (worst margin first); blind_spots=false drops the per-entry
    # forecaster blind-spot detail for a compact body.
    EndPoint.REDTEAM: {"entries": _int, "blind_spots": _bool},
}


def parse_parameters(endpoint: EndPoint, query: Mapping[str, list[str]],
                     ) -> dict[str, Any]:
    """Coerce a parsed query string; rejects unknown parameters
    (ParameterUtils semantics: a typo must not silently no-op)."""
    schema = {**_COMMON, **SCHEMAS[endpoint]}
    out: dict[str, Any] = {}
    for name, values in query.items():
        key = name.lower()
        if key not in schema:
            raise ParameterParseError(
                f"unknown parameter {name!r} for {endpoint.name}")
        if not values:
            continue
        try:
            out[key] = schema[key](values[-1])
        except ParameterParseError:
            raise
        except Exception as e:
            raise ParameterParseError(f"bad value for {name}: {e}")
    return out
