"""Application bootstrap: ``python -m cruise_control_tpu.api.app``.

Reference parity: KafkaCruiseControlMain.java:26 (main(config,[port],[host]))
+ KafkaCruiseControlApp/KafkaCruiseControlServletApp — build the facade from
a properties file, start monitor + detectors, serve REST until interrupted.

Without --properties the app runs against a synthetic in-memory cluster
(the demo/dev mode; the reference needs a live Kafka for the same tour).
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys

from ..config.cruise_control_config import CruiseControlConfig
from ..facade import CruiseControl
from .server import make_server, serve_forever_in_thread

LOG = logging.getLogger(__name__)


def load_properties(path: str) -> dict:
    """Java .properties subset: key=value lines, # comments."""
    out: dict[str, str] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith(("#", "!")):
                continue
            key, _, value = line.partition("=")
            out[key.strip()] = value.strip()
    return out


def build_demo_cruise_control(cfg: CruiseControlConfig) -> CruiseControl:
    from ..common.resources import Resource
    from ..executor.admin import InMemoryAdminBackend, PartitionState
    from ..monitor import LoadMonitor, StaticCapacityResolver
    from ..monitor.sampling import SyntheticSampler

    parts = {}
    for t in range(4):
        for p in range(8):
            reps = (0, 1 + (t + p) % 3)
            parts[(f"demo{t}", p)] = PartitionState(f"demo{t}", p, reps,
                                                    reps[0], isr=reps)
    backend = InMemoryAdminBackend(parts.values())
    caps = StaticCapacityResolver({}, {Resource.CPU: 100.0, Resource.DISK: 1e7,
                                       Resource.NW_IN: 1e6, Resource.NW_OUT: 1e6})
    monitor = LoadMonitor(cfg, backend, samplers=[SyntheticSampler()],
                          capacity_resolver=caps)
    return CruiseControl(cfg, backend, load_monitor=monitor)


def _configured_sample_store(cfg: CruiseControlConfig, bootstrap: str):
    """sample.store.class resolution for live mode: the Kafka store gets
    the bootstrap servers, the file store its configured path, a custom
    class a bare constructor. The configured store must actually be built
    — silently dropping it would cold-start the load model on every
    restart (no warm-window replay)."""
    from ..config.abstract_config import resolve_class
    from ..kafka import KafkaSampleStore
    from ..monitor.sampling.sample_store import FileSampleStore

    spec = cfg.get("sample.store.class")
    cls = resolve_class(spec) if isinstance(spec, str) else spec
    if cls is KafkaSampleStore:
        return KafkaSampleStore(bootstrap)
    if cls is FileSampleStore or cls is None:
        return FileSampleStore(cfg.get("sample.store.path"))
    return cls()


def _configured_capacity_resolver(cfg: CruiseControlConfig):
    """broker.capacity.config.resolver.class resolution (the
    getConfiguredInstance path): hardcoding a default here would feed the
    goals fictitious capacities on heterogeneous clusters."""
    from ..config.abstract_config import resolve_class
    from ..monitor.capacity import FileCapacityResolver

    spec = cfg.get("broker.capacity.config.resolver.class")
    cls = resolve_class(spec) if isinstance(spec, str) else spec
    if cls is FileCapacityResolver or cls is None:
        return FileCapacityResolver(cfg.get("capacity.config.file"))
    return cls()


def build_live_cruise_control(cfg: CruiseControlConfig) -> CruiseControl:
    """Wire the full stack against a LIVE Kafka cluster through the
    framework's own wire-protocol client (kafka/): admin ops, the
    __CruiseControlMetrics reporter-topic sampler, the configured sample
    store and capacity resolver, and broker racks from cluster metadata
    (refreshed per model build for late-joining brokers)."""
    from ..kafka import KafkaAdminBackend, KafkaMetricsTransport
    from ..monitor import LoadMonitor
    from ..monitor.sampling.sampler import CruiseControlMetricsReporterSampler
    from ..utils.resilience import RetryPolicy

    bootstrap = ",".join(cfg.get_list("bootstrap.servers"))
    admin = KafkaAdminBackend(bootstrap,
                              retry_policy=RetryPolicy.from_config(cfg))
    transport = KafkaMetricsTransport(bootstrap)
    sampler = CruiseControlMetricsReporterSampler(transport)
    if cfg.get_boolean("chaos.enabled"):
        # Game-day drill wiring: wrap BEFORE the monitor is built so the
        # sampling fetch and monitor metadata paths see injected faults
        # too (the facade's own wrap is idempotent and shares this
        # schedule — wrapping only there would leave the monitor clean
        # and report resilience as proven without exercising it).
        from ..testing.chaos import ChaosAdminBackend, ChaosSampler
        admin = ChaosAdminBackend.from_config(admin, cfg)
        sampler = ChaosSampler(sampler, schedule=admin.schedule)
    monitor = LoadMonitor(
        cfg, admin, samplers=[sampler],
        sample_store=_configured_sample_store(cfg, bootstrap),
        capacity_resolver=_configured_capacity_resolver(cfg))
    return CruiseControl(cfg, admin, load_monitor=monitor)


# Demo-mode tunables: a fresh operator should see a working rebalance in
# seconds, not after the production 5-minute window fills (the reference
# demo tour has the same cold-start, but it needs a live cluster anyway).
_DEMO_DEFAULTS = {
    "metric.sampling.interval.ms": 2_000,
    "partition.metrics.window.ms": 5_000,
    "broker.metrics.window.ms": 5_000,
    "min.valid.partition.ratio": 0.0,
}


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="cruise-control-tpu")
    parser.add_argument("--properties", help="config properties file")
    parser.add_argument("--port", type=int, help="REST port override")
    parser.add_argument("--host", help="bind address override")
    parser.add_argument("--demo", action="store_true",
                        help="synthetic in-memory cluster (default when no "
                        "--properties is given)")
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO, format="%(asctime)s %(name)s "
                        "%(levelname)s %(message)s")
    overrides = load_properties(args.properties) if args.properties else {}
    if overrides.get("bootstrap.servers") and not args.demo:
        # Live mode: the wire-protocol client manages the real cluster.
        cc = build_live_cruise_control(CruiseControlConfig(overrides))
    else:
        demo_cfg = dict(_DEMO_DEFAULTS)
        demo_cfg.update(overrides)
        cc = build_demo_cruise_control(CruiseControlConfig(demo_cfg))
    # start_up wires the persistent compile cache + the background shape
    # prewarm from the solver.compile.cache.* / solver.prewarm.* config
    # keys (round 18) — no wrapper-script env vars needed; configure the
    # cache as early as possible anyway so even monitor-warmup jits land
    # in it.
    from cruise_control_tpu.warmstart import configure_compile_cache
    configure_compile_cache(cc.config)
    cc.start_up(block_on_load=False)

    server, api = make_server(cc, host=args.host, port=args.port)
    thread = serve_forever_in_thread(server)
    host, port = server.server_address[:2]
    LOG.info("cruise-control-tpu listening on http://%s:%s/kafkacruisecontrol/state",
             host, port)

    stop = {"flag": False}

    def _sigterm(_sig, _frm):
        stop["flag"] = True

    signal.signal(signal.SIGINT, _sigterm)
    signal.signal(signal.SIGTERM, _sigterm)
    try:
        while not stop["flag"] and thread.is_alive():
            thread.join(timeout=0.5)
    finally:
        server.shutdown()
        api.shutdown()
        cc.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
