"""Application bootstrap: ``python -m cruise_control_tpu.api.app``.

Reference parity: KafkaCruiseControlMain.java:26 (main(config,[port],[host]))
+ KafkaCruiseControlApp/KafkaCruiseControlServletApp — build the facade from
a properties file, start monitor + detectors, serve REST until interrupted.

Without --properties the app runs against a synthetic in-memory cluster
(the demo/dev mode; the reference needs a live Kafka for the same tour).
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys

from ..config.cruise_control_config import CruiseControlConfig
from ..facade import CruiseControl
from .server import make_server, serve_forever_in_thread

LOG = logging.getLogger(__name__)


def load_properties(path: str) -> dict:
    """Java .properties subset: key=value lines, # comments."""
    out: dict[str, str] = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith(("#", "!")):
                continue
            key, _, value = line.partition("=")
            out[key.strip()] = value.strip()
    return out


def build_demo_cruise_control(cfg: CruiseControlConfig) -> CruiseControl:
    from ..common.resources import Resource
    from ..executor.admin import InMemoryAdminBackend, PartitionState
    from ..monitor import LoadMonitor, StaticCapacityResolver
    from ..monitor.sampling import SyntheticSampler

    parts = {}
    for t in range(4):
        for p in range(8):
            reps = (0, 1 + (t + p) % 3)
            parts[(f"demo{t}", p)] = PartitionState(f"demo{t}", p, reps,
                                                    reps[0], isr=reps)
    backend = InMemoryAdminBackend(parts.values())
    caps = StaticCapacityResolver({}, {Resource.CPU: 100.0, Resource.DISK: 1e7,
                                       Resource.NW_IN: 1e6, Resource.NW_OUT: 1e6})
    monitor = LoadMonitor(cfg, backend, samplers=[SyntheticSampler()],
                          capacity_resolver=caps)
    return CruiseControl(cfg, backend, load_monitor=monitor)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="cruise-control-tpu")
    parser.add_argument("--properties", help="config properties file")
    parser.add_argument("--port", type=int, help="REST port override")
    parser.add_argument("--host", help="bind address override")
    parser.add_argument("--demo", action="store_true",
                        help="synthetic in-memory cluster (default when no "
                        "--properties is given)")
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO, format="%(asctime)s %(name)s "
                        "%(levelname)s %(message)s")
    overrides = load_properties(args.properties) if args.properties else {}
    cfg = CruiseControlConfig(overrides)
    if overrides.get("bootstrap.servers") and not args.demo:
        # Honest failure over a silent fake: this build ships the in-memory
        # backend only (a live-Kafka AdminBackend is a deployment add-on);
        # pass --demo to run the synthetic cluster with these tunables.
        parser.error("bootstrap.servers is set but no live-Kafka backend is "
                     "available in this build; pass --demo to run the "
                     "synthetic in-memory cluster with this config")
    cc = build_demo_cruise_control(cfg)
    cc.start_up(block_on_load=False)

    server, api = make_server(cc, host=args.host, port=args.port)
    thread = serve_forever_in_thread(server)
    host, port = server.server_address[:2]
    LOG.info("cruise-control-tpu listening on http://%s:%s/kafkacruisecontrol/state",
             host, port)

    stop = {"flag": False}

    def _sigterm(_sig, _frm):
        stop["flag"] = True

    signal.signal(signal.SIGINT, _sigterm)
    signal.signal(signal.SIGTERM, _sigterm)
    try:
        while not stop["flag"] and thread.is_alive():
            thread.join(timeout=0.5)
    finally:
        server.shutdown()
        api.shutdown()
        cc.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
