"""OpenAPI spec generated from the shared parameter schemas.

Reference parity: cruise-control/src/main/resources/yaml/base.yaml (the
hand-written spec the Vert.x front-end routes from,
vertx/MainVerticle.java:54). Here the spec is DERIVED from the same
``api.parameters.SCHEMAS`` tables the dispatcher validates against, so it
cannot drift from the implementation. Served at ``/openapi``.
"""

from __future__ import annotations

from .endpoints import EndPoint
from .parameters import _COMMON, SCHEMAS
from .server import URL_PREFIX

_TYPE_BY_COERCION = {
    "_bool": ("boolean", None),
    "_int": ("integer", None),
    "_float": ("number", None),
    "_long_ms": ("integer", "epoch milliseconds"),
    "_str": ("string", None),
    "_csv": ("string", "comma-separated list"),
    "_int_csv": ("string", "comma-separated integers"),
    "_broker_logdir_csv": ("string", "comma-separated brokerid-logdir pairs"),
}


def _param_spec(name: str, coercion) -> dict:
    oa_type, note = _TYPE_BY_COERCION.get(
        getattr(coercion, "__name__", ""), ("string", None))
    out = {"name": name, "in": "query", "required": False,
           "schema": {"type": oa_type}}
    if note:
        out["description"] = note
    return out


def openapi_spec() -> dict:
    paths: dict = {}
    for endpoint in EndPoint:
        params = [_param_spec(n, c)
                  for n, c in sorted({**_COMMON, **SCHEMAS[endpoint]}.items())]
        paths[f"{URL_PREFIX}/{endpoint.name.lower()}"] = {
            endpoint.method.lower(): {
                "operationId": endpoint.name.lower(),
                "summary": f"{endpoint.name} "
                           f"(requires role {endpoint.required_role.name})",
                "parameters": params,
                "responses": {"200": {"description": "OK (JSON envelope)"},
                              "202": {"description":
                                      "async task accepted; poll with the "
                                      "User-Task-ID header"},
                              "400": {"description": "bad parameter"},
                              "401": {"description": "unauthenticated"},
                              "403": {"description": "unauthorized"}},
            }}
    paths["/metrics"] = {"get": {
        "operationId": "metrics",
        "summary": "Prometheus sensor exposition",
        "responses": {"200": {"description": "text exposition format"}}}}
    return {
        "openapi": "3.0.0",
        "info": {"title": "cruise-control-tpu",
                 "description": "TPU-native Cruise Control REST API "
                                "(endpoint parity with "
                                "CruiseControlEndPoint.java:17-39)",
                 "version": "1.0"},
        "paths": paths,
    }


def openapi_yaml() -> str:
    import yaml

    return yaml.safe_dump(openapi_spec(), sort_keys=False)
