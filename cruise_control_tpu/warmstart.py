"""Always-hot solver machinery (round 18): warm-start seeds, violation
fingerprints, and per-shape AOT prewarm.

ROADMAP item 3's three composing pieces live here and in their call
sites:

- **Warm starts** — ``WarmSeedStore`` keeps the last ACCEPTED
  ``(assignment, leader_slot)`` per facade (one facade = one cluster;
  fleet clusters each own a store). Under sustained drift most goals are
  already satisfied at the previous target, so seeding the next chain
  solve from it collapses rounds-to-convergence. Safety: the facade
  diffs proposals against the TRUE current model (never the seed), and a
  warm-seeded result that falls below the cold path's sentry band —
  ``solver.warm.start.quality.band`` balancedness drop, or a violated
  goal the seed's own solve did not have — triggers a COUNTED cold
  re-solve (``solver_warm_fallbacks``), so warm starts can never
  silently degrade proposals.

- **Violation fingerprints** — ``violation_fingerprint`` hashes the
  per-goal entry-violation vector the ONE batched
  ``chain_all_goal_stats`` program snapshots before the bounded chain
  loop (analyzer.chain / analyzer.optimizer). A goal whose snapshot
  shows zero entry violation applies nothing, so its dispatches are
  skipped byte-identically (``DispatchStats.goals_skipped``).

- **AOT prewarm** — ``ShapeRegistry`` persists every solved padded
  bucket-shape signature under the XLA persistent-cache partition dir
  (one JSON file per host fingerprint), and ``PrewarmManager`` compiles
  the whole per-shape kernel set in a background thread at ``start_up``
  (``GoalOptimizer.prewarm_shape`` executes the production kernels on an
  inert synthetic model: full compile, zero search work). Watched by the
  existing ``xla_compile_cache_{hits,misses}`` counters; progress is
  surfaced on ``GET /state`` (AnalyzerState.prewarm) and ``GET /fleet``.

Determinism: this module is in CCSA004's deterministic set — the warm
path influences solver inputs and must be wall-clock/random-free; the
prewarm manager times itself through the injectable ``monotonic`` seam
(observability only).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
import weakref
import zlib
from typing import Any

import numpy as np

LOG = logging.getLogger(__name__)


# -- compile-cache config seam (satellite: solver.compile.cache.*) ---------

def configure_compile_cache(config) -> str | None:
    """Point XLA's persistent compilation cache at the configured
    directory — the ``solver.compile.cache.{enabled,dir,min.compile.secs}``
    seam replacing the env-var/hardcoded values every entry point used to
    wire by hand. Called from facade ``start_up`` so SERVING processes
    (not just bench/CLI wrappers) persist their solver compiles. Returns
    the host-partitioned cache dir, or None when disabled."""
    if not config.get_boolean("solver.compile.cache.enabled"):
        return None
    from . import enable_persistent_compile_cache
    return enable_persistent_compile_cache(
        config.get("solver.compile.cache.dir") or None,
        min_compile_secs=config.get_double(
            "solver.compile.cache.min.compile.secs"))


# -- violation fingerprints ------------------------------------------------

def violation_fingerprint(violations) -> int:
    """crc32 of the per-goal entry-violation vector (rounded to 1e-6 so
    f32 noise cannot flap the fingerprint). Zero entries are exactly the
    goals the bounded chain loop may skip dispatch-free."""
    v = np.asarray(violations, dtype=np.float64).reshape(-1)
    return zlib.crc32(np.round(v, 6).astype(np.float32).tobytes())


# -- warm-start seeds ------------------------------------------------------

@dataclasses.dataclass
class WarmSeed:
    """The last accepted solver target plus the quality it was accepted
    at (the fallback band's reference point). ``partition_index`` /
    ``broker_ids`` pin the index space the tensors are meaningful in."""

    assignment: Any           # [P, S] device array
    leader_slot: Any          # [P] device array
    partition_index: Any      # ClusterMeta.partition_index (ref)
    broker_ids: Any           # ClusterMeta.broker_ids (ref)
    balancedness_after: float
    violated_after: frozenset


def _same_index(a, b) -> bool:
    # The refresh pipeline's topology cache returns the SAME ClusterMeta
    # object on a topology hit, so the identity check makes steady-state
    # validation O(1); equality is the fallback across rebuilds.
    return a is b or a == b


class WarmSeedStore:
    """Lock-guarded single-slot store of the facade's last accepted
    solve target. A seed is valid for a new model exactly when the
    padded tensor shapes AND the index spaces (partition rows, broker
    axis) match — liveness/load changes do NOT invalidate it: the goal
    chain re-checks everything, and the quality fallback guards the
    rest. No wall-clock: staleness is bounded by topology identity plus
    the fallback band, not by age."""

    def __init__(self):
        self._lock = threading.Lock()
        self._seed: WarmSeed | None = None

    def store(self, final_state, meta, result,
              reference: "tuple[float, frozenset] | None" = None) -> None:
        """Record a solve's final state as the next warm seed (called on
        ACCEPTED results only — quality-flunked warm solves never seed).

        ``reference`` overrides the quality the NEXT warm solve is gated
        against. COLD solves pass None (their own quality re-anchors the
        gate); a gate-passing WARM solve passes the sticky reference —
        max(previous reference, own balancedness) with its own (never
        larger, gate-guaranteed) violated set — so repeated warm solves
        cannot ratchet served quality down by one band per tick: the
        reference only rises until a cold solve re-anchors it."""
        if reference is None:
            reference = (float(result.balancedness_after),
                         frozenset(result.violated_goals_after))
        seed = WarmSeed(
            assignment=final_state.assignment,
            leader_slot=final_state.leader_slot,
            partition_index=meta.partition_index,
            broker_ids=meta.broker_ids,
            balancedness_after=float(reference[0]),
            violated_after=frozenset(reference[1]))
        with self._lock:
            self._seed = seed
        from .utils.sensors import SENSORS
        SENSORS.count("solver_warm_seed_stored")

    def match(self, state, meta) -> WarmSeed | None:
        """The stored seed when it is valid for ``(state, meta)``, else
        None (an invalid seed is dropped and counted — topology moved)."""
        with self._lock:
            seed = self._seed
        if seed is None:
            return None
        if (tuple(seed.assignment.shape) != tuple(state.assignment.shape)
                or tuple(seed.leader_slot.shape)
                != tuple(state.leader_slot.shape)
                or not _same_index(seed.partition_index,
                                   meta.partition_index)
                or not _same_index(seed.broker_ids, meta.broker_ids)):
            # Compare-and-clear: validation ran outside the lock, and a
            # concurrent store() may have replaced the slot with a seed
            # valid for the NEW topology — only drop the exact seed
            # that failed.
            with self._lock:
                if self._seed is seed:
                    self._seed = None
            from .utils.sensors import SENSORS
            SENSORS.count("solver_warm_seed_invalid")
            return None
        return seed

    def clear(self) -> None:
        with self._lock:
            self._seed = None


def warm_quality_ok(result, reference_balancedness: float,
                    reference_violated, band: float) -> bool:
    """THE warm-start sentry-band predicate (shared by the facade's
    serving gate and the bench's served-semantics measurement, so the
    two can never drift): a warm result is acceptable iff it violates
    no goal the reference did not and its balancedness sits within
    ``band`` of the reference."""
    if set(result.violated_goals_after) - set(reference_violated):
        return False
    return result.balancedness_after >= reference_balancedness - band


def seed_band_ok(entry_balancedness: float, entry_violated,
                 seed: WarmSeed, band: float) -> bool:
    """The warm-band PRE-CHECK predicate (round 19, ROADMAP 3a tail):
    the seed scored against the CURRENT loads — one batched
    ``chain_all_goal_stats`` entry snapshot — must sit inside the same
    sentry band ``warm_quality_ok`` enforces after the solve: no
    violated goal the seed's accepted solve did not have, balancedness
    within ``band`` of the accepted reference. Honest trade: the chain
    COULD sometimes repair an out-of-band seed and keep the warm win,
    but the measured drift case (±5 % wave, bench --warmstart) converges
    band-worse and pays attempt+fallback — the pre-check skips that
    doomed double solve. Served results stay byte-equal either way: the
    skip path runs exactly the fallback's cold solve (pinned in
    tests/test_warmstart.py)."""
    if set(entry_violated) - set(seed.violated_after):
        return False
    return entry_balancedness >= seed.balancedness_after - band


def apply_seed(state, seed: WarmSeed):
    """``state`` with the seed's mutable pair swapped in — the warm
    search start. The seed arrays enter the chain exactly like the cold
    pair: the first dispatch donates a device COPY (donate_input=False),
    so the stored seed survives the solve (CCSA002's donation contract
    is unchanged)."""
    return dataclasses.replace(state, assignment=seed.assignment,
                               leader_slot=seed.leader_slot)


# -- shape signatures (prewarm registry entries) ---------------------------

_MASK_FIELDS = ("excluded_topics", "excluded_replica_move_brokers",
                "excluded_leadership_brokers")


def goal_spec(g) -> str | dict | None:
    """Reproducible signature spec of ONE goal instance: the bare
    registry name for a default-constructible goal; a ``{"name",
    "state"}`` dict when the goal carries bound JSON-round-trippable
    dataclass state (round 20: bound-broker-set chains prewarm too —
    the round-18 documented gap); None when the instance cannot be
    rebuilt equal in a fresh process (then the chain records nothing,
    as before)."""
    name = type(g).__name__
    try:
        if type(g)() == g:
            return name
    except Exception:  # noqa: BLE001 — bound state; try the dict spec
        pass
    if not dataclasses.is_dataclass(g):
        return None
    try:
        state = json.loads(json.dumps(dataclasses.asdict(g)))
    except (TypeError, ValueError):
        return None
    spec = {"name": name, "state": state}
    try:
        from .analyzer.goals import ALL_GOALS
        # The spec is only a spec if it round-trips to an EQUAL instance
        # — anything lossy (non-tuple containers, derived fields) must
        # fall back to recording nothing rather than prewarming a
        # different program.
        if goal_from_spec(spec, ALL_GOALS) != g:
            return None
    except Exception:  # noqa: BLE001 — unregistered/unbuildable goal
        return None
    return spec


def goal_from_spec(spec: str | dict, registry: dict):
    """Rebuild a goal instance from its signature spec (KeyError for
    names missing from ``registry``). JSON turned the frozen dataclass's
    tuples into lists; top-level sequence fields convert back."""
    if isinstance(spec, str):
        return registry[spec]()
    cls = registry[spec["name"]]
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name in spec.get("state", {}):
            v = spec["state"][f.name]
            kwargs[f.name] = tuple(v) if isinstance(v, list) else v
    return cls(**kwargs)


def shape_signature(state, num_topics: int, goal_chain, masks,
                    batch: int = 0) -> dict | None:
    """JSON-serializable identity of one solved shape: every tensor
    field's (shape, dtype), the mask layout, the goal chain (by
    registry name, or ``goal_spec`` dicts for goals with bound
    JSON-round-trippable state; chains with irreproducible state record
    nothing), and the megabatch width. Enough to rebuild an inert
    synthetic model and re-compile the exact kernel set."""
    names = []
    for g in goal_chain:
        spec = goal_spec(g)
        if spec is None:
            return None
        names.append(spec)
    tensors = {}
    for f in dataclasses.fields(state):
        arr = getattr(state, f.name)
        tensors[f.name] = [list(arr.shape), str(arr.dtype)]
    mask_shapes = {}
    for name in _MASK_FIELDS:
        m = getattr(masks, name)
        mask_shapes[name] = None if m is None \
            else [list(m.shape), str(m.dtype)]
    return {"tensors": tensors, "num_topics": int(num_topics),
            "goals": names, "mask_shapes": mask_shapes,
            "batch": int(batch)}


def synthetic_state(entry: dict):
    """An inert model at the entry's recorded shape (the
    ``inert_state_like`` encoding built from a signature instead of a
    template): all-dead masked brokers, empty masked partitions — every
    kernel compiles fully against it but runs zero search work."""
    import jax.numpy as jnp

    from .common.broker_state import BrokerState
    from .model.tensors import ClusterTensors
    fills = {"assignment": -1, "leader_slot": -1,
             "broker_state": int(BrokerState.DEAD)}
    kwargs = {}
    for name, (shape, dtype) in entry["tensors"].items():
        kwargs[name] = jnp.full(tuple(shape), fills.get(name, 0),
                                dtype=dtype)
    return ClusterTensors(**kwargs)


def synthetic_masks(entry: dict):
    """Inert all-False exclusion masks matching the entry's recorded
    presence layout (mask presence is a compile-time property of the
    kernels)."""
    import jax.numpy as jnp

    from .analyzer.search import ExclusionMasks
    shapes = entry.get("mask_shapes") or {}

    def build(name):
        spec = shapes.get(name)
        if spec is None:
            return None
        return jnp.zeros(tuple(spec[0]), dtype=spec[1])

    return ExclusionMasks(*(build(n) for n in _MASK_FIELDS))


class ShapeRegistry:
    """The persisted set of solved shape signatures, one JSON file under
    the XLA persistent-cache partition dir (host-fingerprint scoped, so
    a machine never prewarms another machine's unloadable artifacts).
    Atomic rewrite on every NEW shape; the set is tiny (one entry per
    padded bucket shape x chain x mask layout)."""

    def __init__(self, path: str):
        self._path = path
        self._lock = threading.Lock()
        self._known: dict[str, dict] | None = None

    @property
    def path(self) -> str:
        return self._path

    def _load_locked(self) -> None:
        if self._known is not None:
            return
        try:
            with open(self._path) as f:
                data = json.load(f)
            self._known = dict(data) if isinstance(data, dict) else {}
        except (OSError, ValueError):
            self._known = {}

    def record(self, entry: dict) -> bool:
        """Add one signature; returns True when it was new (and
        persisted)."""
        key = format(zlib.crc32(
            json.dumps(entry, sort_keys=True).encode()), "08x")
        with self._lock:
            self._load_locked()
            if key in self._known:
                return False
            self._known[key] = entry
            try:
                os.makedirs(os.path.dirname(self._path), exist_ok=True)
                tmp = f"{self._path}.tmp"
                with open(tmp, "w") as f:
                    json.dump(self._known, f, sort_keys=True)
                os.replace(tmp, self._path)
            except OSError:
                LOG.debug("prewarm shape registry write failed",
                          exc_info=True)
        from .utils.sensors import SENSORS
        SENSORS.count("prewarm_shapes_recorded")
        return True

    def entries(self) -> list[dict]:
        with self._lock:
            self._load_locked()
            return [dict(v) for v in self._known.values()]


class PrewarmManager:
    """Background compiler of the known shape set. ``start()`` is
    idempotent and double-start safe (one thread per manager, ever);
    re-prewarming is pointless in-process — the jit caches already hold
    everything the first run compiled. Status is served on GET /state
    and /fleet; the xla_compile_cache_{hits,misses} counters say whether
    the compiles were disk retrievals or cold builds."""

    def __init__(self, optimizer, registry: ShapeRegistry,
                 monotonic=time.monotonic):
        # Weak ref: the module registry is weak-keyed by the optimizer,
        # and a manager (held as that entry's VALUE) strongly
        # referencing its key would keep the key alive forever — the
        # exact leak the weak keying exists to prevent. A sweep whose
        # optimizer died mid-run just stops.
        self._optimizer_ref = weakref.ref(optimizer)
        self._registry = registry
        self._monotonic = monotonic
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self._state = "idle"
        self.shapes_total = 0
        self.shapes_done = 0
        self.shapes_failed = 0
        self.shapes_skipped = 0
        self.duration_s = 0.0

    @property
    def registry(self) -> ShapeRegistry:
        return self._registry

    @property
    def running(self) -> bool:
        with self._lock:
            return self._state == "running"

    def start(self) -> bool:
        """Spawn the prewarm thread; False when already started (running
        OR finished — a second start_up never re-compiles)."""
        with self._lock:
            if self._thread is not None:
                return False
            self._state = "running"
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="solver-prewarm")
            thread = self._thread
        thread.start()
        return True

    def join(self, timeout: float | None = None) -> None:
        with self._lock:
            thread = self._thread
        if thread is not None:
            thread.join(timeout)

    def _run(self) -> None:
        from .utils.sensors import SENSORS
        t0 = self._monotonic()
        entries = self._registry.entries()
        with self._lock:
            self.shapes_total = len(entries)
        for entry in entries:
            optimizer = self._optimizer_ref()
            if optimizer is None:
                break
            try:
                ok = optimizer.prewarm_shape(entry)
            except Exception:  # noqa: BLE001 — warm the rest regardless
                LOG.warning("prewarm of shape entry failed", exc_info=True)
                with self._lock:
                    self.shapes_failed += 1
                SENSORS.count("prewarm_shapes_failed")
                continue
            with self._lock:
                if ok:
                    self.shapes_done += 1
                else:
                    self.shapes_skipped += 1
                self.duration_s = self._monotonic() - t0
            # Two explicit call sites: gen_docs/CCSA006 discover sensor
            # names by scanning for a literal after the call paren, so a
            # conditional name would vanish from SENSORS.md.
            if ok:
                SENSORS.count("prewarm_shapes_compiled")
            else:
                SENSORS.count("prewarm_shapes_skipped")
        with self._lock:
            self._state = "done"
            self.duration_s = self._monotonic() - t0
        SENSORS.gauge("prewarm_duration_seconds", self.duration_s)

    def status_dict(self) -> dict:
        with self._lock:
            return {"state": self._state,
                    "shapesTotal": self.shapes_total,
                    "shapesDone": self.shapes_done,
                    "shapesFailed": self.shapes_failed,
                    "shapesSkipped": self.shapes_skipped,
                    "durationS": round(self.duration_s, 3)}


# Module-level prewarm registry: ONE manager per (prewarm-enabled)
# optimizer, so a fleet's clusters sharing a GoalOptimizer prewarm once
# and a facade restarting its lifecycle never spawns a second compile
# sweep. Weak-keyed by the optimizer: a process that builds and drops
# many prewarm-enabled facades (test suites, embedders) must not pin
# every optimizer — and its jit/controller caches — for process
# lifetime; when the optimizer dies its manager entry (the only strong
# ref to the manager once the sweep thread finishes) dies with it.
_REGISTRY_LOCK = threading.Lock()
_MANAGERS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def ensure_prewarm(optimizer, config, start: bool = True,
                   ) -> PrewarmManager | None:
    """Create (once) and start (idempotently) the prewarm manager for
    ``optimizer`` per ``config``. Returns None when prewarm is disabled
    or the persistent compile cache is off — the shape registry lives in
    the cache's host-partition dir, and prewarming without persistence
    would re-pay every compile on the next restart anyway."""
    if not config.get_boolean("solver.prewarm.enabled"):
        return None
    cache_dir = configure_compile_cache(config)
    if cache_dir is None:
        return None
    with _REGISTRY_LOCK:
        mgr = _MANAGERS.get(optimizer)
        if mgr is None:
            registry = ShapeRegistry(
                os.path.join(cache_dir, "solver_shapes.json"))
            optimizer.attach_shape_registry(registry)
            mgr = PrewarmManager(optimizer, registry)
            _MANAGERS[optimizer] = mgr
    if start:
        mgr.start()
    return mgr


def prewarm_manager(optimizer) -> PrewarmManager | None:
    """The optimizer's prewarm manager, or None when none exists
    (prewarm disabled)."""
    with _REGISTRY_LOCK:
        return _MANAGERS.get(optimizer)


def prewarm_status(optimizer) -> dict | None:
    """The optimizer's prewarm progress (GET /state, GET /fleet), or
    None when no manager exists (prewarm disabled)."""
    mgr = prewarm_manager(optimizer)
    return mgr.status_dict() if mgr is not None else None
