from .tensors import (
    ClusterMeta, ClusterTensors, alive_mask, apply_leadership_move,
    apply_replica_move, apply_swap, broker_leader_counts, broker_load,
    broker_replica_counts, is_leader_slot, new_broker_mask, offline_replicas,
    potential_nw_out, rack_partition_counts, replica_exists, replica_load,
    set_broker_state, topic_broker_leader_counts, topic_broker_replica_counts,
)
from .builder import BrokerSpec, ClusterModelBuilder, PartitionSpec, derive_follower_load
from .refresh import IncrementalModelPipeline, RefreshStats, TopologyCache
from .stats import ClusterModelStats, cluster_stats
from . import fixtures
