"""Incremental device-resident model refresh pipeline.

The cold build path (``build_cluster_from_arrays``) re-derives EVERYTHING
from Python dicts on every ``cluster_model()`` call: sorts the partition
table, rebuilds the broker/rack/host index tables, re-maps every replica
id, and ships every tensor to the device — O(cluster) host work per cycle
even though topology changes are rare between metric windows (BENCH_r05:
9.3 s of model build against 12.8 s of solve at 1k brokers / 100k
partitions).

This pipeline splits the model into the two halves with different change
cadences:

- **Topology** (sorted partition order, the [P, S] replica-index matrix,
  leader/broker/rack/host tables, bucket shapes) — cached host-side, keyed
  by a metadata-generation token (or a structural fingerprint when the
  backend has none), and its device tensors are REUSED across generations
  with no re-transfer at all.
- **Load** (leader/follower [P, R] matrices, leader slots) — re-gathered
  every cycle into preallocated host buffers and shipped with a single
  fused ``device_put`` (with the previous generation's device buffers
  donated back to the allocator first, when the pipeline holds their only
  reference).

Correctness bar (pinned by tests/test_refresh.py): an incremental refresh
is byte-identical to a cold full rebuild for the same inputs — same
dtypes, same padding, same row order.
"""

from __future__ import annotations

import dataclasses
import operator
import threading
import time
from typing import Callable, Mapping, Sequence

import numpy as np

from ..common.resources import NUM_RESOURCES
from .builder import (
    BrokerSpec, _pad_up, build_cluster_from_arrays, graduated_bucket,
)
from .tensors import ClusterMeta, ClusterTensors

# ClusterTensors fields that depend only on topology: on a cache hit their
# device arrays are reused as-is — zero host work, zero transfer.
TOPOLOGY_FIELDS = ("assignment", "capacity", "rack", "broker_state", "topic",
                   "partition_mask", "broker_mask", "host")


def broker_table_fingerprint(brokers: Sequence[BrokerSpec]) -> int:
    """Structural hash of the broker table (id, rack, host, state,
    capacity). Always part of the cache key — capacity-config or
    broker-state changes must invalidate even when the metadata
    generation token says partitions are unchanged."""
    # ccsa: ok[CCSA004] in-process cache key only: compared against keys
    # from the SAME interpreter, never persisted or replayed cross-process
    return hash(tuple(
        (b.broker_id, b.rack, b.host, int(b.state),
         tuple(sorted((int(r), float(v)) for r, v in b.capacity.items())))
        for b in brokers))


def partition_topology_fingerprint(partitions: Mapping) -> int:
    """Fallback key for backends without ``metadata_generation()``:
    hash of the (topic, partition) → replicas structure. The LEADER is
    deliberately excluded — leadership is re-derived on every refresh from
    the live partition states, so a leader-only election stays on the
    cheap path."""
    # ccsa: ok[CCSA004] in-process cache key only (see above)
    return hash(frozenset(
        (t, p, st.replicas) for (t, p), st in partitions.items()))


@dataclasses.dataclass
class RefreshStats:
    """Per-assemble timing breakdown (also exported through SENSORS)."""

    topology_hit: bool
    assemble_s: float   # host-side gather: loads + leader slots
    freeze_s: float     # cold path only: table build + builder freeze
    transfer_s: float   # hit path only: the fused load device_put


@dataclasses.dataclass
class TopologyCache:
    """Everything derivable from metadata alone, frozen until the
    topology key changes."""

    key: tuple
    part_names: list
    # PartitionState rows in (topic, partition) order AS OF THE REBUILD —
    # refreshes read live leaders straight from the partitions mapping,
    # so this list is not updated on hits.
    states: list
    # The partitions mapping's INSERTION order + the permutation taking
    # it to (topic, partition) row order: when a later cycle's mapping
    # iterates in the same order (the common case — backends rebuild the
    # dict from a stable source), per-row gathers run over .values() at
    # C speed and permute, instead of 100k tuple-keyed dict lookups.
    insertion_names: list
    sort_perm: np.ndarray
    rep_ids: np.ndarray          # [P, S] int32 broker IDS (-1 = empty slot)
    n_p: int                     # padded partition rows
    n_b: int                     # padded broker rows
    partition_bucket: int
    broker_bucket: int
    meta: ClusterMeta
    topo_dev: dict               # field name -> device array (reused on hits)
    ll_buf: np.ndarray           # [n_p, R] float32, preallocated
    fl_buf: np.ndarray           # [n_p, R] float32, preallocated
    ls_buf: np.ndarray           # [n_p] int32, preallocated
    # Caller-owned derived caches (e.g. the LoadMonitor's aggregation
    # entity-row lookup); dropped with the cache on topology change.
    scratch: dict = dataclasses.field(default_factory=dict)
    # The previous generation's device load arrays — donated/released
    # before each new transfer.
    load_dev: tuple | None = None


class IncrementalModelPipeline:
    """Topology-cached, buffer-reusing (state, meta) assembler.

    ``fill_loads(cache)`` is the caller's load gather: it must write the
    real rows of ``cache.ll_buf`` / ``cache.fl_buf`` (padding rows arrive
    pre-zeroed). Leadership is derived here, vectorized against the cached
    replica-id matrix — no per-partition ``list.index`` loops.
    """

    def __init__(self, partition_bucket: int = 0, broker_bucket: int = 0,
                 donate: bool | None = None):
        self._partition_bucket = partition_bucket
        self._broker_bucket = broker_bucket
        # None = auto: on CPU the host stays the source of truth and the
        # allocator is the system heap, so early buffer release buys
        # nothing — donate only where device memory is the scarce resource.
        self._donate = donate
        self._cache: TopologyCache | None = None
        self._lock = threading.Lock()
        self.topology_hits = 0
        self.topology_misses = 0
        self.last_stats: RefreshStats | None = None

    # -- public ------------------------------------------------------------
    def invalidate(self) -> None:
        with self._lock:
            self._cache = None

    @property
    def cache(self) -> TopologyCache | None:
        return self._cache

    def assemble(self, brokers: Sequence[BrokerSpec], partitions: Mapping,
                 fill_loads: Callable[[TopologyCache], None],
                 topology_token: object = None,
                 ) -> tuple[ClusterTensors, ClusterMeta]:
        """Build (or refresh) the device-resident model. ``partitions`` is
        the admin backend's ``describe_partitions()`` mapping;
        ``topology_token`` is an O(1) metadata-generation stamp when the
        backend provides one (None → structural fingerprint, O(cluster)
        hashing but still far cheaper than a rebuild)."""
        from ..utils.tracing import TRACER
        t0 = time.perf_counter()
        brokers = sorted(brokers, key=lambda b: b.broker_id)
        bfp = broker_table_fingerprint(brokers)
        if topology_token is None:
            key = ("fp", partition_topology_fingerprint(partitions), bfp)
        else:
            key = ("gen", topology_token, bfp)
        with TRACER.span("model.assemble",
                         num_partitions=len(partitions),
                         num_brokers=len(brokers)), self._lock:
            cache = self._cache
            if cache is not None and cache.key == key \
                    and len(partitions) == len(cache.part_names):
                # Re-gather the LIVE leader per partition in cached sort
                # order: leadership can change without a topology bump
                # (elections) and is re-derived on every refresh. One
                # fused O(P) pass — the cached states list is rebuild-time
                # data and deliberately NOT refreshed here.
                n = len(cache.part_names)
                try:
                    if list(partitions) == cache.insertion_names:
                        raw = np.fromiter(
                            map(operator.attrgetter("leader"),
                                partitions.values()),
                            dtype=np.int32, count=n)
                        leaders = raw[cache.sort_perm]
                    else:
                        leaders = np.fromiter(
                            (partitions[tp].leader
                             for tp in cache.part_names),
                            dtype=np.int32, count=n)
                except KeyError:
                    pass  # key set changed under an unchanged token: rebuild
                else:
                    return self._refresh(cache, leaders, fill_loads, t0)
            return self._rebuild(key, brokers, partitions, fill_loads, t0)

    # -- cold path ---------------------------------------------------------
    def _rebuild(self, key: tuple, brokers: Sequence[BrokerSpec],
                 partitions: Mapping, fill_loads, t0: float,
                 ) -> tuple[ClusterTensors, ClusterMeta]:
        prev = self._cache
        self._cache = None
        self.topology_misses += 1
        ordered = sorted(partitions.items())
        part_names = [tp for tp, _st in ordered]
        states = [st for _tp, st in ordered]
        n = len(ordered)

        # Vectorized [P, S] replica-ID matrix: one flat fromiter + one
        # masked scatter instead of the per-replica Python loop the
        # builder warns "is minutes at 1M partitions".
        if n:
            lens = np.fromiter((len(st.replicas) for st in states),
                               dtype=np.int64, count=n)
            max_rf = max(int(lens.max()), 1)
            rep_ids = np.full((n, max_rf), -1, dtype=np.int32)
            flat = np.fromiter((b for st in states for b in st.replicas),
                               dtype=np.int32, count=int(lens.sum()))
            rep_ids[np.arange(max_rf)[None, :] < lens[:, None]] = flat
        else:
            rep_ids = np.full((0, 1), -1, dtype=np.int32)

        # Bucket hysteresis: a cluster hovering at an ``n // 8`` boundary
        # keeps its previous bucket instead of flapping padded shapes
        # (and recompiling the solver) on alternate cycles.
        pb = graduated_bucket(n, self._partition_bucket,
                              prev=prev.partition_bucket if prev else None)
        bb = graduated_bucket(len(brokers), self._broker_bucket,
                              prev=prev.broker_bucket if prev else None)
        n_p = _pad_up(n, pb)
        n_b = _pad_up(len(brokers), bb)
        insertion_names = list(partitions)
        pos = {k: i for i, k in enumerate(insertion_names)}
        sort_perm = np.fromiter((pos[k] for k in part_names),
                                dtype=np.int64, count=n)
        cache = TopologyCache(
            key=key, part_names=part_names, states=states, rep_ids=rep_ids,
            insertion_names=insertion_names, sort_perm=sort_perm,
            n_p=n_p, n_b=n_b, partition_bucket=pb, broker_bucket=bb,
            meta=None, topo_dev={},
            ll_buf=np.zeros((n_p, NUM_RESOURCES), dtype=np.float32),
            fl_buf=np.zeros((n_p, NUM_RESOURCES), dtype=np.float32),
            ls_buf=np.full((n_p,), -1, dtype=np.int32))
        fill_loads(cache)
        leaders = np.fromiter((st.leader for st in states), dtype=np.int32,
                              count=n) if n else np.zeros(0, dtype=np.int32)
        self._leader_slots(cache, leaders)
        t1 = time.perf_counter()
        state, meta = build_cluster_from_arrays(
            brokers, part_names, rep_ids, cache.ls_buf[:n],
            cache.ll_buf[:n], cache.fl_buf[:n],
            partition_bucket=pb, broker_bucket=bb)
        t2 = time.perf_counter()
        cache.meta = _meta_copy(meta)
        cache.topo_dev = {f: getattr(state, f) for f in TOPOLOGY_FIELDS}
        cache.load_dev = (state.leader_load, state.follower_load,
                          state.leader_slot)
        self._cache = cache
        # Cold build ships EVERYTHING (topology + loads) — account it so
        # the hit path's near-zero transfer is visible by contrast.
        from ..utils.xla_telemetry import record_transfer
        record_transfer(
            sum(getattr(a, "nbytes", 0) for a in cache.topo_dev.values())
            + sum(getattr(a, "nbytes", 0) for a in cache.load_dev),
            direction="h2d", source="model_rebuild")
        self._record(RefreshStats(False, assemble_s=t1 - t0,
                                  freeze_s=t2 - t1, transfer_s=0.0))
        return state, meta

    # -- hit path ----------------------------------------------------------
    def _refresh(self, cache: TopologyCache, leaders: np.ndarray, fill_loads,
                 t0: float) -> tuple[ClusterTensors, ClusterMeta]:
        self.topology_hits += 1
        cache.ll_buf[:] = 0.0
        cache.fl_buf[:] = 0.0
        fill_loads(cache)
        self._leader_slots(cache, leaders)
        t1 = time.perf_counter()
        ll, fl, ls = self._ship(cache)
        t2 = time.perf_counter()
        state = ClusterTensors(
            leader_load=ll, follower_load=fl, leader_slot=ls,
            **cache.topo_dev)
        self._record(RefreshStats(True, assemble_s=t1 - t0, freeze_s=0.0,
                                  transfer_s=t2 - t1))
        return state, _meta_copy(cache.meta)

    def _leader_slots(self, cache: TopologyCache,
                      leaders: np.ndarray) -> None:
        """[P] leader slot indices, vectorized: first replica-id column
        matching the partition's leader (same first-occurrence semantics
        as ``replicas.index(leader)``); -1 when the leader is offline or
        not in the replica list."""
        n = len(leaders)
        cache.ls_buf[:] = -1
        if not n:
            return
        hit = (cache.rep_ids == leaders[:, None]) & (cache.rep_ids >= 0)
        cache.ls_buf[:n] = np.where(hit.any(axis=1),
                                    hit.argmax(axis=1), -1).astype(np.int32)

    def _ship(self, cache: TopologyCache) -> tuple:
        """One fused host→device transfer for the load-dependent tensors.
        The host buffers are REUSED next cycle, so on backends whose
        "transfer" zero-copies host memory (CPU) the arrays are snapshotted
        first — otherwise every previously returned generation would be
        mutated in place. With donation on, the previous generation's
        device buffers are deleted first — when this pipeline holds the
        only reference — so the allocator can serve the new transfer from
        the just-freed memory."""
        import jax
        prev, cache.load_dev = cache.load_dev, None
        donate = self._donate
        if donate is None:
            donate = jax.default_backend() != "cpu"
        if donate and prev is not None and _sole_owner(prev):
            for a in prev:
                a.delete()
        del prev
        host = (cache.ll_buf, cache.fl_buf, cache.ls_buf)
        if _transfer_may_alias_host():
            host = tuple(a.copy() for a in host)
        dev = jax.device_put(host)
        cache.load_dev = dev
        # Transfer accounting: the fused load device_put is THE recurring
        # host→device shipment of the steady-state pipeline; counted in
        # /metrics and attached to the ambient model.assemble span.
        from ..utils.xla_telemetry import record_transfer
        record_transfer(sum(a.nbytes for a in host), direction="h2d",
                        source="model_refresh")
        return dev

    def _record(self, stats: RefreshStats) -> None:
        self.last_stats = stats
        from ..utils.sensors import SENSORS
        from ..utils.tracing import TRACER
        SENSORS.count("model_topology_cache_hit" if stats.topology_hit
                      else "model_topology_cache_miss")
        SENSORS.record_timer("model_refresh_assemble", stats.assemble_s)
        TRACER.annotate(topology_hit=stats.topology_hit,
                        assemble_s=round(stats.assemble_s, 6))
        if stats.topology_hit:
            SENSORS.record_timer("model_refresh_transfer", stats.transfer_s)
        else:
            SENSORS.record_timer("model_refresh_freeze", stats.freeze_s)


def _transfer_may_alias_host() -> bool:
    """Whether ``jax.device_put`` of a numpy array MAY share the host
    buffer instead of copying. The CPU backend zero-copies when alignment
    allows (and ``may_alias=False`` does not force a copy on this jax
    line); accelerator backends always DMA. A runtime probe is no good —
    the zero-copy decision depends on per-buffer alignment — so snapshot
    conservatively on anything host-local."""
    import jax
    return jax.default_backend() not in ("tpu", "gpu", "cuda", "rocm")


def _sole_owner(arrays: tuple) -> bool:
    """True when the pipeline holds the only live reference to each of the
    previous generation's device arrays. Donation DELETES the donated
    buffers — a previous ClusterTensors still held by a caller (proposal
    cache, in-flight solve) must never be invalidated underneath them."""
    import sys
    for a in arrays:
        # Expected refs when sole-owned: the ``arrays`` tuple element, the
        # loop variable ``a``, and getrefcount's own argument — anything
        # beyond 3 is an external holder.
        if sys.getrefcount(a) > 3:
            return False
    return True


def _meta_copy(meta: ClusterMeta) -> ClusterMeta:
    """Fresh ClusterMeta with copied name tables: callers may hold or
    decorate the meta across generations; the cache's copy must stay
    pristine."""
    return ClusterMeta(broker_ids=list(meta.broker_ids),
                       topic_names=list(meta.topic_names),
                       rack_names=list(meta.rack_names),
                       num_topics=meta.num_topics,
                       partition_index=list(meta.partition_index),
                       host_names=list(meta.host_names))
