"""Deterministic and randomized cluster fixtures for tests and benchmarks.

Reference parity (ideas, not data): cruise-control common/DeterministicCluster.java
(small hand-built unbalanced clusters, rack-aware satisfiable/unsatisfiable
topologies) and model/RandomCluster.java (clusters drawn from UNIFORM /
LINEAR / EXPONENTIAL resource distributions).
"""

from __future__ import annotations

import enum

import numpy as np

from ..common.broker_state import BrokerState
from ..common.resources import Resource
from .builder import ClusterModelBuilder
from .tensors import ClusterMeta, ClusterTensors

_CAP = {Resource.CPU: 100.0, Resource.NW_IN: 1000.0,
        Resource.NW_OUT: 1000.0, Resource.DISK: 10000.0}


def small_unbalanced(num_brokers: int = 3, partitions_per_topic: int = 4,
                     rf: int = 2) -> tuple[ClusterTensors, ClusterMeta]:
    """All leaders piled on broker 0 (DeterministicCluster.unbalanced idea):
    replica and leader distribution goals must move load off broker 0."""
    b = ClusterModelBuilder()
    for i in range(num_brokers):
        b.add_broker(i, f"r{i % 2}", _CAP)
    for t in ("t1", "t2"):
        for p in range(partitions_per_topic):
            replicas = [0] + [1 + (p + k) % (num_brokers - 1) for k in range(rf - 1)] \
                if num_brokers > 1 else [0]
            b.add_partition(t, p, replicas,
                            leader_load={Resource.CPU: 10.0, Resource.NW_IN: 50.0,
                                         Resource.NW_OUT: 60.0, Resource.DISK: 300.0})
    return b.build()


def rack_aware_satisfiable() -> tuple[ClusterTensors, ClusterMeta]:
    """Three racks, RF=2, one partition placed with both replicas in the
    same rack (fixable: another rack has room).
    (DeterministicCluster.rackAwareSatisfiable idea.)"""
    b = ClusterModelBuilder()
    b.add_broker(0, "rA", _CAP).add_broker(1, "rA", _CAP)
    b.add_broker(2, "rB", _CAP).add_broker(3, "rC", _CAP)
    load = {Resource.CPU: 5.0, Resource.NW_IN: 20.0, Resource.NW_OUT: 25.0,
            Resource.DISK: 100.0}
    b.add_partition("t1", 0, [0, 1], leader_load=load)      # violation: both in rA
    b.add_partition("t1", 1, [2, 0], leader_load=load)
    b.add_partition("t1", 2, [3, 2], leader_load=load)
    return b.build()


def rack_aware_unsatisfiable() -> tuple[ClusterTensors, ClusterMeta]:
    """RF=3 but only two racks: RackAwareGoal must fail
    (DeterministicCluster.rackAwareUnsatisfiable idea)."""
    b = ClusterModelBuilder()
    b.add_broker(0, "rA", _CAP).add_broker(1, "rA", _CAP).add_broker(2, "rB", _CAP)
    load = {Resource.CPU: 5.0, Resource.NW_IN: 20.0, Resource.NW_OUT: 25.0,
            Resource.DISK: 100.0}
    b.add_partition("t1", 0, [0, 1, 2], leader_load=load)
    return b.build()


def dead_broker_cluster() -> tuple[ClusterTensors, ClusterMeta]:
    """A 4-broker cluster where broker 3 is DEAD and hosts replicas —
    self-healing must move them (deadBroker fixture idea)."""
    b = ClusterModelBuilder()
    for i in range(3):
        b.add_broker(i, f"r{i}", _CAP)
    b.add_broker(3, "r0", _CAP, state=BrokerState.DEAD)
    load = {Resource.CPU: 5.0, Resource.NW_IN: 20.0, Resource.NW_OUT: 25.0,
            Resource.DISK: 100.0}
    for p in range(4):
        b.add_partition("t1", p, [3, (p % 3)], leader_load=load)
    return b.build()


class Dist(enum.Enum):
    UNIFORM = "uniform"
    LINEAR = "linear"
    EXPONENTIAL = "exponential"


def random_cluster(num_brokers: int, num_topics: int, num_partitions: int,
                   rf: int = 3, num_racks: int = 4, dist: Dist = Dist.UNIFORM,
                   seed: int = 0, skew_to_first: float = 0.0,
                   partition_bucket: int = 0, broker_bucket: int = 0,
                   target_utilization: float = 0.5,
                   brokers_per_host: int = 1,
                   ) -> tuple[ClusterTensors, ClusterMeta]:
    """Random cluster à la RandomCluster.java: partition loads drawn from the
    given distribution; ``skew_to_first`` biases placement toward low-index
    brokers to create imbalance worth fixing. Loads are normalized so the
    cluster-average NW_OUT utilization ≈ ``target_utilization``.

    ``num_racks=0`` builds a RACKLESS cluster; with ``brokers_per_host``
    > 1 consecutive brokers share a physical host, so the fault domain
    degrades to host-awareness (Host.java / rack-falls-back-to-host)."""
    rng = np.random.default_rng(seed)
    rf = min(rf, num_brokers)
    b = ClusterModelBuilder(partition_bucket=partition_bucket, broker_bucket=broker_bucket)
    for i in range(num_brokers):
        b.add_broker(i, f"rack{i % num_racks}" if num_racks > 0 else "",
                     _CAP, host=(f"host{i // brokers_per_host}"
                                 if brokers_per_host > 1 else ""))

    if dist is Dist.UNIFORM:
        base = rng.uniform(0.2, 1.0, size=num_partitions)
    elif dist is Dist.LINEAR:
        base = np.linspace(0.1, 1.0, num_partitions)
        rng.shuffle(base)
    else:
        base = rng.exponential(0.3, size=num_partitions).clip(0.02, 3.0)

    topic_of = rng.integers(0, num_topics, size=num_partitions)
    weights = np.ones(num_brokers)
    if skew_to_first > 0:
        weights = np.exp(-skew_to_first * np.arange(num_brokers) / max(1, num_brokers - 1))
    weights = weights / weights.sum()

    # Per-resource load coefficients solved so each resource's expected
    # cluster-average utilization ≈ target. Replication multiplies NW_IN and
    # DISK by rf and CPU by 1 + follower_fraction·(rf-1); NW_OUT is
    # leader-only (derive_follower_load semantics).
    mean_scale = float(base.mean())
    per_broker = num_partitions / num_brokers * mean_scale
    coeff = {
        Resource.NW_OUT: target_utilization * _CAP[Resource.NW_OUT] / per_broker,
        Resource.NW_IN: target_utilization * _CAP[Resource.NW_IN] / (per_broker * rf),
        Resource.DISK: target_utilization * _CAP[Resource.DISK] / (per_broker * rf),
        Resource.CPU: target_utilization * _CAP[Resource.CPU]
        / (per_broker * (1.0 + 0.4 * (rf - 1))),
    }

    if num_partitions >= 200_000:
        return _random_cluster_bulk(b, rng, num_brokers, num_partitions, rf,
                                    topic_of, base, weights, coeff)

    per_topic_counter: dict[int, int] = {}
    for i in range(num_partitions):
        t = int(topic_of[i])
        pnum = per_topic_counter.get(t, 0)
        per_topic_counter[t] = pnum + 1
        replicas = rng.choice(num_brokers, size=rf, replace=False, p=weights)
        scale = float(base[i])
        b.add_partition(
            f"topic{t}", pnum, [int(x) for x in replicas],
            leader_load={r: coeff[r] * scale for r in Resource})
    return b.build()


def _random_cluster_bulk(b: ClusterModelBuilder, rng, num_brokers: int,
                         num_partitions: int, rf: int, topic_of, base,
                         weights, coeff) -> tuple[ClusterTensors, ClusterMeta]:
    """Vectorized generator for LinkedIn-scale fixtures (7k brokers / 1M
    partitions): the per-partition ``rng.choice(replace=False, p=...)``
    loop costs minutes at that size. Weighted sampling rides the inverse
    CDF (with replacement), then only the rows that drew a duplicate
    broker are re-drawn — a vanishing fraction when rf ≪ num_brokers."""
    from .builder import build_cluster_from_arrays
    from ..common.resources import NUM_RESOURCES

    cdf = np.cumsum(weights)
    replicas = np.searchsorted(
        cdf, rng.random((num_partitions, rf)) * cdf[-1]).astype(np.int32)
    replicas = np.minimum(replicas, num_brokers - 1)
    for _ in range(64):
        srt = np.sort(replicas, axis=1)
        bad = (srt[:, 1:] == srt[:, :-1]).any(axis=1)
        if not bad.any():
            break
        n_bad = int(bad.sum())
        replicas[bad] = np.minimum(np.searchsorted(
            cdf, rng.random((n_bad, rf)) * cdf[-1]), num_brokers - 1)
    else:  # pragma: no cover - rf ~ num_brokers degenerate case
        for i in np.flatnonzero(bad):
            replicas[i] = rng.choice(num_brokers, size=rf, replace=False,
                                     p=weights)

    # Partition numbers in draw order within each topic, rows ordered by
    # (lexicographic topic name, partition) — identical layout to the
    # per-partition builder path.
    names = [f"topic{t}" for t in range(len(np.bincount(topic_of)))]
    order = np.argsort(topic_of, kind="stable")
    counts = np.bincount(topic_of, minlength=len(names))
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    pnum_sorted = np.arange(num_partitions) - np.repeat(starts, counts)
    pnum = np.empty(num_partitions, dtype=np.int64)
    pnum[order] = pnum_sorted
    lex = np.argsort(np.array(names))
    lex_rank = np.empty(len(names), dtype=np.int64)
    lex_rank[lex] = np.arange(len(names))
    row_order = np.lexsort((pnum, lex_rank[topic_of]))

    ll = np.zeros((num_partitions, NUM_RESOURCES), dtype=np.float32)
    for r, c in coeff.items():
        ll[:, int(r)] = c * base
    # Vectorized derive_follower_load (same 0.4 follower CPU fraction).
    fl = np.array(ll, dtype=np.float32)
    fl[:, int(Resource.NW_OUT)] = 0.0
    fl[:, int(Resource.CPU)] *= 0.4

    part_names = [(names[int(t)], int(p))
                  for t, p in zip(topic_of[row_order], pnum[row_order])]
    return build_cluster_from_arrays(
        b.broker_specs, part_names, replicas[row_order],
        np.zeros(num_partitions, dtype=np.int32),
        ll[row_order], fl[row_order],
        partition_bucket=b.partition_bucket,
        broker_bucket=b.broker_bucket)
