"""The cluster model as dense device tensors.

Reference parity: model/ClusterModel.java (rack→broker→replica topology with
per-replica load), model/Load.java, model/Partition.java. Where the
reference keeps a mutable object graph and mutates it during search, this
model is a frozen pytree of arrays; "mutation" is a functional update that
XLA fuses into the search loop, and a model "generation" is simply a new
pytree value.

Array schema (P partitions × S replica slots × B brokers × R resources):

- ``assignment[P, S]`` int32 — broker index per replica slot, -1 empty.
- ``leader_slot[P]`` int32 — which slot is the leader (-1 = offline/no leader).
- ``leader_load[P, R]`` float32 — resource load a broker bears when hosting
  the leader replica (CPU=leader cpu, NW_IN=leader bytes-in, NW_OUT=leader
  bytes-out, DISK=partition size; MonitorUtils.populatePartitionLoad).
- ``follower_load[P, R]`` float32 — load when hosting a follower (follower
  cpu estimate, replication bytes-in, zero NW_OUT, same disk).
- ``capacity[B, R]`` float32 — broker capacity (BrokerCapacityConfigResolver).
- ``rack[B]`` int32 — rack index per broker (Rack.java topology flattened).
- ``broker_state[B]`` int8 — BrokerState codes (ALIVE/DEAD/NEW/DEMOTED/BAD_DISKS).
- ``topic[P]`` int32 — topic index per partition.
- ``partition_mask[P]`` / ``broker_mask[B]`` bool — padding masks (static
  shapes for XLA; clusters are padded up to bucket sizes).

Padded replica slots use broker index = B (one-past-the-end) inside kernels
so segment reductions drop them without branching.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..common.broker_state import BrokerState


@partial(jax.tree_util.register_dataclass,
         data_fields=["assignment", "leader_slot", "leader_load", "follower_load",
                      "capacity", "rack", "broker_state", "topic",
                      "partition_mask", "broker_mask", "host"],
         meta_fields=[])
@dataclasses.dataclass(frozen=True)
class ClusterTensors:
    assignment: jax.Array     # [P, S] int32
    leader_slot: jax.Array    # [P] int32
    leader_load: jax.Array    # [P, R] float32
    follower_load: jax.Array  # [P, R] float32
    capacity: jax.Array       # [B, R] float32
    # Fault-domain index per broker (Rack.java semantics): the builder
    # folds rack-falls-back-to-host in — a broker with no configured rack
    # gets its HOST's domain, so co-hosted brokers share one rack index
    # (ClusterModel.handleDeadBroker / Host.java level). Rack-aware goal
    # kernels therefore need no host special-casing.
    rack: jax.Array           # [B] int32
    broker_state: jax.Array   # [B] int8
    topic: jax.Array          # [P] int32
    partition_mask: jax.Array  # [P] bool
    broker_mask: jax.Array    # [B] bool
    # Physical host index per broker (model/Host.java, the level between
    # rack and broker): multiple brokers may share a host; host-level
    # stats and the rack fallback derive from it. Defaults to one host
    # per broker when topology is unknown.
    host: jax.Array = None    # [B] int32

    def __post_init__(self):
        # Default host topology = one host per broker. Guarded on capacity
        # actually being an array: pytree unflattens re-enter __init__ with
        # arbitrary leaf payloads (tree_map/broadcast_prefix pass None or
        # spec objects through), and those dummy trees must round-trip
        # untouched.
        if self.host is None and hasattr(self.capacity, "shape"):
            object.__setattr__(
                self, "host",
                jnp.arange(self.capacity.shape[0], dtype=jnp.int32))

    @property
    def num_partitions(self) -> int:
        return self.assignment.shape[0]

    @property
    def max_replication_factor(self) -> int:
        return self.assignment.shape[1]

    @property
    def num_brokers(self) -> int:
        return self.capacity.shape[0]

    @property
    def num_topics(self) -> int:
        # Static upper bound: topics are indexed densely by the builder.
        return self.num_partitions


@dataclasses.dataclass
class ClusterMeta:
    """Host-side names for the integer indices of a ClusterTensors value
    (broker ids, topic names, rack names). Not traced."""

    broker_ids: list[int]
    topic_names: list[str]
    rack_names: list[str]
    num_topics: int
    partition_index: list[tuple[str, int]]  # row → (topic, partition number)
    # Physical host names indexed by ClusterTensors.host (Host.java level);
    # empty when the builder predates host topology.
    host_names: list[str] = dataclasses.field(default_factory=list)


# ---- derived quantities (all jittable) -----------------------------------

def replica_exists(state: ClusterTensors) -> jax.Array:
    """[P, S] bool — slot holds a real replica of a real partition."""
    return (state.assignment >= 0) & state.partition_mask[:, None]


def is_leader_slot(state: ClusterTensors) -> jax.Array:
    """[P, S] bool — slot is the partition's leader."""
    s = jnp.arange(state.max_replication_factor, dtype=state.leader_slot.dtype)
    return (state.leader_slot[:, None] == s[None, :]) & replica_exists(state)


def replica_load(state: ClusterTensors) -> jax.Array:
    """[P, S, R] float32 — per-slot resource load (leader vs follower)."""
    lead = is_leader_slot(state)
    load = jnp.where(lead[:, :, None], state.leader_load[:, None, :],
                     state.follower_load[:, None, :])
    return load * replica_exists(state)[:, :, None]


def replica_load_total(state: ClusterTensors) -> jax.Array:
    """[P, S] float32 — summed-over-resources load per replica slot.
    Equivalent to ``replica_load(state).sum(axis=-1)`` without
    materializing the [P, S, R] cube: the per-partition leader/follower
    totals are loop-invariant [P] reductions (XLA hoists them out of the
    search while-loop), leaving only a [P, S] select per round."""
    lsum = state.leader_load.sum(axis=-1)
    fsum = state.follower_load.sum(axis=-1)
    lead = is_leader_slot(state)
    return jnp.where(lead, lsum[:, None], fsum[:, None]) \
        * replica_exists(state)


def replica_load_column(state: ClusterTensors, r: int) -> jax.Array:
    """[P, S] float32 — one resource column of the per-replica load,
    without the [P, S, R] materialization (see replica_load_total)."""
    lead = is_leader_slot(state)
    return jnp.where(lead, state.leader_load[:, r][:, None],
                     state.follower_load[:, r][:, None]) \
        * replica_exists(state)


def _scatter_to_brokers(state: ClusterTensors, per_slot: jax.Array) -> jax.Array:
    """Sum a [P, S] or [P, S, R] per-replica quantity into per-broker rows
    ([B] or [B, R]). Padded slots route to a dead bucket at index B."""
    b = state.num_brokers
    seg = jnp.where(state.assignment >= 0, state.assignment, b).reshape(-1)
    flat = per_slot.reshape((seg.shape[0],) + per_slot.shape[2:])
    out = jax.ops.segment_sum(flat, seg, num_segments=b + 1)
    return out[:b]


def broker_load(state: ClusterTensors) -> jax.Array:
    """[B, R] float32 — total resource load per broker
    (ClusterModel load accounting; the solver's hottest reduction)."""
    return _scatter_to_brokers(state, replica_load(state))


def broker_replica_counts(state: ClusterTensors) -> jax.Array:
    """[B] int32 — replicas hosted per broker."""
    return _scatter_to_brokers(state, replica_exists(state).astype(jnp.int32))


def broker_leader_counts(state: ClusterTensors) -> jax.Array:
    """[B] int32 — leader replicas per broker."""
    return _scatter_to_brokers(state, is_leader_slot(state).astype(jnp.int32))


def _topic_broker_counts(state: ClusterTensors, num_topics: int,
                         per_slot: jax.Array) -> jax.Array:
    """[T, B] int32 — count of ``per_slot``-selected replicas per
    (topic, broker) via one flattened segment-sum; masked-out slots route to
    a one-past-the-end bucket."""
    b = state.num_brokers
    seg = jnp.where(per_slot, state.topic[:, None] * (b + 1)
                    + jnp.where(state.assignment >= 0, state.assignment, b),
                    num_topics * (b + 1))
    flat = per_slot.astype(jnp.int32).reshape(-1)
    out = jax.ops.segment_sum(flat, seg.reshape(-1), num_segments=num_topics * (b + 1) + 1)
    return out[:num_topics * (b + 1)].reshape(num_topics, b + 1)[:, :b]


def topic_broker_replica_counts(state: ClusterTensors, num_topics: int) -> jax.Array:
    """[T, B] int32 — replicas per (topic, broker), for topic-replica
    distribution and min-topic-leaders goals."""
    return _topic_broker_counts(state, num_topics, replica_exists(state))


def topic_broker_leader_counts(state: ClusterTensors, num_topics: int) -> jax.Array:
    """[T, B] int32 — leaders per (topic, broker)."""
    return _topic_broker_counts(state, num_topics, is_leader_slot(state))


def potential_nw_out(state: ClusterTensors) -> jax.Array:
    """[B] float32 — potential network-outbound load per broker: the NW_OUT
    every broker would bear if all its replicas became leaders
    (ClusterModel.potentialLeadershipLoadFor; used by PotentialNwOutGoal)."""
    from ..common.resources import Resource
    nw_out = state.leader_load[:, Resource.NW_OUT]
    per_slot = jnp.broadcast_to(nw_out[:, None], state.assignment.shape) \
        * replica_exists(state)
    return _scatter_to_brokers(state, per_slot)


def leader_bytes_in(state: ClusterTensors) -> jax.Array:
    """[B] float32 — leader NW_IN per broker (the LeaderBytesInDistribution
    aggregate; also maintained incrementally by analyzer.agg)."""
    from ..common.resources import Resource
    per_slot = jnp.where(
        is_leader_slot(state),
        jnp.broadcast_to(state.leader_load[:, int(Resource.NW_IN)][:, None],
                         state.assignment.shape),
        0.0)
    return _scatter_to_brokers(state, per_slot)


def rack_partition_counts(state: ClusterTensors, num_racks: int) -> jax.Array:
    """[P, K] int32 — replicas of each partition per rack (rack-aware goals)."""
    exists = replica_exists(state)
    broker_rack = jnp.concatenate([state.rack, jnp.array([num_racks], dtype=state.rack.dtype)])
    slot_rack = broker_rack[jnp.where(state.assignment >= 0, state.assignment,
                                      state.num_brokers)]
    one_hot = jax.nn.one_hot(slot_rack, num_racks + 1, dtype=jnp.int32)
    return (one_hot * exists[:, :, None].astype(jnp.int32)).sum(axis=1)[:, :num_racks]


def alive_mask(state: ClusterTensors) -> jax.Array:
    """[B] bool — broker alive & real (Broker.State ALIVE/NEW/DEMOTED/BAD_DISKS
    count as alive for hosting; DEAD does not: Broker.java isAlive)."""
    return (state.broker_state != jnp.int8(BrokerState.DEAD)) & state.broker_mask


def new_broker_mask(state: ClusterTensors) -> jax.Array:
    return (state.broker_state == jnp.int8(BrokerState.NEW)) & state.broker_mask


def offline_replicas(state: ClusterTensors) -> jax.Array:
    """[P, S] bool — replicas on dead brokers (self-healing eligible;
    ClusterModel.selfHealingEligibleReplicas)."""
    dead = ~alive_mask(state)
    dead_pad = jnp.concatenate([dead, jnp.array([True])])
    return replica_exists(state) & dead_pad[
        jnp.where(state.assignment >= 0, state.assignment, state.num_brokers)]


# ---- functional mutations (the search's move operators) ------------------

def apply_replica_move(state: ClusterTensors, partition: jax.Array, slot: jax.Array,
                       dst_broker: jax.Array) -> ClusterTensors:
    """Move the replica at (partition, slot) to dst_broker
    (ClusterModel.relocateReplica:380, functional)."""
    new_assignment = state.assignment.at[partition, slot].set(
        dst_broker.astype(state.assignment.dtype))
    return dataclasses.replace(state, assignment=new_assignment)


def apply_leadership_move(state: ClusterTensors, partition: jax.Array,
                          new_leader_slot: jax.Array) -> ClusterTensors:
    """Transfer leadership to another in-sync slot
    (ClusterModel.relocateLeadership:409, functional)."""
    new_leader = state.leader_slot.at[partition].set(
        new_leader_slot.astype(state.leader_slot.dtype))
    return dataclasses.replace(state, leader_slot=new_leader)


def apply_swap(state: ClusterTensors, p1: jax.Array, s1: jax.Array,
               p2: jax.Array, s2: jax.Array) -> ClusterTensors:
    """Swap the broker placements of two replicas (INTER_BROKER_REPLICA_SWAP)."""
    b1 = state.assignment[p1, s1]
    b2 = state.assignment[p2, s2]
    new_assignment = state.assignment.at[p1, s1].set(b2).at[p2, s2].set(b1)
    return dataclasses.replace(state, assignment=new_assignment)


def set_broker_state(state: ClusterTensors, broker: jax.Array, code: int) -> ClusterTensors:
    """(ClusterModel.setBrokerState:297, functional)."""
    return dataclasses.replace(
        state, broker_state=state.broker_state.at[broker].set(jnp.int8(code)))
