"""JBOD disk modeling + the intra-broker disk balancer.

Reference parity: model/Disk.java (per-disk capacity + ALIVE/DEAD state),
ClusterModel's disk-aware replica placement, and the intra-broker goals
IntraBrokerDiskCapacityGoal.java:316 /
IntraBrokerDiskUsageDistributionGoal.java:509 (move replicas between one
broker's log dirs to respect per-disk capacity and balance usage).

Kernel design: brokers are INDEPENDENT for intra-broker moves, so the
balancer runs one move per broker per round, every broker in parallel — a
[B]-wide vectorized greedy with no conflict resolution needed (the
reference serializes disk-by-disk inside each broker). Disk identity is
(broker, disk-slot); dead disks are treated as infinitely over capacity so
their replicas drain first (the remove-disks / fix-offline-dirs path).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..common.resources import Resource
from .tensors import ClusterMeta, ClusterTensors, replica_exists, replica_load


@partial(jax.tree_util.register_dataclass,
         data_fields=["disk_assignment", "disk_capacity", "disk_alive"],
         meta_fields=[])
@dataclasses.dataclass(frozen=True)
class DiskTensors:
    disk_assignment: jax.Array   # [P, S] int32 — disk slot within the broker, -1 none
    disk_capacity: jax.Array     # [B, D] float32 — 0 = slot unused
    disk_alive: jax.Array        # [B, D] bool

    @property
    def max_disks(self) -> int:
        return self.disk_capacity.shape[1]


@dataclasses.dataclass
class DiskMeta:
    """Host-side log-dir names per (broker index, disk slot)."""

    dir_names: list[list[str]]   # [B][D] ('' for unused slots)

    def slot_of(self, broker_idx: int, logdir: str) -> int:
        return self.dir_names[broker_idx].index(logdir)


def disk_load(state: ClusterTensors, disks: DiskTensors) -> jax.Array:
    """[B, D] — disk-resource load per (broker, disk slot)."""
    b, d = state.num_brokers, disks.max_disks
    exists = replica_exists(state) & (disks.disk_assignment >= 0)
    seg = jnp.where(exists,
                    state.assignment * d + disks.disk_assignment,
                    b * d)
    load = replica_load(state)[:, :, Resource.DISK]
    out = jax.ops.segment_sum(jnp.where(exists, load, 0.0).reshape(-1),
                              seg.reshape(-1), num_segments=b * d + 1)
    return out[: b * d].reshape(b, d)


def intra_broker_violations(state: ClusterTensors, disks: DiskTensors,
                            capacity_threshold: float = 0.8,
                            balance_band: tuple[float, float] | None = None,
                            ) -> jax.Array:
    """[B, D] violation magnitude: load beyond capacity·threshold, any load
    on a dead disk, and (optionally) load outside the per-broker balance
    band — the two intra-broker goals' objectives fused."""
    load = disk_load(state, disks)
    cap = disks.disk_capacity
    present = cap > 0
    over_cap = jnp.maximum(load - cap * capacity_threshold, 0.0)
    dead = present & ~disks.disk_alive
    v = jnp.where(present, over_cap, 0.0) + jnp.where(dead, load, 0.0)
    if balance_band is not None:
        lower, upper = balance_band
        util = jnp.where(present, load / jnp.maximum(cap, 1e-9), 0.0)
        alive = present & disks.disk_alive
        n_alive = jnp.maximum(alive.sum(axis=1, keepdims=True), 1)
        avg = (util * alive).sum(axis=1, keepdims=True) / n_alive
        band_v = jnp.maximum(util - avg * upper, 0.0) \
            + jnp.maximum(avg * lower - util, 0.0)
        v = v + jnp.where(alive, band_v * cap, 0.0)
    return v


def balance_intra_broker(state: ClusterTensors, disks: DiskTensors,
                         capacity_threshold: float = 0.8,
                         balance_band: tuple[float, float] | None = None,
                         max_rounds: int = 64,
                         movable: "jax.Array | None" = None) -> DiskTensors:
    """One fused `lax.while_loop`: per round, EVERY broker moves the
    heaviest replica off its most-violating disk onto its least-utilized
    alive disk (if that improves the violation), until fixed-point.

    ``movable`` ([P] bool, optional) pins partitions whose replicas must
    never move (topics.excluded.from.partition.movement): their load still
    counts toward disk utilization, they are just never candidates."""
    b, d = state.num_brokers, disks.max_disks
    p_count, s = state.assignment.shape
    rep_load = replica_load(state)[:, :, Resource.DISK]            # [P, S]
    exists = replica_exists(state)
    if movable is not None:
        exists_candidates = exists & movable[:, None]
    else:
        exists_candidates = exists
    # Flatten replicas for per-(broker,disk) argmax selection: for each
    # (broker, disk) find its heaviest replica each round via segment_max.
    flat_broker = jnp.where(exists, state.assignment, b).reshape(-1)
    flat_load = jnp.where(exists_candidates, rep_load, -1.0).reshape(-1)

    def round_fn(carry):
        assign, _moved = carry
        load = _disk_load_from(assign)
        cap = disks.disk_capacity
        present = cap > 0
        alive = present & disks.disk_alive
        # Source pressure = only the SHED side of the violation (over
        # capacity, dead-disk load, above the band): an underfull disk has
        # nothing to move and is the *destination*, not a source.
        viol = _shed_pressure_from(load)
        src_disk = jnp.argmax(viol, axis=1)                         # [B]
        has_viol = jnp.take_along_axis(viol, src_disk[:, None], axis=1)[:, 0] > 1e-9
        util = jnp.where(alive, load / jnp.maximum(cap, 1e-9), jnp.inf)
        dst_disk = jnp.argmin(util, axis=1)                         # [B]
        dst_ok = jnp.take_along_axis(alive, dst_disk[:, None], axis=1)[:, 0] \
            & (dst_disk != src_disk)

        # Heaviest replica on (broker, src_disk[broker]) per broker.
        flat_disk = jnp.where((assign >= 0) & exists, assign, -1).reshape(-1)
        on_src = (flat_disk == src_disk[jnp.clip(flat_broker, 0, b - 1)]) \
            & (flat_broker < b)
        seg = jnp.where(on_src, flat_broker, b)
        # argmax per broker via one-hot of max value
        score = jnp.where(on_src, flat_load, -1.0)
        best = jax.ops.segment_max(score, seg, num_segments=b + 1)[:b]   # [B]
        is_best = on_src & (score == best[jnp.clip(flat_broker, 0, b - 1)]) \
            & (score >= 0)
        # First best index per broker:
        idx = jnp.where(is_best, jnp.arange(p_count * s), p_count * s)
        pick = jax.ops.segment_min(idx, seg, num_segments=b + 1)[:b]     # [B]
        valid = has_viol & dst_ok & (pick < p_count * s)

        rows = jnp.clip(pick // s, 0, p_count - 1)
        cols = jnp.clip(pick % s, 0, s - 1)
        new_assign = assign.at[rows, cols].set(
            jnp.where(valid, dst_disk.astype(assign.dtype),
                      assign[rows, cols]))
        return new_assign, valid.any()

    def _disk_load_from(assign):
        ex = exists & (assign >= 0)
        seg = jnp.where(ex, state.assignment * d + assign, b * d)
        out = jax.ops.segment_sum(jnp.where(ex, rep_load, 0.0).reshape(-1),
                                  seg.reshape(-1), num_segments=b * d + 1)
        return out[: b * d].reshape(b, d)

    def _shed_pressure_from(load):
        cap = disks.disk_capacity
        present = cap > 0
        over = jnp.maximum(load - cap * capacity_threshold, 0.0)
        dead = present & ~disks.disk_alive
        v = jnp.where(present, over, 0.0) + jnp.where(dead, load, 0.0)
        if balance_band is not None:
            _lower, upper = balance_band
            util = jnp.where(present, load / jnp.maximum(cap, 1e-9), 0.0)
            alive = present & disks.disk_alive
            n_alive = jnp.maximum(alive.sum(axis=1, keepdims=True), 1)
            avg = (util * alive).sum(axis=1, keepdims=True) / n_alive
            v = v + jnp.where(alive,
                              jnp.maximum(util - avg * upper, 0.0) * cap, 0.0)
        return v

    def cond(carry_round):
        (_assign, moved), i = carry_round
        return moved & (i < max_rounds)

    def body(carry_round):
        (assign, _moved), i = carry_round
        return round_fn((assign, True)), i + 1

    (assign, _), _rounds = jax.lax.while_loop(
        cond, body, ((disks.disk_assignment, jnp.asarray(True)),
                     jnp.asarray(0)))
    return dataclasses.replace(disks, disk_assignment=assign)


@dataclasses.dataclass(frozen=True)
class IntraBrokerMove:
    """One logdir move (ExecutionProposal's intra-broker leg)."""

    topic: str
    partition: int
    broker_id: int
    source_logdir: str
    destination_logdir: str


def diff_intra_broker_moves(initial: DiskTensors, final: DiskTensors,
                            state: ClusterTensors, meta: ClusterMeta,
                            disk_meta: DiskMeta) -> list[IntraBrokerMove]:
    """Mirror of AnalyzerUtils.getDiff for the disk axis."""
    before = np.asarray(initial.disk_assignment)
    after = np.asarray(final.disk_assignment)
    assign = np.asarray(state.assignment)
    exists = np.asarray(replica_exists(state))
    moves: list[IntraBrokerMove] = []
    for p_idx, s_idx in zip(*np.nonzero((before != after) & exists)):
        broker_idx = int(assign[p_idx, s_idx])
        topic, part = meta.partition_index[int(p_idx)]
        names = disk_meta.dir_names[broker_idx]
        moves.append(IntraBrokerMove(
            topic=topic, partition=part,
            broker_id=meta.broker_ids[broker_idx],
            source_logdir=names[int(before[p_idx, s_idx])],
            destination_logdir=names[int(after[p_idx, s_idx])]))
    return moves


def build_disk_tensors(state: ClusterTensors, meta: ClusterMeta,
                       logdirs_by_broker: dict[int, dict[str, bool]],
                       replica_dirs: dict[tuple[str, int, int], str],
                       capacity_by_dir: dict[tuple[int, str], float] | None = None,
                       default_capacity: float = 1e12,
                       ) -> tuple[DiskTensors, DiskMeta]:
    """Assemble DiskTensors from backend JBOD facts (describe_logdirs +
    replica_logdirs + per-dir capacities from capacityJBOD.json)."""
    b = state.num_brokers
    s = state.max_replication_factor
    idx_of = {bid: i for i, bid in enumerate(meta.broker_ids)}
    dir_names: list[list[str]] = [[] for _ in range(b)]
    for bid, dirs in logdirs_by_broker.items():
        if bid in idx_of:
            dir_names[idx_of[bid]] = sorted(dirs)
    d = max((len(n) for n in dir_names), default=1) or 1
    cap = np.zeros((b, d), dtype=np.float32)
    alive = np.zeros((b, d), dtype=bool)
    for bid, dirs in logdirs_by_broker.items():
        if bid not in idx_of:
            continue
        i = idx_of[bid]
        for slot, name in enumerate(dir_names[i]):
            cap[i, slot] = (capacity_by_dir or {}).get((bid, name),
                                                       default_capacity)
            alive[i, slot] = dirs[name]
        dir_names[i] += [""] * (d - len(dir_names[i]))
    for i in range(b):
        if not dir_names[i]:
            dir_names[i] = [""] * d

    assign = np.asarray(state.assignment)
    disk_assign = np.full((state.num_partitions, s), -1, dtype=np.int32)
    for p_idx, (topic, part) in enumerate(meta.partition_index):
        for s_idx in range(s):
            broker_idx = assign[p_idx, s_idx]
            if broker_idx < 0:
                continue
            bid = meta.broker_ids[broker_idx]
            logdir = replica_dirs.get((topic, part, bid))
            if logdir and logdir in dir_names[broker_idx]:
                disk_assign[p_idx, s_idx] = dir_names[broker_idx].index(logdir)
    return (DiskTensors(disk_assignment=jnp.asarray(disk_assign),
                        disk_capacity=jnp.asarray(cap),
                        disk_alive=jnp.asarray(alive)),
            DiskMeta(dir_names=dir_names))
