"""Host-side builder: topology description → ClusterTensors + ClusterMeta.

Reference parity: the construction path LoadMonitor.clusterModel →
createRack/createBroker/createReplica/setReplicaLoads
(ClusterModel.java:297-520, MonitorUtils.populatePartitionLoad:415).
Redesign: the builder collects plain Python/numpy rows then freezes them
into padded device arrays once; there is no mutable model object.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Sequence

import numpy as np

from ..common.broker_state import BrokerState
from ..common.resources import NUM_RESOURCES, Resource
from .tensors import ClusterMeta, ClusterTensors


def graduated_bucket(n: int, bucket: int, prev: int | None = None,
                     hysteresis: float = 0.125) -> int:
    """Shape-bucket size capped at ~n/8: padding overhead stays bounded
    (≤ ~12.5%) while shapes still quantize to a handful per octave, so
    ordinary cluster growth reuses compiled kernels without tiny clusters
    paying large pads (solver.partition.bucket.size semantics).

    ``prev`` is the bucket last used for this axis: a cluster hovering at
    an ``n // 8`` boundary (bucket b is freshly selected iff n >= 8b)
    would otherwise flap between b and b/2 — alternating padded shapes
    and recompiling the solver chain on alternate cycles. With
    hysteresis, the previous bucket is kept while n stays inside
    ``[8·prev·(1-h), 16·prev·(1+h))``, so only a real move past a
    boundary (by margin h) changes the padded shape. The padding-overhead
    bound loosens to ~12.5%·(1+h) while the sticky bucket is held."""
    if bucket <= 0:
        return 0
    fresh = bucket
    while fresh > 1 and fresh > max(1, n // 8):
        fresh //= 2
    if prev and prev != fresh and prev <= bucket \
            and 8 * prev * (1.0 - hysteresis) <= n < 16 * prev * (1.0 + hysteresis):
        return prev
    return fresh


def _pad_up(n: int, bucket: int) -> int:
    """Round up to a bucket size so recompilation only happens when a
    cluster crosses a bucket boundary (dynamic topics/partitions strategy,
    SURVEY.md §7 hard part (d))."""
    if bucket <= 1:
        return max(n, 1)
    return max(bucket, ((n + bucket - 1) // bucket) * bucket)


@dataclasses.dataclass
class BrokerSpec:
    broker_id: int
    rack: str
    capacity: Mapping[Resource, float]
    state: BrokerState = BrokerState.ALIVE
    # Physical host (model/Host.java). Empty = unknown -> the broker is
    # its own host. A broker with an EMPTY rack inherits its host as the
    # fault domain (rack-falls-back-to-host, ClusterModel.createBroker:
    # rack == null ? host : rack), so co-hosted rackless brokers share one
    # rack index and RackAwareGoal keeps them replica-disjoint.
    host: str = ""


def _effective_rack(b: "BrokerSpec") -> str:
    return b.rack or _effective_host(b)


def _effective_host(b: "BrokerSpec") -> str:
    return b.host or f"broker-{b.broker_id}"


@dataclasses.dataclass
class PartitionSpec:
    topic: str
    partition: int
    replicas: Sequence[int]          # broker ids, leader first by convention
    leader_index: int = 0            # index into replicas; -1 = no leader
    leader_load: Mapping[Resource, float] | None = None
    follower_load: Mapping[Resource, float] | None = None


class ClusterModelBuilder:
    def __init__(self, partition_bucket: int = 0, broker_bucket: int = 0):
        self._brokers: list[BrokerSpec] = []
        self._partitions: list[PartitionSpec] = []
        self._partition_bucket = partition_bucket
        self._broker_bucket = broker_bucket

    def add_broker(self, broker_id: int, rack: str,
                   capacity: Mapping[Resource, float],
                   state: BrokerState = BrokerState.ALIVE,
                   host: str = "") -> "ClusterModelBuilder":
        self._brokers.append(BrokerSpec(broker_id, rack, capacity, state,
                                        host=host))
        return self

    @property
    def broker_specs(self) -> list[BrokerSpec]:
        return list(self._brokers)

    @property
    def partition_bucket(self) -> int:
        return self._partition_bucket

    @property
    def broker_bucket(self) -> int:
        return self._broker_bucket

    def add_partition(self, topic: str, partition: int, replicas: Sequence[int],
                      leader_load: Mapping[Resource, float] | None = None,
                      follower_load: Mapping[Resource, float] | None = None,
                      leader_index: int = 0) -> "ClusterModelBuilder":
        self._partitions.append(PartitionSpec(topic, partition, replicas,
                                              leader_index, leader_load, follower_load))
        return self

    def build(self) -> tuple[ClusterTensors, ClusterMeta]:
        if not self._brokers:
            raise ValueError("cluster must have at least one broker")
        brokers = sorted(self._brokers, key=lambda b: b.broker_id)
        broker_ids = [b.broker_id for b in brokers]
        if len(set(broker_ids)) != len(broker_ids):
            raise ValueError("duplicate broker ids")
        broker_index = {bid: i for i, bid in enumerate(broker_ids)}
        racks = sorted({_effective_rack(b) for b in brokers})
        rack_index = {r: i for i, r in enumerate(racks)}
        hosts = sorted({_effective_host(b) for b in brokers})
        host_index = {h: i for i, h in enumerate(hosts)}

        topics = sorted({p.topic for p in self._partitions})
        topic_index = {t: i for i, t in enumerate(topics)}
        parts = sorted(self._partitions, key=lambda p: (p.topic, p.partition))

        n_p = _pad_up(len(parts), self._partition_bucket)
        n_b = _pad_up(len(brokers), self._broker_bucket)
        max_rf = max((len(p.replicas) for p in parts), default=1)

        assignment = np.full((n_p, max_rf), -1, dtype=np.int32)
        leader_slot = np.full((n_p,), -1, dtype=np.int32)
        leader_load = np.zeros((n_p, NUM_RESOURCES), dtype=np.float32)
        follower_load = np.zeros((n_p, NUM_RESOURCES), dtype=np.float32)
        topic_arr = np.zeros((n_p,), dtype=np.int32)
        partition_mask = np.zeros((n_p,), dtype=bool)

        seen_parts = set()
        part_names: list[tuple[str, int]] = []
        for i, p in enumerate(parts):
            if (p.topic, p.partition) in seen_parts:
                raise ValueError(f"duplicate partition {p.topic}-{p.partition}")
            seen_parts.add((p.topic, p.partition))
            if len(set(p.replicas)) != len(p.replicas):
                raise ValueError(f"partition {p.topic}-{p.partition} has duplicate replicas")
            if p.leader_index != -1 and not 0 <= p.leader_index < len(p.replicas):
                raise ValueError(f"partition {p.topic}-{p.partition}: leader_index "
                                 f"{p.leader_index} out of range for {len(p.replicas)} replicas")
            for s, bid in enumerate(p.replicas):
                if bid not in broker_index:
                    raise ValueError(f"partition {p.topic}-{p.partition} references "
                                     f"unknown broker {bid}")
                assignment[i, s] = broker_index[bid]
            leader_slot[i] = p.leader_index
            topic_arr[i] = topic_index[p.topic]
            partition_mask[i] = True
            part_names.append((p.topic, p.partition))
            if p.leader_load:
                for r, v in p.leader_load.items():
                    leader_load[i, int(r)] = v
            if p.follower_load is not None:
                for r, v in p.follower_load.items():
                    follower_load[i, int(r)] = v
            else:
                follower_load[i] = derive_follower_load(leader_load[i])

        capacity = np.zeros((n_b, NUM_RESOURCES), dtype=np.float32)
        rack_arr = np.zeros((n_b,), dtype=np.int32)
        host_arr = np.arange(n_b, dtype=np.int32) + len(hosts)  # pad rows: own host
        broker_state = np.full((n_b,), int(BrokerState.DEAD), dtype=np.int8)
        broker_mask = np.zeros((n_b,), dtype=bool)
        for i, b in enumerate(brokers):
            for r, v in b.capacity.items():
                capacity[i, int(r)] = v
            rack_arr[i] = rack_index[_effective_rack(b)]
            host_arr[i] = host_index[_effective_host(b)]
            broker_state[i] = int(b.state)
            broker_mask[i] = True

        import jax.numpy as jnp
        state = ClusterTensors(
            assignment=jnp.asarray(assignment),
            leader_slot=jnp.asarray(leader_slot),
            leader_load=jnp.asarray(leader_load),
            follower_load=jnp.asarray(follower_load),
            capacity=jnp.asarray(capacity),
            rack=jnp.asarray(rack_arr),
            broker_state=jnp.asarray(broker_state),
            topic=jnp.asarray(topic_arr),
            partition_mask=jnp.asarray(partition_mask),
            broker_mask=jnp.asarray(broker_mask),
            host=jnp.asarray(host_arr),
        )
        meta = ClusterMeta(broker_ids=broker_ids, topic_names=topics,
                           rack_names=racks, num_topics=len(topics),
                           partition_index=part_names, host_names=hosts)
        return state, meta


def derive_follower_load(leader_load_row: np.ndarray,
                         follower_cpu_fraction: float = 0.4) -> np.ndarray:
    """Follower load from leader load: replication bytes-in ≈ leader
    bytes-in, no NW_OUT, same disk footprint, reduced CPU
    (ModelUtils.estimateFollowerCpuUtilFromLeaderLoad, ModelUtils.java:64)."""
    out = np.array(leader_load_row, dtype=np.float32)
    out[int(Resource.NW_OUT)] = 0.0
    out[int(Resource.CPU)] = leader_load_row[int(Resource.CPU)] * follower_cpu_fraction
    return out


def build_cluster_from_arrays(brokers: Sequence[BrokerSpec],
                              part_names: Sequence[tuple[str, int]],
                              replicas: Sequence[Sequence[int]],
                              leader_indices: np.ndarray,
                              leader_load: np.ndarray,
                              follower_load: np.ndarray,
                              partition_bucket: int = 0,
                              broker_bucket: int = 0,
                              ) -> tuple[ClusterTensors, ClusterMeta]:
    """Bulk freeze path: per-partition loads arrive as [P, R] matrices
    (LoadMonitor's vectorized window reduction) instead of per-partition
    dicts. ``replicas`` holds broker IDS; rows must be sorted by
    (topic, partition) already."""
    import jax.numpy as jnp

    brokers = sorted(brokers, key=lambda b: b.broker_id)
    broker_ids = [b.broker_id for b in brokers]
    broker_index = {bid: i for i, bid in enumerate(broker_ids)}
    racks = sorted({_effective_rack(b) for b in brokers})
    rack_index = {r: i for i, r in enumerate(racks)}
    hosts = sorted({_effective_host(b) for b in brokers})
    host_index = {h: i for i, h in enumerate(hosts)}
    topics = sorted({t for t, _p in part_names})
    topic_index = {t: i for i, t in enumerate(topics)}

    n = len(part_names)
    n_p = _pad_up(n, partition_bucket)
    n_b = _pad_up(len(brokers), broker_bucket)
    max_rf = max((len(r) for r in replicas), default=1)

    assignment = np.full((n_p, max_rf), -1, dtype=np.int32)
    if isinstance(replicas, np.ndarray):
        # Bulk path: [N, rf] broker-ID matrix → index lookup table (a
        # per-replica Python loop is minutes at 1M partitions). -1 slots
        # are the empty-slot sentinel and pass through unchanged; any
        # other out-of-table id is an error (negative ids must not wrap
        # into lut[-1], and too-large ids must not surface as a raw
        # IndexError).
        empty = replicas < 0
        if replicas.size:
            if not broker_ids or ((replicas < -1)
                                  | (replicas > max(broker_ids))).any():
                raise ValueError("replica matrix references unknown broker ids")
            lut = np.full(max(broker_ids) + 1, -1, dtype=np.int32)
            lut[np.asarray(broker_ids)] = np.arange(len(broker_ids),
                                                    dtype=np.int32)
            mapped = lut[np.where(empty, 0, replicas)]
            if (mapped[~empty] < 0).any():
                raise ValueError("replica matrix references unknown broker ids")
            assignment[:len(replicas), :replicas.shape[1]] = \
                np.where(empty, -1, mapped)
    else:
        for i, reps in enumerate(replicas):
            for s, bid in enumerate(reps):
                assignment[i, s] = broker_index[bid]
    leader_slot = np.full((n_p,), -1, dtype=np.int32)
    leader_slot[:n] = np.asarray(leader_indices, dtype=np.int32)
    ll = np.zeros((n_p, NUM_RESOURCES), dtype=np.float32)
    fl = np.zeros((n_p, NUM_RESOURCES), dtype=np.float32)
    ll[:n] = leader_load
    fl[:n] = follower_load
    topic_arr = np.zeros((n_p,), dtype=np.int32)
    topic_arr[:n] = [topic_index[t] for t, _p in part_names]
    partition_mask = np.zeros((n_p,), dtype=bool)
    partition_mask[:n] = True

    capacity = np.zeros((n_b, NUM_RESOURCES), dtype=np.float32)
    rack_arr = np.zeros((n_b,), dtype=np.int32)
    host_arr = np.arange(n_b, dtype=np.int32) + len(hosts)  # pad rows: own host
    broker_state = np.full((n_b,), int(BrokerState.DEAD), dtype=np.int8)
    broker_mask = np.zeros((n_b,), dtype=bool)
    for i, b in enumerate(brokers):
        for r, v in b.capacity.items():
            capacity[i, int(r)] = v
        rack_arr[i] = rack_index[_effective_rack(b)]
        host_arr[i] = host_index[_effective_host(b)]
        broker_state[i] = int(b.state)
        broker_mask[i] = True

    state = ClusterTensors(
        assignment=jnp.asarray(assignment), leader_slot=jnp.asarray(leader_slot),
        leader_load=jnp.asarray(ll), follower_load=jnp.asarray(fl),
        capacity=jnp.asarray(capacity), rack=jnp.asarray(rack_arr),
        broker_state=jnp.asarray(broker_state), topic=jnp.asarray(topic_arr),
        partition_mask=jnp.asarray(partition_mask),
        broker_mask=jnp.asarray(broker_mask),
        host=jnp.asarray(host_arr))
    meta = ClusterMeta(broker_ids=broker_ids, topic_names=topics,
                       rack_names=racks, num_topics=len(topics),
                       partition_index=list(part_names), host_names=hosts)
    return state, meta
