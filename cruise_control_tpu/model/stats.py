"""Cluster statistics: the optimizer's objective snapshot.

Reference parity: model/ClusterModelStats.java:84 (populate) — {AVG, MAX,
MIN, ST_DEV} over alive brokers for per-resource utilization, potential
NW-out, replica counts, leader-replica counts, topic-replica counts.
Computed as one jitted reduction over the tensor model.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from .tensors import (
    ClusterTensors, alive_mask, broker_leader_counts, broker_load,
    broker_replica_counts, potential_nw_out,
)


@partial(jax.tree_util.register_dataclass,
         data_fields=["utilization_avg", "utilization_max", "utilization_min",
                      "utilization_std", "potential_nw_out_stats",
                      "replica_count_stats", "leader_count_stats",
                      "num_alive_brokers"],
         meta_fields=[])
@dataclasses.dataclass(frozen=True)
class ClusterModelStats:
    utilization_avg: jax.Array      # [R]
    utilization_max: jax.Array      # [R]
    utilization_min: jax.Array      # [R]
    utilization_std: jax.Array      # [R]
    potential_nw_out_stats: jax.Array  # [4] avg/max/min/std
    replica_count_stats: jax.Array     # [4]
    leader_count_stats: jax.Array      # [4]
    num_alive_brokers: jax.Array       # scalar int32


def _masked_stats(values: jax.Array, mask: jax.Array) -> jax.Array:
    """avg/max/min/std over masked entries; zeros when mask is empty."""
    n = jnp.maximum(mask.sum(), 1)
    masked = jnp.where(mask, values, 0.0)
    avg = masked.sum() / n
    mx = jnp.where(mask, values, -jnp.inf).max()
    mn = jnp.where(mask, values, jnp.inf).min()
    var = jnp.where(mask, (values - avg) ** 2, 0.0).sum() / n
    any_alive = mask.any()
    return jnp.where(any_alive,
                     jnp.stack([avg, mx, mn, jnp.sqrt(var)]),
                     jnp.zeros(4))


@jax.jit
def cluster_stats(state: ClusterTensors) -> ClusterModelStats:
    alive = alive_mask(state)
    load = broker_load(state)                      # [B, R]
    cap = jnp.maximum(state.capacity, 1e-9)
    util = load / cap                              # [B, R]

    per_resource = jax.vmap(lambda col: _masked_stats(col, alive), in_axes=1,
                            out_axes=1)(util)      # [4, R]
    pot = _masked_stats(potential_nw_out(state), alive)
    rep = _masked_stats(broker_replica_counts(state).astype(jnp.float32), alive)
    led = _masked_stats(broker_leader_counts(state).astype(jnp.float32), alive)

    return ClusterModelStats(
        utilization_avg=per_resource[0],
        utilization_max=per_resource[1],
        utilization_min=per_resource[2],
        utilization_std=per_resource[3],
        potential_nw_out_stats=pot,
        replica_count_stats=rep,
        leader_count_stats=led,
        num_alive_brokers=alive.sum().astype(jnp.int32),
    )
