"""CPU estimation: partition CPU load from broker CPU + traffic shares.

Reference parity: model/ModelUtils.java (estimateLeaderCpuUtilPerCore:96,
getFollowerCpuUtilFromLeaderLoad:64), model/ModelParameters.java (static
coefficients, defaults 0.7/0.15/0.15), and
model/LinearRegressionModelParameters.java (optional trained linear model
fed by the TRAIN endpoint, updateModelCoefficient:70).

Redesign notes: the reference estimates per-partition CPU one call at a
time inside the sample processor; here the estimator is vectorized over
whole partition arrays (the processor hands us columns, we hand back a
column), and the trained model is an ordinary least-squares solve on a
bucketed observation matrix (diversity bucketing by CPU percentile mirrors
the reference's CPU_UTIL bucket histogram used to gate training
completeness).
"""

from __future__ import annotations

import dataclasses
import threading

import numpy as np

# Reference: ModelUtils.java:44-45.
ALLOWED_METRIC_ERROR_FACTOR = 1.05
UNSTABLE_METRIC_THROUGHPUT_THRESHOLD = 10.0


@dataclasses.dataclass(frozen=True)
class CpuModelCoefficients:
    """Static CPU attribution weights (ModelParameters.java:23-31)."""

    leader_bytes_in: float = 0.7
    leader_bytes_out: float = 0.15
    follower_bytes_in: float = 0.15


def estimate_leader_cpu_util(broker_cpu_util: np.ndarray,
                             broker_leader_bytes_in: np.ndarray,
                             broker_leader_bytes_out: np.ndarray,
                             broker_follower_bytes_in: np.ndarray,
                             partition_bytes_in: np.ndarray,
                             partition_bytes_out: np.ndarray,
                             coef: CpuModelCoefficients = CpuModelCoefficients(),
                             ) -> np.ndarray:
    """Vectorized ModelUtils.estimateLeaderCpuUtilPerCore.

    All broker_* inputs are per-partition columns (already gathered to the
    leader broker of each partition). Returns per-partition leader CPU util
    in [0, 1]; NaN marks the reference's ``null`` (inconsistent byte rates)
    so callers can drop/extrapolate those samples.
    """
    bli = np.asarray(broker_leader_bytes_in, dtype=np.float64)
    blo = np.asarray(broker_leader_bytes_out, dtype=np.float64)
    bfi = np.asarray(broker_follower_bytes_in, dtype=np.float64)
    pin = np.asarray(partition_bytes_in, dtype=np.float64)
    pout = np.asarray(partition_bytes_out, dtype=np.float64)
    cpu = np.asarray(broker_cpu_util, dtype=np.float64)

    zero_broker = (bli == 0) & (blo == 0)
    bad_in = (bli * ALLOWED_METRIC_ERROR_FACTOR < pin) & \
        (bli > UNSTABLE_METRIC_THROUGHPUT_THRESHOLD)
    bad_out = (blo * ALLOWED_METRIC_ERROR_FACTOR < pout) & \
        (blo > UNSTABLE_METRIC_THROUGHPUT_THRESHOLD)

    lead_in = coef.leader_bytes_in * bli
    lead_out = coef.leader_bytes_out * blo
    foll_in = coef.follower_bytes_in * bfi
    total = lead_in + lead_out + foll_in
    safe_total = np.where(total > 0, total, 1.0)
    # Partition's share of each contribution (clip partition rates to broker
    # rates — the reference tolerates up to 5% measurement error).
    share_in = np.where(bli > 0, np.minimum(pin, bli) / np.where(bli > 0, bli, 1.0), 0.0)
    share_out = np.where(blo > 0, np.minimum(pout, blo) / np.where(blo > 0, blo, 1.0), 0.0)
    est = cpu * (lead_in * share_in + lead_out * share_out) / safe_total
    est = np.where(zero_broker, 0.0, est)
    return np.where(bad_in | bad_out, np.nan, est)


def follower_cpu_util_from_leader_load(leader_bytes_in: np.ndarray,
                                       leader_bytes_out: np.ndarray,
                                       leader_cpu_util: np.ndarray,
                                       coef: CpuModelCoefficients = CpuModelCoefficients(),
                                       ) -> np.ndarray:
    """Vectorized ModelUtils.getFollowerCpuUtilFromLeaderLoad:64."""
    lin = np.asarray(leader_bytes_in, dtype=np.float64)
    lout = np.asarray(leader_bytes_out, dtype=np.float64)
    cpu = np.asarray(leader_cpu_util, dtype=np.float64)
    denom = coef.leader_bytes_in * lin + coef.leader_bytes_out * lout
    out = np.where(denom > 0, cpu * (coef.follower_bytes_in * lin) /
                   np.where(denom > 0, denom, 1.0), 0.0)
    return out


class LinearRegressionCpuModel:
    """Trained alternative (LinearRegressionModelParameters.java).

    Observations are (leader_bytes_in, leader_bytes_out, follower_bytes_in)
    → broker CPU util rows collected by the TRAIN flow. To avoid a fit
    dominated by the steady-state operating point, observations are spread
    across ``num_buckets`` CPU-utilization buckets with a per-bucket cap
    (the reference keeps a CPU-bucket histogram and reports training
    completeness as the fraction of buckets observed).
    """

    NUM_FEATURES = 3

    def __init__(self, num_buckets: int = 20, max_per_bucket: int = 500,
                 min_completeness: float = 0.5,
                 required_samples_per_bucket: int = 1,
                 min_num_buckets: int | None = None):
        """``required_samples_per_bucket`` — a bucket counts toward
        completeness only once it holds this many observations
        (linear.regression.model.required.samples.per.bucket).
        ``min_num_buckets`` — buckets that must be complete before training
        proceeds (linear.regression.model.min.num.cpu.util.buckets);
        overrides ``min_completeness`` when given."""
        self._num_buckets = num_buckets
        self._max_per_bucket = max_per_bucket
        self._required_per_bucket = max(1, required_samples_per_bucket)
        if min_num_buckets is not None:
            # Clamp: more required buckets than exist would make the
            # completeness threshold unreachable (>1.0) and training
            # silently never finish.
            min_completeness = min(min_num_buckets, num_buckets) / num_buckets
        self._min_completeness = min_completeness
        self._buckets: list[list[np.ndarray]] = [[] for _ in range(num_buckets)]
        self._coef: np.ndarray | None = None
        self._lock = threading.Lock()

    def add_observations(self, cpu_util: np.ndarray, leader_bytes_in: np.ndarray,
                         leader_bytes_out: np.ndarray,
                         follower_bytes_in: np.ndarray) -> None:
        cpu = np.clip(np.asarray(cpu_util, dtype=np.float64), 0.0, 1.0)
        rows = np.stack([np.asarray(leader_bytes_in, np.float64),
                         np.asarray(leader_bytes_out, np.float64),
                         np.asarray(follower_bytes_in, np.float64),
                         cpu], axis=-1).reshape(-1, 4)
        idx = np.minimum((cpu.reshape(-1) * self._num_buckets).astype(int),
                         self._num_buckets - 1)
        with self._lock:
            for b in range(self._num_buckets):
                take = rows[idx == b]
                room = self._max_per_bucket - len(self._buckets[b])
                if room > 0 and len(take):
                    self._buckets[b].extend(take[:room])

    @property
    def training_completeness(self) -> float:
        with self._lock:
            return self.training_completeness_locked()

    @property
    def trained(self) -> bool:
        return self._coef is not None

    @property
    def coefficients(self) -> np.ndarray | None:
        return None if self._coef is None else self._coef.copy()

    def train(self) -> bool:
        """Least-squares fit; returns False when bucket diversity is below
        the completeness threshold (LinearRegressionModelParameters:
        training stays incomplete until enough CPU buckets are seen)."""
        with self._lock:
            if self.training_completeness_locked() < self._min_completeness:
                return False
            rows = np.concatenate([np.stack(b) for b in self._buckets if b])
        x, y = rows[:, :3], rows[:, 3]
        coef, *_ = np.linalg.lstsq(x, y, rcond=None)
        self._coef = np.maximum(coef, 0.0)
        return True

    def training_completeness_locked(self) -> float:
        return sum(1 for b in self._buckets
                   if len(b) >= self._required_per_bucket) / self._num_buckets

    def estimate_leader_cpu_util(self, partition_bytes_in: np.ndarray,
                                 partition_bytes_out: np.ndarray) -> np.ndarray:
        """LinearRegressionModelParameters-based per-partition estimate."""
        if self._coef is None:
            raise RuntimeError("linear regression CPU model is not trained")
        pin = np.asarray(partition_bytes_in, np.float64)
        pout = np.asarray(partition_bytes_out, np.float64)
        return self._coef[0] * pin + self._coef[1] * pout


@dataclasses.dataclass
class CpuEstimator:
    """Facade selecting static-coefficient vs trained model
    (ModelUtils.init + useLinearRegressionModel flag)."""

    coef: CpuModelCoefficients = dataclasses.field(default_factory=CpuModelCoefficients)
    linear_model: LinearRegressionCpuModel | None = None
    use_linear_regression: bool = False

    def leader_cpu(self, broker_cpu_util, broker_leader_bytes_in,
                   broker_leader_bytes_out, broker_follower_bytes_in,
                   partition_bytes_in, partition_bytes_out) -> np.ndarray:
        if self.use_linear_regression and self.linear_model is not None \
                and self.linear_model.trained:
            return self.linear_model.estimate_leader_cpu_util(
                partition_bytes_in, partition_bytes_out)
        return estimate_leader_cpu_util(
            broker_cpu_util, broker_leader_bytes_in, broker_leader_bytes_out,
            broker_follower_bytes_in, partition_bytes_in, partition_bytes_out,
            self.coef)

    def follower_cpu(self, leader_bytes_in, leader_bytes_out,
                     leader_cpu_util) -> np.ndarray:
        if self.use_linear_regression and self.linear_model is not None \
                and self.linear_model.trained:
            fb = self.linear_model.coefficients[2]
            return fb * np.asarray(leader_bytes_in, np.float64)
        return follower_cpu_util_from_leader_load(
            leader_bytes_in, leader_bytes_out, leader_cpu_util, self.coef)
