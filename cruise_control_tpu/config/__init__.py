from .configdef import ConfigDef, ConfigType, Importance, Range, ValidString, ConfigException
from .abstract_config import AbstractConfig
from .cruise_control_config import CruiseControlConfig
