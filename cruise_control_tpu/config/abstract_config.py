"""AbstractConfig: typed access + plugin instantiation.

Reference parity: cruise-control-core .../common/config/AbstractConfig.java
(typed getters, ``getConfiguredInstance`` reflection-based plugin loading).
Python version loads plugins by dotted import path and passes the config to
a ``configure(config)`` method when the plugin defines one — mirroring the
reference's ``CruiseControlConfigurable.configure(Map)`` contract.
"""

from __future__ import annotations

import importlib
from typing import Any, Mapping

from .configdef import ConfigDef, ConfigException


def resolve_class(spec: Any):
    """Resolve a class from a dotted ``pkg.module.ClassName`` path (or pass
    through an already-resolved class/callable)."""
    if not isinstance(spec, str):
        return spec
    module_name, _, attr = spec.rpartition(".")
    if not module_name:
        raise ConfigException(f"not a dotted class path: {spec!r}")
    try:
        module = importlib.import_module(module_name)
        return getattr(module, attr)
    except (ImportError, AttributeError) as exc:
        raise ConfigException(f"cannot load class {spec!r}: {exc}") from exc


class AbstractConfig:
    def __init__(self, definition: ConfigDef, props: Mapping[str, Any]):
        self._definition = definition
        self._props = dict(props)
        self._values = definition.parse(props)
        # Keys present in props but not defined are retained for plugins
        # (originals()), matching AbstractConfig.java behavior.
        defined = set(definition.names)
        self._unused = {k: v for k, v in self._props.items() if k not in defined}

    def originals(self) -> dict[str, Any]:
        return dict(self._props)

    def values(self) -> dict[str, Any]:
        return dict(self._values)

    def get(self, name: str) -> Any:
        if name not in self._values:
            raise ConfigException(f"unknown config {name!r}")
        return self._values[name]

    # Typed getters mirroring AbstractConfig.java
    def get_int(self, name: str) -> int:
        return self.get(name)

    def get_long(self, name: str) -> int:
        return self.get(name)

    def get_double(self, name: str) -> float:
        return self.get(name)

    def get_boolean(self, name: str) -> bool:
        return self.get(name)

    def get_string(self, name: str) -> str:
        return self.get(name)

    def get_list(self, name: str) -> list[str]:
        return self.get(name)

    def get_configured_instance(self, name: str, expected_type: type | None = None, **kwargs) -> Any:
        """Instantiate the plugin class named by config ``name`` and configure
        it (AbstractConfig.getConfiguredInstance)."""
        spec = self.get(name)
        if spec is None:
            return None
        return self._make_instance(name, spec, expected_type, kwargs)

    def get_configured_instances(self, name: str, expected_type: type | None = None, **kwargs) -> list[Any]:
        specs = self.get(name) or []
        return [self._make_instance(name, spec, expected_type, kwargs) for spec in specs]

    def _make_instance(self, name: str, spec: Any, expected_type: type | None,
                       extra: Mapping[str, Any]) -> Any:
        cls = resolve_class(spec)
        instance = cls()
        if expected_type is not None and not isinstance(instance, expected_type):
            raise ConfigException(
                f"{name}: {cls!r} is not an instance of {expected_type!r}")
        self._configure(instance, extra)
        return instance

    def _configure(self, instance: Any, extra: Mapping[str, Any]) -> None:
        configure = getattr(instance, "configure", None)
        if callable(configure):
            merged = dict(self._values)
            merged.update(self._unused)
            merged.update(extra)
            configure(merged)
