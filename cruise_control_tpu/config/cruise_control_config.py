"""The merged framework configuration.

Reference parity: config/KafkaCruiseControlConfig.java (merges
MonitorConfig / AnalyzerConfig / ExecutorConfig / AnomalyDetectorConfig /
WebServerConfig / UserTaskManagerConfig constants and performs cross-field
sanity checks such as hard-goals ⊆ goals). Defaults follow
config/cruisecontrol.properties.

The goal class names here are dotted paths into
``cruise_control_tpu.analyzer.goals`` — the TPU-native goal kernels.
"""

from __future__ import annotations

from typing import Any, Mapping

from .abstract_config import AbstractConfig
from .configdef import ConfigDef, ConfigException, ConfigType, Importance, Range

_G = "cruise_control_tpu.analyzer.goals"

# Default goal chain: mirrors config/cruisecontrol.properties goals= order.
DEFAULT_GOALS = [
    f"{_G}.RackAwareGoal",
    f"{_G}.ReplicaCapacityGoal",
    f"{_G}.DiskCapacityGoal",
    f"{_G}.NetworkInboundCapacityGoal",
    f"{_G}.NetworkOutboundCapacityGoal",
    f"{_G}.CpuCapacityGoal",
    f"{_G}.ReplicaDistributionGoal",
    f"{_G}.PotentialNwOutGoal",
    f"{_G}.DiskUsageDistributionGoal",
    f"{_G}.NetworkInboundUsageDistributionGoal",
    f"{_G}.NetworkOutboundUsageDistributionGoal",
    f"{_G}.CpuUsageDistributionGoal",
    f"{_G}.TopicReplicaDistributionGoal",
    f"{_G}.LeaderReplicaDistributionGoal",
    f"{_G}.LeaderBytesInDistributionGoal",
]

DEFAULT_HARD_GOALS = [
    f"{_G}.RackAwareGoal",
    f"{_G}.ReplicaCapacityGoal",
    f"{_G}.DiskCapacityGoal",
    f"{_G}.NetworkInboundCapacityGoal",
    f"{_G}.NetworkOutboundCapacityGoal",
    f"{_G}.CpuCapacityGoal",
]

DEFAULT_ANOMALY_DETECTION_GOALS = [
    f"{_G}.RackAwareGoal",
    f"{_G}.ReplicaCapacityGoal",
    f"{_G}.DiskCapacityGoal",
]


def _definition() -> ConfigDef:
    d = ConfigDef()
    T, I = ConfigType, Importance

    # --- Monitor (MonitorConfig.java; defaults cruisecontrol.properties) ---
    d.define("bootstrap.servers", T.LIST, [], None, I.HIGH,
             "Kafka bootstrap servers for the managed cluster.")
    d.define("metric.sampling.interval.ms", T.LONG, 120_000, Range.at_least(1), I.HIGH,
             "Interval of metric sampling (default 120s).")
    d.define("partition.metrics.window.ms", T.LONG, 300_000, Range.at_least(1), I.HIGH,
             "Partition metrics window size.")
    d.define("num.partition.metrics.windows", T.INT, 5, Range.at_least(1), I.HIGH,
             "Number of partition windows kept.")
    d.define("broker.metrics.window.ms", T.LONG, 300_000, Range.at_least(1), I.HIGH,
             "Broker metrics window size.")
    d.define("num.broker.metrics.windows", T.INT, 20, Range.at_least(1), I.HIGH,
             "Number of broker windows kept.")
    d.define("min.samples.per.partition.metrics.window", T.INT, 1, Range.at_least(1), I.MEDIUM,
             "Minimum samples for a partition window to be valid.")
    d.define("min.samples.per.broker.metrics.window", T.INT, 1, Range.at_least(1), I.MEDIUM,
             "Minimum samples for a broker window to be valid.")
    d.define("min.valid.partition.ratio", T.DOUBLE, 0.95, Range.between(0, 1), I.HIGH,
             "Minimum monitored-valid partition ratio for model building.")
    d.define("max.allowed.extrapolations.per.partition", T.INT, 8, Range.at_least(0), I.LOW,
             "Max extrapolated windows tolerated per partition entity.")
    d.define("max.allowed.extrapolations.per.broker", T.INT, 8, Range.at_least(0), I.LOW,
             "Max extrapolated windows tolerated per broker entity.")
    d.define("prometheus.server.endpoint", T.STRING, None, None, I.LOW,
             "Prometheus base URL for PrometheusMetricSampler.from_endpoint "
             "(prometheus/PrometheusMetricSampler.java config).")
    d.define("metric.sampler.class", T.CLASS,
             "cruise_control_tpu.monitor.sampling.synthetic_sampler.SyntheticMetricSampler",
             None, I.HIGH, "Pluggable MetricSampler implementation.")
    d.define("sample.store.class", T.CLASS,
             "cruise_control_tpu.monitor.sampling.sample_store.FileSampleStore",
             None, I.MEDIUM, "Pluggable SampleStore implementation.")
    d.define("sample.store.path", T.STRING, "fileStore/samples", None, I.LOW,
             "Directory for the file-backed sample store.")
    d.define("num.metric.fetchers", T.INT, 1, Range.at_least(1), I.LOW,
             "Parallel metric fetcher workers.")
    d.define("broker.capacity.config.resolver.class", T.CLASS,
             "cruise_control_tpu.monitor.capacity.FileCapacityResolver",
             None, I.HIGH, "Pluggable broker capacity resolver.")
    d.define("capacity.config.file", T.STRING, "config/capacity.json", None, I.HIGH,
             "Capacity JSON file (DISK MB, CPU %, NW KB/s; JBOD maps).")
    d.define("monitor.state.update.interval.ms", T.LONG, 30_000, Range.at_least(1), I.LOW,
             "Monitor state refresh cadence.")
    d.define("metric.sampler.partition.assignor.class", T.CLASS,
             "cruise_control_tpu.monitor.sampling.fetcher.DefaultPartitionAssignor",
             None, I.LOW, "Partition→fetcher assignment policy.")
    d.define("fetch.metric.samples.max.retry.count", T.INT, 5,
             Range.at_least(0), I.LOW, "Sampling fetch retries per window.")
    d.define("skip.loading.samples", T.BOOLEAN, False, None, I.LOW,
             "Skip the warm-start sample replay at startup.")
    d.define("sampling.allow.cpu.capacity.estimation", T.BOOLEAN, True, None,
             I.LOW, "Estimate CPU capacity from cores when unset.")
    d.define("sample.partition.metric.store.on.execution.class", T.CLASS,
             None, None, I.LOW,
             "Extra store receiving samples gathered mid-execution.")
    d.define("use.linear.regression.model", T.BOOLEAN, False, None, I.LOW,
             "CPU estimation via the trained linear model instead of the "
             "static coefficients.")
    d.define("linear.regression.model.cpu.util.bucket.size", T.INT, 5,
             Range.between(1, 100), I.LOW,
             "CPU-utilization bucket width for training sample balance.")
    d.define("leader.network.inbound.weight.for.cpu.util", T.DOUBLE, 0.6,
             Range.at_least(0), I.LOW,
             "Static CPU model coefficient (ModelParameters.java).")
    d.define("leader.network.outbound.weight.for.cpu.util", T.DOUBLE, 0.1,
             Range.at_least(0), I.LOW, "Static CPU model coefficient.")
    d.define("follower.network.inbound.weight.for.cpu.util", T.DOUBLE, 0.3,
             Range.at_least(0), I.LOW, "Static CPU model coefficient.")
    d.define("topic.config.provider.class", T.CLASS, None, None, I.LOW,
             "Pluggable topic-config source (default: the admin backend).")
    d.define("zookeeper.security.enabled", T.BOOLEAN, False, None, I.LOW,
             "Legacy ZK flag; accepted for config parity, ZK paths are not "
             "implemented (metadata polling replaces the ZK watcher).")
    d.define("failed.brokers.zk.path", T.STRING, None, None, I.LOW,
             "Legacy ZK persistence path; the file store replaces it.")
    d.define("network.client.provider.class", T.CLASS, None, None, I.LOW,
             "Network client factory override (reference plumbing; the "
             "wire binding manages its own connections).")

    # --- Analyzer (AnalyzerConfig.java) ---
    d.define("goals", T.LIST, list(DEFAULT_GOALS), None, I.HIGH,
             "Default goal chain, priority order.")
    d.define("hard.goals", T.LIST, list(DEFAULT_HARD_GOALS), None, I.HIGH,
             "Goals that must always be satisfied.")
    d.define("default.goals", T.LIST, [], None, I.MEDIUM,
             "Goals used for precomputed proposals (empty = goals).")
    d.define("anomaly.detection.goals", T.LIST, list(DEFAULT_ANOMALY_DETECTION_GOALS), None,
             I.MEDIUM, "Goals replayed by the goal-violation detector.")
    d.define("cpu.balance.threshold", T.DOUBLE, 1.1, Range.at_least(1), I.MEDIUM,
             "Balance band multiplier for CPU.")
    d.define("disk.balance.threshold", T.DOUBLE, 1.1, Range.at_least(1), I.MEDIUM,
             "Balance band multiplier for disk.")
    d.define("network.inbound.balance.threshold", T.DOUBLE, 1.1, Range.at_least(1), I.MEDIUM,
             "Balance band multiplier for NW in.")
    d.define("network.outbound.balance.threshold", T.DOUBLE, 1.1, Range.at_least(1), I.MEDIUM,
             "Balance band multiplier for NW out.")
    d.define("replica.count.balance.threshold", T.DOUBLE, 1.1, Range.at_least(1), I.MEDIUM,
             "Balance band multiplier for replica counts.")
    d.define("leader.replica.count.balance.threshold", T.DOUBLE, 1.1, Range.at_least(1), I.MEDIUM,
             "Balance band multiplier for leader replica counts.")
    d.define("topic.replica.count.balance.threshold", T.DOUBLE, 1.1, Range.at_least(1), I.MEDIUM,
             "Balance band multiplier for per-topic replica counts.")
    d.define("cpu.capacity.threshold", T.DOUBLE, 0.7, Range.between(0, 1), I.MEDIUM,
             "Usable fraction of CPU capacity.")
    d.define("disk.capacity.threshold", T.DOUBLE, 0.8, Range.between(0, 1), I.MEDIUM,
             "Usable fraction of disk capacity.")
    d.define("network.inbound.capacity.threshold", T.DOUBLE, 0.8, Range.between(0, 1), I.MEDIUM,
             "Usable fraction of NW-in capacity.")
    d.define("network.outbound.capacity.threshold", T.DOUBLE, 0.8, Range.between(0, 1), I.MEDIUM,
             "Usable fraction of NW-out capacity.")
    d.define("cpu.low.utilization.threshold", T.DOUBLE, 0.0, Range.between(0, 1), I.LOW,
             "Below this avg utilization the resource is considered low-utilized.")
    d.define("disk.low.utilization.threshold", T.DOUBLE, 0.0, Range.between(0, 1), I.LOW, "")
    d.define("network.inbound.low.utilization.threshold", T.DOUBLE, 0.0, Range.between(0, 1), I.LOW, "")
    d.define("network.outbound.low.utilization.threshold", T.DOUBLE, 0.0, Range.between(0, 1), I.LOW, "")
    d.define("max.replicas.per.broker", T.LONG, 10_000, Range.at_least(1), I.MEDIUM,
             "ReplicaCapacityGoal ceiling.")
    d.define("proposal.expiration.ms", T.LONG, 60_000, Range.at_least(0), I.MEDIUM,
             "Precomputed proposal freshness budget.")
    d.define("num.proposal.precompute.threads", T.INT, 1, Range.at_least(1), I.LOW,
             "Precompute workers (host-side; device search is batched).")
    d.define("max.solver.rounds", T.INT, 2000, Range.at_least(1), I.MEDIUM,
             "TPU solver: max accepted-move rounds per goal.")
    d.define("solver.candidates.per.round", T.INT, 4096, Range.at_least(16), I.MEDIUM,
             "TPU solver: candidate actions scored per round.")
    d.define("solver.moves.per.round", T.INT, 64, Range.at_least(1), I.MEDIUM,
             "TPU solver: max non-conflicting moves applied per round.")
    d.define("concurrency.adjuster.enabled", T.BOOLEAN, True, None, I.MEDIUM,
             "Re-tune execution concurrency caps each interval from broker "
             "health and (At/Under)MinISR state (Executor.java:465-683).")
    d.define("concurrency.adjuster.interval.ms", T.LONG, 1_000,
             Range.at_least(1), I.LOW,
             "ConcurrencyAdjuster evaluation interval.")
    d.define("concurrency.adjuster.min.isr.check.enabled", T.BOOLEAN, False,
             None, I.LOW, "Consult (At/Under)MinISR state when adjusting "
             "(reference default: false, ExecutorConfig.java:583).")
    d.define("concurrency.adjuster.min.isr.retention.ms", T.LONG, 30_000,
             Range.at_least(1), I.LOW,
             "TopicMinIsrCache entry TTL (TopicMinIsrCache.java).")
    d.define("concurrency.adjuster.min.isr.cache.size", T.INT, 10_000,
             Range.at_least(1), I.LOW, "TopicMinIsrCache size bound.")
    d.define("concurrency.adjuster.inter.broker.replica.enabled", T.BOOLEAN,
             True, None, I.LOW, "Adjust inter-broker movement caps.")
    d.define("concurrency.adjuster.leadership.enabled", T.BOOLEAN, True, None,
             I.LOW, "Adjust leadership movement caps.")
    d.define("concurrency.adjuster.max.leadership.movements", T.INT, 1_100,
             Range.at_least(1), I.LOW, "Adjuster ceiling for cluster "
             "leadership movements (ExecutorConfig.java:350).")
    d.define("concurrency.adjuster.min.leadership.movements", T.INT, 100,
             Range.at_least(1), I.LOW, "Adjuster floor for leadership.")
    # AIMD tuning surface (ExecutorConfig.java:340-583).
    d.define("concurrency.adjuster.additive.increase.inter.broker.replica",
             T.INT, 1, Range.at_least(1), I.LOW,
             "Per-tick additive increase of the per-broker inter-broker "
             "movement cap while the cluster is healthy.")
    d.define("concurrency.adjuster.additive.increase.leadership", T.INT, 100,
             Range.at_least(1), I.LOW,
             "Per-tick additive increase of the cluster leadership cap.")
    d.define("concurrency.adjuster.additive.increase.leadership.per.broker",
             T.INT, 25, Range.at_least(1), I.LOW,
             "Per-tick additive increase of the per-broker leadership cap.")
    d.define("concurrency.adjuster.multiplicative.decrease.inter.broker.replica",
             T.DOUBLE, 2.0, Range.at_least(1), I.LOW,
             "Divisor applied to the inter-broker cap under min-ISR or "
             "metric-limit pressure.")
    d.define("concurrency.adjuster.multiplicative.decrease.leadership",
             T.DOUBLE, 2.0, Range.at_least(1), I.LOW,
             "Divisor applied to the cluster leadership cap under pressure.")
    d.define("concurrency.adjuster.multiplicative.decrease.leadership.per.broker",
             T.DOUBLE, 2.0, Range.at_least(1), I.LOW,
             "Divisor applied to the per-broker leadership cap under "
             "pressure.")
    d.define("concurrency.adjuster.min.partition.movements.per.broker", T.INT,
             1, Range.at_least(1), I.LOW,
             "Adjuster floor for per-broker inter-broker movements.")
    d.define("concurrency.adjuster.max.partition.movements.per.broker", T.INT,
             12, Range.at_least(1), I.LOW,
             "Adjuster ceiling for per-broker inter-broker movements.")
    d.define("concurrency.adjuster.min.leadership.movements.per.broker",
             T.INT, 25, Range.at_least(1), I.LOW,
             "Adjuster floor for per-broker leadership movements.")
    d.define("concurrency.adjuster.max.leadership.movements.per.broker",
             T.INT, 500, Range.at_least(1), I.LOW,
             "Adjuster ceiling for per-broker leadership movements.")
    d.define("concurrency.adjuster.leadership.per.broker.enabled", T.BOOLEAN,
             False, None, I.LOW,
             "Adjust the per-broker leadership cap too.")
    d.define("concurrency.adjuster.limit.log.flush.time.ms", T.DOUBLE, 2000.0,
             Range.at_least(0), I.LOW,
             "Broker log-flush p999 above this counts as a metric-limit "
             "violation.")
    d.define("concurrency.adjuster.limit.follower.fetch.local.time.ms",
             T.DOUBLE, 500.0, Range.at_least(0), I.LOW,
             "Follower-fetch local-time p999 limit.")
    d.define("concurrency.adjuster.limit.produce.local.time.ms", T.DOUBLE,
             1000.0, Range.at_least(0), I.LOW,
             "Produce local-time p999 limit.")
    d.define("concurrency.adjuster.limit.consumer.fetch.local.time.ms",
             T.DOUBLE, 500.0, Range.at_least(0), I.LOW,
             "Consumer-fetch local-time p999 limit.")
    d.define("concurrency.adjuster.limit.request.queue.size", T.DOUBLE,
             1000.0, Range.at_least(0), I.LOW,
             "Request-queue size limit.")
    d.define("min.num.brokers.violate.metric.limit.to.decrease.cluster.concurrency",
             T.INT, 2, Range.at_least(1), I.LOW,
             "Brokers that must exceed a metric limit before the adjuster "
             "decreases concurrency.")
    d.define("concurrency.adjuster.num.min.isr.check", T.INT, 5,
             Range.at_least(1), I.LOW,
             "Recent adjuster ticks whose (At/Under)MinISR observations "
             "stay sticky: pressure seen in ANY of the last N checks keeps "
             "the decrease signal active.")
    d.define("num.concurrent.leader.movements.per.broker", T.INT, 250,
             Range.at_least(1), I.MEDIUM,
             "Per-broker bound on leadership movements per batch.")
    d.define("min.execution.progress.check.interval.ms", T.LONG, 5_000,
             Range.at_least(1), I.LOW,
             "Floor for the progress-check interval override.")
    d.define("auto.stop.external.agent", T.BOOLEAN, True, None, I.MEDIUM,
             "Cancel reassignments started by an external tool before "
             "executing (maybeStopExternalAgent:1261).")
    d.define("list.partition.reassignment.timeout.ms", T.LONG, 60_000,
             Range.at_least(1), I.LOW, "listPartitionReassignments timeout.")
    d.define("list.partition.reassignment.max.attempts", T.INT, 3,
             Range.at_least(1), I.LOW, "listPartitionReassignments retries.")
    d.define("logdir.response.timeout.ms", T.LONG, 10_000, Range.at_least(1),
             I.LOW, "DescribeLogDirs per-broker timeout.")
    d.define("admin.client.request.timeout.ms", T.LONG, 30_000,
             Range.at_least(1), I.LOW, "AdminClient request timeout.")
    d.define("executor.notifier.class", T.CLASS,
             "cruise_control_tpu.executor.notifier.LoggingExecutorNotifier",
             None, I.LOW, "ExecutorNotifier implementation.")
    d.define("demotion.history.retention.time.ms", T.LONG, 86_400_000,
             Range.at_least(1), I.LOW,
             "How long recently-demoted brokers stay excluded.")
    d.define("removal.history.retention.time.ms", T.LONG, 86_400_000,
             Range.at_least(1), I.LOW,
             "How long recently-removed brokers stay excluded.")
    d.define("slow.task.alerting.backoff.ms", T.LONG, 60_000,
             Range.at_least(0), I.LOW,
             "Backoff between slow-task alerts.")
    d.define("solver.chain.fused", T.BOOLEAN, True, None, I.MEDIUM,
             "TPU solver: run the whole goal chain in one device dispatch "
             "(chain.chain_optimize_full) instead of one dispatch per goal "
             "phase.")
    d.define("solver.fused.chain.max.brokers", T.INT, 512, Range.at_least(0),
             I.MEDIUM,
             "Above this broker count the solver switches from the whole-"
             "chain single dispatch to bounded per-goal dispatches: one "
             "XLA program running tens of seconds trips execution "
             "watchdogs on tunneled TPU runtimes. 0 = never switch.")
    d.define("solver.dispatch.max.rounds", T.INT, 16, Range.at_least(1),
             I.MEDIUM,
             "Initial (and minimum) search rounds per device dispatch on "
             "the bounded per-goal path (the host loops to the same fixed "
             "point).")
    d.define("solver.wide.batch.min.brokers", T.INT, 512, Range.at_least(0),
             I.LOW,
             "Cluster size from which goals flagged prefers_wide_batches "
             "run with the widened source grid on the bounded per-goal "
             "path (0 disables wide batches entirely).")
    d.define("solver.wide.batch.source.multiplier", T.INT, 8,
             Range.at_least(1), I.LOW,
             "Source-grid width multiplier for prefers_wide_batches goals "
             "(sources capped at 2048, moves at 2x). Source-limited "
             "late-chain goals convert extra width directly into fewer "
             "rounds (measured at 7k/1M: x8 cuts total rounds 4,258 -> "
             "3,065 at identical balancedness and violated-goal set); "
             "validate quality at scale before raising further.")
    d.define("solver.partition.bucket.size", T.INT, 1024, Range.at_least(0),
             I.LOW,
             "Pad the model's partition axis up to a multiple of this so "
             "ordinary partition-count changes reuse the already-compiled "
             "solver kernels (XLA compiles per shape; a full-chain compile "
             "at large scale is minutes). 0 disables padding.")
    d.define("solver.broker.bucket.size", T.INT, 32, Range.at_least(0), I.LOW,
             "Pad the broker axis up to a multiple of this (see "
             "solver.partition.bucket.size). Pad brokers are masked out "
             "(broker_mask) and DEAD. 0 disables padding.")
    d.define("solver.dispatch.target.seconds", T.DOUBLE, 2.5,
             Range.at_least(0), I.MEDIUM,
             "Adaptive bounded-dispatch sizing: grow the per-dispatch round "
             "budget while a full dispatch completes under half this "
             "wall-clock, shrink when it overshoots 2x. Amortizes the "
             "per-dispatch host-device link latency (a tunneled TPU pays a "
             "fixed RTT per execution) while every dispatch stays far "
             "below execution-watchdog territory. 0 disables adaptation.")
    d.define("solver.megastep.donate", T.BOOLEAN, True, None, I.LOW,
             "Bounded megastep dispatches donate the mutable state tensors "
             "(assignment, leader_slot) to XLA so each dispatch rewrites "
             "them in place instead of allocating a fresh generation. "
             "Automatically disabled on zero-copy backends (CPU), where "
             "device arrays may alias host buffers owned by the "
             "incremental model pipeline.")
    d.define("solver.dispatch.async.readback", T.BOOLEAN, True, None, I.LOW,
             "Bounded-dispatch pipelining: enqueue the next megastep "
             "before reading the previous one's stats scalars, so the "
             "host-device readback RTT overlaps device compute. The "
             "adaptive dispatch controller then learns from the completed "
             "dispatch one step behind. Trajectory-invariant; the only "
             "cost is one speculative zero-apply round per pass.")
    d.define("solver.deficit.moves.cap", T.INT, 2048, Range.at_least(0),
             I.LOW,
             "Deficit-aware batch sizing for count-distribution goals on "
             "the bounded path: moves-per-round / source width are sized "
             "from the goal's measured total band violation (~2x the "
             "moves still needed), rounded up to a power of two and "
             "capped here, instead of the fixed configured width — an "
             "O(10k)-move imbalance stops burning hundreds of fixed-"
             "width rounds. Applies at/above "
             "solver.wide.batch.min.brokers; 0 disables sizing.")
    d.define("solver.direct.assignment.enabled", T.BOOLEAN, False, None,
             I.MEDIUM,
             "Direct-assignment transport kernels for the count-"
             "distribution goals (analyzer.direct): compute the per-"
             "broker / per-topic target counts on device and solve the "
             "surplus-to-deficit matching as a vectorized rank "
             "assignment in one (or a few) dispatches, instead of "
             "hundreds of acceptance-density-limited greedy rounds; the "
             "greedy rounds then only polish the feasibility-vetoed "
             "residue. Applies at/above solver.wide.batch.min.brokers "
             "(it replaces deficit-sized greedy; below the gate the "
             "greedy path is kept byte-identical) and only to chains "
             "whose prior goals the transport feasibility masks can "
             "represent. Ships OFF: enable only with the bench "
             "regression sentry green on the full fixture matrix — "
             "final quality is chaotically sensitive to source "
             "composition (two prior density fixes silently flipped the "
             "86.0 -> 82.74 CpuUsageDistribution canary).")
    d.define("solver.direct.max.sweeps", T.INT, 16, Range.at_least(1), I.LOW,
             "Sweep budget of one direct-assignment dispatch: each sweep "
             "re-plans the transport on the updated counts (vetoed "
             "pairings rotate to different destinations), so a bounded "
             "number of sweeps clears what feasibility allows and the "
             "rest falls to the greedy polish. The loop exits early when "
             "no movers remain OR a few consecutive sweeps apply nothing "
             "(a stalled rotation), so budget beyond convergence is "
             "near-free.")
    d.define("solver.direct.sparse.margin.frac", T.DOUBLE, 0.25,
             Range.between(0.0, 0.5), I.LOW,
             "Fractional band-edge margin of the sparse-aware transport "
             "plan (round 21): shed targets sit margin.frac x band-width "
             "inside the upper edge, fill targets the mirror above the "
             "lower (never below half a count, so 1-count bands keep a "
             "center-ward pull), and deterministic randomized rounding "
             "resolves the fractional per-cell targets so EXPECTED "
             "counts equal the fractional band math in every density "
             "regime. 0 reproduces the parked-at-the-edge plans that "
             "stalled the greedy polish; 0.5 pulls everything to the "
             "band center.")
    d.define("solver.direct.sparse.rounding.salt", T.STRING, "", None, I.LOW,
             "Extra salt folded (crc32, trace time) into the sparse "
             "plan's deterministic rounding seed. Empty keeps the "
             "module's fixed crc32 seed — byte-identical replays per "
             "configuration (the CCSA004 contract); fleets set distinct "
             "salts to decorrelate rounding across replicas without "
             "giving up determinism within each.")
    d.define("solver.direct.density.sparse.threshold", T.DOUBLE, 2.0,
             Range.at_least(0.0), I.LOW,
             "Per-goal density-aware path choice (round 23, ROADMAP 2d): "
             "below this many replicas per (topic, broker) transport "
             "cell, only the goals measured faster under direct at "
             "sparse geometry (TopicReplicaDistribution) keep the "
             "direct-transport arm; Replica/LeaderReplica take "
             "deficit-sized greedy there (the documented honest "
             "negative). At or above the threshold every direct-eligible "
             "goal keeps the direct arm. 0 disables the choice.")
    d.define("solver.fingerprint.skip.enabled", T.BOOLEAN, True, None, I.LOW,
             "Always-hot solver (round 18): snapshot EVERY goal's entry "
             "violation in ONE batched stats program before the bounded "
             "chain loop, and skip a goal's move/swap (and per-goal "
             "stats) dispatches entirely while the snapshot is valid and "
             "shows nothing to do — byte-identical to the unskipped "
             "path, since a violation-free goal applies nothing. Under "
             "sustained drift with warm starts most goals skip, so the "
             "per-goal dispatch floor collapses to one program.")
    d.define("solver.warm.start.enabled", T.BOOLEAN, False, None, I.MEDIUM,
             "Always-hot solver (round 18): seed each default-chain solve "
             "from the facade's last ACCEPTED (assignment, leader_slot) "
             "instead of the cold model state — proposals still diff "
             "against the TRUE current model, and a warm-seeded result "
             "worse than the cold path's sentry band (see "
             "solver.warm.start.quality.band) triggers a counted cold "
             "re-solve, so warm starts can never silently degrade "
             "proposals. OFF by default: warm-seeded searches may reach "
             "a different (quality-band-equivalent) optimum than cold "
             "ones, which flips byte-pinned replay digests.")
    d.define("solver.warm.start.quality.band", T.DOUBLE, 0.05,
             Range.at_least(0.0), I.LOW,
             "Warm-start fallback band: a warm-seeded solve whose "
             "balancedness_after drops more than this below the seed's "
             "own accepted balancedness, or that violates a goal the "
             "seed's solve did not, is discarded and re-solved cold "
             "(counted in solver_warm_fallbacks). Matches the bench "
             "regression sentry's balancedness canary band.")
    d.define("solver.compile.cache.enabled", T.BOOLEAN, True, None, I.LOW,
             "Persist XLA compilation artifacts across process restarts "
             "(the enable_persistent_compile_cache seam, called from "
             "facade start_up so SERVING processes get the cache without "
             "wrapper scripts). The cache is partitioned per host "
             "fingerprint; see solver.compile.cache.dir.")
    d.define("solver.compile.cache.dir", T.STRING, None, None, I.LOW,
             "Root directory of the persistent compile cache. Unset "
             "falls back to $JAX_COMPILATION_CACHE_DIR, then "
             "/tmp/cc_tpu_jax_cache.")
    d.define("solver.compile.cache.min.compile.secs", T.DOUBLE, 1.0,
             Range.at_least(0.0), I.LOW,
             "Minimum backend-compile duration for an artifact to be "
             "persisted (jax_persistent_cache_min_compile_time_secs): "
             "keeps the cache to the expensive solver programs.")
    d.define("solver.prewarm.enabled", T.BOOLEAN, False, None, I.MEDIUM,
             "Always-hot solver (round 18): record every solved padded "
             "bucket-shape signature under the persistent compile "
             "cache's host partition, and have a fresh process compile "
             "the whole known-shape kernel set in a background thread at "
             "start_up (GoalOptimizer.prewarm_shape on inert synthetic "
             "models) — a new replica serves its first rebalance in "
             "seconds instead of paying the warmup compile on the "
             "request path. Requires solver.compile.cache.enabled; "
             "progress on GET /state and /fleet, compiles watched by "
             "xla_compile_cache_{hits,misses}.")
    d.define("solver.warm.start.precheck.enabled", T.BOOLEAN, True, None,
             I.LOW,
             "Warm-band pre-check (round 19, ROADMAP 3a tail): before "
             "committing to a full warm chain, score the seed against "
             "the CURRENT loads in one batched goal-stats program and "
             "skip the warm attempt when the seed's entry picture "
             "already breaches the sentry band (a violated goal its "
             "accepted solve did not have) — the measured drift case "
             "where warm pays attempt+fallback for the cold answer. "
             "Skips counted in solver_warm_precheck_skips. The skip "
             "path serves exactly the fallback's cold solve; a "
             "band-worse seed the full chain COULD have repaired back "
             "into the band is served cold instead — a forfeited warm "
             "win, never degraded quality.")
    d.define("forecast.enabled", T.BOOLEAN, False, None, I.MEDIUM,
             "Predictive rebalancing (round 19): fit a seasonal-trend "
             "forecaster over the monitor's windowed per-partition "
             "history in ONE batched jitted program, project each "
             "resource load forecast.horizon.windows ahead, and let the "
             "PredictiveViolationDetector raise PREDICTED_GOAL_VIOLATION "
             "anomalies whose fix PRECOMPUTES the proposal (never "
             "executes; see anomaly.detection.predictive.fix.enabled). "
             "OFF by default: off means off — the engine and detector "
             "cost one config read per tick and serving behavior is "
             "byte-identical (forecast_noop_overhead guards it).")
    d.define("forecast.fit.windows", T.INT, 16, Range.at_least(4), I.LOW,
             "Exactly how many of the monitor's most recent stable "
             "windows the forecaster fits (fixed so ONE program "
             "compiles per shape instead of one per history length); "
             "fewer available windows = forecast not ready "
             "(forecast_skipped_not_ready).")
    d.define("forecast.horizon.windows", T.INT, 6, Range.at_least(1), I.LOW,
             "How many windows past the last observation the forecaster "
             "projects. The violation-scoring view takes the per-cell "
             "PEAK over the horizon, so one goal-stats program answers "
             "'does any window within the horizon violate?'.")
    d.define("forecast.seasonal.period.windows", T.INT, 0,
             Range.at_least(0), I.LOW,
             "Seasonal period (windows) added to the fit basis as a "
             "sin/cos pair — set to the diurnal period in window units "
             "for daily load shapes; 0 = trend-only fit.")
    d.define("forecast.confidence.z", T.DOUBLE, 2.0, Range.at_least(0.0),
             I.LOW,
             "Confidence-band width in residual-RMS units reported with "
             "each projection (GET /forecast bandMax; detection scores "
             "the mean projection — documented in DESIGN.md).")
    d.define("anomaly.detection.predictive.fix.enabled", T.BOOLEAN, False,
             None, I.MEDIUM,
             "Opt-in PROACTIVE execution for predicted violations: when "
             "true, a PREDICTED_GOAL_VIOLATION fix runs a real "
             "self-healing rebalance BEFORE the violation materializes. "
             "Default false: the fix only precomputes (projected-model "
             "dry-run solve + warm-seed store + fleet pacer promotion) "
             "so the proposal is hot when the real violation lands.")
    d.define("self.healing.predicted.violation.enabled", T.BOOLEAN, True,
             None, I.LOW,
             "Per-type self-healing switch for PREDICTED_GOAL_VIOLATION "
             "anomalies (the notifier's FIX verdict gate). The fix is a "
             "dry-run precompute unless "
             "anomaly.detection.predictive.fix.enabled is also true, so "
             "the default-on only spends solver time, never moves.")
    d.define("futures.live.seed.enabled", T.BOOLEAN, True, None, I.LOW,
             "Futures engine (ROADMAP 5b tail): seed COMPARE_FUTURES "
             "twins from the LIVE cluster's geometry (brokers, racks, "
             "topics, RF) instead of the synthetic BASE_SPEC, and let "
             "the forecast_horizon template solve the REAL projected "
             "loads — candidate futures become futures of THIS cluster. "
             "Falls back to BASE_SPEC when the model is not ready.")
    d.define("fleet.bucket.broker.base", T.INT, 4, Range.at_least(1), I.LOW,
             "Fleet federation: smallest broker-axis bucket of the shared "
             "geometric shape grid (fleet.bucketing.BucketGrid). Every "
             "registered cluster's model is padded up to a grid point so "
             "N clusters share a handful of compiled chain kernels.")
    d.define("fleet.bucket.partition.base", T.INT, 256, Range.at_least(1),
             I.LOW,
             "Fleet federation: smallest partition-axis bucket of the "
             "shared geometric shape grid.")
    d.define("fleet.bucket.topic.base", T.INT, 8, Range.at_least(1), I.LOW,
             "Fleet federation: smallest bucket for the topic-count "
             "static solver argument (the [T, B] topic planes); pad "
             "topics host no replicas and are goal-neutral.")
    d.define("fleet.bucket.geometric.factor", T.DOUBLE, 2.0,
             Range.at_least(1.01), I.LOW,
             "Fleet federation: growth factor between grid points on both "
             "axes (bucket sizes base x factor^k; 2.0 = powers of two, "
             "bounding pad overhead below one octave).")
    d.define("fleet.precompute.cadence.ms", T.LONG, 60_000,
             Range.at_least(1), I.LOW,
             "Fleet federation: per-cluster proposal-precompute cadence "
             "enforced by the FleetScheduler's pacer (overridable per "
             "cluster via its registration overlay). The fleet analogue "
             "of the facade's own precompute loop.")
    d.define("fleet.scheduler.starvation.bound.ms", T.LONG, 30_000,
             Range.at_least(1), I.LOW,
             "Fleet federation: any queued solver job older than this "
             "runs next regardless of priority class, so one cluster's "
             "flood can delay but never starve another cluster's work. "
             "With megabatch coalescing the bound applies to BATCHES: "
             "the overdue job is picked first and its compatible queued "
             "peers ride along in its batch.")
    d.define("fleet.megabatch.enabled", T.BOOLEAN, True, None, I.MEDIUM,
             "Megabatch fleet solver (round 14): the scheduler drains "
             "compatible queued precomputes (same bucket shape + goal "
             "chain) into ONE batched device program — same-bucket "
             "clusters stacked along a cluster axis and solved through "
             "the donated megastep kernels, byte-identical per cluster "
             "to serial solves. Solver throughput then scales with the "
             "batch, not threads. Disabled, every job runs solo (the "
             "round-6 behavior).")
    d.define("fleet.megabatch.width", T.INT, 4, Range.at_least(1), I.LOW,
             "Cluster-axis width of a megabatch program. FIXED per "
             "bucket shape: partially-filled batches pad with inert "
             "zero-weight cluster slots, so one compiled program per "
             "bucket shape serves any occupancy (occupancy is traced, "
             "never a new compile). More queued compatibles than the "
             "width split into multiple batches.")
    d.define("fleet.shard.enabled", T.BOOLEAN, True, None, I.MEDIUM,
             "Device-sharded megabatch (round 23): with a device mesh "
             "attached, shard the megabatch CLUSTER axis across it — "
             "batch_width / n_devices cluster slots per device, each "
             "device early-exiting on its own shard's convergence, "
             "per-cluster results byte-identical to the single-device "
             "megabatch. Disabled (or single-device), batched solves "
             "run on one device as in round 14.")
    d.define("fleet.shard.workers", T.INT, 1, Range.at_least(1), I.MEDIUM,
             "Multi-replica control plane (round 23): number of fleet "
             "solver worker threads sharing the scheduler queue, the "
             "persistent AOT cache, and the shape registry. Placement "
             "is bucket-affine (a batch key sticks to the worker that "
             "first solved it, keeping its compiled programs hot) with "
             "work-stealing: overdue jobs (past the starvation bound) "
             "and idle workers steal across affinity, so the starvation "
             "bound holds fleet-wide. 1 = the single-worker round-6..22 "
             "behavior, byte-identical.")
    d.define("serving.task.queue.viewer.capacity", T.INT, 64,
             Range.at_least(1), I.LOW,
             "Serving front door (round 20): bound on QUEUED "
             "VIEWER-class async tasks (cheap reads: load, "
             "partition_load, ...). A full queue sheds the request with "
             "429 + Retry-After before any task is created.")
    d.define("serving.task.queue.solver.capacity", T.INT, 32,
             Range.at_least(1), I.LOW,
             "Serving front door: bound on QUEUED SOLVER-class async "
             "tasks (proposals, rebalance, broker ops, futures — the "
             "device-heavy endpoints).")
    d.define("serving.task.viewer.threads", T.INT, 4, Range.at_least(1),
             I.LOW,
             "Serving front door: worker threads draining the VIEWER "
             "task queue.")
    d.define("serving.task.solver.threads", T.INT, 2, Range.at_least(1),
             I.LOW,
             "Serving front door: worker threads draining the SOLVER "
             "task queue. These threads only WAIT on fleet-scheduler "
             "futures — the device work itself runs on the scheduler's "
             "worker, so this bounds concurrent waiters, not compiles.")
    d.define("serving.cache.enabled", T.BOOLEAN, True, None, I.MEDIUM,
             "Serving front door: model-generation-keyed response cache. "
             "A response is identified by (cluster, endpoint, canonical "
             "params, load-model generation, goal-chain fingerprint) and "
             "served byte-identical until the generation or the "
             "configured goal chain moves. Only deterministic "
             "generation-pure endpoints (proposals, futures) are "
             "cacheable; cache-busting params (ignore_proposal_cache, "
             "data_from, what_if, ...) bypass it.")
    d.define("serving.cache.max.entries", T.INT, 256, Range.at_least(1),
             I.LOW,
             "Serving front door: response-cache entry bound (oldest "
             "evicted first; entries also die with their generation).")
    d.define("serving.cache.state.enabled", T.BOOLEAN, False, None, I.LOW,
             "Serving front door: also cache GET /state envelopes. OFF "
             "by default — executor progress and anomaly-detector state "
             "move WITHOUT a model-generation bump, so a generation-"
             "keyed /state cache can serve stale operational truth; "
             "enable only for dashboards that poll faster than they "
             "need freshness.")
    d.define("serving.coalesce.enabled", T.BOOLEAN, True, None, I.MEDIUM,
             "Serving front door: cross-user request coalescing. "
             "Identical concurrent in-flight requests (same cluster, "
             "endpoint, canonical params, generation, goal chain) "
             "attach to ONE solve — each caller still gets its own "
             "session-bound User-Task-ID, but every task shares the "
             "leader's future (the round-15 precompute-coalescing "
             "contract generalized to user traffic).")
    d.define("serving.admission.enabled", T.BOOLEAN, True, None, I.MEDIUM,
             "Serving front door: queue-depth-aware admission control "
             "layered ABOVE the per-cluster breaker. New work arriving "
             "while a class queue is past its depth bound is shed with "
             "429 + Retry-After derived from the observed per-class "
             "service rate (depth x EWMA service time). Polls of "
             "existing tasks, cache hits and coalesced joins are never "
             "shed.")
    d.define("serving.admission.queue.viewer.max", T.INT, 32,
             Range.at_least(1), I.LOW,
             "Serving front door: VIEWER queue depth beyond which new "
             "viewer requests are shed (must not exceed the queue "
             "capacity or the capacity bound sheds first).")
    d.define("serving.admission.queue.solver.max", T.INT, 8,
             Range.at_least(0), I.LOW,
             "Serving front door: SOLVER queue depth beyond which new "
             "solver requests are shed. 0 sheds ALL new solver work — a "
             "drain valve for maintenance windows.")
    d.define("tracing.enabled", T.BOOLEAN, True, None, I.LOW,
             "Pipeline span tracing (utils.tracing): every operation — "
             "sampling, model build, per-goal solve, execution — records "
             "a span tree served at GET /trace, with per-stage latency "
             "histograms on /metrics. Disabled, the tracer is a shared "
             "no-op context manager: nothing on the solver hot path.")
    d.define("tracing.max.traces", T.INT, 256, Range.at_least(1), I.LOW,
             "Bound on the in-memory ring of recent traces (oldest "
             "evicted; ~a few KB per trace).")
    d.define("tracing.jsonl.path", T.STRING, "", None, I.LOW,
             "Append one JSON line per completed trace to this file "
             "(bench/CI artifact hook); empty = off.")
    d.define("tracing.jsonl.max.bytes", T.LONG, 67_108_864,
             Range.at_least(0), I.LOW,
             "Size cap on the tracing JSONL dump: when an append would "
             "push the file past this, it is rotated to <path>.1 (see "
             "tracing.jsonl.max.files for how many rotated generations "
             "are kept) so a long-running process can never grow the "
             "dump without bound. 0 = unlimited.")
    d.define("tracing.jsonl.max.files", T.INT, 1, Range.at_least(1), I.LOW,
             "Rotated JSONL generations kept: rotation cascades "
             "<path>.1 -> <path>.2 -> ... up to this count before the "
             "oldest falls off. 1 preserves the historical single-"
             "generation behavior.")
    d.define("solver.flight.recorder.enabled", T.BOOLEAN, True, None, I.LOW,
             "Solver flight recorder (utils.flight_recorder): per-goal, "
             "per-dispatch search telemetry — acceptance density, "
             "candidate-kill attribution, per-round violation "
             "trajectories, deficit-sizing decisions, AdaptiveDispatch "
             "state — served at GET /solver and exported as "
             "solver_flight_* sensors. Recording never changes solver "
             "trajectories (byte-parity pinned in tests); disabled, "
             "every hook is a shared no-op (bench-guarded by "
             "flight_recorder_noop_overhead).")
    d.define("solver.flight.recorder.max.passes", T.INT, 64,
             Range.at_least(1), I.LOW,
             "Bound on the in-memory ring of recorded optimization "
             "passes (oldest evicted).")
    d.define("solver.flight.recorder.ring.rounds", T.INT, 128,
             Range.at_least(0), I.LOW,
             "Length of the on-device per-round stats ring carried "
             "through the single-device move megasteps (~24 bytes per "
             "slot; older rounds of a longer dispatch are overwritten "
             "oldest-first). Trace-time constant: changing it recompiles "
             "the recording chain kernels. 0 records at dispatch "
             "granularity only.")
    d.define("heal.ledger.enabled", T.BOOLEAN, True, None, I.LOW,
             "Heal ledger (utils.heal_ledger): per-anomaly lifecycle "
             "chains — detection, notifier verdicts, fix dispatch, "
             "model/solve phases (flight-recorder pass ids linked), "
             "execution progress, and the terminal outcome — served at "
             "GET /heals and exported as heal_phase_seconds{phase=} / "
             "time_to_heal_seconds{type=} histograms and the "
             "heals_open{type=} gauge. Observation only: proposals and "
             "final assignments are byte-identical with the ledger on "
             "or off (pinned); disabled, every hook is the shared NO_HEAL "
             "no-op (bench-guarded by heal_ledger_noop_overhead).")
    d.define("heal.ledger.max.chains", T.INT, 256, Range.at_least(1), I.LOW,
             "Bound on retained heal chains per facade (oldest evicted; "
             "a still-open evicted chain terminates as 'evicted' so no "
             "heal silently vanishes from the export).")
    d.define("heal.ledger.max.phases", T.INT, 64, Range.at_least(4), I.LOW,
             "Bound on phase transitions kept per chain; further "
             "transitions are counted in the chain's droppedPhases "
             "field instead of growing it without bound.")
    # --- Request journeys + SLO engine (round 18) ---
    d.define("journey.enabled", T.BOOLEAN, True, None, I.LOW,
             "Request journeys (serving.journey): per-request segment "
             "attribution — admission, cache lookup, coalesce join, "
             "queue wait, fleet-scheduler wait, model build, solve "
             "(flight-recorder pass ids + heal chain linked), proposal "
             "diff, render, cache store — kept in a bounded ring served "
             "at GET /journeys and exported as "
             "journey_segment_seconds{endpoint=,segment=} histograms. "
             "Observation only: responses are byte-identical with "
             "journeys on or off (pinned); disabled, the open() hook "
             "returns the shared NO_JOURNEY no-op (bench-guarded by "
             "journey_noop_overhead).")
    d.define("journey.max.entries", T.INT, 256, Range.at_least(1), I.LOW,
             "Bound on the in-memory ring of completed journeys per "
             "facade (oldest evicted; ~1 KB per journey).")
    d.define("slo.enabled", T.BOOLEAN, False, None, I.MEDIUM,
             "SLO engine (utils.slo): declarative objectives evaluated "
             "over sliding multi-window counters, exported as "
             "slo_error_budget_remaining{objective=} and "
             "slo_burn_rate{objective=,window=} and served at GET /slo. "
             "Off (default) the engine records nothing and every probe "
             "is ns-scale (bench-guarded by slo_noop_overhead).")
    d.define("slo.objectives", T.LIST, ["latency", "error", "shed"], None,
             I.LOW,
             "Active objective kinds (subset of latency, error, shed, "
             "staleness, heal); each kind reads its own "
             "slo.objectives.<kind>.* budget/threshold keys.")
    d.define("slo.objectives.latency.quantile", T.DOUBLE, 0.99,
             Range.between(0, 1), I.LOW,
             "Latency objective: the serving_request_seconds quantile "
             "the threshold applies to (reported on GET /slo; the burn "
             "accounting itself is per-request event-based).")
    d.define("slo.objectives.latency.threshold.seconds", T.DOUBLE, 2.0,
             Range.at_least(0), I.LOW,
             "Latency objective: a successful request slower than this "
             "is a bad event against the latency budget.")
    d.define("slo.objectives.latency.budget", T.DOUBLE, 0.05,
             Range.between(0, 1), I.LOW,
             "Latency objective: tolerated bad-event fraction (error "
             "budget). Burn rate = observed bad fraction / budget.")
    d.define("slo.objectives.error.budget", T.DOUBLE, 0.01,
             Range.between(0, 1), I.LOW,
             "Error objective: tolerated fraction of requests answering "
             "5xx/4xx (sheds excluded — they have their own objective).")
    d.define("slo.objectives.shed.budget", T.DOUBLE, 0.05,
             Range.between(0, 1), I.LOW,
             "Shed objective: tolerated fraction of requests answered "
             "429 by the admission layer.")
    d.define("slo.objectives.staleness.threshold.seconds", T.DOUBLE, 300.0,
             Range.at_least(0), I.LOW,
             "Staleness objective: a stale-serve whose proposal age "
             "exceeds this is a bad event.")
    d.define("slo.objectives.staleness.budget", T.DOUBLE, 0.05,
             Range.between(0, 1), I.LOW,
             "Staleness objective: tolerated bad-event fraction among "
             "stale serves.")
    d.define("slo.objectives.heal.threshold.seconds", T.DOUBLE, 600.0,
             Range.at_least(0), I.LOW,
             "Heal objective: a completed heal chain slower than this "
             "(detection -> cleared) is a bad event.")
    d.define("slo.objectives.heal.budget", T.DOUBLE, 0.1,
             Range.between(0, 1), I.LOW,
             "Heal objective: tolerated fraction of slow heals.")
    d.define("slo.burn.windows", T.LIST,
             ["300", "3600", "1800", "21600"], None, I.LOW,
             "Burn-rate windows in seconds, ordered fast-short, "
             "fast-long, slow-short, slow-long (the multi-window "
             "multi-burn-rate alerting shape: a page needs BOTH windows "
             "of a pair burning, so a blip can't page and a slow leak "
             "can't hide).")
    d.define("slo.burn.fast.threshold", T.DOUBLE, 14.4,
             Range.at_least(0), I.LOW,
             "Fast-pair burn multiple that raises SLO_BURN (14.4x "
             "spends 2% of a 30-day budget in an hour).")
    d.define("slo.burn.slow.threshold", T.DOUBLE, 6.0,
             Range.at_least(0), I.LOW,
             "Slow-pair burn multiple that raises SLO_BURN (6x spends "
             "5% of a 30-day budget in 6 hours).")
    d.define("profiling.enabled", T.BOOLEAN, True, None, I.LOW,
             "On-demand device profiling (GET /profile): "
             "jax.profiler.trace captures of live solves plus the "
             "in-process op-class microbench (utils.profiling; "
             "single-flight, busy requests get 503 + Retry-After).")
    d.define("profiling.trace.dir", T.STRING, "/tmp/cc_profile", None,
             I.LOW,
             "Directory receiving Perfetto/TensorBoard trace captures "
             "(one timestamped subdirectory per capture).")
    d.define("profiling.max.duration.seconds", T.DOUBLE, 60.0,
             Range.at_least(0.05), I.LOW,
             "Cap on one profile capture's duration_s: the capture holds "
             "the profiler gate and buffers host/device events for its "
             "whole window, so an oversized request is clamped, not "
             "honored.")
    d.define("xla.telemetry.enabled", T.BOOLEAN, True, None, I.LOW,
             "Hook jax.monitoring compile events (per padded-bucket-shape "
             "count + seconds — the recompile-churn watchdog), "
             "compilation-cache hit/miss counters, and device memory "
             "gauges into /metrics (utils.xla_telemetry).")
    # --- Resilience layer (utils/resilience.py, round 9) ---
    d.define("resilience.enabled", T.BOOLEAN, True, None, I.MEDIUM,
             "Retry/backoff + circuit breaking on every external "
             "interaction (sampling fetch, admin calls, reassignment "
             "submission, fleet jobs, detector runs). Disabled, every "
             "wrapped call is a bare passthrough (ns-scale, bench-"
             "guarded by resilience_noop_overhead).")
    d.define("resilience.retry.max.attempts", T.INT, 5, Range.at_least(1),
             I.MEDIUM, "Attempts per wrapped call (1 = no retries).")
    d.define("resilience.retry.base.backoff.ms", T.LONG, 100,
             Range.at_least(0), I.LOW,
             "Backoff before the first re-attempt; doubles (see "
             "multiplier) up to the max per further attempt.")
    d.define("resilience.retry.max.backoff.ms", T.LONG, 10_000,
             Range.at_least(0), I.LOW, "Backoff ceiling per attempt.")
    d.define("resilience.retry.backoff.multiplier", T.DOUBLE, 2.0,
             Range.at_least(1), I.LOW, "Exponential backoff growth factor.")
    d.define("resilience.retry.jitter.ratio", T.DOUBLE, 0.2,
             Range.between(0, 1), I.LOW,
             "Fraction of the exponential backoff subtracted by the "
             "DETERMINISTIC seeded jitter (crc32 of seed:op:attempt — "
             "replayable, not a PRNG stream).")
    d.define("resilience.retry.seed", T.INT, 0, None, I.LOW,
             "Jitter seed; the same seed replays the same backoff "
             "schedule byte-for-byte (chaos-test determinism).")
    d.define("resilience.retry.overall.deadline.ms", T.LONG, 60_000,
             Range.at_least(1), I.LOW,
             "Overall wall budget per wrapped call: a retry whose "
             "backoff would overrun it gives up instead of sleeping.")
    d.define("resilience.breaker.failure.threshold", T.INT, 5,
             Range.at_least(0), I.MEDIUM,
             "Consecutive failures per target (cluster id, detector, "
             "model path) before its circuit breaker opens; 0 disables "
             "breaking while keeping retries.")
    d.define("resilience.breaker.recovery.ms", T.LONG, 30_000,
             Range.at_least(1), I.LOW,
             "Open-breaker recovery window; afterwards one half-open "
             "probe decides reopen vs. close. Also the Retry-After "
             "hint on 503 responses for open targets.")
    d.define("resilience.sampling.min.completeness", T.DOUBLE, 0.5,
             Range.between(0, 1), I.MEDIUM,
             "Minimum fraction of the partition universe a sampling "
             "interval must fetch to be ingested: windows above the "
             "floor are accepted PARTIAL (degraded beats absent), "
             "below it rejected (PartialWindowError).")
    d.define("resilience.executor.dead.letter.attempts", T.INT, 3,
             Range.at_least(1), I.MEDIUM,
             "Failed submissions per execution task before it is dead-"
             "lettered to the EXECUTION_ABANDONED terminal state (with "
             "a notifier event) instead of hanging the execution.")
    # --- Chaos harness (testing/chaos.py) ---
    d.define("chaos.enabled", T.BOOLEAN, False, None, I.LOW,
             "Wrap the admin backend in the deterministic fault "
             "injector (game-day drills; NEVER in production serving).")
    d.define("chaos.seed", T.INT, 0, None, I.LOW,
             "Fault-schedule seed: the same seed injects the same "
             "fault sequence byte-for-byte.")
    d.define("chaos.fault.rate", T.DOUBLE, 0.1, Range.between(0, 1), I.LOW,
             "Per-call injected fault probability (timeout / transient "
             "/ partial / slow, crc32-uniform).")
    d.define("chaos.broker.flap.rate", T.DOUBLE, 0.0, Range.between(0, 1),
             I.LOW,
             "Per-call probability that alive_brokers transiently "
             "omits one deterministic broker (flap injection; opt-in — "
             "flapped destinations DEAD-mark in-flight tasks).")
    # --- Digital-twin scenario harness (testing/simulator.py, round 11) ---
    d.define("scenario.tick.seconds", T.DOUBLE, 60.0, Range.at_least(0.001),
             I.LOW,
             "Simulated seconds each digital-twin tick advances the "
             "injected clock (the scenario harness's time step).")
    d.define("scenario.default.ticks", T.INT, 120, Range.at_least(1), I.LOW,
             "Default number of simulated ticks a scenario runs when the "
             "caller does not override it.")
    d.define("scenario.what.if.max.ticks", T.INT, 240, Range.at_least(1),
             I.LOW,
             "Cap on the tick count a PROPOSALS ?what_if= request may ask "
             "for (a what-if replay is real solver work; unbounded ticks "
             "would let one request monopolize the device).")
    d.define("scenario.slo.balancedness.min", T.DOUBLE, 75.0,
             Range.between(0, 100), I.LOW,
             "Quality SLO floor: a tick whose balancedness score sits "
             "below this (once detection has scored at all) counts as an "
             "SLO violation in the scenario report.")
    d.define("scenario.slo.heal.ticks", T.INT, 30, Range.at_least(1), I.LOW,
             "Stability SLO: an injected fault not healed within this "
             "many ticks — or never healed — is an SLO violation.")
    d.define("scenario.slo.moves.per.simhour", T.DOUBLE, 0.0,
             Range.at_least(0), I.LOW,
             "Churn SLO: replica moves per simulated hour above this "
             "rate are an SLO violation (0 disables the churn SLO).")
    d.define("scenario.proposal.probe.ticks", T.INT, 10, Range.at_least(0),
             I.LOW,
             "Every N simulated ticks the scenario harness issues a "
             "client-style proposals() probe so degraded serving "
             "(stale=true responses, model-build failures) is part of "
             "the scored trajectory (0 disables probing).")
    # --- Futures engine (futures/, round 15) ---
    d.define("futures.default.count", T.INT, 8, Range.at_least(1), I.LOW,
             "Candidate futures a COMPARE_FUTURES request evaluates when "
             "num_futures is not given (templates round-robin, seeds "
             "advance per cycle — every row replayable via "
             "what_if=random:<template>:<seed>).")
    d.define("futures.max.count", T.INT, 32, Range.at_least(1), I.LOW,
             "Cap on num_futures per COMPARE_FUTURES request: each "
             "future costs a twin advance (host) and a batched solve "
             "slot (device); unbounded requests would let one client "
             "monopolize both.")
    d.define("futures.default.ticks", T.INT, 12, Range.at_least(4), I.LOW,
             "Default advance horizon (simulated ticks to each future's "
             "decision point) when a COMPARE_FUTURES request omits "
             "ticks. Floor 4: the twin fills one metrics window per "
             "tick and the decision model build needs its windows.")
    d.define("futures.max.ticks", T.INT, 60, Range.at_least(4), I.LOW,
             "Cap on a COMPARE_FUTURES advance horizon (the advance is "
             "per-future host-side simulation; the what-if replay cap "
             "scenario.what.if.max.ticks plays the same role for full-"
             "loop replays).")
    d.define("futures.batch.width", T.INT, 8, Range.at_least(1), I.LOW,
             "Cluster-axis width of a batched futures solve (the "
             "evaluator's direct path; fleet-coalesced futures use "
             "fleet.megabatch.width). Fixed per bucket shape: partial "
             "chunks pad with inert slots so one compiled program per "
             "shape serves any occupancy.")
    # --- Red-team scenario mining (redteam/, round 22) ---
    d.define("redteam.enabled", T.BOOLEAN, True, None, I.LOW,
             "Serve the mined regression frontier (GET /redteam, "
             "what_if=mined:<id> replays). False = both surfaces answer "
             "400 and nothing else changes: mining only ever runs when "
             "explicitly invoked (bench.py --redteam), never on the "
             "serving path.")
    d.define("redteam.population", T.INT, 12, Range.at_least(2), I.LOW,
             "Candidates per mining generation (half mutations of the "
             "current frontier, half fresh crc32-derived samples; "
             "generation 0 is all fresh).")
    d.define("redteam.generations", T.INT, 4, Range.at_least(1), I.LOW,
             "Mining generations per sweep: sample -> megabatch screen "
             "-> full-loop score survivors -> keep the K worst -> "
             "mutate.")
    d.define("redteam.survivors", T.INT, 4, Range.at_least(1), I.LOW,
             "Worst-screened candidates per generation that earn a "
             "full-loop scored replay (detection + self-healing on) — "
             "the expensive half of the eval budget.")
    d.define("redteam.frontier.size", T.INT, 8, Range.at_least(1), I.LOW,
             "Worst-case survivors the frontier retains (lowest SLO "
             "margin first, ties broken on entry id byte-stably).")
    d.define("redteam.ticks", T.INT, 24, Range.at_least(4), I.LOW,
             "Full-loop horizon of a mined candidate (its sampled story "
             "compresses into this many ticks, faults included). Floor "
             "4: one metrics window fills per tick.")
    d.define("redteam.eval.budget", T.INT, 200, Range.at_least(1), I.LOW,
             "Total candidate evaluations (megabatch screens + full-"
             "loop replays) one sweep may spend; exhaustion ends the "
             "sweep partial=True with the reason recorded — never a "
             "silent cap.")
    d.define("redteam.frontier.path", T.STRING,
             "fileStore/redteam_frontier.json", None, I.LOW,
             "The committed regression frontier file GET /redteam and "
             "what_if=mined:<id> serve (sorted-keys JSON; every entry "
             "replayable byte-identically).")
    d.define("goal.violation.distribution.threshold.multiplier", T.DOUBLE, 1.0,
             Range.at_least(1), I.LOW,
             "Detector-triggered balance-threshold relaxation.")
    d.define("goal.balancedness.priority.weight", T.DOUBLE, 1.1, Range.at_least(1), I.LOW,
             "Geometric weight per goal-priority level in balancedness score.")
    d.define("goal.balancedness.strictness.weight", T.DOUBLE, 1.5, Range.at_least(1), I.LOW,
             "Extra weight for hard goals in balancedness score.")
    d.define("fast.mode.per.broker.move.timeout.ms", T.LONG, 500, Range.at_least(1), I.LOW,
             "Fast-mode (fast_mode=true request param) per-broker time "
             "budget: each goal's search wall-clock is capped at this "
             "value x num_brokers, and every goal runs the wide-batch "
             "grid (fewer, coarser rounds). Batch-search mapping of the "
             "reference's per-broker greedy timeout.")
    d.define("intra.broker.goals", T.LIST,
             ["IntraBrokerDiskCapacityGoal", "IntraBrokerDiskUsageDistributionGoal"],
             None, I.LOW, "Goal chain for rebalance_disk/remove_disks.")
    d.define("optimization.options.generator.class", T.CLASS, None, None,
             I.LOW,
             "Pluggable OptimizationOptions generation for goal-violation "
             "detection and cached-proposal computation "
             "(DefaultOptimizationOptionsGenerator.java).")
    d.define("rack.aware.goal.rack.id.mapper.class", T.CLASS, None, None,
             I.LOW,
             "Transforms broker rack ids before rack-aware goals group by "
             "them, e.g. collapsing AZ suffixes (goals/rackaware/"
             "RackAwareGoalRackIdMapper.java).")
    d.define("topics.excluded.from.partition.movement", T.STRING, "", None,
             I.MEDIUM, "Regex of topics never moved.")
    d.define("topic.replica.count.balance.min.gap", T.INT, 2,
             Range.at_least(0), I.LOW,
             "TopicReplicaDistribution band minimum width.")
    d.define("topic.replica.count.balance.max.gap", T.INT, 40,
             Range.at_least(0), I.LOW,
             "TopicReplicaDistribution band maximum width.")
    d.define("topics.with.min.leaders.per.broker", T.STRING, "", None, I.LOW,
             "Regex of topics MinTopicLeadersPerBrokerGoal applies to.")
    d.define("min.topic.leaders.per.broker", T.INT, 1, Range.at_least(0),
             I.LOW, "Leader floor per broker for matched topics.")
    d.define("allow.capacity.estimation.on.proposal.precompute", T.BOOLEAN,
             True, None, I.LOW,
             "Precompute passes may estimate missing capacities.")
    d.define("broker.set.resolver.class", T.CLASS, None, None, I.LOW,
             "BrokerSet membership resolver plugin.")
    d.define("broker.set.assignment.policy.class", T.CLASS, None, None, I.LOW,
             "BrokerSet assignment policy plugin.")
    d.define("broker.set.config.file", T.STRING, "config/brokerSets.json",
             None, I.LOW, "BrokerSet definitions.")
    d.define("overprovisioned.min.brokers", T.INT, 3, Range.at_least(1),
             I.LOW, "Provisioner floor before recommending removal.")
    d.define("overprovisioned.max.replicas.per.broker", T.LONG, 1_500,
             Range.at_least(1), I.LOW,
             "Replica ceiling that still counts as over-provisioned.")
    d.define("overprovisioned.min.extra.racks", T.INT, 2, Range.at_least(0),
             I.LOW, "Extra racks required to call a cluster over-provisioned.")
    d.define("metadata.factor.exponent", T.DOUBLE, 1.0, Range.at_least(0),
             I.LOW, "Metadata-scale exponent in provision recommendations.")

    # --- Executor (ExecutorConfig.java) ---
    d.define("num.concurrent.partition.movements.per.broker", T.INT, 10, Range.at_least(1),
             I.HIGH, "Per-broker inter-broker replica move cap.")
    d.define("max.num.cluster.partition.movements", T.INT, 1250, Range.at_least(1), I.HIGH,
             "Cluster-wide in-flight replica move cap.")
    d.define("num.concurrent.intra.broker.partition.movements", T.INT, 2, Range.at_least(1),
             I.MEDIUM, "Per-broker intra-broker (disk) move cap.")
    d.define("num.concurrent.leader.movements", T.INT, 1000, Range.at_least(1), I.HIGH,
             "Cluster-wide leadership movement cap.")
    d.define("max.num.cluster.movements", T.INT, 1250, Range.at_least(1), I.MEDIUM,
             "Upper bound of total in-flight movements.")
    d.define("execution.progress.check.interval.ms", T.LONG, 10_000, Range.at_least(1), I.HIGH,
             "Execution progress poll interval.")
    d.define("default.replication.throttle", T.LONG, None, None, I.MEDIUM,
             "Bytes/sec replication throttle during moves (None = no throttle).")
    d.define("replica.movement.strategies", T.LIST,
             ["cruise_control_tpu.executor.strategy.BaseReplicaMovementStrategy"],
             None, I.LOW, "Chain of replica movement orderings.")
    d.define("default.replica.movement.strategies", T.LIST,
             ["cruise_control_tpu.executor.strategy.BaseReplicaMovementStrategy"],
             None, I.LOW, "Default strategy chain.")
    d.define("executor.concurrency.adjuster.enabled", T.BOOLEAN, True, None, I.MEDIUM,
             "Adaptive concurrency adjuster on/off.")
    d.define("executor.concurrency.adjuster.interval.ms", T.LONG, 360_000, Range.at_least(1),
             I.LOW, "Concurrency adjuster cadence.")
    d.define("leader.movement.timeout.ms", T.LONG, 180_000, Range.at_least(1), I.LOW,
             "Leadership movement timeout before marking dead.")
    d.define("task.execution.alerting.threshold.ms", T.LONG, 90_000, Range.at_least(1), I.LOW,
             "Slow-task alert threshold.")
    d.define("admin.client.class", T.CLASS,
             "cruise_control_tpu.executor.admin.SimulatedAdminBackend",
             None, I.HIGH, "Cluster admin backend (simulated or Kafka).")

    # --- Anomaly detector (AnomalyDetectorConfig.java) ---
    d.define("anomaly.detection.interval.ms", T.LONG, 300_000, Range.at_least(1), I.HIGH,
             "Base detector cadence.")
    d.define("goal.violation.detection.interval.ms", T.LONG, None, None, I.LOW,
             "Override for goal-violation detector cadence.")
    d.define("metric.anomaly.detection.interval.ms", T.LONG, None, None, I.LOW, "")
    d.define("broker.failure.detection.backoff.ms", T.LONG, 300_000, Range.at_least(1), I.LOW, "")
    d.define("anomaly.notifier.class", T.CLASS,
             "cruise_control_tpu.detector.notifier.SelfHealingNotifier",
             None, I.HIGH, "AnomalyNotifier implementation.")
    d.define("self.healing.enabled", T.BOOLEAN, False, None, I.HIGH,
             "Global self-healing toggle.")
    d.define("self.healing.broker.failure.enabled", T.BOOLEAN, True, None, I.MEDIUM, "")
    d.define("self.healing.goal.violation.enabled", T.BOOLEAN, True, None, I.MEDIUM, "")
    d.define("self.healing.disk.failure.enabled", T.BOOLEAN, True, None, I.MEDIUM, "")
    d.define("self.healing.metric.anomaly.enabled", T.BOOLEAN, False, None, I.MEDIUM, "")
    d.define("self.healing.topic.anomaly.enabled", T.BOOLEAN, False, None, I.MEDIUM, "")
    d.define("self.healing.maintenance.event.enabled", T.BOOLEAN, False, None, I.MEDIUM, "")
    d.define("self.healing.slo.burn.enabled", T.BOOLEAN, False, None,
             I.MEDIUM,
             "Per-type self-healing switch for SLO_BURN anomalies (the "
             "notifier's FIX verdict gate). The fix is a mitigation "
             "nudge — it marks the predictive precompute pending so the "
             "next fleet cycle refreshes proposals — never a move.")
    d.define("maintenance.event.reader.class", T.CLASS,
             "cruise_control_tpu.detector.maintenance.InMemoryMaintenanceEventReader",
             None, I.MEDIUM,
             "Pluggable maintenance-plan source "
             "(MaintenanceEventTopicReader analogue: "
             "detector.maintenance_serde.TopicMaintenanceEventReader reads "
             "versioned plans from a Kafka topic; the file reader tails a "
             "JSON-lines file).")
    d.define("maintenance.event.topic", T.STRING,
             "__CruiseControlMaintenanceEvent", None, I.LOW,
             "Topic the maintenance-plan reader consumes.")
    d.define("maintenance.event.enable.idempotence", T.BOOLEAN, True, None,
             I.LOW, "Drop duplicate maintenance plans (IdempotenceCache).")
    d.define("maintenance.event.idempotence.retention.ms", T.LONG, 3_600_000,
             Range.at_least(1), I.LOW, "Idempotence-cache retention window.")
    d.define("maintenance.event.max.idempotence.cache.size", T.INT, 25,
             Range.at_least(1), I.LOW, "Idempotence-cache size bound.")
    d.define("maintenance.event.stop.ongoing.execution", T.BOOLEAN, False,
             None, I.LOW,
             "Maintenance plans may stop an in-flight execution.")
    d.define("broker.failure.detection.interval.ms", T.LONG, None, None,
             I.LOW, "Broker-failure detector interval "
             "(None = anomaly.detection.interval.ms).")
    d.define("disk.failure.detection.interval.ms", T.LONG, None, None, I.LOW,
             "Disk-failure detector interval (None = shared default).")
    d.define("topic.anomaly.detection.interval.ms", T.LONG, None, None, I.LOW,
             "Topic-anomaly detector interval (None = shared default).")
    d.define("kafka.broker.failure.detection.enable", T.BOOLEAN, True, None,
             I.LOW, "Metadata-polling broker failure detection (the ZK "
             "watcher variant is legacy and not implemented).")
    d.define("fixable.failed.broker.count.threshold", T.INT, 10,
             Range.at_least(0), I.LOW,
             "Self-healing declines when more brokers than this failed.")
    d.define("fixable.failed.broker.percentage.threshold", T.DOUBLE, 0.4,
             Range.between(0, 1), I.LOW,
             "Self-healing declines above this failed-broker fraction.")
    d.define("self.healing.goals", T.LIST, [], None, I.LOW,
             "Goal subset used when self-healing (empty = default goals).")
    d.define("self.healing.exclude.recently.demoted.brokers", T.BOOLEAN, True,
             None, I.LOW, "Self-healing skips recently demoted brokers for "
             "leadership.")
    d.define("self.healing.exclude.recently.removed.brokers", T.BOOLEAN, True,
             None, I.LOW, "Self-healing skips recently removed brokers for "
             "replica placement.")
    d.define("replication.factor.self.healing.skip.rack.awareness.check",
             T.BOOLEAN, False, None, I.LOW,
             "Allow self-healing RF changes to place multiple replicas of a "
             "partition in one rack when racks < RF "
             "(AnomalyDetectorConfig.java:309).")
    d.define("num.cached.recent.anomaly.states", T.INT, 10, Range.at_least(1),
             I.LOW, "Recent anomalies kept per type in the detector state.")
    d.define("anomaly.detection.allow.capacity.estimation", T.BOOLEAN, True,
             None, I.LOW, "Detectors may estimate missing broker capacity.")
    d.define("metric.anomaly.class", T.CLASS, None, None, I.LOW,
             "Metric-anomaly implementation override.")
    d.define("goal.violations.class", T.CLASS, None, None, I.LOW,
             "Goal-violation anomaly implementation override.")
    d.define("broker.failures.class", T.CLASS, None, None, I.LOW,
             "Broker-failure anomaly implementation override.")
    d.define("disk.failures.class", T.CLASS, None, None, I.LOW,
             "Disk-failure anomaly implementation override.")
    d.define("maintenance.event.class", T.CLASS, None, None, I.LOW,
             "Maintenance-event anomaly implementation override.")
    d.define("topic.anomaly.finder.class", T.LIST, None, None, I.LOW,
             "Topic-anomaly finder chain.")
    d.define("broker.failure.alert.threshold.ms", T.LONG, 900_000, Range.at_least(0), I.MEDIUM,
             "Age at which a broker failure alerts.")
    d.define("broker.failure.self.healing.threshold.ms", T.LONG, 1_800_000, Range.at_least(0),
             I.MEDIUM, "Age at which a broker failure auto-fixes.")
    d.define("failed.brokers.file.path", T.STRING, "fileStore/failed_brokers.json", None, I.LOW,
             "Persistence for failure times across restarts.")
    d.define("metric.anomaly.finder.class", T.CLASS,
             "cruise_control_tpu.detector.metric_anomaly.PercentileMetricAnomalyFinder",
             None, I.LOW, "MetricAnomalyFinder implementation.")
    d.define("metric.anomaly.percentile.upper.threshold", T.DOUBLE, 95.0,
             Range.between(0, 100), I.LOW, "")
    d.define("metric.anomaly.percentile.lower.threshold", T.DOUBLE, 2.0,
             Range.between(0, 100), I.LOW, "")
    d.define("slow.broker.bytes.in.rate.detection.threshold", T.DOUBLE, 1024.0,
             Range.at_least(0), I.LOW, "Min traffic for slow-broker relevance (KB/s).")
    d.define("slow.broker.demotion.score", T.INT, 5, Range.at_least(0), I.LOW,
             "Scoring threshold for demotion of slow brokers.")
    d.define("slow.broker.decommission.score", T.INT, 50, Range.at_least(0), I.LOW,
             "Scoring threshold for removal of slow brokers.")
    d.define("self.healing.target.topic.replication.factor", T.INT, None, None,
             I.LOW, "Desired RF enforced by the topic-anomaly detector; unset "
             "disables RF anomaly detection (TopicReplicationFactorAnomalyFinder).")
    d.define("topic.anomaly.topic.pattern", T.STRING, ".*", None, I.LOW,
             "Regex scoping which topics the RF anomaly finder enforces.")
    d.define("provisioner.class", T.CLASS,
             "cruise_control_tpu.detector.provisioner.BasicProvisioner",
             None, I.LOW, "Provisioner implementation.")

    # --- Web server / API (WebServerConfig.java) ---
    d.define("webserver.http.port", T.INT, 9090, Range.between(0, 65535), I.HIGH,
             "REST port.")
    d.define("webserver.http.address", T.STRING, "127.0.0.1", None, I.HIGH, "Bind address.")
    d.define("webserver.api.urlprefix", T.STRING, "/kafkacruisecontrol/*", None, I.LOW,
             "URL prefix of the REST API.")
    d.define("webserver.session.maxExpiryPeriodMs", T.LONG, 60_000, Range.at_least(1), I.LOW,
             "Async task session retention.")
    d.define("two.step.verification.enabled", T.BOOLEAN, False, None, I.MEDIUM,
             "Purgatory review flow on/off.")
    d.define("webserver.security.enable", T.BOOLEAN, False, None, I.MEDIUM, "")
    d.define("webserver.security.provider", T.CLASS,
             "cruise_control_tpu.api.security.BasicSecurityProvider",
             None, I.LOW, "SecurityProvider implementation.")
    d.define("webserver.auth.credentials.file", T.STRING, None, None, I.LOW,
             "htpasswd-style credentials for basic auth.")
    d.define("max.active.user.tasks", T.INT, 25, Range.at_least(1), I.LOW,
             "UserTaskManager active task cap.")
    d.define("completed.user.task.retention.time.ms", T.LONG, 86_400_000, Range.at_least(1),
             I.LOW, "Completed task retention.")
    d.define("max.cached.completed.user.tasks", T.INT, 100, Range.at_least(1),
             I.LOW, "Completed task cache size (default retention class).")
    d.define("max.cached.completed.kafka.monitor.user.tasks", T.INT, 20,
             Range.at_least(1), I.LOW,
             "Per-endpoint-class retention: monitor-type tasks "
             "(UserTaskManager.java:69-138).")
    d.define("max.cached.completed.kafka.admin.user.tasks", T.INT, 30,
             Range.at_least(1), I.LOW,
             "Per-endpoint-class retention: admin-type tasks.")
    d.define("max.cached.completed.cruise.control.monitor.user.tasks", T.INT,
             20, Range.at_least(1), I.LOW,
             "Per-endpoint-class retention: Cruise-Control-monitor tasks "
             "(STATE, USER_TASKS, REVIEW_BOARD, PERMISSIONS).")
    d.define("max.cached.completed.cruise.control.admin.user.tasks", T.INT,
             30, Range.at_least(1), I.LOW,
             "Per-endpoint-class retention: Cruise-Control-admin tasks "
             "(ADMIN, REVIEW, PAUSE/RESUME_SAMPLING, STOP, RIGHTSIZE).")
    d.define("completed.kafka.monitor.user.task.retention.time.ms", T.LONG,
             None, None, I.LOW,
             "Retention override for Kafka-monitor tasks (None = the "
             "completed.user.task.retention.time.ms default).")
    d.define("completed.kafka.admin.user.task.retention.time.ms", T.LONG,
             None, None, I.LOW,
             "Retention override for Kafka-admin tasks.")
    d.define("completed.cruise.control.monitor.user.task.retention.time.ms",
             T.LONG, None, None, I.LOW,
             "Retention override for Cruise-Control-monitor tasks.")
    d.define("completed.cruise.control.admin.user.task.retention.time.ms",
             T.LONG, None, None, I.LOW,
             "Retention override for Cruise-Control-admin tasks.")
    d.define("request.reason.required", T.BOOLEAN, False, None, I.LOW,
             "Require a non-empty reason parameter on proposal-executing "
             "POST endpoints (ExecutorConfig.REQUEST_REASON_REQUIRED).")
    d.define("webserver.http.header.size", T.INT, 65_536, Range.at_least(1),
             I.LOW, "Reject requests whose combined header bytes exceed "
             "this (431).")
    d.define("webserver.ssl.sts.enabled", T.BOOLEAN, False, None, I.LOW,
             "Send Strict-Transport-Security on HTTPS responses.")
    d.define("webserver.ssl.sts.include.subdomains", T.BOOLEAN, True, None,
             I.LOW, "includeSubDomains on the STS header.")
    d.define("webserver.ssl.sts.max.age", T.LONG, 31_536_000,
             Range.at_least(0), I.LOW, "STS max-age seconds.")
    d.define("provisioner.enable", T.BOOLEAN, True, None, I.LOW,
             "Right-sizing provisioner on/off: when disabled, RIGHTSIZE "
             "requests are refused and provision recommendations are not "
             "acted on (AnomalyDetectorConfig.PROVISIONER_ENABLE).")
    d.define("partition.metric.sample.aggregator.completeness.cache.size",
             T.INT, 5, Range.at_least(1), I.LOW,
             "Aggregation/completeness result cache entries kept on the "
             "partition aggregator (MonitorConfig).")
    d.define("broker.metric.sample.aggregator.completeness.cache.size",
             T.INT, 5, Range.at_least(1), I.LOW,
             "Aggregation/completeness result cache entries kept on the "
             "broker aggregator.")
    d.define("linear.regression.model.min.num.cpu.util.buckets", T.INT, 5,
             Range.at_least(1), I.LOW,
             "CPU-utilization buckets that must hold enough samples before "
             "the linear CPU model trains.")
    d.define("linear.regression.model.required.samples.per.bucket", T.INT,
             100, Range.at_least(1), I.LOW,
             "Samples a bucket needs before it counts toward training "
             "completeness (MonitorConfig default 100).")
    d.define("replica.to.broker.set.mapping.policy.class", T.CLASS, None,
             None, I.LOW,
             "Pluggable broker→broker-set mapping (default: the "
             "brokerSets.json file resolver; BrokerSetResolutionHelper).")
    d.define("inter.broker.replica.movement.rate.alerting.threshold",
             T.DOUBLE, 0.1, Range.at_least(0), I.LOW,
             "Alert when an execution's average inter-broker data movement "
             "rate (MB/s) falls below this.")
    d.define("intra.broker.replica.movement.rate.alerting.threshold",
             T.DOUBLE, 0.2, Range.at_least(0), I.LOW,
             "Alert when an execution's average intra-broker data movement "
             "rate (MB/s) falls below this.")
    d.define("webserver.request.maxBlockTimeMs", T.LONG, 10_000,
             Range.at_least(0), I.LOW,
             "How long a request blocks inline before returning 202 + "
             "User-Task-ID (the async wait).")
    d.define("webserver.session.maxExpiryTimeMs", T.LONG, 60_000,
             Range.at_least(1), I.LOW,
             "Session retention (accepted for config parity; the stdlib "
             "server is sessionless — tasks bind via User-Task-ID).")
    d.define("webserver.session.path", T.STRING, "/", None, I.LOW,
             "Session cookie path (accepted for config parity; sessionless "
             "server).")
    d.define("webserver.accesslog.enabled", T.BOOLEAN, True, None, I.LOW,
             "Log one line per handled request.")
    d.define("webserver.ui.diskpath", T.STRING, None, None, I.LOW,
             "Static Web-UI directory (accepted for config parity; no UI "
             "bundle ships with this framework).")
    d.define("webserver.ui.urlprefix", T.STRING, "/*", None, I.LOW,
             "UI URL prefix (accepted for config parity).")
    d.define("webserver.http.cors.enabled", T.BOOLEAN, False, None, I.LOW,
             "CORS headers on/off.")
    d.define("webserver.http.cors.origin", T.STRING, "*", None, I.LOW,
             "Access-Control-Allow-Origin value.")
    d.define("webserver.http.cors.allowmethods", T.STRING, "OPTIONS,GET,POST",
             None, I.LOW, "Access-Control-Allow-Methods value.")
    d.define("webserver.http.cors.exposeheaders", T.STRING, "User-Task-ID",
             None, I.LOW, "Access-Control-Expose-Headers value.")
    d.define("webserver.ssl.enable", T.BOOLEAN, False, None, I.MEDIUM,
             "Serve HTTPS (stdlib ssl; keystore location is a PEM "
             "cert+key file here, not a JKS).")
    d.define("webserver.ssl.keystore.location", T.STRING, None, None, I.MEDIUM,
             "PEM file with certificate + private key.")
    d.define("webserver.ssl.keystore.password", T.PASSWORD, None, None, I.LOW,
             "Private-key password.")
    d.define("webserver.ssl.keystore.type", T.STRING, "PEM", None, I.LOW,
             "Keystore format (PEM only in this implementation).")
    d.define("webserver.ssl.key.password", T.PASSWORD, None, None, I.LOW,
             "Key password (alias of keystore.password for PEM).")
    d.define("webserver.ssl.protocol", T.STRING, "TLS", None, I.LOW,
             "SSL protocol (accepted for parity; the stdlib server always "
             "negotiates via PROTOCOL_TLS_SERVER).")
    d.define("webserver.ssl.include.ciphers", T.LIST, None, None, I.LOW,
             "Cipher allowlist (None = library default).")
    d.define("webserver.ssl.exclude.ciphers", T.LIST, None, None, I.LOW,
             "Cipher denylist (accepted for parity; use include.ciphers — "
             "the stdlib ssl API takes an allowlist).")
    d.define("webserver.ssl.include.protocols", T.LIST, None, None, I.LOW,
             "Protocol allowlist (accepted for parity; PROTOCOL_TLS_SERVER "
             "negotiates the strongest shared version).")
    d.define("webserver.ssl.exclude.protocols", T.LIST, None, None, I.LOW,
             "Protocol denylist (accepted for parity; see include.protocols).")
    d.define("two.step.purgatory.retention.time.ms", T.LONG, 1_209_600_000,
             Range.at_least(1), I.LOW,
             "How long un-reviewed requests stay parked (Purgatory.java).")
    d.define("two.step.purgatory.max.requests", T.INT, 25, Range.at_least(1),
             I.LOW, "Max parked requests.")
    d.define("vertx.enabled", T.BOOLEAN, False, None, I.LOW,
             "Reference dual-stack flag; this implementation has one HTTP "
             "stack, so the flag is accepted and ignored.")
    d.define("jwt.authentication.provider.url", T.STRING, None, None, I.LOW,
             "Login redirect URL for JWT auth (token issuer).")
    d.define("jwt.cookie.name", T.STRING, None, None, I.LOW,
             "Cookie carrying the JWT (falls back to Bearer header).")
    d.define("jwt.auth.certificate.location", T.STRING, None, None, I.LOW,
             "Public key for token verification (RS256 requires the "
             "cryptography package; HS256 secret file otherwise).")
    d.define("jwt.expected.audiences", T.LIST, None, None, I.LOW,
             "Accepted aud claims (None = any).")
    d.define("spnego.principal", T.STRING, None, None, I.LOW,
             "Kerberos service principal for SPNEGO.")
    d.define("spnego.keytab.file", T.STRING, None, None, I.LOW,
             "Keytab backing the service principal.")
    d.define("trusted.proxy.services", T.LIST, None, None, I.LOW,
             "Service principals allowed to proxy (doAs) requests.")
    d.define("trusted.proxy.services.ip.regex", T.STRING, None, None, I.LOW,
             "Source-address pattern a trusted proxy must match.")
    d.define("trusted.proxy.spnego.fallback.enabled", T.BOOLEAN, False, None,
             I.LOW, "Fall back to SPNEGO auth when the caller is not a "
             "trusted proxy.")

    # --- Per-endpoint plugin bindings (CruiseControlParametersConfig /
    # CruiseControlRequestConfig: every endpoint's parameter parser and
    # request handler are config-swappable classes; None = built-in) ---
    for ep in ("bootstrap", "train", "load", "partition.load", "proposals",
               "state", "kafka.cluster.state", "user.tasks", "review.board",
               "permissions", "add.broker", "remove.broker",
               "fix.offline.replicas", "rebalance", "stop.proposal",
               "pause.sampling", "resume.sampling", "demote.broker", "admin",
               "review", "topic.configuration", "rightsize", "remove.disks",
               "fleet", "trace", "solver", "profile", "compare.futures",
               "heals", "forecast", "journeys", "slo", "redteam"):
        d.define(f"{ep}.parameters.class", T.CLASS, None, None, I.LOW,
                 f"Parameter-parsing plugin for the {ep} endpoint "
                 "(callable(query) -> params dict).")
        d.define(f"{ep}.request.class", T.CLASS, None, None, I.LOW,
                 f"Request-handling plugin for the {ep} endpoint "
                 "(instance.handle(facade, params, principal) -> body).")

    # --- TPU / device placement (new; no reference equivalent) ---
    d.define("tpu.mesh.axis.candidates", T.STRING, "candidates", None, I.LOW,
             "Mesh axis name over which candidate scoring is sharded.")
    d.define("tpu.num.devices", T.INT, None, None, I.LOW,
             "Device count override (None = all visible devices).")
    d.define("tpu.solver.dtype", T.STRING, "float32", None, I.LOW,
             "Accumulation dtype for goal kernels.")
    return d


_DEFINITION = _definition()


class CruiseControlConfig(AbstractConfig):
    """Merged, sanity-checked configuration (KafkaCruiseControlConfig.java)."""

    def __init__(self, props: Mapping[str, Any] | None = None):
        super().__init__(_DEFINITION, props or {})
        self._sanity_check()

    def _sanity_check(self) -> None:
        # KafkaCruiseControlConfig.sanityCheckGoalNames: hard.goals ⊆ goals,
        # anomaly.detection.goals ⊆ goals.
        goal_list = self.get_list("goals")
        if not goal_list:
            # KafkaCruiseControlConfig.java:161-166 — empty goals fail fast.
            raise ConfigException("goals must not be empty")
        goals = set(goal_list)
        for key in ("hard.goals", "anomaly.detection.goals"):
            subset = set(self.get_list(key))
            if not subset.issubset(goals):
                raise ConfigException(
                    f"{key} must be a subset of goals; extras: {sorted(subset - goals)}")
        if self.get_int("num.concurrent.partition.movements.per.broker") > \
                self.get_int("max.num.cluster.partition.movements"):
            raise ConfigException(
                "per-broker concurrent movements exceed the cluster-wide cap")
