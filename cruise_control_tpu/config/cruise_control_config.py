"""The merged framework configuration.

Reference parity: config/KafkaCruiseControlConfig.java (merges
MonitorConfig / AnalyzerConfig / ExecutorConfig / AnomalyDetectorConfig /
WebServerConfig / UserTaskManagerConfig constants and performs cross-field
sanity checks such as hard-goals ⊆ goals). Defaults follow
config/cruisecontrol.properties.

The goal class names here are dotted paths into
``cruise_control_tpu.analyzer.goals`` — the TPU-native goal kernels.
"""

from __future__ import annotations

from typing import Any, Mapping

from .abstract_config import AbstractConfig
from .configdef import ConfigDef, ConfigException, ConfigType, Importance, Range

_G = "cruise_control_tpu.analyzer.goals"

# Default goal chain: mirrors config/cruisecontrol.properties goals= order.
DEFAULT_GOALS = [
    f"{_G}.RackAwareGoal",
    f"{_G}.ReplicaCapacityGoal",
    f"{_G}.DiskCapacityGoal",
    f"{_G}.NetworkInboundCapacityGoal",
    f"{_G}.NetworkOutboundCapacityGoal",
    f"{_G}.CpuCapacityGoal",
    f"{_G}.ReplicaDistributionGoal",
    f"{_G}.PotentialNwOutGoal",
    f"{_G}.DiskUsageDistributionGoal",
    f"{_G}.NetworkInboundUsageDistributionGoal",
    f"{_G}.NetworkOutboundUsageDistributionGoal",
    f"{_G}.CpuUsageDistributionGoal",
    f"{_G}.TopicReplicaDistributionGoal",
    f"{_G}.LeaderReplicaDistributionGoal",
    f"{_G}.LeaderBytesInDistributionGoal",
]

DEFAULT_HARD_GOALS = [
    f"{_G}.RackAwareGoal",
    f"{_G}.ReplicaCapacityGoal",
    f"{_G}.DiskCapacityGoal",
    f"{_G}.NetworkInboundCapacityGoal",
    f"{_G}.NetworkOutboundCapacityGoal",
    f"{_G}.CpuCapacityGoal",
]

DEFAULT_ANOMALY_DETECTION_GOALS = [
    f"{_G}.RackAwareGoal",
    f"{_G}.ReplicaCapacityGoal",
    f"{_G}.DiskCapacityGoal",
]


def _definition() -> ConfigDef:
    d = ConfigDef()
    T, I = ConfigType, Importance

    # --- Monitor (MonitorConfig.java; defaults cruisecontrol.properties) ---
    d.define("bootstrap.servers", T.LIST, [], None, I.HIGH,
             "Kafka bootstrap servers for the managed cluster.")
    d.define("metric.sampling.interval.ms", T.LONG, 120_000, Range.at_least(1), I.HIGH,
             "Interval of metric sampling (default 120s).")
    d.define("partition.metrics.window.ms", T.LONG, 300_000, Range.at_least(1), I.HIGH,
             "Partition metrics window size.")
    d.define("num.partition.metrics.windows", T.INT, 5, Range.at_least(1), I.HIGH,
             "Number of partition windows kept.")
    d.define("broker.metrics.window.ms", T.LONG, 300_000, Range.at_least(1), I.HIGH,
             "Broker metrics window size.")
    d.define("num.broker.metrics.windows", T.INT, 20, Range.at_least(1), I.HIGH,
             "Number of broker windows kept.")
    d.define("min.samples.per.partition.metrics.window", T.INT, 1, Range.at_least(1), I.MEDIUM,
             "Minimum samples for a partition window to be valid.")
    d.define("min.samples.per.broker.metrics.window", T.INT, 1, Range.at_least(1), I.MEDIUM,
             "Minimum samples for a broker window to be valid.")
    d.define("min.valid.partition.ratio", T.DOUBLE, 0.95, Range.between(0, 1), I.HIGH,
             "Minimum monitored-valid partition ratio for model building.")
    d.define("max.allowed.extrapolations.per.partition", T.INT, 8, Range.at_least(0), I.LOW,
             "Max extrapolated windows tolerated per partition entity.")
    d.define("max.allowed.extrapolations.per.broker", T.INT, 8, Range.at_least(0), I.LOW,
             "Max extrapolated windows tolerated per broker entity.")
    d.define("metric.sampler.class", T.CLASS,
             "cruise_control_tpu.monitor.sampling.synthetic_sampler.SyntheticMetricSampler",
             None, I.HIGH, "Pluggable MetricSampler implementation.")
    d.define("sample.store.class", T.CLASS,
             "cruise_control_tpu.monitor.sampling.sample_store.FileSampleStore",
             None, I.MEDIUM, "Pluggable SampleStore implementation.")
    d.define("sample.store.path", T.STRING, "fileStore/samples", None, I.LOW,
             "Directory for the file-backed sample store.")
    d.define("num.metric.fetchers", T.INT, 1, Range.at_least(1), I.LOW,
             "Parallel metric fetcher workers.")
    d.define("broker.capacity.config.resolver.class", T.CLASS,
             "cruise_control_tpu.monitor.capacity.FileCapacityResolver",
             None, I.HIGH, "Pluggable broker capacity resolver.")
    d.define("capacity.config.file", T.STRING, "config/capacity.json", None, I.HIGH,
             "Capacity JSON file (DISK MB, CPU %, NW KB/s; JBOD maps).")
    d.define("monitor.state.update.interval.ms", T.LONG, 30_000, Range.at_least(1), I.LOW,
             "Monitor state refresh cadence.")

    # --- Analyzer (AnalyzerConfig.java) ---
    d.define("goals", T.LIST, list(DEFAULT_GOALS), None, I.HIGH,
             "Default goal chain, priority order.")
    d.define("hard.goals", T.LIST, list(DEFAULT_HARD_GOALS), None, I.HIGH,
             "Goals that must always be satisfied.")
    d.define("default.goals", T.LIST, [], None, I.MEDIUM,
             "Goals used for precomputed proposals (empty = goals).")
    d.define("anomaly.detection.goals", T.LIST, list(DEFAULT_ANOMALY_DETECTION_GOALS), None,
             I.MEDIUM, "Goals replayed by the goal-violation detector.")
    d.define("cpu.balance.threshold", T.DOUBLE, 1.1, Range.at_least(1), I.MEDIUM,
             "Balance band multiplier for CPU.")
    d.define("disk.balance.threshold", T.DOUBLE, 1.1, Range.at_least(1), I.MEDIUM,
             "Balance band multiplier for disk.")
    d.define("network.inbound.balance.threshold", T.DOUBLE, 1.1, Range.at_least(1), I.MEDIUM,
             "Balance band multiplier for NW in.")
    d.define("network.outbound.balance.threshold", T.DOUBLE, 1.1, Range.at_least(1), I.MEDIUM,
             "Balance band multiplier for NW out.")
    d.define("replica.count.balance.threshold", T.DOUBLE, 1.1, Range.at_least(1), I.MEDIUM,
             "Balance band multiplier for replica counts.")
    d.define("leader.replica.count.balance.threshold", T.DOUBLE, 1.1, Range.at_least(1), I.MEDIUM,
             "Balance band multiplier for leader replica counts.")
    d.define("topic.replica.count.balance.threshold", T.DOUBLE, 1.1, Range.at_least(1), I.MEDIUM,
             "Balance band multiplier for per-topic replica counts.")
    d.define("cpu.capacity.threshold", T.DOUBLE, 0.7, Range.between(0, 1), I.MEDIUM,
             "Usable fraction of CPU capacity.")
    d.define("disk.capacity.threshold", T.DOUBLE, 0.8, Range.between(0, 1), I.MEDIUM,
             "Usable fraction of disk capacity.")
    d.define("network.inbound.capacity.threshold", T.DOUBLE, 0.8, Range.between(0, 1), I.MEDIUM,
             "Usable fraction of NW-in capacity.")
    d.define("network.outbound.capacity.threshold", T.DOUBLE, 0.8, Range.between(0, 1), I.MEDIUM,
             "Usable fraction of NW-out capacity.")
    d.define("cpu.low.utilization.threshold", T.DOUBLE, 0.0, Range.between(0, 1), I.LOW,
             "Below this avg utilization the resource is considered low-utilized.")
    d.define("disk.low.utilization.threshold", T.DOUBLE, 0.0, Range.between(0, 1), I.LOW, "")
    d.define("network.inbound.low.utilization.threshold", T.DOUBLE, 0.0, Range.between(0, 1), I.LOW, "")
    d.define("network.outbound.low.utilization.threshold", T.DOUBLE, 0.0, Range.between(0, 1), I.LOW, "")
    d.define("max.replicas.per.broker", T.LONG, 10_000, Range.at_least(1), I.MEDIUM,
             "ReplicaCapacityGoal ceiling.")
    d.define("proposal.expiration.ms", T.LONG, 60_000, Range.at_least(0), I.MEDIUM,
             "Precomputed proposal freshness budget.")
    d.define("num.proposal.precompute.threads", T.INT, 1, Range.at_least(1), I.LOW,
             "Precompute workers (host-side; device search is batched).")
    d.define("max.solver.rounds", T.INT, 2000, Range.at_least(1), I.MEDIUM,
             "TPU solver: max accepted-move rounds per goal.")
    d.define("solver.candidates.per.round", T.INT, 4096, Range.at_least(16), I.MEDIUM,
             "TPU solver: candidate actions scored per round.")
    d.define("solver.moves.per.round", T.INT, 64, Range.at_least(1), I.MEDIUM,
             "TPU solver: max non-conflicting moves applied per round.")
    d.define("concurrency.adjuster.enabled", T.BOOLEAN, True, None, I.MEDIUM,
             "Re-tune execution concurrency caps each interval from broker "
             "health and (At/Under)MinISR state (Executor.java:465-683).")
    d.define("concurrency.adjuster.interval.ms", T.LONG, 1_000,
             Range.at_least(1), I.LOW,
             "ConcurrencyAdjuster evaluation interval.")
    d.define("solver.chain.fused", T.BOOLEAN, True, None, I.MEDIUM,
             "TPU solver: run the whole goal chain in one device dispatch "
             "(chain.chain_optimize_full) instead of one dispatch per goal "
             "phase.")
    d.define("goal.violation.distribution.threshold.multiplier", T.DOUBLE, 1.0,
             Range.at_least(1), I.LOW,
             "Detector-triggered balance-threshold relaxation.")
    d.define("goal.balancedness.priority.weight", T.DOUBLE, 1.1, Range.at_least(1), I.LOW,
             "Geometric weight per goal-priority level in balancedness score.")
    d.define("goal.balancedness.strictness.weight", T.DOUBLE, 1.5, Range.at_least(1), I.LOW,
             "Extra weight for hard goals in balancedness score.")
    d.define("fast.mode.per.broker.move.timeout.ms", T.LONG, 500, Range.at_least(1), I.LOW,
             "Fast-mode per-broker time budget.")

    # --- Executor (ExecutorConfig.java) ---
    d.define("num.concurrent.partition.movements.per.broker", T.INT, 10, Range.at_least(1),
             I.HIGH, "Per-broker inter-broker replica move cap.")
    d.define("max.num.cluster.partition.movements", T.INT, 1250, Range.at_least(1), I.HIGH,
             "Cluster-wide in-flight replica move cap.")
    d.define("num.concurrent.intra.broker.partition.movements", T.INT, 2, Range.at_least(1),
             I.MEDIUM, "Per-broker intra-broker (disk) move cap.")
    d.define("num.concurrent.leader.movements", T.INT, 1000, Range.at_least(1), I.HIGH,
             "Cluster-wide leadership movement cap.")
    d.define("max.num.cluster.movements", T.INT, 1250, Range.at_least(1), I.MEDIUM,
             "Upper bound of total in-flight movements.")
    d.define("execution.progress.check.interval.ms", T.LONG, 10_000, Range.at_least(1), I.HIGH,
             "Execution progress poll interval.")
    d.define("default.replication.throttle", T.LONG, None, None, I.MEDIUM,
             "Bytes/sec replication throttle during moves (None = no throttle).")
    d.define("replica.movement.strategies", T.LIST,
             ["cruise_control_tpu.executor.strategy.BaseReplicaMovementStrategy"],
             None, I.LOW, "Chain of replica movement orderings.")
    d.define("default.replica.movement.strategies", T.LIST,
             ["cruise_control_tpu.executor.strategy.BaseReplicaMovementStrategy"],
             None, I.LOW, "Default strategy chain.")
    d.define("executor.concurrency.adjuster.enabled", T.BOOLEAN, True, None, I.MEDIUM,
             "Adaptive concurrency adjuster on/off.")
    d.define("executor.concurrency.adjuster.interval.ms", T.LONG, 360_000, Range.at_least(1),
             I.LOW, "Concurrency adjuster cadence.")
    d.define("leader.movement.timeout.ms", T.LONG, 180_000, Range.at_least(1), I.LOW,
             "Leadership movement timeout before marking dead.")
    d.define("task.execution.alerting.threshold.ms", T.LONG, 90_000, Range.at_least(1), I.LOW,
             "Slow-task alert threshold.")
    d.define("admin.client.class", T.CLASS,
             "cruise_control_tpu.executor.admin.SimulatedAdminBackend",
             None, I.HIGH, "Cluster admin backend (simulated or Kafka).")

    # --- Anomaly detector (AnomalyDetectorConfig.java) ---
    d.define("anomaly.detection.interval.ms", T.LONG, 300_000, Range.at_least(1), I.HIGH,
             "Base detector cadence.")
    d.define("goal.violation.detection.interval.ms", T.LONG, None, None, I.LOW,
             "Override for goal-violation detector cadence.")
    d.define("metric.anomaly.detection.interval.ms", T.LONG, None, None, I.LOW, "")
    d.define("broker.failure.detection.backoff.ms", T.LONG, 300_000, Range.at_least(1), I.LOW, "")
    d.define("anomaly.notifier.class", T.CLASS,
             "cruise_control_tpu.detector.notifier.SelfHealingNotifier",
             None, I.HIGH, "AnomalyNotifier implementation.")
    d.define("self.healing.enabled", T.BOOLEAN, False, None, I.HIGH,
             "Global self-healing toggle.")
    d.define("self.healing.broker.failure.enabled", T.BOOLEAN, True, None, I.MEDIUM, "")
    d.define("self.healing.goal.violation.enabled", T.BOOLEAN, True, None, I.MEDIUM, "")
    d.define("self.healing.disk.failure.enabled", T.BOOLEAN, True, None, I.MEDIUM, "")
    d.define("self.healing.metric.anomaly.enabled", T.BOOLEAN, False, None, I.MEDIUM, "")
    d.define("self.healing.topic.anomaly.enabled", T.BOOLEAN, False, None, I.MEDIUM, "")
    d.define("self.healing.maintenance.event.enabled", T.BOOLEAN, False, None, I.MEDIUM, "")
    d.define("broker.failure.alert.threshold.ms", T.LONG, 900_000, Range.at_least(0), I.MEDIUM,
             "Age at which a broker failure alerts.")
    d.define("broker.failure.self.healing.threshold.ms", T.LONG, 1_800_000, Range.at_least(0),
             I.MEDIUM, "Age at which a broker failure auto-fixes.")
    d.define("failed.brokers.file.path", T.STRING, "fileStore/failed_brokers.json", None, I.LOW,
             "Persistence for failure times across restarts.")
    d.define("metric.anomaly.finder.class", T.CLASS,
             "cruise_control_tpu.detector.metric_anomaly.PercentileMetricAnomalyFinder",
             None, I.LOW, "MetricAnomalyFinder implementation.")
    d.define("metric.anomaly.percentile.upper.threshold", T.DOUBLE, 95.0,
             Range.between(0, 100), I.LOW, "")
    d.define("metric.anomaly.percentile.lower.threshold", T.DOUBLE, 2.0,
             Range.between(0, 100), I.LOW, "")
    d.define("slow.broker.bytes.in.rate.detection.threshold", T.DOUBLE, 1024.0,
             Range.at_least(0), I.LOW, "Min traffic for slow-broker relevance (KB/s).")
    d.define("slow.broker.demotion.score", T.INT, 5, Range.at_least(0), I.LOW,
             "Scoring threshold for demotion of slow brokers.")
    d.define("slow.broker.decommission.score", T.INT, 50, Range.at_least(0), I.LOW,
             "Scoring threshold for removal of slow brokers.")
    d.define("self.healing.target.topic.replication.factor", T.INT, None, None,
             I.LOW, "Desired RF enforced by the topic-anomaly detector; unset "
             "disables RF anomaly detection (TopicReplicationFactorAnomalyFinder).")
    d.define("topic.anomaly.topic.pattern", T.STRING, ".*", None, I.LOW,
             "Regex scoping which topics the RF anomaly finder enforces.")
    d.define("provisioner.class", T.CLASS,
             "cruise_control_tpu.detector.provisioner.BasicProvisioner",
             None, I.LOW, "Provisioner implementation.")

    # --- Web server / API (WebServerConfig.java) ---
    d.define("webserver.http.port", T.INT, 9090, Range.between(0, 65535), I.HIGH,
             "REST port.")
    d.define("webserver.http.address", T.STRING, "127.0.0.1", None, I.HIGH, "Bind address.")
    d.define("webserver.api.urlprefix", T.STRING, "/kafkacruisecontrol/*", None, I.LOW,
             "URL prefix of the REST API.")
    d.define("webserver.session.maxExpiryPeriodMs", T.LONG, 60_000, Range.at_least(1), I.LOW,
             "Async task session retention.")
    d.define("two.step.verification.enabled", T.BOOLEAN, False, None, I.MEDIUM,
             "Purgatory review flow on/off.")
    d.define("webserver.security.enable", T.BOOLEAN, False, None, I.MEDIUM, "")
    d.define("webserver.security.provider", T.CLASS,
             "cruise_control_tpu.api.security.BasicSecurityProvider",
             None, I.LOW, "SecurityProvider implementation.")
    d.define("webserver.auth.credentials.file", T.STRING, None, None, I.LOW,
             "htpasswd-style credentials for basic auth.")
    d.define("max.active.user.tasks", T.INT, 25, Range.at_least(1), I.LOW,
             "UserTaskManager active task cap.")
    d.define("completed.user.task.retention.time.ms", T.LONG, 86_400_000, Range.at_least(1),
             I.LOW, "Completed task retention.")

    # --- TPU / device placement (new; no reference equivalent) ---
    d.define("tpu.mesh.axis.candidates", T.STRING, "candidates", None, I.LOW,
             "Mesh axis name over which candidate scoring is sharded.")
    d.define("tpu.num.devices", T.INT, None, None, I.LOW,
             "Device count override (None = all visible devices).")
    d.define("tpu.solver.dtype", T.STRING, "float32", None, I.LOW,
             "Accumulation dtype for goal kernels.")
    return d


_DEFINITION = _definition()


class CruiseControlConfig(AbstractConfig):
    """Merged, sanity-checked configuration (KafkaCruiseControlConfig.java)."""

    def __init__(self, props: Mapping[str, Any] | None = None):
        super().__init__(_DEFINITION, props or {})
        self._sanity_check()

    def _sanity_check(self) -> None:
        # KafkaCruiseControlConfig.sanityCheckGoalNames: hard.goals ⊆ goals,
        # anomaly.detection.goals ⊆ goals.
        goal_list = self.get_list("goals")
        if not goal_list:
            # KafkaCruiseControlConfig.java:161-166 — empty goals fail fast.
            raise ConfigException("goals must not be empty")
        goals = set(goal_list)
        for key in ("hard.goals", "anomaly.detection.goals"):
            subset = set(self.get_list(key))
            if not subset.issubset(goals):
                raise ConfigException(
                    f"{key} must be a subset of goals; extras: {sorted(subset - goals)}")
        if self.get_int("num.concurrent.partition.movements.per.broker") > \
                self.get_int("max.num.cluster.partition.movements"):
            raise ConfigException(
                "per-broker concurrent movements exceed the cluster-wide cap")
