"""Kafka-style typed configuration definitions.

Reference parity: cruise-control-core/src/main/java/com/linkedin/
cruisecontrol/common/config/ConfigDef.java — typed keys with defaults,
validators, importance and documentation; parse() validates and coerces a
raw ``{name: value}`` map.

This is a fresh Python design (dataclasses, no reflection); plugin loading
uses dotted import paths instead of Java class reflection
(AbstractConfig.getConfiguredInstance).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Callable, Iterable, Mapping


class ConfigException(ValueError):
    """Invalid configuration key/value (ConfigException.java equivalent)."""


class ConfigType(enum.Enum):
    BOOLEAN = "boolean"
    STRING = "string"
    INT = "int"
    LONG = "long"
    DOUBLE = "double"
    LIST = "list"
    CLASS = "class"
    PASSWORD = "password"


class Importance(enum.Enum):
    HIGH = "high"
    MEDIUM = "medium"
    LOW = "low"


_NO_DEFAULT = object()


class Password:
    """Opaque wrapper that hides secrets from str()/repr()
    (core types/Password.java)."""

    def __init__(self, value: str):
        self.value = value

    def __repr__(self) -> str:  # pragma: no cover - trivial
        return "[hidden]"

    __str__ = __repr__

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Password) and other.value == self.value

    def __hash__(self) -> int:
        return hash(self.value)


@dataclasses.dataclass
class Range:
    """Numeric range validator (ConfigDef.Range)."""

    min: float | None = None
    max: float | None = None

    def __call__(self, name: str, value: Any) -> None:
        if value is None:
            return
        if self.min is not None and value < self.min:
            raise ConfigException(f"{name}: value {value} below minimum {self.min}")
        if self.max is not None and value > self.max:
            raise ConfigException(f"{name}: value {value} above maximum {self.max}")

    @classmethod
    def at_least(cls, lo: float) -> "Range":
        return cls(min=lo)

    @classmethod
    def between(cls, lo: float, hi: float) -> "Range":
        return cls(min=lo, max=hi)


@dataclasses.dataclass
class ValidString:
    """Enumerated-string validator (ConfigDef.ValidString)."""

    allowed: tuple[str, ...] = ()

    def __call__(self, name: str, value: Any) -> None:
        if value is not None and value not in self.allowed:
            raise ConfigException(
                f"{name}: value {value!r} not in allowed set {self.allowed}")


@dataclasses.dataclass
class ConfigKey:
    name: str
    type: ConfigType
    default: Any
    validator: Callable[[str, Any], None] | None
    importance: Importance
    doc: str

    @property
    def has_default(self) -> bool:
        return self.default is not _NO_DEFAULT


def _parse_bool(name: str, value: Any) -> bool:
    if isinstance(value, bool):
        return value
    if isinstance(value, str):
        low = value.strip().lower()
        if low == "true":
            return True
        if low == "false":
            return False
    raise ConfigException(f"{name}: expected boolean, got {value!r}")


def _parse_list(name: str, value: Any) -> list[str]:
    if value is None:
        return []
    if isinstance(value, str):
        return [v.strip() for v in value.split(",") if v.strip()]
    if isinstance(value, (list, tuple)):
        return [str(v) for v in value]
    raise ConfigException(f"{name}: expected list, got {value!r}")


class ConfigDef:
    """A registry of typed config keys; ``parse`` coerces + validates a raw
    mapping into a plain dict with defaults applied."""

    def __init__(self) -> None:
        self._keys: dict[str, ConfigKey] = {}

    def define(
        self,
        name: str,
        type: ConfigType,
        default: Any = _NO_DEFAULT,
        validator: Callable[[str, Any], None] | None = None,
        importance: Importance = Importance.MEDIUM,
        doc: str = "",
    ) -> "ConfigDef":
        if name in self._keys:
            raise ConfigException(f"duplicate config key {name!r}")
        self._keys[name] = ConfigKey(name, type, default, validator, importance, doc)
        return self

    def merge(self, other: "ConfigDef") -> "ConfigDef":
        for key in other._keys.values():
            if key.name not in self._keys:
                self._keys[key.name] = key
        return self

    @property
    def names(self) -> Iterable[str]:
        return self._keys.keys()

    def key(self, name: str) -> ConfigKey:
        return self._keys[name]

    def parse(self, props: Mapping[str, Any]) -> dict[str, Any]:
        out: dict[str, Any] = {}
        for name, key in self._keys.items():
            if name in props and props[name] is not None:
                value = self._coerce(key, props[name])
            elif key.has_default:
                value = self._coerce(key, key.default) if key.default is not None else None
            else:
                raise ConfigException(f"missing required config {name!r}")
            if key.validator is not None:
                key.validator(name, value)
            out[name] = value
        return out

    @staticmethod
    def _coerce(key: ConfigKey, value: Any) -> Any:
        if value is None:
            return None
        t = key.type
        name = key.name
        try:
            if t is ConfigType.BOOLEAN:
                return _parse_bool(name, value)
            if t in (ConfigType.INT, ConfigType.LONG):
                if isinstance(value, bool):
                    raise ConfigException(f"{name}: expected int, got bool")
                return int(value)
            if t is ConfigType.DOUBLE:
                if isinstance(value, bool):
                    raise ConfigException(f"{name}: expected double, got bool")
                return float(value)
            if t is ConfigType.LIST:
                return _parse_list(name, value)
            if t is ConfigType.STRING:
                return str(value)
            if t is ConfigType.CLASS:
                return value  # dotted path string or callable/class object
            if t is ConfigType.PASSWORD:
                return value if isinstance(value, Password) else Password(str(value))
        except ConfigException:
            raise
        except (TypeError, ValueError) as exc:
            raise ConfigException(f"{name}: cannot coerce {value!r} to {t.value}") from exc
        raise ConfigException(f"{name}: unknown type {t}")
