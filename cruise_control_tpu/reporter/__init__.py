"""Broker-side metrics reporter equivalent (cruise-control-metrics-reporter).

The reporter runs inside/alongside each managed broker, samples its metric
registry every interval, and produces serialized CruiseControlMetric
records to the metrics transport (the ``__CruiseControlMetrics`` topic in a
real deployment; an in-memory transport in tests/simulations).
"""

from .agent import BrokerMetricsRegistry, MetricsReporterAgent, MetricsRegistryView
from .container import cgroup_cpu_cores, container_cpu_util
from .metrics import (
    CruiseControlMetric, broker_metric, deserialize, partition_metric,
    serialize, topic_metric,
)

__all__ = ["CruiseControlMetric", "broker_metric", "deserialize",
           "partition_metric", "serialize", "topic_metric",
           "BrokerMetricsRegistry", "MetricsReporterAgent",
           "MetricsRegistryView", "cgroup_cpu_cores", "container_cpu_util"]
