"""Raw metric records emitted by the broker-side reporter agent.

Reference parity: cruise-control-metrics-reporter
metric/CruiseControlMetric.java + BrokerMetric/TopicMetric/PartitionMetric
records and MetricSerde.java (versioned binary serde over the
``__CruiseControlMetrics`` topic).

The serde here is a compact little-endian struct (type tag, version, raw
metric id, time, broker id, value, optional topic/partition) — not the
Java serde format (no cross-compat needed; both ends are ours).
"""

from __future__ import annotations

import dataclasses
import struct

from ..metricdef.raw_metric_type import MetricScope, RawMetricType, scope_of

SERDE_VERSION = 1
_HEADER = struct.Struct("<BBhqid")  # version, scope, raw id, time_ms, broker, value
_LEN = struct.Struct("<H")


@dataclasses.dataclass(frozen=True)
class CruiseControlMetric:
    raw_type: RawMetricType
    time_ms: int
    broker_id: int
    value: float
    topic: str | None = None      # TOPIC and PARTITION scope
    partition: int = -1           # PARTITION scope

    @property
    def scope(self) -> MetricScope:
        return scope_of(self.raw_type)


def broker_metric(raw: RawMetricType, time_ms: int, broker_id: int,
                  value: float) -> CruiseControlMetric:
    assert scope_of(raw) is MetricScope.BROKER, raw
    return CruiseControlMetric(raw, time_ms, broker_id, value)


def topic_metric(raw: RawMetricType, time_ms: int, broker_id: int,
                 topic: str, value: float) -> CruiseControlMetric:
    assert scope_of(raw) is MetricScope.TOPIC, raw
    return CruiseControlMetric(raw, time_ms, broker_id, value, topic=topic)


def partition_metric(raw: RawMetricType, time_ms: int, broker_id: int,
                     topic: str, partition: int, value: float) -> CruiseControlMetric:
    assert scope_of(raw) is MetricScope.PARTITION, raw
    return CruiseControlMetric(raw, time_ms, broker_id, value, topic=topic,
                               partition=partition)


def serialize(m: CruiseControlMetric) -> bytes:
    scope = {MetricScope.BROKER: 0, MetricScope.TOPIC: 1,
             MetricScope.PARTITION: 2}[m.scope]
    head = _HEADER.pack(SERDE_VERSION, scope, int(m.raw_type), m.time_ms,
                        m.broker_id, m.value)
    if m.scope is MetricScope.BROKER:
        return head
    tb = (m.topic or "").encode()
    body = _LEN.pack(len(tb)) + tb
    if m.scope is MetricScope.PARTITION:
        body += struct.pack("<i", m.partition)
    return head + body


def deserialize(buf: bytes) -> CruiseControlMetric:
    version, scope, raw_id, time_ms, broker, value = _HEADER.unpack_from(buf)
    if version != SERDE_VERSION:
        raise ValueError(f"unsupported metric serde version {version}")
    raw = RawMetricType(raw_id)
    if scope == 0:
        return CruiseControlMetric(raw, time_ms, broker, value)
    off = _HEADER.size
    (tlen,) = _LEN.unpack_from(buf, off)
    off += _LEN.size
    topic = buf[off:off + tlen].decode()
    off += tlen
    if scope == 1:
        return CruiseControlMetric(raw, time_ms, broker, value, topic=topic)
    (part,) = struct.unpack_from("<i", buf, off)
    return CruiseControlMetric(raw, time_ms, broker, value, topic=topic,
                               partition=part)
