"""Raw metric records emitted by the broker-side reporter agent.

Reference parity: cruise-control-metrics-reporter
metric/CruiseControlMetric.java + BrokerMetric/TopicMetric/PartitionMetric
records and MetricSerde.java (versioned binary serde over the
``__CruiseControlMetrics`` topic).

The serde here is a compact little-endian struct (type tag, version, raw
metric id, time, broker id, value, optional topic/partition) — not the
Java serde format (no cross-compat needed; both ends are ours).
"""

from __future__ import annotations

import dataclasses
import struct

from ..metricdef.raw_metric_type import MetricScope, RawMetricType, scope_of

SERDE_VERSION = 1
_HEADER = struct.Struct("<BBhqid")  # version, scope, raw id, time_ms, broker, value
_LEN = struct.Struct("<H")


@dataclasses.dataclass(frozen=True)
class CruiseControlMetric:
    raw_type: RawMetricType
    time_ms: int
    broker_id: int
    value: float
    topic: str | None = None      # TOPIC and PARTITION scope
    partition: int = -1           # PARTITION scope

    @property
    def scope(self) -> MetricScope:
        return scope_of(self.raw_type)


def broker_metric(raw: RawMetricType, time_ms: int, broker_id: int,
                  value: float) -> CruiseControlMetric:
    assert scope_of(raw) is MetricScope.BROKER, raw
    return CruiseControlMetric(raw, time_ms, broker_id, value)


def topic_metric(raw: RawMetricType, time_ms: int, broker_id: int,
                 topic: str, value: float) -> CruiseControlMetric:
    assert scope_of(raw) is MetricScope.TOPIC, raw
    return CruiseControlMetric(raw, time_ms, broker_id, value, topic=topic)


def partition_metric(raw: RawMetricType, time_ms: int, broker_id: int,
                     topic: str, partition: int, value: float) -> CruiseControlMetric:
    assert scope_of(raw) is MetricScope.PARTITION, raw
    return CruiseControlMetric(raw, time_ms, broker_id, value, topic=topic,
                               partition=partition)


def serialize(m: CruiseControlMetric) -> bytes:
    scope = {MetricScope.BROKER: 0, MetricScope.TOPIC: 1,
             MetricScope.PARTITION: 2}[m.scope]
    head = _HEADER.pack(SERDE_VERSION, scope, int(m.raw_type), m.time_ms,
                        m.broker_id, m.value)
    if m.scope is MetricScope.BROKER:
        return head
    tb = (m.topic or "").encode()
    body = _LEN.pack(len(tb)) + tb
    if m.scope is MetricScope.PARTITION:
        body += struct.pack("<i", m.partition)
    return head + body


@dataclasses.dataclass
class MetricColumns:
    """Columnar view of a metric record batch: one vectorized parse of the
    fixed-offset serde header per record, topics interned into a string
    table. The ingest path's answer to per-record Python objects — at 1M
    partitions a sampling interval carries millions of records, and
    ``deserialize`` per record is minutes of pure interpreter time."""

    scope: "np.ndarray"      # [N] uint8 (0=BROKER, 1=TOPIC, 2=PARTITION)
    raw_id: "np.ndarray"     # [N] int16
    time_ms: "np.ndarray"    # [N] int64
    broker: "np.ndarray"     # [N] int32
    value: "np.ndarray"      # [N] float64
    partition: "np.ndarray"  # [N] int32 (-1 for non-partition scope)
    topic_id: "np.ndarray"   # [N] int32 into .topics (-1 = none)
    topics: list[str]

    def __len__(self) -> int:
        return len(self.raw_id)

    def take(self, mask) -> "MetricColumns":
        return MetricColumns(
            scope=self.scope[mask], raw_id=self.raw_id[mask],
            time_ms=self.time_ms[mask], broker=self.broker[mask],
            value=self.value[mask], partition=self.partition[mask],
            topic_id=self.topic_id[mask], topics=self.topics)


def deserialize_columns(data: bytes, spans) -> MetricColumns:
    """Vectorized ``deserialize`` over value spans.

    ``spans``: int64 ndarray [N, 2] of (byte offset, byte length) into
    ``data`` — e.g. columns 4:6 of ``native.index_records``. Raises
    ValueError on any malformed record (same failure class as the scalar
    path)."""
    import numpy as np

    spans = np.asarray(spans, dtype=np.int64).reshape(-1, 2)
    n = spans.shape[0]
    u1 = np.frombuffer(data, dtype=np.uint8)
    off, length = spans[:, 0], spans[:, 1]
    if n and (length < _HEADER.size).any():
        raise ValueError("metric record shorter than the serde header")
    if n and (off < 0).any() or n and (off + length > len(u1)).any():
        raise ValueError("metric value span out of bounds")
    hdr = u1[off[:, None] + np.arange(_HEADER.size)[None, :]] if n else \
        np.zeros((0, _HEADER.size), np.uint8)
    version = hdr[:, 0]
    if n and (version != SERDE_VERSION).any():
        bad = int(version[version != SERDE_VERSION][0])
        raise ValueError(f"unsupported metric serde version {bad}")
    scope = hdr[:, 1]
    raw_id = np.ascontiguousarray(hdr[:, 2:4]).view("<i2")[:, 0]
    time_ms = np.ascontiguousarray(hdr[:, 4:12]).view("<i8")[:, 0]
    broker = np.ascontiguousarray(hdr[:, 12:16]).view("<i4")[:, 0]
    value = np.ascontiguousarray(hdr[:, 16:24]).view("<f8")[:, 0]

    topic_id = np.full(n, -1, dtype=np.int32)
    partition = np.full(n, -1, dtype=np.int32)
    topics: list[str] = []
    scoped = np.nonzero(scope > 0)[0]
    if scoped.size:
        t_off = off[scoped] + _HEADER.size
        if (t_off + 2 > off[scoped] + length[scoped]).any():
            raise ValueError("truncated topic length")
        tlen = (u1[t_off].astype(np.int64)
                | (u1[t_off + 1].astype(np.int64) << 8))
        end_ok = t_off + 2 + tlen + np.where(scope[scoped] == 2, 4, 0) \
            <= off[scoped] + length[scoped]
        if not end_ok.all():
            raise ValueError("truncated topic/partition field")
        # Topic interning: the per-row dict probe is the one remaining
        # Python loop; topics repeat heavily so it is dominated by bytes
        # hashing, not object construction.
        intern: dict[bytes, int] = {}
        ids = []
        to_l = (t_off + 2).tolist()
        end_l = (t_off + 2 + tlen).tolist()
        for start, end in zip(to_l, end_l):
            raw = data[start:end]
            tid = intern.get(raw)
            if tid is None:
                tid = intern.setdefault(raw, len(intern))
            ids.append(tid)
        topic_id[scoped] = np.asarray(ids, dtype=np.int32)
        topics = [b.decode() for b in intern]
        parts_rows = scoped[scope[scoped] == 2]
        if parts_rows.size:
            p_off = off[parts_rows] + _HEADER.size + 2 \
                + tlen[scope[scoped] == 2]
            pbytes = u1[p_off[:, None] + np.arange(4)[None, :]]
            partition[parts_rows] = np.ascontiguousarray(
                pbytes).view("<i4")[:, 0]
    return MetricColumns(scope=scope, raw_id=raw_id, time_ms=time_ms,
                         broker=broker, value=value, partition=partition,
                         topic_id=topic_id, topics=topics)


def deserialize(buf: bytes) -> CruiseControlMetric:
    version, scope, raw_id, time_ms, broker, value = _HEADER.unpack_from(buf)
    if version != SERDE_VERSION:
        raise ValueError(f"unsupported metric serde version {version}")
    raw = RawMetricType(raw_id)
    if scope == 0:
        return CruiseControlMetric(raw, time_ms, broker, value)
    off = _HEADER.size
    (tlen,) = _LEN.unpack_from(buf, off)
    off += _LEN.size
    topic = buf[off:off + tlen].decode()
    off += tlen
    if scope == 1:
        return CruiseControlMetric(raw, time_ms, broker, value, topic=topic)
    (part,) = struct.unpack_from("<i", buf, off)
    return CruiseControlMetric(raw, time_ms, broker, value, topic=topic,
                               partition=part)
