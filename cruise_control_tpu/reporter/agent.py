"""The broker-side metrics reporter agent.

Reference parity: cruise-control-metrics-reporter
CruiseControlMetricsReporter.java:62-93 (plugin registered inside the
broker, periodic sampling loop), :241-270 (reporting interval, producer
send), topic auto-creation (maybeCreateCruiseControlMetricsTopic) and
YammerMetricProcessor (registry → raw metric records). Container CPU
awareness via ``container.py``.

Redesign: the broker's metrics registry is abstracted behind a small view
(``snapshot(time_ms) -> [CruiseControlMetric]``) so the agent is testable
and embeddable (a real deployment wires a psutil/JMX-bridge view; tests
and the demo wire ``BrokerMetricsRegistry`` which the embedding process
updates directly). Transport is the same ``MetricsTransport`` protocol the
sampler consumes — in-memory for tests, Kafka via
``cruise_control_tpu.kafka.KafkaMetricsTransport`` in production.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Protocol

from ..metricdef.raw_metric_type import RawMetricType as R
from .container import container_cpu_util
from .metrics import (
    CruiseControlMetric, broker_metric, partition_metric, serialize,
    topic_metric,
)

LOG = logging.getLogger(__name__)


class MetricsRegistryView(Protocol):
    """What the agent samples each interval (YammerMetricProcessor's role)."""

    def snapshot(self, time_ms: int) -> list[CruiseControlMetric]: ...


class BrokerMetricsRegistry:
    """A concrete registry the embedding broker process keeps updated:
    per-topic byte rates, partition sizes, and host CPU utilization. Its
    ``snapshot`` emits the same record families the reference's Yammer
    walk produces (BROKER_CPU_UTIL, ALL_TOPIC_*, TOPIC_*, PARTITION_SIZE)."""

    def __init__(self, broker_id: int):
        self.broker_id = broker_id
        self._lock = threading.Lock()
        self._cpu_util = 0.0
        self._topic_rates: dict[str, tuple[float, float]] = {}
        self._replication_in = 0.0
        self._partition_sizes: dict[tuple[str, int], float] = {}

    def set_cpu_util(self, util: float) -> None:
        with self._lock:
            self._cpu_util = util

    def set_topic_rate(self, topic: str, bytes_in: float, bytes_out: float) -> None:
        with self._lock:
            self._topic_rates[topic] = (bytes_in, bytes_out)

    def set_replication_bytes_in(self, rate: float) -> None:
        with self._lock:
            self._replication_in = rate

    def set_partition_size(self, topic: str, partition: int, size: float) -> None:
        with self._lock:
            self._partition_sizes[(topic, partition)] = size

    def snapshot(self, time_ms: int) -> list[CruiseControlMetric]:
        with self._lock:
            bid = self.broker_id
            out = [broker_metric(R.BROKER_CPU_UTIL, time_ms, bid, self._cpu_util)]
            total_in = sum(r[0] for r in self._topic_rates.values())
            total_out = sum(r[1] for r in self._topic_rates.values())
            out.append(broker_metric(R.ALL_TOPIC_BYTES_IN, time_ms, bid, total_in))
            out.append(broker_metric(R.ALL_TOPIC_BYTES_OUT, time_ms, bid, total_out))
            out.append(broker_metric(R.ALL_TOPIC_REPLICATION_BYTES_IN, time_ms,
                                     bid, self._replication_in))
            for topic, (bin_, bout) in sorted(self._topic_rates.items()):
                out.append(topic_metric(R.TOPIC_BYTES_IN, time_ms, bid, topic, bin_))
                out.append(topic_metric(R.TOPIC_BYTES_OUT, time_ms, bid, topic, bout))
            for (topic, part), size in sorted(self._partition_sizes.items()):
                out.append(partition_metric(R.PARTITION_SIZE, time_ms, bid,
                                            topic, part, size))
            return out


class SystemMetricsRegistry:
    """A REAL registry bridge for deployments where the agent runs beside
    the broker process (the psutil view round 2 left to the deployer):

    - BROKER_CPU_UTIL from host CPU (cgroup-adjusted via container.py),
    - ALL_TOPIC_BYTES_IN/OUT from NIC counter deltas between snapshots
      (the broker-level traffic view; per-topic split needs broker
      internals the reference gets from Yammer — deployments wanting it
      layer BrokerMetricsRegistry on top),
    - PARTITION_SIZE by scanning the broker's log dirs
      (``<logdir>/<topic>-<partition>/``), the same numbers
      DescribeLogDirs reports.
    """

    def __init__(self, broker_id: int, log_dirs: list[str] | None = None,
                 nic: str | None = None):
        import psutil
        self._psutil = psutil
        self.broker_id = broker_id
        self._log_dirs = list(log_dirs or [])
        self._nic = nic
        self._last_net: tuple[int, float] | None = None  # (bytes, ts)
        self._last_net_out: int = 0
        psutil.cpu_percent(interval=None)  # prime the sampler

    def _net_counters(self):
        counters = self._psutil.net_io_counters(pernic=self._nic is not None)
        if self._nic is not None:
            counters = counters.get(self._nic)
        return counters

    def _partition_dirs(self):
        import os
        for root in self._log_dirs:
            if not os.path.isdir(root):
                continue
            for name in os.listdir(root):
                topic, sep, part = name.rpartition("-")
                if not sep or not part.isdigit():
                    continue
                path = os.path.join(root, name)
                if os.path.isdir(path):
                    yield topic, int(part), path

    @staticmethod
    def _dir_size(path) -> float:
        import os
        total = 0
        for entry in os.scandir(path):
            if entry.is_file(follow_symlinks=False):
                total += entry.stat().st_size
        return float(total)

    def snapshot(self, time_ms: int) -> list[CruiseControlMetric]:
        bid = self.broker_id
        cpu = self._psutil.cpu_percent(interval=None) / 100.0
        out = [broker_metric(R.BROKER_CPU_UTIL, time_ms, bid, cpu)]
        counters = self._net_counters()
        now = time_ms / 1000.0
        if counters is not None:
            if self._last_net is not None:
                last_in, last_ts = self._last_net
                dt = max(now - last_ts, 1e-3)
                out.append(broker_metric(
                    R.ALL_TOPIC_BYTES_IN, time_ms, bid,
                    max(counters.bytes_recv - last_in, 0) / dt))
                out.append(broker_metric(
                    R.ALL_TOPIC_BYTES_OUT, time_ms, bid,
                    max(counters.bytes_sent - self._last_net_out, 0) / dt))
            self._last_net = (counters.bytes_recv, now)
            self._last_net_out = counters.bytes_sent
        for topic, part, path in self._partition_dirs():
            try:
                out.append(partition_metric(R.PARTITION_SIZE, time_ms, bid,
                                            topic, part,
                                            self._dir_size(path)))
            except OSError:
                continue  # partition directory vanished mid-scan
        return out


class MetricsReporterAgent:
    """The in-broker sampling loop: every ``interval_s`` snapshot the
    registry, adjust CPU for container limits, serialize, produce."""

    def __init__(self, registry: MetricsRegistryView, transport,
                 interval_s: float = 120.0,
                 adjust_cpu_for_container: bool = True,
                 cgroup_root: str | None = None,
                 time_fn=time.time):
        self._registry = registry
        self._transport = transport
        self._interval = interval_s
        self._adjust_cpu = adjust_cpu_for_container
        self._cgroup_root = cgroup_root
        self._time = time_fn
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.reports = 0

    def start(self) -> None:
        """Create the metrics topic if the transport supports it
        (maybeCreateCruiseControlMetricsTopic), then start the loop."""
        ensure = getattr(self._transport, "ensure_topic", None)
        if ensure is not None:
            try:
                ensure()
            except Exception:  # noqa: BLE001 - topic may already exist / races
                LOG.warning("metrics topic auto-creation failed", exc_info=True)
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="cc-metrics-reporter")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.report_once()
            except Exception:  # noqa: BLE001 - keep the loop alive
                LOG.warning("metric report failed", exc_info=True)

    def report_once(self, time_ms: int | None = None) -> int:
        """One sampling pass (public: tests and deterministic harnesses
        drive intervals explicitly). Returns records produced."""
        now_ms = int(self._time() * 1000) if time_ms is None else time_ms
        records = self._registry.snapshot(now_ms)
        n = 0
        for m in records:
            if self._adjust_cpu and m.raw_type is R.BROKER_CPU_UTIL:
                kwargs = {} if self._cgroup_root is None \
                    else {"root": self._cgroup_root}
                m = broker_metric(R.BROKER_CPU_UTIL, m.time_ms, m.broker_id,
                                  container_cpu_util(m.value, **kwargs))
            self._transport.produce(serialize(m))
            n += 1
        flush = getattr(self._transport, "flush", None)
        if flush is not None:
            flush()
        self.reports += 1
        return n

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
