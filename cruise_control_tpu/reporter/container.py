"""Container awareness: cgroup CPU limits for in-container brokers.

Reference parity: cruise-control-metrics-reporter ContainerMetricUtils
(adjusts the reported CPU utilization for cgroup CPU quotas so a broker
limited to 2 of 64 host cores reports util relative to ITS allotment, not
the host's). Supports cgroup v2 (``cpu.max``) and v1
(``cpu/cpu.cfs_quota_us`` / ``cpu.cfs_period_us``); the filesystem root is
injectable for tests.
"""

from __future__ import annotations

import os

CGROUP_ROOT = "/sys/fs/cgroup"


def _read(path: str) -> str | None:
    try:
        with open(path) as f:
            return f.read().strip()
    except OSError:
        return None


def cgroup_cpu_cores(root: str = CGROUP_ROOT,
                     host_cores: int | None = None) -> float:
    """Effective CPU cores available to this process: the cgroup quota when
    one is set, else the host core count."""
    host = float(host_cores if host_cores is not None else os.cpu_count() or 1)

    # cgroup v2: "cpu.max" = "<quota|max> <period>"
    v2 = _read(os.path.join(root, "cpu.max"))
    if v2:
        parts = v2.split()
        if len(parts) == 2 and parts[0] != "max":
            try:
                quota, period = float(parts[0]), float(parts[1])
                if quota > 0 and period > 0:
                    return min(host, quota / period)
            except ValueError:
                pass
        return host

    # cgroup v1
    quota_s = _read(os.path.join(root, "cpu", "cpu.cfs_quota_us"))
    period_s = _read(os.path.join(root, "cpu", "cpu.cfs_period_us"))
    if quota_s and period_s:
        try:
            quota, period = float(quota_s), float(period_s)
            if quota > 0 and period > 0:
                return min(host, quota / period)
        except ValueError:
            pass
    return host


def container_cpu_util(host_cpu_util: float, root: str = CGROUP_ROOT,
                       host_cores: int | None = None) -> float:
    """Rescale a host-wide CPU utilization fraction to the container's CPU
    allotment (ContainerMetricUtils.getContainerProcessCpuLoad): with a
    quota of 2 cores on a 64-core host, 3% host util is ~96% of the
    container's budget."""
    host = float(host_cores if host_cores is not None else os.cpu_count() or 1)
    cores = cgroup_cpu_cores(root, host_cores=int(host))
    if cores <= 0:
        return host_cpu_util
    return min(1.0, host_cpu_util * host / cores)
