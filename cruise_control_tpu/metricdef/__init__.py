from .metricdef import MetricDef, MetricInfo, ValueComputingStrategy
from .kafka_metric_def import KafkaMetricDef, CommonMetric, BrokerMetric
from .raw_metric_type import RawMetricType, MetricScope
