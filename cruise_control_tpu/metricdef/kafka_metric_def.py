"""Model-level metric definitions (the bridge raw→model).

Reference parity: monitor/metricdefinition/KafkaMetricDef.java:43-134 —
~50 model metrics with COMMON (partition+broker) vs BROKER_ONLY scope and a
per-metric window-reduction strategy; the four resource metrics map onto the
``Resource`` axis used by the solver.
"""

from __future__ import annotations

import enum

from ..common.resources import Resource
from .metricdef import MetricDef, ValueComputingStrategy as S

COMMON = "common"
BROKER_ONLY = "broker_only"


class CommonMetric(enum.Enum):
    """(ordinal, strategy, resource) per metric; COMMON scope = defined for
    both partition and broker entities (KafkaMetricDef.java:43-53). The
    ordinal keeps enum values unique (otherwise members alias)."""

    CPU_USAGE = (0, S.AVG, Resource.CPU)
    DISK_USAGE = (1, S.LATEST, Resource.DISK)
    LEADER_BYTES_IN = (2, S.AVG, Resource.NW_IN)
    LEADER_BYTES_OUT = (3, S.AVG, Resource.NW_OUT)
    PRODUCE_RATE = (4, S.AVG, None)
    FETCH_RATE = (5, S.AVG, None)
    MESSAGE_IN_RATE = (6, S.AVG, None)
    REPLICATION_BYTES_IN_RATE = (7, S.AVG, Resource.NW_IN)
    REPLICATION_BYTES_OUT_RATE = (8, S.AVG, Resource.NW_OUT)

    @property
    def strategy(self) -> S:
        return self.value[1]

    @property
    def resource(self) -> "Resource | None":
        return self.value[2]


# BROKER_ONLY latency metrics (KafkaMetricDef.java:55-101); all AVG.
# Ordinal parity with the reference: MAX/MEAN block first (phase-outer,
# op-middle), then log-flush, then the 50TH/999TH block.
_BROKER_ONLY_NAMES: list[str] = [
    "BROKER_PRODUCE_REQUEST_RATE",
    "BROKER_CONSUMER_FETCH_REQUEST_RATE",
    "BROKER_FOLLOWER_FETCH_REQUEST_RATE",
    "BROKER_REQUEST_HANDLER_POOL_IDLE_PERCENT",
    "BROKER_REQUEST_QUEUE_SIZE",
    "BROKER_RESPONSE_QUEUE_SIZE",
]
for _phase in ("REQUEST_QUEUE", "TOTAL", "LOCAL"):
    for _op in ("PRODUCE", "CONSUMER_FETCH", "FOLLOWER_FETCH"):
        for _stat in ("MAX", "MEAN"):
            _BROKER_ONLY_NAMES.append(f"BROKER_{_op}_{_phase}_TIME_MS_{_stat}")
_BROKER_ONLY_NAMES += [
    "BROKER_LOG_FLUSH_RATE",
    "BROKER_LOG_FLUSH_TIME_MS_MAX",
    "BROKER_LOG_FLUSH_TIME_MS_MEAN",
]
for _phase in ("REQUEST_QUEUE", "TOTAL", "LOCAL"):
    for _op in ("PRODUCE", "CONSUMER_FETCH", "FOLLOWER_FETCH"):
        for _stat in ("50TH", "999TH"):
            _BROKER_ONLY_NAMES.append(f"BROKER_{_op}_{_phase}_TIME_MS_{_stat}")
_BROKER_ONLY_NAMES += [
    "BROKER_LOG_FLUSH_TIME_MS_50TH",
    "BROKER_LOG_FLUSH_TIME_MS_999TH",
]

BrokerMetric = enum.Enum("BrokerMetric", [(n, n) for n in _BROKER_ONLY_NAMES])


class KafkaMetricDef:
    """Holds the two MetricDef registries (common/partition vs broker) and
    the resource → metric-id maps consumed by the model builder."""

    _common_def: MetricDef | None = None
    _broker_def: MetricDef | None = None

    @classmethod
    def common_metric_def(cls) -> MetricDef:
        if cls._common_def is None:
            d = MetricDef()
            for m in CommonMetric:
                d.define(m.name, m.strategy, group=COMMON)
            cls._common_def = d
        return cls._common_def

    @classmethod
    def broker_metric_def(cls) -> MetricDef:
        """Broker entities carry COMMON + BROKER_ONLY metrics."""
        if cls._broker_def is None:
            d = MetricDef()
            for m in CommonMetric:
                d.define(m.name, m.strategy, group=COMMON)
            for name in _BROKER_ONLY_NAMES:
                d.define(name, S.AVG, group=BROKER_ONLY)
            cls._broker_def = d
        return cls._broker_def

    @classmethod
    def resource_to_metric_ids(cls, which: str = "common") -> dict[Resource, list[int]]:
        """Resource → metric ids whose values sum into that resource's load
        (KafkaMetricDef.resourceToMetricIds)."""
        d = cls.common_metric_def() if which == "common" else cls.broker_metric_def()
        out: dict[Resource, list[int]] = {r: [] for r in Resource}
        for m in CommonMetric:
            if m.resource is not None:
                out[m.resource].append(d.metric_info(m.name).id)
        return out

    @classmethod
    def common_metric_id(cls, m: CommonMetric) -> int:
        return cls.common_metric_def().metric_info(m.name).id
