"""Raw metric taxonomy reported by the broker-side agent.

Reference parity: cruise-control-metrics-reporter .../metric/RawMetricType.java
(63 raw metric ids at BROKER/TOPIC/PARTITION scope, versioned serde). The
names and scopes mirror the reference so samples are interoperable; ids are
assigned from enumeration order and double as rows of the ingest tensors.
"""

from __future__ import annotations

import enum


class MetricScope(enum.Enum):
    BROKER = "broker"
    TOPIC = "topic"
    PARTITION = "partition"


_BROKER = MetricScope.BROKER
_TOPIC = MetricScope.TOPIC
_PARTITION = MetricScope.PARTITION

# name -> scope, in reference id order (RawMetricType.java:27-95).
_RAW_METRICS: list[tuple[str, MetricScope]] = [
    ("ALL_TOPIC_BYTES_IN", _BROKER),
    ("ALL_TOPIC_BYTES_OUT", _BROKER),
    ("TOPIC_BYTES_IN", _TOPIC),
    ("TOPIC_BYTES_OUT", _TOPIC),
    ("PARTITION_SIZE", _PARTITION),
    ("BROKER_CPU_UTIL", _BROKER),
    ("ALL_TOPIC_REPLICATION_BYTES_IN", _BROKER),
    ("ALL_TOPIC_REPLICATION_BYTES_OUT", _BROKER),
    ("ALL_TOPIC_PRODUCE_REQUEST_RATE", _BROKER),
    ("ALL_TOPIC_FETCH_REQUEST_RATE", _BROKER),
    ("ALL_TOPIC_MESSAGES_IN_PER_SEC", _BROKER),
    ("TOPIC_REPLICATION_BYTES_IN", _TOPIC),
    ("TOPIC_REPLICATION_BYTES_OUT", _TOPIC),
    ("TOPIC_PRODUCE_REQUEST_RATE", _TOPIC),
    ("TOPIC_FETCH_REQUEST_RATE", _TOPIC),
    ("TOPIC_MESSAGES_IN_PER_SEC", _TOPIC),
    ("BROKER_PRODUCE_REQUEST_RATE", _BROKER),
    ("BROKER_CONSUMER_FETCH_REQUEST_RATE", _BROKER),
    ("BROKER_FOLLOWER_FETCH_REQUEST_RATE", _BROKER),
    ("BROKER_REQUEST_HANDLER_AVG_IDLE_PERCENT", _BROKER),
    ("BROKER_REQUEST_QUEUE_SIZE", _BROKER),
    ("BROKER_RESPONSE_QUEUE_SIZE", _BROKER),
]

# The 42 latency/percentile broker metrics (queue/total/local time for
# produce / consumer-fetch / follower-fetch plus log-flush), MAX & MEAN then
# 50TH & 999TH — generated phase-outer / op-middle to match the reference id
# order exactly (RawMetricType.java:55-95).
for _phase in ("REQUEST_QUEUE", "TOTAL", "LOCAL"):
    for _op in ("PRODUCE", "CONSUMER_FETCH", "FOLLOWER_FETCH"):
        for _stat in ("MAX", "MEAN"):
            _RAW_METRICS.append((f"BROKER_{_op}_{_phase}_TIME_MS_{_stat}", _BROKER))
_RAW_METRICS.append(("BROKER_LOG_FLUSH_RATE", _BROKER))
_RAW_METRICS.append(("BROKER_LOG_FLUSH_TIME_MS_MAX", _BROKER))
_RAW_METRICS.append(("BROKER_LOG_FLUSH_TIME_MS_MEAN", _BROKER))
for _phase in ("REQUEST_QUEUE", "TOTAL", "LOCAL"):
    for _op in ("PRODUCE", "CONSUMER_FETCH", "FOLLOWER_FETCH"):
        for _stat in ("50TH", "999TH"):
            _RAW_METRICS.append((f"BROKER_{_op}_{_phase}_TIME_MS_{_stat}", _BROKER))
_RAW_METRICS.append(("BROKER_LOG_FLUSH_TIME_MS_50TH", _BROKER))
_RAW_METRICS.append(("BROKER_LOG_FLUSH_TIME_MS_999TH", _BROKER))


RawMetricType = enum.IntEnum("RawMetricType", [(name, i) for i, (name, _) in enumerate(_RAW_METRICS)])

_SCOPES = {RawMetricType[name]: scope for name, scope in _RAW_METRICS}


def scope_of(raw: "RawMetricType") -> MetricScope:
    return _SCOPES[raw]


def metrics_for_scope(scope: MetricScope) -> list["RawMetricType"]:
    return [m for m in RawMetricType if _SCOPES[m] is scope]
