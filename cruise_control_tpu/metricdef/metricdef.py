"""Metric definition registry.

Reference parity: cruise-control-core .../metricdef/MetricDef.java,
MetricInfo.java, ValueComputingStrategy.java — maps metric name → integer id
and records how samples within a window are reduced (AVG / MAX / LATEST).

The integer ids are the row indices of the metric axis in the aggregator's
dense window tensors, so the registry doubles as the tensor schema.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Iterable


class ValueComputingStrategy(enum.Enum):
    AVG = "avg"
    MAX = "max"
    LATEST = "latest"


@dataclasses.dataclass(frozen=True)
class MetricInfo:
    name: str
    id: int
    strategy: ValueComputingStrategy
    group: str | None = None


class MetricDef:
    """Append-only metric registry; ids are assigned densely in definition
    order (MetricDef.java:define)."""

    def __init__(self) -> None:
        self._by_name: dict[str, MetricInfo] = {}
        self._by_id: list[MetricInfo] = []
        self._groups: dict[str, list[MetricInfo]] = {}

    def define(self, name: str, strategy: ValueComputingStrategy | str,
               group: str | None = None) -> MetricInfo:
        if name in self._by_name:
            raise ValueError(f"metric {name!r} already defined")
        if isinstance(strategy, str):
            strategy = ValueComputingStrategy(strategy.lower())
        info = MetricInfo(name=name, id=len(self._by_id), strategy=strategy, group=group)
        self._by_name[name] = info
        self._by_id.append(info)
        if group is not None:
            self._groups.setdefault(group, []).append(info)
        return info

    def metric_info(self, name: str) -> MetricInfo:
        return self._by_name[name]

    def has_metric(self, name: str) -> bool:
        return name in self._by_name

    def metric_info_for_id(self, metric_id: int) -> MetricInfo:
        return self._by_id[metric_id]

    @property
    def num_metrics(self) -> int:
        return len(self._by_id)

    def all(self) -> Iterable[MetricInfo]:
        return tuple(self._by_id)

    def ids_for_group(self, group: str) -> list[int]:
        return [m.id for m in self._groups.get(group, [])]

    def strategies_array(self):
        """Per-metric strategy codes as a list aligned with ids (consumed by
        the window-reduction kernel)."""
        return [m.strategy for m in self._by_id]
