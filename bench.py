"""Driver benchmark: full rebalance-proposal generation wall-clock.

Prints MULTIPLE JSON lines, one as each stage completes, smallest scale
first — the LAST line is the headline result (the largest completed stage).
Each line: {"metric", "value", "unit", "vs_baseline", "extras"}.

``value`` is the steady-state wall-clock (seconds) of a full
GoalOptimizer.optimizations() pass over the default 15-goal chain — model
resident on device, kernels compiled (the deployment steady state: the
reference keeps a warm JVM + proposal precompute pool for the same reason,
GoalOptimizer.java:112-119; its own hook for this number is the
proposal-computation-timer, GoalOptimizer.java:128).

``vs_baseline`` is the ratio of the scale-prorated north-star budget to the
measured value (>1 = faster than budget): BASELINE.md targets a full
proposal for 7,000 brokers / 1M partitions in <30 s on v5e-8, so
budget = 30 s × (partitions / 1M) × (8 chips / chips-used).

Failure modes are first-class (VERDICT round 1):
- The single-chip TPU tunnel ("axon") can block for MINUTES at claim time.
  A subprocess probes it under a hard timeout; on failure the bench falls
  back to the host-CPU platform and says so in extras.device.
- A wall-clock watchdog (BENCH_BUDGET_S, default 780 s — under the tier-1
  harness budget) alarms out of whatever is stuck; every completed stage
  has already been printed. Each stage additionally gets its OWN prorated
  deadline and emits a ``stage_partial_*`` record with the phases it
  finished on expiry, so one slow stage can never drive the whole run
  into an external rc=124 kill with a truncated tail (BENCH_r05).
- A bootstrap line is printed as soon as the device resolves, so even a
  timeout leaves a parseable tail.

Output hygiene (VERDICT round 4 — the round-4 artifact recorded NOTHING
because XLA:CPU ``cpu_aot_loader`` machine-feature-mismatch errors, one
per persisted kernel, flooded the captured tail and displaced every
metric line):
- fd 2 is redirected at the OS level to BENCH_STDERR_FILE (default
  /tmp/cc_bench_stderr.log) before jax loads, so native XLA/absl spam can
  never share the captured stream with the metric lines (set
  BENCH_KEEP_STDERR=1 to disable when debugging interactively).
- The persistent compile cache is partitioned per host fingerprint
  (``cruise_control_tpu.enable_persistent_compile_cache``), so AOT
  artifacts from a different machine are invisible instead of loudly
  rejected.
- Every emitted line is journaled in-process; after the run — including
  the hard-exit watchdog path — every completed stage line is RE-emitted
  followed by one ``bench_summary`` JSON line, so any tail window of
  stdout contains the full story.
"""

from __future__ import annotations

import json
import math
import os
import signal
import subprocess
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/cc_tpu_jax_cache")

if not os.environ.get("BENCH_KEEP_STDERR"):
    # OS-level redirect (not sys.stderr): XLA / absl / TSL log from C++
    # directly to fd 2, bypassing Python objects entirely.
    _stderr_path = os.environ.get("BENCH_STDERR_FILE",
                                  "/tmp/cc_bench_stderr.log")
    try:
        _stderr_fd = os.open(_stderr_path,
                             os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        os.dup2(_stderr_fd, 2)
        os.close(_stderr_fd)
        sys.stderr = os.fdopen(2, "w", buffering=1)
    except OSError:
        _stderr_path = "(redirect failed; stderr left on tty)"
else:
    _stderr_path = "(kept on tty: BENCH_KEEP_STDERR)"

# (num_brokers, num_partitions, drain) smallest-first; BASELINE.md configs
# #2/#3/#4 — drain N means N brokers are marked DEAD (RemoveBrokers path:
# every hosted replica becomes offline and must be re-placed under capacity
# + rack constraints).
STAGES = [(16, 512, 0), (50, 2_000, 0), (100, 10_000, 0), (1_000, 100_000, 0),
          (1_000, 100_000, 50), (7_000, 1_000_000, 0)]
PROBE_TIMEOUT_S = int(os.environ.get("BENCH_PROBE_TIMEOUT_S", "120"))
# Default budget sized to EXIT 0 UNDER the tier-1 harness budget (870 s):
# BENCH_r05 showed the opposite failure mode — a 3600 s internal budget
# let the external harness timeout kill the run at rc=124 with a
# truncated tail. Each stage now gets its own prorated deadline and emits
# a partial record on expiry, so a slow stage costs only itself; raise
# BENCH_BUDGET_S for a full-scale standalone run.
BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "780"))
# A stage that times out is recorded as a partial; anything larger is
# skipped (stages are ordered smallest-first, so a bigger stage cannot
# fit where a smaller one expired).

# --scenarios: run the digital-twin canonical scenario library
# (testing/simulator.py) instead of the perf stages — one JSON line per
# scenario with the ScenarioScore extras the CI SCENARIO_MATRIX table
# reads. Same watchdog discipline: each scenario gets a prorated
# deadline and emits a stage_partial_* record on expiry.
SCENARIO_MODE = "--scenarios" in sys.argv or bool(
    os.environ.get("BENCH_SCENARIOS"))
SCENARIO_SEED = int(os.environ.get("BENCH_SCENARIO_SEED", "0"))
# 0 = each scenario's full spec horizon.
SCENARIO_TICKS = int(os.environ.get("BENCH_SCENARIO_TICKS", "0"))

# --fleet: run ONLY the megabatch fleet stage (K same-bucket synthetic
# clusters solved serially vs through one batched device program —
# ROADMAP item 3's throughput lever). The stage also runs at the END of
# every default bench pass, so the CI MEGABATCH row and the regression
# sentry see it without a separate invocation.
FLEET_MODE = "--fleet" in sys.argv or bool(os.environ.get("BENCH_FLEET"))
FLEET_K = int(os.environ.get("BENCH_FLEET_CLUSTERS", "4"))

# --fleet-shard: run ONLY the device-sharded megabatch stage (round 23):
# hundreds of tiny same-bucket clusters pushed through the chain-solve
# layer, A/B-ing exactly what fleet.shard.enabled toggles — each
# W·N-wide bucket batch solved as ONE single-device megabatch program
# (global early exit: every round computes every row until the bucket's
# slowest cluster converges) vs sharded across the N-device mesh at
# FIXED per-device occupancy W (device-local exit: a device whose W
# clusters converged stops computing). The mesh comes from a fresh
# subprocess pinning --xla_force_host_platform_device_count=N (a
# process-level XLA init flag — the only way to grow a host-CPU mesh,
# so the stage cannot run in-process). vs_baseline is the clusters/s
# ratio against the 1.6x acceptance bar; per-cluster results are
# asserted BYTE-IDENTICAL between the arms (the parity pin — the CI
# FLEET_SHARD row hard-fails anything but "ok"). Like the other riders,
# the stage also runs at the END of every default bench pass.
# --fleet-shard-child is the subprocess entry, handled before any
# device probing.
FLEETSHARD_MODE = "--fleet-shard" in sys.argv or bool(
    os.environ.get("BENCH_FLEET_SHARD"))
FLEETSHARD_CHILD = "--fleet-shard-child" in sys.argv
FLEETSHARD_DEVICES = int(os.environ.get("BENCH_FLEET_SHARD_DEVICES", "4"))
FLEETSHARD_OCCUPANCY = int(
    os.environ.get("BENCH_FLEET_SHARD_OCCUPANCY", "16"))
FLEETSHARD_CLUSTERS = int(
    os.environ.get("BENCH_FLEET_SHARD_CLUSTERS", "256"))

# --futures: run ONLY the futures-engine stage (N sampled candidate
# futures advanced to their decision points, then solved serially vs
# through one batched megabatch program — ROADMAP item 5's throughput
# lever). Like --fleet, the stage also rides the END of every default
# bench pass so the CI FUTURES row and the regression sentry (which
# hard-fails a ranked-order flip) see it without a separate invocation.
FUTURES_MODE = "--futures" in sys.argv or bool(os.environ.get("BENCH_FUTURES"))
FUTURES_N = int(os.environ.get("BENCH_FUTURES_COUNT", "8"))

# --direct: run ONLY the direct-assignment stage (the round-17 transport
# kernels for the count-distribution goals, greedy deficit-sized vs
# direct+polish through the REAL optimizer at a wide-regime shape). Like
# --fleet/--futures, the stage also rides the END of every default bench
# pass so the CI DIRECT row sees steady per-count-goal wall, dispatch
# counts, and the balancedness/violated-goal canary (judged direct vs
# greedy in the same run) without a separate invocation.
DIRECT_MODE = "--direct" in sys.argv or bool(os.environ.get("BENCH_DIRECT"))
DIRECT_BROKERS = int(os.environ.get("BENCH_DIRECT_BROKERS", "200"))
DIRECT_PARTITIONS = int(os.environ.get("BENCH_DIRECT_PARTITIONS", "10000"))

# --transport: run ONLY the sparse-regime transport stage (round 21):
# the SAME greedy-vs-direct A/B as --direct but at the sparse-cell
# geometry the retired density gate used to wall off (100 topics at
# 200b/10k → 1.5 replicas per [topic, broker] cell, where the
# per-partition greedy rounds crawl and the old integral plan had no
# fractional mass to move). TopicReplicaDistribution is the headline:
# TR rounds/wall/residual ride the extras and the direct arm's TR wall
# must beat greedy (vs_baseline > 1). The balancedness/violated-goal
# canary is judged within the run exactly like --direct; the CI
# TRANSPORT row hard-fails on a canary flip or the stage missing. Like
# the other riders, the stage also runs at the END of every default
# bench pass.
TRANSPORT_MODE = "--transport" in sys.argv or bool(
    os.environ.get("BENCH_TRANSPORT"))
TRANSPORT_BROKERS = int(os.environ.get("BENCH_TRANSPORT_BROKERS", "200"))
TRANSPORT_PARTITIONS = int(
    os.environ.get("BENCH_TRANSPORT_PARTITIONS", "10000"))
TRANSPORT_TOPICS = int(os.environ.get("BENCH_TRANSPORT_TOPICS", "100"))

# --warmstart: run ONLY the always-hot stage (round 18): (1) restart-to-
# first-proposal measured in FRESH subprocesses — cold cache vs persistent
# cache + background prewarm — and (2) steady-state warm-seeded vs cold
# solves under the round-11 drift twin, with a balancedness/violated-set
# flip between the two arms as a hard in-run canary (the WARMSTART CI
# row). Like the other riders, the stage also runs at the END of every
# default bench pass.
WARMSTART_MODE = "--warmstart" in sys.argv or bool(
    os.environ.get("BENCH_WARMSTART"))
WARMSTART_BROKERS = int(os.environ.get("BENCH_WARMSTART_BROKERS", "16"))
WARMSTART_PARTITIONS = int(
    os.environ.get("BENCH_WARMSTART_PARTITIONS", "512"))
WARMSTART_TICKS = int(os.environ.get("BENCH_WARMSTART_TICKS", "32"))

# --forecast: run ONLY the predictive-rebalancing stage (round 19): the
# diurnal_forecast_capacity twin run REACTIVE (forecast off, the
# default) vs PROACTIVE (forecast.enabled + the predictive-fix opt-in)
# at a pinned seed, judged on SLO-violation ticks, goal-violation
# time-to-heal (heal ledger, sim clock), and a moves-per-simhour band —
# proactive-worse-than-reactive on any of them is a hard in-run canary
# (the CI FORECAST row). Like the other riders, the stage also runs at
# the END of every default bench pass.
FORECAST_MODE = "--forecast" in sys.argv or bool(
    os.environ.get("BENCH_FORECAST"))
FORECAST_SEED = int(os.environ.get("BENCH_FORECAST_SEED", "0"))
#: Proactive-arm overrides (forecast fit geometry matched to the
#: scenario's 17-window monitor and 48-tick diurnal period).
FORECAST_OVERRIDES = {
    "forecast.enabled": True,
    "forecast.fit.windows": 16,
    "forecast.horizon.windows": 6,
    "forecast.seasonal.period.windows": 48,
    "anomaly.detection.predictive.fix.enabled": True,
}

# --serving: run ONLY the serving front-door stage (round 20): (1) a
# parity pre-pass — fresh solve vs response-cache replay must be
# byte-identical at TWO different fleet bucket shapes, and concurrent
# identical requests (coalesced or cache-served) must match the serial
# body; (2) a steady arm — the pinned-seed mixed loadgen schedule
# replayed through the task engine against the REAL api, its schedule
# digest pinned in bench_baseline.json via the ranked_order hard canary;
# (3) an overload arm — a solver admission bound of zero must shed every
# new solve with Retry-After while viewer reads keep flowing. Like the
# other riders, the stage also runs at the END of every default bench
# pass (the CI SERVING row).
SERVING_MODE = "--serving" in sys.argv or bool(
    os.environ.get("BENCH_SERVING"))
SERVING_SEED = int(os.environ.get("BENCH_SERVING_SEED", "0"))
SERVING_RATE_RPS = float(os.environ.get("BENCH_SERVING_RATE", "50"))
SERVING_DURATION_S = float(os.environ.get("BENCH_SERVING_DURATION", "2"))

# --redteam: run ONLY the adversarial-mining stage (round 22): (1) the
# PINNED regression replays — the committed frontier's worst entries
# replayed full-loop; a flipped SLO verdict set hard-fails the stage
# (vs_baseline=0) because a mined worst case that stopped violating (or
# started violating differently) is exactly the regression the frontier
# exists to catch; (2) a budget-bounded FRESH mining sweep whose
# frontier JSON lands in the observability artifact bundle
# (BENCH_REDTEAM_FILE) with the margin histogram, blind-spot count, and
# found-below-library tally in the extras (the CI RED_TEAM row). Like
# the other riders, the stage also runs at the END of every default
# bench pass.
REDTEAM_MODE = "--redteam" in sys.argv or bool(
    os.environ.get("BENCH_REDTEAM"))
# Sweep seed 3 is the committed-frontier pin: at this (seed, shape) the
# 4th generation's late-fault squeeze (fault_timing +16 on a cascading
# kill pair) lands a genuine unhealed_faults violation inside the CI
# budget — regenerate fileStore/redteam_frontier.json if these change.
REDTEAM_SEED = int(os.environ.get("BENCH_REDTEAM_SEED", "3"))
REDTEAM_POP = int(os.environ.get("BENCH_REDTEAM_POP", "6"))
REDTEAM_GENERATIONS = int(os.environ.get("BENCH_REDTEAM_GENERATIONS", "4"))
REDTEAM_SURVIVORS = int(os.environ.get("BENCH_REDTEAM_SURVIVORS", "2"))
REDTEAM_TICKS = int(os.environ.get("BENCH_REDTEAM_TICKS", "16"))
REDTEAM_EVAL_BUDGET = int(os.environ.get("BENCH_REDTEAM_EVALS", "40"))
REDTEAM_REPLAYS = int(os.environ.get("BENCH_REDTEAM_REPLAYS", "2"))

# Generator-sampled SCENARIO_MATRIX rows (pinned (template, seed) pairs
# so the matrix stays deterministic): the scenario-diversity axis beyond
# the 6-scenario canonical library. Violation-free at these pins by
# construction — a new SLO violation on one IS a regression.
SAMPLED_MATRIX = (("load_ramp", 3), ("cascading_failures", 5))


# Journal of every emitted line, re-printed at exit (even via the watchdog
# hard-exit) so the final stdout tail always contains every completed stage.
_EMITTED: list[dict] = []


def _emit(obj) -> None:
    # ccsa: ok[CCSA007] single-writer journal: only the main bench thread
    # appends; the watchdog hard-exit path READS a snapshot under the GIL
    # and tolerates a missing in-flight line (summary tail is best-effort)
    _EMITTED.append(obj)
    print(json.dumps(obj), flush=True)


def _emit_summary_tail() -> None:
    """Re-emit every completed/partial stage line + one summary line, LAST
    on stdout. Idempotent and exception-free: it runs inside the watchdog
    hard-exit path."""
    try:
        stages = [o for o in _EMITTED
                  if str(o.get("metric", "")).startswith(
                      ("rebalance_proposal_wall_clock", "stage_partial",
                       "scenario_"))]
        for o in stages:
            print(json.dumps(o), flush=True)
        completed = [o for o in stages
                     if str(o["metric"]).startswith(
                         ("rebalance", "scenario_"))]
        headline = completed[-1] if completed else None
        print(json.dumps({
            "metric": "bench_summary",
            "value": headline["value"] if headline else 0.0,
            "unit": "s",
            "vs_baseline": headline["vs_baseline"] if headline else 0.0,
            "extras": {
                "headline_metric": headline["metric"] if headline else None,
                "stages_completed": [o["metric"] for o in stages],
                "device": (headline or {}).get("extras", {}).get("device"),
                "stderr_file": _stderr_path,
            },
        }), flush=True)
    except Exception:  # pragma: no cover — never let the tail re-emit
        pass            # throw away the primary emission path's output.


def _probe_device_once(timeout_s: float) -> str | None:
    """Ask a subprocess whether the ambient jax backend comes up. A wedged
    TPU tunnel hangs the child, not the bench; the child is killed on
    timeout so it cannot keep holding the chip's grant."""
    code = "import jax; print('PLATFORM=' + jax.devices()[0].platform)"
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return None
    if r.returncode != 0:
        return None
    for line in r.stdout.splitlines():
        if line.startswith("PLATFORM="):
            return line.split("=", 1)[1]
    return None


def _probe_device(deadline: float) -> str | None:
    """Retry the device probe with backoff until ~40% of the bench budget
    is spent (VERDICT r3 weak #1: the tunnel wedges for minutes and then
    returns — ONE 120 s probe is not a policy; r3's artifact fell back to
    host CPU on a single timeout and recorded no TPU number at all).
    Emits a probe-attempt line per try so the artifact shows the story."""
    probe_budget = time.time() + max(
        PROBE_TIMEOUT_S, 0.4 * (deadline - time.time()))
    attempt = 0
    backoff = 10.0
    while True:
        attempt += 1
        t0 = time.time()
        platform = _probe_device_once(PROBE_TIMEOUT_S)
        _emit({"metric": "device_probe_attempt", "value": round(
            time.time() - t0, 3), "unit": "s", "vs_baseline": 1.0,
            "extras": {"attempt": attempt,
                       "result": platform or "timeout_or_error"}})
        if platform is not None:
            return platform
        if time.time() + backoff + PROBE_TIMEOUT_S > probe_budget:
            return None
        time.sleep(backoff)
        backoff = min(60.0, backoff * 2)


class _Watchdog(Exception):
    pass


def _alarm(_sig, _frame):
    raise _Watchdog()


def _model_pipeline_probe(num_brokers: int, num_partitions: int,
                          rf: int = 3) -> dict:
    """model_build vs. model_refresh extras: drive the incremental
    pipeline (model/refresh.py — the same code path LoadMonitor's
    cluster_model uses) over a synthetic partition table. Measures a cold
    topology rebuild and a steady-state load-only refresh through the
    warm cache; the acceptance bar is refresh ≥ 5× faster than cold at
    1000 brokers / 100k partitions."""
    import time as _time

    import jax
    import numpy as np

    from cruise_control_tpu.common.broker_state import BrokerState
    from cruise_control_tpu.common.resources import NUM_RESOURCES, Resource
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.executor.admin import PartitionState
    from cruise_control_tpu.model.builder import BrokerSpec
    from cruise_control_tpu.model.refresh import IncrementalModelPipeline

    cap = {Resource.CPU: 100.0, Resource.NW_IN: 1e5, Resource.NW_OUT: 1e5,
           Resource.DISK: 1e6}
    brokers = [BrokerSpec(i, rack=f"r{i % 8}", capacity=cap,
                          state=BrokerState.ALIVE, host=f"h{i}")
               for i in range(num_brokers)]
    parts = {}
    for i in range(num_partitions):
        t, p = f"t{i % 64}", i // 64
        base = (i * 7919) % num_brokers
        reps = tuple((base + k) % num_brokers for k in range(rf))
        parts[(t, p)] = PartitionState(t, p, reps, reps[0], isr=reps)
    # Pre-generated load matrices: the filler models the monitor's gather
    # (a bulk copy into the preallocated buffers), not RNG cost.
    rng = np.random.default_rng(11)
    loads = [rng.random((num_partitions, NUM_RESOURCES)).astype(np.float32)
             for _ in range(3)]

    def filler(k):
        def fill(cache):
            n = len(cache.part_names)
            cache.ll_buf[:n] = loads[k]
            cache.fl_buf[:n] = loads[k]
            cache.fl_buf[:n, int(Resource.NW_OUT)] = 0.0
        return fill

    cfg = CruiseControlConfig()
    pipe = IncrementalModelPipeline(
        partition_bucket=cfg.get_int("solver.partition.bucket.size"),
        broker_bucket=cfg.get_int("solver.broker.bucket.size"))
    # Warm-up miss + hit (numpy/jax dispatch paths), then measure.
    s, _ = pipe.assemble(brokers, parts, filler(0), topology_token=0)
    jax.block_until_ready(s.assignment)
    s, _ = pipe.assemble(brokers, parts, filler(1), topology_token=0)
    jax.block_until_ready(s.leader_load)
    t0 = _time.perf_counter()
    s, _ = pipe.assemble(brokers, parts, filler(2), topology_token=1)
    jax.block_until_ready(s.assignment)
    cold_s = _time.perf_counter() - t0
    t0 = _time.perf_counter()
    s, _ = pipe.assemble(brokers, parts, filler(0), topology_token=1)
    jax.block_until_ready(s.leader_load)
    refresh_s = _time.perf_counter() - t0
    stats = pipe.last_stats
    return {
        "model_cold_rebuild_s": round(cold_s, 3),
        "model_refresh_s": round(refresh_s, 3),
        "model_refresh_speedup": round(cold_s / max(refresh_s, 1e-9), 1),
        "model_refresh_assemble_s": round(stats.assemble_s, 4),
        "model_refresh_transfer_s": round(stats.transfer_s, 4),
        "model_topology_cache": {"hits": pipe.topology_hits,
                                 "misses": pipe.topology_misses},
    }


def _tracing_noop_overhead_ns(iterations: int = 100_000) -> float:
    """Per-call cost of a DISABLED tracer span (the acceptance guard:
    tracing off must add nothing measurable to the solver hot path —
    the disabled path is one shared no-op context manager)."""
    from cruise_control_tpu.utils.tracing import TRACER
    was_enabled = TRACER.enabled
    TRACER.configure(enabled=False)
    try:
        t0 = time.perf_counter_ns()
        for _ in range(iterations):
            with TRACER.span("noop"):
                pass
        return (time.perf_counter_ns() - t0) / iterations
    finally:
        TRACER.configure(enabled=was_enabled)


def _flight_recorder_noop_overhead_ns(iterations: int = 100_000) -> float:
    """Per-call cost of a DISABLED flight recorder's record sites (the
    acceptance guard, same discipline as the tracing span: pass_scope
    returns a shared no-op whose goal() returns a shared no-op hook, so
    recording off must add nothing measurable to the solver driver
    paths). One iteration = one pass open/close + one goal hook + the
    three per-goal record calls + one per-dispatch call — strictly MORE
    work than any real driver pays per dispatch."""
    from cruise_control_tpu.utils.flight_recorder import FLIGHT
    was_enabled = FLIGHT.enabled
    FLIGHT.configure(enabled=False)
    try:
        t0 = time.perf_counter_ns()
        for _ in range(iterations):
            with FLIGHT.pass_scope(seq=0) as p:
                g = p.goal("noop")
                g.entry(violation=0.0)
                g.grid(8, 8, 8)
                g.dispatch("move", 8, 8, 0)
                g.exit(violation=0.0)
        return (time.perf_counter_ns() - t0) / iterations
    finally:
        FLIGHT.configure(enabled=was_enabled)


def _heal_ledger_noop_overhead_ns(iterations: int = 100_000) -> float:
    """Per-call cost of a DISABLED heal ledger's record sites (the
    acceptance guard, same discipline as the flight recorder: a disabled
    ledger's open() returns the shared NO_HEAL handle and handle_for()
    resolves to it, so ledgering off must add nothing measurable to the
    detection/fix/execution paths). One iteration = one open + one
    handle lookup + one ambient read + one phase + one resolve —
    strictly MORE work than any real call site pays per transition."""
    from cruise_control_tpu.utils.heal_ledger import HealLedger, current_heal
    led = HealLedger(enabled=False)
    t0 = time.perf_counter_ns()
    for _ in range(iterations):
        h = led.open("BROKER_FAILURE", "bench")
        led.handle_for("bench")
        current_heal().phase("noop")
        h.phase("noop")
        h.resolve("cleared")
    return (time.perf_counter_ns() - t0) / iterations


def _journey_noop_overhead_ns(iterations: int = 100_000) -> float:
    """Per-call cost of a DISABLED journey log's stamp sites (the
    acceptance guard, same discipline as the heal ledger: open() on a
    disabled log returns the shared NO_JOURNEY handle, every stamp a
    no-op). One iteration = one open + one segment scope + one ambient
    read/stamp + one note + one close — strictly MORE work than any
    request pays per stamp site."""
    from cruise_control_tpu.serving.journey import JourneyLog, current_journey
    log = JourneyLog(enabled=False)
    t0 = time.perf_counter_ns()
    for _ in range(iterations):
        j = log.open("PROPOSALS")
        with j.seg("noop"):
            pass
        current_journey().add("noop", 0.0)
        j.note(outcome="ok")
        log.close(j)
    return (time.perf_counter_ns() - t0) / iterations


def _slo_noop_overhead_ns(iterations: int = 100_000) -> float:
    """Per-call cost of a DISABLED SLO registry's record sites (the
    acceptance guard: slo.enabled=false means every probe is one
    attribute check and an early return — nothing on the front-door
    path). One iteration = one request classification + one staleness
    + one heal observation — MORE than any single response pays."""
    from cruise_control_tpu.utils.slo import SloRegistry
    reg = SloRegistry(enabled=False)
    t0 = time.perf_counter_ns()
    for _ in range(iterations):
        reg.record_request(0.01, 200)
        reg.observe_staleness(1.0)
        reg.observe_heal(1.0)
    return (time.perf_counter_ns() - t0) / iterations


def _run_heal_stage(progress: dict) -> dict:
    """The heal-ledger stage: drive the broker_loss_drift twin with
    per-tick detection (the cross-validation configuration — detection
    lands the tick the fault does, and the twin's per-tick health
    observation closes chains on the same anchor ScenarioScore uses) and
    report the ledger's per-fault heal percentiles. All durations are
    SIMULATED seconds, so heal_p50_s/heal_p99_s are deterministic at the
    pinned seed — the regression sentry warn-bands them (a pipeline
    change that slows detection→cleared shows up here first)."""
    import dataclasses as _dc

    from cruise_control_tpu.testing.simulator import (
        CANONICAL_SCENARIOS, ClusterSimulator,
    )
    t0 = time.time()
    spec = _dc.replace(CANONICAL_SCENARIOS["broker_loss_drift"], ticks=32)
    sim = ClusterSimulator(spec, seed=0, config_overrides={
        "anomaly.detection.interval.ms": int(spec.tick_s * 1000)})
    result = sim.run()
    progress["heal_sim_s"] = round(time.time() - t0, 3)
    led = sim.cc.heal_ledger
    durs = led.heal_durations_s("BROKER_FAILURE")

    def pct(q: float):
        if not durs:
            return None
        return durs[min(len(durs) - 1,
                        max(0, int(math.ceil(q * len(durs))) - 1))]

    chains = led.chains()
    outcomes: dict[str, int] = {}
    for c in chains:
        key = c["outcome"] or "open"
        outcomes[key] = outcomes.get(key, 0) + 1
    heal_file = os.environ.get("BENCH_HEAL_FILE")
    if heal_file:
        try:
            led.dump_json(heal_file)
        except Exception:  # noqa: BLE001 — the dump is best-effort
            pass
    score = result.score
    return {
        "metric": "heal_broker_loss_drift",
        "value": round(time.time() - t0, 3),
        "unit": "s",
        # >0 = the fault healed and every chain reached a terminal.
        "vs_baseline": 1.0 if durs and not led.open_count() else 0.0,
        "extras": {
            "heal_p50_s": pct(0.5), "heal_p99_s": pct(0.99),
            "broker_failure_heals": len(durs),
            "chains": len(chains), "open_chains": led.open_count(),
            "outcomes": {k: outcomes[k] for k in sorted(outcomes)},
            "mean_time_to_start_fix_ms": led.mean_time_to_start_fix_ms(),
            "score_heal_p95_ticks": score.time_to_heal_p95_ticks(),
            "slo_violations": score.slo_violations(),
            "heal_file": heal_file,
        },
    }


def _resilience_noop_overhead_ns(iterations: int = 100_000) -> float:
    """Per-call cost of the resilience wrapper with retries DISABLED
    (policy=None, breaker=None — the production configuration when
    resilience.enabled=false): the acceptance guard is the same no-op
    discipline as the tracing span — nothing measurable on any path
    that wraps its calls unconditionally."""
    from cruise_control_tpu.utils.resilience import call_with_resilience

    def fn():
        return None

    t0 = time.perf_counter_ns()
    for _ in range(iterations):
        call_with_resilience("noop", fn)
    return (time.perf_counter_ns() - t0) / iterations


def _flight_ring_overhead_probe(num_brokers: int = 200,
                                num_partitions: int = 5_000,
                                goal_idx: int = 12, k: int = 24) -> dict:
    """Marginal per-round cost of the RECORDING move kernel vs. the plain
    one (chain_optimize_rounds ring_rounds=16 vs 0), chained-marginal
    style (profile_round.py: (t2k - tk) / extra-rounds so dispatch glue
    cancels). The noop guard only covers the DISABLED hooks; recording is
    default-on in production, and its per-round stats row includes a
    broker_violations reduction the round body does not otherwise run —
    this probe is the live cost of that choice."""
    import jax
    import jax.numpy as jnp

    from cruise_control_tpu.analyzer.chain import chain_optimize_rounds
    from cruise_control_tpu.analyzer.optimizer import (
        GoalOptimizer, goals_by_priority,
    )
    from cruise_control_tpu.analyzer.search import ExclusionMasks
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.model.fixtures import Dist, random_cluster

    state, meta = random_cluster(
        num_brokers=num_brokers, num_topics=max(8, num_brokers // 10),
        num_partitions=num_partitions, rf=3, num_racks=8,
        dist=Dist.EXPONENTIAL, seed=42, skew_to_first=2.0,
        target_utilization=0.55)
    state = jax.device_put(state)
    jax.block_until_ready(state.assignment)
    cfg = CruiseControlConfig()
    opt = GoalOptimizer(cfg)
    scfg = opt.search_config(state)
    goals = tuple(goals_by_priority(cfg))
    masks = ExclusionMasks()
    prior = jnp.asarray([j < goal_idx for j in range(len(goals))])

    def run(budget: int, ring: int) -> int:
        out = chain_optimize_rounds(
            state, jnp.int32(goal_idx), prior, goals, opt.constraint, scfg,
            meta.num_topics, masks, budget=jnp.int32(budget),
            ring_rounds=ring)
        jax.block_until_ready(out[0].assignment)
        return int(out[2])

    def marginal(ring: int) -> tuple[float, int]:
        run(1, ring)                         # compile + warm
        t0 = time.monotonic()
        r1 = run(k, ring)
        t1 = time.monotonic()
        r2 = run(2 * k, ring)
        t2 = time.monotonic()
        return ((t2 - t1) - (t1 - t0)) / max(1, r2 - r1), r2

    off_s, off_r = marginal(0)
    on_s, on_r = marginal(16)
    return {
        "ms_per_round_recording_off": round(off_s * 1e3, 3),
        "ms_per_round_recording_on": round(on_s * 1e3, 3),
        "recording_overhead_ms_per_round": round((on_s - off_s) * 1e3, 3),
        "rounds_measured": {"off": off_r, "on": on_r},
        "shape": f"b{num_brokers}_p{num_partitions}",
        "goal": goals[goal_idx].name,
    }


# ---------------------------------------------------------------------------
# Regression sentry (bench_baseline.json)
#
# The exact failure mode that forced two TopicReplica reverts — a perf fix
# silently flipping the CpuUsageDistribution canary 86.0 → 82.74 — gets an
# automated gate: solution QUALITY (balancedness_after, the violated-goals
# set) is a hard canary and FAILS the comparison; perf-shaped numbers
# (solve wall clock, dispatch counts) are machine-sensitive and only get a
# tolerance band (warn). CI fails the job on any canary failure; warns are
# surfaced in the REGRESSION_SENTRY table for a human eye.
# ---------------------------------------------------------------------------

BASELINE_FILE = os.environ.get(
    "BENCH_BASELINE_FILE",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "bench_baseline.json"))


def load_baseline(path: str = "") -> dict | None:
    try:
        with open(path or BASELINE_FILE) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def compare_stage_to_baseline(record: dict, baseline: dict) -> dict | None:
    """One stage record vs. its committed baseline entry → the sentry
    verdict dict (None when the stage has no baseline entry). Canaries
    (hard fail): balancedness_after dropping more than
    ``tolerance.balancedness_abs`` below baseline, and any goal newly in
    the violated set. Tolerance band (warn): solve wall clock or dispatch
    count above ``tolerance.*_ratio`` × baseline, and goals that LEFT the
    violated set (an improvement — flagged so the baseline gets
    re-pinned, not silently absorbed)."""
    entry = (baseline.get("stages") or {}).get(record["metric"])
    if entry is None:
        return None
    tol = baseline.get("tolerance") or {}
    bal_abs = float(tol.get("balancedness_abs", 0.05))
    wall_ratio = float(tol.get("wall_clock_ratio", 2.0))
    disp_ratio = float(tol.get("dispatch_ratio", 1.5))
    ex = record.get("extras") or {}
    canaries: list[str] = []
    warnings: list[str] = []

    bal = ex.get("balancedness_after")
    bal_base = entry.get("balancedness_after")
    if bal is not None and bal_base is not None \
            and bal < bal_base - bal_abs:
        canaries.append(f"balancedness_after {bal} < baseline {bal_base} "
                        f"- {bal_abs}")
    new_viol = sorted(set(ex.get("violated_goals_after") or ())
                      - set(entry.get("violated_goals_after") or ()))
    gone_viol = sorted(set(entry.get("violated_goals_after") or ())
                       - set(ex.get("violated_goals_after") or ()))
    if new_viol:
        canaries.append(f"newly violated goals: {new_viol}")
    if gone_viol:
        warnings.append(f"goals no longer violated (re-pin baseline): "
                        f"{gone_viol}")

    rank = ex.get("ranked_order")
    rank_base = entry.get("ranked_order")
    if rank is not None and rank_base is not None \
            and list(rank) != list(rank_base):
        # The futures stage's headline contract: which future WINS is a
        # solution-quality statement, deterministic at pinned seeds —
        # a flip is a regression (or a deliberate change that must
        # re-pin the baseline and say why).
        canaries.append(f"ranked order flipped: {rank} != baseline "
                        f"{rank_base}")

    wall = ex.get("solve_wall_clock_s")
    wall_base = entry.get("solve_wall_clock_s")
    if wall is not None and wall_base and wall > wall_ratio * wall_base:
        warnings.append(f"solve_wall_clock_s {wall} > {wall_ratio}x "
                        f"baseline {wall_base}")
    disp = ex.get("dispatch_count")
    disp_base = entry.get("dispatch_count")
    if disp is not None and disp_base and disp > disp_ratio * disp_base:
        warnings.append(f"dispatch_count {disp} > {disp_ratio}x "
                        f"baseline {disp_base}")

    # Heal percentiles (heal_broker_loss_drift stage): warn-band in BOTH
    # directions — the values are twin-driven SIM seconds, so they are
    # deterministic at the pinned seed and any drift is a real pipeline
    # change (slower: detection/fix/clearing latency regressed; faster:
    # an improvement the baseline should re-pin), but heal latency is an
    # SLO trend, not a proposals-quality canary, so it never hard-fails.
    heal_ratio = float(tol.get("heal_ratio", 1.5))
    for key in ("heal_p50_s", "heal_p99_s"):
        val, base = ex.get(key), entry.get(key)
        if val is None or not base:
            continue
        if val > heal_ratio * base:
            warnings.append(f"{key} {val} > {heal_ratio}x baseline {base}")
        elif val < base / heal_ratio:
            warnings.append(f"{key} {val} improved past 1/{heal_ratio}x "
                            f"baseline {base} (re-pin baseline)")

    status = "fail" if canaries else ("warn" if warnings else "ok")
    return {
        "metric": f"regression_sentry_{record['metric']}",
        "value": 0.0 if canaries else 1.0,
        "unit": "pass",
        "vs_baseline": 0.0 if canaries else 1.0,
        "extras": {
            "stage": record["metric"], "status": status,
            "canaries": canaries, "warnings": warnings,
            "balancedness_after": bal,
            "balancedness_baseline": bal_base,
            "violated_goals_after": ex.get("violated_goals_after"),
            "violated_goals_baseline": entry.get("violated_goals_after"),
            "solve_wall_clock_s": wall,
            "solve_wall_clock_baseline_s": wall_base,
            "dispatch_count": disp,
            "dispatch_count_baseline": disp_base,
            "ranked_order": rank,
            "ranked_order_baseline": rank_base,
            "heal_p50_s": ex.get("heal_p50_s"),
            "heal_p99_s": ex.get("heal_p99_s"),
            "heal_p50_baseline_s": entry.get("heal_p50_s"),
            "heal_p99_baseline_s": entry.get("heal_p99_s"),
        },
    }


def _emit_sentry_summary(verdicts: list[dict], baseline: dict | None) -> None:
    """The sentry's closing verdict. A baselined stage that never produced
    a comparison (timed out, crashed, or was budget-skipped) makes the
    summary ``incomplete`` — NOT ok: a regression severe enough to also
    break its stage must not pass the gate by breaking it (the CI gate
    fails on incomplete just like fail)."""
    statuses = [v["extras"]["status"] for v in verdicts]
    compared = {v["extras"]["stage"] for v in verdicts}
    expected = set((baseline or {}).get("stages") or {})
    missing = sorted(expected - compared)
    if baseline is None:
        status = "no_baseline"
    elif "fail" in statuses:
        status = "fail"
    elif missing:
        status = "incomplete"
    elif "warn" in statuses:
        status = "warn"
    else:
        status = "ok"
    bad = status in ("fail", "incomplete")
    _emit({"metric": "regression_sentry_summary",
           "value": 0.0 if bad else 1.0, "unit": "pass",
           "vs_baseline": 0.0 if bad else 1.0,
           "extras": {"status": status,
                      "baseline_file": BASELINE_FILE,
                      "baseline_found": baseline is not None,
                      "stages_compared": [v["extras"]["stage"]
                                          for v in verdicts],
                      "stages_missing": missing}})


def _degraded_cycle_probe(seed: int = 11) -> dict:
    """``degraded_cycle_s``: wall-clock of a full executor cycle pushed
    through the fault-injecting backend (25% transient rate, zero-sleep
    backoff) — the cost of a rebalance cycle while the control plane
    misbehaves, and a convergence canary for the resilience layer."""
    from cruise_control_tpu.testing.chaos import run_faulted_executor_cycle
    r = run_faulted_executor_cycle(seed=seed, fault_rate=0.25,
                                   max_attempts=8, dead_letter_attempts=6)
    return {"degraded_cycle_s": round(r["elapsed_s"], 4),
            "degraded_cycle_converged": r["converged"],
            "degraded_cycle_faults_injected": r["faults_injected"]}


def _scenario_record(scenario, seed: int, ticks: int | None,
                     label: str | None = None) -> dict:
    """Run one scenario (a canonical name or a generator-sampled
    ScenarioSpec) on the digital twin and flatten its ScenarioScore into
    the extras the SCENARIO_MATRIX table reads. ``label`` names the
    metric for sampled specs (colons don't belong in metric names)."""
    from cruise_control_tpu.testing.simulator import run_scenario
    r = run_scenario(scenario, seed=seed, ticks=ticks)
    d = r.score.as_dict()
    name = label or d["scenario"]
    return {
        "metric": f"scenario_{name}",
        "value": round(r.wall_s, 3),
        "unit": "s",
        # >0 = every SLO held; the matrix table prints the violation list.
        "vs_baseline": 0.0 if d["sloViolations"] else 1.0,
        "extras": {
            "scenario": d["scenario"], "seed": seed,
            "ticks": d["ticks"], "sim_hours": d["simHours"],
            "replica_moves": d["churn"]["replicaMoves"],
            "leader_moves": d["churn"]["leaderMoves"],
            "bytes_mb_per_simhour": d["churn"]["bytesMbPerSimHour"],
            "moves_per_simhour": d["churn"]["movesPerSimHour"],
            "time_to_heal_p95_ticks": d["heal"]["p95Ticks"],
            "unhealed_faults": d["heal"]["unhealed"],
            "dead_letters": d["deadLetters"],
            "stale_served": d["degraded"]["staleServed"],
            "degraded_ticks": d["degraded"]["degradedTicks"],
            "balancedness_final": d["balancedness"]["final"],
            "events_applied": d["eventsApplied"],
            "faults_injected": d["faultsInjected"],
            "slo_violations": d["sloViolations"],
            "assignment_digest": r.assignment_digest,
        },
    }


def _run_scenario_matrix(deadline: float) -> int:
    """The --scenarios mode body: every canonical scenario under the same
    per-stage prorated-deadline discipline as the perf stages (weights =
    simulated ticks ≈ cost), so the matrix can NEVER ride one slow
    scenario into an external rc=124 kill."""
    from cruise_control_tpu.futures.generator import sample_scenario
    from cruise_control_tpu.testing.simulator import CANONICAL_SCENARIOS
    items = sorted(CANONICAL_SCENARIOS.items(),
                   key=lambda kv: kv[1].ticks)
    # Generator-sampled rows at pinned (template, seed) pairs: the
    # scenario-diversity axis the canonical library cannot cover, kept
    # deterministic (and SLO-clean at these pins) so the matrix gate
    # applies to them unchanged.
    items = items + [(f"random_{t}_s{s}", sample_scenario(t, s))
                     for t, s in SAMPLED_MATRIX]
    for i, (name, spec) in enumerate(items):
        remaining = deadline - time.time()
        if remaining < 45:
            # No silent caps: every un-run scenario still leaves a
            # parseable record, so the CI matrix can tell "skipped for
            # budget" apart from "never existed".
            for skipped_name, _s in items[i:]:
                _emit({"metric": f"stage_partial_scenario_{skipped_name}",
                       "value": 0.0, "unit": "s", "vs_baseline": 0.0,
                       "extras": {"scenario": skipped_name,
                                  "partial": True, "skipped": True,
                                  "reason": "budget exhausted"}})
            break
        weights = [s.ticks for _n, s in items[i:]]
        stage_budget = min(remaining - 15.0,
                           max(60.0, remaining * weights[0] / sum(weights)))
        t0 = time.time()
        signal.alarm(max(1, int(stage_budget)))
        try:
            record = _scenario_record(
                spec if name.startswith("random_") else name,
                SCENARIO_SEED, SCENARIO_TICKS or None, label=name)
            signal.alarm(0)
            _emit(record)
        except _Watchdog:
            _emit({"metric": f"stage_partial_scenario_{name}",
                   "value": round(time.time() - t0, 3), "unit": "s",
                   "vs_baseline": 0.0,
                   "extras": {"scenario": name, "partial": True,
                              "stage_budget_s": round(stage_budget, 1)}})
            continue
        except Exception as e:  # noqa: BLE001 — a crashed scenario must
            # still leave a parseable record; the library is independent
            # per scenario, so keep going.
            _emit({"metric": "stage_failed", "value": round(
                time.time() - t0, 3), "unit": "s", "vs_baseline": 0.0,
                "extras": {"stage": f"scenario_{name}",
                           "error": f"{type(e).__name__}: {e}"[:500]}})
            continue
        finally:
            signal.alarm(0)
    # The fleet_megabatch TWIN scenario (round 14) closes the matrix:
    # two ClusterSimulators sharing one bucket, one optimizer, and a
    # coalescing scheduler — the multi-cluster case the single-cluster
    # library cannot represent.
    remaining = deadline - time.time()
    if remaining < 60:
        _emit({"metric": "stage_partial_scenario_fleet_megabatch",
               "value": 0.0, "unit": "s", "vs_baseline": 0.0,
               "extras": {"scenario": "fleet_megabatch", "partial": True,
                          "skipped": True, "reason": "budget exhausted"}})
        return 0
    t0 = time.time()
    signal.alarm(max(1, int(min(remaining - 15.0, 240.0))))
    try:
        record = _fleet_twin_scenario_record()
        signal.alarm(0)
        _emit(record)
    except _Watchdog:
        _emit({"metric": "stage_partial_scenario_fleet_megabatch",
               "value": round(time.time() - t0, 3), "unit": "s",
               "vs_baseline": 0.0,
               "extras": {"scenario": "fleet_megabatch", "partial": True}})
    except Exception as e:  # noqa: BLE001 — parseable record always
        _emit({"metric": "stage_failed", "value": round(
            time.time() - t0, 3), "unit": "s", "vs_baseline": 0.0,
            "extras": {"stage": "scenario_fleet_megabatch",
                       "error": f"{type(e).__name__}: {e}"[:500]}})
    finally:
        signal.alarm(0)
    return 0


def _run_fleet_stage(progress: dict, k: int | None = None) -> dict:
    """The --fleet stage: K same-bucket synthetic clusters pushed
    through the CHAIN-SOLVE layer serially (one bounded
    optimize_goal_in_chain pass per cluster — round 6's fleet
    scheduling) vs megabatched (one optimize_goal_in_chain_megabatch
    over all K — round 14). The chain layer is exactly what the
    megabatch batches — per-cluster host work around it (model build,
    proposal diff, result assembly) is unchanged by batching and
    excluded from the ratio. Both paths are warmed so the ratio
    compares steady states; per-cluster results are asserted
    BYTE-IDENTICAL between the two paths (the parity pin — CI
    hard-fails on anything but "ok"), and per-cluster balancedness over
    the stage chain rides the extras so the regression sentry guards
    batched solve QUALITY alongside throughput."""
    import numpy as np

    from cruise_control_tpu.analyzer.chain import (
        AdaptiveDispatch, DispatchStats, MegastepConfig,
        optimize_goal_in_chain, optimize_goal_in_chain_megabatch,
        stack_states, unstack_state,
    )
    from cruise_control_tpu.analyzer.constraint import BalancingConstraint
    from cruise_control_tpu.analyzer.goals import (
        NetworkOutboundUsageDistributionGoal, PreferredLeaderElectionGoal,
        RackAwareGoal, ReplicaCapacityGoal, ReplicaDistributionGoal,
    )
    from cruise_control_tpu.analyzer.optimizer import balancedness_score
    from cruise_control_tpu.analyzer.search import (
        ExclusionMasks, SearchConfig,
    )
    from cruise_control_tpu.model.fixtures import random_cluster

    k = k or FLEET_K
    chain = (RackAwareGoal(), ReplicaCapacityGoal(),
             NetworkOutboundUsageDistributionGoal(),
             ReplicaDistributionGoal(), PreferredLeaderElectionGoal())
    cfg = SearchConfig(num_sources=32, num_dests=8, moves_per_round=32,
                       max_rounds=60)
    mega = MegastepConfig(donate=True, async_readback=True,
                          deficit_moves_cap=0)
    constraint = BalancingConstraint()
    masks = ExclusionMasks()
    dispatch_rounds = 16

    t0 = time.time()
    clusters = [random_cluster(num_brokers=12, num_topics=6,
                               num_partitions=96, rf=2, num_racks=3,
                               seed=3 + s, skew_to_first=2.0,
                               partition_bucket=32) for s in range(k)]
    num_topics = clusters[0][1].num_topics
    progress["fleet_model_build_s"] = round(time.time() - t0, 3)

    def serial_solve(state, stats=None):
        d = AdaptiveDispatch(dispatch_rounds, 0.0)
        infos = []
        for i in range(len(chain)):
            state, info = optimize_goal_in_chain(
                state, chain, i, constraint, cfg, num_topics, masks,
                dispatch_rounds=dispatch_rounds, dispatch=d, megastep=mega,
                stats=stats,
                donate_input=bool(infos)
                and any(x["rounds"] > 0 for x in infos))
            infos.append(info)
        return state, infos

    def batch_solve(states, physical=None):
        batched = stack_states(states)
        d = AdaptiveDispatch(dispatch_rounds, 0.0)
        mask = np.ones(len(states), dtype=bool)
        infos_per_goal = []
        ran = False
        for i in range(len(chain)):
            batched, infos = optimize_goal_in_chain_megabatch(
                batched, chain, i, constraint, cfg, num_topics, masks,
                mask, dispatch_rounds=dispatch_rounds, dispatch=d,
                megastep=mega, physical_stats=physical, donate_input=ran)
            ran = ran or any(x["rounds"] > 0 for x in infos)
            infos_per_goal.append(infos)
        return batched, infos_per_goal

    t0 = time.time()
    serial_solve(clusters[0][0])
    progress["fleet_warm_serial_s"] = round(time.time() - t0, 3)
    t0 = time.time()
    batch_solve([st for st, _m in clusters])
    progress["fleet_warm_megabatch_s"] = round(time.time() - t0, 3)

    t0 = time.time()
    serial = [serial_solve(st) for st, _m in clusters]
    serial_s = max(time.time() - t0, 1e-9)
    progress["fleet_serial_s"] = round(serial_s, 3)
    physical = DispatchStats()
    t0 = time.time()
    batched, infos_per_goal = batch_solve([st for st, _m in clusters],
                                          physical=physical)
    mb_s = max(time.time() - t0, 1e-9)
    progress["fleet_megabatch_s"] = round(mb_s, 3)

    parity = "ok"
    balancedness = []
    violated: set[str] = set()
    for b, (s_final, s_infos) in enumerate(serial):
        m_final = unstack_state(batched, b)
        if not np.array_equal(np.asarray(s_final.assignment),
                              np.asarray(m_final.assignment)) \
                or not np.array_equal(np.asarray(s_final.leader_slot),
                                      np.asarray(m_final.leader_slot)):
            parity = "MISMATCH"
        viol_b = {chain[i].name for i in range(len(chain))
                  if not infos_per_goal[i][b]["succeeded"]}
        violated.update(viol_b)
        balancedness.append(round(balancedness_score(chain, viol_b), 2))

    speedup = serial_s / mb_s
    return {
        "metric": f"fleet_megabatch_solve_{k}clusters",
        "value": round(mb_s, 3),
        "unit": "s",
        # Acceptance bar: >= 2x clusters-per-second over serial
        # scheduling (>1 here means the bar is met).
        "vs_baseline": round(speedup / 2.0, 3),
        "extras": {
            "clusters": k,
            "parity_pin": parity,
            "serial_solve_s": round(serial_s, 3),
            "megabatch_solve_s": round(mb_s, 3),
            "megabatch_speedup": round(speedup, 3),
            "serial_clusters_per_s": round(k / serial_s, 3),
            "fleet_solve_throughput_clusters_per_s": round(k / mb_s, 3),
            "megabatch_clusters_per_dispatch": k,
            "megabatch_occupancy": k,
            "measured_layer": "chain solve (bounded megastep drivers; "
                              "per-cluster model build / proposal diff "
                              "excluded — unchanged by batching)",
            "balancedness_per_cluster": balancedness,
            "balancedness_after": min(balancedness) if balancedness
            else None,
            "violated_goals_after": sorted(violated),
            "solve_wall_clock_s": round(mb_s, 3),
            "dispatch_count": physical.dispatch_count,
            "donated_dispatches": physical.donated,
            **progress,
        },
    }


def _run_fleet_shard_child() -> int:
    """Subprocess body for --fleet-shard (round 23). Runs with
    ``--xla_force_host_platform_device_count=N`` already in XLA_FLAGS
    (set by the parent — a process-level init flag, hence the fresh
    process). The A/B is exactly what ``fleet.shard.enabled`` toggles
    in production: the same W·N-wide bucket batches solved as ONE
    single-device megabatch program (the round-14 path — every round
    computes every row until the bucket's SLOWEST cluster converges)
    vs sharded across the N-device mesh at the control plane's fixed
    per-device occupancy of W cluster slots, where each device's
    while_loop exits as soon as ITS W clusters converge. The workload
    is difficulty-banded along the cluster axis (three light bands +
    one heavy — the realistic fleet shape: most clusters near
    equilibrium, a few churning), so single-core hosts see the
    early-exit-locality win and a real mesh adds device parallelism on
    top. The freeze-select discipline makes each cluster's trajectory
    a function of its own rows plus the global round index, so
    per-cluster results must be BYTE-IDENTICAL across the arms. Prints
    one JSON line with both arms' clusters/s and the parity verdict."""
    import numpy as np

    import jax

    from cruise_control_tpu.analyzer.chain import (
        AdaptiveDispatch, MegastepConfig, optimize_goal_in_chain_megabatch,
        stack_states, unstack_state,
    )
    from cruise_control_tpu.analyzer.constraint import BalancingConstraint
    from cruise_control_tpu.analyzer.goals import (
        NetworkOutboundUsageDistributionGoal, ReplicaDistributionGoal,
    )
    from cruise_control_tpu.analyzer.search import (
        ExclusionMasks, SearchConfig,
    )
    from cruise_control_tpu.model.fixtures import random_cluster
    from cruise_control_tpu.parallel.megabatch_sharded import (
        shard_megabatch, shard_megabatch_masks,
    )
    from cruise_control_tpu.parallel.mesh import make_mesh

    ndev = jax.device_count()
    w = FLEETSHARD_OCCUPANCY
    wide = w * ndev
    c = FLEETSHARD_CLUSTERS - FLEETSHARD_CLUSTERS % wide
    chain = (NetworkOutboundUsageDistributionGoal(),
             ReplicaDistributionGoal())
    cfg = SearchConfig(num_sources=8, num_dests=4, moves_per_round=4,
                       max_rounds=96)
    mega = MegastepConfig(donate=True, async_readback=True,
                          deficit_moves_cap=0)
    constraint = BalancingConstraint()
    masks = ExclusionMasks()
    dispatch_rounds = 96
    num_topics = 6

    def skew(s):
        # Difficulty band by device block: the last block churns (deep
        # imbalance, many rounds), the rest sit near equilibrium.
        band = (s % wide) // w
        return 32.0 if band == ndev - 1 else 1.0 + 0.4 * band

    states = [random_cluster(num_brokers=6, num_topics=num_topics,
                             num_partitions=96, rf=2, num_racks=3,
                             seed=3 + s, skew_to_first=skew(s),
                             partition_bucket=32)[0] for s in range(c)]
    mesh = make_mesh(ndev)

    def assemble(chunk, m):
        batched = stack_states(chunk)
        bmasks = masks
        if m is not None:
            batched = shard_megabatch(batched, m)
            bmasks = shard_megabatch_masks(masks, m)
        jax.block_until_ready(batched.assignment)
        return batched, bmasks

    def solve(batched, bmasks, n, m):
        d = AdaptiveDispatch(dispatch_rounds, 0.0)
        act = np.ones(n, dtype=bool)
        ran = False
        for i in range(len(chain)):
            batched, infos = optimize_goal_in_chain_megabatch(
                batched, chain, i, constraint, cfg, num_topics, bmasks,
                act, dispatch_rounds=dispatch_rounds, dispatch=d,
                megastep=mega, donate_input=ran, mesh=m)
            ran = ran or any(x["rounds"] > 0 for x in infos)
        return batched

    # Warm both arms (compiles) before timing steady states. Bucket
    # assembly (stack + shard placement) happens OUTSIDE the timed
    # region both times — it is per-cluster host work the sharding does
    # not change, exactly like the --fleet stage's model-build split.
    for m in (None, mesh):
        b, bm = assemble(states[:wide], m)
        jax.block_until_ready(solve(b, bm, wide, m).assignment)

    walls = {}
    finals = {}
    for label, m in (("single", None), ("sharded", mesh)):
        best = None
        for _rep in range(3):
            pre = [assemble(states[j * wide:(j + 1) * wide], m)
                   for j in range(c // wide)]
            t0 = time.time()
            outs = [solve(b, bm, wide, m) for b, bm in pre]
            jax.block_until_ready([o.assignment for o in outs])
            dt = max(time.time() - t0, 1e-9)
            best = dt if best is None else min(best, dt)
        walls[label] = best
        finals[label] = outs

    parity = "ok"
    for s in range(c):
        j, r = divmod(s, wide)
        a = unstack_state(finals["single"][j], r)
        b = unstack_state(finals["sharded"][j], r)
        if not np.array_equal(np.asarray(a.assignment),
                              np.asarray(b.assignment)) \
                or not np.array_equal(np.asarray(a.leader_slot),
                                      np.asarray(b.leader_slot)):
            parity = f"MISMATCH(cluster {s})"
            break

    print(json.dumps({
        "devices": ndev, "clusters": c, "per_device_occupancy": w,
        "bucket_width": wide,
        "single_device_s": round(walls["single"], 3),
        "sharded_s": round(walls["sharded"], 3),
        "clusters_per_s_single": round(c / walls["single"], 3),
        "clusters_per_s_sharded": round(c / walls["sharded"], 3),
        "parity_pin": parity}), flush=True)
    return 0


def _run_fleet_shard_stage(progress: dict, budget_s: float = 480.0) -> dict:
    """The --fleet-shard stage (round 23): the device-sharded megabatch
    measured where it matters — clusters/s for the same bucket queue
    with ``fleet.shard.enabled`` off (one single-device program per
    W·N-wide bucket batch) vs on (the batch sharded across the N-device
    mesh at fixed per-device occupancy W, device-local early exit). The
    measurement runs in a fresh subprocess (``_run_fleet_shard_child``)
    because XLA's host-platform device count is a process-level init
    flag. vs_baseline is the clusters/s ratio against the 1.6x
    acceptance bar; the cross-arm byte-parity pin rides the extras (the
    CI FLEET_SHARD row hard-fails anything but "ok")."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count="
                        + str(FLEETSHARD_DEVICES))
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--fleet-shard-child"],
        env=env, capture_output=True, text=True,
        timeout=max(60.0, budget_s))
    progress["fleet_shard_child_s"] = round(time.time() - t0, 3)
    data = None
    for line in reversed(proc.stdout.splitlines()):
        if line.startswith("{"):
            try:
                data = json.loads(line)
                break
            except ValueError:
                continue
    if proc.returncode != 0 or data is None:
        raise RuntimeError(
            f"fleet-shard child rc={proc.returncode}: "
            f"{(proc.stderr or proc.stdout)[-400:]}")
    speedup = data["clusters_per_s_sharded"] / max(
        data["clusters_per_s_single"], 1e-9)
    return {
        "metric": f"fleet_shard_solve_{data['clusters']}clusters_"
                  f"{data['devices']}dev",
        "value": data["sharded_s"],
        "unit": "s",
        # Acceptance bar: >= 1.6x clusters/s at N devices vs 1 at fixed
        # per-device occupancy (>1 here means the bar is met).
        "vs_baseline": round(speedup / 1.6, 3),
        "extras": {
            "devices": data["devices"],
            "clusters": data["clusters"],
            "per_device_occupancy": data["per_device_occupancy"],
            "bucket_width": data["bucket_width"],
            "parity_pin": data["parity_pin"],
            "single_device_s": data["single_device_s"],
            "sharded_s": data["sharded_s"],
            "fleet_shard_speedup": round(speedup, 3),
            "clusters_per_s_single": data["clusters_per_s_single"],
            "clusters_per_s_sharded": data["clusters_per_s_sharded"],
            "clusters_per_s_per_device": round(
                data["clusters_per_s_sharded"]
                / max(data["devices"], 1), 3),
            "solve_wall_clock_s": data["sharded_s"],
            "measured_layer": "chain solve via the shard_map twins "
                              "(same bucket batch both arms: one "
                              "single-device program vs the N-device "
                              "mesh; byte parity asserted per cluster)",
            **progress,
        },
    }


def _run_direct_stage(progress: dict) -> dict:
    """The --direct stage: the count-distribution goals solved by the
    deficit-sized GREEDY path vs the DIRECT-assignment transport + greedy
    polish (round 17), both through the real GoalOptimizer with the
    wide-regime gate lowered to put the stage shape in regime. Both arms
    are warmed (first pass pays the compiles), then the SECOND pass is
    the steady-state measurement — the ISSUE-13 acceptance bar is a
    steady-solve ratio, not a compile race.

    The QUALITY canary is judged direct-vs-greedy within this run:
    balancedness_after must not drop > 0.05 below the greedy arm's and
    the direct arm must introduce NO violated goal the greedy arm does
    not have (the exact silent-flip class that forced two prior density
    reverts); the CI DIRECT row hard-fails on either, or on this stage
    missing."""
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.model.fixtures import random_cluster

    b = DIRECT_BROKERS
    p = DIRECT_PARTITIONS
    count_goals = ("ReplicaDistributionGoal", "TopicReplicaDistributionGoal",
                   "LeaderReplicaDistributionGoal")
    t0 = time.time()
    state, meta = random_cluster(num_brokers=b, num_topics=max(8, b // 5),
                                 num_partitions=p, rf=3, num_racks=5,
                                 seed=11, skew_to_first=2.0)
    progress["direct_model_build_s"] = round(time.time() - t0, 3)

    def arm(enabled: bool):
        cfg = CruiseControlConfig({
            "solver.direct.assignment.enabled": enabled,
            # Put the stage shape in the wide regime (where the kernel
            # replaces deficit-sized greedy) and force the bounded
            # per-goal path the regime uses at scale.
            "solver.wide.batch.min.brokers": min(128, b),
            "solver.fused.chain.max.brokers": 128,
        })
        opt = GoalOptimizer(cfg)
        t_w = time.time()
        opt.optimizations(state, meta)              # warm: compiles
        warm_s = time.time() - t_w
        t_s = time.time()
        _st, res = opt.optimizations(state, meta)   # steady
        steady_s = time.time() - t_s
        return res, warm_s, steady_s, opt.last_dispatch_stats()

    g_res, g_warm, g_steady, g_stats = arm(False)
    progress["direct_greedy_warm_s"] = round(g_warm, 3)
    progress["direct_greedy_steady_s"] = round(g_steady, 3)
    d_res, d_warm, d_steady, d_stats = arm(True)
    progress["direct_warm_s"] = round(d_warm, 3)
    progress["direct_steady_s"] = round(d_steady, 3)

    per_goal = {}
    for gr, dr in zip(g_res.goal_results, d_res.goal_results):
        if gr.name in count_goals:
            per_goal[gr.name] = {
                "greedy_s": round(gr.duration_s, 3),
                "direct_s": round(dr.duration_s, 3),
                "greedy_rounds": gr.rounds, "direct_rounds": dr.rounds,
                "greedy_violation": round(gr.residual_violation, 1),
                "direct_violation": round(dr.residual_violation, 1)}
    count_g = sum(v["greedy_s"] for v in per_goal.values())
    count_d = max(sum(v["direct_s"] for v in per_goal.values()), 1e-9)
    speedup = count_g / count_d
    new_violated = sorted(set(d_res.violated_goals_after)
                          - set(g_res.violated_goals_after))
    bal_drop = g_res.balancedness_after - d_res.balancedness_after
    canary = "ok"
    if new_violated:
        canary = "NEW_VIOLATED:" + ",".join(new_violated)
    elif bal_drop > 0.05:
        canary = f"BALANCEDNESS_DROP:{bal_drop:.3f}"
    return {
        "metric": f"direct_vs_greedy_count_goals_{b}b",
        "value": round(count_d, 3),
        "unit": "s",
        # Acceptance bar: >= 3x on the count goals' steady solve (>1
        # here means the bar is met).
        "vs_baseline": round(speedup / 3.0, 3),
        "extras": {
            "brokers": b, "partitions": p,
            "canary": canary,
            "count_goal_wall_greedy_s": round(count_g, 3),
            "count_goal_wall_direct_s": round(count_d, 3),
            "count_goal_speedup": round(speedup, 3),
            "steady_pass_greedy_s": round(g_steady, 3),
            "steady_pass_direct_s": round(d_steady, 3),
            "balancedness_greedy": round(g_res.balancedness_after, 3),
            "balancedness_direct": round(d_res.balancedness_after, 3),
            "violated_goals_greedy": sorted(g_res.violated_goals_after),
            "violated_goals_direct": sorted(d_res.violated_goals_after),
            "new_violated_goals": new_violated,
            "direct_dispatches": d_stats.get("direct_dispatches", 0),
            "dispatch_count_direct": d_stats.get("dispatch_count"),
            "dispatch_count_greedy": g_stats.get("dispatch_count"),
            "per_goal": per_goal,
            **progress,
        },
    }


def _run_transport_stage(progress: dict) -> dict:
    """The --transport stage (round 21): the SAME greedy-vs-direct A/B
    as --direct, but at the sparse-cell geometry the retired
    ``direct_regime_ok`` density gate used to wall off — 100 topics at
    200b/10k·rf3 is ~1.5 replicas per [topic, broker] cell, where the
    old integral per-cell plan had nothing to move and per-partition
    greedy rounds crawl. The sparse-aware fractional plan (cell-
    aggregated surplus/deficit targets + deterministic randomized
    rounding) must make TopicReplicaDistribution the win here:
    vs_baseline is the TR steady-wall speedup (>1 = the direct arm's TR
    solve beats greedy), with TR rounds and residual riding the extras.
    REPL and Leader are individually FASTER under greedy at this
    geometry (tiny per-broker deficits; reported honestly in per_goal,
    not gated) — the stage's bar is TR plus the same balancedness /
    no-new-violated canary as --direct; the CI TRANSPORT row hard-fails
    on a canary flip or this stage missing.

    Round 23 adds the per-goal density choice to the pins: below
    ``solver.direct.density.sparse.threshold`` replicas per cell the
    shipped optimizer routes only the sparse-plan winners (TR) through
    the transport kernel and lets REPL/Leader keep their faster greedy
    path — ``density_path_choice`` in the extras records which path
    each count goal took at this stage's density, so the choice is
    pinned per PR."""
    from cruise_control_tpu.analyzer.optimizer import (
        GoalOptimizer, direct_goal_choice, replica_density,
    )
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.model.fixtures import random_cluster

    b = TRANSPORT_BROKERS
    p = TRANSPORT_PARTITIONS
    tr_goal = "TopicReplicaDistributionGoal"
    count_goals = ("ReplicaDistributionGoal", tr_goal,
                   "LeaderReplicaDistributionGoal")
    t0 = time.time()
    state, meta = random_cluster(num_brokers=b, num_topics=TRANSPORT_TOPICS,
                                 num_partitions=p, rf=3, num_racks=5,
                                 seed=11, skew_to_first=2.0)
    progress["transport_model_build_s"] = round(time.time() - t0, 3)
    density = replica_density(state, TRANSPORT_TOPICS)
    progress["transport_replicas_per_cell"] = round(density, 3)
    sparse_threshold = CruiseControlConfig().get_double(
        "solver.direct.density.sparse.threshold")
    chosen = direct_goal_choice(density, sparse_threshold)
    path_choice = {g: ("direct" if chosen is None or g in chosen
                       else "greedy") for g in count_goals}

    def arm(enabled: bool):
        cfg = CruiseControlConfig({
            "solver.direct.assignment.enabled": enabled,
            "solver.wide.batch.min.brokers": min(128, b),
            "solver.fused.chain.max.brokers": 128,
        })
        opt = GoalOptimizer(cfg)
        t_w = time.time()
        opt.optimizations(state, meta)              # warm: compiles
        warm_s = time.time() - t_w
        t_s = time.time()
        _st, res = opt.optimizations(state, meta)   # steady
        steady_s = time.time() - t_s
        return res, warm_s, steady_s, opt.last_dispatch_stats()

    g_res, g_warm, g_steady, g_stats = arm(False)
    progress["transport_greedy_warm_s"] = round(g_warm, 3)
    progress["transport_greedy_steady_s"] = round(g_steady, 3)
    d_res, d_warm, d_steady, d_stats = arm(True)
    progress["transport_warm_s"] = round(d_warm, 3)
    progress["transport_steady_s"] = round(d_steady, 3)

    per_goal = {}
    tr = None
    for gr, dr in zip(g_res.goal_results, d_res.goal_results):
        if gr.name in count_goals:
            per_goal[gr.name] = {
                "greedy_s": round(gr.duration_s, 3),
                "direct_s": round(dr.duration_s, 3),
                "greedy_rounds": gr.rounds, "direct_rounds": dr.rounds,
                "greedy_violation": round(gr.residual_violation, 1),
                "direct_violation": round(dr.residual_violation, 1)}
            if gr.name == tr_goal:
                tr = per_goal[gr.name]
    if tr is None:
        raise RuntimeError(f"{tr_goal} missing from goal results")
    tr_speedup = tr["greedy_s"] / max(tr["direct_s"], 1e-9)
    new_violated = sorted(set(d_res.violated_goals_after)
                          - set(g_res.violated_goals_after))
    bal_drop = g_res.balancedness_after - d_res.balancedness_after
    canary = "ok"
    if new_violated:
        canary = "NEW_VIOLATED:" + ",".join(new_violated)
    elif bal_drop > 0.05:
        canary = f"BALANCEDNESS_DROP:{bal_drop:.3f}"
    return {
        "metric": f"transport_sparse_tr_{b}b",
        "value": tr["direct_s"],
        "unit": "s",
        # Acceptance bar: the sparse plan must beat greedy on the TR
        # steady solve outright (>1 here means the bar is met).
        "vs_baseline": round(tr_speedup, 3),
        "extras": {
            "brokers": b, "partitions": p, "topics": TRANSPORT_TOPICS,
            "replicas_per_cell": round(density, 3),
            "sparse_threshold": sparse_threshold,
            "density_path_choice": path_choice,
            "canary": canary,
            "tr_wall_greedy_s": tr["greedy_s"],
            "tr_wall_direct_s": tr["direct_s"],
            "tr_rounds_greedy": tr["greedy_rounds"],
            "tr_rounds_direct": tr["direct_rounds"],
            "tr_residual_greedy": tr["greedy_violation"],
            "tr_residual_direct": tr["direct_violation"],
            "tr_speedup": round(tr_speedup, 3),
            "steady_pass_greedy_s": round(g_steady, 3),
            "steady_pass_direct_s": round(d_steady, 3),
            # Sentry-comparable keys (the DIRECT arm is the shipped
            # configuration, so its quality is what the baseline pins).
            "balancedness_after": round(d_res.balancedness_after, 3),
            "violated_goals_after": sorted(d_res.violated_goals_after),
            "solve_wall_clock_s": tr["direct_s"],
            "balancedness_greedy": round(g_res.balancedness_after, 3),
            "balancedness_direct": round(d_res.balancedness_after, 3),
            "violated_goals_greedy": sorted(g_res.violated_goals_after),
            "violated_goals_direct": sorted(d_res.violated_goals_after),
            "new_violated_goals": new_violated,
            "direct_dispatches": d_stats.get("direct_dispatches", 0),
            "dispatch_count_direct": d_stats.get("dispatch_count"),
            "dispatch_count_greedy": g_stats.get("dispatch_count"),
            "per_goal": per_goal,
            **progress,
        },
    }


def _run_futures_stage(progress: dict, n: int | None = None) -> dict:
    """The --futures stage: evaluating N sampled candidate futures the
    round-11 way (one FULL serial ``run_scenario`` replay per future —
    exactly what ``?what_if=`` does per request: detection, self-healing
    solves, and probes every tick) vs the round-15 futures engine
    (per-future advance with detection off + ONE batched decision
    solve). Same templates, same seeds, same compressed story in the
    same tick horizon — the workload-level ratio is the acceptance bar
    (≥ 2x futures/s on CPU; measured ~27x at 8 futures / 16 ticks on a
    2-core dev box).

    Transparency split: the DECISION-SOLVE layer is also timed serial
    (one fused ``optimizations()`` per future) vs batched, with
    per-future scores asserted BYTE-IDENTICAL between those two paths
    (the parity pin — CI hard-fails anything but "ok"). At CI's toy
    shapes the fused solo solve is individually cheaper than a batched
    bounded program — the batch pays off in dispatch amortization at
    real link latency and in compile-once sharing — so the solve split
    is reported, not gated. The RANKED ORDER rides the extras as a
    regression-sentry canary: a rank flip against the committed
    baseline hard-fails the sentry."""
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.futures.evaluator import (
        PRESENT, FutureSpec, evaluate_prepared, plan_futures,
        prepare_future, rank_results,
    )
    from cruise_control_tpu.futures.generator import sample_future
    from cruise_control_tpu.testing.simulator import run_scenario
    n = n or FUTURES_N
    ticks = int(os.environ.get("BENCH_FUTURES_TICKS", "16"))
    width = n + 1  # every future + the present in ONE batched program
    plan = plan_futures((), n, seed=0, ticks=ticks)
    specs = plan + [FutureSpec(PRESENT, 0, ticks)]

    # Warm both worlds (compiles) before timing steady states.
    t0 = time.time()
    run_scenario(sample_future(plan[0].template,
                               plan[0].seed).replay_spec(ticks),
                 seed=plan[0].seed)
    progress["futures_warm_replay_s"] = round(time.time() - t0, 3)
    t0 = time.time()
    prepared = [prepare_future(fs) for fs in specs]
    optimizer = GoalOptimizer(prepared[0].config)
    evaluate_prepared(prepared, optimizer, batched=False)
    evaluate_prepared(prepared, optimizer, width=width)
    progress["futures_warm_engine_s"] = round(time.time() - t0, 3)

    # The round-11 way: one full serial replay per candidate future —
    # the SAME story compressed into the same horizon (replay_spec
    # rescales every event; plain truncation would let the baseline
    # under-work by dropping late faults/maintenance).
    t0 = time.time()
    for fs in plan:
        run_scenario(sample_future(fs.template,
                                   fs.seed).replay_spec(ticks),
                     seed=fs.seed)
    replay_s = max(time.time() - t0, 1e-9)

    # The futures engine, end to end: advance every twin + ONE batched
    # decision solve (the COMPARE_FUTURES body, minus response shaping).
    t0 = time.time()
    prepared = [prepare_future(fs) for fs in specs]
    batched = evaluate_prepared(prepared, optimizer, width=width)
    engine_s = max(time.time() - t0, 1e-9)
    dispatch_stats = optimizer.last_dispatch_stats()

    # Decision-solve transparency split + the byte-parity pin.
    t0 = time.time()
    serial = evaluate_prepared(prepared, optimizer, batched=False)
    solve_serial_s = max(time.time() - t0, 1e-9)
    t0 = time.time()
    batched2 = evaluate_prepared(prepared, optimizer, width=width)
    solve_batched_s = max(time.time() - t0, 1e-9)
    parity = "ok" if [r.score_dict() for r in serial] \
        == [r.score_dict() for r in batched] \
        == [r.score_dict() for r in batched2] else "MISMATCH"

    ranked = rank_results(batched)
    ranked_order = [r.future_id for r in ranked]
    bals = [r.balancedness_after for r in ranked if r.error is None]
    violated = sorted({g for r in ranked for g in r.violated_goals_after})
    speedup = replay_s / engine_s
    return {
        "metric": f"futures_compare_{n}futures",
        "value": round(engine_s, 3),
        "unit": "s",
        # Acceptance bar: >= 2x futures/s over serial replay on CPU
        # (>1 here means the bar is met).
        "vs_baseline": round(speedup / 2.0, 3),
        "extras": {
            "futures": n,
            "ticks": ticks,
            "parity_pin": parity,
            "replay_serial_s": round(replay_s, 3),
            "engine_batched_s": round(engine_s, 3),
            "futures_speedup": round(speedup, 3),
            "futures_per_s_replay": round(n / replay_s, 3),
            "futures_per_s_batched": round(n / engine_s, 3),
            "futures_occupancy": len(prepared),
            "decision_solve_serial_s": round(solve_serial_s, 3),
            "decision_solve_batched_s": round(solve_batched_s, 3),
            "ranked_order": ranked_order,
            "measured_layer": "whole evaluation workload (serial "
                              "run_scenario replay per future vs "
                              "advance + one batched decision solve); "
                              "decision_solve_* is the solve-layer "
                              "split, parity-pinned",
            "balancedness_after": min(bals) if bals else None,
            "violated_goals_after": violated,
            "solve_wall_clock_s": round(engine_s, 3),
            "dispatch_count": dispatch_stats.get("dispatch_count", 0),
            **progress,
        },
    }


# Self-contained restart probe run in a FRESH python process: builds a
# deterministic skewed cluster facade, starts it up (which wires the
# persistent compile cache + background prewarm per config), and times
# the first proposal. Reports its own phase breakdown as one JSON line;
# the parent times the whole subprocess. Parameterized by env so the
# script stays byte-identical across arms (same code path, different
# config switches).
_RESTART_PROBE_SCRIPT = r"""
import json, os, time
T0 = time.time()
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.config.cruise_control_config import (
    CruiseControlConfig,
)
from cruise_control_tpu.executor.admin import (
    InMemoryAdminBackend, PartitionState,
)
from cruise_control_tpu.executor.executor import Executor
from cruise_control_tpu.facade import CruiseControl
from cruise_control_tpu.monitor import LoadMonitor, StaticCapacityResolver
from cruise_control_tpu.monitor.sampling import SyntheticSampler
from cruise_control_tpu.warmstart import prewarm_manager

brokers = int(os.environ["WS_BROKERS"])
parts = int(os.environ["WS_PARTITIONS"])
partitions = {}
for p in range(parts):
    a = p % brokers
    b = (a + 1 + (p * 7) % (brokers - 1)) % brokers
    reps = (0 if p % 3 == 0 else a, b if b != (0 if p % 3 == 0 else a)
            else (b + 1) % brokers)
    partitions[(f"t{p % 8}", p // 8)] = PartitionState(
        f"t{p % 8}", p // 8, reps, reps[0], isr=reps)
props = {
    "partition.metrics.window.ms": 1000,
    "num.partition.metrics.windows": 3,
    "min.valid.partition.ratio": 0.0,
    "anomaly.detection.interval.ms": 600_000,
    "failed.brokers.file.path": "",
    "solver.compile.cache.enabled": os.environ["WS_CACHE"] == "1",
    "solver.prewarm.enabled": os.environ["WS_PREWARM"] == "1",
}
if os.environ.get("WS_CACHE_DIR"):
    props["solver.compile.cache.dir"] = os.environ["WS_CACHE_DIR"]
cfg = CruiseControlConfig(props)
caps = StaticCapacityResolver({}, {Resource.CPU: 100.0, Resource.DISK: 1e7,
                                   Resource.NW_IN: 1e6,
                                   Resource.NW_OUT: 1e6})
backend = InMemoryAdminBackend(partitions.values())
monitor = LoadMonitor(cfg, backend, samplers=[SyntheticSampler()],
                      capacity_resolver=caps,
                      broker_racks={b: f"r{b % 3}" for b in range(brokers)})
cc = CruiseControl(cfg, backend, load_monitor=monitor,
                   executor=Executor(backend, synchronous=True))
for k in range(1, 4):
    monitor.task_runner.run_sampling_once(end_ms=k * 1000)
t_model = time.time()
cc.start_up(block_on_load=False, start_precompute=False)
prewarm_wait_s = 0.0
prewarm = None
if os.environ.get("WS_WAIT_PREWARM") == "1":
    mgr = prewarm_manager(cc.optimizer)
    if mgr is not None:
        t = time.time()
        mgr.join(timeout=float(os.environ.get("WS_TIMEOUT", "240")))
        prewarm_wait_s = time.time() - t
        prewarm = mgr.status_dict()
t_req = time.time()
res = cc.proposals()
done = time.time()
print(json.dumps({
    "import_and_model_s": round(t_model - T0, 3),
    "prewarm_wait_s": round(prewarm_wait_s, 3),
    "first_proposal_request_s": round(done - t_req, 3),
    "process_to_first_proposal_s": round(done - T0, 3),
    "num_proposals": len(res.proposals),
    "balancedness_after": res.optimizer_result.balancedness_after,
    "prewarm": prewarm,
}))
cc.shutdown()
"""


def _restart_probe(cache: bool, prewarm: bool, wait_prewarm: bool,
                   cache_dir: str, timeout_s: float) -> dict:
    """One fresh-subprocess restart measurement (arm of the --warmstart
    stage)."""
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "WS_BROKERS": str(WARMSTART_BROKERS),
        "WS_PARTITIONS": str(WARMSTART_PARTITIONS),
        "WS_CACHE": "1" if cache else "0",
        "WS_CACHE_DIR": cache_dir,
        "WS_PREWARM": "1" if prewarm else "0",
        "WS_WAIT_PREWARM": "1" if wait_prewarm else "0",
        "WS_TIMEOUT": str(int(timeout_s)),
        # The probe must pay its OWN compiles (or cache retrievals) —
        # never inherit a cache dir from the parent bench process.
        "JAX_COMPILATION_CACHE_DIR": "",
    })
    t0 = time.time()
    proc = subprocess.run([sys.executable, "-c", _RESTART_PROBE_SCRIPT],
                          capture_output=True, text=True, env=env,
                          timeout=timeout_s, cwd=os.path.dirname(
                              os.path.abspath(__file__)))
    wall = time.time() - t0
    if proc.returncode != 0:
        return {"error": (proc.stderr or "")[-400:], "subprocess_s": wall}
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    out["subprocess_s"] = round(wall, 3)
    return out


def _run_warmstart_stage(progress: dict) -> dict:
    """The --warmstart stage (round 18, two measurements):

    (1) RESTART-TO-FIRST-PROPOSAL in fresh subprocesses — arm A pays the
    full cold compile on the request path (no persistent cache, no
    prewarm); a prime run populates the persistent cache + shape
    registry; arm B restarts against them with background prewarm and
    measures both the prewarm sweep and the first request after it.

    (2) STEADY-STATE warm vs cold under the round-11 drift twin
    (broker_loss_drift, per-tick detection): identical scenario at one
    seed with ``solver.warm.start.enabled`` flipped. The in-run canary
    HARD-FAILS (vs_baseline=0) on a balancedness or violated-set flip
    between the arms — warm starts must never change solution quality
    beyond the sentry band."""
    import dataclasses as _dc
    import tempfile

    from cruise_control_tpu.testing.simulator import (
        CANONICAL_SCENARIOS, ClusterSimulator,
    )
    from cruise_control_tpu.utils.sensors import SENSORS

    cache_dir = tempfile.mkdtemp(prefix="cc_warmstart_cache_")
    probe_timeout = float(os.environ.get("BENCH_WARMSTART_TIMEOUT_S",
                                         "240"))
    t0 = time.time()
    # Arm A is cold AND prime at once: the cache starts empty, so its
    # first proposal pays the full compile on the request path (cache
    # writes/shape recording are off-path — this IS the cold
    # measurement), while populating the disk cache + shape registry
    # arm B restarts against.
    cold = _restart_probe(cache=True, prewarm=True, wait_prewarm=True,
                          cache_dir=cache_dir, timeout_s=probe_timeout)
    progress["restart_cold"] = cold
    warm = _restart_probe(cache=True, prewarm=True, wait_prewarm=True,
                          cache_dir=cache_dir, timeout_s=probe_timeout)
    progress["restart_warm"] = warm
    restart_s = time.time() - t0

    def _counter(name: str) -> float:
        return SENSORS._counters.get((name, ()), 0.0)

    # (2) the drift twin, cold arm then warm arm.
    spec = _dc.replace(CANONICAL_SCENARIOS["broker_loss_drift"],
                       ticks=WARMSTART_TICKS)
    overrides = {"anomaly.detection.interval.ms": int(spec.tick_s * 1000)}
    # Warm both arms' COMPILES first (discarded run): the wall-clock
    # comparison below must measure warm seeding, not whichever arm
    # happened to pay the jit compiles for the twin's shapes.
    t0 = time.time()
    ClusterSimulator(spec, seed=0, config_overrides=overrides).run()
    progress["twin_compile_warmup_s"] = round(time.time() - t0, 3)
    t0 = time.time()
    cold_run = ClusterSimulator(spec, seed=0, config_overrides=overrides
                                ).run()
    cold_twin_s = time.time() - t0
    seeded0 = _counter("solver_warm_seeded")
    fallback0 = _counter("solver_warm_fallbacks")
    skipped0 = _counter("solver_goals_skipped")
    t0 = time.time()
    warm_run = ClusterSimulator(
        spec, seed=0, config_overrides={
            **overrides, "solver.warm.start.enabled": True}).run()
    warm_twin_s = time.time() - t0

    def _summ(run):
        s = run.score
        return {
            "final_balancedness": s.balancedness[-1] if s.balancedness
            else None,
            "ticks_below_balancedness_slo": s.ticks_below_balancedness_slo,
            "slo_violations": s.slo_violations(),
            "heal_p95_ticks": s.time_to_heal_p95_ticks(),
            "replica_moves": s.replica_moves,
        }

    cold_s, warm_s = _summ(cold_run), _summ(warm_run)

    # Steady-state drift A/B on the BOUNDED dispatch path (the at-scale
    # production path, where per-goal dispatches are the cost the warm
    # seed + fingerprint skip remove; the twin's 6-broker facade runs
    # the fused path, whose on-device skip already hides them): solve a
    # skewed cluster, drift its loads ±5%, then solve the drifted model
    # cold vs warm-seeded from the accepted target.
    import jax.numpy as jnp
    import numpy as _np

    from cruise_control_tpu.analyzer.constraint import OptimizationOptions
    from cruise_control_tpu.analyzer.optimizer import (
        GoalOptimizer, goals_by_priority,
    )
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.model.fixtures import random_cluster
    from cruise_control_tpu.warmstart import WarmSeedStore, apply_seed
    cfg = CruiseControlConfig({"solver.fused.chain.max.brokers": 4})
    optzr = GoalOptimizer(cfg)
    st, meta = random_cluster(
        num_brokers=WARMSTART_BROKERS, num_topics=8,
        num_partitions=WARMSTART_PARTITIONS, rf=2, num_racks=3, seed=7,
        skew_to_first=2.0)
    chain_goals = goals_by_priority(cfg)
    t0 = time.time()
    final1, res1 = optzr.optimizations(st, meta, chain_goals,
                                       OptimizationOptions())
    progress["steady_compile_pass_s"] = round(time.time() - t0, 3)
    store = WarmSeedStore()
    store.store(final1, meta, res1)

    def solve(model, seed=None):
        t = time.time()
        if seed is None:
            f, r = optzr.optimizations(model, meta, chain_goals,
                                       OptimizationOptions())
        else:
            f, r = optzr.optimizations(
                apply_seed(model, seed), meta, chain_goals,
                OptimizationOptions(), initial_state=model)
        return f, r, time.time() - t, optzr.last_dispatch_stats()

    flips_steady: list[str] = []
    # (a) REFRESH: re-solve the UNCHANGED model — the proposal-cache
    # refresh / regeneration case (the precompute loop's every tick when
    # nothing moved). This is where warm seeding collapses the dispatch
    # floor.
    _f, res_rc, refresh_cold_s, stats_rc = solve(st)
    _f, res_rw, refresh_warm_s, stats_rw = solve(st,
                                                 store.match(st, meta))
    if abs(res_rw.balancedness_after - res_rc.balancedness_after) > 0.05 \
            or set(res_rw.violated_goals_after) \
            - set(res_rc.violated_goals_after):
        flips_steady.append(
            f"steady refresh A/B: warm balancedness "
            f"{res_rw.balancedness_after:.3f} vs cold "
            f"{res_rc.balancedness_after:.3f}")
    # (b) DRIFT: the loads move ±5% and the cluster did NOT execute the
    # previous target (the adversarial case for warm seeds — from the
    # old target the chain can converge band-worse). Measured WITH the
    # facade's quality gate: a warm attempt below the seed's accepted
    # band falls back to a counted cold re-solve, so the SERVED quality
    # is gate-protected exactly like production.
    wave = 1.0 + 0.05 * _np.cos(
        _np.arange(st.num_partitions, dtype=_np.float32))
    drifted = _dc.replace(
        st, leader_load=st.leader_load * jnp.asarray(wave)[:, None],
        follower_load=st.follower_load * jnp.asarray(wave)[:, None])
    _f, res_cold, steady_cold_s, stats_cold = solve(drifted)
    seed = store.match(drifted, meta)
    _f, res_attempt, attempt_s, stats_warm = solve(drifted, seed)
    # THE production gate predicate (warmstart.warm_quality_ok) at the
    # configured band — the bench's "SERVED semantics" can never drift
    # from what the facade actually serves.
    from cruise_control_tpu.warmstart import warm_quality_ok
    band = cfg.get_double("solver.warm.start.quality.band")
    steady_fallback = not warm_quality_ok(
        res_attempt, res1.balancedness_after,
        res1.violated_goals_after, band)
    if steady_fallback:
        _f, res_served, fb_s, _stats_fb = solve(drifted)
        steady_warm_s = attempt_s + fb_s
    else:
        res_served = res_attempt
        steady_warm_s = attempt_s
    if abs(res_served.balancedness_after - res_cold.balancedness_after) \
            > 0.05 or set(res_served.violated_goals_after) \
            - set(res_cold.violated_goals_after):
        flips_steady.append(
            f"steady drift A/B (served): warm-path balancedness "
            f"{res_served.balancedness_after:.3f} vs cold "
            f"{res_cold.balancedness_after:.3f}, warm-only violated "
            f"{sorted(set(res_served.violated_goals_after) - set(res_cold.violated_goals_after))}")
    # The in-run canary: the warm arm must not lose balancedness beyond
    # the sentry band nor pick up an SLO violation the cold arm lacks.
    flips: list[str] = []
    if cold_s["final_balancedness"] is not None \
            and warm_s["final_balancedness"] is not None \
            and warm_s["final_balancedness"] \
            < cold_s["final_balancedness"] - 0.05:
        flips.append(
            f"warm final balancedness {warm_s['final_balancedness']} < "
            f"cold {cold_s['final_balancedness']} - 0.05")
    new_slo = sorted(set(warm_s["slo_violations"])
                     - set(cold_s["slo_violations"]))
    if new_slo:
        flips.append(f"warm-only SLO violations: {new_slo}")
    flips.extend(flips_steady)
    # A crashed restart-probe arm is a hard failure, not a row of None
    # cells: the probes exist to exercise exactly the cache/prewarm
    # start_up path a regression there would break.
    for arm, out in (("cold", cold), ("warm", warm)):
        if "error" in out:
            flips.append(f"restart probe {arm} arm failed: "
                         f"{out['error'][:200]}")

    return {
        "metric": "warmstart_always_hot",
        "value": round(warm_twin_s, 3),
        "unit": "s",
        "vs_baseline": 0.0 if flips else 1.0,
        "extras": {
            "canary_flips": flips,
            "restart_cold_first_proposal_s":
                cold.get("process_to_first_proposal_s"),
            "restart_prewarmed_first_proposal_s":
                warm.get("process_to_first_proposal_s"),
            "restart_prewarmed_request_s":
                warm.get("first_proposal_request_s"),
            "restart_prewarm_wait_s": warm.get("prewarm_wait_s"),
            "restart_speedup": round(
                cold["process_to_first_proposal_s"]
                / max(warm.get("first_proposal_request_s") or 1e-9, 1e-9),
                2) if "process_to_first_proposal_s" in cold
            and "first_proposal_request_s" in warm else None,
            "restart_probe_shapes": warm.get("prewarm"),
            "restart_measurement_s": round(restart_s, 3),
            "twin": f"broker_loss_drift@{WARMSTART_TICKS}ticks",
            "twin_cold": cold_s,
            "twin_warm": warm_s,
            "twin_cold_wall_s": round(cold_twin_s, 3),
            "twin_warm_wall_s": round(warm_twin_s, 3),
            "refresh_cold_solve_s": round(refresh_cold_s, 3),
            "refresh_warm_solve_s": round(refresh_warm_s, 3),
            "refresh_warm_speedup": round(refresh_cold_s
                                          / max(refresh_warm_s, 1e-9), 2),
            "refresh_cold_dispatches": stats_rc.get("dispatch_count"),
            "refresh_warm_dispatches": stats_rw.get("dispatch_count"),
            "refresh_warm_goals_skipped": stats_rw.get("goals_skipped", 0),
            "steady_cold_solve_s": round(steady_cold_s, 3),
            "steady_warm_solve_s": round(steady_warm_s, 3),
            "steady_warm_fallback": steady_fallback,
            "steady_warm_attempt_s": round(attempt_s, 3),
            "steady_cold_dispatches": stats_cold.get("dispatch_count"),
            "steady_warm_dispatches": stats_warm.get("dispatch_count"),
            "steady_warm_goals_skipped": stats_warm.get("goals_skipped", 0),
            "steady_balancedness_cold": round(
                res_cold.balancedness_after, 3),
            "steady_balancedness_served": round(
                res_served.balancedness_after, 3),
            "warm_seeded_solves": _counter("solver_warm_seeded") - seeded0,
            "warm_fallbacks": _counter("solver_warm_fallbacks") - fallback0,
            "goals_skipped": _counter("solver_goals_skipped") - skipped0,
            # Sentry canaries come from ONE deterministic arm — the
            # drift A/B's SERVED result (solver byte-determinism at the
            # pinned seed): a chain regression that shifts quality in
            # BOTH twin arms equally passes the in-run A/B canary but
            # still trips these against bench_baseline.json.
            "balancedness_after": round(res_served.balancedness_after, 3),
            "violated_goals_after": sorted(res_served.violated_goals_after),
            "twin_final_balancedness": warm_s["final_balancedness"],
            "solve_wall_clock_s": round(warm_twin_s, 3),
            "measured_layer": "restart: fresh subprocess to first "
                              "proposal (cold vs persistent-cache + "
                              "prewarm); steady state: identical drift "
                              "twin with warm starts flipped; the canary "
                              "compares the two arms in-run",
            **progress,
        },
    }


def _forecast_noop_overhead_ns(iterations: int = 100_000) -> float:
    """Per-call cost of a DISABLED predictive-detector tick (the
    off-means-off guard, same discipline as the tracing span): with
    forecast.enabled=false a tick is one config read and an early
    return — no monitor touch, no model build, no device work."""
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.detector.predictive import (
        PredictiveViolationDetector,
    )
    from cruise_control_tpu.forecast import ForecastEngine
    cfg = CruiseControlConfig({"failed.brokers.file.path": ""})

    class _ExplodingMonitor:  # touched ⇒ the guard is broken
        def __getattr__(self, name):  # pragma: no cover
            raise AssertionError("disabled forecast touched the monitor")

    det = PredictiveViolationDetector(
        cfg, ForecastEngine(cfg, _ExplodingMonitor()), None, lambda a: None)
    t0 = time.perf_counter_ns()
    for _ in range(iterations):
        det.run_once()
    return (time.perf_counter_ns() - t0) / iterations


def _run_forecast_stage(progress: dict) -> dict:
    """The --forecast stage (round 19): proactive vs reactive on the
    diurnal_forecast_capacity twin at the pinned seed. Both arms replay
    the IDENTICAL scenario (same seed, same events, same drift); the
    proactive arm adds the forecaster + the predictive-fix opt-in. The
    judge (all sim-clock-deterministic at the pinned seed):

    - STRICT SLO-violation ticks (trajectory below 99.5): proactive
      must be strictly fewer (the reactive arm's violation window is
      the scenario's point — zero reactive ticks means the scenario
      broke and the stage fails);
    - goal-violation TIME-TO-HEAL (heal ledger, sim seconds): the
      proactive arm prevents the violation, so its worst GOAL_VIOLATION
      heal must beat the reactive arm's (no heals = 0);
    - MOVES-PER-SIMHOUR band: proactive ≤ max(6, 2.5× reactive) — the
      win must not be bought with unbounded churn.

    Any flip hard-fails in-run (vs_baseline=0, the CI FORECAST row);
    balancedness_after/violated_goals_after pin the PROACTIVE arm's
    final picture in bench_baseline.json."""
    from cruise_control_tpu.testing.simulator import (
        CANONICAL_SCENARIOS, ClusterSimulator,
    )
    spec = CANONICAL_SCENARIOS["diurnal_forecast_capacity"]

    def run_arm(overrides):
        sim = ClusterSimulator(spec, seed=FORECAST_SEED,
                               config_overrides=overrides)
        t0 = time.time()
        result = sim.run()
        return sim, result, time.time() - t0

    r_sim, r_res, r_wall = run_arm({})
    progress["reactive_wall_s"] = round(r_wall, 3)
    p_sim, p_res, p_wall = run_arm(dict(FORECAST_OVERRIDES))
    progress["proactive_wall_s"] = round(p_wall, 3)

    def strict_ticks(res):
        return sum(1 for b in res.score.balancedness if b < 99.5)

    def p95(sorted_vals):
        # Same index convention as ScenarioScore.time_to_heal_p95_ticks;
        # no heals = 0 (the proactive arm's win condition).
        if not sorted_vals:
            return 0.0
        return sorted_vals[min(len(sorted_vals) - 1,
                               math.ceil(0.95 * len(sorted_vals)) - 1)]

    r_ticks, p_ticks = strict_ticks(r_res), strict_ticks(p_res)
    r_heals = r_sim.cc.heal_ledger.heal_durations_s("GOAL_VIOLATION")
    p_heals = p_sim.cc.heal_ledger.heal_durations_s("GOAL_VIOLATION")
    r_p95 = p95(r_heals)
    p_p95 = p95(p_heals)
    moves_band = max(6, int(2.5 * r_res.score.replica_moves))
    det = p_sim.cc.predictive_detector.state()

    flips: list[str] = []
    if r_ticks < 1 or not r_heals:
        flips.append(
            f"scenario integrity: reactive arm saw no violation window "
            f"(strict_ticks={r_ticks}, goal_violation_heals={len(r_heals)})")
    if p_ticks >= max(r_ticks, 1):
        flips.append(f"proactive SLO ticks {p_ticks} not better than "
                     f"reactive {r_ticks}")
    if r_heals and p_p95 >= r_p95:
        flips.append(f"proactive goal-violation heal p95 {p_p95}s not "
                     f"better than reactive {r_p95}s")
    if p_res.score.replica_moves > moves_band:
        flips.append(f"proactive moves {p_res.score.replica_moves} "
                     f"outside band {moves_band}")
    if not det["predictionsMade"]:
        flips.append("proactive arm made no prediction")
    def slo_categories(res):
        # ScenarioScore.slo_violations embeds VALUES in each string
        # (time_to_heal_p95=9>6_ticks, balancedness_below_40.0_for_12_
        # ticks): the arms differ by design here, so a same-category
        # violation with a BETTER proactive count must not read as a
        # proactive-only violation. Compare categories, not strings.
        return {v.split("=")[0].split("_below_")[0]
                for v in res.score.slo_violations()}

    new_slo = sorted(slo_categories(p_res) - slo_categories(r_res))
    if new_slo:
        flips.append(f"proactive-only SLO violation categories: {new_slo}")

    final_bal = p_res.score.balancedness[-1] \
        if p_res.score.balancedness else None
    return {
        "metric": "forecast_proactive_vs_reactive",
        "value": round(p_wall, 3),
        "unit": "s",
        "vs_baseline": 0.0 if flips else 1.0,
        "extras": {
            "canary_flips": flips,
            "scenario": f"diurnal_forecast_capacity@seed{FORECAST_SEED}",
            "reactive_slo_ticks": r_ticks,
            "proactive_slo_ticks": p_ticks,
            "reactive_heal_p95_s": r_p95,
            "proactive_heal_p95_s": p_p95,
            "reactive_moves": r_res.score.replica_moves,
            "proactive_moves": p_res.score.replica_moves,
            "moves_band": moves_band,
            "predictions": det,
            "reactive_digest": r_res.assignment_digest,
            "proactive_digest": p_res.assignment_digest,
            "reactive_wall_s": round(r_wall, 3),
            "proactive_wall_s": round(p_wall, 3),
            # Sentry canaries: the PROACTIVE arm's deterministic final
            # picture at the pinned seed (a regression that degrades
            # BOTH arms equally passes the in-run A/B but trips these).
            "balancedness_after": final_bal,
            "violated_goals_after": sorted(
                getattr(p_sim.cc.goal_violation_detector.last_result,
                        "violated_goals_after", []) or []),
            "solve_wall_clock_s": round(p_wall, 3),
            "measured_layer": "two full twin replays (reactive vs "
                              "proactive) judged on sim-clock ticks, "
                              "ledger heal seconds, and the moves band",
            **progress,
        },
    }


def _run_serving_stage(progress: dict) -> dict:
    """Serving front-door stage (round 20): three arms against the REAL
    api (``api.handle`` — the transport-independent surface CI can drive
    without sockets). Parity pre-pass: a fresh solve vs its
    response-cache replay must be byte-identical at two different fleet
    bucket shapes, and concurrent identical requests must resolve to the
    serial body (one solve, N responses). Steady arm: the pinned-seed
    mixed loadgen schedule replayed through the task engine, with the
    schedule digest as the ranked_order hard canary and loose in-run
    SLOs (latency is machine-sensitive — only error/shed rates and
    response-body stability hard-fail). Overload arm: solver admission
    bound 0 must shed every new solve with Retry-After while viewer
    reads keep flowing."""
    import threading

    from cruise_control_tpu.api.server import CruiseControlApi
    from cruise_control_tpu.common.resources import Resource
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.executor.admin import (
        InMemoryAdminBackend, PartitionState,
    )
    from cruise_control_tpu.executor.executor import Executor
    from cruise_control_tpu.facade import CruiseControl
    from cruise_control_tpu.fleet import FleetRegistry, FleetScheduler
    from cruise_control_tpu.monitor import (
        LoadMonitor, StaticCapacityResolver,
    )
    from cruise_control_tpu.monitor.sampling import SyntheticSampler
    from cruise_control_tpu.serving import loadgen

    caps = StaticCapacityResolver({}, {
        Resource.CPU: 100.0, Resource.DISK: 1e7,
        Resource.NW_IN: 1e6, Resource.NW_OUT: 1e6})

    def _parts(brokers, topics, parts):
        out = {}
        for t in range(topics):
            for p in range(parts):
                reps = (brokers[0],
                        brokers[1 + (t + p) % (len(brokers) - 1)])
                out[(f"t{t}", p)] = PartitionState(
                    f"t{t}", p, reps, reps[0], isr=reps)
        return out

    def _config(extra=None):
        return CruiseControlConfig({
            "partition.metrics.window.ms": 1000,
            "num.partition.metrics.windows": 3,
            "min.valid.partition.ratio": 0.0,
            "max.solver.rounds": 30,
            "failed.brokers.file.path": "",
            "solver.partition.bucket.size": 0,
            "solver.broker.bucket.size": 0,
            "fleet.bucket.broker.base": 4,
            "fleet.bucket.partition.base": 16,
            **(extra or {})})

    def _make_cc(config, parts):
        backend = InMemoryAdminBackend(parts.values())
        monitor = LoadMonitor(config, backend,
                              samplers=[SyntheticSampler()],
                              capacity_resolver=caps)
        cc = CruiseControl(config, backend, load_monitor=monitor,
                           executor=Executor(backend, synchronous=True))
        for k in range(1, 4):
            monitor.task_runner.run_sampling_once(end_ms=k * 1000)
        return cc

    flips: list[str] = []
    # SLO engine ON for the steady arm: its false-positive canary (a
    # healthy run must never page). The latency threshold is lifted far
    # above machine noise — the canary judges the burn MACHINERY, not
    # this host's latency.
    base = _config({"slo.enabled": True,
                    "slo.objectives.latency.threshold.seconds": 30.0})
    scheduler = FleetScheduler(starvation_bound_s=30.0)
    registry = FleetRegistry(base_config=base, scheduler=scheduler)
    # alpha pads to bucket (16, 256), gamma to (4, 16): the byte-identity
    # claim is pinned at two genuinely different padded shapes.
    registry.register("alpha", cc=_make_cc(
        base, _parts(tuple(range(16)), 2, 65)))
    registry.register("gamma", cc=_make_cc(
        base, _parts((0, 1, 2, 3), 2, 6)))
    api = CruiseControlApi(registry.get("alpha"), fleet=registry)
    api._async_wait_s = 300
    t_stage0 = time.time()
    report = oreport = None
    coalesced_delta = 0
    attribution: dict = {}
    journey_file = os.environ.get("BENCH_JOURNEY_FILE")
    steady_burns = 0
    try:
        # -- parity pre-pass: cache replay byte-identity at two shapes --
        for cid in ("alpha", "gamma"):
            s1, b1, _h1 = api.handle(
                "GET", "/kafkacruisecontrol/proposals", f"cluster={cid}")
            s2, b2, h2 = api.handle(
                "GET", "/kafkacruisecontrol/proposals", f"cluster={cid}")
            if s1 != 200 or s2 != 200:
                flips.append(f"parity: {cid} proposals statuses "
                             f"({s1}, {s2})")
                continue
            if h2.get("X-Serving-Cache") != "hit":
                flips.append(f"parity: {cid} replay missed the cache")
            if json.dumps(b1, sort_keys=True) != \
                    json.dumps(b2, sort_keys=True):
                flips.append(f"parity: {cid} cache replay not "
                             "byte-identical")
        progress["parity"] = "done"

        # -- coalesce parity: N concurrent identical requests, then one
        # serial cache replay — all bodies must be the SAME bytes (one
        # leader solve; the rest attach in flight or hit the cache).
        api.response_cache.invalidate()
        coalesced0 = api._tasks.coalesced
        conc: list = [None] * 6

        def _req(i):
            conc[i] = api.handle("GET", "/kafkacruisecontrol/proposals",
                                 "cluster=alpha")

        threads = [threading.Thread(target=_req, args=(i,), daemon=True)
                   for i in range(len(conc))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        _s, serial, _h = api.handle(
            "GET", "/kafkacruisecontrol/proposals", "cluster=alpha")
        want = json.dumps(serial, sort_keys=True)
        for i, r in enumerate(conc):
            if r is None or r[0] != 200:
                flips.append(f"parity: concurrent request {i} failed "
                             f"({'hung' if r is None else r[0]})")
            elif json.dumps(r[1], sort_keys=True) != want:
                flips.append(f"parity: concurrent request {i} body "
                             "diverged from the serial replay")
        coalesced_delta = api._tasks.coalesced - coalesced0
        progress["coalesce"] = "done"

        # -- steady arm: the pinned-seed mixed schedule against the
        # real api. The digest is a pure function of the seed — pinned
        # in bench_baseline.json through the ranked_order hard canary.
        api.response_cache.invalidate()
        schedule = loadgen.generate_schedule(
            loadgen.mixed_profile(["alpha", "gamma"]), seed=SERVING_SEED,
            rate_rps=SERVING_RATE_RPS, duration_s=SERVING_DURATION_S)
        sched_digest = loadgen.schedule_digest(schedule)
        progress["schedule_digest"] = sched_digest
        t0 = time.time()
        report = loadgen.run_schedule(
            api, schedule, concurrency=8,
            journey_log=registry.get("alpha").journeys)
        steady_wall = time.time() - t0
        flips.extend(f"steady: {f}" for f in loadgen.slo_violations(
            report, {"max_error_rate": 0.0, "max_shed_rate": 0.0,
                     "min_throughput_rps": 1.0}))
        # Response stability: the load model's generation never moves
        # during the run, so every 200 body a proposals spec produced
        # must be ONE byte pattern (first solve, then replays/joins).
        for name, digs in sorted(report.digests.items()):
            if name.startswith("proposals") and len(digs) > 1:
                flips.append(f"steady: {name} produced {len(digs)} "
                             "distinct response bodies")
        # -- journey attribution canary: >= 95% of the steady-arm
        # request wall must land in NAMED segments across BOTH facades'
        # rings (parity-pass journeys included — coalesce followers
        # attribute their wait as coalesce_wait, never silently).
        from cruise_control_tpu.serving.journey import segment_attribution
        entries = registry.get("alpha").journeys.entries() \
            + registry.get("gamma").journeys.entries()
        attribution = segment_attribution(entries)
        if attribution["journeys"] == 0:
            flips.append("journeys: steady arm recorded no journeys")
        elif attribution["attributed_fraction"] < 0.95:
            flips.append(
                f"journeys: only {attribution['attributed_fraction']:.1%}"
                f" of {attribution['wall_s']:.3f}s request wall "
                "attributed to named segments "
                f"(unattributed {attribution['unattributed_s']:.3f}s)")
        if journey_file:
            try:
                registry.get("alpha").journeys.dump_json(journey_file)
            except Exception:  # noqa: BLE001 — the dump is best-effort
                pass
        # -- SLO false-positive canary: a healthy steady arm must not
        # burn (one detector tick on the live registry raises nothing).
        acc = registry.get("alpha")
        acc.anomaly_detector.run_detector_once(acc.slo_burn_detector)
        steady_burns = acc.slo_burn_detector.state()["burnsRaised"]
        if steady_burns:
            flips.append(f"slo: steady arm raised {steady_burns} "
                         "SLO_BURN anomalies (false positive)")
        progress["steady"] = "done"
    finally:
        api.shutdown()
        scheduler.shutdown()

    # -- overload arm: shed-all solver bound on a solo api (cache and
    # coalescing off so every solver request actually reaches admission).
    # SLO engine + SLO_BURN self-healing ON with a tight shed budget: the
    # sustained shedding must raise EXACTLY ONE burn heal chain (fast AND
    # slow pairs both over threshold), reach fix_started, then clear once
    # recovery traffic dilutes the shed fraction below the thresholds.
    ocfg = _config({"serving.admission.queue.solver.max": 0,
                    "serving.coalesce.enabled": False,
                    "serving.cache.enabled": False,
                    "slo.enabled": True,
                    "slo.objectives.shed.budget": 0.01,
                    "slo.objectives.latency.threshold.seconds": 30.0,
                    "self.healing.enabled": True,
                    "self.healing.slo.burn.enabled": True})
    occ = _make_cc(ocfg, _parts((0, 1, 2, 3), 2, 6))
    oapi = CruiseControlApi(occ)
    oapi._async_wait_s = 300
    slo_burn_chains: list = []
    try:
        oschedule = loadgen.generate_schedule(
            loadgen.mixed_profile(), seed=SERVING_SEED + 5,
            rate_rps=30.0, duration_s=1.0)
        oreport = loadgen.run_schedule(oapi, oschedule, concurrency=4)
        flips.extend(f"overload: {f}" for f in loadgen.slo_violations(
            oreport, {"min_shed": 1, "require_retry_after": True,
                      "max_error_rate": 0.0}))
        # Burn detection + fix dispatch, driven synchronously (the
        # simulator's run_detector_once/drain discipline — no threads).
        occ.anomaly_detector.run_detector_once(occ.slo_burn_detector)
        occ.anomaly_detector.drain_anomalies()
        raised = occ.slo_burn_detector.state()["burnsRaised"]
        if raised != 1:
            flips.append(f"slo: overload arm raised {raised} SLO_BURN "
                         "anomalies; expected exactly 1 (shed burn)")
        # Recovery: enough healthy viewer reads to pull the shed
        # fraction back under BOTH burn thresholds, then one more
        # detector tick must clear the standing burn.
        for _ in range(220):
            oapi.handle("GET", "/kafkacruisecontrol/state", "")
        occ.anomaly_detector.run_detector_once(occ.slo_burn_detector)
        slo_burn_chains = occ.heal_ledger.chains(anomaly_type="SLO_BURN")
        if len(slo_burn_chains) != 1:
            flips.append(f"slo: {len(slo_burn_chains)} SLO_BURN heal "
                         "chains; expected exactly 1")
        else:
            chain = slo_burn_chains[0]
            phases = {p["phase"] for p in chain["phases"]}
            if "fix_started" not in phases:
                flips.append("slo: the burn chain never reached "
                             f"fix_started (phases {sorted(phases)})")
            if chain["outcome"] != "cleared":
                flips.append("slo: the burn chain did not clear after "
                             f"load dropped (outcome {chain['outcome']})")
    finally:
        oapi.shutdown()
    progress["overload"] = "done"

    wall = time.time() - t_stage0
    steady = report.to_dict() if report is not None else {}
    return {
        "metric": "serving_loadgen_mixed",
        "value": round(steady_wall, 3),
        "unit": "s",
        "vs_baseline": 0.0 if flips else 1.0,
        "extras": {
            "canary_flips": flips,
            # The schedule digest rides the sentry's ranked_order hard
            # canary: same seed ⇒ byte-identical arrival schedule, so a
            # flip means the loadgen's determinism contract broke.
            "ranked_order": [f"serving:sched:{sched_digest}"],
            "seed": SERVING_SEED,
            "steady_report": steady,
            "steady_wall_s": round(steady_wall, 3),
            "coalesced_in_parity_pass": coalesced_delta,
            "overload_report":
                oreport.to_dict() if oreport is not None else {},
            "attribution": attribution,
            "journey_file": journey_file,
            "steady_slo_burns": steady_burns,
            "overload_slo_burn_chains": [
                {"chainId": c["chainId"], "outcome": c["outcome"],
                 "timeToStartFixMs": c["timeToStartFixMs"]}
                for c in slo_burn_chains],
            "stage_wall_s": round(wall, 3),
            "solve_wall_clock_s": round(steady_wall, 3),
            "measured_layer": "parity pre-pass (cache + coalesce "
                              "byte-identity at two bucket shapes), the "
                              "pinned-seed mixed loadgen replay, and the "
                              "shed-all overload arm, all through the "
                              "real api.handle surface",
            **progress,
        },
    }


def _fleet_twin_scenario_record() -> dict:
    """The fleet_megabatch twin scenario (testing/fleet_twin.py) as a
    SCENARIO_MATRIX row: two drifting clusters sharing one bucket, both
    self-healing a broker loss while their precomputes flow through
    megabatched solves (slo_violations includes a no-batched-solves
    guard, so a silent fallback to solo precomputes fails the matrix)."""
    from cruise_control_tpu.testing.fleet_twin import run_fleet_megabatch
    r = run_fleet_megabatch(seed=SCENARIO_SEED,
                            ticks=SCENARIO_TICKS or None)
    wall = r.pop("wall_s")
    return {
        "metric": "scenario_fleet_megabatch",
        "value": wall,
        "unit": "s",
        "vs_baseline": 0.0 if r["slo_violations"] else 1.0,
        "extras": r,
    }


_QUANTILE_SPANS = ("analyzer.optimize", "goal.solve", "model.assemble",
                   "monitor.aggregate", "analyzer.proposal_diff")


def _span_histogram_snapshots() -> dict:
    from cruise_control_tpu.utils.sensors import SENSORS
    return {s: SENSORS.histogram_snapshot("trace_span_seconds",
                                          labels={"span": s})
            for s in _QUANTILE_SPANS}


def _span_quantile_extras(baseline: dict) -> dict:
    """p50/p99 per key pipeline stage from the trace_span_seconds
    histograms, diffed against the snapshot taken at STAGE START so each
    stage's columns reflect only its own observations (a cumulative read
    would let an early fast stage mask a later stage's tail)."""
    from cruise_control_tpu.utils.sensors import bucket_quantile
    p50, p99 = {}, {}
    for span, after in _span_histogram_snapshots().items():
        if after is None:
            continue
        counts = list(after["counts"])
        before = baseline.get(span)
        if before is not None:
            counts = [a - b for a, b in zip(counts, before["counts"])]
        q50 = bucket_quantile(after["buckets"], counts, 0.50)
        if q50 is None:
            continue
        p50[span] = round(q50, 4)
        p99[span] = round(bucket_quantile(after["buckets"], counts, 0.99), 4)
    return {"span_p50_s": p50, "span_p99_s": p99}


def _run_stage(jax, num_brokers: int, num_partitions: int, drain: int,
               device: str, on_cpu: bool, progress: dict) -> dict:
    from cruise_control_tpu.analyzer.optimizer import (
        GoalOptimizer, goals_by_priority,
    )
    from cruise_control_tpu.common.broker_state import BrokerState
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.model.fixtures import Dist, random_cluster
    from cruise_control_tpu.model.tensors import set_broker_state

    # CPU (ambient or fallback) is scored on the same 8-chip parity basis so
    # the vs_baseline ratio means the same thing across devices.
    chips = 8 if on_cpu else jax.device_count()
    budget_s = 30.0 * (num_partitions / 1_000_000) * (8.0 / min(chips, 8))

    t0 = time.time()
    state, meta = random_cluster(
        num_brokers=num_brokers, num_topics=max(8, num_brokers // 10),
        num_partitions=num_partitions, rf=3, num_racks=8,
        dist=Dist.EXPONENTIAL, seed=42, skew_to_first=2.0,
        target_utilization=0.55)
    if drain:
        # BASELINE config #4: drain the last N brokers (RemoveBrokers
        # semantics — mark DEAD, facade.py:308: every hosted replica is
        # offline and must be re-placed elsewhere).
        import jax.numpy as jnp
        state = set_broker_state(
            state, jnp.arange(num_brokers - drain, num_brokers),
            BrokerState.DEAD)
    state = jax.device_put(state)
    jax.block_until_ready(state.assignment)
    build_s = time.time() - t0
    progress["model_build_s"] = round(build_s, 3)

    from cruise_control_tpu.utils.tracing import TRACER
    spans_before = TRACER.spans_closed
    hist_baseline = _span_histogram_snapshots()

    cfg = CruiseControlConfig()
    # The solver mesh spans every available chip (single-chip TPU tunnel →
    # mesh None → single-device fused chain kernel).
    optimizer = GoalOptimizer(cfg, mesh="auto")

    # Warm-up pass: compiles the fused whole-chain kernel (ONE compilation
    # — analyzer/chain.py chain_optimize_full, or its sharded analogue —
    # cached across runs via the persistent cache).
    t0 = time.time()
    _, warm = optimizer.optimizations(state, meta,
                                      goals=goals_by_priority(cfg))
    warm_s = time.time() - t0
    progress["warmup_incl_compile_s"] = round(warm_s, 3)

    # Steady-state pass from the original (skewed) state: kernels hot.
    t0 = time.time()
    _, result = optimizer.optimizations(state, meta,
                                        goals=goals_by_priority(cfg))
    steady_s = time.time() - t0
    progress["steady_s"] = round(steady_s, 3)
    # Megastep dispatch accounting for the steady pass: how many XLA
    # executions the solve cost and the median rounds each carried (the
    # link-latency amortization the megastep path exists for).
    dispatch_stats = optimizer.last_dispatch_stats()
    progress.update(dispatch_stats)

    # Incremental model pipeline probe (cold rebuild vs. warm refresh) —
    # capped at the acceptance scale; the synthetic partition-table setup
    # is itself O(P) host work and the 1M stage's answer is the same.
    pipeline_extras = {}
    if num_partitions <= 100_000 and not drain:
        pipeline_extras = _model_pipeline_probe(num_brokers, num_partitions)
        progress.update(pipeline_extras)

    name = f"rebalance_proposal_wall_clock_{num_brokers}brokers_" \
        + (f"{num_partitions // 1000}kpartitions"
           if num_partitions >= 1000 else f"{num_partitions}partitions") \
        + (f"_drain{drain}" if drain else "")
    return {
        "metric": name,
        "value": round(steady_s, 3),
        "unit": "s",
        "vs_baseline": round(budget_s / steady_s, 3),
        "extras": {
            # Per-stage stamp from the live backend, not the probe label:
            # a mid-bench fallback must not let later stages claim the
            # probed platform (VERDICT r3 weak #1).
            "device": jax.devices()[0].platform,
            "resolved_device": device,
            "solver_devices": optimizer.solver_devices(),
            "model_build_s": round(build_s, 3),
            "warmup_incl_compile_s": round(warm_s, 3),
            "compile_overhead_s": round(max(0.0, warm_s - steady_s), 3),
            "num_proposals": len(result.proposals),
            "balancedness_before": round(result.balancedness_before, 2),
            "balancedness_after": round(result.balancedness_after, 2),
            "violated_goals_after": result.violated_goals_after,
            "goal_durations_steady_s": {
                g.name: round(g.duration_s, 4) for g in result.goal_results},
            "budget_s_prorated": round(budget_s, 3),
            "solve_wall_clock_s": round(steady_s, 3),
            "dispatch_count": dispatch_stats.get("dispatch_count", 0),
            "rounds_per_dispatch_p50": dispatch_stats.get(
                "rounds_per_dispatch_p50", 0.0),
            "donated_dispatches": dispatch_stats.get("donated_dispatches", 0),
            "trace_span_count": TRACER.spans_closed - spans_before,
            **_span_quantile_extras(hist_baseline),
            **pipeline_extras,
        },
    }


def _run_redteam_stage(progress: dict, budget_s: float | None = None) -> dict:
    """The --redteam stage (round 22): pinned regression replays of the
    committed frontier + a budget-bounded fresh mining sweep.

    Phase 1 replays the committed frontier's worst entries full-loop
    (``replay_entry`` — the exact recipe the miner stamped) and compares
    the rendered SLO verdict set against the entry's pin: a FLIP
    hard-fails the stage (vs_baseline=0). The score-JSON digest ride
    along per entry (digest_match) — byte drift without a verdict flip
    is reported, not gated, because verdict stability is the contract
    serving depends on.

    Phase 2 runs ``mine()`` fresh at CI scale under the caller's wall
    budget (the miner itself never reads the clock — bench passes
    ``time.monotonic``), writes the mined frontier JSON to
    BENCH_REDTEAM_FILE for the artifact bundle, and reports the margin
    histogram, blind-spot count, and how many mined entries got UNDER
    the canonical library's minimum margin (the committed frontier
    carries the library map so the stage never pays for the canonical
    replays itself)."""
    import zlib

    from cruise_control_tpu.redteam import (
        load_frontier, mine, replay_entry, save_frontier,
    )
    from cruise_control_tpu.utils.slo import scenario_margin

    committed_path = os.environ.get("BENCH_REDTEAM_FRONTIER",
                                    "fileStore/redteam_frontier.json")
    committed = load_frontier(committed_path)
    progress["redteam_committed_frontier"] = committed is not None

    # Phase 1: pinned regression replays (worst margin first — the
    # committed frontier is already sorted that way).
    t0 = time.time()
    replayed, flips = [], []
    for entry in ((committed or {}).get("frontier") or [])[:REDTEAM_REPLAYS]:
        result = replay_entry(entry)
        margin = round(scenario_margin(result.score.slo_margins()), 6)
        digest = f"{zlib.crc32(result.score.to_json().encode()):08x}"
        flip = sorted(result.score.slo_violations()) \
            != sorted(entry.get("sloViolations", []))
        if flip:
            flips.append(entry.get("id"))
        replayed.append({
            "id": entry.get("id"),
            "margin_pin": entry.get("margin"), "margin": margin,
            "digest_pin": entry.get("scoreDigest"), "digest": digest,
            "digest_match": digest == entry.get("scoreDigest"),
            "verdict_flip": flip})
    replay_s = round(time.time() - t0, 3)
    progress["redteam_pinned_replays"] = len(replayed)
    progress["redteam_replay_s"] = replay_s

    # Phase 2: a fresh CI-scale sweep under the remaining wall budget.
    library = ((committed or {}).get("library") or {}).get("margins")
    t0 = time.time()
    mined = mine(
        REDTEAM_SEED, population=REDTEAM_POP,
        generations=REDTEAM_GENERATIONS, survivors=REDTEAM_SURVIVORS,
        frontier_size=REDTEAM_POP, ticks=REDTEAM_TICKS,
        eval_budget=REDTEAM_EVAL_BUDGET, library=library,
        budget_s=(None if budget_s is None
                  else max(30.0, budget_s - replay_s)),
        clock=time.monotonic)
    mine_s = round(time.time() - t0, 3)
    redteam_file = os.environ.get("BENCH_REDTEAM_FILE",
                                  "/tmp/cc_bench_redteam_frontier.json")
    save_frontier(mined, redteam_file)

    margins = [e["margin"] for e in mined["frontier"]]
    histogram = {
        "violating(<0)": sum(1 for m in margins if m < 0),
        "near(0..0.1)": sum(1 for m in margins if 0 <= m < 0.1),
        "tight(0.1..0.5)": sum(1 for m in margins if 0.1 <= m < 0.5),
        "comfortable(>=0.5)": sum(1 for m in margins if m >= 0.5),
    }
    return {
        "metric": "redteam_mine",
        "value": mine_s,
        "unit": "s",
        # Hard gate: any pinned replay whose SLO verdict set flipped.
        "vs_baseline": 0.0 if flips else 1.0,
        "extras": {
            "pinned_replays": len(replayed),
            "verdict_flips": flips,
            "pinned_replay_detail": replayed,
            "replay_s": replay_s,
            "sweep_seed": REDTEAM_SEED,
            "generations_run": mined["generationsRun"],
            "evals": mined["evals"],
            "replays": mined["replays"],
            "partial": mined["partial"],
            "partial_reason": mined["partialReason"],
            "frontier_entries": len(mined["frontier"]),
            "frontier_margin_min": min(margins) if margins else None,
            "margin_histogram": histogram,
            "blind_spot_count": mined["blindSpotCount"],
            "found_below_library": mined["foundBelowLibrary"],
            "library_min_margin": (min(library.values())
                                   if library else None),
            "redteam_file": redteam_file,
            "committed_frontier": committed_path
            if committed is not None else None,
            **progress,
        },
    }


def main() -> int:
    if FLEETSHARD_CHILD:
        # The --fleet-shard subprocess body: no watchdog, no device
        # probe — the parent owns the budget and set the env (JAX must
        # init from the forced-device-count XLA_FLAGS untouched).
        return _run_fleet_shard_child()
    deadline = time.time() + BUDGET_S
    # Two-tier watchdog: SIGALRM interrupts Python-level code gracefully,
    # but a wedged TPU call blocks inside native code where the handler
    # never runs — the daemon timer backstop hard-exits (results so far
    # are already printed and flushed line-by-line).
    import threading

    def _hard_exit():
        _emit_summary_tail()
        os._exit(0)

    backstop = threading.Timer(BUDGET_S + 30.0, _hard_exit)
    backstop.daemon = True
    backstop.start()
    signal.signal(signal.SIGALRM, _alarm)
    signal.alarm(int(BUDGET_S))
    try:
        return _guarded_main(deadline)
    except _Watchdog:
        return 0
    finally:
        signal.alarm(0)
        backstop.cancel()
        _emit_summary_tail()


def _guarded_main(deadline: float) -> int:
    t0 = time.time()
    platform = _probe_device(deadline)
    if platform is None:
        # The TPU tunnel never came up — first-class failure mode, not an
        # excuse to print nothing. Fall back to host CPU.
        os.environ["PALLAS_AXON_POOL_IPS"] = ""
        os.environ["JAX_PLATFORMS"] = "cpu"
        device = "cpu_fallback(tpu_unreachable)"
    else:
        device = platform

    import jax

    from cruise_control_tpu import enable_persistent_compile_cache
    cache_dir = enable_persistent_compile_cache()
    if platform is None:
        jax.config.update("jax_platforms", "cpu")
    n_dev = jax.device_count()

    # Tracing + XLA telemetry for the whole run: every optimizer pass
    # records a span tree (JSONL-dumped for the CI artifact) and every
    # XLA compile lands in the shape-labeled histograms the per-stage
    # p50/p99 extras read. The disabled-path overhead is measured and
    # emitted FIRST so a tracing hot-path regression fails loudly.
    from cruise_control_tpu.utils.tracing import TRACER
    from cruise_control_tpu.utils.xla_telemetry import install as _xla_install
    trace_file = os.environ.get("BENCH_TRACE_FILE",
                                "/tmp/cc_bench_trace.jsonl")
    try:  # a stale dump must not accrete across runs
        os.unlink(trace_file)
    except OSError:
        pass
    TRACER.configure(enabled=True, jsonl_path=trace_file)
    _xla_install()
    if SCENARIO_MODE:
        # Scenario matrix replaces the perf stages AND the overhead
        # probes: the whole budget belongs to the digital twin (each
        # scenario.run span still lands in the JSONL artifact).
        _emit({"metric": "bench_bootstrap",
               "value": round(time.time() - t0, 3), "unit": "s",
               "vs_baseline": 1.0,
               "extras": {"device": device, "num_devices": n_dev,
                          "mode": "scenarios",
                          "scenario_seed": SCENARIO_SEED,
                          "scenario_ticks": SCENARIO_TICKS or "spec",
                          "compile_cache_dir": cache_dir,
                          "trace_file": trace_file,
                          "stderr_file": _stderr_path}})
        return _run_scenario_matrix(deadline)
    if FLEET_MODE:
        _emit({"metric": "bench_bootstrap",
               "value": round(time.time() - t0, 3), "unit": "s",
               "vs_baseline": 1.0,
               "extras": {"device": device, "num_devices": n_dev,
                          "mode": "fleet", "clusters": FLEET_K,
                          "compile_cache_dir": cache_dir,
                          "stderr_file": _stderr_path}})
        try:
            _emit(_run_fleet_stage({}))
        except Exception as e:  # noqa: BLE001 — parseable record always
            _emit({"metric": "stage_failed", "value": 0.0, "unit": "s",
                   "vs_baseline": 0.0,
                   "extras": {"stage": "fleet_megabatch",
                              "error": f"{type(e).__name__}: {e}"[:500]}})
        return 0
    if FLEETSHARD_MODE:
        _emit({"metric": "bench_bootstrap",
               "value": round(time.time() - t0, 3), "unit": "s",
               "vs_baseline": 1.0,
               "extras": {"device": device, "num_devices": n_dev,
                          "mode": "fleet_shard",
                          "virtual_devices": FLEETSHARD_DEVICES,
                          "clusters": FLEETSHARD_CLUSTERS,
                          "per_device_occupancy": FLEETSHARD_OCCUPANCY,
                          "compile_cache_dir": cache_dir,
                          "stderr_file": _stderr_path}})
        try:
            record = _run_fleet_shard_stage(
                {}, budget_s=deadline - time.time() - 30.0)
            _emit(record)
            baseline = load_baseline()
            if baseline is not None:
                verdict = compare_stage_to_baseline(record, baseline)
                if verdict is not None:
                    _emit(verdict)
        except Exception as e:  # noqa: BLE001 — parseable record always
            _emit({"metric": "stage_failed", "value": 0.0, "unit": "s",
                   "vs_baseline": 0.0,
                   "extras": {"stage": "fleet_shard",
                              "error": f"{type(e).__name__}: {e}"[:500]}})
        return 0
    if FUTURES_MODE:
        _emit({"metric": "bench_bootstrap",
               "value": round(time.time() - t0, 3), "unit": "s",
               "vs_baseline": 1.0,
               "extras": {"device": device, "num_devices": n_dev,
                          "mode": "futures", "futures": FUTURES_N,
                          "compile_cache_dir": cache_dir,
                          "stderr_file": _stderr_path}})
        try:
            _emit(_run_futures_stage({}))
        except Exception as e:  # noqa: BLE001 — parseable record always
            _emit({"metric": "stage_failed", "value": 0.0, "unit": "s",
                   "vs_baseline": 0.0,
                   "extras": {"stage": "futures_compare",
                              "error": f"{type(e).__name__}: {e}"[:500]}})
        return 0
    if DIRECT_MODE:
        _emit({"metric": "bench_bootstrap",
               "value": round(time.time() - t0, 3), "unit": "s",
               "vs_baseline": 1.0,
               "extras": {"device": device, "num_devices": n_dev,
                          "mode": "direct", "brokers": DIRECT_BROKERS,
                          "partitions": DIRECT_PARTITIONS,
                          "compile_cache_dir": cache_dir,
                          "stderr_file": _stderr_path}})
        try:
            _emit(_run_direct_stage({}))
        except Exception as e:  # noqa: BLE001 — parseable record always
            _emit({"metric": "stage_failed", "value": 0.0, "unit": "s",
                   "vs_baseline": 0.0,
                   "extras": {"stage": "direct_vs_greedy",
                              "error": f"{type(e).__name__}: {e}"[:500]}})
        return 0
    if TRANSPORT_MODE:
        _emit({"metric": "bench_bootstrap",
               "value": round(time.time() - t0, 3), "unit": "s",
               "vs_baseline": 1.0,
               "extras": {"device": device, "num_devices": n_dev,
                          "mode": "transport",
                          "brokers": TRANSPORT_BROKERS,
                          "partitions": TRANSPORT_PARTITIONS,
                          "topics": TRANSPORT_TOPICS,
                          "compile_cache_dir": cache_dir,
                          "stderr_file": _stderr_path}})
        try:
            record = _run_transport_stage({})
            _emit(record)
            baseline = load_baseline()
            if baseline is not None:
                verdict = compare_stage_to_baseline(record, baseline)
                if verdict is not None:
                    _emit(verdict)
        except Exception as e:  # noqa: BLE001 — parseable record always
            _emit({"metric": "stage_failed", "value": 0.0, "unit": "s",
                   "vs_baseline": 0.0,
                   "extras": {"stage": "transport_sparse_tr",
                              "error": f"{type(e).__name__}: {e}"[:500]}})
        return 0
    if WARMSTART_MODE:
        _emit({"metric": "bench_bootstrap",
               "value": round(time.time() - t0, 3), "unit": "s",
               "vs_baseline": 1.0,
               "extras": {"device": device, "num_devices": n_dev,
                          "mode": "warmstart",
                          "brokers": WARMSTART_BROKERS,
                          "partitions": WARMSTART_PARTITIONS,
                          "compile_cache_dir": cache_dir,
                          "stderr_file": _stderr_path}})
        try:
            record = _run_warmstart_stage({})
            _emit(record)
            baseline = load_baseline()
            if baseline is not None:
                verdict = compare_stage_to_baseline(record, baseline)
                if verdict is not None:
                    _emit(verdict)
        except Exception as e:  # noqa: BLE001 — parseable record always
            _emit({"metric": "stage_failed", "value": 0.0, "unit": "s",
                   "vs_baseline": 0.0,
                   "extras": {"stage": "warmstart_always_hot",
                              "error": f"{type(e).__name__}: {e}"[:500]}})
        return 0
    if FORECAST_MODE:
        _emit({"metric": "bench_bootstrap",
               "value": round(time.time() - t0, 3), "unit": "s",
               "vs_baseline": 1.0,
               "extras": {"device": device, "num_devices": n_dev,
                          "mode": "forecast", "seed": FORECAST_SEED,
                          "compile_cache_dir": cache_dir,
                          "stderr_file": _stderr_path}})
        try:
            record = _run_forecast_stage({})
            _emit(record)
            baseline = load_baseline()
            if baseline is not None:
                verdict = compare_stage_to_baseline(record, baseline)
                if verdict is not None:
                    _emit(verdict)
        except Exception as e:  # noqa: BLE001 — parseable record always
            _emit({"metric": "stage_failed", "value": 0.0, "unit": "s",
                   "vs_baseline": 0.0,
                   "extras": {"stage": "forecast_proactive_vs_reactive",
                              "error": f"{type(e).__name__}: {e}"[:500]}})
        return 0
    if SERVING_MODE:
        _emit({"metric": "bench_bootstrap",
               "value": round(time.time() - t0, 3), "unit": "s",
               "vs_baseline": 1.0,
               "extras": {"device": device, "num_devices": n_dev,
                          "mode": "serving", "seed": SERVING_SEED,
                          "rate_rps": SERVING_RATE_RPS,
                          "duration_s": SERVING_DURATION_S,
                          "compile_cache_dir": cache_dir,
                          "stderr_file": _stderr_path}})
        try:
            record = _run_serving_stage({})
            _emit(record)
            baseline = load_baseline()
            if baseline is not None:
                verdict = compare_stage_to_baseline(record, baseline)
                if verdict is not None:
                    _emit(verdict)
        except Exception as e:  # noqa: BLE001 — parseable record always
            _emit({"metric": "stage_failed", "value": 0.0, "unit": "s",
                   "vs_baseline": 0.0,
                   "extras": {"stage": "serving_loadgen_mixed",
                              "error": f"{type(e).__name__}: {e}"[:500]}})
        return 0
    if REDTEAM_MODE:
        _emit({"metric": "bench_bootstrap",
               "value": round(time.time() - t0, 3), "unit": "s",
               "vs_baseline": 1.0,
               "extras": {"device": device, "num_devices": n_dev,
                          "mode": "redteam", "sweep_seed": REDTEAM_SEED,
                          "compile_cache_dir": cache_dir,
                          "stderr_file": _stderr_path}})
        try:
            record = _run_redteam_stage({}, budget_s=deadline - time.time()
                                        - 30.0)
            _emit(record)
            baseline = load_baseline()
            if baseline is not None:
                verdict = compare_stage_to_baseline(record, baseline)
                if verdict is not None:
                    _emit(verdict)
        except Exception as e:  # noqa: BLE001 — parseable record always
            _emit({"metric": "stage_failed", "value": 0.0, "unit": "s",
                   "vs_baseline": 0.0,
                   "extras": {"stage": "redteam_mine",
                              "error": f"{type(e).__name__}: {e}"[:500]}})
        return 0
    noop_ns = _tracing_noop_overhead_ns()
    _emit({"metric": "tracing_noop_span_overhead", "value": round(noop_ns, 1),
           "unit": "ns", "vs_baseline": 1.0,
           "extras": {"trace_file": trace_file,
                      "guard": "disabled tracing must stay sub-microsecond "
                               "per call (nothing on the solver hot path)"}})
    res_ns = _resilience_noop_overhead_ns()
    _emit({"metric": "resilience_noop_overhead", "value": round(res_ns, 1),
           "unit": "ns", "vs_baseline": 1.0,
           "extras": {"guard": "resilience wrapper with retries disabled "
                               "must stay ns-scale (same no-op discipline "
                               "as tracing)"}})
    flight_ns = _flight_recorder_noop_overhead_ns()
    _emit({"metric": "flight_recorder_noop_overhead",
           "value": round(flight_ns, 1), "unit": "ns", "vs_baseline": 1.0,
           "extras": {"guard": "disabled flight recorder must stay ns-scale "
                               "per record site (shared no-op hooks, same "
                               "guard as tracing_noop_span_overhead)"}})
    heal_ns = _heal_ledger_noop_overhead_ns()
    _emit({"metric": "heal_ledger_noop_overhead",
           "value": round(heal_ns, 1), "unit": "ns", "vs_baseline": 1.0,
           "extras": {"guard": "disabled heal ledger must stay ns-scale "
                               "per phase transition (shared NO_HEAL "
                               "handle, same guard family as the flight "
                               "recorder)"}})
    forecast_ns = _forecast_noop_overhead_ns()
    _emit({"metric": "forecast_noop_overhead",
           "value": round(forecast_ns, 1), "unit": "ns", "vs_baseline": 1.0,
           "extras": {"guard": "forecast.enabled=false must make a "
                               "predictive-detector tick one config read "
                               "(off means off: no monitor touch, no "
                               "model build, no device work)"}})
    journey_ns = _journey_noop_overhead_ns()
    _emit({"metric": "journey_noop_overhead",
           "value": round(journey_ns, 1), "unit": "ns", "vs_baseline": 1.0,
           "extras": {"guard": "disabled journey log must stay ns-scale "
                               "per stamp site (shared NO_JOURNEY handle, "
                               "same guard family as the heal ledger)"}})
    slo_ns = _slo_noop_overhead_ns()
    _emit({"metric": "slo_noop_overhead",
           "value": round(slo_ns, 1), "unit": "ns", "vs_baseline": 1.0,
           "extras": {"guard": "slo.enabled=false must make every record "
                               "probe one attribute check + early return "
                               "(off means off on the front-door path)"}})
    try:
        ring = _flight_ring_overhead_probe()
        _emit({"metric": "flight_ring_overhead",
               "value": ring["recording_overhead_ms_per_round"],
               "unit": "ms", "vs_baseline": 1.0,
               "extras": {**ring,
                          "guard": "per-round cost of the RECORDING move "
                                   "kernel vs plain (recording is "
                                   "default-on; the noop guard only "
                                   "covers the disabled hooks)"}})
    except Exception as e:  # noqa: BLE001 — a probe failure must not
        # cost the stages their budget
        _emit({"metric": "stage_failed", "value": 0.0, "unit": "s",
               "vs_baseline": 0.0,
               "extras": {"stage": "flight_ring_overhead_probe",
                          "error": f"{type(e).__name__}: {e}"[:300]}})
    degraded = _degraded_cycle_probe()
    _emit({"metric": "degraded_cycle_s",
           "value": degraded["degraded_cycle_s"], "unit": "s",
           "vs_baseline": 1.0, "extras": degraded})

    _emit({"metric": "bench_bootstrap", "value": round(time.time() - t0, 3),
           "unit": "s", "vs_baseline": 1.0,
           "extras": {"device": device, "num_devices": n_dev,
                      "compile_cache_dir": cache_dir,
                      "trace_file": trace_file,
                      "stderr_file": _stderr_path}})

    baseline = load_baseline()
    sentry_verdicts: list[dict] = []
    stages = STAGES[:2] if os.environ.get("BENCH_SCALE") == "small" else STAGES
    prev_total = 0.0
    for i, (num_brokers, num_partitions, drain) in enumerate(stages):
        remaining = deadline - time.time()
        # A stage costs roughly: build + compile (flat, shapes change) +
        # steady (scales). Skip if the remaining budget clearly can't fit
        # ~4x the previous stage (compile dominates and is ~flat).
        if prev_total and remaining < min(4.0 * prev_total, BUDGET_S / 2) + 30:
            break
        if remaining < 60:
            break
        # Per-stage prorated deadline (BENCH_r05: one slow stage must not
        # ride the global budget into an external rc=124 kill): split the
        # remaining budget across the remaining stages proportional to
        # partition count (≈ cost), floored so small stages always get
        # room for their flat compile overhead.
        weights = [p for _b, p, _d in stages[i:]]
        stage_budget = min(remaining - 30.0,
                           max(90.0, remaining * weights[0] / sum(weights)))
        stage_name = f"{num_brokers}b_{num_partitions}p" \
            + (f"_drain{drain}" if drain else "")
        progress: dict = {}
        t0 = time.time()
        signal.alarm(max(1, int(stage_budget)))
        try:
            record = _run_stage(jax, num_brokers, num_partitions, drain,
                                device,
                                on_cpu=platform is None or platform == "cpu",
                                progress=progress)
            # Disarm BEFORE emitting: an alarm landing mid-_emit would
            # record the same stage as both completed and partial.
            signal.alarm(0)
            _emit(record)
            if baseline is not None:
                verdict = compare_stage_to_baseline(record, baseline)
                if verdict is not None:
                    sentry_verdicts.append(verdict)
                    _emit(verdict)
        except _Watchdog:
            # Stage deadline expired: emit the phases it DID finish as a
            # partial record and move on — a stage capped by the proration
            # FLOOR (e.g. a cold compile cache on a small stage) must not
            # discard later stages that still have real budget.
            _emit({"metric": f"stage_partial_{stage_name}", "value": round(
                time.time() - t0, 3), "unit": "s", "vs_baseline": 0.0,
                "extras": {"stage": stage_name, "partial": True,
                           "stage_budget_s": round(stage_budget, 1),
                           **progress}})
            prev_total = time.time() - t0
            continue
        except Exception as e:  # noqa: BLE001 — a dead stage must still
            # leave a parseable record (e.g. the TPU worker being killed at
            # scale); the device is likely gone, so stop rather than hang
            # the remaining stages on a dead tunnel.
            _emit({"metric": "stage_failed", "value": round(
                time.time() - t0, 3), "unit": "s", "vs_baseline": 0.0,
                "extras": {"stage": stage_name,
                           "error": f"{type(e).__name__}: {e}"[:500],
                           **progress}})
            _emit_sentry_summary(sentry_verdicts, baseline)
            _dump_flight_recorder()
            return 0
        finally:
            signal.alarm(0)
        prev_total = time.time() - t0
    # The megabatch fleet stage rides every default pass (cheap, CI-scale
    # shapes) so the MEGABATCH summary row and the regression sentry see
    # batched throughput + per-cluster balancedness on every run.
    remaining = deadline - time.time()
    if remaining > 90:
        progress: dict = {}
        t0 = time.time()
        signal.alarm(max(1, int(min(remaining - 15.0, 300.0))))
        try:
            record = _run_fleet_stage(progress)
            signal.alarm(0)
            _emit(record)
            if baseline is not None:
                verdict = compare_stage_to_baseline(record, baseline)
                if verdict is not None:
                    sentry_verdicts.append(verdict)
                    _emit(verdict)
        except _Watchdog:
            _emit({"metric": "stage_partial_fleet_megabatch",
                   "value": round(time.time() - t0, 3), "unit": "s",
                   "vs_baseline": 0.0,
                   "extras": {"stage": "fleet_megabatch", "partial": True,
                              **progress}})
        except Exception as e:  # noqa: BLE001 — parseable record always
            _emit({"metric": "stage_failed", "value": round(
                time.time() - t0, 3), "unit": "s", "vs_baseline": 0.0,
                "extras": {"stage": "fleet_megabatch",
                           "error": f"{type(e).__name__}: {e}"[:500],
                           **progress}})
        finally:
            signal.alarm(0)
    else:
        _emit({"metric": "stage_partial_fleet_megabatch", "value": 0.0,
               "unit": "s", "vs_baseline": 0.0,
               "extras": {"stage": "fleet_megabatch", "partial": True,
                          "skipped": True, "reason": "budget exhausted"}})
    # The futures stage rides every default pass too (round 15): the CI
    # FUTURES row, the parity pin, and the ranked-order canary see it
    # per-PR without a separate invocation.
    remaining = deadline - time.time()
    if remaining > 90:
        progress = {}
        t0 = time.time()
        signal.alarm(max(1, int(min(remaining - 15.0, 300.0))))
        try:
            record = _run_futures_stage(progress)
            signal.alarm(0)
            _emit(record)
            if baseline is not None:
                verdict = compare_stage_to_baseline(record, baseline)
                if verdict is not None:
                    sentry_verdicts.append(verdict)
                    _emit(verdict)
        except _Watchdog:
            _emit({"metric": "stage_partial_futures_compare",
                   "value": round(time.time() - t0, 3), "unit": "s",
                   "vs_baseline": 0.0,
                   "extras": {"stage": "futures_compare", "partial": True,
                              **progress}})
        except Exception as e:  # noqa: BLE001 — parseable record always
            _emit({"metric": "stage_failed", "value": round(
                time.time() - t0, 3), "unit": "s", "vs_baseline": 0.0,
                "extras": {"stage": "futures_compare",
                           "error": f"{type(e).__name__}: {e}"[:500],
                           **progress}})
        finally:
            signal.alarm(0)
    else:
        _emit({"metric": "stage_partial_futures_compare", "value": 0.0,
               "unit": "s", "vs_baseline": 0.0,
               "extras": {"stage": "futures_compare", "partial": True,
                          "skipped": True, "reason": "budget exhausted"}})
    # The heal-ledger stage rides every default pass too (round 16): the
    # CI HEAL row and the sentry's heal_p50/p99 warn-bands see the
    # twin-driven time-to-heal per PR, and the ledger dump lands in the
    # observability artifact bundle (BENCH_HEAL_FILE).
    remaining = deadline - time.time()
    if remaining > 60:
        progress = {}
        t0 = time.time()
        signal.alarm(max(1, int(min(remaining - 15.0, 240.0))))
        try:
            record = _run_heal_stage(progress)
            signal.alarm(0)
            _emit(record)
            if baseline is not None:
                verdict = compare_stage_to_baseline(record, baseline)
                if verdict is not None:
                    sentry_verdicts.append(verdict)
                    _emit(verdict)
        except _Watchdog:
            _emit({"metric": "stage_partial_heal_broker_loss_drift",
                   "value": round(time.time() - t0, 3), "unit": "s",
                   "vs_baseline": 0.0,
                   "extras": {"stage": "heal_broker_loss_drift",
                              "partial": True, **progress}})
        except Exception as e:  # noqa: BLE001 — parseable record always
            _emit({"metric": "stage_failed", "value": round(
                time.time() - t0, 3), "unit": "s", "vs_baseline": 0.0,
                "extras": {"stage": "heal_broker_loss_drift",
                           "error": f"{type(e).__name__}: {e}"[:500],
                           **progress}})
        finally:
            signal.alarm(0)
    else:
        _emit({"metric": "stage_partial_heal_broker_loss_drift",
               "value": 0.0, "unit": "s", "vs_baseline": 0.0,
               "extras": {"stage": "heal_broker_loss_drift", "partial": True,
                          "skipped": True, "reason": "budget exhausted"}})
    # The direct-assignment stage rides every default pass too (round
    # 17): the CI DIRECT row sees the count-goal direct-vs-greedy wall,
    # the O(few)-dispatch claim, and the balancedness/violated-goal
    # canary per PR without a separate invocation.
    remaining = deadline - time.time()
    if remaining > 120:
        progress = {}
        t0 = time.time()
        signal.alarm(max(1, int(min(remaining - 15.0, 300.0))))
        try:
            record = _run_direct_stage(progress)
            signal.alarm(0)
            _emit(record)
            if baseline is not None:
                verdict = compare_stage_to_baseline(record, baseline)
                if verdict is not None:
                    sentry_verdicts.append(verdict)
                    _emit(verdict)
        except _Watchdog:
            _emit({"metric": "stage_partial_direct_vs_greedy",
                   "value": round(time.time() - t0, 3), "unit": "s",
                   "vs_baseline": 0.0,
                   "extras": {"stage": "direct_vs_greedy", "partial": True,
                              **progress}})
        except Exception as e:  # noqa: BLE001 — parseable record always
            _emit({"metric": "stage_failed", "value": round(
                time.time() - t0, 3), "unit": "s", "vs_baseline": 0.0,
                "extras": {"stage": "direct_vs_greedy",
                           "error": f"{type(e).__name__}: {e}"[:500],
                           **progress}})
        finally:
            signal.alarm(0)
    else:
        _emit({"metric": "stage_partial_direct_vs_greedy", "value": 0.0,
               "unit": "s", "vs_baseline": 0.0,
               "extras": {"stage": "direct_vs_greedy", "partial": True,
                          "skipped": True, "reason": "budget exhausted"}})
    # The always-hot stage rides every default pass too (round 18): the
    # CI WARMSTART row sees restart-to-first-proposal (cold vs
    # prewarmed) and the warm-vs-cold drift-twin canary per PR without a
    # separate invocation.
    remaining = deadline - time.time()
    if remaining > 120:
        progress = {}
        t0 = time.time()
        signal.alarm(max(1, int(min(remaining - 15.0, 600.0))))
        try:
            record = _run_warmstart_stage(progress)
            signal.alarm(0)
            _emit(record)
            if baseline is not None:
                verdict = compare_stage_to_baseline(record, baseline)
                if verdict is not None:
                    sentry_verdicts.append(verdict)
                    _emit(verdict)
        except _Watchdog:
            _emit({"metric": "stage_partial_warmstart_always_hot",
                   "value": round(time.time() - t0, 3), "unit": "s",
                   "vs_baseline": 0.0,
                   "extras": {"stage": "warmstart_always_hot",
                              "partial": True, **progress}})
        except Exception as e:  # noqa: BLE001 — parseable record always
            _emit({"metric": "stage_failed", "value": round(
                time.time() - t0, 3), "unit": "s", "vs_baseline": 0.0,
                "extras": {"stage": "warmstart_always_hot",
                           "error": f"{type(e).__name__}: {e}"[:500],
                           **progress}})
        finally:
            signal.alarm(0)
    else:
        _emit({"metric": "stage_partial_warmstart_always_hot", "value": 0.0,
               "unit": "s", "vs_baseline": 0.0,
               "extras": {"stage": "warmstart_always_hot", "partial": True,
                          "skipped": True, "reason": "budget exhausted"}})
    # The forecast stage rides every default pass too (round 19): the CI
    # FORECAST row sees the proactive-vs-reactive twin A/B — SLO ticks,
    # ledger heal seconds, moves band — per PR without a separate
    # invocation.
    remaining = deadline - time.time()
    if remaining > 60:
        progress = {}
        t0 = time.time()
        signal.alarm(max(1, int(min(remaining - 15.0, 300.0))))
        try:
            record = _run_forecast_stage(progress)
            signal.alarm(0)
            _emit(record)
            if baseline is not None:
                verdict = compare_stage_to_baseline(record, baseline)
                if verdict is not None:
                    sentry_verdicts.append(verdict)
                    _emit(verdict)
        except _Watchdog:
            _emit({"metric": "stage_partial_forecast_proactive_vs_reactive",
                   "value": round(time.time() - t0, 3), "unit": "s",
                   "vs_baseline": 0.0,
                   "extras": {"stage": "forecast_proactive_vs_reactive",
                              "partial": True, **progress}})
        except Exception as e:  # noqa: BLE001 — parseable record always
            _emit({"metric": "stage_failed", "value": round(
                time.time() - t0, 3), "unit": "s", "vs_baseline": 0.0,
                "extras": {"stage": "forecast_proactive_vs_reactive",
                           "error": f"{type(e).__name__}: {e}"[:500],
                           **progress}})
        finally:
            signal.alarm(0)
    else:
        _emit({"metric": "stage_partial_forecast_proactive_vs_reactive",
               "value": 0.0, "unit": "s", "vs_baseline": 0.0,
               "extras": {"stage": "forecast_proactive_vs_reactive",
                          "partial": True, "skipped": True,
                          "reason": "budget exhausted"}})
    # The serving stage rides every default pass too (round 20): the CI
    # SERVING row sees cache/coalesce byte-identity at two bucket shapes,
    # the pinned-seed loadgen schedule digest, and the overload-sheds-
    # with-Retry-After contract per PR without a separate invocation.
    remaining = deadline - time.time()
    if remaining > 60:
        progress = {}
        t0 = time.time()
        signal.alarm(max(1, int(min(remaining - 15.0, 300.0))))
        try:
            record = _run_serving_stage(progress)
            signal.alarm(0)
            _emit(record)
            if baseline is not None:
                verdict = compare_stage_to_baseline(record, baseline)
                if verdict is not None:
                    sentry_verdicts.append(verdict)
                    _emit(verdict)
        except _Watchdog:
            _emit({"metric": "stage_partial_serving_loadgen_mixed",
                   "value": round(time.time() - t0, 3), "unit": "s",
                   "vs_baseline": 0.0,
                   "extras": {"stage": "serving_loadgen_mixed",
                              "partial": True, **progress}})
        except Exception as e:  # noqa: BLE001 — parseable record always
            _emit({"metric": "stage_failed", "value": round(
                time.time() - t0, 3), "unit": "s", "vs_baseline": 0.0,
                "extras": {"stage": "serving_loadgen_mixed",
                           "error": f"{type(e).__name__}: {e}"[:500],
                           **progress}})
        finally:
            signal.alarm(0)
    else:
        _emit({"metric": "stage_partial_serving_loadgen_mixed",
               "value": 0.0, "unit": "s", "vs_baseline": 0.0,
               "extras": {"stage": "serving_loadgen_mixed",
                          "partial": True, "skipped": True,
                          "reason": "budget exhausted"}})
    # The sparse-transport stage rides every default pass too (round
    # 21): the CI TRANSPORT row sees the TR greedy-vs-direct wall,
    # rounds, and residual at the 1.5-replicas-per-cell geometry plus
    # the balancedness/violated-goal canary per PR without a separate
    # invocation.
    remaining = deadline - time.time()
    if remaining > 120:
        progress = {}
        t0 = time.time()
        signal.alarm(max(1, int(min(remaining - 15.0, 300.0))))
        try:
            record = _run_transport_stage(progress)
            signal.alarm(0)
            _emit(record)
            if baseline is not None:
                verdict = compare_stage_to_baseline(record, baseline)
                if verdict is not None:
                    sentry_verdicts.append(verdict)
                    _emit(verdict)
        except _Watchdog:
            _emit({"metric": "stage_partial_transport_sparse_tr",
                   "value": round(time.time() - t0, 3), "unit": "s",
                   "vs_baseline": 0.0,
                   "extras": {"stage": "transport_sparse_tr",
                              "partial": True, **progress}})
        except Exception as e:  # noqa: BLE001 — parseable record always
            _emit({"metric": "stage_failed", "value": round(
                time.time() - t0, 3), "unit": "s", "vs_baseline": 0.0,
                "extras": {"stage": "transport_sparse_tr",
                           "error": f"{type(e).__name__}: {e}"[:500],
                           **progress}})
        finally:
            signal.alarm(0)
    else:
        _emit({"metric": "stage_partial_transport_sparse_tr",
               "value": 0.0, "unit": "s", "vs_baseline": 0.0,
               "extras": {"stage": "transport_sparse_tr",
                          "partial": True, "skipped": True,
                          "reason": "budget exhausted"}})
    # The red-team stage rides every default pass too (round 22): the CI
    # RED_TEAM row sees the pinned frontier replays (SLO verdict flips
    # hard-fail) plus a budget-bounded fresh mining sweep whose frontier
    # JSON lands in the observability artifact bundle per PR.
    remaining = deadline - time.time()
    if remaining > 90:
        progress = {}
        t0 = time.time()
        stage_budget = min(remaining - 15.0, 300.0)
        signal.alarm(max(1, int(stage_budget)))
        try:
            record = _run_redteam_stage(progress, budget_s=stage_budget)
            signal.alarm(0)
            _emit(record)
            if baseline is not None:
                verdict = compare_stage_to_baseline(record, baseline)
                if verdict is not None:
                    sentry_verdicts.append(verdict)
                    _emit(verdict)
        except _Watchdog:
            _emit({"metric": "stage_partial_redteam_mine",
                   "value": round(time.time() - t0, 3), "unit": "s",
                   "vs_baseline": 0.0,
                   "extras": {"stage": "redteam_mine", "partial": True,
                              **progress}})
        except Exception as e:  # noqa: BLE001 — parseable record always
            _emit({"metric": "stage_failed", "value": round(
                time.time() - t0, 3), "unit": "s", "vs_baseline": 0.0,
                "extras": {"stage": "redteam_mine",
                           "error": f"{type(e).__name__}: {e}"[:500],
                           **progress}})
        finally:
            signal.alarm(0)
    else:
        _emit({"metric": "stage_partial_redteam_mine",
               "value": 0.0, "unit": "s", "vs_baseline": 0.0,
               "extras": {"stage": "redteam_mine", "partial": True,
                          "skipped": True, "reason": "budget exhausted"}})
    # The fleet-shard stage rides every default pass too (round 23): the
    # CI FLEET_SHARD row sees the N-virtual-device clusters/s scaling
    # and the cross-arm byte-parity pin per PR without a separate
    # invocation (the measurement itself lives in a fresh subprocess —
    # the forced host device count is a process-level XLA init flag).
    remaining = deadline - time.time()
    if remaining > 120:
        progress = {}
        t0 = time.time()
        stage_budget = min(remaining - 15.0, 420.0)
        signal.alarm(max(1, int(stage_budget)))
        try:
            record = _run_fleet_shard_stage(progress,
                                            budget_s=stage_budget - 10.0)
            signal.alarm(0)
            _emit(record)
            if baseline is not None:
                verdict = compare_stage_to_baseline(record, baseline)
                if verdict is not None:
                    sentry_verdicts.append(verdict)
                    _emit(verdict)
        except _Watchdog:
            _emit({"metric": "stage_partial_fleet_shard",
                   "value": round(time.time() - t0, 3), "unit": "s",
                   "vs_baseline": 0.0,
                   "extras": {"stage": "fleet_shard", "partial": True,
                              **progress}})
        except Exception as e:  # noqa: BLE001 — parseable record always
            _emit({"metric": "stage_failed", "value": round(
                time.time() - t0, 3), "unit": "s", "vs_baseline": 0.0,
                "extras": {"stage": "fleet_shard",
                           "error": f"{type(e).__name__}: {e}"[:500],
                           **progress}})
        finally:
            signal.alarm(0)
    else:
        _emit({"metric": "stage_partial_fleet_shard",
               "value": 0.0, "unit": "s", "vs_baseline": 0.0,
               "extras": {"stage": "fleet_shard", "partial": True,
                          "skipped": True, "reason": "budget exhausted"}})
    _emit_sentry_summary(sentry_verdicts, baseline)
    _dump_flight_recorder()
    return 0


def _dump_flight_recorder() -> None:
    """Write every retained flight-recorder pass to BENCH_FLIGHT_FILE (CI
    uploads it next to the trace JSONL): the per-PR record of what the
    bench's solves actually did — acceptance densities, kill attribution,
    per-round violation trajectories — so a sentry warn/fail comes with
    its own diagnosis attached."""
    flight_file = os.environ.get("BENCH_FLIGHT_FILE",
                                 "/tmp/cc_bench_flight.json")
    try:
        from cruise_control_tpu.utils.flight_recorder import FLIGHT
        n = FLIGHT.dump_json(flight_file)
        _emit({"metric": "flight_recorder_dump", "value": float(n),
               "unit": "passes", "vs_baseline": 1.0,
               "extras": {"flight_file": flight_file}})
    except Exception:  # noqa: BLE001 — the dump is best-effort
        pass


if __name__ == "__main__":
    sys.exit(main())
