"""Driver benchmark: full rebalance-proposal generation wall-clock.

Config #3 of BASELINE.md: synthetic 1,000 brokers / 100k partitions, the
full default goal chain (hard capacity + rack-aware goals, then the soft
distribution goals), skewed initial placement so there is real work.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
``value`` is the steady-state wall-clock (seconds) of a full
GoalOptimizer.optimizations() pass — model already resident on device,
kernels compiled (the deployment steady state: the reference keeps a warm
JVM + proposal precompute pool for the same reason, GoalOptimizer.java:112).
``vs_baseline`` is the ratio of the scale-prorated north-star budget to the
measured value (>1 = faster than budget): BASELINE.md's target is a full
proposal for 7,000 brokers / 1M partitions in <30 s on v5e-8; this config is
1/10 of that partition count on one chip, so budget = 30 s × (100k/1M) ×
(8 chips / 1 chip) = 24 s.

Extra keys (informational): compile+first-run time, proposal count,
balancedness score before/after (SURVEY.md §A.4), per-goal rounds.
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/cc_tpu_jax_cache")


def main() -> None:
    import jax

    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer, goals_by_priority
    from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
    from cruise_control_tpu.model.fixtures import Dist, random_cluster

    small = os.environ.get("BENCH_SCALE") == "small"
    num_brokers = 50 if small else 1000
    num_partitions = 2_000 if small else 100_000
    budget_s = (30.0 * (num_partitions / 1_000_000) * 8.0)

    t0 = time.time()
    state, meta = random_cluster(
        num_brokers=num_brokers, num_topics=max(8, num_brokers // 10),
        num_partitions=num_partitions, rf=3, num_racks=8,
        dist=Dist.EXPONENTIAL, seed=42, skew_to_first=2.0,
        target_utilization=0.55)
    state = jax.device_put(state)
    jax.block_until_ready(state.assignment)
    build_s = time.time() - t0

    cfg = CruiseControlConfig()
    optimizer = GoalOptimizer(cfg)
    goals = goals_by_priority(cfg)

    # Warm-up pass: compiles every goal kernel (cached across runs via the
    # persistent compilation cache) and returns the optimized state.
    t0 = time.time()
    _, warm = optimizer.optimizations(state, meta, goals=goals)
    warm_s = time.time() - t0

    # Steady-state pass from the original (skewed) state: all kernels hot.
    goals2 = goals_by_priority(cfg)
    t0 = time.time()
    _, result = optimizer.optimizations(state, meta, goals=goals2)
    steady_s = time.time() - t0

    print(json.dumps({
        "metric": f"rebalance_proposal_wall_clock_{num_brokers}brokers_"
                  f"{num_partitions // 1000}kpartitions",
        "value": round(steady_s, 3),
        "unit": "s",
        "vs_baseline": round(budget_s / steady_s, 3),
        "extras": {
            "device": str(jax.devices()[0]),
            "model_build_s": round(build_s, 3),
            "warmup_incl_compile_s": round(warm_s, 3),
            "num_proposals": len(result.proposals),
            "balancedness_before": round(result.balancedness_before, 2),
            "balancedness_after": round(result.balancedness_after, 2),
            "violated_goals_before": result.violated_goals_before,
            "violated_goals_after": result.violated_goals_after,
            "budget_s_prorated": budget_s,
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
