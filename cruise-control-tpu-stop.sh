#!/usr/bin/env bash
# Stop cruise-control-tpu (reference parity: kafka-cruise-control-stop.sh).
set -euo pipefail
base_dir=$(dirname "$0")
pidfile="$base_dir/fileStore/cruise-control-tpu.pid"
if [[ -f "$pidfile" ]]; then
  kill "$(cat "$pidfile")" 2>/dev/null || true
  rm -f "$pidfile"
  echo "stopped"
else
  pkill -f "cruise_control_tpu.api.app" || echo "no running instance found"
fi
