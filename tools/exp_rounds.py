"""Per-goal round/moves/wall-clock breakdown at a given scale (host CPU).

Experiment harness for round-count work: prints one JSON line per goal plus
a summary line, so grid/width changes can be validated (rounds down, quality
pinned) before touching defaults.

    JAX_PLATFORMS=cpu python tools/exp_rounds.py [brokers] [partitions] [drain]
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/cc_tpu_jax_cache")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    num_brokers = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
    num_partitions = int(sys.argv[2]) if len(sys.argv) > 2 else 100_000
    drain = int(sys.argv[3]) if len(sys.argv) > 3 else 0
    import jax

    from cruise_control_tpu import enable_persistent_compile_cache
    enable_persistent_compile_cache()
    from cruise_control_tpu.analyzer.optimizer import (
        GoalOptimizer, goals_by_priority,
    )
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.model.fixtures import Dist, random_cluster

    state, meta = random_cluster(
        num_brokers=num_brokers, num_topics=max(8, num_brokers // 10),
        num_partitions=num_partitions, rf=3, num_racks=8,
        dist=Dist.EXPONENTIAL, seed=42, skew_to_first=2.0,
        target_utilization=0.55)
    if drain:
        import jax.numpy as jnp

        from cruise_control_tpu.common.broker_state import BrokerState
        from cruise_control_tpu.model.tensors import set_broker_state
        state = set_broker_state(
            state, jnp.arange(num_brokers - drain, num_brokers),
            BrokerState.DEAD)
    state = jax.device_put(state)
    jax.block_until_ready(state.assignment)

    overrides = json.loads(os.environ.get("EXP_CONFIG", "{}"))
    cfg = CruiseControlConfig(overrides)
    optimizer = GoalOptimizer(cfg, mesh="auto")
    t0 = time.time()
    _, warm = optimizer.optimizations(state, meta,
                                      goals=goals_by_priority(cfg))
    warm_s = time.time() - t0
    t0 = time.time()
    _, res = optimizer.optimizations(state, meta,
                                     goals=goals_by_priority(cfg))
    steady_s = time.time() - t0
    for g in res.goal_results:
        print(json.dumps({"goal": g.name, "rounds": g.rounds,
                          "moves": g.moves_applied, "swaps": g.swaps_applied,
                          "duration_s": round(g.duration_s, 3),
                          "violation": round(g.residual_violation, 4)}),
              flush=True)
    print(json.dumps({
        "steady_s": round(steady_s, 3), "warm_s": round(warm_s, 3),
        "total_rounds": sum(g.rounds for g in res.goal_results),
        "total_moves": sum(g.moves_applied for g in res.goal_results),
        "num_proposals": len(res.proposals),
        "balancedness_after": round(res.balancedness_after, 2),
        "violated_goals_after": res.violated_goals_after,
        "overrides": overrides}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
