"""Shared preamble for the offline diagnostic tools.

Every tool in this directory needs the same three lines before it can
import the package from a source checkout: a persistent compile-cache
dir (so repeated diagnostic runs skip recompiles), the repo root on
``sys.path``, and the cache-enable call once jax is importable. They
were copy-pasted four times; this module is the one place they live.

Usage (first import in each tool, before any ``cruise_control_tpu``
import)::

    import _common  # noqa: F401  (side effects: sys.path + cache dir)
    ...
    _common.enable_cache()        # after this, import the package
"""

from __future__ import annotations

import os
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/cc_tpu_jax_cache")
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))


def enable_cache() -> str | None:
    """Enable the host-fingerprinted persistent compile cache (imports
    jax, so call it where the tool is ready to pay backend init)."""
    from cruise_control_tpu import enable_persistent_compile_cache
    return enable_persistent_compile_cache()
