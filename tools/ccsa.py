"""ccsa CLI — the repo's invariant lint gate (docs/STATIC_ANALYSIS.md).

Usage (from the repo root)::

    python -m tools.ccsa                      # lint the default tree
    python -m tools.ccsa path/to/file.py      # lint specific paths
    python -m tools.ccsa --format=json        # machine output
    python -m tools.ccsa --format=github      # ::error annotations + job
                                              # summary table (CI gate)
    python -m tools.ccsa --rules CCSA004,CCSA007 paths...
    python -m tools.ccsa --write-baseline     # accept current findings
    python -m tools.ccsa --list-rules
    python -m tools.ccsa --list-suppressions  # every documented tolerance

Exit codes: 0 = clean (no new findings), 1 = new findings, 2 = usage or
internal error. Runs before pyflakes in CI; the committed baseline
(.ccsa-baseline.json) is kept EMPTY by policy — fix or suppress with
``# ccsa: ok[RULE] reason`` instead of baselining.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from cruise_control_tpu.lint import (  # noqa: E402
    all_rules, build_contexts, collect_files, iter_suppressions,
    load_baseline, run_lint, write_baseline,
)
from cruise_control_tpu.lint.core import (  # noqa: E402
    DEFAULT_BASELINE, DEFAULT_PATHS, fingerprint,
)


def _counts_table(result) -> str:
    lines = ["| rule | new | baselined | suppressed |",
             "|---|---|---|---|"]
    counts = result.counts()
    for rule_id, row in counts.items():
        lines.append(f"| {rule_id} | {row['new']} | {row['baselined']} | "
                     f"{row['suppressed']} |")
    if not counts:
        lines.append("| (none) | 0 | 0 | 0 |")
    total_new = len(result.new) + len(result.errors)
    lines.append(f"\nCCSA={'FAILED' if result.failed else 'PASSED'} "
                 f"({result.files_scanned} files, {total_new} new, "
                 f"{len(result.baselined)} baselined, "
                 f"{len(result.suppressed)} suppressed)")
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.ccsa",
        description="cruise-control-tpu invariant linter")
    ap.add_argument("paths", nargs="*",
                    help=f"files/dirs to lint (default: {DEFAULT_PATHS})")
    ap.add_argument("--format", choices=("human", "json", "github"),
                    default="human")
    ap.add_argument("--root", default=str(REPO_ROOT),
                    help="repo root for relative paths + doc rules")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: <root>/{DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline entirely")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept all current findings into the baseline")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--list-suppressions", action="store_true",
                    help="enumerate every documented `# ccsa: ok[...]` "
                         "tolerance in the tree")
    args = ap.parse_args(argv)

    root = pathlib.Path(args.root).resolve()
    if args.list_rules:
        for rule_id, rule in all_rules().items():
            print(f"{rule_id}  {rule.title}")
        return 0

    paths = args.paths or list(DEFAULT_PATHS)
    if args.list_suppressions:
        ctxs, _ = build_contexts(collect_files(paths, root), root)
        for s in iter_suppressions(ctxs):
            rules = ",".join(s.rules)
            print(f"{s.path}:{s.line}: ok[{rules}] {s.reason}")
        return 0

    baseline_path = pathlib.Path(args.baseline) if args.baseline \
        else root / DEFAULT_BASELINE
    baseline = set() if args.no_baseline else load_baseline(baseline_path)
    rules = [r.strip() for r in args.rules.split(",") if r.strip()] \
        if args.rules else None

    try:
        result = run_lint(paths, root=root, rules=rules, baseline=baseline)
    except Exception as exc:  # internal error must not pass as clean
        print(f"ccsa: internal error: {type(exc).__name__}: {exc}",
              file=sys.stderr)
        return 2

    if args.write_baseline:
        by_rel = {c.rel: c for c in result.contexts}
        # New AND still-present previously-baselined findings: rewriting
        # the file must never un-accept a prior acceptance.
        fps = {fingerprint(f, by_rel[f.path].line_text(f.line)
                           if f.path in by_rel else "")
               for f in result.new + result.baselined
               if f.rule != "CCSA000"}
        if args.paths:
            # Scoped run: out-of-scope files were never linted, so their
            # accepted fingerprints must carry over untouched — only a
            # FULL default-tree run may shrink the baseline.
            fps |= baseline
        write_baseline(baseline_path, fps)
        print(f"wrote {len(fps)} fingerprints to {baseline_path}")
        return 0

    rc = 1 if result.failed else 0
    try:
        _report(args, result)
    except BrokenPipeError:
        # Downstream (`| head`) closed the pipe mid-print: the VERDICT is
        # already computed and must survive — only the output is lost.
        devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(devnull, sys.stdout.fileno())
    return rc


def _report(args, result) -> None:
    reportable = result.errors + result.new + result.baselined
    if args.format == "json":
        print(json.dumps({
            "failed": result.failed,
            "files_scanned": result.files_scanned,
            "counts": result.counts(),
            "findings": [f.as_dict() for f in reportable],
            "suppressed": [f.as_dict() for f in result.suppressed],
        }, indent=2))
    elif args.format == "github":
        for f in result.errors + result.new:
            print(f"::error file={f.path},line={max(f.line, 1)},"
                  f"title={f.rule}::{f.message}")
        for f in result.baselined:
            print(f"::warning file={f.path},line={max(f.line, 1)},"
                  f"title={f.rule} (baselined)::{f.message}")
        summary = os.environ.get("GITHUB_STEP_SUMMARY")
        table = "### CCSA invariant lint\n\n" + _counts_table(result) + "\n"
        if summary:
            with open(summary, "a") as fh:
                fh.write(table)
        else:
            print(table)
    else:
        for f in result.errors + result.new:
            print(f"{f.path}:{f.line}: {f.rule} {f.message}")
        for f in result.baselined:
            print(f"{f.path}:{f.line}: {f.rule} [baselined] {f.message}")
        print()
        print(_counts_table(result))


if __name__ == "__main__":
    try:
        rc = main()
    except BrokenPipeError:
        # Pipe closed before the lint even reported (e.g. --list-* piped
        # to head): no verdict was lost, exit clean.
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        rc = 0
    raise SystemExit(rc)
