"""Diagnose TopicReplicaDistribution's accepted-moves-per-round density.

Runs the chain up to (but not including) TopicReplica with the per-goal
chain kernels, then single-steps TR rounds and histograms where the 2048
candidate slots go: invalid cards, vetoed by which prior goal's
acceptance, lost to the active goal's non-positive improvement, dropped
by per-partition dedup, or rejected by the joint recheck.

Before the greedy rounds it prints the SPARSE-PLAN attribution (round
21): the fractional per-cell shed/fill targets of the direct transport,
the rounding outcome per plane (systematic randomized rounding under the
crc32 seed), and — for one live transport sweep — how many planned
movers rank-filled a destination vs died to a feasibility veto
(stranded). This is the column to read when the sparse-regime transport
under-delivers: a large fractional mass with a small rounded plan means
the margin knob (solver.direct.sparse.margin.frac) is starving the
fill; a large planned-vs-applied gap means the guard set is vetoing the
plan and the polish will inherit the residue.

    JAX_PLATFORMS=cpu python tools/diag_tr_density.py [brokers] [partitions] [rounds]
"""

from __future__ import annotations

import sys
import time

import _common


def main() -> int:
    num_brokers = int(sys.argv[1]) if len(sys.argv) > 1 else 2000
    num_partitions = int(sys.argv[2]) if len(sys.argv) > 2 else 200_000
    diag_rounds = int(sys.argv[3]) if len(sys.argv) > 3 else 3
    import jax
    import jax.numpy as jnp
    import numpy as np

    _common.enable_cache()
    from cruise_control_tpu.analyzer.chain import optimize_goal_in_chain
    from cruise_control_tpu.analyzer.constraint import BalancingConstraint
    from cruise_control_tpu.analyzer.optimizer import (
        GoalOptimizer, goals_by_priority,
    )
    from cruise_control_tpu.analyzer.search import (
        ExclusionMasks, score_round_candidates, reduce_per_source,
        cumulative_select, apply_selected,
    )
    from cruise_control_tpu.analyzer.candidates import compute_deltas
    from cruise_control_tpu.analyzer.fill import targets_enabled
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.model.fixtures import Dist, random_cluster

    state, meta = random_cluster(
        num_brokers=num_brokers, num_topics=max(8, num_brokers // 10),
        num_partitions=num_partitions, rf=3, num_racks=8,
        dist=Dist.EXPONENTIAL, seed=42, skew_to_first=2.0,
        target_utilization=0.55)
    cfg = CruiseControlConfig()
    opt = GoalOptimizer(cfg)
    goals = tuple(goals_by_priority(cfg))
    constraint = BalancingConstraint.from_config(cfg)

    scfg = opt.search_config(state)
    wide = opt._widen(scfg, num_brokers)
    masks = ExclusionMasks()
    tr_idx = next(i for i, g in enumerate(goals)
                  if g.name == "TopicReplicaDistributionGoal")

    t0 = time.time()
    for i in range(tr_idx):
        state, info = optimize_goal_in_chain(
            state, goals, i, constraint,
            wide if goals[i].prefers_wide_batches else scfg,
            meta.num_topics, masks)
    print(f"pre-TR chain done in {time.time() - t0:.1f}s", flush=True)

    goal = goals[tr_idx]
    prior = tuple(goals[:tr_idx])

    # --- sparse-plan attribution (round 21) -----------------------------
    # The direct transport's view of the same instant: fractional
    # targets, their rounding outcome, and rank-fill vs veto kill for
    # one live sweep.
    from cruise_control_tpu.analyzer import direct as direct_mod
    from cruise_control_tpu.analyzer.derived import compute_derived

    if direct_mod.direct_eligible(goals, tr_idx):
        derived = compute_derived(state)
        aux = direct_mod.goal_aux(goal, state, derived, constraint,
                                  meta.num_topics)
        cnt, lower, upper, _grp, _mv = goal.direct_spec(
            state, derived, constraint, aux, meta.num_topics)
        cnt = np.asarray(cnt, dtype=np.float64)
        lower = np.asarray(jnp.broadcast_to(lower, cnt.shape), np.float64)
        upper = np.asarray(jnp.broadcast_to(upper, cnt.shape), np.float64)
        alive = np.asarray(derived.alive)
        margin_frac = 0.25
        width = np.maximum(upper - lower, 0.0)
        margin = width * margin_frac
        hi_t = np.maximum(upper - margin, lower)
        lo_t = np.minimum(lower + np.maximum(margin, 0.5), hi_t)
        over = alive[None, :] & (cnt > upper + 1e-6)
        under = alive[None, :] & (cnt < lower - 1e-6)
        sur_frac = np.where(over, np.maximum(cnt - hi_t, 0.0), 0.0)
        head_frac = np.where(alive[None, :],
                             np.maximum(lo_t - np.maximum(cnt, lower), 0.0),
                             0.0)
        sur, defi, headr = (np.asarray(x) for x in direct_mod._surplus_deficit(
            jnp.asarray(cnt, jnp.float32), jnp.asarray(lower, jnp.float32),
            jnp.asarray(upper, jnp.float32), derived.alive,
            derived.allowed_replica_move & derived.alive))
        dens = cnt.sum() / max(float(alive.sum()) * cnt.shape[0], 1.0)
        print(f"--- sparse plan: {cnt.shape[0]} groups x {cnt.shape[1]} "
              f"brokers, {dens:.2f} replicas/cell "
              f"(retired-gate regime: {'SPARSE' if dens < 4.0 else 'dense'})")
        print(f"    cells over band {int(over.sum())}, under band "
              f"{int(under.sum())}")
        print(f"    fractional target mass: shed {sur_frac.sum():.1f} "
              f"fill-headroom {head_frac.sum():.1f}")
        print(f"    rounded plan: surplus {sur.sum():.0f} deficit "
              f"{defi.sum():.0f} headroom {headr.sum():.0f} "
              f"(rounding delta {sur.sum() - sur_frac.sum():+.1f} on the "
              f"shed plane)")
        st_sw, applied, planned = direct_mod._direct_sweep(
            state, goals, tr_idx, constraint, meta.num_topics, masks)
        applied, planned = int(applied), int(planned)
        killed = planned - applied
        print(f"    live sweep: planned movers {planned}, rank-filled "
              f"{applied}, veto-killed {killed} "
              f"({killed / max(planned, 1):.0%} of the plan)", flush=True)
    else:
        print("--- sparse plan: chain prefix not direct-eligible; "
              "greedy-only diagnostics below", flush=True)

    for rnd in range(diag_rounds):
        cand, deltas, score, layout, (derived, aux, aux_by) = \
            score_round_candidates(state, masks, goal, prior, constraint,
                                   wide, meta.num_topics)
        # Per-prior-goal veto counts over VALID cards.
        valid = np.asarray(deltas.valid)
        n = valid.size
        print(f"--- round {rnd}: grid {n} cards, valid {valid.sum()}")
        acc = np.ones(n, bool)
        for g in prior:
            a = np.asarray(g.acceptance(state, derived, constraint,
                                        aux_by[g.name], deltas))
            newly = (acc & ~a & valid).sum()
            acc &= a
            if newly:
                print(f"    vetoed by {g.name}: {newly}")
        imp = np.asarray(goal.improvement(state, derived, constraint, aux,
                                          deltas))
        pos = valid & acc & np.isfinite(imp) & (imp > 1e-9)
        print(f"    valid+accepted {int((valid & acc).sum())}, "
              f"positive-improvement {int(pos.sum())}")

        # Mirror search._round_body: the targeted-destination column is
        # only present when targets are enabled for this shape, and the
        # tie-rotation modulo must match production selection exactly.
        extra_col = targets_enabled(state.num_partitions)
        red_idx = np.asarray(reduce_per_source(score, layout,
                                               extra_last_col=extra_col))
        red_score = np.asarray(score)[red_idx]
        good_rows = np.isfinite(red_score) & (red_score > 1e-9)
        print(f"    rows with a usable winner: {int(good_rows.sum())} "
              f"of {red_idx.size}")

        def recheck(sub, has_earlier):
            a = jnp.ones(sub.valid.shape[0], dtype=bool)
            for g in prior:
                a &= g.acceptance(state, derived, constraint,
                                  aux_by[g.name], sub)
            a &= (~has_earlier) | goal.acceptance(state, derived, constraint,
                                                  aux, sub)
            return a

        m = max(wide.moves_per_round, wide.num_sources)
        top_idx, sel, _sub, _pot, _lbi = cumulative_select(
            state, deltas, score, layout, m, wide.moves_per_round,
            False, recheck,
            extra_last_col=extra_col)
        sel_np = np.asarray(sel)
        print(f"    selected after dedup+recheck: {int(sel_np.sum())}")
        state = apply_selected(
            state, sel, deltas.partition[top_idx], deltas.src_slot[top_idx],
            deltas.dst_broker[top_idx], cand.kind[top_idx],
            cand.dst_slot[top_idx])
    return 0


if __name__ == "__main__":
    sys.exit(main())
