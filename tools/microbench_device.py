"""Device microbench: per-op-class cost INSIDE a fused while_loop.

The chained-marginal goal profile (tools/profile_round.py) gives per-round
totals; this attributes them to op classes by timing tight while_loops of
each class at solver-realistic shapes. Marginal method per class: run k and
2k iterations, report (t2k - tk) / k — dispatch/RTT cancels.

Thin CLI over ``cruise_control_tpu.utils.microbench`` — the SAME
measurement the live service serves at
``GET /kafkacruisecontrol/profile?microbench=true``, so the shell tool and
the HTTP surface can never drift.

    python tools/microbench_device.py [brokers] [partitions]   # ambient env = TPU
"""

from __future__ import annotations

import sys

import _common


def main() -> int:
    b = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
    p = int(sys.argv[2]) if len(sys.argv) > 2 else 100_000
    _common.enable_cache()
    from cruise_control_tpu.utils.microbench import run_microbench

    out = run_microbench(brokers=b, partitions=p)
    print(f"platform: {out['platform']}  B={b} P={p}", flush=True)
    for name, res in out["results"].items():
        if isinstance(res, dict):
            print(f"{name:14s} FAILED: {res['error']}", flush=True)
        else:
            print(f"{name:14s} ~{res:8.3f} ms/iter", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
