"""Device microbench: per-op-class cost INSIDE a fused while_loop.

The chained-marginal goal profile (tools/profile_round.py) gives per-round
totals; this attributes them to op classes by timing tight while_loops of
each class at solver-realistic shapes. Marginal method per class: run k and
2k iterations, report (t2k - tk) / k — dispatch/RTT cancels.

    python tools/microbench_device.py [brokers] [partitions]   # ambient env = TPU
"""

from __future__ import annotations

import os
import sys
import time
from functools import partial

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/cc_tpu_jax_cache")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    b = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
    p = int(sys.argv[2]) if len(sys.argv) > 2 else 100_000
    import jax
    import jax.numpy as jnp

    from cruise_control_tpu import enable_persistent_compile_cache
    enable_persistent_compile_cache()
    print(f"platform: {jax.devices()[0].platform}  B={b} P={p}", flush=True)

    s = 3
    n_flat = p * s
    key = jax.random.PRNGKey(0)
    w = jax.random.normal(key, (n_flat,))
    seg = jax.random.randint(key, (n_flat,), 0, b)
    grid = 256 * max(16, min(512, b // 4))
    gscore = jax.random.normal(key, (grid,))
    gidx = jax.random.randint(key, (grid,), 0, b)
    m = 512
    midx = jax.random.randint(key, (m,), 0, b)
    mvals = jax.random.normal(key, (m, 4))
    loads = jax.random.normal(key, (b, 4))

    def loop(body, carry, iters):
        def c(st):
            return st[0] < iters

        def bd(st):
            i, x = st
            return (i + 1, body(x))
        return jax.lax.while_loop(c, bd, (jnp.int32(0), carry))[1]

    @partial(jax.jit, static_argnames=("iters", "which"))
    def run(x, iters, which):
        if which == "topk128":
            return loop(lambda v: jax.lax.top_k(v + 1.0, 128)[0].sum() + v,
                        x, iters)
        if which == "topk1024":
            return loop(lambda v: jax.lax.top_k(v + 1.0, 1024)[0].sum() + v,
                        x, iters)
        if which == "approx1024":
            return loop(
                lambda v: jax.lax.approx_max_k(v + 1.0, 1024)[0].sum() + v,
                x, iters)
        if which == "segsum":
            return loop(
                lambda v: v + jax.ops.segment_sum(v, seg, num_segments=b + 1)[
                    seg] * 1e-9, x, iters)
        if which == "segmax":
            return loop(
                lambda v: v + jax.ops.segment_max(v, seg, num_segments=b + 1)[
                    seg] * 1e-9, x, iters)
        if which == "gather_grid":
            return loop(
                lambda v: v + (v[gidx % grid] * 1e-9).sum(), x, iters)
        if which == "scatter_m":
            return loop(
                lambda v: v.at[midx].add(mvals * 1e-9), x, iters)
        if which == "elemwise":
            return loop(lambda v: jnp.where(v > 0, v * 0.999999, v), x, iters)
        if which == "pairwise_m":
            # attach_cumulative-like [m, m] mask + matmul
            def bd(v):
                mask = (v[:, :1] > v[None, :, 0]).astype(jnp.float32)
                return v + (mask @ v) * 1e-9
            return loop(bd, x, iters)
        raise ValueError(which)

    cases = [
        ("topk128", w), ("topk1024", w), ("approx1024", w),
        ("segsum", w), ("segmax", w),
        ("gather_grid", gscore), ("scatter_m", loads),
        ("elemwise", w), ("pairwise_m", mvals),
    ]
    for name, x in cases:
        try:
            # Warm EACH timed variant (iters is static: 16 and 32 are
            # separate compilations the iters=2 warmup would not cover).
            jax.block_until_ready(run(x, 16, name))
            jax.block_until_ready(run(x, 32, name))
            t0 = time.monotonic()
            jax.block_until_ready(run(x, 16, name))
            t1 = time.monotonic()
            jax.block_until_ready(run(x, 32, name))
            t2 = time.monotonic()
            per = ((t2 - t1) - (t1 - t0)) / 16
            print(f"{name:14s} ~{per * 1e3:8.3f} ms/iter", flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"{name:14s} FAILED: {type(e).__name__}: {e}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
