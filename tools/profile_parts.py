"""Per-round cost attribution: time each stage of the chain round body.

Times jitted sub-stages of ``_chain_round_body`` separately (derived state,
per-goal aux, scores, candidate generation, deltas + acceptance stack,
selection, apply) and the fused whole for comparison — the gap between the
sum of parts and the fused round is what XLA fusion buys.

    JAX_PLATFORMS=cpu python tools/profile_parts.py [brokers] [partitions] [active_goal_idx]
"""

from __future__ import annotations

import sys
import time

import _common


def bench(fn, *args, n=20, **kw):
    import jax
    out = fn(*args, **kw)
    jax.block_until_ready(out)
    t0 = time.monotonic()
    for _ in range(n):
        out = fn(*args, **kw)
    jax.block_until_ready(out)
    return (time.monotonic() - t0) / n, out


def main() -> int:
    num_brokers = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
    num_partitions = int(sys.argv[2]) if len(sys.argv) > 2 else 100_000
    active_idx = int(sys.argv[3]) if len(sys.argv) > 3 else 12
    import jax
    import jax.numpy as jnp

    _common.enable_cache()
    from cruise_control_tpu.analyzer.candidates import (
        compute_deltas, generate_candidates,
    )
    from cruise_control_tpu.analyzer.derived import compute_derived
    from cruise_control_tpu.analyzer.optimizer import (
        GoalOptimizer, goals_by_priority,
    )
    from cruise_control_tpu.analyzer.search import (
        ExclusionMasks, cumulative_select, goal_aux,
    )
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.model.fixtures import Dist, random_cluster

    print(f"platform: {jax.devices()[0].platform}", flush=True)
    state, meta = random_cluster(
        num_brokers=num_brokers, num_topics=max(8, num_brokers // 10),
        num_partitions=num_partitions, rf=3, num_racks=8,
        dist=Dist.EXPONENTIAL, seed=42, skew_to_first=2.0,
        target_utilization=0.55)
    state = jax.device_put(state)
    jax.block_until_ready(state.assignment)

    cfg = CruiseControlConfig()
    optimizer = GoalOptimizer(cfg)
    scfg = optimizer.search_config(state)
    goals = tuple(goals_by_priority(cfg))
    masks = ExclusionMasks()
    constraint = optimizer.constraint
    nt = meta.num_topics
    print(f"grid: sources={scfg.num_sources} dests={scfg.num_dests} "
          f"moves={scfg.moves_per_round} active={goals[active_idx].name}")

    t, derived = bench(jax.jit(lambda s: compute_derived(s)), state)
    print(f"{'compute_derived':44s} {t * 1e3:8.2f} ms")

    aux_t = {}
    for i, g in enumerate(goals):
        fn = jax.jit(lambda s, d, g=g: goal_aux(g, s, d, constraint, nt))
        t, _ = bench(fn, state, derived)
        aux_t[g.name] = t
    total_aux = sum(aux_t.values())
    for name, t in sorted(aux_t.items(), key=lambda kv: -kv[1])[:5]:
        print(f"  aux {name:40s} {t * 1e3:8.2f} ms")
    print(f"{'aux total (all 15)':44s} {total_aux * 1e3:8.2f} ms")

    g = goals[active_idx]

    @jax.jit
    def scores(s, d):
        a = goal_aux(g, s, d, constraint, nt)
        return (g.source_score(s, d, constraint, a),
                g.dest_score(s, d, constraint, a),
                g.replica_weight(s, d, constraint, a))

    t, (src, dst, w) = bench(scores, state, derived)
    print(f"{'active scores (incl aux)':44s} {t * 1e3:8.2f} ms")

    gen = jax.jit(lambda s, d, a, b, c: generate_candidates(
        s, d, a, b, c, scfg.num_sources, scfg.num_dests, True, False)[0])
    t, cand = bench(gen, state, derived, src, dst, w)
    # Static grid layout (generate_candidates returns it as python ints,
    # which a jitted return would trace).
    s_dim = state.max_replication_factor
    n_flat = state.num_partitions * s_dim
    layout = ((min(scfg.num_sources, n_flat), min(scfg.num_dests, num_brokers)),
              (min(scfg.num_sources, n_flat), s_dim))
    print(f"{'generate_candidates':44s} {t * 1e3:8.2f} ms")

    t, deltas = bench(jax.jit(compute_deltas), state, derived, cand)
    print(f"{'compute_deltas':44s} {t * 1e3:8.2f} ms")

    @jax.jit
    def acceptance_stack(s, d, dl):
        acc = dl.valid
        for gg in goals[:active_idx]:
            a = goal_aux(gg, s, d, constraint, nt)
            acc &= gg.acceptance(s, d, constraint, a, dl)
        return acc

    t, accept = bench(acceptance_stack, state, derived, deltas)
    print(f"{'acceptance stack (prior aux+accept)':44s} {t * 1e3:8.2f} ms")

    @jax.jit
    def select(s, d, dl, acc):
        a = goal_aux(g, s, d, constraint, nt)
        imp = g.improvement(s, d, constraint, a, dl)
        score = jnp.where(acc, imp, -jnp.inf)
        m = max(scfg.moves_per_round, scfg.num_sources)

        def recheck(sub, has_earlier):
            out = jnp.ones(sub.valid.shape[0], dtype=bool)
            for gg in goals[:active_idx]:
                aa = goal_aux(gg, s, d, constraint, nt)
                out &= gg.acceptance(s, d, constraint, aa, sub)
            return out

        return cumulative_select(s, dl, score, layout, m,
                                 scfg.moves_per_round, False, recheck)

    t, _ = bench(select, state, derived, deltas, accept)
    print(f"{'improvement + cumulative_select':44s} {t * 1e3:8.2f} ms")

    # Fused single round for comparison (budget=1).
    from cruise_control_tpu.analyzer.chain import chain_optimize_rounds
    prior = jnp.asarray([j < active_idx for j in range(len(goals))])

    def one_round(s):
        st, mv, r = chain_optimize_rounds(
            s, jnp.int32(active_idx), prior, goals, constraint, scfg, nt,
            masks, budget=jnp.int32(1))
        return st.assignment
    t, _ = bench(one_round, state, n=10)
    print(f"{'FUSED full round (chain kernel, budget=1)':44s} {t * 1e3:8.2f} ms")
    return 0


if __name__ == "__main__":
    sys.exit(main())
