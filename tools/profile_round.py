"""Chained-marginal per-round cost profile of the chain search kernel.

VERDICT r3 #3: attribute the ~46 ms/round device cost at 1k brokers.
``block_until_ready`` per call lies through the tunnel (fixed RTT per
dispatch), so every number here is a MARGINAL: run the fused driver for
k and 2k rounds and report (t2k - tk) / k — RTT and dispatch glue cancel.

    python tools/profile_round.py [brokers] [partitions] [goal_index]
"""

from __future__ import annotations

import sys
import time

import _common


def main() -> int:
    num_brokers = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
    num_partitions = int(sys.argv[2]) if len(sys.argv) > 2 else 100_000
    import jax
    import jax.numpy as jnp

    _common.enable_cache()
    from cruise_control_tpu.analyzer.chain import chain_optimize_rounds
    from cruise_control_tpu.analyzer.optimizer import (
        GoalOptimizer, goals_by_priority,
    )
    from cruise_control_tpu.analyzer.search import ExclusionMasks
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.model.fixtures import Dist, random_cluster

    print(f"platform: {jax.devices()[0].platform}", flush=True)
    state, meta = random_cluster(
        num_brokers=num_brokers, num_topics=max(8, num_brokers // 10),
        num_partitions=num_partitions, rf=3, num_racks=8,
        dist=Dist.EXPONENTIAL, seed=42, skew_to_first=2.0,
        target_utilization=0.55)
    state = jax.device_put(state)
    jax.block_until_ready(state.assignment)

    cfg = CruiseControlConfig()
    optimizer = GoalOptimizer(cfg)
    scfg = optimizer.search_config(state)
    goals = tuple(goals_by_priority(cfg))
    masks = ExclusionMasks()
    constraint = optimizer.constraint

    def run(goal_idx: int, budget: int, cfg_used):
        prior = jnp.asarray([j < goal_idx for j in range(len(goals))])
        st, moves, rounds = chain_optimize_rounds(
            state, jnp.int32(goal_idx), prior, goals, constraint, cfg_used,
            meta.num_topics, masks, budget=jnp.int32(budget))
        jax.block_until_ready(st.assignment)
        return int(rounds)

    def marginal(goal_idx: int, cfg_used, k: int = 8) -> tuple[float, int]:
        run(goal_idx, 1, cfg_used)            # compile + warm
        t0 = time.monotonic(); r1 = run(goal_idx, k, cfg_used)
        t1 = time.monotonic(); r2 = run(goal_idx, 2 * k, cfg_used)
        t2 = time.monotonic()
        extra_rounds = max(1, r2 - r1)
        return ((t2 - t1) - (t1 - t0)) / extra_rounds, r2

    from dataclasses import replace
    wide = replace(scfg, num_sources=min(2048, scfg.num_sources * 4),
                   moves_per_round=min(2048, scfg.moves_per_round * 2))
    for goal_idx in (0, 6, 9, 12):   # rack, replica-count, nw-out-dist, topic
        name = goals[goal_idx].name
        per_round, r = marginal(goal_idx, scfg)
        print(f"goal[{goal_idx}] {name:42s} narrow({scfg.num_sources}) "
              f"~{per_round * 1000:7.1f} ms/round  (ran {r})", flush=True)
        per_round_w, rw = marginal(goal_idx, wide)
        print(f"goal[{goal_idx}] {name:42s} wide({wide.num_sources})   "
              f"~{per_round_w * 1000:7.1f} ms/round  (ran {rw})", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
