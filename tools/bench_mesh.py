"""Sharded-vs-single-device solver comparison on a virtual CPU mesh.

VERDICT r3 #5: demonstrate the multi-chip story past a smoke test — run
the SAME 1k-broker fixture through the single-device fused solver and the
8-virtual-device sharded solver, record both wall-clocks, and check the
final assignments/quality against each other (the trajectory-equivalence
check of tests/test_parallel.py at bench scale).

Host-CPU devices share the same physical cores, so the 8-device wall-clock
here measures SPMD overhead (collectives + per-device dispatch), not
speedup — the ratio is the lower bound a real 8-chip ICI mesh improves on
(each real chip has its own compute). Prints one JSON line per
configuration plus a comparison line.

    python tools/bench_mesh.py [brokers] [partitions]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_DEV = int(os.environ.get("MESH_DEVICES", "8"))


def main() -> int:
    num_brokers = int(sys.argv[1]) if len(sys.argv) > 1 else 1000
    num_partitions = int(sys.argv[2]) if len(sys.argv) > 2 else 100_000
    from cruise_control_tpu.utils import force_host_cpu_devices

    jax = force_host_cpu_devices(N_DEV)
    import numpy as np

    from cruise_control_tpu import enable_persistent_compile_cache
    enable_persistent_compile_cache()
    from cruise_control_tpu.analyzer.optimizer import (
        GoalOptimizer, goals_by_priority,
    )
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.model.fixtures import Dist, random_cluster
    from cruise_control_tpu.parallel import make_mesh

    state, meta = random_cluster(
        num_brokers=num_brokers, num_topics=max(8, num_brokers // 10),
        num_partitions=num_partitions, rf=3, num_racks=8,
        dist=Dist.EXPONENTIAL, seed=42, skew_to_first=2.0,
        target_utilization=0.55, partition_bucket=N_DEV)
    cfg = CruiseControlConfig()

    results = {}
    for label, mesh in (("single_device", None),
                        (f"mesh_{N_DEV}dev", make_mesh(N_DEV))):
        optimizer = GoalOptimizer(cfg, mesh=mesh)
        t0 = time.time()
        final, res = optimizer.optimizations(state, meta,
                                             goals=goals_by_priority(cfg))
        warm_s = time.time() - t0
        t0 = time.time()
        final, res = optimizer.optimizations(state, meta,
                                             goals=goals_by_priority(cfg))
        steady_s = time.time() - t0
        results[label] = (np.asarray(jax.device_get(final).assignment),
                          res, steady_s)
        print(json.dumps({
            "metric": f"mesh_bench_{label}_{num_brokers}b",
            "value": round(steady_s, 3), "unit": "s", "vs_baseline": 1.0,
            "extras": {
                "devices": optimizer.solver_devices(),
                "warmup_incl_compile_s": round(warm_s, 3),
                "num_proposals": len(res.proposals),
                "balancedness_after": round(res.balancedness_after, 2),
                "violated_goals_after": res.violated_goals_after,
                "total_rounds": sum(g.rounds for g in res.goal_results),
            }}), flush=True)

    (a1, r1, t1) = results["single_device"]
    (a8, r8, t8) = results[f"mesh_{N_DEV}dev"]
    print(json.dumps({
        "metric": f"mesh_bench_ratio_{num_brokers}b",
        "value": round(t1 / t8, 3), "unit": "x_single_over_mesh",
        "vs_baseline": 1.0,
        "extras": {
            "assignments_identical": bool((a1 == a8).all()),
            "balancedness_match": round(r1.balancedness_after, 2)
            == round(r8.balancedness_after, 2),
            "violated_goals_match":
                r1.violated_goals_after == r8.violated_goals_after,
            "note": "host-CPU devices share cores: ratio measures SPMD "
                    "overhead, a lower bound for a real 8-chip mesh",
        }}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
