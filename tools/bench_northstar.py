"""Standalone north-star stage: 7,000 brokers / 1M partitions, full chain.

The driver's bench budget (840 s) ends at the 1k stages; this runner
measures BASELINE.md config #5 in isolation with no watchdog, printing the
same JSON line shape as bench.py so results can be pasted into BASELINE.md
/ BENCH notes. Run it SOLO (one TPU process at a time — the tunnel
serializes and then times out concurrent claims).

    JAX_COMPILATION_CACHE_DIR=/tmp/cc_tpu_jax_cache python tools/bench_northstar.py
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/cc_tpu_jax_cache")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    num_brokers = int(os.environ.get("NS_BROKERS", "7000"))
    num_partitions = int(os.environ.get("NS_PARTITIONS", "1000000"))
    import jax

    from cruise_control_tpu import enable_persistent_compile_cache
    enable_persistent_compile_cache()
    from cruise_control_tpu.analyzer.optimizer import (
        GoalOptimizer, goals_by_priority,
    )
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.model.fixtures import Dist, random_cluster

    device = jax.devices()[0].platform
    chips = jax.device_count()
    budget_s = 30.0 * (num_partitions / 1_000_000) * (8.0 / min(chips, 8))

    t0 = time.time()
    state, meta = random_cluster(
        num_brokers=num_brokers, num_topics=max(8, num_brokers // 10),
        num_partitions=num_partitions, rf=3, num_racks=8,
        dist=Dist.EXPONENTIAL, seed=42, skew_to_first=2.0,
        target_utilization=0.55)
    state = jax.device_put(state)
    jax.block_until_ready(state.assignment)
    build_s = time.time() - t0

    cfg = CruiseControlConfig()
    optimizer = GoalOptimizer(cfg, mesh="auto")
    t0 = time.time()
    _, warm = optimizer.optimizations(state, meta,
                                      goals=goals_by_priority(cfg))
    warm_s = time.time() - t0
    t0 = time.time()
    _, res = optimizer.optimizations(state, meta,
                                     goals=goals_by_priority(cfg))
    steady_s = time.time() - t0
    print(json.dumps({
        "metric": f"rebalance_proposal_wall_clock_{num_brokers}brokers_"
                  f"{num_partitions // 1000}kpartitions",
        "value": round(steady_s, 3), "unit": "s",
        "vs_baseline": round(budget_s / steady_s, 3),
        "extras": {
            "device": device, "solver_devices": optimizer.solver_devices(),
            "model_build_s": round(build_s, 3),
            "warmup_incl_compile_s": round(warm_s, 3),
            "num_proposals": len(res.proposals),
            "balancedness_after": round(res.balancedness_after, 2),
            "violated_goals_after": res.violated_goals_after,
            "total_rounds": sum(g.rounds for g in res.goal_results),
            "budget_s_prorated": round(budget_s, 3),
        }}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
