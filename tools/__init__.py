"""Offline diagnostic + lint tools. Package-ized so gate entry points
run as modules from the repo root (``python -m tools.ccsa``); the
standalone scripts here still run directly (``python tools/bench_*.py``)
via the ``import _common`` preamble."""
