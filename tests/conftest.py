"""Test harness: force an 8-device virtual CPU platform so multi-chip
sharding paths (Mesh/shard_map) are exercised without TPU pods.

The ambient environment may pin jax to a TPU tunnel (axon) via
sitecustomize, which overrides JAX_PLATFORMS with a config update at
interpreter startup — so env vars alone are not enough; we must update the
jax config again after import (but before first backend use)."""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402  (import after env setup)

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)
