"""Test harness: force an 8-device virtual CPU platform BEFORE jax import so
multi-chip sharding paths (Mesh/shard_map) are exercised without TPU pods."""

import os

# Hard override: the ambient environment may pin JAX_PLATFORMS to a TPU
# tunnel (axon) whose remote compiles take tens of seconds per jit. Tests
# always run on the virtual multi-device CPU platform.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (import after env setup)

jax.config.update("jax_enable_x64", False)
