"""Test harness: force an 8-device virtual CPU platform so multi-chip
sharding paths (Mesh/shard_map) are exercised without TPU pods.

The ambient environment may pin jax to a TPU tunnel (axon) via
sitecustomize; see cruise_control_tpu/utils/platform.py — the shared home
of the workaround — for why env vars alone are not enough."""

from cruise_control_tpu import enable_persistent_compile_cache
from cruise_control_tpu.utils import force_host_cpu_devices

jax = force_host_cpu_devices(8)
jax.config.update("jax_enable_x64", False)
# jax 0.9 ignores the JAX_COMPILATION_CACHE_DIR env var; without the
# programmatic enable every test session cold-compiles the solver kernels.
enable_persistent_compile_cache()
