"""Resilience layer + chaos harness (round 9).

Unit coverage: retry/backoff/jitter determinism and circuit-breaker
state transitions under an INJECTED clock (no sleeps, no wall-clock
assertions). Integration coverage: full rebalance/execution cycles
driven through the fault-injecting backend at several seeds — the
acceptance bar is convergence with correct final assignments, zero
flakes, plus partial-window acceptance, executor dead-lettering, the
fleet skip-on-open-breaker path, and the facade's stale-cache
fallback / 503-on-open-breaker behavior.
"""

import pytest

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
from cruise_control_tpu.executor.admin import (
    InMemoryAdminBackend, PartitionState,
)
from cruise_control_tpu.executor.executor import Executor
from cruise_control_tpu.facade import CruiseControl
from cruise_control_tpu.monitor import LoadMonitor, StaticCapacityResolver
from cruise_control_tpu.monitor.sampling import SyntheticSampler
from cruise_control_tpu.testing.chaos import (
    ChaosAdminBackend, ChaosSampler, ChaosTransientError, FaultSchedule,
    run_faulted_executor_cycle,
)
from cruise_control_tpu.utils.resilience import (
    BreakerOpenError, BreakerState, CircuitBreaker, RetryPolicy,
    call_with_resilience,
)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# RetryPolicy: backoff + jitter determinism
# ---------------------------------------------------------------------------

def test_backoff_schedule_is_deterministic_and_seeded():
    p1 = RetryPolicy(base_backoff_s=0.1, max_backoff_s=10.0, multiplier=2.0,
                     jitter_ratio=0.2, seed=7)
    p2 = RetryPolicy(base_backoff_s=0.1, max_backoff_s=10.0, multiplier=2.0,
                     jitter_ratio=0.2, seed=7)
    sched1 = [p1.backoff_s("op", a) for a in range(2, 10)]
    sched2 = [p2.backoff_s("op", a) for a in range(2, 10)]
    assert sched1 == sched2, "same seed must replay the same schedule"
    # Jitter only ever SUBTRACTS from the exponential envelope.
    for attempt, b in enumerate(sched1, start=2):
        envelope = min(10.0, 0.1 * 2.0 ** (attempt - 2))
        assert envelope * (1 - 0.2) <= b <= envelope
    # A different seed (or op) jitters differently.
    p3 = RetryPolicy(base_backoff_s=0.1, jitter_ratio=0.2, seed=8)
    assert [p3.backoff_s("op", a) for a in range(2, 10)] != sched1
    assert [p1.backoff_s("other", a) for a in range(2, 10)] != sched1


def test_retry_succeeds_after_transient_failures_with_exact_backoffs():
    policy = RetryPolicy(max_attempts=5, base_backoff_s=0.5, jitter_ratio=0.2,
                         seed=3, overall_deadline_s=1e9)
    clock, sleeps, calls = FakeClock(), [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ChaosTransientError("boom")
        return "ok"

    out = call_with_resilience("flaky.op", flaky, policy=policy,
                               clock=clock, sleep=sleeps.append)
    assert out == "ok"
    assert len(calls) == 3
    assert sleeps == [policy.backoff_s("flaky.op", 2),
                      policy.backoff_s("flaky.op", 3)]


def test_retry_exhaustion_and_nonretryable_classification():
    policy = RetryPolicy(max_attempts=3, base_backoff_s=0.0,
                         jitter_ratio=0.0, overall_deadline_s=1e9)
    calls = []

    def always():
        calls.append(1)
        raise ChaosTransientError("nope")

    with pytest.raises(ChaosTransientError):
        call_with_resilience("x", always, policy=policy,
                             sleep=lambda s: None)
    assert len(calls) == 3, "transient errors retry to the attempt budget"

    calls.clear()

    def broken():
        calls.append(1)
        raise ValueError("a bug, not weather")

    with pytest.raises(ValueError):
        call_with_resilience("x", broken, policy=policy,
                             sleep=lambda s: None)
    assert len(calls) == 1, "programming errors must never retry"


def test_overall_deadline_stops_retrying():
    policy = RetryPolicy(max_attempts=100, base_backoff_s=10.0,
                         jitter_ratio=0.0, overall_deadline_s=15.0)
    clock, calls = FakeClock(), []

    def always():
        calls.append(1)
        raise ChaosTransientError()

    def sleep(s):
        clock.advance(s)

    with pytest.raises(ChaosTransientError):
        call_with_resilience("x", always, policy=policy, clock=clock,
                             sleep=sleep)
    # 10s backoff fits the 15s budget once; the second would overrun.
    assert len(calls) == 2


def test_kafka_protocol_errors_classify_as_transient_by_code():
    """The wire client's retriable broker responses (leadership /
    controller movement) must retry under a RetryPolicy; permanent
    protocol errors must not."""
    from cruise_control_tpu.kafka.wire import messages as m
    from cruise_control_tpu.utils.resilience import default_retryable

    assert default_retryable(m.KafkaProtocolError(m.NOT_CONTROLLER))
    assert default_retryable(m.KafkaProtocolError(m.NOT_LEADER_OR_FOLLOWER))
    assert not default_retryable(m.KafkaProtocolError(m.INVALID_REQUEST))
    assert not default_retryable(m.KafkaProtocolError(m.LOG_DIR_NOT_FOUND))


def test_retries_are_visible_as_spans_and_sensors():
    """Acceptance: every retry shows up in /trace (a resilience.retry
    child span nested in the ambient operation) and /metrics
    (retry_attempts_total{op=})."""
    from cruise_control_tpu.utils.sensors import SENSORS
    from cruise_control_tpu.utils.tracing import TRACER, span_names

    TRACER.configure(enabled=True)
    TRACER.clear()
    policy = RetryPolicy(max_attempts=3, base_backoff_s=0.0,
                         jitter_ratio=0.0)
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 2:
            raise ChaosTransientError()
        return "ok"

    with TRACER.span("rebalance", operation="rebalance"):
        call_with_resilience("admin.alter_partition_reassignments", flaky,
                             policy=policy, sleep=lambda s: None)
    (trace,) = TRACER.traces(operation="rebalance", limit=1)
    assert "resilience.retry" in span_names(trace)
    snap = SENSORS.render()
    assert 'retry_attempts_total{op="admin.alter_partition_reassignments"}' \
        in snap.replace("kafka_cruisecontrol_", "")


# ---------------------------------------------------------------------------
# CircuitBreaker: state transitions on an injected clock
# ---------------------------------------------------------------------------

def test_breaker_full_lifecycle_under_injected_clock():
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=3, recovery_s=30.0, clock=clock)
    t = "cluster-a"
    assert b.state(t) is BreakerState.CLOSED and b.allow(t)
    b.record_failure(t)
    b.record_failure(t)
    assert b.state(t) is BreakerState.CLOSED, "below threshold stays closed"
    b.record_failure(t)
    assert b.state(t) is BreakerState.OPEN
    assert not b.allow(t)
    assert b.retry_after_s(t) == pytest.approx(30.0)
    clock.advance(29.0)
    assert not b.allow(t)
    assert b.retry_after_s(t) == pytest.approx(1.0)
    clock.advance(1.0)
    assert b.allow(t), "recovery elapsed: half-open probe admitted"
    assert b.state(t) is BreakerState.HALF_OPEN
    # Failed probe re-opens with a FRESH window.
    b.record_failure(t)
    assert b.state(t) is BreakerState.OPEN
    assert b.retry_after_s(t) == pytest.approx(30.0)
    clock.advance(31.0)
    assert b.allow(t)
    b.record_success(t)
    assert b.state(t) is BreakerState.CLOSED
    # A success resets the consecutive-failure count.
    b.record_failure(t)
    b.record_failure(t)
    b.record_success(t)
    b.record_failure(t)
    b.record_failure(t)
    assert b.state(t) is BreakerState.CLOSED


def test_breaker_targets_are_independent_and_guard_raises():
    clock = FakeClock()
    b = CircuitBreaker(failure_threshold=1, recovery_s=10.0, clock=clock)
    b.record_failure("bad")
    assert b.state("bad") is BreakerState.OPEN
    assert b.allow("good"), "one target's breaker must not affect another"
    with pytest.raises(BreakerOpenError) as ei:
        b.guard("bad")
    assert ei.value.retry_after_s == pytest.approx(10.0)
    b.guard("good")  # no raise


def test_disabled_breaker_and_noop_wrapper_passthrough():
    b = CircuitBreaker(failure_threshold=0)
    for _ in range(10):
        b.record_failure("t")
    assert b.allow("t")
    assert call_with_resilience("x", lambda: 42) == 42


# ---------------------------------------------------------------------------
# Chaos schedule + faulted executor cycles
# ---------------------------------------------------------------------------

def test_fault_schedule_is_deterministic_and_stoppable():
    s1 = FaultSchedule(seed=5, fault_rate=0.3)
    s2 = FaultSchedule(seed=5, fault_rate=0.3)
    rolls1 = [s1.next_fault("op") for _ in range(300)]
    rolls2 = [s2.next_fault("op") for _ in range(300)]
    assert rolls1 == rolls2
    injected = [k for k in rolls1 if k is not None]
    assert 0.15 < len(injected) / 300 < 0.45, "rate must be roughly honored"
    assert {"timeout", "transient", "partial", "slow"} >= set(injected)
    s1.stop()
    assert all(s1.next_fault("op") is None for _ in range(50))


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_faulted_executor_cycle_converges(seed):
    """Acceptance: a full execution against the fault-injecting backend
    (25% transient rate, deterministic seed) completes with correct
    final assignments — across three seeds, no flakes."""
    r = run_faulted_executor_cycle(seed=seed, fault_rate=0.25,
                                   max_attempts=8, dead_letter_attempts=6)
    assert r["converged"], r
    assert r["abandoned"] == 0
    assert r["faults_injected"] > 0, "the schedule must actually fire"


def test_executor_dead_letters_unsubmittable_tasks():
    """A submission that NEVER reaches the backend is dead-lettered to
    EXECUTION_ABANDONED after the attempt budget (with a notifier
    event) instead of hanging until the global task timeout."""
    from cruise_control_tpu.analyzer.proposals import ExecutionProposal

    parts = {("t", 0): PartitionState("t", 0, (0, 1), 0, isr=(0, 1))}
    backend = InMemoryAdminBackend(parts.values())

    class DeadControlPlane:
        def __getattr__(self, name):
            return getattr(backend, name)

        def alter_partition_reassignments(self, targets):
            raise ChaosTransientError("control plane unreachable")

    events = []

    class Recorder:
        def on_execution_finished(self, summary):
            events.append(("finished", summary))

        def on_execution_stopped(self, summary):
            events.append(("stopped", summary))

        def on_tasks_abandoned(self, summary):
            events.append(("abandoned", summary))

    policy = RetryPolicy(max_attempts=2, base_backoff_s=0.0,
                         jitter_ratio=0.0)
    ex = Executor(DeadControlPlane(), synchronous=True,
                  progress_check_interval_s=0.0, adjuster_enabled=False,
                  retry_policy=policy, dead_letter_attempts=2,
                  notifier=Recorder())
    ex.execute_proposals([ExecutionProposal(
        topic="t", partition=0, old_leader=0, old_replicas=(0, 1),
        new_replicas=(1, 2), new_leader=1)], uuid="dead-letter")
    counts = ex.execution_state()["taskCounts"]
    assert counts["inter_broker_replica_action"] == {"abandoned": 1}
    kinds = [k for k, _ in events]
    assert "abandoned" in kinds and "finished" in kinds
    abandoned = dict(events)["abandoned"]
    assert abandoned["numTasks"] == 1 and abandoned["uuid"] == "dead-letter"


def test_leadership_verify_failures_kill_but_never_dead_letter():
    """elect_leaders lands but the completion read-back keeps failing:
    the tasks must NOT be reported as EXECUTION_ABANDONED ('control
    plane never got through' — a lie here); after the verify budget
    they are DEAD-marked, with no on_tasks_abandoned event."""
    from cruise_control_tpu.analyzer.proposals import ExecutionProposal

    parts = {("t", 0): PartitionState("t", 0, (0, 1), 1, isr=(0, 1))}
    backend = InMemoryAdminBackend(parts.values())

    class BlindReadback:
        def __init__(self):
            self.elections = 0

        def __getattr__(self, name):
            return getattr(backend, name)

        def elect_leaders(self, partitions):
            self.elections += 1
            return backend.elect_leaders(partitions)

        def describe_partitions(self):
            raise ChaosTransientError("metadata unreachable")

    events = []

    class Recorder:
        def on_execution_finished(self, summary):
            pass

        def on_execution_stopped(self, summary):
            pass

        def on_tasks_abandoned(self, summary):
            events.append(summary)

    admin = BlindReadback()
    ex = Executor(admin, synchronous=True, progress_check_interval_s=0.0,
                  adjuster_enabled=False,
                  retry_policy=RetryPolicy(max_attempts=2, base_backoff_s=0.0,
                                           jitter_ratio=0.0),
                  dead_letter_attempts=3, notifier=Recorder())
    ex.execute_proposals([ExecutionProposal(
        topic="t", partition=0, old_leader=1, old_replicas=(0, 1),
        new_replicas=(0, 1), new_leader=0)], uuid="blind")
    counts = ex.execution_state()["taskCounts"]["leader_action"]
    assert counts == {"dead": 1}, counts
    assert not events, "verify failures must not fire on_tasks_abandoned"
    assert admin.elections == 3, "requeued re-elections up to the budget"


def test_executor_task_timeout_sensor_and_notifier_event():
    """The deduped timeout helper fires on both poll paths: a stalled
    reassignment past task_timeout_s is DEAD-marked with a
    task_timeouts_total sensor and an on_task_timeout notifier event."""
    from cruise_control_tpu.analyzer.proposals import ExecutionProposal
    from cruise_control_tpu.utils.sensors import SENSORS

    parts = {("t", 0): PartitionState("t", 0, (0, 1), 0, isr=(0, 1)),
             # Broker 2 hosts something, so it is ALIVE — the stalled
             # task must hit the TIMEOUT branch, not dead-destination.
             ("t", 1): PartitionState("t", 1, (2,), 2, isr=(2,))}
    # steps_per_tick=0: the simulated cluster never completes the move.
    backend = InMemoryAdminBackend(parts.values(), steps_per_tick=0)
    timeouts = []

    class Recorder:
        def on_execution_finished(self, summary):
            pass

        def on_execution_stopped(self, summary):
            pass

        def on_task_timeout(self, task):
            timeouts.append(task)

    ex = Executor(backend, synchronous=True, progress_check_interval_s=0.0,
                  adjuster_enabled=False, task_timeout_s=0.0,
                  notifier=Recorder())
    ex.execute_proposals([ExecutionProposal(
        topic="t", partition=0, old_leader=0, old_replicas=(0, 1),
        new_replicas=(0, 2), new_leader=0)], uuid="timeout")
    counts = ex.execution_state()["taskCounts"]
    assert counts["inter_broker_replica_action"] == {"dead": 1}
    assert len(timeouts) == 1 and timeouts[0]["state"] == "in_progress"
    snap = SENSORS.render()
    assert "task_timeouts_total" in snap


# ---------------------------------------------------------------------------
# Fetcher: partial-window acceptance + stable assignment
# ---------------------------------------------------------------------------

class _RecordingAgg:
    def __init__(self):
        self.batches = []

    def add_samples_batch(self, ents, time_ms, vals):
        self.batches.append((ents, time_ms, vals))


class _NullStore:
    def store_samples(self, result):
        pass


def _split_assignor(partitions, num_fetchers):
    buckets = [{} for _ in range(num_fetchers)]
    for i, (key, st) in enumerate(sorted(partitions.items())):
        buckets[i % num_fetchers][key] = st
    return buckets


class _FailingSampler:
    def get_samples(self, partitions, start_ms, end_ms):
        raise ChaosTransientError("sampler down")

    def close(self):
        pass


def _fetch_partitions(n=8):
    return {(f"t{i}", 0): PartitionState(f"t{i}", 0, (0,), 0, isr=(0,))
            for i in range(n)}


def test_fetcher_accepts_partial_window_above_floor():
    from cruise_control_tpu.monitor.sampling.fetcher import (
        MetricFetcherManager,
    )
    pagg, bagg = _RecordingAgg(), _RecordingAgg()
    mgr = MetricFetcherManager(
        [SyntheticSampler(), _FailingSampler()], pagg, bagg, _NullStore(),
        assignor=_split_assignor, min_completeness=0.25)
    merged = mgr.fetch_metric_samples(_fetch_partitions(), 0, 1000)
    assert merged.skipped_partitions == 4, "the failed fetcher's bucket"
    assert len(merged.partition_samples) == 4, "the healthy bucket landed"
    assert pagg.batches, "partial window must still be ingested"
    mgr.shutdown()


def test_fetcher_rejects_window_below_completeness_floor():
    from cruise_control_tpu.monitor.sampling.fetcher import (
        MetricFetcherManager, PartialWindowError,
    )
    pagg, bagg = _RecordingAgg(), _RecordingAgg()
    mgr = MetricFetcherManager(
        [SyntheticSampler(), _FailingSampler()], pagg, bagg, _NullStore(),
        assignor=_split_assignor, min_completeness=0.75)
    with pytest.raises(PartialWindowError):
        mgr.fetch_metric_samples(_fetch_partitions(), 0, 1000)
    assert not pagg.batches, "a rejected window must not be ingested"
    mgr.shutdown()


def test_fetcher_retries_flaky_sampler_to_success():
    from cruise_control_tpu.monitor.sampling.fetcher import (
        MetricFetcherManager,
    )

    class FlakyOnce:
        def __init__(self):
            self.calls = 0
            self.inner = SyntheticSampler()

        def get_samples(self, partitions, start_ms, end_ms):
            self.calls += 1
            if self.calls == 1:
                raise ChaosTransientError("first call drops")
            return self.inner.get_samples(partitions, start_ms, end_ms)

        def close(self):
            pass

    pagg, bagg = _RecordingAgg(), _RecordingAgg()
    flaky = FlakyOnce()
    mgr = MetricFetcherManager(
        [flaky], pagg, bagg, _NullStore(),
        retry_policy=RetryPolicy(max_attempts=3, base_backoff_s=0.0,
                                 jitter_ratio=0.0))
    merged = mgr.fetch_metric_samples(_fetch_partitions(), 0, 1000)
    assert flaky.calls == 2
    assert merged.skipped_partitions == 0
    assert len(merged.partition_samples) == 8
    mgr.shutdown()


# ---------------------------------------------------------------------------
# Fleet scheduler: skip-on-open-breaker
# ---------------------------------------------------------------------------

def test_fleet_scheduler_skips_open_breaker_cluster_and_recovers():
    from cruise_control_tpu.fleet.scheduler import FleetScheduler, JobKind

    clock = FakeClock()
    breaker = CircuitBreaker(failure_threshold=2, recovery_s=30.0,
                             clock=clock, name="fleet")
    sched = FleetScheduler(starvation_bound_s=1e9, clock=clock,
                           breaker=breaker)

    def boom():
        raise ChaosTransientError("cluster broken")

    for _ in range(2):
        f = sched.submit("bad", JobKind.ON_DEMAND, boom)
        sched.run_pending()
        with pytest.raises(ChaosTransientError):
            f.result(timeout=1)
    assert breaker.state("bad") is BreakerState.OPEN

    ran = []
    f_bad = sched.submit("bad", JobKind.ON_DEMAND, lambda: ran.append("bad"))
    f_good = sched.submit("good", JobKind.ON_DEMAND,
                          lambda: ran.append("good") or "ok")
    sched.run_pending()
    with pytest.raises(BreakerOpenError):
        f_bad.result(timeout=1)
    assert f_good.result(timeout=1) == "ok"
    assert ran == ["good"], "open-breaker cluster skipped, healthy one ran"

    # Recovery window elapses: the next job is the half-open probe; its
    # success closes the breaker.
    clock.advance(31.0)
    f2 = sched.submit("bad", JobKind.ON_DEMAND, lambda: "recovered")
    sched.run_pending()
    assert f2.result(timeout=1) == "recovered"
    assert breaker.state("bad") is BreakerState.CLOSED


# ---------------------------------------------------------------------------
# Detector isolation
# ---------------------------------------------------------------------------

def test_detector_breaker_isolates_crashing_detector():
    from cruise_control_tpu.detector.manager import AnomalyDetectorManager

    cfg = CruiseControlConfig({
        "resilience.breaker.failure.threshold": 2,
        "resilience.breaker.recovery.ms": 30_000,
        "failed.brokers.file.path": ""})
    mgr = AnomalyDetectorManager(cfg)
    clock = FakeClock()
    mgr._detector_breaker = CircuitBreaker(failure_threshold=2,
                                           recovery_s=30.0, clock=clock,
                                           name="detector")

    class Crashing:
        def __init__(self):
            self.runs = 0

        def run_once(self):
            self.runs += 1
            raise RuntimeError("detector bug")

    det = Crashing()
    assert not mgr.run_detector_once(det)
    assert not mgr.run_detector_once(det)
    assert det.runs == 2
    # Breaker open: further ticks are skipped without invoking it.
    assert not mgr.run_detector_once(det)
    assert not mgr.run_detector_once(det)
    assert det.runs == 2
    clock.advance(31.0)
    assert not mgr.run_detector_once(det)
    assert det.runs == 3, "recovery window elapsed: probe tick runs again"


# ---------------------------------------------------------------------------
# Facade: stale-cache fallback + breaker-gated 503, end-to-end chaos
# ---------------------------------------------------------------------------

def _partitions(brokers=(0, 1, 2, 3), topics=2, parts=6, rf=2):
    out = {}
    for t in range(topics):
        for p in range(parts):
            reps = (brokers[0], brokers[1 + (t + p) % (len(brokers) - 1)])[:rf]
            out[(f"t{t}", p)] = PartitionState(f"t{t}", p, reps, reps[0],
                                               isr=reps)
    return out


def _chaos_cruise_control(fault_rate=0.15, seed=11, extra_cfg=None):
    backend = InMemoryAdminBackend(_partitions().values())
    chaos = ChaosAdminBackend(backend, seed=seed, fault_rate=fault_rate)
    cfg = CruiseControlConfig({
        "partition.metrics.window.ms": 1000,
        "num.partition.metrics.windows": 3,
        "min.valid.partition.ratio": 0.0,
        "anomaly.detection.interval.ms": 60_000,
        "max.solver.rounds": 40,
        "failed.brokers.file.path": "",
        "resilience.retry.base.backoff.ms": 0,
        "resilience.retry.max.backoff.ms": 0,
        "resilience.retry.max.attempts": 8,
        **(extra_cfg or {})})
    caps = StaticCapacityResolver({}, {Resource.CPU: 100.0, Resource.DISK: 1e7,
                                       Resource.NW_IN: 1e6,
                                       Resource.NW_OUT: 1e6})
    sampler = ChaosSampler(SyntheticSampler(),
                           schedule=chaos.schedule)
    monitor = LoadMonitor(cfg, chaos, samplers=[sampler],
                          capacity_resolver=caps,
                          broker_racks={b: f"r{b % 2}" for b in range(8)})
    executor = Executor(chaos, synchronous=True, adjuster_enabled=False,
                        progress_check_interval_s=0.0,
                        retry_policy=RetryPolicy(
                            max_attempts=8, base_backoff_s=0.0,
                            jitter_ratio=0.0, seed=seed),
                        dead_letter_attempts=6)
    cc = CruiseControl(cfg, chaos, load_monitor=monitor, executor=executor)
    for k in range(1, 5):
        monitor.task_runner.run_sampling_once(end_ms=k * 1000)
    return cc, backend, chaos


@pytest.mark.parametrize("seed", [11, 23, 42])
def test_full_rebalance_cycle_through_chaos_backend(seed):
    """The headline chaos test: sample → model → optimize → execute with
    ≥10% injected transient failure rate end to end; the cycle must
    complete with the proposals actually applied on the (unwrapped)
    backend, deterministically per seed."""
    cc, backend, chaos = _chaos_cruise_control(fault_rate=0.15, seed=seed)
    res = cc.rebalance(dryrun=False)
    assert res.proposals, "skewed cluster must yield proposals"
    assert res.executed
    cc.executor.await_completion()
    counts = cc.executor.execution_state()["taskCounts"]
    assert counts["inter_broker_replica_action"].get("abandoned", 0) == 0
    after = backend.describe_partitions()
    for pr in res.proposals:
        assert set(after[(pr.topic, pr.partition)].replicas) \
            == set(pr.new_replicas)
    assert chaos.schedule.faults_injected > 0
    # Faults stop → the next full cycle is clean and still converges.
    chaos.schedule.stop()
    cc.load_monitor.task_runner.run_sampling_once(end_ms=10_000)
    res2 = cc.rebalance(dryrun=False)
    cc.executor.await_completion()
    after2 = backend.describe_partitions()
    for pr in res2.proposals:
        assert set(after2[(pr.topic, pr.partition)].replicas) \
            == set(pr.new_replicas)


def test_facade_serves_stale_cache_then_503_when_breaker_opens():
    cc, _backend, chaos = _chaos_cruise_control(
        fault_rate=0.0, extra_cfg={"resilience.breaker.failure.threshold": 2,
                                   "resilience.breaker.recovery.ms": 60_000})
    chaos.schedule.stop()
    good = cc.proposals()
    assert good.reason != "cached" and not good.extra.get("stale")

    def explode(*a, **k):
        raise RuntimeError("model build failed")

    cc._optimizer.optimizations = explode
    # Failure 1 + 2 (fresh model generations force real computes that
    # fail): stale fallback, marked clearly.
    for k in range(2):
        cc.load_monitor.task_runner.run_sampling_once(end_ms=(10 + k) * 1000)
        res = cc.proposals()
        assert res.extra.get("stale") is True
        assert tuple(res.proposals) == tuple(good.proposals)
        assert "stale cache fallback" in res.reason
    # Threshold reached: breaker open → fail fast with Retry-After.
    cc.load_monitor.task_runner.run_sampling_once(end_ms=12_000)
    with pytest.raises(BreakerOpenError) as ei:
        cc.proposals()
    assert ei.value.retry_after_s > 0


def test_facade_ignore_proposal_cache_refuses_stale_fallback():
    """An explicit ignore_proposal_cache=true is a contract: the caller
    refused cached answers, so a failed compute must raise, not serve
    the stale set with a 200."""
    cc, _backend, chaos = _chaos_cruise_control(fault_rate=0.0)
    chaos.schedule.stop()
    cc.proposals()  # prime the cache

    def explode(*a, **k):
        raise RuntimeError("model build failed")

    cc._optimizer.optimizations = explode
    with pytest.raises(RuntimeError, match="model build failed"):
        cc.proposals(ignore_proposal_cache=True)


def test_facade_chaos_enabled_config_wraps_admin():
    backend = InMemoryAdminBackend(_partitions().values())
    cfg = CruiseControlConfig({
        "chaos.enabled": True, "chaos.seed": 4, "chaos.fault.rate": 0.5,
        "failed.brokers.file.path": ""})
    cc = CruiseControl(cfg, backend)
    assert isinstance(cc._admin, ChaosAdminBackend)
    assert cc._admin.schedule.seed == 4
