"""Sensor exposition: Prometheus validity (label escaping, TYPE lines),
histogram semantics (bucket monotonicity, +Inf == _count), fleet series
removal, and concurrent recording under the ambient cluster label."""

import re
import threading

from cruise_control_tpu.utils.sensors import (
    DEFAULT_BUCKETS, SensorRegistry, cluster_label, escape_label_value,
)


def _parse_label_value(escaped: str) -> str:
    """Inverse of the exposition escaping (what a Prometheus parser does)."""
    out = []
    i = 0
    while i < len(escaped):
        c = escaped[i]
        if c == "\\" and i + 1 < len(escaped):
            nxt = escaped[i + 1]
            out.append({"\\": "\\", '"': '"', "n": "\n"}.get(nxt, nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def test_label_escaping_round_trip():
    nasty = 'quote " backslash \\ newline \n tail'
    r = SensorRegistry()
    r.count("requests", labels={"path": nasty})
    text = r.render()
    line = next(ln for ln in text.splitlines()
                if ln.startswith("kafka_cruisecontrol_requests_total{"))
    # The emitted line must be ONE line (raw newline would split the
    # sample and break the whole scrape).
    m = re.fullmatch(r'kafka_cruisecontrol_requests_total\{path="(.*)"\} '
                     r'1\.0', line)
    assert m, line
    assert _parse_label_value(m.group(1)) == nasty
    assert escape_label_value(nasty) == m.group(1)


def test_type_lines_for_counters_gauges_histograms():
    r = SensorRegistry()
    r.count("c")
    r.gauge("g", 1.0)
    r.observe("h", 0.2)
    text = r.render()
    assert "# TYPE kafka_cruisecontrol_c_total counter" in text
    assert "# TYPE kafka_cruisecontrol_g gauge" in text
    assert "# TYPE kafka_cruisecontrol_h histogram" in text
    # One TYPE line per family even with multiple label sets.
    r.count("c", labels={"x": "1"})
    assert r.render().count("# TYPE kafka_cruisecontrol_c_total") == 1


def test_histogram_buckets_monotone_and_inf_equals_count():
    r = SensorRegistry()
    values = [0.0004, 0.003, 0.003, 0.04, 0.9, 3.0, 100.0, 500.0]
    for v in values:
        r.observe("solve", v)
    text = r.render()
    pat = re.compile(
        r'kafka_cruisecontrol_solve_bucket\{le="([^"]+)"\} (\d+)')
    buckets = [(le, int(n)) for le, n in pat.findall(text)]
    assert buckets[-1][0] == "+Inf"
    counts = [n for _le, n in buckets]
    assert counts == sorted(counts), "cumulative buckets must be monotone"
    assert counts[-1] == len(values)
    assert f"kafka_cruisecontrol_solve_count {len(values)}" in text
    # every finite bound is parseable and ascending (log-spaced ladder)
    finite = [float(le) for le, _n in buckets[:-1]]
    assert finite == sorted(finite) and finite == list(DEFAULT_BUCKETS)


def test_histogram_quantile_estimates():
    r = SensorRegistry()
    for _ in range(99):
        r.observe("lat", 0.02)
    r.observe("lat", 30.0)
    p50 = r.quantile("lat", 0.50)
    p99 = r.quantile("lat", 0.99)
    assert p50 is not None and 0.01 <= p50 <= 0.025
    assert p99 is not None and p99 <= 0.025, \
        "p99 of 99x20ms + 1x30s still lands in the 25ms bucket"
    assert r.quantile("lat", 1.0) >= 25.0
    assert r.quantile("absent", 0.5) is None


def test_remove_labeled_drops_histogram_series():
    r = SensorRegistry()
    r.observe("span", 0.1, labels={"cluster": "a"})
    r.observe("span", 0.1, labels={"cluster": "b"})
    r.count("jobs", labels={"cluster": "a"})
    removed = r.remove_labeled("cluster", "a")
    assert removed == 2
    text = r.render()
    assert 'cluster="a"' not in text
    assert 'kafka_cruisecontrol_span_bucket{cluster="b"' in text


def test_concurrent_recording_under_cluster_label():
    r = SensorRegistry()
    n = 500
    errs = []

    def work(cid):
        try:
            with cluster_label(cid):
                for _ in range(n):
                    r.count("ops")
                    r.observe("lat", 0.01)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    threads = [threading.Thread(target=work, args=(c,)) for c in ("a", "b")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    # ContextVar scoping: each thread's records carry ITS cluster label,
    # with no cross-talk and no lost updates under contention.
    for cid in ("a", "b"):
        snap = r.histogram_snapshot("lat", labels={"cluster": cid})
        assert snap["count"] == n
        text = r.render()
        assert f'kafka_cruisecontrol_ops_total{{cluster="{cid}"}} {float(n)}' \
            in text


def test_clear_covers_histograms():
    r = SensorRegistry()
    r.observe("h", 0.5)
    r.clear()
    assert r.histogram_snapshot("h") is None
    assert "bucket" not in r.render()


def test_bucket_quantile_edge_cases_are_pinned():
    """The SLO engine's latency objectives call this hot: edges answer a
    NUMBER (0.0 / the bucket bound), never None/NaN (round 18)."""
    from cruise_control_tpu.utils.sensors import bucket_quantile
    # Empty window: all-zero counts -> 0.0.
    assert bucket_quantile((0.1, 1.0), [0, 0, 0], 0.99) == 0.0
    # No finite bounds at all -> 0.0.
    assert bucket_quantile((), [5], 0.5) == 0.0
    # Single-bucket layout answers its one bound.
    assert bucket_quantile((2.5,), [3, 1], 0.5) == 2.5
    # +Inf overflow clamps to the top finite bound.
    assert bucket_quantile((0.1, 1.0), [0, 0, 7], 0.99) == 1.0
    # A NaN can never escape: every answer compares equal to itself.
    for counts in ([0, 0, 0], [1, 0, 0], [0, 0, 9]):
        got = bucket_quantile((0.5, 5.0), counts, 0.99)
        assert got == got


def test_registry_quantile_none_only_for_absent_series():
    r = SensorRegistry()
    assert r.quantile("never_observed", 0.5) is None
    r.observe("lat", 0.2, buckets=(0.1, 1.0))
    assert r.quantile("lat", 0.5) is not None
    # Same name, different labels = a different (absent) series.
    assert r.quantile("lat", 0.5, labels={"cluster": "x"}) is None
