"""Predictive rebalancing (round 19): forecaster fit/projection,
monitor history export, predicted-anomaly lifecycle, and the
proactive-vs-reactive twin A/B.

Load-bearing contracts:

- fit + projection is ONE batched jitted program over the full
  partition axis (jit-cache counter pin, the megabatch discipline) and
  a pure function of the history tensor (byte-identical re-runs);
- pinned accuracy bounds on the round-11 DriftSpec diurnal ramp — the
  ground truth the whole subsystem is scored against;
- the predicted-anomaly lifecycle through the heal ledger:
  detected → predicted=true → fix (precompute) → proposal_ready, then
  cleared (via=prediction_confirmed) when the real violation lands and
  self_cleared (via=prediction_missed) when it never does;
- proactive beats reactive on SLO-violation ticks and goal-violation
  time-to-heal in the pinned diurnal-drift twin, with moves within
  band, at pinned seeds;
- off means off: forecast.enabled=false costs one config read per
  detector tick and never touches the monitor.
"""

import math
import zlib

import numpy as np
import pytest

from cruise_control_tpu.utils.sensors import SENSORS

FORECAST_OVERRIDES = {
    "forecast.enabled": True,
    "forecast.fit.windows": 16,
    "forecast.horizon.windows": 6,
    "forecast.seasonal.period.windows": 48,
}
PROACTIVE_OVERRIDES = {
    **FORECAST_OVERRIDES,
    "anomaly.detection.predictive.fix.enabled": True,
}


def _counter(name: str) -> float:
    return SENSORS._counters.get((name, ()), 0.0)


def _diurnal_history(num_w=16, num_p=12, num_r=4, amplitude=0.5,
                     period=48.0, seed=7):
    """Synthetic history shaped exactly like the round-11 DriftSpec
    diurnal ramp: base × (1 + A·sin(2πt/T)) per series."""
    rng_base = np.array(
        [[1.0 + (zlib.crc32(f"{seed}:{p}:{r}".encode()) % 1000) / 250.0
          for r in range(num_r)] for p in range(num_p)], dtype=np.float32)
    t = np.arange(num_w, dtype=np.float32)
    wave = 1.0 + amplitude * np.sin(2 * math.pi * t / period)
    return (rng_base[None] * wave[:, None, None]).astype(np.float32), \
        rng_base, wave


# ---------------------------------------------------------------------------
# Forecaster kernel

def test_fit_project_is_one_program_and_deterministic():
    import jax.numpy as jnp

    from cruise_control_tpu.forecast.forecaster import fit_project_loads
    hist, _base, _wave = _diurnal_history()
    cur = jnp.asarray(hist[-1])
    cache0 = fit_project_loads._cache_size()
    outs = []
    for _ in range(3):
        pl, pf, band, traj = fit_project_loads(
            jnp.asarray(hist), cur, cur * 0.5, 6, 48)
        outs.append((np.asarray(pl).tobytes(), np.asarray(pf).tobytes(),
                     np.asarray(band).tobytes(),
                     np.asarray(traj).tobytes()))
    # ONE compiled program serves every call of this shape (the no
    # per-partition-host-loop pin), and re-runs are byte-identical —
    # the projection is a pure function of the history tensor.
    assert fit_project_loads._cache_size() - cache0 == 1
    assert outs[0] == outs[1] == outs[2]
    digest = zlib.crc32(outs[0][0])
    assert digest == zlib.crc32(np.asarray(fit_project_loads(
        jnp.asarray(hist), cur, cur * 0.5, 6, 48)[0]).tobytes())


def test_projection_accuracy_on_diurnal_ramp():
    """Pinned accuracy on the DriftSpec ground truth: a trend+seasonal
    fit over 16 windows of a clean diurnal ramp must project the next
    6 windows within 2% relative error (measured ~1e-6; the bound
    leaves room for BLAS variation, not for a broken fit)."""
    import jax.numpy as jnp

    from cruise_control_tpu.forecast.forecaster import project_series
    hist, base, _wave = _diurnal_history()
    num_w, num_p, num_r = hist.shape
    proj, sigma = project_series(
        jnp.asarray(hist.reshape(num_w, -1)), 6, 48)
    t_future = num_w - 1 + np.arange(1, 7, dtype=np.float32)
    true = (base.reshape(-1)[None]
            * (1.0 + 0.5 * np.sin(2 * math.pi * t_future / 48.0))[:, None])
    rel = np.abs(np.asarray(proj) - true) / np.maximum(true, 1e-9)
    assert float(rel.max()) < 0.02
    # The confidence band is honest: a clean sinusoid fits tightly.
    assert float(np.asarray(sigma).max()) < 0.02 * float(base.max())


def test_model_view_rolling_mean():
    """The violation-scoring trajectory is the MODEL's view: for
    AVG-strategy resources, the rolling W-window mean over observed +
    projected windows (a raw-window view would predict violations the
    lagging model never reports)."""
    import jax.numpy as jnp

    from cruise_control_tpu.forecast.forecaster import (
        fit_project_loads, project_series,
    )
    hist, _b, _w = _diurnal_history(num_w=8)
    cur = jnp.asarray(hist[-1])
    horizon = 3
    _pl, _pf, _band, traj = fit_project_loads(
        jnp.asarray(hist), cur, cur, horizon, 48)
    raw, _sig = project_series(
        jnp.asarray(hist.reshape(8, -1)), horizon, 48)
    raw = np.asarray(raw).reshape(horizon, *hist.shape[1:])
    for h in range(1, horizon + 1):
        want = (hist[h:].sum(axis=0) + raw[:h].sum(axis=0)) / 8.0
        # NW_IN (col 1) is AVG-strategy -> rolling mean.
        np.testing.assert_allclose(np.asarray(traj)[h - 1, :, 1],
                                   want[:, 1], rtol=1e-5)
        # DISK (col 3) is LATEST-strategy -> raw projected window.
        np.testing.assert_allclose(np.asarray(traj)[h - 1, :, 3],
                                   raw[h - 1, :, 3], rtol=1e-5)


# ---------------------------------------------------------------------------
# Monitor history export seam

def _forecast_sim(extra=None, seed=0):
    from cruise_control_tpu.testing.simulator import (
        CANONICAL_SCENARIOS, ClusterSimulator,
    )
    spec = CANONICAL_SCENARIOS["diurnal_forecast_capacity"]
    return ClusterSimulator(spec, seed=seed,
                            config_overrides=extra or {})


def test_monitor_history_export_alignment():
    sim = _forecast_sim()
    # Not ready before enough stable windows accumulated.
    for t in range(4):
        sim.run_tick(t)
    assert sim.cc.load_monitor.load_history(16) is None
    for t in range(4, 20):
        sim.run_tick(t)
    out = sim.cc.load_monitor.load_history(16)
    assert out is not None
    history, window_ms, state, meta = out
    assert history.shape == (16, int(state.num_partitions), 4)
    assert window_ms == 60_000
    # Alignment: the last window's NW_IN per partition matches the
    # sampler's deterministic per-partition rates for LIVE rows.
    row = 0
    topic, part = meta.partition_index[row]
    assert history[-1, row, 1] > 0.0
    # Padded rows beyond the partition index stay zero.
    if state.num_partitions > len(meta.partition_index):
        assert float(history[:, len(meta.partition_index):, :].sum()) == 0.0


# ---------------------------------------------------------------------------
# Predicted-anomaly lifecycle (stubbed detector unit)

class _StubEngine:
    def __init__(self, results):
        self.enabled = True
        self._results = results
        self._i = 0
        self.last_result = None

    def forecast(self):
        r = self._results[min(self._i, len(self._results) - 1)]
        self._i += 1
        self.last_result = r
        return r


class _StubOptimizer:
    """goal_entry_stats stub: violation vectors keyed by id(state)."""

    def __init__(self, config, by_state):
        from cruise_control_tpu.analyzer.optimizer import goals_by_priority
        self._chain = goals_by_priority(
            config, config.get_list("anomaly.detection.goals"))
        self._by_state = by_state

    def goal_entry_stats(self, state, meta, goals=None, options=None):
        viol = np.asarray(self._by_state[id(state)], dtype=np.float64)
        return list(self._chain), viol, np.zeros_like(viol), 0


def test_predicted_lifecycle_confirm_and_miss():
    """Detector unit on stubs + a REAL ledger: a prediction opens a
    predicted=true chain; the real violation confirms it (cleared,
    via=prediction_confirmed); a prediction that lapses un-forecast
    self-clears (via=prediction_missed)."""
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.detector.manager import AnomalyDetectorManager
    from cruise_control_tpu.detector.predictive import (
        PredictiveViolationDetector,
    )
    from cruise_control_tpu.testing.simulator import SimClock

    cfg = CruiseControlConfig({"failed.brokers.file.path": ""})
    clock = SimClock()
    mgr = AnomalyDetectorManager(cfg, clock=clock)
    cur, proj_bad, proj_ok = object(), object(), object()

    class _Meta:
        topic_names: list = []

    class R:  # minimal ForecastResult stand-in
        def __init__(self, projected):
            self.generation = 0
            self.horizon_s = 120.0
            self.state = cur
            self.meta = _Meta()
            self.projected_state = projected
            self.band = np.zeros((1, 1))

    results = [R(proj_bad), R(proj_bad), R(proj_ok), R(proj_ok)]
    for i, r in enumerate(results):
        r.generation = i
    # Goals: detection chain has 2 entries by default config? Use the
    # configured anomaly.detection.goals; violations vector per state:
    # current clean; bad projection violates goal 0; ok projection
    # clean.
    chain_len = len(cfg.get_list("anomaly.detection.goals"))
    by_state = {id(cur): [0.0] * chain_len,
                id(proj_bad): [5.0] + [0.0] * (chain_len - 1),
                id(proj_ok): [0.0] * chain_len}
    eng = _StubEngine(results)
    det = PredictiveViolationDetector(
        cfg, eng, _StubOptimizer(cfg, by_state), mgr.report,
        ledger=mgr.heal_ledger, clock=clock)

    a = det.run_once()
    assert a is not None and a.predicted_goals
    assert det.state()["openPredictions"] == a.predicted_goals
    chains = mgr.heal_ledger.chains("PREDICTED_GOAL_VIOLATION")
    assert len(chains) == 1 and chains[0]["outcome"] is None
    predicted_phases = [p for p in chains[0]["phases"]
                        if p["phase"] == "predicted"]
    assert predicted_phases and predicted_phases[0]["predicted"] is True

    # CONFIRM: the real violation lands within the horizon.
    clock.advance(60.0)
    by_state[id(cur)] = [5.0] + [0.0] * (chain_len - 1)
    confirmed0 = _counter("anomaly_predicted_confirmed")
    assert det.run_once() is None     # predicted - now = empty
    assert _counter("anomaly_predicted_confirmed") == confirmed0 + 1
    chain = mgr.heal_ledger.chains("PREDICTED_GOAL_VIOLATION")[0]
    assert chain["outcome"] == "cleared"
    assert chain["phases"][-1]["via"] == "prediction_confirmed"

    # MISS: a fresh prediction that lapses while no longer forecast.
    by_state[id(cur)] = [0.0] * chain_len
    eng._results = [R(proj_bad), R(proj_ok), R(proj_ok)]
    for i, r in enumerate(eng._results):
        r.generation = 10 + i
    eng._i = 0
    a2 = det.run_once()
    assert a2 is not None
    missed0 = _counter("anomaly_predicted_missed")
    clock.advance(60.0)
    det.run_once()                    # still inside horizon: stays open
    assert _counter("anomaly_predicted_missed") == missed0
    clock.advance(120.1)              # past the (refreshed) deadline
    det.run_once()
    assert _counter("anomaly_predicted_missed") == missed0 + 1
    chain2 = mgr.heal_ledger.chains("PREDICTED_GOAL_VIOLATION")[0]
    assert chain2["outcome"] == "self_cleared"
    assert chain2["phases"][-1]["via"] == "prediction_missed"


def test_forecast_off_means_off():
    """forecast.enabled=false: the detector tick is a no-op that never
    touches the monitor, and serving behavior is unchanged (the pinned
    scenario digests in test_simulator are the byte-level guard)."""
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.detector.predictive import (
        PredictiveViolationDetector,
    )

    class Exploding:
        enabled = False

        def forecast(self):  # pragma: no cover - must never run
            raise AssertionError("disabled engine was consulted")

    cfg = CruiseControlConfig({"failed.brokers.file.path": ""})
    det = PredictiveViolationDetector(cfg, Exploding(), None,
                                      lambda a: None)
    assert det.run_once() is None


# ---------------------------------------------------------------------------
# Twin integration: precompute mode + the proactive-vs-reactive A/B

@pytest.mark.slow
def test_precompute_mode_feeds_warm_store_and_confirms():
    """Forecast ON, proactive execution OFF (the default): the
    prediction's fix PRECOMPUTES — warm-seed store filled from the
    projected target, pacer flag raised, nothing executed — and the
    chain confirms (cleared via=prediction_confirmed) when the real
    violation lands."""
    sim = _forecast_sim(FORECAST_OVERRIDES)
    precomputes0 = _counter("anomaly_predicted_precomputes")
    for t in range(26):
        sim.run_tick(t)
    assert _counter("anomaly_predicted_precomputes") >= precomputes0 + 1
    assert sim.cc._warm_seeds._seed is not None
    assert sim.cc.predicted_precompute_pending
    chains = sim.cc.heal_ledger.chains("PREDICTED_GOAL_VIOLATION")
    assert chains, "no predicted chain opened"
    newest = chains[0]
    phases = {p["phase"] for p in newest["phases"]}
    assert {"predicted", "fix_started", "predictive_solve",
            "proposal_ready"} <= phases
    # Precompute mode does not prevent the violation: the real one
    # lands and confirms the prediction.
    assert newest["outcome"] == "cleared"
    assert newest["phases"][-1]["via"] == "prediction_confirmed"
    # The REACTIVE heal still ran (its own chain, warm-seeded solve
    # available to it).
    assert sim.cc.heal_ledger.chains("GOAL_VIOLATION")
    # Serving surface sanity: every broker row carries the full
    # current/projected/band triple the endpoint documents.
    body = sim.cc.forecast_state()
    assert body["forecastEnabled"] is True
    assert body["detector"]["predictionsConfirmed"] >= 1
    per_broker = body["forecast"]["perBroker"]
    assert per_broker
    for loads in per_broker.values():
        for cell in loads.values():
            assert {"current", "projected", "band"} <= set(cell)
            assert cell["band"] >= 0.0


def _run_arm(overrides, seed):
    sim = _forecast_sim(overrides, seed=seed)
    for t in range(sim.spec.ticks):
        sim.run_tick(t)
    return sim


def _strict_slo_ticks(score, floor=99.5):
    return sum(1 for b in score.balancedness if b < floor)


@pytest.mark.parametrize("seed", [0])
def test_proactive_beats_reactive(seed):
    """The acceptance A/B at the pinned seed: proactive ≤ reactive on
    strict SLO-violation ticks (strictly fewer when reactive has any)
    and on goal-violation time-to-heal, with replica moves within a
    2.5x band. Seed 1 runs in the slow tier
    (test_proactive_beats_reactive_second_seed)."""
    rsim = _run_arm({}, seed)
    psim = _run_arm(PROACTIVE_OVERRIDES, seed)
    r_ticks = _strict_slo_ticks(rsim.score)
    p_ticks = _strict_slo_ticks(psim.score)
    assert r_ticks >= 1, "scenario lost its reactive violation window"
    assert p_ticks < r_ticks
    # Goal-violation time-to-heal via the heal ledger on the sim clock:
    # the proactive arm prevents the violation, so it has no (or
    # strictly faster) GOAL_VIOLATION heals.
    def p95(vals):
        if not vals:
            return 0.0
        return vals[min(len(vals) - 1, math.ceil(0.95 * len(vals)) - 1)]

    r_heals = rsim.cc.heal_ledger.heal_durations_s("GOAL_VIOLATION")
    p_heals = psim.cc.heal_ledger.heal_durations_s("GOAL_VIOLATION")
    assert r_heals, "reactive arm healed nothing to compare against"
    assert p95(p_heals) < p95(r_heals)
    # Moves-per-simhour band: proactive must not buy its win with
    # unbounded churn.
    assert psim.score.replica_moves \
        <= max(6, int(2.5 * rsim.score.replica_moves))
    # The proactive arm's prediction lifecycle closed honestly.
    det = psim.cc.predictive_detector.state()
    assert det["predictionsMade"] >= 1
    assert (det["predictionsAverted"] + det["predictionsConfirmed"]) >= 1


@pytest.mark.slow
@pytest.mark.parametrize("seed", [1])
def test_proactive_beats_reactive_second_seed(seed):
    rsim = _run_arm({}, seed)
    psim = _run_arm(PROACTIVE_OVERRIDES, seed)
    assert _strict_slo_ticks(psim.score) <= _strict_slo_ticks(rsim.score)
    assert psim.score.replica_moves \
        <= max(6, int(2.5 * rsim.score.replica_moves))


def test_proactive_run_is_deterministic():
    """Byte-identical score JSON at one seed — the same determinism
    contract every other scenario carries, now with the forecaster in
    the loop."""
    a = _forecast_sim(PROACTIVE_OVERRIDES, seed=0)
    b = _forecast_sim(PROACTIVE_OVERRIDES, seed=0)
    for t in range(14):
        a.run_tick(t)
        b.run_tick(t)
    sa, sb = a._snapshot(), b._snapshot()
    assert sa == sb


def test_engine_single_flight_under_concurrency():
    """Concurrent forecast() calls for one uncached generation share ONE
    history export + fit (the detector tick, a /forecast?refresh request
    and a futures worker must not race three byte-identical model builds
    last-writer-wins), and last_result reads stay lock-free."""
    import threading

    from cruise_control_tpu.forecast.engine import ForecastEngine

    sim = _forecast_sim()
    for t in range(20):
        sim.run_tick(t)
    mon = sim.cc.load_monitor
    calls = []
    orig = mon.load_history

    def counting(n):
        calls.append(1)
        return orig(n)

    mon.load_history = counting

    class _Cfg:
        def get_boolean(self, k):
            return True

        def get_int(self, k):
            return {"forecast.fit.windows": 8,
                    "forecast.horizon.windows": 2,
                    "forecast.seasonal.period.windows": 0}[k]

    eng = ForecastEngine(_Cfg(), mon)
    outs = [None] * 4
    threads = [threading.Thread(
        target=lambda i=i: outs.__setitem__(i, eng.forecast()))
        for i in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert outs[0] is not None
    assert all(o is outs[0] for o in outs)
    assert len(calls) == 1
    # The published result is re-served generation-cached.
    assert eng.forecast() is outs[0]
    assert len(calls) == 1


def test_forecast_state_refresh_falls_back_to_cache():
    """GET /forecast?refresh=true serves the CACHED projection when the
    fresh fit is not ready (refresh means 'at least as fresh as the
    cache'), and a disabled engine serves null even with a pre-flip fit
    still cached (off means off)."""
    sim = _forecast_sim(FORECAST_OVERRIDES)
    for t in range(20):
        sim.run_tick(t)
    cc = sim.cc
    body = cc.forecast_state(refresh=True)
    assert body["forecast"] is not None
    cached_gen = body["forecast"]["generation"]
    # Fresh fit impossible (monitor export refuses) but a cache exists:
    # refresh still serves the cached projection.
    mon = cc.load_monitor
    orig = mon.load_history
    mon.load_history = lambda n: None
    sim.run_tick(20)  # generation advances past the cached fit
    body = cc.forecast_state(refresh=True)
    assert body["forecast"] is not None
    assert body["forecast"]["generation"] == cached_gen
    mon.load_history = orig
    # Disabled: null, even though the engine still holds a cached fit.
    cc.config._values["forecast.enabled"] = False
    try:
        body = cc.forecast_state(refresh=True)
        assert body["forecastEnabled"] is False
        assert body["forecast"] is None
    finally:
        cc.config._values["forecast.enabled"] = True


def test_prediction_lapses_when_forecast_unavailable():
    """A monitor that loses its stable windows (engine.forecast() ->
    None) must not freeze open predictions forever: with no current
    forecast backing the 'still predicted' claim, an open prediction
    lapses to self_cleared (via=prediction_missed) once its deadline
    passes."""
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.detector.manager import AnomalyDetectorManager
    from cruise_control_tpu.detector.predictive import (
        PredictiveViolationDetector,
    )
    from cruise_control_tpu.testing.simulator import SimClock

    cfg = CruiseControlConfig({"failed.brokers.file.path": ""})
    clock = SimClock()
    mgr = AnomalyDetectorManager(cfg, clock=clock)
    cur, proj_bad = object(), object()

    class _Meta:
        topic_names: list = []

    class R:
        generation = 0
        horizon_s = 120.0
        state = cur
        meta = _Meta()
        projected_state = proj_bad
        band = np.zeros((1, 1))

    chain_len = len(cfg.get_list("anomaly.detection.goals"))
    by_state = {id(cur): [0.0] * chain_len,
                id(proj_bad): [5.0] + [0.0] * (chain_len - 1)}
    eng = _StubEngine([R()])
    det = PredictiveViolationDetector(
        cfg, eng, _StubOptimizer(cfg, by_state), mgr.report,
        ledger=mgr.heal_ledger, clock=clock)
    assert det.run_once() is not None
    assert det.state()["openPredictions"]

    # The monitor loses its windows: every later tick has no forecast.
    eng.forecast = lambda: None
    missed0 = _counter("anomaly_predicted_missed")
    clock.advance(60.0)
    det.run_once()                    # inside the horizon: stays open
    assert det.state()["openPredictions"]
    clock.advance(120.1)              # past the deadline: must lapse
    det.run_once()
    assert not det.state()["openPredictions"]
    assert _counter("anomaly_predicted_missed") == missed0 + 1
    chain = mgr.heal_ledger.chains("PREDICTED_GOAL_VIOLATION")[0]
    assert chain["outcome"] == "self_cleared"
    assert chain["phases"][-1]["via"] == "prediction_missed"
