"""Aggregator tests.

Mirrors the behaviors of core MetricSampleAggregatorTest / RawMetricValues:
window rolling, AVG/MAX/LATEST reduction, the four extrapolation categories,
completeness gating, and generation bumping.
"""

import numpy as np
import pytest

from cruise_control_tpu.metricdef.metricdef import MetricDef, ValueComputingStrategy as S
from cruise_control_tpu.monitor.aggregator import (
    AggregationOptions, Extrapolation, Granularity, MetricSampleAggregator,
    NotEnoughValidWindowsError,
)

WINDOW_MS = 1000


def make_def():
    d = MetricDef()
    d.define("avg_m", S.AVG)
    d.define("max_m", S.MAX)
    d.define("latest_m", S.LATEST)
    return d


def agg(num_windows=4, min_samples=2, group_fn=None):
    return MetricSampleAggregator(num_windows, WINDOW_MS, min_samples, make_def(),
                                  group_fn=group_fn)


def fill_window(a, entity, window, n, base=10.0):
    for i in range(n):
        a.add_sample(entity, window * WINDOW_MS + i, np.array([base + i, base + i, base + i]))


def test_avg_max_latest_reduction():
    a = agg()
    # Fill windows 0..3 (3 is still "current"; stable = 0..2 after rolling to 3).
    for w in range(4):
        fill_window(a, "e0", w, 2, base=10.0 * (w + 1))
    res = a.aggregate(AggregationOptions(min_valid_windows=1))
    assert res.window_indices == [0, 1, 2]
    vals = res.values[0]  # [M, W]
    # AVG: (10+11)/2=10.5 in window 0
    assert vals[0, 0] == pytest.approx(10.5)
    # MAX: max(10,11)=11
    assert vals[1, 0] == pytest.approx(11.0)
    # LATEST: last value wins
    assert vals[2, 0] == pytest.approx(11.0)
    assert (res.extrapolations[0] == Extrapolation.NONE).all()
    assert res.entity_valid[0]


def test_avg_available_extrapolation():
    a = agg(min_samples=4)  # half-min = 2
    for w in range(4):
        n = 2 if w == 1 else 4  # window 1 has only half the required samples
        fill_window(a, "e0", w, n)
    res = a.aggregate(AggregationOptions(min_valid_windows=1))
    cats = res.extrapolations[0]
    assert cats[0] == Extrapolation.NONE
    assert cats[1] == Extrapolation.AVG_AVAILABLE
    assert res.entity_valid[0]  # extrapolated but valid


def test_avg_adjacent_extrapolation():
    a = agg(min_samples=2)
    for w in range(4):
        if w == 1:
            continue  # window 1 empty; neighbours 0 and 2 are full
        fill_window(a, "e0", w, 2, base=30.0)
    res = a.aggregate(AggregationOptions(min_valid_windows=1))
    cats = res.extrapolations[0]
    assert cats[1] == Extrapolation.AVG_ADJACENT
    # AVG metric: (sum0 + 0 + sum2) / (2 + 0 + 2) = avg of neighbours
    assert res.values[0][0, 1] == pytest.approx(30.5)
    # MAX metric: (31 + 31) / 2
    assert res.values[0][1, 1] == pytest.approx(31.0)
    assert res.entity_valid[0]


def test_forced_insufficient_and_no_valid():
    a = agg(min_samples=4)  # half-min = 2
    # window 0: 1 sample (< half-min, edge → FORCED_INSUFFICIENT)
    fill_window(a, "e0", 0, 1)
    # window 1: 0 samples, neighbours not both full → NO_VALID
    fill_window(a, "e0", 2, 1)
    a.store.roll_to(3)
    res = a.aggregate(AggregationOptions(min_valid_windows=1,
                                         include_invalid_entities=True))
    cats = res.extrapolations[0]
    assert cats[0] == Extrapolation.FORCED_INSUFFICIENT
    assert cats[1] == Extrapolation.NO_VALID_EXTRAPOLATION
    assert not res.entity_valid[0]  # window 1 invalid → entity invalid


def test_window_rolling_drops_old():
    a = agg(num_windows=2)
    fill_window(a, "e0", 0, 2)
    fill_window(a, "e0", 10, 2)  # far future roll; old windows reset
    assert a.available_windows() == [8, 9]
    # windows 8,9 are empty (counts reset); only current window 10 has data.
    assert a.num_samples() == 2


def test_late_sample_dropped():
    a = agg(num_windows=2)
    fill_window(a, "e0", 5, 2)
    assert not a.add_sample("e0", 0 * WINDOW_MS, np.zeros(3))


def test_completeness_entity_ratio_gate():
    a = agg(min_samples=1)
    for w in range(4):
        fill_window(a, "good", w, 1)
    fill_window(a, "sparse", 0, 1)  # sparse entity misses windows 1,2
    opts = AggregationOptions(min_valid_entity_ratio=0.9, min_valid_windows=3)
    with pytest.raises(NotEnoughValidWindowsError):
        a.aggregate(opts)
    # Lower the bar: all 3 stable windows pass at 50% entity coverage.
    res = a.aggregate(AggregationOptions(min_valid_entity_ratio=0.5, min_valid_windows=3))
    assert len(res.window_indices) == 3


def test_entity_group_granularity():
    # Two entities in the same group (topic); one sparse entity poisons the
    # group under ENTITY_GROUP granularity.
    group_fn = lambda e: e.split("-")[0]
    a = agg(min_samples=1, group_fn=group_fn)
    for w in range(4):
        fill_window(a, "t1-p0", w, 1)
        fill_window(a, "t2-p0", w, 1)
    fill_window(a, "t1-p1", 0, 1)  # t1-p1 invalid in windows 1,2
    res_e = a.completeness(AggregationOptions(min_valid_windows=1,
                                              granularity=Granularity.ENTITY))
    res_g = a.completeness(AggregationOptions(min_valid_windows=1,
                                              granularity=Granularity.ENTITY_GROUP))
    # Under ENTITY granularity windows 1,2 have 2/3 coverage; under
    # ENTITY_GROUP the t1 group is invalid there so coverage drops to 1/3.
    assert res_e.valid_entity_ratio_by_window[1] == pytest.approx(2 / 3)
    assert res_g.valid_entity_ratio_by_window[1] == pytest.approx(1 / 3)


def test_generation_bumps_and_cache():
    a = agg(min_samples=1)
    g0 = a.generation
    fill_window(a, "e0", 0, 1)
    assert a.generation > g0
    for w in range(1, 4):
        fill_window(a, "e0", w, 1)
    r1 = a.aggregate(AggregationOptions())
    r2 = a.aggregate(AggregationOptions())
    assert r1 is r2  # cached at same generation
    fill_window(a, "e0", 3, 1)
    r3 = a.aggregate(AggregationOptions())
    assert r3 is not r1


def test_batch_ingest_matches_loop():
    a1 = agg(min_samples=1)
    a2 = agg(min_samples=1)
    ents = [f"p{i}" for i in range(5)]
    vals = np.arange(15, dtype=np.float32).reshape(5, 3)
    for w in range(4):
        for i, e in enumerate(ents):
            a1.add_sample(e, w * WINDOW_MS, vals[i])
        a2.add_samples_batch(ents, w * WINDOW_MS, vals)
    r1 = a1.aggregate(AggregationOptions())
    r2 = a2.aggregate(AggregationOptions())
    np.testing.assert_allclose(r1.values, r2.values)


def test_remove_and_retain_entities():
    a = agg(min_samples=1)
    for w in range(4):
        for e in ("a", "b", "c"):
            fill_window(a, e, w, 1)
    a.remove_entities(["b"])
    res = a.aggregate(AggregationOptions())
    assert res.entities == ["a", "c"]
    a.retain_entities(["c"])
    res = a.aggregate(AggregationOptions())
    assert res.entities == ["c"]
