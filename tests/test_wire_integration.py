"""Integration tier: real wire bytes over real sockets.

The reference's outermost test tier boots broker JVMs
(CCKafkaIntegrationTestHarness, CruiseControlIntegrationTestHarness.java:17).
Here the embedded wire-conformant broker (kafka/wire/broker.py) plays that
role: every test round-trips through BOTH codec stacks (client encode →
socket → broker decode → broker encode → socket → client decode), so a
schema error on either side fails loudly.

Tiers covered:
1. codec unit round-trips (types, records, crc32c known answers)
2. WireClient ↔ EmbeddedKafkaCluster per-API conformance
3. the three bindings (admin/transport/sample store) over the wire
4. the EXECUTOR running a real proposal against the embedded cluster
   through KafkaAdminBackend — the full inter-broker + leadership flow.
"""

from __future__ import annotations

import pytest

from cruise_control_tpu.kafka import (
    KafkaAdminBackend, KafkaMetricsTransport, KafkaSampleStore,
)
from cruise_control_tpu.kafka.wire import messages as m
from cruise_control_tpu.kafka.wire.broker import EmbeddedKafkaCluster
from cruise_control_tpu.kafka.wire.client import WireClient
from cruise_control_tpu.kafka.wire.crc32c import crc32c
from cruise_control_tpu.kafka.wire.records import (
    Record, decode_batches, encode_batch,
)
from cruise_control_tpu.kafka.wire.types import (
    Array, Boolean, CompactArray, CompactNullableString, CompactString,
    Int8, Int16, Int32, Int64, NullableString, String, Struct, UVarInt,
    VarInt, decode, encode,
)


# ---------------------------------------------------------------------------
# tier 1: codecs
# ---------------------------------------------------------------------------

def test_crc32c_known_answers():
    # RFC 3720 / common test vectors for Castagnoli.
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"") == 0
    assert crc32c(b"\x00" * 32) == 0x8A9136AA


def test_varint_zigzag_roundtrip():
    for v in (0, 1, -1, 63, -64, 300, -300, 2**31 - 1, -(2**31)):
        assert decode(VarInt, encode(VarInt, v)) == v


def test_uvarint_boundaries():
    for v in (0, 127, 128, 16383, 16384, 2**32 - 1):
        assert decode(UVarInt, encode(UVarInt, v)) == v


def test_struct_roundtrip_classic_and_flexible():
    classic = Struct(("a", Int32), ("b", NullableString),
                     ("c", Array(Int16)))
    v = {"a": 7, "b": None, "c": [1, 2, 3]}
    assert decode(classic, encode(classic, v)) == v

    flexible = Struct(("x", CompactString), ("y", CompactNullableString),
                      ("z", CompactArray(Int64)), flexible=True)
    v = {"x": "hello", "y": None, "z": [10, -10]}
    assert decode(flexible, encode(flexible, v)) == v


def test_record_batch_roundtrip_and_crc_guard():
    recs = [Record(100, 5000, b"k", b"v"),
            Record(101, 5001, None, b"w", [("h", b"x"), ("i", None)])]
    data = encode_batch(recs)
    back = decode_batches(data)
    assert [(r.offset, r.timestamp_ms, r.key, r.value) for r in back] == \
        [(100, 5000, b"k", b"v"), (101, 5001, None, b"w")]
    assert back[1].headers == [("h", b"x"), ("i", None)]
    # flip a payload byte -> CRC must catch it
    corrupted = bytearray(data)
    corrupted[-1] ^= 0xFF
    with pytest.raises(ValueError, match="CRC"):
        decode_batches(bytes(corrupted))
    # partial trailing batch is dropped, not an error
    assert len(decode_batches(data + data[:7])) == 2


def test_all_api_schemas_have_distinct_keys():
    assert len(m.BY_KEY) == len(m.ALL_APIS)


# ---------------------------------------------------------------------------
# tier 2: client ↔ embedded broker
# ---------------------------------------------------------------------------

@pytest.fixture()
def cluster():
    c = EmbeddedKafkaCluster(
        num_brokers=3, racks={0: "r0", 1: "r1", 2: "r2"}).start()
    yield c
    c.stop()


@pytest.fixture()
def client(cluster):
    c = WireClient(cluster.bootstrap_servers)
    yield c
    c.close()


def test_api_versions_and_metadata(cluster, client):
    versions = client.api_versions()
    assert set(versions) == {a.key for a in m.ALL_APIS}
    assert client.alive_broker_ids() == {0, 1, 2}
    meta = client.metadata()
    assert meta["controller_id"] == 0
    assert {b["rack"] for b in meta["brokers"]} == {"r0", "r1", "r2"}


def test_create_topic_and_partition_metadata(cluster, client):
    assert client.create_topic("t", 4, 2) == m.NONE
    assert client.create_topic("t", 4, 2) == m.TOPIC_ALREADY_EXISTS
    parts = client.partitions_for("t")
    assert set(parts) == {0, 1, 2, 3}
    for p in parts.values():
        assert len(p["replicas"]) == 2
        assert p["leader"] == p["replicas"][0]


def test_produce_fetch_list_offsets(cluster, client):
    client.create_topic("data", 1, 1)
    base = client.produce("data", 0, [
        Record(0, 1000, None, b"a"), Record(1, 2000, None, b"b"),
        Record(2, 3000, None, b"c")])
    assert base == 0
    recs, hw = client.fetch("data", 0, 1)
    assert hw == 3 and [r.value for r in recs] == [b"b", b"c"]
    # timestamp index (KIP-79 semantics)
    assert client.list_offsets("data", 0, 1500)[0] == 1
    assert client.list_offsets("data", 0, m.LATEST_TIMESTAMP)[0] == 3
    assert client.list_offsets("data", 0, m.EARLIEST_TIMESTAMP)[0] == 0
    assert client.list_offsets("data", 0, 9999)[0] == -1  # nothing after


def test_incremental_configs_set_and_delete(cluster, client):
    client.create_topic("cfg", 1, 1)
    client.incremental_alter_configs(
        m.RESOURCE_TOPIC, {"cfg": {"retention.ms": "60000"}})
    assert client.describe_configs(m.RESOURCE_TOPIC, ["cfg"]) == \
        {"cfg": {"retention.ms": "60000"}}
    client.incremental_alter_configs(
        m.RESOURCE_BROKER, {2: {"follower.replication.throttled.rate": "1"}})
    assert client.describe_configs(m.RESOURCE_BROKER, [2])["2"] == \
        {"follower.replication.throttled.rate": "1"}
    # delete = None (OP_DELETE on the wire)
    client.incremental_alter_configs(
        m.RESOURCE_TOPIC, {"cfg": {"retention.ms": None}})
    assert client.describe_configs(m.RESOURCE_TOPIC, ["cfg"]) == {"cfg": {}}


def test_reassignment_flow_flexible_encoding(cluster, client):
    """KIP-455 over compact/tagged encodings — the APIs with no classic
    version, so this is the flexible codec's conformance test."""
    cluster.auto_complete = False
    client.create_topic("ra", 1, 2)
    before = client.partitions_for("ra")[0]["replicas"]
    target = [b for b in (0, 1, 2) if b not in before[:1]][:2]
    client.alter_partition_reassignments({("ra", 0): target})
    inflight = client.list_partition_reassignments()
    assert ("ra", 0) in inflight
    assert set(inflight[("ra", 0)]["adding"]) == set(target) - set(before)
    cluster.complete_reassignments()
    assert client.list_partition_reassignments() == {}
    assert client.partitions_for("ra")[0]["replicas"] == target
    # cancelling nothing is tolerated (NO_REASSIGNMENT_IN_PROGRESS)
    client.alter_partition_reassignments({("ra", 0): None})


def test_elect_leaders_preferred(cluster, client):
    cluster.create_topic("el", 1, 2, assignment={0: [1, 2]})
    p = cluster.topics["el"].partitions[0]
    p.leader = 2  # non-preferred
    client.elect_leaders([("el", 0)])
    assert client.partitions_for("el")[0]["leader"] == 1
    # already preferred -> ELECTION_NOT_NEEDED is tolerated
    client.elect_leaders([("el", 0)])


def test_log_dirs_describe_and_alter(cluster, client):
    cluster.create_topic("jb", 2, 1, assignment={0: [1], 1: [1]})
    dirs = client.describe_log_dirs(1)
    assert {d["log_dir"] for d in dirs} == {"/data/d0", "/data/d1"}
    failed = client.alter_replica_log_dirs(1, {"/data/d1": {"jb": [0]}})
    assert failed == []
    d1 = next(d for d in client.describe_log_dirs(1)
              if d["log_dir"] == "/data/d1")
    assert [(t["name"], [p["partition_index"] for p in t["partitions"]])
            for t in d1["topics"]] == [("jb", [0])]
    # unknown dir + offline dir produce per-partition error codes
    assert client.alter_replica_log_dirs(1, {"/nope": {"jb": [1]}}) == \
        [("jb", 1, m.LOG_DIR_NOT_FOUND)]
    cluster.set_logdir_health(1, "/data/d0", False)
    codes = {d["log_dir"]: d["error_code"]
             for d in client.describe_log_dirs(1)}
    assert codes["/data/d0"] == m.KAFKA_STORAGE_ERROR


def test_dead_broker_connection_refused(cluster, client):
    cluster.create_topic("kb", 1, 1, assignment={0: [2]})
    cluster.kill_broker(2)
    assert client.alive_broker_ids() == {0, 1}
    with pytest.raises(ConnectionError):
        client.describe_log_dirs(2)


# ---------------------------------------------------------------------------
# tier 3: bindings over the wire
# ---------------------------------------------------------------------------

def test_admin_backend_describe_partitions(cluster):
    admin = KafkaAdminBackend(cluster.bootstrap_servers)
    cluster.create_topic("t", 2, 2)
    parts = admin.describe_partitions()
    assert set(parts) == {("t", 0), ("t", 1)}
    st = parts[("t", 0)]
    assert st.leader in st.replicas and not st.is_reassigning
    assert admin.alive_brokers() == {0, 1, 2}
    admin.close()


def test_admin_backend_reassignment_and_adoption_view(cluster):
    cluster.auto_complete = False
    cluster.create_topic("mv", 1, 2, assignment={0: [0, 1]})
    admin = KafkaAdminBackend(cluster.bootstrap_servers)
    admin.alter_partition_reassignments({("mv", 0): (1, 2)})
    assert admin.list_reassigning_partitions() == [("mv", 0)]
    st = admin.describe_partitions()[("mv", 0)]
    assert st.is_reassigning and set(st.adding) == {2} \
        and set(st.removing) == {0}
    admin.cancel_partition_reassignments([("mv", 0)])
    assert admin.list_reassigning_partitions() == []
    admin.close()


def test_admin_backend_throttle_configs_incremental(cluster):
    """ReplicationThrottleHelper's set/clear cycle — now real KIP-339
    increments (round 2 emulated them with describe+merge on the legacy
    API)."""
    cluster.create_topic("th", 1, 1)
    admin = KafkaAdminBackend(cluster.bootstrap_servers)
    admin.alter_broker_configs(
        {0: {"leader.replication.throttled.rate": "1000"},
         1: {"leader.replication.throttled.rate": "1000"}})
    admin.alter_topic_configs(
        {"th": {"leader.replication.throttled.replicas": "0:0"}})
    assert admin.describe_broker_configs([0, 1]) == {
        0: {"leader.replication.throttled.rate": "1000"},
        1: {"leader.replication.throttled.rate": "1000"}}
    # clear = None value
    admin.alter_broker_configs(
        {0: {"leader.replication.throttled.rate": None}})
    assert admin.describe_broker_configs([0]) == {0: {}}
    admin.close()


def test_admin_backend_jbod_surface(cluster):
    cluster.create_topic("jb", 1, 1, assignment={0: [0]})
    admin = KafkaAdminBackend(cluster.bootstrap_servers)
    assert admin.describe_logdirs()[0] == {"/data/d0": True,
                                           "/data/d1": True}
    assert admin.replica_logdirs([0]) == {("jb", 0, 0): "/data/d0"}
    failed = admin.alter_replica_logdirs([(("jb", 0), 0, "/data/d1")])
    assert failed == []
    assert admin.replica_logdirs([0]) == {("jb", 0, 0): "/data/d1"}
    # rejected move surfaces the key, not an exception
    failed = admin.alter_replica_logdirs([(("jb", 0), 0, "/missing")])
    assert failed == [("jb", 0, 0)]
    admin.close()


def test_metrics_transport_window_poll(cluster):
    transport = KafkaMetricsTransport(cluster.bootstrap_servers,
                                      num_partitions=4)
    transport.ensure_topic()
    transport.ensure_topic()  # idempotent
    for i in range(10):
        transport.produce(b"payload-%d" % i)
    transport.flush()
    now_ms = __import__("time").time() * 1000
    got = transport.poll(int(now_ms - 60_000), int(now_ms + 60_000))
    assert sorted(got) == sorted(b"payload-%d" % i for i in range(10))
    # a window in the past matches nothing
    assert transport.poll(0, 1000) == []
    transport.close()


def test_sample_store_roundtrip(cluster):
    from cruise_control_tpu.monitor.sampling.sampler import SamplerResult
    from cruise_control_tpu.monitor.sampling.samples import (
        BrokerEntity, BrokerMetricSample, PartitionEntity,
        PartitionMetricSample,
    )

    store = KafkaSampleStore(cluster.bootstrap_servers, num_partitions=2)
    result = SamplerResult(
        partition_samples=[PartitionMetricSample(
            PartitionEntity("t", 0), 1_000, (1.0, 2.0, 3.0, 4.0))],
        broker_samples=[BrokerMetricSample(
            BrokerEntity(1), 1_000, (0.5,) * 4)],
        skipped_partitions=0)
    store.store_samples(result)
    replayed = store.load_samples()
    assert len(replayed.partition_samples) == 1
    assert replayed.partition_samples[0].entity == PartitionEntity("t", 0)
    assert list(replayed.partition_samples[0].values) == [1.0, 2.0, 3.0, 4.0]
    assert len(replayed.broker_samples) == 1
    store.close()


def test_sample_replay_survives_retention_trim(cluster):
    """Warm-start replay must begin at the LOG-START offset, not 0:
    cleanup.policy=delete advances the log start on a real cluster, and a
    fetch(0) would be OFFSET_OUT_OF_RANGE — silently skipping the whole
    partition (KafkaSampleStore.loadSamples uses earliest, not 0)."""
    from cruise_control_tpu.monitor.sampling.sampler import SamplerResult
    from cruise_control_tpu.monitor.sampling.samples import (
        PartitionEntity, PartitionMetricSample,
    )

    store = KafkaSampleStore(cluster.bootstrap_servers, num_partitions=1)
    for i in range(6):
        store.store_samples(SamplerResult(
            [PartitionMetricSample(PartitionEntity("t", i), 1000 + i,
                                   (float(i),) * 4)], [], 0))
    topic = store._topics["partition"]
    cluster.trim_log(topic, 0, 3)
    replayed = store.load_samples()
    assert sorted(s.entity.partition for s in replayed.partition_samples) \
        == [3, 4, 5]
    store.close()


def test_controller_failover_reroutes_admin_ops(cluster, client):
    """Killing the controller must not wedge controller-routed admin ops:
    the client re-resolves the controller and retries."""
    client.create_topic("cf", 1, 2)
    assert client._controller_id == 0
    cluster.kill_broker(0)
    client.create_topic("cf2", 1, 1)  # must reroute to the new controller
    assert client._controller_id != 0
    assert "cf2" in cluster.topics


def test_fetch_paginates_whole_batches(cluster, client):
    """A byte-budget smaller than the full window must yield complete
    batches that make progress, never a truncated batch that decodes to []
    and reads as end-of-data (silent data loss)."""
    client.create_topic("page", 1, 1)
    payload = b"x" * 1000
    client.produce("page", 0, [Record(i, 1000 + i, None, payload)
                               for i in range(20)])
    got, offset = [], 0
    for _ in range(50):
        records, hw = client.fetch("page", 0, offset, max_bytes=2048)
        if not records:
            break
        got.extend(records)
        offset = records[-1].offset + 1
        if offset >= hw:
            break
    assert [r.offset for r in got] == list(range(20))


def test_transport_requeues_batch_on_broker_outage(cluster):
    transport = KafkaMetricsTransport(cluster.bootstrap_servers,
                                      num_partitions=1)
    transport.ensure_topic()
    transport.produce(b"survives")
    for b in list(cluster.broker_ids):
        cluster.kill_broker(b)
    with pytest.raises((ConnectionError, m.KafkaProtocolError)):
        transport.flush()
    assert transport._pending, "batch must be re-queued, not dropped"
    transport._client.close()  # drop connections to the dead listeners
    for b in list(cluster.broker_ids):
        cluster.revive_broker(b)
    transport.flush()
    now_ms = __import__("time").time() * 1000
    got = transport.poll(int(now_ms - 60_000), int(now_ms + 60_000))
    assert got == [b"survives"]
    transport.close()


def test_elect_leaders_tolerates_unavailable_preferred(cluster, client):
    """One degraded partition must not abort the batch (removed-tolerance
    regression guard): the healthy partition's election still lands."""
    cluster.create_topic("mix", 2, 2, assignment={0: [2, 0], 1: [1, 0]})
    cluster.topics["mix"].partitions[0].leader = 0
    cluster.topics["mix"].partitions[0].isr = [0]  # preferred 2 out of ISR
    cluster.topics["mix"].partitions[1].leader = 0
    failed = client.elect_leaders([("mix", 0), ("mix", 1)])
    assert failed == [("mix", 0, m.PREFERRED_LEADER_NOT_AVAILABLE)]
    assert client.partitions_for("mix")[1]["leader"] == 1
    # the admin binding degrades to a warning, not an exception
    admin = KafkaAdminBackend(cluster.bootstrap_servers)
    admin.elect_leaders([("mix", 0)])
    admin.close()


def test_admin_strategy_views(cluster):
    """The three ClusterInfo predicates movement strategies sort by."""
    cluster.create_topic("sv", 1, 2, assignment={0: [0, 1]})
    admin = KafkaAdminBackend(cluster.bootstrap_servers,
                              view_snapshot_ttl_s=0.0)
    client = WireClient(cluster.bootstrap_servers)
    client.produce("sv", 0, [Record(0, 1000, None, b"z" * 500)])
    assert admin.partition_size("sv", 0) >= 500
    assert not admin.is_under_replicated("sv", 0)
    cluster.topics["sv"].partitions[0].isr = [0]
    assert admin.is_under_replicated("sv", 0)
    assert not admin.is_under_min_isr_with_offline("sv", 0)
    client.incremental_alter_configs(
        m.RESOURCE_TOPIC, {"sv": {"min.insync.replicas": "2"}})
    cluster.kill_broker(1)
    assert admin.is_under_min_isr_with_offline("sv", 0)
    client.close()
    admin.close()


# ---------------------------------------------------------------------------
# tier 4: executor end-to-end over the wire
# ---------------------------------------------------------------------------

def test_executor_full_flow_against_embedded_cluster(cluster):
    """The reference's ExecutorTest against an embedded cluster
    (Executor.java three-phase flow): inter-broker move + leadership move
    execute through KafkaAdminBackend over real sockets, tasks reach
    COMPLETED, throttles are set and cleared."""
    from cruise_control_tpu.analyzer.proposals import ExecutionProposal
    from cruise_control_tpu.executor.executor import Executor

    cluster.create_topic("work", 2, 2, assignment={0: [0, 1], 1: [1, 2]})
    admin = KafkaAdminBackend(cluster.bootstrap_servers)
    executor = Executor(admin, progress_check_interval_s=0.01,
                        replication_throttle=100_000, synchronous=True)

    proposals = [
        # replica move 0 -> 2 (leader stays on 1 via reordered target)
        ExecutionProposal(topic="work", partition=0, old_leader=0,
                          old_replicas=(0, 1), new_replicas=(1, 2),
                          new_leader=1),
        # pure leadership move on partition 1 (1 -> 2)
        ExecutionProposal(topic="work", partition=1, old_leader=1,
                          old_replicas=(1, 2), new_replicas=(2, 1),
                          new_leader=2),
    ]
    executor.execute_proposals(proposals, uuid="wire-e2e")

    state = admin.describe_partitions()
    assert tuple(state[("work", 0)].replicas) == (1, 2)
    assert state[("work", 0)].leader == 1
    assert state[("work", 1)].leader == 2
    # throttle cycle left no residue
    for b, cfg in admin.describe_broker_configs([0, 1, 2]).items():
        assert "leader.replication.throttled.rate" not in cfg, (b, cfg)
    history = executor.execution_state()["recentHistory"]
    assert history and not history[-1]["stopped"]
    counts = history[-1]["taskCounts"]
    assert all(state == "completed"
               for by_state in counts.values()
               for state, n in by_state.items() if n), counts
    admin.close()


def test_executor_adoption_against_embedded_cluster(cluster):
    """Restart recovery (Executor.java:1238): reassignments already in
    flight on the cluster are adopted and tracked to completion without
    resubmission."""
    from cruise_control_tpu.executor.executor import Executor

    cluster.auto_complete = False
    cluster.create_topic("adopt", 1, 2, assignment={0: [0, 1]})
    admin = KafkaAdminBackend(cluster.bootstrap_servers)
    # an "external" (pre-restart) reassignment in flight
    admin.alter_partition_reassignments({("adopt", 0): (1, 2)})
    executor = Executor(admin, progress_check_interval_s=0.01,
                        synchronous=False)
    n = executor.adopt_ongoing_reassignments(uuid="adopted-e2e")
    assert n == 1
    # complete broker-side; the poll loop should observe and finish
    import time as _time
    deadline = _time.time() + 5.0
    cluster.complete_reassignments()
    while executor.has_ongoing_execution() and _time.time() < deadline:
        _time.sleep(0.05)
    assert not executor.has_ongoing_execution()
    assert tuple(admin.describe_partitions()[("adopt", 0)].replicas) == (1, 2)
    admin.close()


def test_codec_fuzz_roundtrips():
    """Randomized round-trips through every API's request+response schema:
    structured random values encode → decode to the same value (schema
    self-consistency; a field-order or length-prefix bug fails loudly)."""
    import random

    from cruise_control_tpu.kafka.wire import types as ty

    rng = random.Random(1234)

    def value_for(codec, depth=0):
        if codec in (ty.Int8,):
            return rng.randint(-128, 127)
        if codec in (ty.Int16,):
            return rng.randint(-2**15, 2**15 - 1)
        if codec in (ty.Int32,):
            return rng.randint(-2**31, 2**31 - 1)
        if codec in (ty.Int64,):
            return rng.randint(-2**63, 2**63 - 1)
        if codec is ty.UInt32:
            return rng.randint(0, 2**32 - 1)
        if codec is ty.Float64:
            return float(rng.randint(-1000, 1000))
        if codec is ty.Boolean:
            return rng.random() < 0.5
        if codec in (ty.VarInt,):
            return rng.randint(-2**31, 2**31 - 1)
        if codec is ty.UVarInt:
            return rng.randint(0, 2**32 - 1)
        if codec is ty.String or codec is ty.CompactString:
            return "".join(rng.choices("abcXYZ-_.0189", k=rng.randint(0, 12)))
        if codec is ty.NullableString or codec is ty.CompactNullableString:
            return None if rng.random() < 0.3 else value_for(ty.String)
        if codec is ty.Bytes or codec is ty.CompactBytes:
            return None if rng.random() < 0.3 else rng.randbytes(
                rng.randint(0, 20))
        if isinstance(codec, (ty.Array, ty.CompactArray)):
            if rng.random() < 0.15:
                return None
            return [value_for(codec._element, depth + 1)
                    for _ in range(rng.randint(0, 3 if depth else 4))]
        if isinstance(codec, ty.Struct):
            return {name: value_for(c, depth + 1)
                    for name, c in codec.fields}
        raise AssertionError(f"unhandled codec {codec!r}")

    for api in m.ALL_APIS:
        for codec in (api.request, api.response):
            for _ in range(20):
                v = value_for(codec)
                assert decode(codec, encode(codec, v)) == v, (api.key, v)


@pytest.mark.slow  # ~17 s: full live-mode stack over real wire bytes;
# the per-API codec roundtrips above keep tier-1 wire coverage.
def test_full_stack_live_mode_against_embedded_cluster():
    """The COMPLETE live-mode story over real wire bytes: broker-side
    reporter agents produce metrics to the embedded cluster's
    __CruiseControlMetrics topic; the app's live wiring (the same
    build_live_cruise_control the server boots with) consumes them
    through the reporter-topic sampler, builds a load model, and serves a
    dryrun rebalance through the REST dispatch pipeline."""
    import time

    from cruise_control_tpu.api.app import build_live_cruise_control
    from cruise_control_tpu.api.server import CruiseControlApi
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.monitor.capacity import StaticCapacityResolver
    from cruise_control_tpu.common.resources import Resource
    from cruise_control_tpu.reporter.agent import (
        BrokerMetricsRegistry, MetricsReporterAgent,
    )

    cluster = EmbeddedKafkaCluster(
        num_brokers=3, racks={0: "rA", 1: "rB", 2: "rC"}).start()
    try:
        # a skewed workload: broker 0 leads everything
        cluster.create_topic("events", 6, 2, assignment={
            i: [0, 1 + i % 2] for i in range(6)})
        cfg = CruiseControlConfig({
            "bootstrap.servers": cluster.bootstrap_servers,
            "partition.metrics.window.ms": 1000,
            "num.partition.metrics.windows": 2,
            "min.valid.partition.ratio": 0.0,
            "max.solver.rounds": 40,
            "failed.brokers.file.path": ""})
        cc = build_live_cruise_control(cfg)
        # deterministic capacities for the test (the default resolver
        # would read config/capacity.json broker ids)
        cc._load_monitor._capacity = StaticCapacityResolver(
            {}, {Resource.CPU: 100.0, Resource.DISK: 1e7,
                 Resource.NW_IN: 1e6, Resource.NW_OUT: 1e6})
        # racks come from cluster metadata over the wire (refreshed at
        # model build through the public accessor)
        assert cc._admin.broker_racks() == {0: "rA", 1: "rB", 2: "rC"}

        # One reporter agent per broker, producing REAL records to the
        # metrics topic through the wire transport. Two produce+sample
        # ROUNDS separated in wall time: the fetcher ingests each
        # sampling interval into the window of its end timestamp, and the
        # newest window is the current (incomplete) one — two rounds give
        # one closed, valid window.
        from cruise_control_tpu.kafka import KafkaMetricsTransport
        agents = []
        for b in range(3):
            reg = BrokerMetricsRegistry(broker_id=b)
            reg.set_cpu_util(30.0 + 20 * (b == 0))
            reg.set_topic_rate("events", 50_000.0 if b == 0 else 5_000.0,
                               80_000.0 if b == 0 else 8_000.0)
            for i in range(6):
                reg.set_partition_size("events", i, 1e6)
            transport = KafkaMetricsTransport(cluster.bootstrap_servers)
            agents.append(MetricsReporterAgent(reg, transport,
                                               interval_s=3600))
        t0 = int(time.time() * 1000)
        for a in agents:
            a.report_once()
        cc._load_monitor.task_runner.run_sampling_once(end_ms=t0 + 50)
        time.sleep(0.2)
        for a in agents:
            a.report_once()
        cc._load_monitor.task_runner.run_sampling_once(end_ms=t0 + 1200)

        api = CruiseControlApi(cc)
        api._async_wait_s = 180
        status, body, _h = api.handle(
            "POST", "/kafkacruisecontrol/rebalance", "dryrun=true")
        assert status == 200, body
        assert body.get("proposals"), "skewed live cluster must yield moves"
        # the model-build rack refresh populated real topology
        assert cc._load_monitor._broker_racks == {0: "rA", 1: "rB", 2: "rC"}
        api.shutdown()
        cc.shutdown()
    finally:
        cluster.stop()


def test_executor_intra_broker_jbod_flow_over_wire(cluster):
    """The executor's intra-broker (JBOD) phase against the embedded
    cluster: AlterReplicaLogDirs submitted over the wire, completion
    observed via replica_logdirs polling, task COMPLETED."""
    from cruise_control_tpu.analyzer.proposals import ExecutionProposal
    from cruise_control_tpu.executor.executor import Executor

    cluster.create_topic("jbod", 2, 1, assignment={0: [1], 1: [1]})
    admin = KafkaAdminBackend(cluster.bootstrap_servers)
    executor = Executor(admin, progress_check_interval_s=0.01,
                        synchronous=True)
    proposals = [ExecutionProposal(
        topic="jbod", partition=0, old_leader=1, old_replicas=(1,),
        new_replicas=(1,), new_leader=1, logdir_broker=1,
        source_logdir="/data/d0", destination_logdir="/data/d1")]
    executor.execute_proposals(proposals, uuid="jbod-wire")
    assert admin.replica_logdirs([1])[("jbod", 0, 1)] == "/data/d1"
    counts = executor.execution_state()["recentHistory"][-1]["taskCounts"]
    intra = counts.get("intra_broker_replica_action", {})
    assert intra.get("completed") == 1, counts
    admin.close()


def test_maintenance_plan_topic_flow_over_wire(cluster):
    """Kafka-topic maintenance flow (MaintenanceEventTopicReader.java:350):
    an ops pipeline produces a serialized plan to the maintenance topic on
    the embedded cluster; the topic reader consumes it through the wire
    transport; the detector reports it ONCE (idempotence cache), drops the
    tampered duplicate, and the anomaly's fix dispatches the mapped
    facade operation."""
    import json

    from cruise_control_tpu.detector.anomaly import (
        AnomalyType, MaintenanceEvent, MaintenanceEventType,
    )
    from cruise_control_tpu.detector.maintenance import (
        MaintenanceEventDetector,
    )
    from cruise_control_tpu.detector.maintenance_serde import (
        MAINTENANCE_TOPIC, TopicMaintenanceEventReader, publish_plan,
        serialize_plan,
    )

    transport = KafkaMetricsTransport(cluster.bootstrap_servers,
                                      topic=MAINTENANCE_TOPIC,
                                      num_partitions=1)
    plan = MaintenanceEvent(event_type=MaintenanceEventType.REMOVE_BROKER,
                            broker_ids=[2])
    publish_plan(transport, plan)
    publish_plan(transport, plan)          # duplicate: idempotence drops it
    # Tampered payload: CRC guard must reject it before the detector.
    raw = json.loads(serialize_plan(plan).decode())
    raw["content"]["brokers"] = [0]        # corrupt without re-CRCing
    transport.produce(json.dumps(raw).encode())
    transport.flush()

    import time as _time

    reported = []
    # settle_ms=0 + explicit sleeps: deterministic window edges in-test
    # (the production default keeps a 1 s settle for clock-skew safety).
    reader = TopicMaintenanceEventReader(transport, settle_ms=0)
    detector = MaintenanceEventDetector(reader, reported.append)
    _time.sleep(0.005)
    events = detector.run_once()
    assert len(events) == 1 == len(reported)
    event = reported[0]
    assert event.anomaly_type is AnomalyType.MAINTENANCE_EVENT
    assert event.event_type is MaintenanceEventType.REMOVE_BROKER
    assert list(event.broker_ids) == [2]

    # Fix dispatch: REMOVE_BROKER plans map to facade.remove_brokers.
    class FakeFacade:
        def __init__(self):
            self.calls = []

        def remove_brokers(self, brokers, **kw):
            self.calls.append(("remove_brokers", tuple(brokers)))

    facade = FakeFacade()
    assert event.fix(facade) is True
    assert facade.calls == [("remove_brokers", (2,))]

    # Later polls see nothing new; a NEW distinct plan flows through.
    assert detector.run_once() == []
    publish_plan(transport, MaintenanceEvent(
        event_type=MaintenanceEventType.REBALANCE))
    _time.sleep(0.005)
    assert [e.event_type for e in detector.run_once()] \
        == [MaintenanceEventType.REBALANCE]
    transport.close()


def test_columnar_poll_matches_record_poll(cluster):
    """poll_columns over real sockets must yield the same metric set as the
    per-record poll, and the columnar sampler path must equal the scalar
    one sample-for-sample."""
    import numpy as np

    from cruise_control_tpu.metricdef.raw_metric_type import RawMetricType as R
    from cruise_control_tpu.monitor.sampling.sampler import (
        CruiseControlMetricsReporterSampler,
    )
    from cruise_control_tpu.native import lib
    from cruise_control_tpu.reporter.metrics import (
        broker_metric, deserialize, deserialize_columns, partition_metric,
        serialize, topic_metric,
    )

    if lib() is None:
        pytest.skip("no C compiler for the native index")
    t = KafkaMetricsTransport(cluster.bootstrap_servers, num_partitions=3,
                              replication_factor=1)
    t.ensure_topic()
    now = 1_700_000_000_000
    import time as _time
    real_now = int(_time.time() * 1000)
    sent = []
    for b in range(3):
        sent.append(broker_metric(R.BROKER_CPU_UTIL, now, b, 0.1 * (b + 1)))
        sent.append(broker_metric(R.ALL_TOPIC_BYTES_IN, now, b, 100.0 * (b + 1)))
        sent.append(broker_metric(R.ALL_TOPIC_BYTES_OUT, now, b, 10.0))
        sent.append(broker_metric(R.ALL_TOPIC_REPLICATION_BYTES_IN, now, b, 1.0))
        for p in range(4):
            sent.append(topic_metric(R.TOPIC_BYTES_IN, now, b, "demo", 50.0))
            sent.append(partition_metric(R.PARTITION_SIZE, now, b, "demo", p,
                                         1000.0 + p))
    for m_ in sent:
        t.produce(serialize(m_))
    t.flush()

    lo, hi = real_now - 60_000, real_now + 60_000
    scalar = [deserialize(b) for b in t.poll(lo, hi)]
    data, spans = t.poll_columns(lo, hi)
    cols = deserialize_columns(data, np.asarray(spans))
    assert len(cols) == len(scalar) == len(sent)
    got = sorted((int(cols.raw_id[i]), int(cols.broker[i]),
                  cols.topics[cols.topic_id[i]] if cols.topic_id[i] >= 0 else None,
                  int(cols.partition[i]), float(cols.value[i]))
                 for i in range(len(cols)))
    want = sorted((int(m_.raw_type), m_.broker_id, m_.topic,
                   m_.partition if m_.partition >= 0 else -1, m_.value)
                  for m_ in scalar)
    assert got == want

    # Sampler equality: columnar fast path vs forced scalar fallback.
    parts = {("demo", p): type("PS", (), {"leader": p % 3})() for p in range(4)}
    sampler = CruiseControlMetricsReporterSampler(t)
    res_col = sampler.get_samples(parts, lo, hi)
    poll_columns = t.poll_columns
    try:
        t.poll_columns = lambda *a: None      # force the per-record path
        res_scalar = sampler.get_samples(parts, lo, hi)
    finally:
        t.poll_columns = poll_columns
    def norm(res):
        return (sorted((s.entity, tuple(np.round(s.values, 6).tolist()))
                       for s in res.partition_samples),
                sorted((s.entity, tuple(np.round(s.values, 6).tolist()))
                       for s in res.broker_samples))
    assert norm(res_col) == norm(res_scalar)
    t.close()
