"""Metric registry tests (reference: MetricDef / KafkaMetricDef / RawMetricType)."""

from cruise_control_tpu.common import Resource
from cruise_control_tpu.metricdef import (
    CommonMetric, KafkaMetricDef, MetricDef, MetricScope, RawMetricType,
    ValueComputingStrategy,
)
from cruise_control_tpu.metricdef.raw_metric_type import metrics_for_scope, scope_of


def test_dense_ids():
    d = MetricDef()
    a = d.define("m0", ValueComputingStrategy.AVG)
    b = d.define("m1", "max")
    assert (a.id, b.id) == (0, 1)
    assert d.metric_info_for_id(1).name == "m1"
    assert d.num_metrics == 2


def test_raw_metric_count_and_scopes():
    # Reference RawMetricType.java defines 63 raw metrics (ids 0..62).
    assert len(list(RawMetricType)) == 63
    assert scope_of(RawMetricType.PARTITION_SIZE) is MetricScope.PARTITION
    assert scope_of(RawMetricType.TOPIC_BYTES_IN) is MetricScope.TOPIC
    assert scope_of(RawMetricType.BROKER_CPU_UTIL) is MetricScope.BROKER
    assert len(metrics_for_scope(MetricScope.TOPIC)) == 7
    assert len(metrics_for_scope(MetricScope.PARTITION)) == 1


def test_raw_metric_id_parity():
    # Pin wire ids to the reference enum (RawMetricType.java:27-95) so the
    # generated ordering can never silently drift.
    assert RawMetricType.ALL_TOPIC_BYTES_IN.value == 0
    assert RawMetricType.PARTITION_SIZE.value == 4
    assert RawMetricType.BROKER_CPU_UTIL.value == 5
    assert RawMetricType.BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_MAX.value == 22
    assert RawMetricType.BROKER_CONSUMER_FETCH_REQUEST_QUEUE_TIME_MS_MAX.value == 24
    assert RawMetricType.BROKER_PRODUCE_TOTAL_TIME_MS_MAX.value == 28
    assert RawMetricType.BROKER_LOG_FLUSH_RATE.value == 40
    assert RawMetricType.BROKER_PRODUCE_REQUEST_QUEUE_TIME_MS_50TH.value == 43
    assert RawMetricType.BROKER_LOG_FLUSH_TIME_MS_999TH.value == 62


def test_kafka_metric_def_resources():
    common = KafkaMetricDef.common_metric_def()
    assert common.num_metrics == len(CommonMetric)
    r2m = KafkaMetricDef.resource_to_metric_ids("common")
    # NW_IN ← LEADER_BYTES_IN + REPLICATION_BYTES_IN_RATE (KafkaMetricDef.java)
    assert len(r2m[Resource.NW_IN]) == 2
    assert len(r2m[Resource.NW_OUT]) == 2
    assert len(r2m[Resource.CPU]) == 1
    assert len(r2m[Resource.DISK]) == 1
    # DISK uses LATEST strategy (disk usage is a level, not a rate).
    disk_id = KafkaMetricDef.common_metric_id(CommonMetric.DISK_USAGE)
    assert common.metric_info_for_id(disk_id).strategy is ValueComputingStrategy.LATEST


def test_broker_metric_def_superset():
    broker = KafkaMetricDef.broker_metric_def()
    common = KafkaMetricDef.common_metric_def()
    assert broker.num_metrics > common.num_metrics
    # Common metrics share ids across both defs (same definition order).
    for m in CommonMetric:
        assert broker.metric_info(m.name).id == common.metric_info(m.name).id
