"""Fleet federation (cruise_control_tpu/fleet/): bucketing equivalence,
registry lifecycle, scheduler fairness/starvation bound, shared-kernel
compile accounting, and ?cluster= API routing."""

import numpy as np
import pytest

from cruise_control_tpu.analyzer.chain import chain_optimize_full, optimize_chain
from cruise_control_tpu.analyzer.constraint import BalancingConstraint
from cruise_control_tpu.analyzer.goals import (
    LeaderReplicaDistributionGoal, RackAwareGoal, ReplicaCapacityGoal,
    ReplicaDistributionGoal, TopicReplicaDistributionGoal,
)
from cruise_control_tpu.analyzer.search import ExclusionMasks, SearchConfig
from cruise_control_tpu.common.broker_state import BrokerState
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
from cruise_control_tpu.executor.admin import InMemoryAdminBackend, PartitionState
from cruise_control_tpu.executor.executor import Executor
from cruise_control_tpu.facade import CruiseControl
from cruise_control_tpu.fleet import (
    BucketGrid, ClusterPausedError, FleetRegistry, FleetScheduler, JobKind,
    UnknownClusterError, pad_to_bucket, unpad_state,
)
from cruise_control_tpu.fleet.bucketing import geometric_round_up
from cruise_control_tpu.model.fixtures import random_cluster
from cruise_control_tpu.monitor import LoadMonitor, StaticCapacityResolver
from cruise_control_tpu.monitor.sampling import SyntheticSampler

# ---- shared fixtures -----------------------------------------------------

_CAPS = StaticCapacityResolver({}, {Resource.CPU: 100.0, Resource.DISK: 1e7,
                                    Resource.NW_IN: 1e6, Resource.NW_OUT: 1e6})


def _partitions(brokers=(0, 1, 2, 3), topics=2, parts=6):
    out = {}
    for t in range(topics):
        for p in range(parts):
            reps = (brokers[0], brokers[1 + (t + p) % (len(brokers) - 1)])
            out[(f"t{t}", p)] = PartitionState(f"t{t}", p, reps, reps[0],
                                               isr=reps)
    return out


def _base_config(extra=None):
    return CruiseControlConfig({
        "partition.metrics.window.ms": 1000,
        "num.partition.metrics.windows": 3,
        "min.valid.partition.ratio": 0.0,
        "max.solver.rounds": 30,
        "failed.brokers.file.path": "",
        # The fleet grid replaces the builder's per-cluster buckets.
        "solver.partition.bucket.size": 0,
        "solver.broker.bucket.size": 0,
        "fleet.bucket.broker.base": 4,
        "fleet.bucket.partition.base": 16,
        "fleet.bucket.topic.base": 8,
        **(extra or {})})


def _make_cc(config, partitions, optimizer=None):
    backend = InMemoryAdminBackend(partitions.values())
    monitor = LoadMonitor(config, backend, samplers=[SyntheticSampler()],
                          capacity_resolver=_CAPS)
    cc = CruiseControl(config, backend, load_monitor=monitor,
                       executor=Executor(backend, synchronous=True),
                       optimizer=optimizer)
    for k in range(1, 4):
        monitor.task_runner.run_sampling_once(end_ms=k * 1000)
    return cc


@pytest.fixture(scope="module")
def fleet():
    """A two-cluster fleet sharing one solver through the bucket grid:
    different topic and partition counts, same bucket. Shapes are chosen
    inside the byte-identical regime (see the equivalence test below):
    the search grid must fit the REAL shape, so the broker count sits on
    a grid point and the real replica-slot count exceeds the source
    width."""
    base = _base_config()
    scheduler = FleetScheduler(starvation_bound_s=30.0)
    registry = FleetRegistry(base_config=base, scheduler=scheduler)
    brokers = tuple(range(16))
    registry.register(
        "alpha", cc=_make_cc(base, _partitions(brokers, topics=2, parts=65),
                             optimizer=registry.optimizer))
    registry.register(
        "beta", cc=_make_cc(base, _partitions(brokers, topics=3, parts=67),
                            optimizer=registry.optimizer))
    yield registry, scheduler
    scheduler.shutdown()


# ---- bucketing -----------------------------------------------------------

def test_geometric_round_up_grid():
    assert geometric_round_up(1, 4, 2.0) == 4
    assert geometric_round_up(4, 4, 2.0) == 4
    assert geometric_round_up(5, 4, 2.0) == 8
    assert geometric_round_up(100, 4, 2.0) == 128
    # Fleet-wide property: any two clusters within one grid step share
    # a bucket; the grid has O(log n) points up to n.
    grid = BucketGrid(broker_base=4, partition_base=16, factor=2.0)
    assert grid.bucket_shape(3, 24) == grid.bucket_shape(4, 32) == (4, 32)
    points = {geometric_round_up(n, 16, 2.0) for n in range(1, 4096)}
    assert len(points) == 9  # 16 .. 4096: one bucket per octave


def test_pad_to_bucket_matches_builder_encoding():
    state, meta = random_cluster(num_brokers=5, num_topics=3,
                                 num_partitions=20, rf=2, num_racks=2, seed=7)
    padded = pad_to_bucket(state, 8, 32, num_hosts=len(meta.host_names))
    assert padded.num_brokers == 8 and padded.num_partitions == 32
    # Pad brokers: DEAD, zero capacity, masked, private host ids.
    assert np.all(np.asarray(padded.broker_state[5:]) == int(BrokerState.DEAD))
    assert np.all(np.asarray(padded.capacity[5:]) == 0)
    assert not np.asarray(padded.broker_mask[5:]).any()
    assert len(set(np.asarray(padded.host).tolist())) == 8
    # Pad partitions: empty, masked.
    assert np.all(np.asarray(padded.assignment[20:]) == -1)
    assert np.all(np.asarray(padded.leader_slot[20:]) == -1)
    assert not np.asarray(padded.partition_mask[20:]).any()
    # Exact round-trip.
    back = unpad_state(padded, 5, 20)
    for f in ("assignment", "leader_slot", "leader_load", "follower_load",
              "capacity", "rack", "broker_state", "topic", "partition_mask",
              "broker_mask", "host"):
        np.testing.assert_array_equal(np.asarray(getattr(back, f)),
                                      np.asarray(getattr(state, f)))


_EQ_CHAIN = (RackAwareGoal(), ReplicaCapacityGoal(),
             ReplicaDistributionGoal(), TopicReplicaDistributionGoal(),
             LeaderReplicaDistributionGoal())
_EQ_CFG = SearchConfig(num_sources=32, num_dests=8, moves_per_round=32,
                       max_rounds=40)


@pytest.mark.parametrize("bucket", [(16, 64, 8), (24, 96, 12)])
def test_padded_chain_trajectory_byte_identical(bucket):
    """The padding-soundness contract at two bucket sizes: the whole-chain
    solve on the padded model must land on EXACTLY the same assignment
    and leadership for the real rows as the unpadded solve, with the same
    per-goal move/round counts — padded brokers/partitions/topics are
    invisible to the search.

    Byte-identity requires the static search grid to fit the REAL shape
    (num_dests and the swap k within the real broker count, num_sources
    within the real replica-slot count): the grid's top-k sizes clamp to
    min(k, shape), so a grid larger than the real cluster would change
    the selection STRUCTURE — not just its contents — when padding grows
    the shape. The fleet's bucket grid operates in that regime by
    construction (grids are sized for production scale, pads are < one
    octave)."""
    nb, npart, ntop = bucket
    state, meta = random_cluster(num_brokers=12, num_topics=5,
                                 num_partitions=48, rf=2, num_racks=3,
                                 seed=11, skew_to_first=2.0)
    constraint = BalancingConstraint()
    masks = ExclusionMasks()

    final_plain, infos_plain = optimize_chain(
        state, _EQ_CHAIN, constraint, _EQ_CFG, meta.num_topics, masks)
    padded = pad_to_bucket(state, nb, npart,
                           num_hosts=len(meta.host_names))
    final_pad, infos_pad = optimize_chain(
        padded, _EQ_CHAIN, constraint, _EQ_CFG, ntop, masks)

    real = unpad_state(final_pad, state.num_brokers, state.num_partitions)
    np.testing.assert_array_equal(np.asarray(real.assignment),
                                  np.asarray(final_plain.assignment))
    np.testing.assert_array_equal(np.asarray(real.leader_slot),
                                  np.asarray(final_plain.leader_slot))
    # No replica may ever land on a pad broker.
    assert int(np.asarray(final_pad.assignment).max()) < state.num_brokers
    # Pad rows stay untouched.
    assert np.all(np.asarray(final_pad.assignment[state.num_partitions:])
                  == -1)
    for a, b in zip(infos_plain, infos_pad):
        assert (a["goal"], a["rounds"], a["moves_applied"],
                a["swaps_applied"]) == \
            (b["goal"], b["rounds"], b["moves_applied"], b["swaps_applied"])


# ---- registry + shared solver -------------------------------------------

@pytest.mark.slow  # ~24 s: two full padded solves + compile counting;
# the padded-trajectory and megabatch-routing pins below stay tier-1.
def test_fleet_serves_both_clusters_through_shared_kernels(fleet):
    """Acceptance: a two-cluster fleet serves proposals for both clusters
    with total chain compilations <= distinct bucket shapes (not
    clusters), and each cluster's padded solve equals its unpadded one."""
    registry, scheduler = fleet
    cache0 = chain_optimize_full._cache_size()
    futs = {cid: scheduler.submit(cid, JobKind.ON_DEMAND,
                                  lambda cid=cid: registry.get(cid).proposals())
            for cid in ("alpha", "beta")}
    scheduler.run_pending()
    results = {cid: f.result() for cid, f in futs.items()}
    assert all(r.proposals for r in results.values())

    entries = {e.cluster_id: e for e in registry.entries()}
    buckets = {entries[c].bucket for c in ("alpha", "beta")}
    assert buckets == {(16, 256)}  # same grid point, different shapes
    compiles = chain_optimize_full._cache_size() - cache0
    assert compiles <= len(buckets), \
        f"{compiles} chain compiles for {len(buckets)} bucket shape(s)"

    # Per-cluster padded-vs-unpadded equality end to end: rebuild each
    # model WITHOUT the fleet pad hook and solve with the same static
    # search configuration the fleet used (derived from the padded
    # shape); the proposal set must match byte for byte.
    for cid in ("alpha", "beta"):
        cc = registry.get(cid)
        hook, cc.load_monitor.model_transform = \
            cc.load_monitor.model_transform, None
        try:
            state, meta = cc.load_monitor.cluster_model()
        finally:
            cc.load_monitor.model_transform = hook
        from cruise_control_tpu.analyzer.optimizer import goals_by_priority
        from cruise_control_tpu.analyzer.proposals import diff_proposals
        chain = tuple(goals_by_priority(cc.config))
        cfg = registry.optimizer.search_config(state)
        final, _ = optimize_chain(state, chain,
                                  registry.optimizer.constraint, cfg,
                                  meta.num_topics, ExclusionMasks())
        plain = diff_proposals(state, final, meta)
        assert list(results[cid].proposals) == list(plain)


def test_registry_lifecycle():
    base = _base_config()
    registry = FleetRegistry(base_config=base)
    backend = InMemoryAdminBackend(_partitions().values())
    entry = registry.register("gamma", admin=backend,
                              overlay={"max.solver.rounds": 7})
    # Overlay wins over base for this cluster only.
    assert entry.config.get_int("max.solver.rounds") == 7
    assert base.get_int("max.solver.rounds") == 30
    assert registry.cluster_ids() == ["gamma"]
    with pytest.raises(ValueError, match="already registered"):
        registry.register("gamma", admin=backend)
    with pytest.raises(ValueError, match="exactly one"):
        registry.register("delta")
    with pytest.raises(ValueError, match="overlay"):
        registry.register("delta", cc=entry.cc,
                          overlay={"max.solver.rounds": 5})
    assert registry.cluster_id_of(entry.cc) == "gamma"

    registry.pause("gamma")
    assert registry.get("gamma") is entry.cc  # reads still allowed
    with pytest.raises(ClusterPausedError):
        registry.get("gamma", for_operation=True)
    registry.resume("gamma")
    assert registry.get("gamma", for_operation=True) is entry.cc

    with pytest.raises(UnknownClusterError):
        registry.get("nope")
    cc = entry.cc
    assert cc.load_monitor.model_transform is not None
    from cruise_control_tpu.utils.sensors import SENSORS
    SENSORS.gauge("fleet_test_lifecycle_gauge", 1.0,
                  labels={"cluster": "gamma"})
    registry.deregister("gamma")
    assert registry.cluster_ids() == []
    # Deregistration hands the facade back clean: the fleet pad hook and
    # the scheduler-routed fix runner are both detached, and the
    # cluster's labeled sensor series are dropped from the export.
    assert cc.load_monitor.model_transform is None
    assert cc.anomaly_detector.fix_runner is None
    assert 'cluster="gamma"' not in SENSORS.render()
    with pytest.raises(UnknownClusterError):
        registry.deregister("gamma")


def test_registry_state_reports_buckets(fleet):
    registry, _ = fleet
    # The pad hook records an entry's bucket on model BUILD; build both
    # models here (no solve) so this test stands alone — the shared-kernel
    # acceptance test that used to populate the buckets is tier-2 slow.
    for cid in ("alpha", "beta"):
        registry.get(cid).load_monitor.cluster_model()
    body = registry.state()
    assert body["numClusters"] == 2
    assert set(body["clusters"]) == {"alpha", "beta"}
    for row in body["clusters"].values():
        assert row["bucketBrokers"] == 16
        assert row["bucketPartitions"] == 256
    assert body["bucketShapes"] == [[16, 256]]
    assert "scheduler" in body


# ---- scheduler -----------------------------------------------------------

class _FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now


def test_scheduler_from_config_reads_starvation_bound():
    sched = FleetScheduler.from_config(
        _base_config({"fleet.scheduler.starvation.bound.ms": 5_000}))
    assert sched._starvation_bound_s == 5.0


def test_fix_runner_runs_inline_when_no_worker_drains():
    """A self-healing fix must not block on a future nobody serves: with
    the scheduler worker not running, the runner executes inline."""
    base = _base_config()
    scheduler = FleetScheduler()  # never started
    registry = FleetRegistry(base_config=base, scheduler=scheduler)
    entry = registry.register(
        "solo", cc=_make_cc(base, _partitions(), optimizer=registry.optimizer))
    assert entry.cc.anomaly_detector.fix_runner(lambda: "healed") == "healed"
    assert scheduler.pending() == 0


def test_scheduler_priorities_and_round_robin_fairness():
    clock = _FakeClock()
    sched = FleetScheduler(starvation_bound_s=1e9, clock=clock)
    order = []

    def job(tag):
        return lambda: order.append(tag)

    # Interleave submissions: on-demand flood from A, precompute for A
    # and B, one self-healing for B.
    for i in range(3):
        sched.submit("A", JobKind.ON_DEMAND, job(f"A-od{i}"))
    sched.submit("A", JobKind.EXPIRING_CACHE, job("A-pre"))
    sched.submit("B", JobKind.EXPIRING_CACHE, job("B-pre"))
    sched.submit("B", JobKind.SELF_HEALING, job("B-heal"))
    sched.submit("B", JobKind.ON_DEMAND, job("B-od"))
    assert sched.run_pending() == 7
    # Highest class first; inside a class, clusters alternate; inside a
    # cluster, FIFO. B just ran (healing), so the cache class starts at A.
    assert order[0] == "B-heal"
    assert order[1:3] == ["A-pre", "B-pre"]
    # On-demand: A has 3 queued vs B's 1 — B must not wait for all of A.
    assert order[3:] == ["A-od0", "B-od", "A-od1", "A-od2"]


def test_scheduler_starvation_bound_overrides_priority():
    clock = _FakeClock()
    sched = FleetScheduler(starvation_bound_s=10.0, clock=clock)
    order = []
    sched.submit("A", JobKind.ON_DEMAND, lambda: order.append("A-old"))
    clock.now += 11.0  # A's on-demand is now past the bound
    sched.submit("B", JobKind.SELF_HEALING, lambda: order.append("B-heal"))
    sched.run_pending()
    assert order == ["A-old", "B-heal"]


def test_flooded_cluster_cannot_starve_other_precompute(fleet):
    """Acceptance: with one cluster flooding on-demand requests, the
    other cluster's precompute still runs within its cadence — the
    EXPIRING_CACHE class outranks ON_DEMAND, and the pacer enqueues it
    as soon as the cadence elapses."""
    registry, scheduler = fleet
    ran = []
    for i in range(20):
        scheduler.submit("alpha", JobKind.ON_DEMAND,
                         lambda i=i: ran.append(f"flood{i}"))
    # Cadence elapsed for both clusters -> pacer enqueues precompute.
    for e in registry.entries():
        e.last_precompute = 0.0
    assert scheduler.pace_once() == 2
    scheduler.run_pending(max_jobs=2)
    # Both precomputes ran BEFORE any of the 20 flooded requests.
    assert ran == []
    for e in registry.entries():
        with e.cc._proposal_lock:
            assert e.cc._proposal_cache is not None
    scheduler.run_pending()
    assert len(ran) == 20


def test_pacer_promotes_predicted_precompute(fleet):
    """Round 19: a cluster flagged predicted_precompute_pending is due
    NOW — the pacer enqueues its precompute regardless of cadence and
    clears the flag; unflagged clusters keep waiting theirs out."""
    registry, scheduler = fleet
    now = __import__("time").monotonic()
    for e in registry.entries():
        e.last_precompute = now          # nobody due by cadence
    assert scheduler.pace_once() == 0
    entry = registry.entries()[0]
    entry.cc.predicted_precompute_pending = True
    assert scheduler.pace_once() == 1
    assert entry.cc.predicted_precompute_pending is False
    assert scheduler.pending(entry.cluster_id,
                             JobKind.EXPIRING_CACHE) == 1
    scheduler.run_pending()
    with entry.cc._proposal_lock:
        assert entry.cc._proposal_cache is not None
    # One promotion, one sweep: the flag does not re-trigger.
    assert scheduler.pace_once() == 0


def test_self_healing_routes_through_scheduler():
    base = _base_config()
    scheduler = FleetScheduler()
    registry = FleetRegistry(base_config=base, scheduler=scheduler)
    entry = registry.register(
        "heal", cc=_make_cc(base, _partitions(), optimizer=registry.optimizer))
    runner = entry.cc.anomaly_detector.fix_runner
    assert runner is not None
    scheduler.start(pacer=False)  # live worker drains the SELF_HEALING job
    try:
        assert runner(lambda: "fixed") == "fixed"
        assert scheduler.jobs_run == 1
        # Paused = administrative, not a failure: the runner reports "fix
        # did not start" instead of raising into the anomaly manager.
        registry.pause("heal")
        assert runner(lambda: "never") is False
    finally:
        scheduler.shutdown()
    # After shutdown a late submit must not strand its caller: it runs
    # inline on the submitting thread.
    assert scheduler.submit("heal", JobKind.SELF_HEALING,
                            lambda: "late").result(timeout=5) == "late"


# ---- API routing ---------------------------------------------------------

@pytest.fixture(scope="module")
def fleet_api(fleet):
    from cruise_control_tpu.api.server import CruiseControlApi
    registry, _ = fleet
    default_cc = registry.get("alpha")
    api = CruiseControlApi(default_cc, fleet=registry)
    api._async_wait_s = 180
    yield api, registry
    api.shutdown()


def test_api_routes_cluster_parameter(fleet_api):
    api, registry = fleet_api
    status, body, _ = api.handle("GET", "/kafkacruisecontrol/state",
                                 "cluster=beta&substates=monitor")
    assert status == 200
    beta_parts = registry.get("beta") \
        ._load_monitor.state().total_num_partitions
    assert body["MonitorState"]["totalNumPartitions"] == beta_parts


def test_api_without_cluster_param_unchanged(fleet_api):
    api, registry = fleet_api
    status, body, _ = api.handle("GET", "/kafkacruisecontrol/state",
                                 "substates=monitor")
    assert status == 200
    alpha_parts = registry.get("alpha") \
        ._load_monitor.state().total_num_partitions
    assert body["MonitorState"]["totalNumPartitions"] == alpha_parts


def test_api_unknown_cluster_404(fleet_api):
    api, _ = fleet_api
    status, body, _ = api.handle("GET", "/kafkacruisecontrol/state",
                                 "cluster=nope")
    assert status == 404
    assert "unknown cluster" in body["errorMessage"]


def test_api_paused_cluster_refuses_solver_endpoints(fleet_api):
    api, registry = fleet_api
    registry.pause("beta")
    try:
        status, body, _ = api.handle("GET", "/kafkacruisecontrol/proposals",
                                     "cluster=beta")
        assert status == 409
        assert "paused" in body["errorMessage"]
        # Reads keep working while paused.
        status, _body, _ = api.handle("GET", "/kafkacruisecontrol/state",
                                      "cluster=beta")
        assert status == 200
    finally:
        registry.resume("beta")


def test_api_default_cluster_gets_fleet_semantics(fleet_api):
    """A request WITHOUT ?cluster= against a default facade that is
    itself registered is that cluster's request: pausing it refuses
    solver endpoints on the default route too."""
    api, registry = fleet_api
    registry.pause("alpha")  # alpha is the fixture's default facade
    try:
        status, body, _ = api.handle("GET", "/kafkacruisecontrol/proposals",
                                     "")
        assert status == 409
        assert "paused" in body["errorMessage"]
    finally:
        registry.resume("alpha")


def test_api_cluster_param_without_fleet_is_400():
    from cruise_control_tpu.api.server import CruiseControlApi
    cc = _make_cc(_base_config(), _partitions())
    api = CruiseControlApi(cc)
    try:
        status, body, _ = api.handle("GET", "/kafkacruisecontrol/state",
                                     "cluster=alpha")
        assert status == 400
        assert "not running a fleet" in body["errorMessage"]
    finally:
        api.shutdown()


def test_fleet_endpoint_dashboard(fleet_api):
    api, _ = fleet_api
    status, body, _ = api.handle("GET", "/kafkacruisecontrol/fleet", "")
    assert status == 200
    assert body["numClusters"] == 2
    assert set(body["clusters"]) == {"alpha", "beta"}


def test_metrics_carry_cluster_labels(fleet_api):
    api, _ = fleet_api
    from cruise_control_tpu.utils.sensors import SENSORS, cluster_label
    with cluster_label("alpha"):
        SENSORS.count("fleet_test_labeled_counter")
    text = api.metrics_text()
    assert 'fleet_test_labeled_counter_total{cluster="alpha"} 1.0' in text
    assert 'fleet_cluster_paused{cluster="beta"}' in text


# ---------------------------------------------------------------------------
# Megabatch coalescing (round 14): whole-bucket fills through one
# batched device program.

_G = "cruise_control_tpu.analyzer.goals"
_SHORT_CHAIN = [f"{_G}.RackAwareGoal", f"{_G}.ReplicaCapacityGoal",
                f"{_G}.ReplicaDistributionGoal"]


def _megabatch_fleet(extra=None):
    base = _base_config(extra={
        "goals": _SHORT_CHAIN,
        "hard.goals": [f"{_G}.RackAwareGoal", f"{_G}.ReplicaCapacityGoal"],
        "anomaly.detection.goals": _SHORT_CHAIN,
        **(extra or {})})
    scheduler = FleetScheduler(starvation_bound_s=30.0)
    registry = FleetRegistry(base_config=base, scheduler=scheduler)
    brokers = tuple(range(8))
    registry.register(
        "mb-a", cc=_make_cc(base, _partitions(brokers, topics=2, parts=10),
                            optimizer=registry.optimizer))
    registry.register(
        "mb-b", cc=_make_cc(base, _partitions(brokers, topics=2, parts=11),
                            optimizer=registry.optimizer))
    return registry, scheduler


def test_megabatch_runner_wired_by_config():
    registry, scheduler = _megabatch_fleet()
    try:
        assert registry.megabatch is not None
        assert scheduler.coalescing
    finally:
        registry.shutdown()
    base = _base_config(extra={"fleet.megabatch.enabled": False})
    off = FleetRegistry(base_config=base,
                        scheduler=FleetScheduler(starvation_bound_s=30.0))
    assert off.megabatch is None


def test_megabatch_pacer_emits_whole_bucket_fill():
    """The whole-bucket batch fill (ROADMAP item 3): both clusters due
    simultaneously coalesce into ONE batched solve at occupancy 2; the
    proposal caches fill, per-cluster dispatch gauges come from the
    SPLIT readback, the flight recorder answers per cluster, and the
    /fleet dashboard shows occupancy."""
    from cruise_control_tpu.utils.flight_recorder import FLIGHT
    from cruise_control_tpu.utils.sensors import SENSORS
    registry, scheduler = _megabatch_fleet()
    try:
        # Sweep 1: no bucket recorded yet -> solo solves record buckets.
        for e in registry.entries():
            e.last_precompute = 0.0
        assert scheduler.pace_once() == 2
        scheduler.run_pending()
        assert registry.megabatch.stats()["batchesSolved"] == 0
        # Sweep 2: buckets known -> one megabatch of occupancy 2.
        for e in registry.entries():
            e.last_precompute = 0.0
            with e.cc._proposal_lock:
                e.cc._proposal_cache = None
        assert scheduler.pace_once() == 2
        ran = scheduler.run_pending()
        assert ran == 2
        stats = registry.megabatch.stats()
        assert stats["batchesSolved"] == 1
        assert stats["lastOccupancy"] == 2
        assert stats["clustersSolved"] == 2
        for e in registry.entries():
            with e.cc._proposal_lock:
                assert e.cc._proposal_cache is not None, e.cluster_id
        body = registry.state()
        assert body["megabatch"]["lastOccupancy"] == 2
        assert body["megabatch"]["width"] == 4
        for cid in ("mb-a", "mb-b"):
            key = ("fleet_precompute_dispatches", (("cluster", cid),))
            assert SENSORS._gauges.get(key, 0) > 0, cid
            passes = FLIGHT.passes(cluster=cid, limit=4)
            assert passes and passes[0]["path"] == "megabatch"
            assert passes[0]["attributes"]["occupancy"] == 2
        snap = SENSORS.histogram_snapshot("solver_megabatch_occupancy")
        assert snap is not None and snap["count"] >= 1
    finally:
        registry.shutdown()


def test_fix_and_on_demand_solves_route_through_megabatch():
    """ROADMAP item 3c tail (round 15): with coalescing wired, a
    registered facade's goal-chain operations — the self-healing fix
    path and on-demand requests — run through the BATCHED kernels at
    occupancy 1 (flight path=megabatch), with per-request exclusion
    options riding the batched mask assembler, and return results
    byte-identical to the serial solve."""
    from cruise_control_tpu.utils.flight_recorder import FLIGHT
    registry, _scheduler = _megabatch_fleet()
    try:
        ea = registry.entry("mb-a")
        assert ea.cc.megabatch_solve_width == registry.megabatch.width
        marker = FLIGHT.marker()
        from cruise_control_tpu.utils.sensors import cluster_label
        with cluster_label("mb-a"):
            batched = ea.cc.rebalance(
                dryrun=True, excluded_topics=("t0",))
        passes = FLIGHT.passes_since(marker)
        assert passes and any(p["path"] == "megabatch" for p in passes)
        ea.cc.megabatch_solve_width = 0
        serial = ea.cc.rebalance(dryrun=True, excluded_topics=("t0",))
        assert [(p.topic, p.partition, p.new_replicas)
                for p in batched.proposals] == \
            [(p.topic, p.partition, p.new_replicas)
             for p in serial.proposals]
        assert batched.optimizer_result.balancedness_after \
            == serial.optimizer_result.balancedness_after
    finally:
        registry.shutdown()


def test_megabatch_batch_failure_contained():
    """A cluster whose model build fails at batch time fails ONLY its
    own future; the batchmate still solves and stores its cache."""
    registry, scheduler = _megabatch_fleet()
    try:
        for e in registry.entries():
            e.last_precompute = 0.0
        scheduler.pace_once()
        scheduler.run_pending()          # record buckets
        from cruise_control_tpu.fleet import PrecomputePayload
        from cruise_control_tpu.fleet.megabatch import precompute_batch_key
        ea = registry.entry("mb-a")
        eb = registry.entry("mb-b")
        with eb.cc._proposal_lock:
            eb.cc._proposal_cache = None

        class Broken:
            def precompute_inputs(self):
                raise RuntimeError("model build exploded")

        key = precompute_batch_key(ea)
        assert key == precompute_batch_key(eb)
        fut_a = scheduler.submit(
            "mb-a", JobKind.EXPIRING_CACHE, lambda: None, batch_key=key,
            payload=PrecomputePayload("mb-a", Broken()))
        fut_b = scheduler.submit(
            "mb-b", JobKind.EXPIRING_CACHE, lambda: None, batch_key=key,
            payload=PrecomputePayload("mb-b", eb.cc))
        scheduler.run_pending()
        with pytest.raises(RuntimeError, match="exploded"):
            fut_a.result(timeout=5)
        assert fut_b.result(timeout=5).proposals is not None
        with eb.cc._proposal_lock:
            assert eb.cc._proposal_cache is not None
    finally:
        registry.shutdown()


# ---------------------------------------------------------------------------
# Multi-replica control plane (round 23): N scheduler workers over one
# shared queue/AOT cache, bucket-affinity placement, work stealing.

def _counter(name):
    from cruise_control_tpu.utils.sensors import SENSORS
    return SENSORS._counters.get((name, ()), 0.0)


def test_scheduler_from_config_reads_worker_count():
    sched = FleetScheduler.from_config(
        _base_config({"fleet.shard.workers": 3}))
    assert sched._workers_n == 3
    # Default stays a single replica — byte-identical control plane.
    assert FleetScheduler.from_config(_base_config())._workers_n == 1


def test_start_spawns_worker_replicas_and_gauge():
    from cruise_control_tpu.utils.sensors import SENSORS
    sched = FleetScheduler(starvation_bound_s=30.0, workers=2)
    sched.start(pacer=False)
    try:
        names = sorted(t.name for t in sched._solvers)
        assert names == ["fleet-solver-0", "fleet-solver-1"]
        assert all(t.is_alive() for t in sched._solvers)
        assert SENSORS._gauges.get(("fleet_shard_workers", ())) == 2.0
        assert sched.submit("x", JobKind.ON_DEMAND,
                            lambda: "ran").result(timeout=5) == "ran"
    finally:
        sched.shutdown()
    assert not any(t.is_alive() for t in sched._solvers)


def test_bucket_affinity_homes_then_prefers_home_worker():
    """First pick homes the bucket; a later pick by the home worker is
    an affinity hit; a DIFFERENT worker with its own work available
    leaves the homed bucket alone even when the homed job is older."""
    clock = _FakeClock()
    sched = FleetScheduler(starvation_bound_s=1e9, clock=clock, workers=2)
    order = []
    k1, k2 = ("bucket", 16, 256), ("bucket", 24, 512)
    # Home k1 on worker 0, k2 on worker 1.
    sched.submit("A", JobKind.ON_DEMAND, lambda: order.append("a0"),
                 batch_key=k1)
    assert sched.run_pending(max_jobs=1, worker_id=0) == 1
    sched.submit("B", JobKind.ON_DEMAND, lambda: order.append("b0"),
                 batch_key=k2)
    assert sched.run_pending(max_jobs=1, worker_id=1) == 1
    assert sched._affinity == {k1: 0, k2: 1}
    hits0 = _counter("fleet_shard_affinity_hits")
    steals0 = _counter("fleet_shard_steals")
    # Queue one job per bucket; the k2 job is OLDER (submitted first).
    sched.submit("B", JobKind.ON_DEMAND, lambda: order.append("b1"),
                 batch_key=k2)
    sched.submit("A", JobKind.ON_DEMAND, lambda: order.append("a1"),
                 batch_key=k1)
    # Worker 0 skips B's older job (homed on 1) and serves its own.
    assert sched.run_pending(max_jobs=1, worker_id=0) == 1
    assert order[-1] == "a1"
    assert sched.run_pending(max_jobs=1, worker_id=1) == 1
    assert order[-1] == "b1"
    assert _counter("fleet_shard_affinity_hits") == hits0 + 2
    assert _counter("fleet_shard_steals") == steals0
    assert sched._affinity == {k1: 0, k2: 1}


def test_idle_worker_steals_and_rehomes_bucket():
    """A worker with NO work of its own steals an affined-elsewhere job
    instead of idling, and the steal re-homes the bucket on it (its
    dispatch caches are now the warm ones)."""
    clock = _FakeClock()
    sched = FleetScheduler(starvation_bound_s=1e9, clock=clock, workers=2)
    k = ("bucket", 16, 256)
    sched.submit("A", JobKind.ON_DEMAND, lambda: None, batch_key=k)
    sched.run_pending(max_jobs=1, worker_id=0)      # homed on 0
    steals0 = _counter("fleet_shard_steals")
    sched.submit("A", JobKind.ON_DEMAND, lambda: None, batch_key=k)
    assert sched.run_pending(max_jobs=1, worker_id=1) == 1
    assert _counter("fleet_shard_steals") == steals0 + 1
    assert sched._affinity[k] == 1
    # The new home now takes hits; the old home would steal back.
    hits0 = _counter("fleet_shard_affinity_hits")
    sched.submit("A", JobKind.ON_DEMAND, lambda: None, batch_key=k)
    sched.run_pending(max_jobs=1, worker_id=1)
    assert _counter("fleet_shard_affinity_hits") == hits0 + 1


def test_starvation_bound_overrides_affinity():
    """The starvation bound is a promise to the CLUSTER, not to a
    worker: an overdue job runs on whichever worker sees it first, even
    against affinity, and the steal re-homes its bucket."""
    clock = _FakeClock()
    sched = FleetScheduler(starvation_bound_s=10.0, clock=clock, workers=2)
    k = ("bucket", 16, 256)
    order = []
    sched.submit("A", JobKind.ON_DEMAND, lambda: order.append("a0"),
                 batch_key=k)
    sched.run_pending(max_jobs=1, worker_id=0)      # homed on 0
    sched.submit("A", JobKind.ON_DEMAND, lambda: order.append("a-old"),
                 batch_key=k)
    sched.submit("B", JobKind.SELF_HEALING, lambda: order.append("b-heal"))
    clock.now += 11.0                                # A's job now overdue
    steals0 = _counter("fleet_shard_steals")
    assert sched.run_pending(max_jobs=1, worker_id=1) == 1
    # Overdue beats both the higher-priority class AND the affinity.
    assert order[-1] == "a-old"
    assert _counter("fleet_shard_steals") == steals0 + 1
    assert sched._affinity[k] == 1
    sched.run_pending(worker_id=1)
    assert order[-1] == "b-heal"


def test_single_worker_scheduling_unchanged_by_affinity():
    """workers=1 (the default): every bucket homes on worker 0 and the
    pick order is byte-identical to the pre-round-23 scheduler —
    affinity can only influence placement when there are replicas."""
    clock = _FakeClock()
    sched = FleetScheduler(starvation_bound_s=1e9, clock=clock)
    order = []
    steals0 = _counter("fleet_shard_steals")
    sched.submit("A", JobKind.ON_DEMAND, lambda: order.append("a0"),
                 batch_key=("k", 1))
    sched.submit("B", JobKind.ON_DEMAND, lambda: order.append("b0"),
                 batch_key=("k", 2))
    sched.submit("A", JobKind.ON_DEMAND, lambda: order.append("a1"),
                 batch_key=("k", 1))
    assert sched.run_pending() == 3
    assert order == ["a0", "b0", "a1"]
    assert set(sched._affinity.values()) == {0}
    assert _counter("fleet_shard_steals") == steals0
