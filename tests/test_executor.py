"""Executor layer tests.

Mirrors the reference's component tier (SURVEY.md §4: ExecutionTaskPlannerTest,
ExecutionTaskManagerTest, ConcurrencyAdjusterTest, ExecutorTest against
embedded brokers — here the embedded cluster is InMemoryAdminBackend)."""

import time

import pytest

from cruise_control_tpu.analyzer.proposals import ExecutionProposal
from cruise_control_tpu.executor import (
    ConcurrencyCaps, ExecutionConcurrencyManager, ExecutionTask,
    ExecutionTaskManager, ExecutionTaskPlanner, Executor, InMemoryAdminBackend,
    OngoingExecutionError, PartitionState, TaskState, TaskType,
    strategy_chain,
)
from cruise_control_tpu.executor.strategy import (
    PrioritizeSmallReplicaMovementStrategy, PostponeUrpReplicaMovementStrategy,
)


def proposal(topic="t", part=0, old=(0, 1), new=(2, 1), old_leader=0, new_leader=2):
    return ExecutionProposal(topic=topic, partition=part, old_leader=old_leader,
                             old_replicas=tuple(old), new_replicas=tuple(new),
                             new_leader=new_leader)


def make_cluster(n_parts=8, brokers=(0, 1, 2, 3), steps_per_tick=3):
    parts = [PartitionState(topic="t", partition=i,
                            replicas=(brokers[i % len(brokers)],
                                      brokers[(i + 1) % len(brokers)]),
                            leader=brokers[i % len(brokers)],
                            isr=(brokers[i % len(brokers)],
                                 brokers[(i + 1) % len(brokers)]))
             for i in range(n_parts)]
    return InMemoryAdminBackend(parts, steps_per_tick=steps_per_tick)


# ---- task state machine ----------------------------------------------------

def test_task_state_machine_legal_path():
    t = ExecutionTask(0, proposal(), TaskType.INTER_BROKER_REPLICA_ACTION)
    assert t.state is TaskState.PENDING
    t.in_progress()
    t.completed()
    assert t.state is TaskState.COMPLETED


def test_task_state_machine_rejects_illegal_transfer():
    t = ExecutionTask(0, proposal(), TaskType.INTER_BROKER_REPLICA_ACTION)
    with pytest.raises(ValueError):
        t.completed()  # PENDING -> COMPLETED not allowed
    t.in_progress()
    t.abort()
    with pytest.raises(ValueError):
        t.completed()  # ABORTING -> COMPLETED not allowed
    t.aborted()
    assert t.state is TaskState.ABORTED


def test_task_manager_expands_proposals():
    tm = ExecutionTaskManager()
    tasks = tm.tasks_from_proposals([
        proposal(part=0, old=(0, 1), new=(2, 1), new_leader=2),   # move + leader
        proposal(part=1, old=(0, 1), new=(1, 0), old_leader=0, new_leader=1),  # reorder + leader
        proposal(part=2, old=(0, 1), new=(0, 1), old_leader=0, new_leader=0),  # no-op
    ])
    kinds = [(t.task_type, t.proposal.partition) for t in tasks]
    assert (TaskType.INTER_BROKER_REPLICA_ACTION, 0) in kinds
    assert (TaskType.LEADER_ACTION, 0) in kinds
    assert (TaskType.INTER_BROKER_REPLICA_ACTION, 1) in kinds
    assert all(p != 2 for _, p in kinds)


# ---- planner ---------------------------------------------------------------

def test_planner_respects_broker_headroom():
    planner = ExecutionTaskPlanner()
    tm = ExecutionTaskManager()
    # Three tasks all adding to broker 9.
    tasks = tm.tasks_from_proposals([
        proposal(part=i, old=(0, 1), new=(9, 1), new_leader=9) for i in range(3)])
    inter = [t for t in tasks if t.task_type is TaskType.INTER_BROKER_REPLICA_ACTION]
    planner.add_tasks(inter, make_cluster())
    picked = planner.inter_broker_tasks(lambda b: 2, max_total=10)
    assert len(picked) == 2  # broker 9 headroom = 2
    assert planner.num_pending(TaskType.INTER_BROKER_REPLICA_ACTION) == 1


def test_strategy_orders_small_first_and_postpones_urp():
    class Info:
        def partition_size(self, t, p):
            return {0: 30.0, 1: 10.0, 2: 20.0}[p]

        def is_under_replicated(self, t, p):
            return p == 1

        def is_under_min_isr_with_offline(self, t, p):
            return False

    tm = ExecutionTaskManager()
    tasks = tm.tasks_from_proposals([
        proposal(part=p, old=(0, 1), new=(2, 1), new_leader=2) for p in range(3)])
    inter = [t for t in tasks if t.task_type is TaskType.INTER_BROKER_REPLICA_ACTION]
    chain = strategy_chain(["PostponeUrpReplicaMovementStrategy",
                            "PrioritizeSmallReplicaMovementStrategy"])
    ordered = chain.sort(inter, Info())
    # URP partition 1 last despite being smallest; others by size.
    assert [t.proposal.partition for t in ordered] == [2, 0, 1]


# ---- concurrency -----------------------------------------------------------

def test_concurrency_adjuster_halves_and_recovers():
    from cruise_control_tpu.executor.concurrency import (
        ConcurrencyAdjusterConfig,
    )
    # min.isr.check.enabled defaults FALSE (ExecutorConfig.java:583);
    # enabled explicitly because this test exercises min-ISR pressure.
    m = ExecutionConcurrencyManager(
        ConcurrencyCaps(inter_broker_per_broker=8),
        adjuster=ConcurrencyAdjusterConfig(min_isr_check_enabled=True))
    m.adjust(cluster_healthy=False, has_under_min_isr=True)
    assert m.state()["interBrokerPerBroker"] == 4
    m.adjust(cluster_healthy=False, has_under_min_isr=True)
    assert m.state()["interBrokerPerBroker"] == 2
    for _ in range(20):
        m.adjust(cluster_healthy=True, has_under_min_isr=False)
    # AIMD ceiling = concurrency.adjuster.max.partition.movements.per.broker
    # (ExecutorConfig.java:340, default 12) — not the old 2x-base rule.
    assert m.state()["interBrokerPerBroker"] == 12


def test_concurrency_adjuster_metric_limits_and_aimd_knobs():
    from cruise_control_tpu.executor.concurrency import (
        ConcurrencyAdjusterConfig,
    )
    adj = ConcurrencyAdjusterConfig(min_brokers_violate_metric_limit=2,
                                    leadership_per_broker_enabled=True,
                                    min_isr_check_enabled=True)
    m = ExecutionConcurrencyManager(
        ConcurrencyCaps(inter_broker_per_broker=8, leadership_cluster=800,
                        leadership_per_broker=200), adjuster=adj)
    # One violating broker: below the threshold — healthy growth continues.
    m.adjust(cluster_healthy=True, has_under_min_isr=False,
             brokers_violating_metric_limits=1)
    assert m.state()["interBrokerPerBroker"] == 9
    # Two violating brokers: multiplicative decrease on every dimension
    # (including per-broker leadership, enabled here).
    m.adjust(cluster_healthy=True, has_under_min_isr=False,
             brokers_violating_metric_limits=2)
    s = m.state()
    assert s["interBrokerPerBroker"] == 4          # (8+1) / 2
    assert s["leadershipCluster"] == 450           # (800+100) / 2
    assert m._caps.leadership_per_broker == 112    # (200+25) / 2
    # brokers_violating_limits counts a broker once even with two limits hit.
    metrics = {1: {"BROKER_LOG_FLUSH_TIME_MS_999TH": 9000.0,
                   "BROKER_REQUEST_QUEUE_SIZE": 5000.0},
               2: {"BROKER_REQUEST_QUEUE_SIZE": 10.0},
               3: {"BROKER_PRODUCE_LOCAL_TIME_MS_999TH": 1500.0}}
    assert adj.brokers_violating_limits(metrics) == 2
    # AIMD floors: decreases clamp at the configured minimums.
    for _ in range(10):
        m.adjust(cluster_healthy=False, has_under_min_isr=True)
    s = m.state()
    assert s["interBrokerPerBroker"] == adj.min_partition_movements_per_broker
    assert s["leadershipCluster"] == adj.min_leadership_movements
    assert m._caps.leadership_per_broker == \
        adj.min_leadership_movements_per_broker


def test_concurrency_headroom_accounting():
    m = ExecutionConcurrencyManager(ConcurrencyCaps(inter_broker_per_broker=2,
                                                    cluster_inter_broker=3))
    assert m.inter_broker_headroom(5) == 2
    m.acquire_inter_broker((5, 6))
    assert m.inter_broker_headroom(5) == 1
    m.acquire_inter_broker((5,))
    assert m.inter_broker_headroom(5) == 0
    assert m.inter_broker_headroom(7) == 1  # cluster cap 3, 2 in flight
    m.release_inter_broker((5, 6))
    assert m.inter_broker_headroom(5) == 1


# ---- executor end-to-end against the fake cluster --------------------------

def test_executor_executes_proposals_to_completion():
    admin = make_cluster()
    ex = Executor(admin, progress_check_interval_s=0.005)
    props = [proposal(part=0, old=(0, 1), new=(2, 1), new_leader=2),
             proposal(part=1, old=(1, 2), old_leader=1, new=(3, 2), new_leader=3)]
    ex.execute_proposals(props, uuid="test")
    assert ex.await_completion(20)
    parts = admin.describe_partitions()
    assert set(parts[("t", 0)].replicas) == {1, 2}
    assert parts[("t", 0)].leader == 2
    assert set(parts[("t", 1)].replicas) == {2, 3}
    assert parts[("t", 1)].leader == 3
    counts = ex.execution_state()["taskCounts"]
    assert counts["inter_broker_replica_action"] == {"completed": 2}
    assert counts["leader_action"] == {"completed": 2}


def test_executor_rejects_concurrent_execution():
    admin = make_cluster()
    ex = Executor(admin, progress_check_interval_s=0.05)
    ex.execute_proposals([proposal(part=0, old=(0, 1), new=(2, 1), new_leader=2)])
    try:
        with pytest.raises(OngoingExecutionError):
            ex.execute_proposals([proposal(part=1)])
    finally:
        assert ex.await_completion(20)


def test_executor_stop_aborts_pending():
    admin = make_cluster(n_parts=8)
    admin._steps_per_tick = 0  # nothing ever completes
    ex = Executor(admin, ConcurrencyCaps(inter_broker_per_broker=1,
                                         cluster_inter_broker=1),
                  progress_check_interval_s=0.01)
    props = [proposal(part=i, old=(0, 1), new=(2, 1), new_leader=2)
             for i in range(0, 8, 4)]
    ex.execute_proposals(props)
    time.sleep(0.05)
    ex.stop_execution()
    assert ex.await_completion(20)
    counts = ex.execution_state()["taskCounts"]["inter_broker_replica_action"]
    assert counts.get("aborted", 0) >= 1
    assert admin.list_reassigning_partitions() == []


def test_executor_marks_dead_destination_tasks():
    admin = make_cluster()
    ex = Executor(admin, progress_check_interval_s=0.005, task_timeout_s=0.5)
    admin.kill_broker(2)
    ex.execute_proposals([proposal(part=0, old=(0, 1), new=(2, 1), new_leader=2)])
    assert ex.await_completion(20)
    counts = ex.execution_state()["taskCounts"]
    assert counts["inter_broker_replica_action"].get("dead") == 1


def test_executor_throttle_set_and_cleared():
    admin = make_cluster()
    ex = Executor(admin, progress_check_interval_s=0.005,
                  replication_throttle=12345)
    ex.execute_proposals([proposal(part=0, old=(0, 1), new=(2, 1), new_leader=2)])
    assert ex.await_completion(20)
    # Throttles were written then deleted (keys the helper set must not
    # survive the execution; pre-existing values would be restored).
    assert "leader.replication.throttled.rate" not in admin.broker_configs[2]
    assert "leader.replication.throttled.replicas" not in admin.topic_configs["t"]


def test_sampling_mode_toggled_around_execution():
    admin = make_cluster()
    flips = []
    ex = Executor(admin, progress_check_interval_s=0.005,
                  on_sampling_mode_change=flips.append)
    ex.execute_proposals([proposal(part=0, old=(0, 1), new=(2, 1), new_leader=2)])
    assert ex.await_completion(20)
    assert flips == [True, False]


# ---- external reassignments, adoption, notifier ----------------------------

class RecordingNotifier:
    def __init__(self):
        self.finished = []
        self.stopped = []

    def on_execution_finished(self, summary):
        self.finished.append(summary)

    def on_execution_stopped(self, summary):
        self.stopped.append(summary)


def test_refuses_external_reassignment_by_default():
    """ExecutionUtils.ongoingPartitionReassignments sanity: an in-flight
    reassignment this executor did not start blocks a new execution."""
    from cruise_control_tpu.executor import OngoingExternalReassignmentError

    admin = make_cluster(steps_per_tick=0)
    admin._auto_advance = False
    # External agent starts a reassignment.
    admin.alter_partition_reassignments({("t", 0): (2, 1)})
    ex = Executor(admin, synchronous=True)
    with pytest.raises(OngoingExternalReassignmentError):
        ex.execute_proposals([proposal(part=1, old=(1, 2), new=(3, 2),
                                       old_leader=1, new_leader=3)], uuid="x")


def test_stop_external_agent_cancels_then_executes():
    """maybeStopExternalAgent (Executor.java:1261): with the flag, the
    external reassignment is cancelled and the execution proceeds."""
    admin = make_cluster(steps_per_tick=0)
    admin._auto_advance = False
    admin.alter_partition_reassignments({("t", 0): (2, 1)})
    admin._steps_per_tick = 1_000_000
    admin._auto_advance = True
    ex = Executor(admin, synchronous=True)
    ex.execute_proposals([proposal(part=1, old=(1, 2), new=(3, 2),
                                   old_leader=1, new_leader=3)],
                         uuid="y", stop_external_agent=True)
    parts = admin.describe_partitions()
    assert set(parts[("t", 0)].replicas) == {0, 1}  # external move undone
    assert set(parts[("t", 1)].replicas) == {3, 2}  # our move applied


def test_adopts_reassignments_after_restart():
    """Executor.java:1238 recovery: a fresh executor (simulating a process
    restart mid-move) observes the in-flight reassignment, reconstructs the
    task, and tracks it to completion without re-submitting."""
    admin = make_cluster(steps_per_tick=0)
    admin._auto_advance = False
    # Previous executor life submitted this, then the process died.
    admin.alter_partition_reassignments({("t", 0): (2, 1)})
    submits_before = admin.reassignment_calls

    notifier = RecordingNotifier()
    ex = Executor(admin, progress_check_interval_s=0.01, notifier=notifier)
    adopted = ex.adopt_ongoing_reassignments(uuid="recovery")
    assert adopted == 1
    # Cluster makes progress; adopted task completes.
    admin._steps_per_tick = 1_000_000
    admin._auto_advance = True
    assert ex.await_completion(10.0)
    assert admin.reassignment_calls == submits_before  # nothing re-submitted
    parts = admin.describe_partitions()
    assert set(parts[("t", 0)].replicas) == {2, 1}
    counts = ex.execution_state()["taskCounts"]
    assert counts["inter_broker_replica_action"]["completed"] == 1
    assert notifier.finished and notifier.finished[0]["uuid"] == "recovery"


def test_adopt_with_nothing_in_flight_is_noop():
    admin = make_cluster()
    ex = Executor(admin, synchronous=True)
    assert ex.adopt_ongoing_reassignments() == 0
    assert not ex.has_ongoing_execution()


def test_notifier_fires_on_finish_and_stop():
    notifier = RecordingNotifier()
    admin = make_cluster()
    ex = Executor(admin, synchronous=True, notifier=notifier)
    ex.execute_proposals([proposal()], uuid="n1")
    assert [s["uuid"] for s in notifier.finished] == ["n1"]

    admin2 = make_cluster(steps_per_tick=0)
    admin2._auto_advance = False
    notifier2 = RecordingNotifier()
    ex2 = Executor(admin2, progress_check_interval_s=0.01, notifier=notifier2)
    ex2.execute_proposals([proposal()], uuid="n2")
    time.sleep(0.05)
    ex2.stop_execution()
    assert ex2.await_completion(10.0)
    assert notifier2.stopped and notifier2.stopped[0]["uuid"] == "n2"


# ---- intra-broker (JBOD logdir) phase --------------------------------------

def dir_proposal(part, broker, dst, src="d0", topic="t"):
    return ExecutionProposal(topic=topic, partition=part, old_leader=-1,
                             old_replicas=(), new_replicas=(), new_leader=-1,
                             logdir_broker=broker, source_logdir=src,
                             destination_logdir=dst)


def make_jbod_cluster(n_parts=8, brokers=(0, 1), dir_moves_per_tick=1):
    parts = [PartitionState(topic="t", partition=i,
                            replicas=(brokers[i % len(brokers)],),
                            leader=brokers[i % len(brokers)],
                            isr=(brokers[i % len(brokers)],))
             for i in range(n_parts)]
    admin = InMemoryAdminBackend(parts, dir_moves_per_tick=dir_moves_per_tick)
    admin.enable_jbod({b: ["d0", "d1"] for b in brokers},
                      placement={("t", i, brokers[i % len(brokers)]): "d0"
                                 for i in range(n_parts)})
    return admin


def test_intra_broker_phase_executes_and_polls_to_completion():
    """Logdir moves are submitted via alter_replica_logdirs, polled against
    replica_logdirs, and completed — not marked done without doing work
    (the round-2 stub drained tasks as completed; Executor.java:1672)."""
    admin = make_jbod_cluster(n_parts=4, brokers=(0,), dir_moves_per_tick=2)
    ex = Executor(admin, ConcurrencyCaps(intra_broker_per_broker=2),
                  progress_check_interval_s=0.005)
    ex.execute_proposals([dir_proposal(i, 0, "d1") for i in range(4)],
                         uuid="jbod")
    assert ex.await_completion(30.0)
    counts = ex.execution_state()["taskCounts"]
    assert counts[TaskType.INTRA_BROKER_REPLICA_ACTION.value] == {
        "completed": 4}
    dirs = admin.replica_logdirs()
    assert all(dirs[("t", i, 0)] == "d1" for i in range(4))


def test_intra_broker_phase_respects_per_broker_cap():
    """At most intra_broker_per_broker moves are in flight per broker at any
    poll interval (num.concurrent.intra.broker.partition.movements)."""
    admin = make_jbod_cluster(n_parts=8, brokers=(0,), dir_moves_per_tick=1)
    observed = []
    orig = admin.alter_replica_logdirs

    def spy(moves):
        observed.append(len(moves))
        orig(moves)

    admin.alter_replica_logdirs = spy
    ex = Executor(admin, ConcurrencyCaps(intra_broker_per_broker=2),
                  progress_check_interval_s=0.005)
    ex.execute_proposals([dir_proposal(i, 0, "d1") for i in range(8)],
                         uuid="jbod-cap")
    assert ex.await_completion(30.0)
    # First batch takes the full cap; every later batch only refills
    # completed slots — the cap holds ACROSS poll intervals.
    assert observed[0] == 2
    assert all(n <= 2 for n in observed)
    counts = ex.execution_state()["taskCounts"]
    assert counts[TaskType.INTRA_BROKER_REPLICA_ACTION.value] == {
        "completed": 8}


def test_intra_broker_phase_kills_tasks_on_dead_broker():
    admin = make_jbod_cluster(n_parts=4, brokers=(0, 1),
                              dir_moves_per_tick=1)
    ex = Executor(admin, ConcurrencyCaps(intra_broker_per_broker=1),
                  progress_check_interval_s=0.005, task_timeout_s=0.3)
    admin.kill_broker(1)
    ex.execute_proposals([dir_proposal(i, i % 2, "d1") for i in range(4)],
                         uuid="jbod-dead")
    assert ex.await_completion(30.0)
    counts = ex.execution_state()["taskCounts"]
    by_state = counts[TaskType.INTRA_BROKER_REPLICA_ACTION.value]
    assert by_state.get("completed") == 2      # broker 0's moves
    assert by_state.get("dead") == 2           # broker 1 died


def test_intra_broker_tasks_dead_without_jbod_backend():
    """A backend without the JBOD surface DEAD-marks logdir tasks instead of
    faking completion."""
    admin = make_cluster()

    class NoJbod:
        def __getattr__(self, name):
            if name in ("alter_replica_logdirs", "replica_logdirs"):
                raise AttributeError(name)
            return getattr(admin, name)

    ex2 = Executor(NoJbod(), synchronous=True)
    ex2.execute_proposals([dir_proposal(0, 0, "d1")], uuid="nojbod")
    counts = ex2.execution_state()["taskCounts"]
    assert counts[TaskType.INTRA_BROKER_REPLICA_ACTION.value] == {"dead": 1}


def test_mixed_proposal_runs_all_three_phases():
    """One proposal carrying an inter-broker move, a logdir leg, and a
    leadership change expands into three tasks executed phase by phase."""
    admin = make_jbod_cluster(n_parts=4, brokers=(0, 1, 2),
                              dir_moves_per_tick=100)
    p = ExecutionProposal(topic="t", partition=0, old_leader=0,
                          old_replicas=(0,), new_replicas=(1,), new_leader=1,
                          logdir_broker=1, source_logdir="d0",
                          destination_logdir="d1")
    ex = Executor(admin, progress_check_interval_s=0.005)
    ex.execute_proposals([p], uuid="mixed")
    assert ex.await_completion(30.0)
    counts = ex.execution_state()["taskCounts"]
    assert counts[TaskType.INTER_BROKER_REPLICA_ACTION.value] == {"completed": 1}
    assert counts[TaskType.INTRA_BROKER_REPLICA_ACTION.value] == {"completed": 1}
    assert admin.replica_logdirs()[("t", 0, 1)] == "d1"


# ---- metric-driven concurrency adjuster ------------------------------------

def test_adjuster_reduces_batch_when_isr_shrinks_mid_execution():
    """Executor.java:465-683: under-min-ISR state observed during the poll
    loop halves the per-broker inter-broker cap, so the NEXT submitted
    batch is smaller; a healthy cluster steps it back up."""
    # 12 proposals moving partitions 0..11 from broker 0 to broker 2; a
    # bystander partition on broker 3 whose ISR will shrink mid-flight.
    parts = [PartitionState(topic="t", partition=i, replicas=(0, 1),
                            leader=0, isr=(0, 1)) for i in range(12)]
    parts.append(PartitionState(topic="t", partition=99, replicas=(3, 1),
                                leader=3, isr=(3, 1)))
    admin = InMemoryAdminBackend(parts, steps_per_tick=0)
    admin.alter_topic_configs({"t": {"min.insync.replicas": "2"}})
    admin.revive_broker(2)

    batch_sizes = []
    orig = admin.alter_partition_reassignments

    def spy(targets):
        batch_sizes.append(len(targets))
        orig(targets)

    admin.alter_partition_reassignments = spy
    from cruise_control_tpu.executor.concurrency import (
        ConcurrencyAdjusterConfig,
    )
    # min.isr.check.enabled defaults FALSE (reference parity); this test
    # exercises the min-ISR pressure path, so enable it explicitly.
    ex = Executor(admin, ConcurrencyCaps(inter_broker_per_broker=4),
                  progress_check_interval_s=0.01,
                  adjuster_enabled=True, adjuster_interval_s=0.0,
                  adjuster_config=ConcurrencyAdjusterConfig(
                      min_isr_check_enabled=True))
    ex.execute_proposals(
        [proposal(part=i, old=(0, 1), new=(2, 1), new_leader=2)
         for i in range(12)], uuid="adj")
    # First batch goes out at the base cap while the cluster looks healthy.
    deadline = time.time() + 5
    while not batch_sizes and time.time() < deadline:
        time.sleep(0.005)
    assert batch_sizes and batch_sizes[0] == 4

    # Shrink ISR below min.insync.replicas: kill the bystander broker.
    admin.kill_broker(3)
    time.sleep(0.1)
    cap_under_pressure = ex.execution_state()["concurrency"][
        "interBrokerPerBroker"]
    assert cap_under_pressure < 4

    # Recovery: revive the broker; the cap steps back up and execution
    # completes.
    admin.revive_broker(3)
    admin._steps_per_tick = 1_000_000
    assert ex.await_completion(30.0)
    assert all(n <= 4 for n in batch_sizes)
    assert any(n < 4 for n in batch_sizes[1:]), batch_sizes
    counts = ex.execution_state()["taskCounts"]
    assert counts[TaskType.INTER_BROKER_REPLICA_ACTION.value] == {
        "completed": 12}
