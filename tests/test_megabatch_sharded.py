"""Device-sharded megabatch (round 23): byte parity of the shard_map
twins against the single-device batched kernels, pad-slot freezing on
the sharded cluster axis, compile accounting (one program per (bucket
shape, mesh)), and the chain-layer goal loop routed through a mesh.

Runs on the 8-device virtual CPU platform from conftest.py: the mesh
here is 4 devices x 2 cluster slots each, so every test exercises a
REAL sharded cluster axis with per-device early exit."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.analyzer.chain import (
    AdaptiveDispatch, MegastepConfig, inert_state_like,
    megabatch_all_goal_stats, megabatch_goal_stats,
    megabatch_optimize_rounds, megabatch_swap_rounds,
    optimize_goal_in_chain_megabatch, stack_states, unstack_state,
)
from cruise_control_tpu.analyzer.direct import megabatch_direct_rounds
from cruise_control_tpu.analyzer.constraint import BalancingConstraint
from cruise_control_tpu.analyzer.goals import (
    NetworkOutboundUsageDistributionGoal, RackAwareGoal,
    ReplicaDistributionGoal,
)
from cruise_control_tpu.analyzer.search import ExclusionMasks, SearchConfig
from cruise_control_tpu.parallel.megabatch_sharded import (
    _make_move_kernels, megabatch_all_goal_stats_sharded,
    megabatch_direct_rounds_donated_sharded, megabatch_direct_rounds_sharded,
    megabatch_goal_stats_sharded, megabatch_optimize_rounds_donated_sharded,
    megabatch_optimize_rounds_sharded, megabatch_swap_rounds_sharded,
    shard_megabatch, shard_megabatch_masks,
)
from cruise_control_tpu.parallel.mesh import make_mesh
from cruise_control_tpu.model.fixtures import random_cluster

CONSTRAINT = BalancingConstraint()
CFG = SearchConfig(num_sources=8, num_dests=4, moves_per_round=8,
                   max_rounds=12)
GOALS = (RackAwareGoal(), ReplicaDistributionGoal())
MASKS = ExclusionMasks()
NUM_TOPICS = 4
WIDTH = 8  # 4 devices x 2 cluster slots


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 4, "conftest must provide virtual devices"
    return make_mesh(4)


def _batch(num_partitions, partition_bucket, n_real):
    """WIDTH-slot megabatch: n_real skewed clusters + inert pad slots,
    plus the host-side active/real masks."""
    states = [random_cluster(num_brokers=6, num_topics=NUM_TOPICS,
                             num_partitions=num_partitions, rf=2,
                             num_racks=2, seed=3 + i, skew_to_first=2.0,
                             partition_bucket=partition_bucket)[0]
              for i in range(n_real)]
    states += [inert_state_like(states[0])] * (WIDTH - n_real)
    real = np.arange(WIDTH) < n_real
    return stack_states(states), jnp.asarray(real), real


def _assert_state_equal(a, b):
    for f in dataclasses.fields(a):
        np.testing.assert_array_equal(
            np.asarray(getattr(a, f.name)), np.asarray(getattr(b, f.name)),
            err_msg=f.name)


# Two bucket shapes x {full, partial} occupancy — the ISSUE-20 parity
# grid. Partial occupancy (inert pad slots, active mask off) must leave
# the pads byte-frozen THROUGH the sharded program.
@pytest.mark.parametrize("shape", [(24, 32), (100, 128)],
                         ids=["bucket32", "bucket128"])
@pytest.mark.parametrize("n_real", [WIDTH, WIDTH - 3],
                         ids=["full", "partial"])
def test_sharded_move_rounds_byte_identical(mesh, shape, n_real):
    npart, bucket = shape
    batched, active, real = _batch(npart, bucket, n_real)
    idx = jnp.int32(1)           # ReplicaDistribution under RackAware
    prior = jnp.asarray([True, False])
    budget = jnp.int32(12)

    ref, rt, rr, ra = megabatch_optimize_rounds(
        batched, active, idx, prior, GOALS, CONSTRAINT, CFG, NUM_TOPICS,
        MASKS, budget)
    out, ot, orr, oa = megabatch_optimize_rounds_sharded(
        mesh, shard_megabatch(batched, mesh), active, idx, prior, GOALS,
        CONSTRAINT, CFG, NUM_TOPICS, shard_megabatch_masks(MASKS, mesh),
        budget)

    _assert_state_equal(jax.device_get(out), jax.device_get(ref))
    np.testing.assert_array_equal(np.asarray(ot), np.asarray(rt))
    np.testing.assert_array_equal(np.asarray(orr), np.asarray(rr))
    np.testing.assert_array_equal(np.asarray(oa), np.asarray(ra))
    assert np.asarray(rt)[real].sum() > 0, "no moves — test is vacuous"
    # Pad slots byte-frozen through the sharded program.
    for s in np.flatnonzero(~real):
        _assert_state_equal(unstack_state(jax.device_get(out), int(s)),
                            unstack_state(jax.device_get(batched), int(s)))
        assert int(np.asarray(ot)[s]) == 0 and int(np.asarray(orr)[s]) == 0


def test_sharded_donated_matches_plain(mesh):
    """CCSA002 on the mesh: the donated twin (separately-donated sharded
    {assignment, leader_slot} + read-only zero-row rest) lands on the
    same bytes as the plain sharded kernel."""
    batched, active, _real = _batch(24, 32, WIDTH)
    idx, prior, budget = jnp.int32(1), jnp.asarray([True, False]), \
        jnp.int32(12)
    sb = shard_megabatch(batched, mesh)
    sm = shard_megabatch_masks(MASKS, mesh)
    ref, rt, _rr, _ra = megabatch_optimize_rounds_sharded(
        mesh, sb, active, idx, prior, GOALS, CONSTRAINT, CFG, NUM_TOPICS,
        sm, budget)
    rest = dataclasses.replace(
        sb, assignment=jnp.zeros((WIDTH, 0, sb.assignment.shape[2]),
                                 sb.assignment.dtype),
        leader_slot=jnp.zeros((WIDTH, 0), sb.leader_slot.dtype))
    a, l, dt, _dr, _da = megabatch_optimize_rounds_donated_sharded(
        mesh, jnp.copy(sb.assignment), jnp.copy(sb.leader_slot), rest,
        active, idx, prior, GOALS, CONSTRAINT, CFG, NUM_TOPICS, sm, budget)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(ref.assignment))
    np.testing.assert_array_equal(np.asarray(l),
                                  np.asarray(ref.leader_slot))
    np.testing.assert_array_equal(np.asarray(dt), np.asarray(rt))


def test_sharded_swap_rounds_byte_identical(mesh):
    batched, active, _real = _batch(24, 32, WIDTH)
    goals = (NetworkOutboundUsageDistributionGoal(),)
    idx, prior, budget = jnp.int32(0), jnp.asarray([False]), jnp.int32(8)
    ref, rt, rr, ra = megabatch_swap_rounds(
        batched, active, idx, prior, goals, CONSTRAINT, NUM_TOPICS, MASKS,
        8, 64, budget)
    out, ot, orr, oa = megabatch_swap_rounds_sharded(
        mesh, shard_megabatch(batched, mesh), active, idx, prior, goals,
        CONSTRAINT, NUM_TOPICS, shard_megabatch_masks(MASKS, mesh), 8, 64,
        budget)
    _assert_state_equal(jax.device_get(out), jax.device_get(ref))
    np.testing.assert_array_equal(np.asarray(ot), np.asarray(rt))
    np.testing.assert_array_equal(np.asarray(orr), np.asarray(rr))
    np.testing.assert_array_equal(np.asarray(oa), np.asarray(ra))


def test_sharded_direct_rounds_byte_identical(mesh):
    """The direct-transport twin, including its deterministic rounding
    PRNG: same seed, same plan, same bytes across the mesh split."""
    batched, active, _real = _batch(100, 128, WIDTH)
    goals = (ReplicaDistributionGoal(),)
    ref, rt, rs, ra = megabatch_direct_rounds(
        batched, active, goals, 0, CONSTRAINT, NUM_TOPICS, MASKS)
    sb = shard_megabatch(batched, mesh)
    sm = shard_megabatch_masks(MASKS, mesh)
    out, ot, os_, oa = megabatch_direct_rounds_sharded(
        mesh, sb, active, goals, 0, CONSTRAINT, NUM_TOPICS, sm)
    _assert_state_equal(jax.device_get(out), jax.device_get(ref))
    np.testing.assert_array_equal(np.asarray(ot), np.asarray(rt))
    np.testing.assert_array_equal(np.asarray(os_), np.asarray(rs))
    rest = dataclasses.replace(
        sb, assignment=jnp.zeros((WIDTH, 0, sb.assignment.shape[2]),
                                 sb.assignment.dtype),
        leader_slot=jnp.zeros((WIDTH, 0), sb.leader_slot.dtype))
    a, l, dt, _ds, _da = megabatch_direct_rounds_donated_sharded(
        mesh, jnp.copy(sb.assignment), jnp.copy(sb.leader_slot), rest,
        active, goals, 0, CONSTRAINT, NUM_TOPICS, sm)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(ref.assignment))
    np.testing.assert_array_equal(np.asarray(dt), np.asarray(rt))


def test_sharded_stats_byte_identical(mesh):
    batched, active, _real = _batch(24, 32, WIDTH - 2)
    sb = shard_megabatch(batched, mesh)
    sm = shard_megabatch_masks(MASKS, mesh)
    v1, o1, f1 = megabatch_goal_stats(batched, jnp.int32(1), GOALS,
                                      CONSTRAINT, NUM_TOPICS, MASKS)
    v2, o2, f2 = megabatch_goal_stats_sharded(mesh, sb, jnp.int32(1),
                                              GOALS, CONSTRAINT,
                                              NUM_TOPICS, sm)
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    np.testing.assert_array_equal(np.asarray(f1), np.asarray(f2))
    a1 = megabatch_all_goal_stats(batched, GOALS, CONSTRAINT, NUM_TOPICS,
                                  MASKS)
    a2 = megabatch_all_goal_stats_sharded(mesh, sb, GOALS, CONSTRAINT,
                                          NUM_TOPICS, sm)
    for x, y in zip(a1, a2):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_one_compiled_program_per_shape_and_mesh(mesh):
    """Compile accounting: re-running the sharded move kernel on new
    DATA at a known (bucket shape, mesh) adds no compilation; a new
    bucket shape adds exactly one; the kernel factory itself is cached
    per (mesh, chain config)."""
    move, _ = _make_move_kernels(mesh, GOALS, CONSTRAINT, CFG, NUM_TOPICS,
                                 (False, False, False), 0)
    move2, _ = _make_move_kernels(mesh, GOALS, CONSTRAINT, CFG, NUM_TOPICS,
                                  (False, False, False), 0)
    assert move is move2, "factory must be cached per (mesh, config)"

    idx, prior, budget = jnp.int32(1), jnp.asarray([True, False]), \
        jnp.int32(4)

    def run(npart, bucket, seed_base):
        states = [random_cluster(num_brokers=6, num_topics=NUM_TOPICS,
                                 num_partitions=npart, rf=2, num_racks=2,
                                 seed=seed_base + i, skew_to_first=2.0,
                                 partition_bucket=bucket)[0]
                  for i in range(WIDTH)]
        sb = shard_megabatch(stack_states(states), mesh)
        sm = shard_megabatch_masks(MASKS, mesh)
        out = move(sb, jnp.ones(WIDTH, bool), sm, idx, prior, budget)
        jax.block_until_ready(out[0].assignment)

    # Bucket shapes no other test in this module touches, so the deltas
    # are exact regardless of suite order (the factory's lru_cache
    # shares one jit object module-wide).
    run(40, 64, 3)
    n0 = move._cache_size()
    run(40, 64, 101)             # same shape, different clusters
    assert move._cache_size() == n0
    run(200, 256, 3)             # new bucket shape
    assert move._cache_size() == n0 + 1


def test_shard_megabatch_rejects_indivisible_width(mesh):
    states = [random_cluster(num_brokers=6, num_topics=NUM_TOPICS,
                             num_partitions=24, rf=2, num_racks=2,
                             seed=3 + i, partition_bucket=32)[0]
              for i in range(6)]  # 6 % 4 != 0
    with pytest.raises(ValueError, match="not divisible"):
        shard_megabatch(stack_states(states), mesh)


def test_chain_goal_loop_mesh_matches_single_device(mesh):
    """The chain layer's megabatch goal loop (pump, donation guard,
    per-cluster infos) routed through ``mesh=`` lands byte-identical to
    ``mesh=None`` — the production parity contract the --fleet-shard
    stage pins at scale."""
    batched, active_mask, real = _batch(24, 32, WIDTH - 1)
    chain = GOALS
    mega = MegastepConfig(donate=True, async_readback=True,
                          deficit_moves_cap=0)

    def run(m):
        st = batched
        bmasks = MASKS
        if m is not None:
            st = shard_megabatch(st, m)
            bmasks = shard_megabatch_masks(MASKS, m)
        infos_all = []
        ran = False
        for i in range(len(chain)):
            st, infos = optimize_goal_in_chain_megabatch(
                st, chain, i, CONSTRAINT, CFG, NUM_TOPICS, bmasks,
                np.asarray(real), dispatch_rounds=6,
                dispatch=AdaptiveDispatch(6, 0.0), megastep=mega,
                donate_input=ran, mesh=m)
            ran = ran or any(x["rounds"] > 0 for x in infos)
            infos_all.append(infos)
        return jax.device_get(st), infos_all

    ref, ref_infos = run(None)
    out, out_infos = run(mesh)
    _assert_state_equal(out, ref)
    for gi_ref, gi_out in zip(ref_infos, out_infos):
        for a, b in zip(gi_ref, gi_out):
            assert (a["goal"], a["rounds"], a["moves_applied"]) == \
                (b["goal"], b["rounds"], b["moves_applied"])
    assert sum(x["moves_applied"] for g in ref_infos for x in g) > 0
