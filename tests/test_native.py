"""Native runtime (native/ccnative.c) vs the pure-Python serde.

The C index parser and the Python record-batch walk must agree byte-for-
byte on every input — valid, fuzzed, truncated, and corrupted. The native
library compiles on first use; if no compiler exists these tests skip
(callers fall back to Python transparently)."""

import random

import pytest

from cruise_control_tpu.kafka.wire.crc32c import _TABLE, crc32c
from cruise_control_tpu.kafka.wire.records import (
    Record, decode_batches, encode_batch,
)
from cruise_control_tpu.native import index_records, lib


def _python_crc(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = _TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _python_decode(data: bytes, verify_crc: bool = True) -> list[Record]:
    """The pure-Python walk, bypassing the native fast path."""
    import cruise_control_tpu.native as native

    saved = native._lib, native._lib_tried
    native._lib, native._lib_tried = None, True
    try:
        return decode_batches(data, verify_crc)
    finally:
        native._lib, native._lib_tried = saved


needs_native = pytest.mark.skipif(lib() is None,
                                  reason="no C compiler available")


def _random_records(rng: random.Random, n: int, base: int) -> list[Record]:
    out = []
    for i in range(n):
        key = None if rng.random() < 0.3 else rng.randbytes(rng.randrange(0, 40))
        value = None if rng.random() < 0.1 else rng.randbytes(rng.randrange(0, 200))
        headers = []
        if rng.random() < 0.25:
            headers = [(f"h{j}", None if rng.random() < 0.3
                        else rng.randbytes(rng.randrange(0, 20)))
                       for j in range(rng.randrange(1, 4))]
        out.append(Record(offset=base + i,
                          timestamp_ms=1_700_000_000_000 + rng.randrange(0, 10_000),
                          key=key, value=value, headers=headers))
    return out


@needs_native
def test_crc32c_native_matches_python():
    rng = random.Random(7)
    for size in (0, 1, 7, 64, 1000):
        data = rng.randbytes(size)
        assert crc32c(data) == _python_crc(data)
    # incremental (crc chaining) parity
    data = rng.randbytes(100)
    assert crc32c(data[50:], crc32c(data[:50])) == _python_crc(
        data[50:], _python_crc(data[:50]))


@needs_native
def test_native_decode_fuzz_equivalence():
    """200 random multi-batch record sets: native and Python decoders must
    return identical records (offsets, timestamps, keys, values, headers)."""
    rng = random.Random(42)
    for trial in range(200):
        chunks, base = [], rng.randrange(0, 1000)
        for _ in range(rng.randrange(1, 4)):
            recs = _random_records(rng, rng.randrange(1, 8), base)
            base += len(recs)
            chunks.append(encode_batch(recs))
        data = b"".join(chunks)
        assert decode_batches(data) == _python_decode(data), trial


@needs_native
def test_native_decode_partial_trailing_batch():
    rng = random.Random(3)
    full = encode_batch(_random_records(rng, 5, 0))
    partial = encode_batch(_random_records(rng, 3, 5))[:-7]
    data = full + partial
    got = decode_batches(data)
    assert got == _python_decode(data)
    assert len(got) == 5


@needs_native
def test_native_decode_crc_and_magic_errors():
    recs = [Record(offset=0, timestamp_ms=1000, key=b"k", value=b"v" * 32)]
    clean = encode_batch(recs)
    # Corrupt a byte INSIDE the value span (framing stays intact, only the
    # checksum catches it).
    voff = int(index_records(clean)[0, 4])
    data = bytearray(clean)
    data[voff + 5] ^= 0xFF
    with pytest.raises(ValueError, match="CRC"):
        decode_batches(bytes(data))
    with pytest.raises(ValueError, match="CRC"):
        _python_decode(bytes(data))
    # verify_crc=False skips the check on both paths
    assert decode_batches(bytes(data), verify_crc=False) == \
        _python_decode(bytes(data), verify_crc=False)
    bad_magic = bytearray(clean)
    bad_magic[16] = 1
    with pytest.raises(ValueError, match="magic"):
        decode_batches(bytes(bad_magic))


@needs_native
def test_native_index_spans():
    """The raw index table's spans must slice exactly the key/value bytes."""
    recs = [Record(offset=10, timestamp_ms=1000, key=b"k0", value=b"v00"),
            Record(offset=11, timestamp_ms=1001, key=None, value=b"v\x00v"),
            Record(offset=12, timestamp_ms=999, key=b"", value=None)]
    data = encode_batch(recs)
    idx = index_records(data)
    assert idx.shape == (3, 8)
    off, ts, koff, klen, voff, vlen, _hoff, hcount = idx[0].tolist()
    assert (off, ts, hcount) == (10, 1000, 0)
    assert data[koff:koff + klen] == b"k0"
    assert data[voff:voff + vlen] == b"v00"
    assert idx[1, 2] == -1 and idx[1, 3] == -1          # null key
    assert data[idx[1, 4]:idx[1, 4] + idx[1, 5]] == b"v\x00v"
    assert idx[2, 3] == 0 and idx[2, 4] == -1           # empty key, null value


@needs_native
def test_native_malformed_garbage_does_not_crash():
    """Adversarial bytes must raise/return cleanly, never read OOB."""
    rng = random.Random(11)
    base = bytearray(encode_batch(_random_records(rng, 6, 0)))
    for trial in range(300):
        data = bytearray(base)
        for _ in range(rng.randrange(1, 6)):
            data[rng.randrange(len(data))] = rng.randrange(256)
        try:
            native = decode_batches(bytes(data), verify_crc=False)
        except ValueError:
            native = ValueError
        try:
            pure = _python_decode(bytes(data), verify_crc=False)
        except ValueError:
            pure = ValueError
        # Both must fail, or both must agree (the native parser is a
        # validator too — it may legitimately reject a mutation the lax
        # Python slicer tolerates, but never the reverse, and never with
        # different successful outputs).
        if native is not ValueError and pure is not ValueError:
            assert native == pure, trial
        elif pure is ValueError:
            assert native is ValueError, trial


@needs_native
def test_native_decode_corrupt_trailing_fragment_parity():
    """A trailing fragment whose batchLength field reads < MIN_BATCH_LEN
    must be treated the same by BOTH decoders: silently dropped when the
    fragment is partial (end > len), rejected when it claims to be a
    complete batch (ADVICE r3: the decoders previously disagreed)."""
    import struct

    rng = random.Random(11)
    full = encode_batch(_random_records(rng, 4, 0))

    # Partial trailing fragment with a garbage (tiny) batchLength: both
    # decoders drop it — the fragment's fields are untrusted.
    frag = struct.pack(">qi", 99, 5) + b"\x01\x02"          # end > len
    data = full + frag
    assert decode_batches(data) == _python_decode(data)
    assert len(decode_batches(data)) == 4

    # "Complete" batch whose length can't hold the fixed header: both
    # decoders reject.
    bad = struct.pack(">qi", 99, 5) + b"\x00" * 5           # end <= len
    for decoder in (decode_batches, _python_decode):
        try:
            decoder(full + bad)
            raise AssertionError("expected ValueError")
        except ValueError:
            pass

    # Negative batchLength: both reject (signed arithmetic must not wrap).
    neg = struct.pack(">qi", 99, -40) + b"\x00" * 8
    for decoder in (decode_batches, _python_decode):
        try:
            decoder(full + neg)
            raise AssertionError("expected ValueError")
        except ValueError:
            pass
