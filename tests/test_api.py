"""REST API layer: endpoint dispatch, parameters, responses, user tasks,
two-step review, security (reference parity: servlet/ test ideas —
KafkaCruiseControlServletEndpointTest, UserTaskManagerTest, purgatory and
security suites — against the stdlib server)."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from cruise_control_tpu.api import (
    EndPoint, Purgatory, ReviewStatus, Role, UserTaskManager,
)
from cruise_control_tpu.api.parameters import (
    ParameterParseError, parse_parameters,
)
from cruise_control_tpu.api.security import (
    AuthenticationError, BasicSecurityProvider, JwtSecurityProvider,
    Principal, TrustedProxySecurityProvider, encode_jwt,
    parse_credentials_file,
)
from cruise_control_tpu.api.server import CruiseControlApi, make_server
from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
from cruise_control_tpu.executor.admin import InMemoryAdminBackend, PartitionState
from cruise_control_tpu.executor.executor import Executor
from cruise_control_tpu.facade import CruiseControl
from cruise_control_tpu.monitor import LoadMonitor, StaticCapacityResolver
from cruise_control_tpu.monitor.sampling import SyntheticSampler


def _partitions(brokers=(0, 1, 2, 3), topics=2, parts=4):
    out = {}
    for t in range(topics):
        for p in range(parts):
            reps = (brokers[0], brokers[1 + (t + p) % (len(brokers) - 1)])
            out[(f"t{t}", p)] = PartitionState(f"t{t}", p, reps, reps[0],
                                               isr=reps)
    return out


@pytest.fixture(scope="module")
def cc():
    partitions = _partitions()
    backend = InMemoryAdminBackend(partitions.values())
    cfg = CruiseControlConfig({
        "partition.metrics.window.ms": 1000,
        "num.partition.metrics.windows": 3,
        "min.valid.partition.ratio": 0.0,
        "max.solver.rounds": 30,
        "failed.brokers.file.path": ""})
    caps = StaticCapacityResolver({}, {Resource.CPU: 100.0, Resource.DISK: 1e7,
                                       Resource.NW_IN: 1e6, Resource.NW_OUT: 1e6})
    monitor = LoadMonitor(cfg, backend, samplers=[SyntheticSampler()],
                          capacity_resolver=caps)
    cc = CruiseControl(cfg, backend, load_monitor=monitor,
                       executor=Executor(backend, synchronous=True))
    for k in range(1, 4):
        monitor.task_runner.run_sampling_once(end_ms=k * 1000)
    return cc


@pytest.fixture()
def api(cc):
    api = CruiseControlApi(cc)
    api._async_wait_s = 180       # cover first-compile of the solver kernels
    yield api
    api.shutdown()


# ---- parameters ----------------------------------------------------------

def test_parameter_parsing_types_and_unknown_rejection():
    q = {"brokerid": ["1,2,3"], "dryrun": ["false"], "reason": ["test"]}
    p = parse_parameters(EndPoint.REMOVE_BROKER, q)
    assert p == {"brokerid": (1, 2, 3), "dryrun": False, "reason": "test"}
    with pytest.raises(ParameterParseError, match="unknown parameter"):
        parse_parameters(EndPoint.REBALANCE, {"tyop": ["x"]})
    with pytest.raises(ParameterParseError, match="not a boolean"):
        parse_parameters(EndPoint.REBALANCE, {"dryrun": ["maybe"]})


def test_remove_disks_parameter_pairs():
    p = parse_parameters(EndPoint.REMOVE_DISKS,
                         {"brokerid_and_logdirs": ["0-/d1,0-/d2,1-/d1"]})
    assert p["brokerid_and_logdirs"] == {0: ("/d1", "/d2"), 1: ("/d1",)}


# ---- endpoint dispatch ---------------------------------------------------

def test_state_endpoint(api):
    status, body, _ = api.handle("GET", "/kafkacruisecontrol/state")
    assert status == 200
    assert {"MonitorState", "ExecutorState", "AnalyzerState",
            "AnomalyDetectorState"} <= set(body)


def test_unknown_endpoint_and_method_mismatch(api):
    assert api.handle("GET", "/kafkacruisecontrol/nope")[0] == 404
    assert api.handle("GET", "/other/state")[0] == 404
    assert api.handle("GET", "/kafkacruisecontrol/rebalance")[0] == 405


def test_kafka_cluster_state(api):
    status, body, _ = api.handle("GET", "/kafkacruisecontrol/kafka_cluster_state")
    assert status == 200
    counts = body["KafkaBrokerState"]["ReplicaCountByBrokerId"]
    assert sum(counts.values()) == 16      # 8 partitions × RF 2


def test_load_and_partition_load(api):
    status, body, _ = api.handle("GET", "/kafkacruisecontrol/load")
    assert status == 200
    assert len(body["brokers"]) == 4
    assert all("DiskMB" in b and "CpuPct" in b for b in body["brokers"])
    # Host-level rows (BrokerStats.java host section): default topology is
    # one host per broker, so sums must match broker-for-broker.
    assert len(body["hosts"]) == 4
    assert all("Host" in h and "Replicas" in h and "DiskMB" in h
               for h in body["hosts"])
    assert sum(h["Replicas"] for h in body["hosts"]) \
        == sum(b["Replicas"] for b in body["brokers"])
    status, body, _ = api.handle(
        "GET", "/kafkacruisecontrol/partition_load",
        "resource=network_outbound&entries=5")
    assert status == 200
    assert len(body["records"]) == 5
    status, _body, _ = api.handle("GET", "/kafkacruisecontrol/partition_load",
                                  "resource=warp_drive")
    assert status == 400


def test_load_host_rows_rack_falls_back_to_host():
    """Rack-falls-back-to-host end-to-end through the LOAD body
    (ClusterModel.createBroker: rack == null ? host : rack +
    model/Host.java:275 host aggregation): two rackless brokers sharing a
    host collapse to one fault domain AND one aggregated host row."""
    from cruise_control_tpu.api.responses import broker_stats
    from cruise_control_tpu.model.builder import ClusterModelBuilder

    cap = {Resource.CPU: 100.0, Resource.NW_IN: 1e5, Resource.NW_OUT: 1e5,
           Resource.DISK: 1e6}
    load = {Resource.CPU: 1.0, Resource.NW_IN: 10.0, Resource.NW_OUT: 10.0,
            Resource.DISK: 100.0}
    b = ClusterModelBuilder()
    b.add_broker(0, "", cap, host="shared-host")
    b.add_broker(1, "", cap, host="shared-host")
    b.add_broker(2, "rackA", cap, host="solo-host")
    b.add_partition("t", 0, [0, 2], leader_load=load)
    b.add_partition("t", 1, [1, 2], leader_load=load)
    state, meta = b.build()
    body = broker_stats(state, meta)

    by_host = {h["Host"]: h for h in body["hosts"]}
    assert set(by_host) == {"shared-host", "solo-host"}
    assert by_host["shared-host"]["Replicas"] == 2   # brokers 0 + 1
    assert by_host["solo-host"]["Replicas"] == 2     # broker 2's two
    assert by_host["shared-host"]["DiskMB"] == pytest.approx(200.0)
    rows = {r["Broker"]: r for r in body["brokers"]}
    # Rackless brokers inherit their host as the fault domain.
    assert rows[0]["Rack"] == rows[1]["Rack"] == "shared-host"
    assert rows[0]["Host"] == rows[1]["Host"] == "shared-host"
    assert rows[2]["Rack"] == "rackA" and rows[2]["Host"] == "solo-host"


def test_proposals_and_rebalance_dryrun(api):
    status, body, headers = api.handle("POST", "/kafkacruisecontrol/rebalance",
                                       "dryrun=true")
    assert status == 200
    assert body["proposals"], "skewed fixture must produce proposals"
    assert "User-Task-ID" in headers
    status, body2, _ = api.handle("GET", "/kafkacruisecontrol/proposals")
    assert status == 200 and "summary" in body2


def test_user_tasks_listing(api):
    api.handle("POST", "/kafkacruisecontrol/rebalance", "dryrun=true")
    status, body, _ = api.handle("GET", "/kafkacruisecontrol/user_tasks")
    assert status == 200
    assert body["userTasks"]
    assert {"UserTaskId", "Status", "RequestURL"} <= set(body["userTasks"][0])


def test_user_task_id_resume(api):
    _s, _b, headers = api.handle("POST", "/kafkacruisecontrol/rebalance",
                                 "dryrun=true")
    tid = headers["User-Task-ID"]
    _s2, _b2, headers2 = api.handle("POST", "/kafkacruisecontrol/rebalance",
                                    "dryrun=true", {"User-Task-ID": tid})
    assert headers2["User-Task-ID"] == tid


def test_admin_self_healing_toggle(api, cc):
    status, body, _ = api.handle(
        "POST", "/kafkacruisecontrol/admin",
        "enable_self_healing_for=broker_failure")
    assert status == 200
    st = cc.anomaly_detector.state()
    assert "BROKER_FAILURE" in st["selfHealingEnabled"]
    status, body, _ = api.handle(
        "POST", "/kafkacruisecontrol/admin",
        "disable_self_healing_for=broker_failure")
    assert status == 200
    assert body["selfHealingDisabledBefore"] == {"broker_failure": True}


def test_admin_concurrency_override(api, cc):
    status, body, _ = api.handle(
        "POST", "/kafkacruisecontrol/admin",
        "concurrent_partition_movements_per_broker=3")
    assert status == 200
    assert cc.executor._concurrency._caps.inter_broker_per_broker == 3


def test_admin_concurrency_adjuster_toggles(api, cc):
    mgr = cc.executor._concurrency
    base = mgr.snapshot()
    status, body, _ = api.handle(
        "POST", "/kafkacruisecontrol/admin",
        "disable_concurrency_adjuster_for=leadership"
        "&min_isr_based_concurrency_adjustment=false")
    assert status == 200
    assert body["concurrencyAdjusterEnabledBefore"] == {"leadership": True}
    # Seeded from concurrency.adjuster.min.isr.check.enabled, which
    # defaults FALSE (ExecutorConfig.java:583).
    assert body["minIsrBasedAdjustmentBefore"] is False
    # LEADERSHIP adjuster off + min-ISR-based adjustment off: an
    # under-min-ISR tick changes neither cap.
    mgr.adjust(cluster_healthy=False, has_under_min_isr=True)
    after = mgr.snapshot()
    assert after.leadership_cluster == base.leadership_cluster
    assert after.inter_broker_per_broker == base.inter_broker_per_broker
    # Re-enable: the same tick now halves the inter-broker cap again.
    assert api.handle("POST", "/kafkacruisecontrol/admin",
                      "enable_concurrency_adjuster_for=leadership"
                      "&min_isr_based_concurrency_adjustment=true")[0] == 200
    mgr.adjust(cluster_healthy=False, has_under_min_isr=True)
    adj = mgr.adjuster_config
    assert mgr.snapshot().inter_broker_per_broker == \
        max(adj.min_partition_movements_per_broker,
            int(base.inter_broker_per_broker
                / adj.multiplicative_decrease_inter_broker))
    cc.executor.set_requested_concurrency(
        inter_broker_per_broker=base.inter_broker_per_broker,
        leadership_cluster=base.leadership_cluster)
    # A typo'd concurrency type must 400, not silently no-op.
    assert api.handle("POST", "/kafkacruisecontrol/admin",
                      "disable_concurrency_adjuster_for=warp_drive")[0] == 400


def test_admin_rejects_whole_request_on_any_bad_name(api, cc):
    """A typo anywhere in an ADMIN request must 400 WITHOUT applying the
    valid toggles that preceded it (no partial mutation under an error)."""
    st_before = cc.anomaly_detector.state()["selfHealingEnabled"]
    status, _b, _ = api.handle(
        "POST", "/kafkacruisecontrol/admin",
        "disable_self_healing_for=broker_failure"
        "&disable_concurrency_adjuster_for=warp_drive")
    assert status == 400
    assert cc.anomaly_detector.state()["selfHealingEnabled"] == st_before
    assert api.handle("POST", "/kafkacruisecontrol/admin",
                      "enable_self_healing_for=warp_core")[0] == 400


def test_stop_execution_stop_external_agent(api, cc):
    backend = cc._admin
    # An "external agent" reassignment: destination broker 9 is dead, so the
    # fake cluster's tick never completes it.
    backend.alter_partition_reassignments({("t0", 0): (0, 9)})
    assert backend.list_reassigning_partitions()
    # A plain stop leaves the external reassignment alone ...
    assert api.handle("POST",
                      "/kafkacruisecontrol/stop_proposal_execution")[0] == 200
    assert backend.list_reassigning_partitions()
    # ... stop_external_agent=true cancels it (maybeStopExternalAgent:1261).
    assert api.handle("POST", "/kafkacruisecontrol/stop_proposal_execution",
                      "stop_external_agent=true&force_stop=true")[0] == 200
    assert not backend.list_reassigning_partitions()


def test_execution_param_surface_parses():
    p = parse_parameters(EndPoint.REBALANCE, {
        "max_partition_movements_in_cluster": ["600"],
        "broker_concurrent_leader_movements": ["50"],
        "dryrun": ["false"]})
    assert p["max_partition_movements_in_cluster"] == 600
    assert p["broker_concurrent_leader_movements"] == 50
    p = parse_parameters(EndPoint.TOPIC_CONFIGURATION,
                         {"skip_rack_awareness_check": ["true"],
                          "topic": ["t0"], "replication_factor": ["3"]})
    assert p["skip_rack_awareness_check"] is True
    p = parse_parameters(EndPoint.BOOTSTRAP, {"developer_mode": ["true"],
                                              "start": ["0"]})
    assert p["developer_mode"] is True


def test_pause_resume_and_stop(api, cc):
    assert api.handle("POST", "/kafkacruisecontrol/pause_sampling",
                      "reason=maintenance")[0] == 200
    assert cc.load_monitor.task_runner.sampling_mode.name == "PAUSED"
    assert api.handle("POST", "/kafkacruisecontrol/resume_sampling")[0] == 200
    assert cc.load_monitor.task_runner.sampling_mode.name == "RUNNING"
    assert api.handle("POST",
                      "/kafkacruisecontrol/stop_proposal_execution")[0] == 200


def test_remove_disks_requires_jbod_backend(api):
    status, body, _ = api.handle("POST", "/kafkacruisecontrol/remove_disks",
                                 "brokerid_and_logdirs=0-/d1")
    assert status == 400
    assert "JBOD" in body["errorMessage"]


# ---- two-step review -----------------------------------------------------

def test_two_step_review_flow(cc):
    api = CruiseControlApi(cc, config=None)
    api._two_step = True
    try:
        status, body, _ = api.handle("POST", "/kafkacruisecontrol/rebalance",
                                     "dryrun=true")
        assert status == 200
        rid = body["reviewResult"]["Id"]
        assert body["reviewResult"]["Status"] == "PENDING_REVIEW"
        # Un-approved submission is rejected.
        status, body2, _ = api.handle("POST", "/kafkacruisecontrol/rebalance",
                                      f"dryrun=true&review_id={rid}")
        assert status == 400
        # Approve via REVIEW, then submit.
        status, body3, _ = api.handle("POST", "/kafkacruisecontrol/review",
                                      f"approve={rid}")
        assert status == 200
        assert body3["requestInfo"][0]["Status"] == "APPROVED"
        # Submission replays the REVIEWED query: smuggled parameter changes
        # (dryrun=false here) are discarded in favor of what was approved.
        status, body4, _ = api.handle("POST", "/kafkacruisecontrol/rebalance",
                                      f"dryrun=false&review_id={rid}")
        assert status == 200 and body4["proposals"]
        assert body4["dryrun"] is True and body4["executed"] is False
        status, board, _ = api.handle("GET", "/kafkacruisecontrol/review_board")
        assert board["requestInfo"][0]["Status"] == "SUBMITTED"
    finally:
        api.shutdown()


def test_purgatory_transitions():
    purgatory = Purgatory()
    info = purgatory.add("REBALANCE", "dryrun=true", "alice")
    with pytest.raises(ValueError):
        purgatory.submit(info.review_id, "REBALANCE")   # not approved yet
    purgatory.approve(info.review_id)
    with pytest.raises(ValueError):
        purgatory.submit(info.review_id, "ADD_BROKER")  # endpoint mismatch
    assert purgatory.submit(info.review_id, "REBALANCE").status \
        is ReviewStatus.SUBMITTED
    info2 = purgatory.add("REBALANCE", "", "bob")
    purgatory.discard(info2.review_id, "nope")
    with pytest.raises(ValueError):
        purgatory.approve(info2.review_id)


# ---- security ------------------------------------------------------------

def test_basic_security_provider_and_roles(cc):
    users = parse_credentials_file(
        "viewer: vpass, VIEWER\nadmin: apass, ADMIN\n")
    api = CruiseControlApi(cc, BasicSecurityProvider(users=users))
    try:
        import base64

        def basic(u, p):
            return {"Authorization": "Basic "
                    + base64.b64encode(f"{u}:{p}".encode()).decode()}

        assert api.handle("GET", "/kafkacruisecontrol/state")[0] == 401
        assert api.handle("GET", "/kafkacruisecontrol/state",
                          headers=basic("viewer", "wrong"))[0] == 401
        assert api.handle("GET", "/kafkacruisecontrol/state",
                          headers=basic("viewer", "vpass"))[0] == 200
        # VIEWER may not POST rebalance (requires ADMIN).
        assert api.handle("POST", "/kafkacruisecontrol/rebalance", "dryrun=true",
                          headers=basic("viewer", "vpass"))[0] == 403
        assert api.handle("POST", "/kafkacruisecontrol/pause_sampling", "",
                          headers=basic("admin", "apass"))[0] == 200
        api.handle("POST", "/kafkacruisecontrol/resume_sampling", "",
                   headers=basic("admin", "apass"))
    finally:
        api.shutdown()


def test_jwt_security_provider():
    secret = b"s3cret"
    provider = JwtSecurityProvider(secret)
    token = encode_jwt({"sub": "ops", "roles": ["ADMIN"],
                        "exp": time.time() + 60}, secret)
    principal = provider.authenticate({"Authorization": f"Bearer {token}"})
    assert principal == Principal("ops", Role.ADMIN)
    expired = encode_jwt({"sub": "ops", "exp": time.time() - 1}, secret)
    with pytest.raises(AuthenticationError, match="expired"):
        provider.authenticate({"Authorization": f"Bearer {expired}"})
    forged = token[:-2] + "xx"
    with pytest.raises(AuthenticationError, match="signature"):
        provider.authenticate({"Authorization": f"Bearer {forged}"})


def test_trusted_proxy_provider():
    provider = TrustedProxySecurityProvider({"10.0.0.1"},
                                            {"alice": Role.ADMIN})
    p = provider.authenticate({"X-Do-As": "alice"}, remote_addr="10.0.0.1")
    assert p.role is Role.ADMIN
    with pytest.raises(AuthenticationError):
        provider.authenticate({"X-Do-As": "alice"}, remote_addr="10.9.9.9")
    with pytest.raises(AuthenticationError):
        provider.authenticate({}, remote_addr="10.0.0.1")


# ---- user task manager ---------------------------------------------------

def test_user_task_manager_caps_active_tasks():
    utm = UserTaskManager(max_active_tasks=1)
    try:
        gate = threading.Event()
        utm.get_or_create_task("STATE", "", gate.wait)
        with pytest.raises(RuntimeError, match="max active"):
            utm.get_or_create_task("STATE", "", lambda: None)
        gate.set()
    finally:
        utm.shutdown()


# ---- real HTTP round-trip ------------------------------------------------

def test_http_server_round_trip(cc):
    server, api = make_server(cc, host="127.0.0.1", port=0)
    from cruise_control_tpu.api.server import serve_forever_in_thread
    serve_forever_in_thread(server)
    try:
        port = server.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/kafkacruisecontrol/state") as r:
            assert r.status == 200
            body = json.loads(r.read())
            assert "MonitorState" in body
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/kafkacruisecontrol/rebalance?dryrun=true",
            method="POST")
        with urllib.request.urlopen(req) as r:
            assert r.status == 200
            body = json.loads(r.read())
            assert body["proposals"]
    finally:
        server.shutdown()
        api.shutdown()


# ---- console client ------------------------------------------------------

def test_cccli_against_live_server(cc, capsys):
    from cruise_control_tpu.client import main as cccli_main
    server, api = make_server(cc, host="127.0.0.1", port=0)
    from cruise_control_tpu.api.server import serve_forever_in_thread
    serve_forever_in_thread(server)
    try:
        port = server.server_address[1]
        rc = cccli_main(["-a", f"http://127.0.0.1:{port}", "state"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert "MonitorState" in out
        rc = cccli_main(["-a", f"http://127.0.0.1:{port}", "rebalance",
                         "--dryrun", "true"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["proposals"]
        # Server-side parameter rejection propagates as a client error.
        rc = cccli_main(["-a", f"http://127.0.0.1:{port}", "partition_load",
                         "--resource", "warp"])
        assert rc == 1
        assert "unknown resource" in capsys.readouterr().err
    finally:
        server.shutdown()
        api.shutdown()


def test_metrics_endpoint_renders_prometheus(api):
    """/metrics publishes the headline sensors (Sensors.md): valid windows,
    monitored-partitions pct, balancedness, proposal-computation timer,
    executor task counters."""
    from cruise_control_tpu.utils.sensors import SENSORS

    SENSORS.clear()  # the registry is process-global; isolate the scrape
    SENSORS.record_timer("analyzer_proposal_computation", 1.25)
    SENSORS.count("executor_tasks", 3, labels={
        "type": "inter_broker_replica_action", "state": "completed"})
    text = api.metrics_text()
    assert "kafka_cruisecontrol_monitor_num_valid_windows" in text
    assert "kafka_cruisecontrol_monitor_monitored_partitions_percentage" in text
    assert "kafka_cruisecontrol_analyzer_balancedness_score" in text
    assert "kafka_cruisecontrol_analyzer_proposal_computation_seconds_count" in text
    assert 'kafka_cruisecontrol_executor_tasks_total{state="completed"' \
           ',type="inter_broker_replica_action"} 3' in text


def test_forecast_endpoint_serves_state(api):
    """GET /forecast (round 19): VIEWER-safe engine + detector state;
    disabled by default (off means off) with the config geometry still
    reported so an operator can see what flipping it on would do."""
    status, body, _ = api.handle("GET", "/kafkacruisecontrol/forecast", "")
    assert status == 200
    assert body["forecastEnabled"] is False
    assert body["forecast"] is None
    assert body["detector"]["predictionsMade"] == 0
    assert body["horizonWindows"] >= 1 and body["fitWindows"] >= 4
    # Unknown params still 400 (the shared parameter discipline).
    status, body, _ = api.handle("GET", "/kafkacruisecontrol/forecast",
                                 "bogus=1")
    assert status == 400


def test_openapi_spec_covers_all_endpoints():
    import yaml

    from cruise_control_tpu.api.endpoints import EndPoint
    from cruise_control_tpu.api.openapi import openapi_yaml

    spec = yaml.safe_load(openapi_yaml())
    assert spec["openapi"].startswith("3.")
    for e in EndPoint:
        path = f"/kafkacruisecontrol/{e.name.lower()}"
        assert path in spec["paths"], path
        assert e.method.lower() in spec["paths"][path]
    # Parameters derive from the live schemas.
    rb = spec["paths"]["/kafkacruisecontrol/rebalance"]["post"]["parameters"]
    names = {p["name"] for p in rb}
    assert {"dryrun", "goals", "verbose", "json",
            "replica_movement_strategies"} <= names


def test_json_false_renders_plaintext(api):
    status, body, headers = api.handle(
        "GET", "/kafkacruisecontrol/state", "json=false")
    assert status == 200
    assert "__text__" in body
    assert "MonitorState" in body["__text__"]
    assert headers["Content-Type"].startswith("text/plain")


def test_get_response_schema_included(api):
    status, body, _ = api.handle(
        "GET", "/kafkacruisecontrol/state", "get_response_schema=true")
    assert status == 200
    assert body["responseSchema"]["version"] == "number"


def test_verbose_adds_stats_and_caps_proposals(api):
    status, body, _ = api.handle("GET", "/kafkacruisecontrol/proposals",
                                 "verbose=true")
    assert status == 200
    assert "loadBeforeOptimization" in body
    assert body["numProposals"] == len(body["proposals"])


def test_endpoint_request_class_is_config_swappable(cc):
    """CruiseControlRequestConfig reflection parity: a configured
    <endpoint>.request.class takes over the endpoint end to end."""

    class CustomStateHandler:
        def handle(self, facade, params, principal):
            return {"version": 1, "custom": True,
                    "caller": principal.name}

    import cruise_control_tpu.api.server as server_mod
    cfg = CruiseControlConfig({
        "state.request.class":
            f"{__name__}.CustomStateHandler",
        "failed.brokers.file.path": ""})
    # Resolution goes through resolve_class on a dotted path; register the
    # class where that path can find it.
    import sys
    setattr(sys.modules[__name__], "CustomStateHandler", CustomStateHandler)
    api = server_mod.CruiseControlApi(cc, config=cfg)
    try:
        status, body, _ = api.handle("GET", "/kafkacruisecontrol/state")
        assert status == 200
        assert body == {"version": 1, "custom": True, "caller": "anonymous"}
    finally:
        api.shutdown()


def test_user_task_manager_max_active_maps_to_429(cc):
    import threading

    from cruise_control_tpu.api.user_tasks import UserTaskManager

    api = CruiseControlApi(cc)
    api._async_wait_s = 0.01
    gate = threading.Event()
    api._tasks = UserTaskManager(max_active_tasks=1)
    api._tasks.get_or_create_task("REBALANCE", "", gate.wait)
    try:
        status, body, _ = api.handle("POST", "/kafkacruisecontrol/rebalance",
                                     "dryrun=true")
        assert status == 429
        assert "max active user tasks" in body["errorMessage"]
    finally:
        gate.set()
        api.shutdown()


def test_user_task_per_class_completed_retention():
    from cruise_control_tpu.api.user_tasks import UserTaskManager

    m = UserTaskManager(max_active_tasks=50,
                        max_cached_completed_monitor_tasks=2,
                        max_cached_completed_admin_tasks=3)
    try:
        for i in range(5):
            m.get_or_create_task("PROPOSALS", f"q={i}", lambda: 1).future.result()
        for i in range(5):
            m.get_or_create_task("REBALANCE", f"q={i}", lambda: 1).future.result()
        tasks = m.all_tasks()
        monitor = [t for t in tasks if t.endpoint == "PROPOSALS"]
        admin = [t for t in tasks if t.endpoint == "REBALANCE"]
        assert len(monitor) == 2     # newest 2 monitor-type kept
        assert len(admin) == 3       # newest 3 admin-type kept
    finally:
        m.shutdown()


def test_async_task_reports_typed_progress(api):
    """OperationProgress parity: a completed model-building task records
    the typed steps (AggregatingMetrics → GeneratingClusterModel → ...)."""
    api.handle("POST", "/kafkacruisecontrol/rebalance", "dryrun=true")
    tasks = [t for t in api.user_tasks.all_tasks()
             if t.endpoint == "REBALANCE"]
    assert tasks
    steps = [p["step"] for p in tasks[0].progress.to_list()]
    assert "GeneratingClusterModel" in steps
    assert "OptimizationForGoalChain" in steps


def test_jwt_rs256_round_trip():
    """RS256 JWT verification against a public key (JwtAuthenticator.java
    parity via the cryptography package), including audience checks."""
    import base64
    import json as json_mod
    import time as time_mod

    # Optional dependency: tier-1 must stay green on images without it
    # (the provider itself degrades the same way at runtime).
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import padding, rsa

    from cruise_control_tpu.api.security import (
        AuthenticationError, JwtSecurityProvider, Role,
    )

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    pem = key.public_key().public_bytes(
        serialization.Encoding.PEM,
        serialization.PublicFormat.SubjectPublicKeyInfo)

    def b64url(data: bytes) -> str:
        return base64.urlsafe_b64encode(data).rstrip(b"=").decode()

    def sign(claims: dict) -> str:
        header = b64url(json_mod.dumps({"alg": "RS256",
                                        "typ": "JWT"}).encode())
        payload = b64url(json_mod.dumps(claims).encode())
        sig = key.sign(f"{header}.{payload}".encode(), padding.PKCS1v15(),
                       hashes.SHA256())
        return f"{header}.{payload}.{b64url(sig)}"

    provider = JwtSecurityProvider(public_key_pem=pem,
                                   expected_audiences=("cruise-control",))
    token = sign({"sub": "alice", "roles": ["ADMIN"],
                  "aud": "cruise-control",
                  "exp": time_mod.time() + 60})
    principal = provider.authenticate({"Authorization": f"Bearer {token}"})
    assert principal.name == "alice" and principal.role is Role.ADMIN

    import pytest as pytest_mod
    with pytest_mod.raises(AuthenticationError, match="audience"):
        provider.authenticate({"Authorization": "Bearer " + sign(
            {"sub": "alice", "aud": "other", "exp": time_mod.time() + 60})})
    # Tampered payload: signature must fail.
    head, payload, sig = token.split(".")
    evil = b64url(json_mod.dumps({"sub": "mallory", "roles": ["ADMIN"],
                                  "aud": "cruise-control"}).encode())
    with pytest_mod.raises(AuthenticationError, match="signature"):
        provider.authenticate(
            {"Authorization": f"Bearer {head}.{evil}.{sig}"})


def test_user_task_id_bound_to_client():
    """A User-Task-ID is a capability scoped to its creator: another
    client presenting the id gets 403, not the first client's result
    (UserTaskManager.java session binding)."""
    from cruise_control_tpu.api.user_tasks import (
        TaskOwnershipError, UserTaskManager,
    )

    mgr = UserTaskManager()
    info = mgr.get_or_create_task("PROPOSALS", "", lambda: 42,
                                  client="alice")
    assert info.future.result(timeout=5) == 42
    # same client resumes fine
    again = mgr.get_or_create_task("PROPOSALS", "", lambda: 43,
                                   task_id=info.task_id, client="alice")
    assert again.task_id == info.task_id
    with pytest.raises(TaskOwnershipError):
        mgr.get_or_create_task("PROPOSALS", "", lambda: 44,
                               task_id=info.task_id, client="mallory")
    mgr.shutdown()


def test_unknown_user_task_id_is_rejected_not_squatted():
    """An unknown/expired User-Task-ID must 400, never create a task
    under the client-chosen id (id squatting would 403 the legitimate
    owner's next poll after cache eviction)."""
    from cruise_control_tpu.api.user_tasks import UserTaskManager

    mgr = UserTaskManager()
    with pytest.raises(ValueError, match="unknown or expired"):
        mgr.get_or_create_task("PROPOSALS", "", lambda: 1,
                               task_id="11111111-2222-3333-4444-555555555555",
                               client="mallory")
    assert mgr.all_tasks() == []
    mgr.shutdown()


def test_request_reason_required(cc):
    api2 = CruiseControlApi(cc)
    api2._reason_required = True
    try:
        status, body, _ = api2.handle("POST", "/kafkacruisecontrol/rebalance",
                                      "dryrun=true")
        assert status == 400 and "reason" in body["errorMessage"]
        # Non-executing POSTs stay exempt (ParameterUtils scopes the flag to
        # the proposal-executing parameter classes).
        assert api2.handle("POST",
                           "/kafkacruisecontrol/pause_sampling")[0] == 200
        assert api2.handle("POST", "/kafkacruisecontrol/resume_sampling",
                           "reason=x")[0] == 200
    finally:
        api2.shutdown()


def test_provisioner_disabled_refuses_rightsize():
    partitions = _partitions()
    backend = InMemoryAdminBackend(partitions.values())
    cfg = CruiseControlConfig({
        "partition.metrics.window.ms": 1000,
        "num.partition.metrics.windows": 3,
        "min.valid.partition.ratio": 0.0,
        "provisioner.enable": False,
        "failed.brokers.file.path": ""})
    caps = StaticCapacityResolver({}, {Resource.CPU: 100.0, Resource.DISK: 1e7,
                                       Resource.NW_IN: 1e6, Resource.NW_OUT: 1e6})
    monitor = LoadMonitor(cfg, backend, samplers=[SyntheticSampler()],
                          capacity_resolver=caps)
    cc2 = CruiseControl(cfg, backend, load_monitor=monitor,
                        executor=Executor(backend, synchronous=True))
    api2 = CruiseControlApi(cc2)
    try:
        status, body, _ = api2.handle("POST", "/kafkacruisecontrol/rightsize",
                                      "numbrokerstoadd=2")
        assert status == 400
        assert "provisioner" in body["errorMessage"]
    finally:
        api2.shutdown()


def test_user_task_manager_four_retention_classes():
    from cruise_control_tpu.api.user_tasks import task_class

    assert task_class("LOAD") == "KAFKA_MONITOR"
    assert task_class("REBALANCE") == "KAFKA_ADMIN"
    assert task_class("STATE") == "CC_MONITOR"
    assert task_class("ADMIN") == "CC_ADMIN"
    mgr = UserTaskManager(max_cached_completed_monitor_tasks=2,
                          max_cached_completed_admin_tasks=5,
                          max_cached_completed_cc_monitor_tasks=1)
    try:
        for i in range(4):
            mgr.get_or_create_task("LOAD", f"q{i}", lambda: 1).future.result()
        for i in range(3):
            mgr.get_or_create_task("STATE", f"q{i}", lambda: 1).future.result()
        tasks = mgr.all_tasks()
        assert sum(1 for t in tasks if t.endpoint == "LOAD") == 2
        assert sum(1 for t in tasks if t.endpoint == "STATE") == 1
    finally:
        mgr.shutdown()


def test_web_ui_served_with_traversal_guard(cc):
    server, api2 = make_server(cc, host="127.0.0.1", port=0)
    try:
        threading.Thread(target=server.serve_forever, daemon=True).start()
        port = server.server_address[1]
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/") as r:
            body = r.read().decode()
            assert r.headers["Content-Type"].startswith("text/html")
            assert "cruise-control-tpu" in body
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/index.html") as r:
            assert r.status == 200
        # Traversal attempts must not escape the UI directory.
        for evil in ("/../facade.py", "/..%2f..%2fetc%2fpasswd",
                     "/nonexistent.js"):
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{evil}") as r:
                    assert r.status == 404, evil
            except urllib.error.HTTPError as e:
                assert e.code == 404, evil
    finally:
        server.shutdown()
        api2.shutdown()


def test_web_ui_bundled_package_files_not_served(cc):
    server, api2 = make_server(cc, host="127.0.0.1", port=0)
    try:
        threading.Thread(target=server.serve_forever, daemon=True).start()
        port = server.server_address[1]
        # Only recognized asset types are public from the bundled package.
        for hidden in ("/__init__.py", "/__pycache__/__init__.cpython-311.pyc"):
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{hidden}") as r:
                    assert r.status == 404, hidden
            except urllib.error.HTTPError as e:
                assert e.code == 404, hidden
    finally:
        server.shutdown()
        api2.shutdown()


def test_web_ui_requires_auth_when_security_enabled(cc):
    from cruise_control_tpu.api.security import BasicSecurityProvider, Role
    import base64 as b64
    provider = BasicSecurityProvider(users={"ops": ("pw", Role.VIEWER)})
    server, api2 = make_server(cc, host="127.0.0.1", port=0,
                               security_provider=provider)
    try:
        threading.Thread(target=server.serve_forever, daemon=True).start()
        port = server.server_address[1]
        try:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/")
            raise AssertionError("expected 401")
        except urllib.error.HTTPError as e:
            assert e.code == 401
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/",
            headers={"Authorization": "Basic "
                     + b64.b64encode(b"ops:pw").decode()})
        with urllib.request.urlopen(req) as r:
            assert r.status == 200
            assert "cruise-control-tpu" in r.read().decode()
    finally:
        server.shutdown()
        api2.shutdown()


# ---- request-parameter conformance (VERDICT r3 weak #4) ------------------

def test_kafka_assigner_mode_switches_chain(api):
    """rebalance?kafka_assigner=true runs EXACTLY the two assigner goals
    (ParameterUtils.getGoals:755-771)."""
    status, body, _ = api.handle(
        "POST", "/kafkacruisecontrol/rebalance",
        "kafka_assigner=true&dryrun=true")
    assert status == 200, body
    names = [g["goal"] for g in body["goalSummary"]]
    assert names == ["KafkaAssignerEvenRackAwareGoal",
                     "KafkaAssignerDiskUsageDistributionGoal"]


def test_kafka_assigner_mode_conflicts_are_400(api):
    status, body, _ = api.handle(
        "POST", "/kafkacruisecontrol/rebalance",
        "kafka_assigner=true&goals=RackAwareGoal&dryrun=true")
    assert status == 400 and "explicitly specifying" in body["errorMessage"]
    status, body, _ = api.handle(
        "POST", "/kafkacruisecontrol/rebalance",
        "kafka_assigner=true&rebalance_disk=true&dryrun=true")
    assert status == 400


def test_use_ready_default_goals_filters_chain(api, cc):
    """With full monitor readiness the ready chain IS the default chain;
    with explicit goals the combination is a 400
    (ParameterUtils.getBooleanExcludeGiven:323-334)."""
    ready = [g.name for g in cc.ready_goals()]
    default_chain = [s.rsplit(".", 1)[-1]
                     for s in cc._config.get_list("goals")]
    assert ready == default_chain  # fixture monitor is fully caught up
    status, body, _ = api.handle(
        "POST", "/kafkacruisecontrol/rebalance",
        "use_ready_default_goals=true&goals=RackAwareGoal&dryrun=true")
    assert status == 400
    status, body, _ = api.handle(
        "POST", "/kafkacruisecontrol/rebalance",
        "use_ready_default_goals=true&dryrun=true")
    assert status == 200, body
    assert [g["goal"] for g in body["goalSummary"]] == default_chain


def test_ready_goals_tracks_monitor_completeness(cc):
    """Resource-metric goals need num_windows//2 valid windows; structural
    goals need one (Goal.clusterModelCompletenessRequirements)."""
    from cruise_control_tpu.analyzer.optimizer import goals_by_priority
    chain = goals_by_priority(cc._config)
    windows = cc._config.get_int("num.partition.metrics.windows")
    for g in chain:
        need_w, _need_r = g.completeness_requirements(windows, 0.95)
        assert need_w == (max(1, windows // 2)
                          if g.uses_resource_metrics else 1)


def test_fast_mode_caps_goal_wall_clock(api):
    """fast_mode=true completes and reports per-goal durations bounded by
    the fast.mode.per.broker.move.timeout.ms x B budget (trivially
    satisfied at this scale — the assertion is that the parameter reaches
    the optimizer and the run still balances)."""
    status, body, _ = api.handle(
        "POST", "/kafkacruisecontrol/rebalance", "fast_mode=true&dryrun=true")
    assert status == 200, body
    assert body["goalSummary"]


def test_every_schema_param_has_a_consumer():
    """Tripwire for accepted-but-dead request parameters (the class of bug
    VERDICT r3 found for kafka_assigner/fast_mode/use_ready_default_goals):
    every parameter name in SCHEMAS must appear in at least one consuming
    module outside parameters.py."""
    import os

    import cruise_control_tpu.api.parameters as params_mod

    root = os.path.dirname(os.path.dirname(
        os.path.abspath(params_mod.__file__)))
    consumers = [
        os.path.join(root, "api", "server.py"),
        os.path.join(root, "api", "responses.py"),
        os.path.join(root, "api", "user_tasks.py"),
        os.path.join(root, "api", "security.py"),
        os.path.join(root, "facade.py"),
        os.path.join(root, "monitor", "load_monitor.py"),
    ]
    blob = "".join(open(f).read() for f in consumers)
    from cruise_control_tpu.api.parameters import _COMMON, SCHEMAS
    all_params = set(_COMMON)
    for schema in SCHEMAS.values():
        all_params |= set(schema)
    dead = sorted(p for p in all_params if f'"{p}"' not in blob)
    assert not dead, f"accepted-but-unused request parameters: {dead}"


def test_spnego_negotiate_with_stub_gssapi(monkeypatch):
    """SPNEGO completes a real accept-side GSS handshake when gssapi is
    importable (stubbed here — the package is not in this image), and
    fails LOUDLY without it (VERDICT r3 #8: no silent shim).
    Reference: security/spnego/SpnegoSecurityProvider.java:21."""
    import base64
    import sys
    import types

    from cruise_control_tpu.api.security import SpnegoSecurityProvider

    calls = {}

    class _Name:
        def __init__(self, name, name_type=None):
            self.name = name

        def __str__(self):
            return self.name

    class _Creds:
        def __init__(self, name=None, usage=None, store=None):
            calls["cred_name"] = str(name) if name else None
            calls["store"] = store

    class _Ctx:
        def __init__(self, creds=None, usage=None):
            calls["usage"] = usage

        def step(self, token):
            calls["token"] = token
            if token == b"bad":
                raise RuntimeError("defective token")

        @property
        def initiator_name(self):
            return _Name("alice/host@EXAMPLE.COM")

    stub = types.ModuleType("gssapi")
    stub.Name = _Name
    stub.NameType = types.SimpleNamespace(kerberos_principal="krb5")
    stub.Credentials = _Creds
    stub.SecurityContext = _Ctx
    monkeypatch.setitem(sys.modules, "gssapi", stub)

    provider = SpnegoSecurityProvider(
        principal="HTTP/cc.example.com@EXAMPLE.COM",
        keytab_file="/etc/krb5.keytab")
    token = base64.b64encode(b"gss-blob").decode()
    principal = provider.authenticate(
        {"Authorization": f"Negotiate {token}"})
    # Kerberos principal shortened to the bare user (principal shortening
    # of the reference provider) + keytab store threaded through.
    assert principal.name == "alice"
    assert calls["token"] == b"gss-blob"
    assert calls["store"] == {"keytab": "/etc/krb5.keytab"}
    assert calls["cred_name"] == "HTTP/cc.example.com@EXAMPLE.COM"

    # A defective token is a 401-class failure.
    bad = base64.b64encode(b"bad").decode()
    with pytest.raises(AuthenticationError, match="negotiation failed"):
        provider.authenticate({"Authorization": f"Negotiate {bad}"})

    # Without the gssapi package: loud server-side failure, never open.
    monkeypatch.delitem(sys.modules, "gssapi")
    import builtins
    real_import = builtins.__import__

    def no_gssapi(name, *a, **k):
        if name == "gssapi":
            raise ImportError("No module named 'gssapi'")
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", no_gssapi)
    with pytest.raises(AuthenticationError, match="python-gssapi"):
        provider.authenticate({"Authorization": f"Negotiate {token}"})
