"""Exactness of the incrementally-maintained aggregate carry (analyzer.agg).

The chain drivers read every per-broker aggregate the goals score and accept
against from an AggCarry updated by O(moves) scatters instead of O(P·S)
segment-sums. These tests pin the carry to the full recompute after many
rounds of moves, leadership transfers, and swaps: integer counts must match
EXACTLY; float sums within accumulation tolerance. (Trajectory-level
agg-on == agg-off parity is covered by tests/test_chain.py's chain-vs-
per-goal-oracle comparisons — the oracle kernels carry no agg.)
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.analyzer.agg import compute_agg
from cruise_control_tpu.analyzer.chain import (
    _chain_round_body, _chain_swap_body,
)
from cruise_control_tpu.analyzer.constraint import BalancingConstraint
from cruise_control_tpu.analyzer.optimizer import goals_by_priority
from cruise_control_tpu.analyzer.search import ExclusionMasks, SearchConfig
from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
from cruise_control_tpu.model.fixtures import Dist, random_cluster


@pytest.fixture(scope="module")
def setup():
    cfg = CruiseControlConfig()
    state, meta = random_cluster(
        num_brokers=24, num_topics=8, num_partitions=768, rf=3, num_racks=4,
        dist=Dist.EXPONENTIAL, seed=11, skew_to_first=2.0,
        target_utilization=0.6)
    goals = tuple(goals_by_priority(cfg))
    constraint = BalancingConstraint.from_config(cfg)
    return state, meta, goals, constraint


def _check_against_recompute(agg, state, num_topics):
    fresh = compute_agg(state, num_topics)
    np.testing.assert_array_equal(np.asarray(agg.broker_replicas),
                                  np.asarray(fresh.broker_replicas))
    np.testing.assert_array_equal(np.asarray(agg.broker_leaders),
                                  np.asarray(fresh.broker_leaders))
    np.testing.assert_array_equal(np.asarray(agg.topic_counts),
                                  np.asarray(fresh.topic_counts))
    np.testing.assert_allclose(np.asarray(agg.broker_load),
                               np.asarray(fresh.broker_load),
                               rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(np.asarray(agg.pot_nw_out),
                               np.asarray(fresh.pot_nw_out),
                               rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(np.asarray(agg.lbi), np.asarray(fresh.lbi),
                               rtol=1e-4, atol=1e-3)


@pytest.mark.slow  # ~25 s: many-round carry-vs-recompute sweep; tier-2.
def test_carry_tracks_moves_and_leadership(setup):
    """Rounds of the chain move body (replica moves + leadership transfers,
    goal switched mid-stream) keep the carry equal to the recompute."""
    state, meta, goals, constraint = setup
    cfg = SearchConfig(num_sources=32, num_dests=12, moves_per_round=16,
                       max_rounds=50)
    masks = ExclusionMasks()
    agg = compute_agg(state, meta.num_topics)
    # Mid-chain resource goal first (moves), then the leadership-only tail
    # goal (leadership movements), with all prior goals' acceptance stacked.
    total = 0
    for active, rounds in ((8, 6), (14, 4)):
        prior = jnp.asarray([j < active for j in range(len(goals))])
        for _ in range(rounds):
            state, agg, applied, _stat = _chain_round_body(
                state, agg, jnp.int32(active), prior, goals, constraint,
                cfg, meta.num_topics, masks)
            total += int(applied)
    assert total > 0, "fixture applied no moves: carry never exercised"
    _check_against_recompute(agg, state, meta.num_topics)


def test_carry_tracks_swaps(setup):
    """Swap rounds (two directional legs each) scatter both legs' exact
    effect onto the carry."""
    state, meta, goals, constraint = setup
    masks = ExclusionMasks()
    agg = compute_agg(state, meta.num_topics)
    active = 8  # DiskUsageDistributionGoal: supports_swap
    prior = jnp.asarray([j < active for j in range(len(goals))])
    total = 0
    for _ in range(5):
        state, agg, applied = _chain_swap_body(
            state, agg, jnp.int32(active), prior, goals, constraint,
            meta.num_topics, masks)
        total += int(applied)
    assert total > 0, "fixture applied no swaps: swap-leg carry not exercised"
    _check_against_recompute(agg, state, meta.num_topics)


def test_agg_backed_goal_aux_matches_recompute(setup):
    """partial_from_agg must agree with prepare_partial on the same state
    (TopicReplicaDistribution counts plane, LeaderBytesIn lbi)."""
    state, meta, goals, constraint = setup
    agg = compute_agg(state, meta.num_topics)
    for g in goals:
        from_agg = g.partial_from_agg(agg)
        if from_agg is None:
            continue
        fresh = g.prepare_partial(state, meta.num_topics)
        for key in fresh:
            np.testing.assert_allclose(np.asarray(from_agg[key]),
                                       np.asarray(fresh[key]), rtol=1e-6)
