"""Tensor cluster model tests.

Mirrors the intents of model/LoadConsistencyTest, CreateOrDeleteReplicasTest
and ClusterModelStats tests: load accounting stays consistent under
functional moves; stats reductions match hand computations.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.common import BrokerState, Resource
from cruise_control_tpu.model import (
    ClusterModelBuilder, apply_leadership_move, apply_replica_move, apply_swap,
    broker_leader_counts, broker_load, broker_replica_counts, cluster_stats,
    fixtures, offline_replicas, potential_nw_out, rack_partition_counts,
    set_broker_state, topic_broker_replica_counts,
)

CAP = {Resource.CPU: 100.0, Resource.NW_IN: 1000.0, Resource.NW_OUT: 1000.0,
       Resource.DISK: 10000.0}
LOAD = {Resource.CPU: 10.0, Resource.NW_IN: 50.0, Resource.NW_OUT: 60.0,
        Resource.DISK: 300.0}


def two_broker_cluster():
    b = ClusterModelBuilder()
    b.add_broker(0, "rA", CAP).add_broker(1, "rB", CAP)
    b.add_partition("t", 0, [0, 1], leader_load=LOAD)
    b.add_partition("t", 1, [1, 0], leader_load=LOAD)
    return b.build()


def test_broker_load_accounting():
    state, meta = two_broker_cluster()
    load = np.asarray(broker_load(state))
    # Each broker: one leader (full load) + one follower (follower load:
    # CPU*0.4, NW_IN same, NW_OUT 0, DISK same).
    assert load[0, Resource.CPU] == pytest.approx(10.0 + 4.0)
    assert load[0, Resource.NW_IN] == pytest.approx(100.0)
    assert load[0, Resource.NW_OUT] == pytest.approx(60.0)
    assert load[0, Resource.DISK] == pytest.approx(600.0)
    np.testing.assert_allclose(load[0], load[1])


def test_replica_and_leader_counts():
    state, _ = two_broker_cluster()
    assert np.asarray(broker_replica_counts(state)).tolist() == [2, 2]
    assert np.asarray(broker_leader_counts(state)).tolist() == [1, 1]


def test_replica_move_conserves_total_load():
    state, _ = two_broker_cluster()
    before = np.asarray(broker_load(state)).sum(axis=0)
    # Move follower of partition 0 (slot 1, on broker 1) to broker 0 is
    # illegal (already hosts p0); move it from broker 1 to... only 2 brokers,
    # so build a 3rd-broker cluster instead.
    b = ClusterModelBuilder()
    b.add_broker(0, "rA", CAP).add_broker(1, "rB", CAP).add_broker(2, "rC", CAP)
    b.add_partition("t", 0, [0, 1], leader_load=LOAD)
    state, _ = b.build()
    before = np.asarray(broker_load(state)).sum(axis=0)
    moved = apply_replica_move(state, jnp.array(0), jnp.array(1), jnp.array(2))
    after_b = np.asarray(broker_load(moved))
    np.testing.assert_allclose(after_b.sum(axis=0), before, rtol=1e-6)
    assert after_b[1].sum() == 0.0
    assert after_b[2, Resource.NW_IN] == pytest.approx(50.0)


def test_leadership_move_shifts_nw_out():
    state, _ = two_broker_cluster()
    moved = apply_leadership_move(state, jnp.array(0), jnp.array(1))
    load = np.asarray(broker_load(moved))
    # Partition 0's leader now on broker 1: broker 1 has 2 leaders.
    assert np.asarray(broker_leader_counts(moved)).tolist() == [0, 2]
    assert load[1, Resource.NW_OUT] == pytest.approx(120.0)
    assert load[0, Resource.NW_OUT] == pytest.approx(0.0)


def test_swap_action():
    b = ClusterModelBuilder()
    b.add_broker(0, "rA", CAP).add_broker(1, "rB", CAP)
    b.add_partition("t", 0, [0], leader_load=LOAD)
    b.add_partition("t", 1, [1], leader_load={Resource.CPU: 2.0})
    state, _ = b.build()
    swapped = apply_swap(state, jnp.array(0), jnp.array(0), jnp.array(1), jnp.array(0))
    load = np.asarray(broker_load(swapped))
    assert load[1, Resource.CPU] == pytest.approx(10.0)
    assert load[0, Resource.CPU] == pytest.approx(2.0)


def test_potential_nw_out():
    state, _ = two_broker_cluster()
    pot = np.asarray(potential_nw_out(state))
    # Every broker hosts replicas of both partitions → potential = 120 each.
    np.testing.assert_allclose(pot[:2], [120.0, 120.0])


def test_rack_partition_counts():
    state, meta = fixtures.rack_aware_satisfiable()
    counts = np.asarray(rack_partition_counts(state, len(meta.rack_names)))
    # Partition 0 has both replicas in rack rA (index 0).
    assert counts[0].tolist() == [2, 0, 0]
    assert counts[1].tolist() == [1, 1, 0]


def test_topic_broker_replica_counts():
    state, meta = two_broker_cluster()
    tb = np.asarray(topic_broker_replica_counts(state, meta.num_topics))
    assert tb.shape[0] == 1
    assert tb[0].tolist() == [2, 2]


def test_offline_replicas_and_set_state():
    state, _ = fixtures.dead_broker_cluster()
    off = np.asarray(offline_replicas(state))
    assert off.sum() == 4  # four replicas on the dead broker 3
    healed = set_broker_state(state, jnp.array(3), int(BrokerState.ALIVE))
    assert np.asarray(offline_replicas(healed)).sum() == 0


def test_cluster_stats_sane():
    state, _ = fixtures.small_unbalanced()
    stats = cluster_stats(state)
    assert int(stats.num_alive_brokers) == 3
    # Broker 0 holds all leaders → max util > avg util for NW_OUT.
    r = int(Resource.NW_OUT)
    assert float(stats.utilization_max[r]) > float(stats.utilization_avg[r])
    assert float(stats.utilization_std[r]) > 0


def test_builder_padding_and_validation():
    b = ClusterModelBuilder(partition_bucket=16, broker_bucket=8)
    b.add_broker(0, "r", CAP)
    b.add_partition("t", 0, [0], leader_load=LOAD)
    state, meta = b.build()
    assert state.num_partitions == 16
    assert state.num_brokers == 8
    assert int(state.partition_mask.sum()) == 1
    assert int(state.broker_mask.sum()) == 1
    # Padded brokers contribute nothing.
    assert np.asarray(broker_load(state))[1:].sum() == 0

    bad = ClusterModelBuilder()
    bad.add_broker(0, "r", CAP)
    bad.add_partition("t", 0, [0, 0], leader_load=LOAD)
    with pytest.raises(ValueError):
        bad.build()

    bad2 = ClusterModelBuilder()
    bad2.add_broker(0, "r", CAP)
    bad2.add_partition("t", 0, [99], leader_load=LOAD)
    with pytest.raises(ValueError):
        bad2.build()


def test_random_cluster_shapes():
    state, meta = fixtures.random_cluster(num_brokers=10, num_topics=5,
                                          num_partitions=100, rf=3, seed=7)
    assert state.num_partitions == 100
    assert int(state.partition_mask.sum()) == 100
    assert np.asarray(broker_replica_counts(state)).sum() == 300
    # skewed variant concentrates load on low brokers
    skew, _ = fixtures.random_cluster(num_brokers=10, num_topics=5,
                                      num_partitions=100, rf=3, seed=7,
                                      skew_to_first=3.0)
    counts = np.asarray(broker_replica_counts(skew))
    assert counts[0] > counts[-1]


def test_random_cluster_bulk_path_invariants():
    """The vectorized LinkedIn-scale generator (>=200k partitions) must
    satisfy the same layout invariants as the per-partition path: valid
    broker-diverse replica rows, (topic, partition) row ordering, leaders
    in slot 0, and the configured placement skew."""
    state, meta = fixtures.random_cluster(
        num_brokers=500, num_topics=50, num_partitions=200_000, rf=3,
        num_racks=8, dist=fixtures.Dist.EXPONENTIAL, seed=11,
        skew_to_first=2.0, target_utilization=0.55)
    a = np.asarray(state.assignment)
    assert a.shape == (200_000, 3)
    assert (a >= 0).all() and (a < 500).all()
    srt = np.sort(a, axis=1)
    assert not (srt[:, 1:] == srt[:, :-1]).any(), "duplicate replicas"
    assert meta.partition_index == sorted(meta.partition_index)
    assert (np.asarray(state.leader_slot) == 0).all()
    counts = np.bincount(a.reshape(-1), minlength=500)
    assert counts[0] > counts[499], "skew_to_first must bias placement"
    # utilization normalization holds on the bulk path too
    from cruise_control_tpu.model.tensors import broker_load
    from cruise_control_tpu.common.resources import Resource
    load = np.asarray(broker_load(state))
    util = load[:, int(Resource.NW_OUT)].mean() / 1000.0
    assert 0.4 < util < 0.7, util


def test_host_level_rack_fallback():
    """Host topology (model/Host.java + ClusterModel.createBroker rack ==
    null ? host : rack): rackless co-hosted brokers share ONE fault
    domain, so RackAwareGoal keeps a partition's replicas host-disjoint
    (VERDICT r3 missing #4)."""
    import jax.numpy as jnp

    from cruise_control_tpu.model.builder import ClusterModelBuilder
    from cruise_control_tpu.model.fixtures import _CAP

    b = ClusterModelBuilder()
    # 6 rackless brokers on 3 hosts (2 per host).
    for i in range(6):
        b.add_broker(i, rack="", capacity=_CAP, host=f"host{i // 2}")
    b.add_partition("t", 0, [0, 2, 4], leader_index=0,
                    leader_load={})
    # Replicas 0 and 1 share host0: a host-domain violation.
    b.add_partition("t", 1, [0, 1, 4], leader_index=0, leader_load={})
    state, meta = b.build()
    assert meta.host_names == ["host0", "host1", "host2"]
    # Effective rack == host: brokers 0,1 share rack index; 2,3 share, etc.
    rack = list(map(int, state.rack))
    assert rack[0] == rack[1] and rack[2] == rack[3] and rack[4] == rack[5]
    assert len({rack[0], rack[2], rack[4]}) == 3
    host = list(map(int, state.host))
    assert host == rack[:len(host)] or host[0] == host[1]  # hosts shared

    from cruise_control_tpu.analyzer.constraint import BalancingConstraint
    from cruise_control_tpu.analyzer.derived import compute_derived
    from cruise_control_tpu.analyzer.goals import RackAwareGoal

    goal = RackAwareGoal()
    derived = compute_derived(state)
    aux = goal.prepare(state, derived, BalancingConstraint(), meta.num_topics)
    viol = goal.broker_violations(state, derived, BalancingConstraint(), aux)
    # Partition t-1 hosts replicas on both brokers of host0 -> exactly one
    # duplicated replica; t-0 is host-disjoint.
    assert float(viol.sum()) == 1.0


def test_host_aware_optimization_separates_cohosted_replicas():
    """End-to-end: with racks unset and 2 brokers/host, the optimizer must
    leave no partition with two replicas on one host (RackAwareGoal.java:229
    behavior via the host fallback)."""
    import numpy as np

    from cruise_control_tpu.analyzer.optimizer import (
        GoalOptimizer, goals_by_priority,
    )
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.model.fixtures import Dist, random_cluster

    state, meta = random_cluster(
        num_brokers=12, num_topics=4, num_partitions=96, rf=3, num_racks=0,
        brokers_per_host=2, dist=Dist.UNIFORM, seed=7, skew_to_first=2.0)
    assert len(meta.host_names) == 6
    cfg = CruiseControlConfig({"max.solver.rounds": 300})
    final, _res = GoalOptimizer(cfg).optimizations(
        state, meta, goals=goals_by_priority(cfg))
    assignment = np.asarray(final.assignment)
    host = np.asarray(final.host)
    for p in range(final.num_partitions):
        reps = assignment[p][assignment[p] >= 0]
        hosts = host[reps]
        assert len(set(hosts.tolist())) == len(reps), \
            f"partition {p} has co-hosted replicas: brokers {reps.tolist()}"
