"""ccsa invariant-linter tests: framework mechanics (suppressions,
baseline, CLI), per-rule true-positive + suppressed fixtures, and the
repo self-check (the tree must lint clean with an empty baseline —
ISSUE 9's acceptance bar)."""

from __future__ import annotations

import json
import pathlib
import subprocess
import sys
import textwrap

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from cruise_control_tpu.lint import (  # noqa: E402
    FileContext, all_rules, load_baseline, run_lint, write_baseline,
)
from cruise_control_tpu.lint.core import (  # noqa: E402
    DEFAULT_PATHS, Finding, fingerprint,
)

FIXTURES = ROOT / "tests" / "fixtures" / "ccsa"


def ctx_for(path: pathlib.Path, rel: str | None = None) -> FileContext:
    """FileContext with an optionally SPOOFED repo-relative path, so
    path-scoped rules (CCSA001 pump modules, CCSA004 deterministic
    modules) can be exercised from fixture files."""
    return FileContext(path, rel or path.name, path.read_text())


def findings_of(rule_id: str, ctx: FileContext) -> tuple[list, list]:
    """(active, suppressed) findings of one rule on one context."""
    rule = all_rules()[rule_id]
    active, suppressed = [], []
    for f in rule.check_file(ctx):
        reason = ctx.suppression_for(f.line, f.rule)
        (suppressed if reason else active).append(f)
    return active, suppressed


# ---------------------------------------------------------------------------
# Per-rule fixtures: ≥1 true positive and ≥1 suppressed case each.

def test_ccsa001_pump_host_sync_fixture():
    ctx = ctx_for(FIXTURES / "bad_host_sync.py",
                  "cruise_control_tpu/analyzer/chain.py")
    active, suppressed = findings_of("CCSA001", ctx)
    assert len(active) == 2           # float(applied) + np.asarray(ring)
    assert len(suppressed) == 1       # the annotated int(rounds)
    assert all("pump region" in f.message for f in active)


def test_ccsa001_outside_pump_modules_is_silent():
    ctx = ctx_for(FIXTURES / "bad_host_sync.py")  # fixture's own path
    active, suppressed = findings_of("CCSA001", ctx)
    assert not active and not suppressed


def test_ccsa002_donation_fixture():
    ctx = ctx_for(FIXTURES / "bad_donation.py")
    active, suppressed = findings_of("CCSA002", ctx)
    # decorator-form `rest` + the vmap-call-form `rest` (the megabatch
    # kernel shape: donation resolved THROUGH jax.vmap to the batched
    # body's parameters).
    assert len(active) == 2
    assert all("rest" in f.message for f in active)
    assert len(suppressed) == 1       # the scratch-buffer donation


def test_ccsa001_megabatch_pump_fixture():
    """Round-14 scoping: the fleet megabatch module is a pump file, its
    pump + enqueue closures are regions, suppressions still apply."""
    ctx = ctx_for(FIXTURES / "bad_megabatch_pump.py",
                  "cruise_control_tpu/fleet/megabatch.py")
    active, suppressed = findings_of("CCSA001", ctx)
    # np.asarray(rounds) + int(active.sum()) in the pump, float(budget)
    # in the module-level enqueue region.
    assert len(active) == 3
    assert len(suppressed) == 1
    # Outside the pump modules the same file is silent.
    plain = ctx_for(FIXTURES / "bad_megabatch_pump.py")
    a2, s2 = findings_of("CCSA001", plain)
    assert not a2 and not s2


def test_ccsa002_repo_donation_sites_resolve():
    """The real donated kernels (decorator form in analyzer/chain —
    including the round-14 batched megabatch twins — and the jit-call
    form wrapping shard_map bodies in parallel/chain_sharded) must
    verify CLEAN — donation exactly {assignment, leader_slot}."""
    for rel in ("cruise_control_tpu/analyzer/chain.py",
                "cruise_control_tpu/analyzer/direct.py",
                "cruise_control_tpu/parallel/chain_sharded.py",
                "cruise_control_tpu/fleet/megabatch.py"):
        ctx = ctx_for(ROOT / rel, rel)
        active, suppressed = findings_of("CCSA002", ctx)
        assert not active, [f.message for f in active]
        assert not suppressed


def test_ccsa001_direct_kernel_fixture():
    """Round-17 scoping: analyzer/direct.py is a pump file — its donated
    transport kernels are regions (structural donate_argnums detection),
    host syncs inside them fire, suppressions apply, and the file is
    silent under a non-pump path."""
    ctx = ctx_for(FIXTURES / "bad_direct.py",
                  "cruise_control_tpu/analyzer/direct.py")
    active, suppressed = findings_of("CCSA001", ctx)
    assert len(active) == 2           # float(plan) + plan.tolist()
    assert len(suppressed) == 1       # the annotated int(plan)
    plain = ctx_for(FIXTURES / "bad_direct.py")
    a2, s2 = findings_of("CCSA001", plain)
    assert not a2 and not s2


def test_ccsa002_direct_fixture():
    """Decorator form (round 17) AND the round-21 mesh traced-driver
    form: donation through ``jax.jit(shard_map(body, ...))`` resolves
    the argnums to the body's same-position parameters, so donating the
    topology `rest` fires in both shapes and the strip_mutable pair
    stays clean."""
    ctx = ctx_for(FIXTURES / "bad_direct.py")
    active, _suppressed = findings_of("CCSA002", ctx)
    assert len(active) == 2
    assert all("rest" in f.message for f in active)


def test_ccsa004_direct_rounding_fixture():
    """Round-21 scoping: analyzer/direct.py is a deterministic module —
    the rounding PRNG must be crc32-seeded derivation only, so a global
    `random` draw fires under the spoofed path, the documented
    suppression holds, the crc32 helper stays clean, and the fixture is
    silent under its own (non-deterministic-module) path."""
    spoofed = ctx_for(FIXTURES / "bad_direct.py",
                      "cruise_control_tpu/analyzer/direct.py")
    active, suppressed = findings_of("CCSA004", spoofed)
    assert len(active) == 1           # random.random() in rounding_seed_bad
    assert "random.random" in active[0].message
    assert len(suppressed) == 1       # the annotated random.uniform
    plain = ctx_for(FIXTURES / "bad_direct.py")
    a2, s2 = findings_of("CCSA004", plain)
    assert not a2 and not s2


def test_ccsa004_real_direct_module_contract():
    """The real kernel module carries the replan determinism contract:
    no active CCSA004 findings, and exactly the two documented
    flight-telemetry clock suppressions in the host driver."""
    rel = "cruise_control_tpu/analyzer/direct.py"
    ctx = ctx_for(ROOT / rel, rel)
    active, suppressed = findings_of("CCSA004", ctx)
    assert not active, [f.message for f in active]
    assert len(suppressed) == 2


def test_ccsa001_real_direct_module_clean():
    """The real direct.py must lint clean: its donated kernels are pure
    traced code, and the synchronous readback lives in run_direct_pass
    (a plain host driver, not a region)."""
    rel = "cruise_control_tpu/analyzer/direct.py"
    ctx = ctx_for(ROOT / rel, rel)
    active, suppressed = findings_of("CCSA001", ctx)
    assert not active, [f.message for f in active]
    assert not suppressed


def test_ccsa003_trace_mutation_fixture():
    ctx = ctx_for(FIXTURES / "bad_trace_mutation.py")
    active, suppressed = findings_of("CCSA003", ctx)
    assert len(active) == 2           # while_loop append + scan subscript
    assert len(suppressed) == 1
    assert all("trace time" in f.message for f in active)


def test_ccsa004_determinism_fixture():
    spoofed = ctx_for(FIXTURES / "bad_determinism.py",
                      "cruise_control_tpu/testing/simulator.py")
    active, suppressed = findings_of("CCSA004", spoofed)
    # hash(topic) + time.time(); the injected-clock default and __hash__
    # stay clean; hash(parts) is suppressed.
    assert len(active) == 2
    assert len(suppressed) == 1
    kinds = {f.message.split("`")[1] for f in active}
    assert kinds == {"hash()", "time.time"} or len(kinds) == 2


def test_ccsa004_covers_futures_modules():
    """The round-15 futures engine sits under the same byte-identical
    determinism contract as the twin: wall-clock and global-random
    calls are findings under the futures paths, the injected-clock
    reference and the documented observability suppression stay legal —
    and the REAL modules verify clean."""
    spoofed = ctx_for(FIXTURES / "bad_futures_generator.py",
                      "cruise_control_tpu/futures/generator.py")
    active, suppressed = findings_of("CCSA004", spoofed)
    assert len(active) == 2           # time.time() + random.random()
    assert len(suppressed) == 1       # the documented perf_counter probe
    for rel in ("cruise_control_tpu/futures/generator.py",
                "cruise_control_tpu/futures/evaluator.py"):
        ctx = ctx_for(ROOT / rel, rel)
        real_active, _sup = findings_of("CCSA004", ctx)
        assert not real_active, [f.message for f in real_active]


def test_ccsa_covers_heal_ledger_module():
    """The round-16 heal ledger is a deterministic module (CCSA004: its
    phase stamps come from the injectable clock seam) whose chain ring
    must mutate under the lock (CCSA007) — the fixture exercises both
    under the spoofed ledger path, and the REAL module verifies clean."""
    spoofed = ctx_for(FIXTURES / "bad_heal_ledger.py",
                      "cruise_control_tpu/utils/heal_ledger.py")
    active, suppressed = findings_of("CCSA004", spoofed)
    assert len(active) == 1           # inline time.time()
    assert len(suppressed) == 1       # documented perf_counter probe
    assert "time.time" in active[0].message
    lock_active, lock_suppressed = findings_of("CCSA007", spoofed)
    assert len(lock_active) == 1      # unlocked _CHAINS.append
    assert len(lock_suppressed) == 1  # documented single-writer append
    assert "_CHAINS" in lock_active[0].message
    rel = "cruise_control_tpu/utils/heal_ledger.py"
    real = ctx_for(ROOT / rel, rel)
    for rule in ("CCSA004", "CCSA007"):
        real_active, _sup = findings_of(rule, real)
        assert not real_active, [f.message for f in real_active]


def test_ccsa_covers_warmstart_module():
    """The round-18 warmstart module is a deterministic module (CCSA004:
    seed validity/fallback are pure functions of model state; the
    prewarm manager's duration rides the injectable monotonic seam) and
    its module-level prewarm-manager registry must mutate under
    _REGISTRY_LOCK (CCSA007) — fixture true-positive + suppressed pairs
    under the spoofed path, and the REAL module verifies clean."""
    spoofed = ctx_for(FIXTURES / "bad_warmstart.py",
                      "cruise_control_tpu/warmstart.py")
    active, suppressed = findings_of("CCSA004", spoofed)
    assert len(active) == 1           # inline time.monotonic()
    assert len(suppressed) == 1       # documented perf_counter sweep
    assert "time.monotonic" in active[0].message
    lock_active, lock_suppressed = findings_of("CCSA007", spoofed)
    assert len(lock_active) == 1      # unlocked _MANAGERS write
    assert len(lock_suppressed) == 1  # documented single-writer write
    assert "_MANAGERS" in lock_active[0].message
    rel = "cruise_control_tpu/warmstart.py"
    real = ctx_for(ROOT / rel, rel)
    for rule in ("CCSA004", "CCSA007"):
        real_active, _sup = findings_of(rule, real)
        assert not real_active, [f.message for f in real_active]


def test_ccsa_covers_forecast_modules():
    """The round-19 forecast subsystem feeds SOLVER INPUTS and anomaly
    decisions, so it sits under CCSA004's deterministic contract: wall
    clock and global randomness are findings under the forecast paths,
    the injected-clock reference and the documented observability
    suppression stay legal — and the REAL modules verify clean."""
    spoofed = ctx_for(FIXTURES / "bad_forecast.py",
                      "cruise_control_tpu/forecast/forecaster.py")
    active, suppressed = findings_of("CCSA004", spoofed)
    assert len(active) == 2           # time.time() + random.random()
    assert len(suppressed) == 1       # the documented perf_counter probe
    assert any("time.time" in f.message for f in active)
    assert any("random.random" in f.message for f in active)
    for rel in ("cruise_control_tpu/forecast/forecaster.py",
                "cruise_control_tpu/forecast/engine.py",
                "cruise_control_tpu/detector/predictive.py"):
        ctx = ctx_for(ROOT / rel, rel)
        real_active, _sup = findings_of("CCSA004", ctx)
        assert not real_active, [f.message for f in real_active]


def test_ccsa_covers_serving_modules():
    """The round-20 serving front door sits under CCSA004's deterministic
    contract: the loadgen schedule is a pure function of the seed (its
    digest is pinned in bench_baseline.json) and the engine/cache/
    admission layers time themselves through injected ``monotonic``
    seams only — wall clock and global randomness are findings under the
    serving paths, the injected-seam reference and the documented
    observability suppression stay legal, and the REAL modules verify
    clean."""
    spoofed = ctx_for(FIXTURES / "bad_serving_loadgen.py",
                      "cruise_control_tpu/serving/loadgen.py")
    active, suppressed = findings_of("CCSA004", spoofed)
    assert len(active) == 2           # time.time() + random.random()
    assert len(suppressed) == 1       # the documented perf_counter probe
    assert any("time.time" in f.message for f in active)
    assert any("random.random" in f.message for f in active)
    for rel in ("cruise_control_tpu/serving/tasks.py",
                "cruise_control_tpu/serving/cache.py",
                "cruise_control_tpu/serving/admission.py",
                "cruise_control_tpu/serving/loadgen.py"):
        ctx = ctx_for(ROOT / rel, rel)
        real_active, _sup = findings_of("CCSA004", ctx)
        assert not real_active, [f.message for f in real_active]


def test_ccsa_covers_redteam_modules():
    """The round-22 red-team miner sits under CCSA004's deterministic
    contract: the whole search — sampling, mutation, tie-breaks,
    frontier order — is crc32-derived from the sweep seed (the committed
    frontier JSON is byte-identical per seed) and the wall budget rides
    the caller-injected ``clock`` callable only. Wall clock and global
    randomness are findings under the redteam paths, the injected-clock
    reference and the documented observability suppression stay legal,
    and the REAL modules verify clean."""
    spoofed = ctx_for(FIXTURES / "bad_redteam.py",
                      "cruise_control_tpu/redteam/miner.py")
    active, suppressed = findings_of("CCSA004", spoofed)
    assert len(active) == 2           # time.time() + random.random()
    assert len(suppressed) == 1       # the documented perf_counter probe
    assert any("time.time" in f.message for f in active)
    assert any("random.random" in f.message for f in active)
    for rel in ("cruise_control_tpu/redteam/miner.py",
                "cruise_control_tpu/redteam/frontier.py",
                "cruise_control_tpu/redteam/blindspot.py"):
        ctx = ctx_for(ROOT / rel, rel)
        real_active, _sup = findings_of("CCSA004", ctx)
        assert not real_active, [f.message for f in real_active]


def test_ccsa004_hash_ban_is_repo_wide_but_clock_is_not():
    plain = ctx_for(FIXTURES / "bad_determinism.py")
    active, suppressed = findings_of("CCSA004", plain)
    assert len(active) == 1           # hash() still flagged
    assert "hash()" in active[0].message
    assert len(suppressed) == 1


def test_ccsa005_undeclared_key_fixture():
    ctx = ctx_for(FIXTURES / "bad_config_key.py")
    active, suppressed = findings_of("CCSA005", ctx)
    assert {f.message.split("`")[1] for f in active} \
        == {"totally.unknown.key", "another.unknown.key"}
    assert len(suppressed) == 1


def test_ccsa007_lock_discipline_fixture():
    ctx = ctx_for(FIXTURES / "bad_lock.py")
    active, suppressed = findings_of("CCSA007", ctx)
    assert len(active) == 2           # put() and drop()
    assert len(suppressed) == 1       # mark()
    assert all("_CACHE" in f.message for f in active)


def test_ccsa006_sensor_drift_detected(tmp_path):
    """A registered-but-undocumented sensor fails CCSA006 in a synthetic
    mini-repo (the real tree's docs are verified by the self-check)."""
    (tmp_path / "tools").mkdir()
    (tmp_path / "tools" / "gen_docs.py").write_text(
        (ROOT / "tools" / "gen_docs.py").read_text())
    pkg = tmp_path / "cruise_control_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        'SENSORS.count("fixture_only_sensor")\n')
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "SENSORS.md").write_text("# Sensors\n")
    rule = all_rules()["CCSA006"]
    findings = rule.check_tree(tmp_path, [])
    assert any("fixture_only_sensor" in f.message for f in findings)


def test_ccsa005_doc_staleness_detected(tmp_path):
    """A CONFIGURATION.md that does not match the live registry fails
    the CCSA005 tree check."""
    (tmp_path / "tools").mkdir()
    (tmp_path / "tools" / "gen_docs.py").write_text(
        (ROOT / "tools" / "gen_docs.py").read_text())
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "CONFIGURATION.md").write_text("# stale\n")
    rule = all_rules()["CCSA005"]
    findings = rule.check_tree(tmp_path, [])
    assert findings and "stale" in findings[0].message


# ---------------------------------------------------------------------------
# Framework mechanics.

def test_suppression_requires_reason(tmp_path):
    f = tmp_path / "frag.py"
    f.write_text(textwrap.dedent("""\
        def unstable(topic):
            return hash(topic)  # ccsa: ok[CCSA004]
    """))
    result = run_lint([f], root=tmp_path, rules=["CCSA004"])
    assert result.failed
    assert any(x.rule == "CCSA000" and "no reason" in x.message
               for x in result.errors)


def test_suppression_comment_block_above(tmp_path):
    f = tmp_path / "frag.py"
    f.write_text(textwrap.dedent("""\
        def unstable(topic):
            # ccsa: ok[CCSA004] memo key that never leaves
            # this process (wrapped reason line)
            return hash(topic)
    """))
    result = run_lint([f], root=tmp_path, rules=["CCSA004"])
    assert not result.failed
    assert len(result.suppressed) == 1
    assert "memo key" in result.suppressed[0].reason


def test_multi_rule_suppression(tmp_path):
    f = tmp_path / "frag.py"
    f.write_text(textwrap.dedent("""\
        _REG: dict = {}


        def put(topic):
            # ccsa: ok[CCSA004,CCSA007] fixture: one comment, two rules
            _REG[hash(topic)] = topic
    """))
    result = run_lint([f], root=tmp_path, rules=["CCSA004", "CCSA007"])
    assert not result.failed
    assert len(result.suppressed) == 2


def test_nested_rebinding_does_not_shadow_outer_scope(tmp_path):
    """A nested closure rebinding a module container's name must not
    hide the OUTER function's unlocked mutation (CCSA007), and a nested
    def rebinding a free name must not hide a lax-body mutation
    (CCSA003) — Python scoping: inner bindings don't leak out."""
    f = tmp_path / "frag.py"
    f.write_text(textwrap.dedent("""\
        import jax

        _CACHE: dict = {}


        def outer(k, v):
            def helper():
                _CACHE = {}
                return _CACHE
            _CACHE[k] = v
            return helper


        def loop(x):
            log = []

            def body(c):
                def rebind():
                    log = []
                    return log
                log.append(c)
                return c + 1, rebind

            def cond(c):
                return c < 3

            return jax.lax.while_loop(cond, body, x), log
    """))
    result = run_lint([f], root=tmp_path, rules=["CCSA007", "CCSA003"])
    assert {(x.rule, "log.append" in x.message or "_CACHE" in x.message)
            for x in result.new} == {("CCSA007", True), ("CCSA003", True)}


def test_nested_region_violation_reported_once():
    """A host-sync inside an `enqueue` closure nested in
    `run_bounded_pass` is one violation, not two (nested regions are
    walked in their own right only)."""
    src = textwrap.dedent("""\
        def run_bounded_pass(st, cap):
            def enqueue(st, budget):
                return int(budget_future)
            return enqueue(st, cap)
    """)
    import cruise_control_tpu.lint.core as core
    ctx = core.FileContext(pathlib.Path("x.py"),
                           "cruise_control_tpu/analyzer/chain.py", src)
    findings = all_rules()["CCSA001"].check_file(ctx)
    assert len(findings) == 1
    assert "enqueue" in findings[0].message


def test_nonexistent_path_fails_the_gate(tmp_path):
    """A typo'd path must not pass vacuously with 0 files scanned."""
    result = run_lint([tmp_path / "no_such_dir"], root=tmp_path,
                      rules=["CCSA004"])
    assert result.failed
    assert any("matched no Python files" in x.message
               for x in result.errors)
    proc = _run_cli("no/such/path.py")
    assert proc.returncode == 1


def test_scoped_write_baseline_keeps_out_of_scope_fingerprints(tmp_path):
    """--write-baseline with explicit paths unions the prior baseline:
    out-of-scope acceptances survive a scoped rewrite."""
    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    a.write_text("def f(t):\n    return hash(t)\n")
    b.write_text("def g(t):\n    return hash(t + 'x')\n")
    base = tmp_path / "base.json"
    proc = _run_cli(str(a), str(b), "--rules", "CCSA004",
                    "--root", str(tmp_path),
                    "--baseline", str(base), "--write-baseline")
    assert proc.returncode == 0, proc.stderr
    full = load_baseline(base)
    assert len(full) == 2
    proc = _run_cli(str(a), "--rules", "CCSA004", "--root", str(tmp_path),
                    "--baseline", str(base), "--write-baseline")
    assert proc.returncode == 0, proc.stderr
    assert load_baseline(base) == full      # b.py's acceptance survived


def test_broken_pipe_preserves_failing_verdict():
    """`ccsa | head -c 1` on a failing tree must still exit non-zero."""
    proc = subprocess.run(
        f"{sys.executable} -m tools.ccsa tests/fixtures/ccsa "
        "--rules CCSA007 | head -c 1; exit ${PIPESTATUS[0]}",
        shell=True, executable="/bin/bash", cwd=ROOT,
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 1, proc.stderr


def test_ccsa007_sees_through_module_level_blocks(tmp_path):
    """Functions (and container declarations) nested under module-level
    if/try blocks are scanned — tree.body-only walking would fail open
    on e.g. the `try: shard_map = ...` pattern in parallel/mesh.py."""
    f = tmp_path / "frag.py"
    f.write_text(textwrap.dedent("""\
        _CACHE: dict = {}

        if True:
            try:
                _AUX: list = []
            except ImportError:
                pass

            def put(k, v):
                _CACHE[k] = v

            def aux(v):
                _AUX.append(v)
    """))
    result = run_lint([f], root=tmp_path, rules=["CCSA007"])
    assert {m.message.split("`")[1] for m in result.new} \
        == {"_CACHE", "_AUX"}


def test_ccsa007_lock_does_not_cover_nested_closure(tmp_path):
    """A closure DEFINED inside `with lock:` executes later, unlocked —
    the guard must not carry into the nested scope."""
    f = tmp_path / "frag.py"
    f.write_text(textwrap.dedent("""\
        import threading

        _CACHE: dict = {}
        _LOCK = threading.Lock()


        def outer():
            with _LOCK:
                _CACHE["init"] = 1          # genuinely guarded

                def cb(k, v):
                    _CACHE[k] = v           # runs after release
            return cb
    """))
    result = run_lint([f], root=tmp_path, rules=["CCSA007"])
    assert len(result.new) == 1
    assert result.new[0].line == 12


def test_rules_filter_tolerates_spaces():
    proc = _run_cli("tests/fixtures/ccsa/bad_lock.py",
                    "--rules", "CCSA004, CCSA007", "--format", "json")
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert {f["rule"] for f in payload["findings"]} == {"CCSA007"}


def test_suppression_marker_in_string_is_inert(tmp_path):
    """A `# ccsa: ok[...]` inside a string literal or docstring is data,
    not a comment: it must neither suppress a finding on its line nor
    appear in the suppression registry."""
    f = tmp_path / "frag.py"
    f.write_text(textwrap.dedent('''\
        def unstable(t):
            """Docs may QUOTE the syntax: # ccsa: ok[CCSA004] example."""
            return hash(t + " # ccsa: ok[CCSA004] smuggled reason")
    '''))
    result = run_lint([f], root=tmp_path, rules=["CCSA004"])
    assert result.failed and len(result.new) == 1
    assert not result.suppressed
    ctx = ctx_for(f, "frag.py")
    assert not ctx.suppressions


def test_stacked_single_rule_suppressions(tmp_path):
    """Two adjacent single-rule markers above one line both apply — a
    non-matching marker must not end the upward walk."""
    f = tmp_path / "frag.py"
    f.write_text(textwrap.dedent("""\
        _REG: dict = {}


        def put(topic):
            # ccsa: ok[CCSA004] reason for the hash
            # ccsa: ok[CCSA007] reason for the unlocked write
            _REG[hash(topic)] = topic
    """))
    result = run_lint([f], root=tmp_path, rules=["CCSA004", "CCSA007"])
    assert not result.failed, [x.message for x in result.new]
    assert len(result.suppressed) == 2


def test_write_baseline_keeps_prior_acceptances(tmp_path):
    """--write-baseline must union still-present baselined findings with
    the new ones — rewriting can never un-accept a prior acceptance."""
    f = tmp_path / "frag.py"
    f.write_text("def a(t):\n    return hash(t)\n")
    base = tmp_path / "base.json"
    proc = _run_cli(str(f), "--rules", "CCSA004", "--root", str(tmp_path),
                    "--baseline", str(base), "--write-baseline")
    assert proc.returncode == 0, proc.stderr
    first = load_baseline(base)
    assert len(first) == 1
    # A second finding appears; rewriting keeps the first fingerprint.
    f.write_text("def a(t):\n    return hash(t)\n"
                 "def b(t):\n    return hash(t + 'x')\n")
    proc = _run_cli(str(f), "--rules", "CCSA004", "--root", str(tmp_path),
                    "--baseline", str(base), "--write-baseline")
    assert proc.returncode == 0, proc.stderr
    assert first <= load_baseline(base)
    proc = _run_cli(str(f), "--rules", "CCSA004", "--root", str(tmp_path),
                    "--baseline", str(base))
    assert proc.returncode == 0, proc.stdout


def test_baseline_accepts_then_clears(tmp_path):
    f = tmp_path / "frag.py"
    f.write_text("def unstable(topic):\n    return hash(topic)\n")
    result = run_lint([f], root=tmp_path, rules=["CCSA004"])
    assert result.failed and len(result.new) == 1
    ctx = ctx_for(f, "frag.py")
    finding = result.new[0]
    baseline_path = tmp_path / "base.json"
    write_baseline(baseline_path,
                   [fingerprint(finding, ctx.line_text(finding.line))])
    result2 = run_lint([f], root=tmp_path, rules=["CCSA004"],
                       baseline=load_baseline(baseline_path))
    assert not result2.failed
    assert len(result2.baselined) == 1


def test_fingerprint_survives_line_moves():
    f = Finding("CCSA004", "a.py", 10, "m")
    moved = Finding("CCSA004", "a.py", 99, "m")
    assert fingerprint(f, "return hash(x)") \
        == fingerprint(moved, "  return   hash(x)")


def test_unknown_rule_filter_fails():
    result = run_lint([FIXTURES / "bad_lock.py"], root=ROOT,
                      rules=["CCSA999"])
    assert result.failed
    assert any("unknown rule" in f.message for f in result.errors)


def test_syntax_error_is_meta_finding(tmp_path):
    f = tmp_path / "broken.py"
    f.write_text("def nope(:\n")
    result = run_lint([f], root=tmp_path, rules=["CCSA007"])
    assert result.failed
    assert any(x.rule == "CCSA000" and "syntax error" in x.message
               for x in result.errors)


# ---------------------------------------------------------------------------
# CLI + gate behavior.

def _run_cli(*args: str):
    return subprocess.run(
        [sys.executable, "-m", "tools.ccsa", *args],
        cwd=ROOT, capture_output=True, text=True, timeout=300)


def test_cli_red_on_seeded_violations():
    """The CI red-gate contract: linting the fixture corpus with the
    path-independent rules MUST exit non-zero."""
    proc = _run_cli("tests/fixtures/ccsa",
                    "--rules", "CCSA002,CCSA003,CCSA004,CCSA007",
                    "--format", "json")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    flagged = {f["rule"] for f in payload["findings"]}
    assert {"CCSA002", "CCSA003", "CCSA004", "CCSA007"} <= flagged


def test_cli_self_check_repo_tree_is_clean():
    """`python -m tools.ccsa` on the default tree exits 0 with the
    committed (EMPTY) baseline — the acceptance criterion."""
    proc = _run_cli("--format", "json")
    assert proc.returncode == 0, proc.stdout[-4000:] + proc.stderr[-2000:]
    payload = json.loads(proc.stdout)
    assert not payload["failed"]
    assert payload["files_scanned"] > 100
    # Bias check: the committed baseline is empty — nothing grandfathered.
    assert not any(f["baselined"] for f in payload["findings"])
    assert load_baseline(ROOT / ".ccsa-baseline.json") == set()


def test_cli_list_rules_names_all_seven():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    listed = {line.split()[0] for line in proc.stdout.splitlines() if line}
    assert {"CCSA001", "CCSA002", "CCSA003", "CCSA004", "CCSA005",
            "CCSA006", "CCSA007"} <= listed


def test_cli_list_suppressions_reports_tolerances():
    proc = _run_cli("--list-suppressions")
    assert proc.returncode == 0
    # The PR 5 persistent-controller tolerance is machine-readable now.
    assert "optimizer.py" in proc.stdout
    assert "CCSA007" in proc.stdout


def test_default_scan_skips_fixture_corpus():
    result = run_lint(DEFAULT_PATHS, root=ROOT,
                      rules=["CCSA004", "CCSA007"])
    assert not any(f.path.startswith("tests/fixtures/ccsa")
                   for f in result.new + result.suppressed)


@pytest.mark.parametrize("rel", [
    "cruise_control_tpu/testing/simulator.py",
    "cruise_control_tpu/testing/chaos.py",
    "cruise_control_tpu/utils/flight_recorder.py",
    "cruise_control_tpu/forecast/forecaster.py",
    "cruise_control_tpu/forecast/engine.py",
    "cruise_control_tpu/detector/predictive.py",
])
def test_deterministic_modules_lint_clean(rel):
    """The twin/chaos/flight-recorder modules carry no ACTIVE wall-clock
    or hash findings — every remaining site is an annotated tolerance."""
    ctx = ctx_for(ROOT / rel, rel)
    active, _suppressed = findings_of("CCSA004", ctx)
    assert not active, [f"{f.line}: {f.message}" for f in active]
