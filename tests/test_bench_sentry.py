"""Bench regression sentry (bench.py + bench_baseline.json): the
deliberate-fixture verification the acceptance bar demands — a stage
record regressed the way the two reverted TopicReplica fixes regressed
(balancedness canary flip, new violated goal) MUST fail the comparison;
perf drift inside the tolerance band must only warn."""

import copy
import json
import os
import pathlib

# bench.py redirects fd 2 at import time unless told not to — a test
# import must never steal pytest's stderr.
os.environ["BENCH_KEEP_STDERR"] = "1"

import bench  # noqa: E402

ROOT = pathlib.Path(__file__).resolve().parent.parent

BASELINE = {
    "tolerance": {"balancedness_abs": 0.05, "wall_clock_ratio": 3.0,
                  "dispatch_ratio": 1.5},
    "stages": {
        "rebalance_proposal_wall_clock_16brokers_512partitions": {
            "balancedness_after": 86.0,
            "violated_goals_after": ["PotentialNwOutGoal"],
            "solve_wall_clock_s": 0.2,
            "dispatch_count": 4,
        }
    },
}

RECORD = {
    "metric": "rebalance_proposal_wall_clock_16brokers_512partitions",
    "value": 0.2, "unit": "s", "vs_baseline": 1.0,
    "extras": {
        "balancedness_after": 86.0,
        "violated_goals_after": ["PotentialNwOutGoal"],
        "solve_wall_clock_s": 0.2,
        "dispatch_count": 4,
    },
}


def _verdict(mutate):
    record = copy.deepcopy(RECORD)
    mutate(record["extras"])
    return bench.compare_stage_to_baseline(record, BASELINE)


def test_clean_stage_passes():
    v = _verdict(lambda ex: None)
    assert v["extras"]["status"] == "ok"
    assert v["value"] == 1.0 and not v["extras"]["canaries"]


def test_balancedness_canary_fails():
    # The exact historical regression: 86.0 -> 82.74 must FAIL.
    v = _verdict(lambda ex: ex.update(balancedness_after=82.74))
    assert v["extras"]["status"] == "fail"
    assert v["value"] == 0.0
    assert any("balancedness" in c for c in v["extras"]["canaries"])


def test_balancedness_within_tolerance_ok():
    v = _verdict(lambda ex: ex.update(balancedness_after=85.96))
    assert v["extras"]["status"] == "ok"


def test_new_violated_goal_fails():
    v = _verdict(lambda ex: ex.update(violated_goals_after=[
        "PotentialNwOutGoal", "CpuUsageDistributionGoal"]))
    assert v["extras"]["status"] == "fail"
    assert any("CpuUsageDistributionGoal" in c
               for c in v["extras"]["canaries"])


def test_goal_leaving_violated_set_warns_only():
    # An IMPROVEMENT must not fail — but must be flagged so the baseline
    # gets re-pinned instead of silently drifting.
    v = _verdict(lambda ex: ex.update(violated_goals_after=[]))
    assert v["extras"]["status"] == "warn"
    assert not v["extras"]["canaries"]
    assert any("re-pin" in w for w in v["extras"]["warnings"])


def test_wall_clock_and_dispatch_drift_warn_only():
    v = _verdict(lambda ex: ex.update(solve_wall_clock_s=10.0,
                                      dispatch_count=40))
    assert v["extras"]["status"] == "warn"
    assert v["value"] == 1.0
    assert len(v["extras"]["warnings"]) == 2


def test_ranked_order_flip_is_a_hard_canary():
    """Round 15: the futures stage pins WHICH future wins. A rank flip
    against the baseline fails hard; matching order (or a stage/baseline
    without one) stays clean."""
    baseline = copy.deepcopy(BASELINE)
    stage = baseline["stages"][RECORD["metric"]]
    stage["ranked_order"] = ["a:1", "b:1", "c:1"]
    record = copy.deepcopy(RECORD)
    record["extras"]["ranked_order"] = ["a:1", "b:1", "c:1"]
    v = bench.compare_stage_to_baseline(record, baseline)
    assert v["extras"]["status"] == "ok"
    record["extras"]["ranked_order"] = ["b:1", "a:1", "c:1"]
    v = bench.compare_stage_to_baseline(record, baseline)
    assert v["extras"]["status"] == "fail"
    assert any("ranked order" in c for c in v["extras"]["canaries"])
    # No baseline order recorded -> the canary does not apply.
    v = _verdict(lambda ex: ex.update(ranked_order=["x:1"]))
    assert v["extras"]["status"] == "ok"


def test_unknown_stage_and_missing_baseline():
    record = copy.deepcopy(RECORD)
    record["metric"] = "rebalance_proposal_wall_clock_unpinned_stage"
    assert bench.compare_stage_to_baseline(record, BASELINE) is None
    assert bench.load_baseline("/nonexistent/baseline.json") is None


def test_committed_baseline_is_valid():
    """The checked-in bench_baseline.json parses and covers the two
    BENCH_SCALE=small stages CI actually runs."""
    baseline = json.loads((ROOT / "bench_baseline.json").read_text())
    stages = baseline["stages"]
    for b, p, drain in bench.STAGES[:2]:
        name = f"rebalance_proposal_wall_clock_{b}brokers_" \
            + (f"{p // 1000}kpartitions" if p >= 1000 else f"{p}partitions")
        assert name in stages, f"baseline missing CI stage {name}"
        entry = stages[name]
        assert isinstance(entry["balancedness_after"], float)
        assert isinstance(entry["violated_goals_after"], list)
    tol = baseline["tolerance"]
    assert tol["balancedness_abs"] > 0 and tol["wall_clock_ratio"] > 1


def test_flight_recorder_noop_overhead_probe():
    """The bench guard the acceptance bar names: the probe runs and the
    disabled-path cost stays ns-scale (generous CI bound — the guard's
    job is catching an accidental O(work) disabled path, not ns drift)."""
    ns = bench._flight_recorder_noop_overhead_ns(iterations=2000)
    assert 0 < ns < 100_000


def test_sentry_summary_statuses():
    rec = copy.deepcopy(RECORD)
    ok = bench.compare_stage_to_baseline(rec, BASELINE)
    emitted = []
    orig = bench._emit
    bench._emit = emitted.append
    try:
        bench._emit_sentry_summary([ok], BASELINE)
        rec2 = copy.deepcopy(RECORD)
        rec2["extras"]["balancedness_after"] = 1.0
        bad = bench.compare_stage_to_baseline(rec2, BASELINE)
        bench._emit_sentry_summary([ok, bad], BASELINE)
        bench._emit_sentry_summary([], None)
    finally:
        bench._emit = orig
    assert emitted[0]["extras"]["status"] == "ok"
    assert emitted[1]["extras"]["status"] == "fail"
    assert emitted[1]["value"] == 0.0
    assert emitted[2]["extras"]["status"] == "no_baseline"


def test_sentry_summary_incomplete_when_baselined_stage_missing():
    """A baselined stage that never produced a verdict (timed out /
    crashed / budget-skipped) must surface as 'incomplete' — a regression
    severe enough to also break its stage must not pass by breaking it."""
    rec = copy.deepcopy(RECORD)
    ok = bench.compare_stage_to_baseline(rec, BASELINE)
    two_stage = copy.deepcopy(BASELINE)
    two_stage["stages"]["rebalance_proposal_wall_clock_50brokers_2kpartitions"] = \
        dict(two_stage["stages"][RECORD["metric"]])
    emitted = []
    orig = bench._emit
    bench._emit = emitted.append
    try:
        bench._emit_sentry_summary([ok], two_stage)
    finally:
        bench._emit = orig
    ex = emitted[0]["extras"]
    assert ex["status"] == "incomplete"
    assert emitted[0]["value"] == 0.0
    assert ex["stages_missing"] == [
        "rebalance_proposal_wall_clock_50brokers_2kpartitions"]
