"""Solver flight recorder (round 12): ring decode, kill attribution,
pass ring/filters, the byte-identical trajectory parity contract at two
padded bucket shapes, the GET /solver surface, and the on-demand
profiling gate.

The parity tests ARE the acceptance bar: recording adds reductions over
tensors the round body already computes — never a new selection input —
so the solver trajectory must be byte-identical with recording on or
off, per shape, on the bounded megastep path that carries the on-device
per-round ring."""

import threading

import numpy as np
import pytest

from cruise_control_tpu.utils.flight_recorder import (
    FLIGHT, NO_FLIGHT, STAT_COLUMNS, FlightRecorder, decode_ring,
    summarize_passes,
)


@pytest.fixture(autouse=True)
def _restore_flight():
    yield
    FLIGHT.configure(enabled=True, max_passes=64, ring_rounds=128)
    FLIGHT.clear()


# ---- ring decode ---------------------------------------------------------

def test_decode_ring_no_wrap():
    ring = np.arange(12, dtype=np.float32).reshape(4, 3)
    rows = decode_ring(ring, 2)
    assert rows == [[0.0, 1.0, 2.0], [3.0, 4.0, 5.0]]
    assert decode_ring(ring, 0) == []


def test_decode_ring_wraps_oldest_first():
    # 6 rounds into a 4-slot ring: rounds 2..5 survive, oldest at
    # slot 6 % 4 = 2.
    ring = np.zeros((4, 1), dtype=np.float32)
    for r in range(6):
        ring[r % 4, 0] = r
    rows = decode_ring(ring, 6)
    assert [r[0] for r in rows] == [2.0, 3.0, 4.0, 5.0]


# ---- goal records --------------------------------------------------------

def _fake_ring(rows):
    """rows: list of (applied, valid, accepted, positive, winners, viol)."""
    return np.asarray(rows, dtype=np.float32)


def test_kill_attribution_and_trajectory():
    rec = FlightRecorder()
    with rec.pass_scope(seq=1) as p:
        g = p.goal("TopicReplicaDistributionGoal")
        g.grid(64, 16, 32)
        g.entry(violation=40.0)
        g.dispatch("move", budget=8, rounds=2, applied=3, ring=_fake_ring([
            (2, 100, 60, 30, 10, 38.0),
            (1, 90, 50, 20, 5, 37.0)]))
        g.exit(violation=37.0)
    (pd,) = rec.passes()
    (gd,) = pd["goals"]
    ka = gd["killAttribution"]
    assert ka["rounds"] == 2
    assert ka["validCards"] == 190
    assert ka["killedByPriorVeto"] == 190 - 110        # valid - accepted
    assert ka["killedByNonPositive"] == 110 - 50       # accepted - positive
    assert ka["killedByPerSourceReduce"] == 50 - 15    # positive - winners
    assert ka["killedByDedupRecheck"] == 15 - 3        # winners - applied
    assert ka["applied"] == 3
    assert gd["violationTrajectory"] == [38.0, 37.0]
    # density = applied / rounds / selection_width (= max(moves, sources))
    assert gd["acceptanceDensity"] == pytest.approx(3 / 2 / 64, abs=1e-6)
    assert gd["violationBefore"] == 40.0
    assert gd["violationAfter"] == 37.0
    rows = gd["dispatches"][0]["rounds_log"]
    assert list(rows[0]) == list(STAT_COLUMNS)


def test_speculative_dispatches_excluded_from_density():
    rec = FlightRecorder()
    with rec.pass_scope(seq=1) as p:
        g = p.goal("g")
        g.grid(8, 8, 8)
        g.dispatch("move", budget=4, rounds=4, applied=8)
        g.dispatch("move", budget=4, rounds=4, applied=0, speculative=True)
    (pd,) = rec.passes()
    (gd,) = pd["goals"]
    assert gd["rounds"] == 4 and gd["movesApplied"] == 8
    assert gd["dispatchCount"] == 2
    assert gd["acceptanceDensity"] == pytest.approx(8 / 4 / 8)


def test_gridless_goal_summaries_report_no_density():
    """Fused/sharded-unbounded passes record goal summaries with NO grid
    (record_goal_infos): density must be 0.0, never raw moves-per-round
    masquerading as a density > 1."""
    rec = FlightRecorder()
    with rec.pass_scope(seq=1) as p:
        p.set(path="fused")
        p.record_goal_infos([{"goal": "g", "residual_violation": 2.0,
                              "violation_before": 9.5, "offline_before": 1,
                              "rounds": 10, "moves_applied": 50}])
    (pd,) = rec.passes()
    (gd,) = pd["goals"]
    assert gd["movesApplied"] == 50 and gd["rounds"] == 10
    assert gd["acceptanceDensity"] == 0.0
    # entry stats from the whole-chain stats land too (violationBefore
    # must not be null on the production fused path)
    assert gd["violationBefore"] == 9.5 and gd["offlineBefore"] == 1
    s = summarize_passes(rec.passes())
    assert s["meanAcceptanceDensity"] == 0.0
    assert s["movesApplied"] == 50


def test_swap_dispatches_excluded_from_density():
    """grid() records the MOVE config's geometry; swap kernels run their
    own fixed grid, so swap dispatches carry no density and stay out of
    the histogram and the per-goal aggregate."""
    from cruise_control_tpu.utils.sensors import SENSORS
    rec = FlightRecorder()
    with rec.pass_scope(seq=1) as p:
        g = p.goal("SwapDensityGoal")
        g.grid(2048, 16, 1024)
        g.dispatch("move", budget=4, rounds=4, applied=8)
        g.dispatch("swap", budget=4, rounds=4, applied=32)
    (pd,) = rec.passes()
    (gd,) = pd["goals"]
    swap = [d for d in gd["dispatches"] if d["kind"] == "swap"][0]
    assert swap["acceptanceDensity"] == 0.0
    # aggregate density uses move rounds/moves only
    assert gd["acceptanceDensity"] == pytest.approx(8 / 4 / 2048, abs=1e-6)
    snap = SENSORS.histogram_snapshot("solver_acceptance_density",
                                      labels={"goal": "SwapDensityGoal"})
    assert snap is not None and snap["count"] == 1, \
        "only the move dispatch may land in the density histogram"


def test_pass_ring_bound_filters_and_marker():
    rec = FlightRecorder(max_passes=2)
    from cruise_control_tpu.utils.sensors import cluster_label
    marker0 = rec.marker()
    for i, cluster in enumerate((None, "alpha", "beta")):
        with cluster_label(cluster):
            with rec.pass_scope(seq=i) as p:
                g = p.goal(f"goal{i}")
                g.entry(violation=float(i))
                g.exit(violation=0.0)
    assert rec.passes_closed == 3
    passes = rec.passes()
    assert len(passes) == 2                       # ring bound: oldest gone
    assert [p["passSeq"] for p in passes] == [2, 1]   # newest first
    assert rec.passes(cluster="alpha")[0]["passSeq"] == 1
    assert rec.passes(cluster="nope") == []
    assert [p["passSeq"] for p in rec.passes(limit=1)] == [2]
    assert rec.passes(limit=0) == []
    # goal filter keeps only passes touching the goal AND trims to it
    got = rec.passes(goal="goal2")
    assert len(got) == 1 and [g["goal"] for g in got[0]["goals"]] == ["goal2"]
    # passes_since: bounded best-effort tail, oldest first
    since = rec.passes_since(marker0)
    assert [p["passSeq"] for p in since] == [1, 2]
    assert rec.passes_since(rec.marker()) == []


def test_disabled_scope_is_shared_noop():
    rec = FlightRecorder()
    rec.configure(enabled=False)
    p1 = rec.pass_scope(seq=1)
    p2 = rec.pass_scope(seq=2)
    assert p1 is p2                      # shared no-op object, no alloc
    with p1 as p:
        g = p.goal("x")
        assert g is NO_FLIGHT
        assert not g.recording and g.ring_rounds == 0
        g.entry(violation=1.0)
        g.grid(8, 8, 8)
        g.sizing(1.0, 8, 8, 8, 8, 0)
        g.dispatch("move", 8, 8, 8)
        g.exit(violation=0.0)
        p.record_goal_infos([])
        p.set(path="none")
    assert rec.passes() == [] and rec.passes_closed == 0


def test_configure_ring_and_max_passes():
    rec = FlightRecorder(max_passes=4, ring_rounds=128)
    rec.configure(ring_rounds=16, max_passes=1)
    assert rec.ring_rounds == 16
    for i in range(3):
        with rec.pass_scope(seq=i):
            pass
    assert len(rec.passes()) == 1


def test_summarize_passes_aggregates():
    rec = FlightRecorder()
    for i, viol in enumerate((5.0, 3.0)):
        with rec.pass_scope(seq=i) as p:
            g = p.goal("g")
            g.grid(10, 4, 10)
            g.dispatch("move", budget=4, rounds=4, applied=2,
                       ring=_fake_ring([(2, 20, 10, 6, 4, viol)] * 4))
            g.exit(violation=viol)
    s = summarize_passes(rec.passes())
    assert s["passes"] == 2 and s["dispatches"] == 2
    assert s["rounds"] == 8 and s["movesApplied"] == 4
    assert s["killAttribution"]["killedByPerSourceReduce"] == 2 * 4 * (6 - 4)
    assert s["byGoal"]["g"]["lastViolationAfter"] == 5.0 \
        or s["byGoal"]["g"]["lastViolationAfter"] == 3.0
    assert sorted(s["byGoal"]["g"]["violationTrajectory"]) == [3.0, 5.0]
    # mean density: each dispatch contributes applied/width per round
    assert s["meanAcceptanceDensity"] == pytest.approx(2 / 4 / 10, abs=1e-6)


# ---- trajectory parity (the acceptance bar) ------------------------------

_G = "cruise_control_tpu.analyzer.goals"
_PARITY_GOALS = [f"{_G}.RackAwareGoal", f"{_G}.ReplicaCapacityGoal",
                 f"{_G}.ReplicaDistributionGoal",
                 f"{_G}.TopicReplicaDistributionGoal"]


def _parity_solve(num_brokers, num_partitions, enabled: bool):
    import jax

    from cruise_control_tpu.analyzer.optimizer import (
        GoalOptimizer, goals_by_priority,
    )
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.model.fixtures import Dist, random_cluster

    FLIGHT.configure(enabled=enabled, ring_rounds=16)
    FLIGHT.clear()
    state, meta = random_cluster(
        num_brokers=num_brokers, num_topics=8,
        num_partitions=num_partitions, rf=3, num_racks=4,
        dist=Dist.EXPONENTIAL, seed=7, skew_to_first=2.0,
        target_utilization=0.55)
    cfg = CruiseControlConfig({
        # Force the bounded per-goal megastep path — the one that carries
        # the on-device per-round stats ring.
        "solver.fused.chain.max.brokers": 1,
        "solver.dispatch.max.rounds": 8,
        "max.solver.rounds": 24,
        "goals": list(_PARITY_GOALS),
        "hard.goals": _PARITY_GOALS[:2],
        "anomaly.detection.goals": _PARITY_GOALS[:2],
    })
    optimizer = GoalOptimizer(cfg)
    final, result = optimizer.optimizations(
        state, meta, goals=goals_by_priority(cfg))
    jax.block_until_ready(final.assignment)
    return (np.asarray(final.assignment).tobytes(),
            np.asarray(final.leader_slot).tobytes(),
            result.balancedness_after, result.violated_goals_after)


@pytest.mark.parametrize("shape", [(16, 512), (50, 2000)],
                         ids=["bucket512", "bucket2k"])
def test_recording_parity_byte_identical(shape):
    """Flight recording on vs. off: byte-identical final assignment and
    leadership at two padded bucket shapes, identical quality verdicts —
    AND the recording run actually captured per-round detail."""
    b, p = shape
    on = _parity_solve(b, p, enabled=True)
    passes = FLIGHT.passes()
    off = _parity_solve(b, p, enabled=False)
    assert on[0] == off[0], "assignment trajectories diverged"
    assert on[1] == off[1], "leadership trajectories diverged"
    assert on[2] == off[2] and on[3] == off[3]
    # The enabled run recorded the pass with real search telemetry.
    assert passes and passes[0]["path"] == "bounded"
    goals = passes[0]["goals"]
    assert [g["goal"] for g in goals] == [g.rsplit(".", 1)[-1]
                                          for g in _PARITY_GOALS]
    moved = [g for g in goals if g["movesApplied"] > 0]
    assert moved, "no goal recorded applied moves"
    with_ring = [g for g in moved if g.get("killAttribution")]
    assert with_ring, "no per-round ring rows captured on the bounded path"
    g = with_ring[0]
    assert g["acceptanceDensity"] > 0
    assert len(g["violationTrajectory"]) >= 1
    ka = g["killAttribution"]
    assert ka["applied"] >= 1 and ka["validCards"] >= ka["applied"]
    assert FLIGHT.passes() == [], "disabled run must record nothing"


# ---- GET /solver + /profile ----------------------------------------------

@pytest.fixture(scope="module")
def solver_api():
    from cruise_control_tpu.api.server import CruiseControlApi
    from cruise_control_tpu.common.resources import Resource
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.executor.admin import (
        InMemoryAdminBackend, PartitionState,
    )
    from cruise_control_tpu.executor.executor import Executor
    from cruise_control_tpu.facade import CruiseControl
    from cruise_control_tpu.monitor import LoadMonitor, StaticCapacityResolver
    from cruise_control_tpu.monitor.sampling import SyntheticSampler

    parts = {}
    for t in range(2):
        for p in range(8):
            reps = (0, 1 + (t + p) % 3)
            parts[(f"t{t}", p)] = PartitionState(f"t{t}", p, reps, reps[0],
                                                 isr=reps)
    backend = InMemoryAdminBackend(parts.values())
    cfg = CruiseControlConfig({
        "partition.metrics.window.ms": 1000,
        "num.partition.metrics.windows": 3,
        "min.valid.partition.ratio": 0.0,
        "max.solver.rounds": 24,
        # Bounded path so /solver shows per-dispatch + per-round detail.
        "solver.fused.chain.max.brokers": 1,
        "solver.dispatch.max.rounds": 8,
        "goals": list(_PARITY_GOALS),
        "hard.goals": _PARITY_GOALS[:2],
        "anomaly.detection.goals": _PARITY_GOALS[:2],
        "failed.brokers.file.path": ""})
    caps = StaticCapacityResolver(
        {}, {Resource.CPU: 100.0, Resource.DISK: 1e7,
             Resource.NW_IN: 1e6, Resource.NW_OUT: 1e6})
    monitor = LoadMonitor(cfg, backend, samplers=[SyntheticSampler()],
                          capacity_resolver=caps)
    cc = CruiseControl(cfg, backend, load_monitor=monitor,
                       executor=Executor(backend, synchronous=True))
    for k in range(1, 4):
        monitor.task_runner.run_sampling_once(end_ms=k * 1000)
    api = CruiseControlApi(cc)
    api._async_wait_s = 180
    FLIGHT.clear()
    yield api
    api.shutdown()
    FLIGHT.configure(enabled=True, max_passes=64, ring_rounds=128)
    FLIGHT.clear()


def test_solver_endpoint_serves_real_rebalance(solver_api):
    status, body, _ = solver_api.handle(
        "POST", "/kafkacruisecontrol/rebalance", "dryrun=true")
    assert status == 200, body
    status, body, _ = solver_api.handle(
        "GET", "/kafkacruisecontrol/solver", "entries=1")
    assert status == 200, body
    assert body["flightRecorderEnabled"] is True
    assert body["numPasses"] == 1
    p = body["passes"][0]
    assert p["path"] == "bounded"
    assert p["shape"] == {"partitions": 16, "brokers": 4}
    goals = p["goals"]
    assert goals and all("acceptanceDensity" in g for g in goals)
    moved = [g for g in goals if g.get("killAttribution")]
    assert moved, "expected per-round kill attribution for a real rebalance"
    assert moved[0]["violationTrajectory"]
    assert moved[0]["dispatches"][0]["rounds_log"]
    # goal filter trims each pass to the named goal
    status, body, _ = solver_api.handle(
        "GET", "/kafkacruisecontrol/solver",
        f"goal={goals[0]['goal']}")
    assert status == 200
    assert [g["goal"] for g in body["passes"][0]["goals"]] \
        == [goals[0]["goal"]]
    # unknown params rejected like every other endpoint
    status, _body, _ = solver_api.handle(
        "GET", "/kafkacruisecontrol/solver", "nope=1")
    assert status == 400


def test_solver_endpoint_sensors_exported(solver_api):
    from cruise_control_tpu.utils.sensors import SENSORS
    text = solver_api.metrics_text()
    assert "kafka_cruisecontrol_solver_flight_passes_total" in text
    assert "kafka_cruisecontrol_solver_acceptance_density_bucket" in text
    snap = SENSORS.histogram_snapshot(
        "solver_acceptance_density",
        labels={"goal": "ReplicaDistributionGoal"})
    assert snap is None or snap["count"] >= 0  # series shape is valid


@pytest.mark.slow  # ~22 s: real device trace capture via the endpoint;
# the disabled-403 and microbench endpoint pins stay tier-1.
def test_profile_endpoint_capture_and_busy(solver_api, tmp_path):
    solver_api._config._values["profiling.trace.dir"] = str(tmp_path)
    status, body, _ = solver_api.handle(
        "GET", "/kafkacruisecontrol/profile", "duration_s=0.05")
    assert status == 200, body
    assert body["profile"] == "trace"
    assert body["traceDir"].startswith(str(tmp_path))
    assert body["numFiles"] >= 1, "profiler produced no trace files"
    # missing duration_s and microbench → 400
    status, body, _ = solver_api.handle(
        "GET", "/kafkacruisecontrol/profile", "")
    assert status == 400
    # single-flight: a concurrent holder makes the request fail fast with
    # Retry-After (the breaker-style busy response)
    from cruise_control_tpu.utils.profiling import PROFILER
    PROFILER._acquire(5.0)
    try:
        status, body, headers = solver_api.handle(
            "GET", "/kafkacruisecontrol/profile", "duration_s=0.05")
        assert status == 503
        assert int(headers["Retry-After"]) >= 1
    finally:
        PROFILER._lock.release()


def test_profile_endpoint_disabled(solver_api):
    solver_api._config._values["profiling.enabled"] = False
    try:
        status, body, _ = solver_api.handle(
            "GET", "/kafkacruisecontrol/profile", "duration_s=0.05")
        assert status == 403
    finally:
        solver_api._config._values["profiling.enabled"] = True


@pytest.mark.slow  # ~19 s: real concurrent device captures; tier-2.
def test_profile_busy_error_concurrent_capture(tmp_path):
    """Two overlapping captures: exactly one wins the gate."""
    from cruise_control_tpu.utils.profiling import (
        DeviceProfiler, ProfilerBusyError,
    )
    prof = DeviceProfiler()
    results = []

    def capture():
        try:
            results.append(prof.capture(0.2, str(tmp_path)))
        except ProfilerBusyError as e:
            results.append(e)

    threads = [threading.Thread(target=capture) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    busy = [r for r in results if isinstance(r, ProfilerBusyError)]
    ok = [r for r in results if isinstance(r, dict)]
    assert len(ok) == 1 and len(busy) == 1
    assert busy[0].retry_after_s >= 0.5


def test_microbench_in_process_small():
    from cruise_control_tpu.utils.microbench import run_microbench
    out = run_microbench(brokers=20, partitions=200, iters=2,
                         cases=("elemwise", "segsum", "cell_segsum",
                                "frac_round", "stride_sort"))
    assert out["unit"] == "ms_per_iter"
    assert set(out["results"]) == {"elemwise", "segsum", "cell_segsum",
                                   "frac_round", "stride_sort"}
    for v in out["results"].values():
        assert isinstance(v, float), v   # no errors on CPU
    bad = run_microbench(brokers=20, partitions=200, iters=2,
                         cases=("nope",))
    assert "error" in bad["results"]["nope"]
