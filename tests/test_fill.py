"""Unit oracle for the constructive destination kernels (analyzer.fill).

Each kernel is checked against a straightforward numpy reference on
randomized inputs: the binary row search against np.searchsorted, the
deficit fill against sequential profile walking (including the
per-broker overfill invariant), and the best-fit assignment's fit
invariant (every assigned destination's gap covers the card's size).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.analyzer.fill import (
    best_fit_dests, deficit_fill_dests, exclusive_rank, rank_within_group,
    row_searchsorted,
)


@pytest.mark.parametrize("seed,t,b,k", [(0, 5, 17, 64), (1, 1, 7, 33),
                                        (2, 11, 64, 128)])
def test_row_searchsorted_matches_numpy(seed, t, b, k):
    rng = np.random.default_rng(seed)
    cum = np.cumsum(rng.integers(0, 4, (t, b)).astype(np.float32), axis=1)
    rows = rng.integers(0, t, k).astype(np.int32)
    q = rng.uniform(-1, cum[:, -1].max() + 2, k).astype(np.float32)
    got = np.asarray(row_searchsorted(jnp.asarray(cum), jnp.asarray(rows),
                                      jnp.asarray(q)))
    want = np.array([np.searchsorted(cum[r], v, side="right")
                     for r, v in zip(rows, q)])
    np.testing.assert_array_equal(got, want)


def test_rank_helpers():
    group = jnp.asarray([3, 1, 3, 3, 1, 2])
    valid = jnp.asarray([True, True, False, True, True, True])
    ranks = np.asarray(rank_within_group(group, valid))
    # Earlier VALID same-group cards: idx2 is invalid so idx3 sees only idx0.
    np.testing.assert_array_equal(ranks, [0, 0, 1, 1, 1, 0])
    np.testing.assert_array_equal(np.asarray(exclusive_rank(valid)),
                                  [0, 1, 2, 2, 3, 4])


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_deficit_fill_respects_per_broker_gaps(seed):
    rng = np.random.default_rng(seed)
    t, b, k = 4, 12, 200
    deficit = rng.integers(0, 3, (t, b)).astype(np.float32)
    headroom = rng.integers(0, 3, (t, b)).astype(np.float32)
    eligible = rng.random(b) < 0.8
    topic = rng.integers(0, t, k).astype(np.int32)
    # Ranks as the production path computes them: position within topic.
    rank = np.asarray(rank_within_group(jnp.asarray(topic),
                                        jnp.ones(k, bool)))
    dst, ok = deficit_fill_dests(jnp.asarray(topic), jnp.asarray(rank),
                                 jnp.asarray(deficit), jnp.asarray(headroom),
                                 jnp.asarray(eligible))
    dst, ok = np.asarray(dst), np.asarray(ok)
    d_el = np.where(eligible[None, :], deficit, 0)
    h_el = np.where(eligible[None, :], headroom, 0)
    for g in range(t):
        sel = (topic == g) & ok
        # Joint per-round fill never exceeds a broker's total gap, and
        # exactly the first total-gap cards of the topic get slots.
        counts = np.bincount(dst[sel], minlength=b)
        assert (counts <= d_el[g] + h_el[g]).all()
        assert sel.sum() == min((topic == g).sum(),
                                int((d_el[g] + h_el[g]).sum()))
        assert eligible[dst[sel]].all() if sel.any() else True
        # Deficit positions fill before plain headroom.
        def_total = int(d_el[g].sum())
        in_def = sel & (rank < def_total)
        if in_def.any():
            assert (d_el[g][dst[in_def]] > 0).all()


@pytest.mark.parametrize("seed", [0, 3])
def test_best_fit_assigns_fitting_destinations(seed):
    rng = np.random.default_rng(seed)
    b, k = 20, 100
    headroom = rng.uniform(0, 10, b).astype(np.float32)
    eligible = rng.random(b) < 0.7
    size = rng.uniform(0.1, 12, k).astype(np.float32)
    rank = np.arange(k, dtype=np.int32)
    dst, ok = best_fit_dests(jnp.asarray(size), jnp.asarray(rank),
                             jnp.asarray(headroom), jnp.asarray(eligible))
    dst, ok = np.asarray(dst), np.asarray(ok)
    max_gap = headroom[eligible].max() if eligible.any() else 0.0
    for i in range(k):
        if ok[i]:
            assert eligible[dst[i]] and headroom[dst[i]] >= size[i]
        else:
            assert size[i] <= 0 or size[i] > max_gap
