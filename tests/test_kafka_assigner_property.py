"""Randomized property sweep for kafka-assigner mode (VERDICT r4 #8).

The r4 deadlock fix (commit 2346255: rack-duplicate fixes may transiently
overshoot the even ceiling by one, later rounds shed the overage) was
validated on one curated fixture. This sweep exercises the property on
randomized HEAVILY SKEWED rack layouts — uneven rack sizes are exactly the
shape that used to deadlock (every under-ceiling destination in a
partition's free rack at the even ceiling).

Feasibility math (drives the layout choices): strict rack-awareness caps a
rack at ONE replica per partition, so a layout is satisfiable iff
Σ_r min(P, ceiling·n_r) ≥ RF·P. With RF = 2, B = 18 and P = 361 the even
ceiling is ceil(722/18) = 41 (rounds UP → slack 16), and any layout whose
largest rack holds ≤ B/RF = 9 brokers is feasible. A layout with a
12-broker rack is PROVABLY infeasible (361 + 6·41 = 607 < 722) — the goal
must then fail LOUDLY (OptimizationFailureError), never silently.

Invariants per feasible run (reference: analyzer/kafkaassigner/
KafkaAssignerEvenRackAwareGoal.java):
- strict rack-awareness: no rack holds two replicas of one partition;
- even ceiling: every broker ends at or under ceil(total/alive) — the
  transient overshoot must have been shed by convergence;
- the optimizer reports success (no violated hard goal).

All runs share one tensor shape so the chain compiles once.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.analyzer.optimizer import (
    GoalOptimizer, goals_by_priority,
)
from cruise_control_tpu.analyzer.search import OptimizationFailureError
from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
from cruise_control_tpu.model import fixtures
from cruise_control_tpu.model.tensors import (
    broker_replica_counts, rack_partition_counts,
)

_B, _T, _P, _RF, _RACKS = 18, 6, 361, 2, 4

# Uneven rack layouts (brokers per rack; sum = _B; max ≤ _B/_RF = 9 keeps
# them feasible per the module docstring). A rack barely wider than one
# broker forces the at-ceiling free-rack shape.
#
# MAX-TIGHT layouts — a 9-broker rack is exactly B/RF, so that rack must
# absorb one replica of (almost) every partition — were the enumerated
# residual gap of the r5 deadlock work: a SINGLE ceiling+1 count overage
# stranded on a broker whose shed channel was consumed by the same
# round's batch. Round 6 closed the remaining strand mechanism: the
# own-rack feasibility branch counted the replica's OWN broker as a
# room-bearing rack-mate, so a self-referential "shed channel" (a move
# onto the broker already hosting the replica — not a real move) could
# admit a same-round overshoot whose real channel did not exist. With
# the own-broker exclusion (_rack_dest_feasibility) every sweep layout,
# max-tight included, converges — these run unmarked.
_LAYOUTS = [
    (9, 5, 3, 1),   # max-tight
    (8, 6, 3, 1),
    (9, 4, 4, 1),   # max-tight
    (7, 7, 3, 1),
]
_MAX_TIGHT = {(9, 5, 3, 1), (9, 4, 4, 1)}  # hardest shapes (see above)


def _rack_vector(layout: tuple[int, ...]) -> jnp.ndarray:
    racks = []
    for r, n in enumerate(layout):
        racks.extend([r] * n)
    return jnp.asarray(racks, dtype=jnp.int32)


def _run(seed: int, layout: tuple[int, ...]):
    cfg = CruiseControlConfig()
    state, meta = fixtures.random_cluster(
        num_brokers=_B, num_topics=_T, num_partitions=_P, rf=_RF,
        num_racks=_RACKS, dist=fixtures.Dist.EXPONENTIAL, seed=seed,
        target_utilization=0.55)
    state = dataclasses.replace(state, rack=_rack_vector(layout))
    opt = GoalOptimizer(cfg)
    return opt.optimizations(state, meta, goals=goals_by_priority(
        cfg, ["KafkaAssignerEvenRackAwareGoal",
              "KafkaAssignerDiskUsageDistributionGoal"]))


@pytest.mark.parametrize(
    "seed,layout",
    [pytest.param(s, lo) for s in (3, 11, 29) for lo in _LAYOUTS])
def test_even_rack_skewed_layout_sweep(seed, layout):
    final, res = _run(seed, layout)
    assert res.violated_goals_after == []
    counts = np.asarray(rack_partition_counts(final, _RACKS))
    live = np.asarray(final.partition_mask)
    assert (counts[live] <= 1).all(), "rack-awareness must hold"
    reps = np.asarray(broker_replica_counts(final))[:_B]
    assert reps.max() <= int(np.ceil(reps.sum() / _B)), \
        (layout, seed, reps.tolist())


def test_swap_counterparty_and_overshoot_guard_semantics():
    """Pin the r5 strand fixes directly (they are invisible to the sweep's
    pass/xfail pattern): swap_dest_score must EXCLUDE over-ceiling
    brokers (an exchange preserves their count but eats the replica
    their shed needs), and the overshoot guard must be COUNT-matched —
    same-round overshoots beyond a broker's distinct shed channels are
    vetoed even though the boolean has-shed form would admit them."""
    import jax.numpy as jnp

    from cruise_control_tpu.analyzer.derived import compute_derived
    from cruise_control_tpu.analyzer.goals import (
        KafkaAssignerEvenRackAwareGoal,
    )
    from cruise_control_tpu.model.builder import ClusterModelBuilder
    from cruise_control_tpu.common.resources import Resource

    cap = {Resource.CPU: 100.0, Resource.NW_IN: 1e5, Resource.NW_OUT: 1e5,
           Resource.DISK: 1e6}
    load = {Resource.CPU: 1.0, Resource.NW_IN: 10.0, Resource.NW_OUT: 10.0,
            Resource.DISK: 100.0}
    b = ClusterModelBuilder()
    for i, rack in enumerate(["r0", "r0", "r1", "r2"]):
        b.add_broker(i, rack, cap)
    # Broker 0: 3 replicas (over the ceiling of ceil(8/4) = 2);
    # brokers 1-3 at or under. Partition layouts leave broker 0 with
    # movable replicas and give broker 2 a shed channel.
    b.add_partition("t", 0, [0, 2], leader_load=load)
    b.add_partition("t", 1, [0, 3], leader_load=load)
    b.add_partition("t", 2, [0, 2], leader_load=load)
    b.add_partition("t", 3, [1, 3], leader_load=load)
    state, meta = b.build()
    goal = KafkaAssignerEvenRackAwareGoal()
    derived = compute_derived(state, None, None, None)

    score = np.asarray(goal.swap_dest_score(state, derived, None, None))
    counts = np.asarray(derived.broker_replicas)[:4]
    ceiling = int(np.ceil(counts.sum() / 4))
    over = counts > ceiling
    assert over[0], "fixture must have an over-ceiling broker"
    assert not np.isfinite(score[0]), \
        "over-ceiling brokers must be excluded as swap counterparties"
    assert np.isfinite(score[1:]).all()

    shed = np.asarray(goal._shed_count_per_broker(state, derived))
    assert shed.shape == (4,) and (shed >= 0).all()
    # Count-matched guard: with pre_dst_count == shed_count the overshoot
    # path must close even where the boolean form would stay open.
    import dataclasses as dc

    from cruise_control_tpu.analyzer.candidates import (
        CandidateDeltas, compute_deltas, Candidates,
    )
    dst = int(np.argmax(shed))
    if shed[dst] > 0:
        cand = Candidates(kind=jnp.zeros(1, jnp.int8),
                          partition=jnp.zeros(1, jnp.int32),
                          src_slot=jnp.zeros(1, jnp.int32),
                          dst_broker=jnp.asarray([dst], jnp.int32),
                          dst_slot=jnp.zeros(1, jnp.int32),
                          valid=jnp.ones(1, bool))
        deltas = compute_deltas(state, derived, cand)
        sat = dc.replace(deltas,
                         pre_dst_count=jnp.asarray([float(shed[dst])]))
        acc_sat = goal.acceptance(state, derived, None, None, sat)
        fresh = dc.replace(deltas, pre_dst_count=jnp.zeros(1))
        acc_fresh = goal.acceptance(state, derived, None, None, fresh)
        # Saturated channels can only ever be MORE restrictive.
        assert bool(np.asarray(acc_sat)[0]) <= bool(np.asarray(acc_fresh)[0])


def test_even_rack_infeasible_layout_fails_loudly():
    """A 12-broker rack makes the even ceiling + strict rack-awareness
    jointly unsatisfiable (see module docstring); the hard goal must
    RAISE — the documented overshoot failure mode reports, never passes
    silently."""
    with pytest.raises(OptimizationFailureError, match="EvenRackAware"):
        _run(3, (12, 3, 2, 1))
