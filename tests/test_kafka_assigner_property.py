"""Randomized property sweep for kafka-assigner mode (VERDICT r4 #8).

The r4 deadlock fix (commit 2346255: rack-duplicate fixes may transiently
overshoot the even ceiling by one, later rounds shed the overage) was
validated on one curated fixture. This sweep exercises the property on
randomized HEAVILY SKEWED rack layouts — uneven rack sizes are exactly the
shape that used to deadlock (every under-ceiling destination in a
partition's free rack at the even ceiling).

Feasibility math (drives the layout choices): strict rack-awareness caps a
rack at ONE replica per partition, so a layout is satisfiable iff
Σ_r min(P, ceiling·n_r) ≥ RF·P. With RF = 2, B = 18 and P = 361 the even
ceiling is ceil(722/18) = 41 (rounds UP → slack 16), and any layout whose
largest rack holds ≤ B/RF = 9 brokers is feasible. A layout with a
12-broker rack is PROVABLY infeasible (361 + 6·41 = 607 < 722) — the goal
must then fail LOUDLY (OptimizationFailureError), never silently.

Invariants per feasible run (reference: analyzer/kafkaassigner/
KafkaAssignerEvenRackAwareGoal.java):
- strict rack-awareness: no rack holds two replicas of one partition;
- even ceiling: every broker ends at or under ceil(total/alive) — the
  transient overshoot must have been shed by convergence;
- the optimizer reports success (no violated hard goal).

All runs share one tensor shape so the chain compiles once.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.analyzer.optimizer import (
    GoalOptimizer, goals_by_priority,
)
from cruise_control_tpu.analyzer.search import OptimizationFailureError
from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
from cruise_control_tpu.model import fixtures
from cruise_control_tpu.model.tensors import (
    broker_replica_counts, rack_partition_counts,
)

_B, _T, _P, _RF, _RACKS = 18, 6, 361, 2, 4

# Uneven rack layouts (brokers per rack; sum = _B; max ≤ _B/_RF = 9 keeps
# them feasible per the module docstring). A rack barely wider than one
# broker forces the at-ceiling free-rack shape.
#
# MAX-TIGHT layouts — a 9-broker rack is exactly B/RF, so that rack must
# absorb one replica of (almost) every partition — are the enumerated
# residual gap of the r5 deadlock work. With the count-preserving swap
# exchange (r5) the rack duplicates now fully resolve; the remaining
# stall shape on some seeds is a SINGLE ceiling+1 count overage stranded
# on a broker whose shed channel was consumed by the same round's batch
# (residual ≤ 2, loudly reported). The known fix is an overage-relay
# move (the overage hops to an at-ceiling broker that still has a shed
# channel) — it needs a termination argument, since relays can cycle.
# These run as xfail(strict=False) until that lands (docs/DESIGN.md).
_LAYOUTS = [
    (9, 5, 3, 1),   # max-tight
    (8, 6, 3, 1),
    (9, 4, 4, 1),   # max-tight
    (7, 7, 3, 1),
]
_MAX_TIGHT = {(9, 5, 3, 1), (9, 4, 4, 1)}


def _rack_vector(layout: tuple[int, ...]) -> jnp.ndarray:
    racks = []
    for r, n in enumerate(layout):
        racks.extend([r] * n)
    return jnp.asarray(racks, dtype=jnp.int32)


def _run(seed: int, layout: tuple[int, ...]):
    cfg = CruiseControlConfig()
    state, meta = fixtures.random_cluster(
        num_brokers=_B, num_topics=_T, num_partitions=_P, rf=_RF,
        num_racks=_RACKS, dist=fixtures.Dist.EXPONENTIAL, seed=seed,
        target_utilization=0.55)
    state = dataclasses.replace(state, rack=_rack_vector(layout))
    opt = GoalOptimizer(cfg)
    return opt.optimizations(state, meta, goals=goals_by_priority(
        cfg, ["KafkaAssignerEvenRackAwareGoal",
              "KafkaAssignerDiskUsageDistributionGoal"]))


@pytest.mark.parametrize(
    "seed,layout",
    [pytest.param(s, lo,
                  marks=[pytest.mark.xfail(
                      reason="max-tight rack layout: a single ceiling+1 "
                      "overage can strand on a shed-less broker (rack "
                      "duplicates fully resolve via the swap exchange); "
                      "fails LOUDLY — needs an overage-relay move",
                      strict=False)] if lo in _MAX_TIGHT else [])
     for s in (3, 11, 29) for lo in _LAYOUTS])
def test_even_rack_skewed_layout_sweep(seed, layout):
    final, res = _run(seed, layout)
    assert res.violated_goals_after == []
    counts = np.asarray(rack_partition_counts(final, _RACKS))
    live = np.asarray(final.partition_mask)
    assert (counts[live] <= 1).all(), "rack-awareness must hold"
    reps = np.asarray(broker_replica_counts(final))[:_B]
    assert reps.max() <= int(np.ceil(reps.sum() / _B)), \
        (layout, seed, reps.tolist())


def test_even_rack_infeasible_layout_fails_loudly():
    """A 12-broker rack makes the even ceiling + strict rack-awareness
    jointly unsatisfiable (see module docstring); the hard goal must
    RAISE — the documented overshoot failure mode reports, never passes
    silently."""
    with pytest.raises(OptimizationFailureError, match="EvenRackAware"):
        _run(3, (12, 3, 2, 1))
