"""Facade orchestration: model-backed operations, proposal cache, state
dashboard, and end-to-end self-healing through the detector manager
(reference parity: KafkaCruiseControl.java + runnable/ + the
AnomalyDetectorManager fix path)."""

import numpy as np
import pytest

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
from cruise_control_tpu.detector import AnomalyStatus, BrokerFailures
from cruise_control_tpu.executor.admin import InMemoryAdminBackend, PartitionState
from cruise_control_tpu.executor.executor import Executor
from cruise_control_tpu.facade import CruiseControl
from cruise_control_tpu.monitor import LoadMonitor, StaticCapacityResolver
from cruise_control_tpu.monitor.sampling import SyntheticSampler


def _partitions(brokers=(0, 1, 2, 3), topics=2, parts=6, rf=2):
    out = {}
    for t in range(topics):
        for p in range(parts):
            # Skewed: broker 0 leads everything (real rebalance work).
            reps = (brokers[0], brokers[1 + (t + p) % (len(brokers) - 1)])[:rf]
            out[(f"t{t}", p)] = PartitionState(f"t{t}", p, reps, reps[0],
                                               isr=reps)
    return out


def _cruise_control(partitions, extra_cfg=None, synchronous_executor=True):
    backend = InMemoryAdminBackend(partitions.values())
    cfg = CruiseControlConfig({
        "partition.metrics.window.ms": 1000,
        "num.partition.metrics.windows": 3,
        "min.valid.partition.ratio": 0.0,
        "anomaly.detection.interval.ms": 60_000,
        "max.solver.rounds": 40,
        "failed.brokers.file.path": "",   # no cross-run persistence in tests

        **(extra_cfg or {})})
    caps = StaticCapacityResolver({}, {Resource.CPU: 100.0, Resource.DISK: 1e7,
                                       Resource.NW_IN: 1e6, Resource.NW_OUT: 1e6})
    monitor = LoadMonitor(cfg, backend, samplers=[SyntheticSampler()],
                          capacity_resolver=caps,
                          broker_racks={b: f"r{b % 2}" for b in range(8)})
    executor = Executor(backend, synchronous=synchronous_executor)
    cc = CruiseControl(cfg, backend, load_monitor=monitor, executor=executor)
    for k in range(1, 4):
        monitor.task_runner.run_sampling_once(end_ms=k * 1000)
    return cc, backend


def test_rebalance_dryrun_produces_proposals_and_does_not_execute():
    cc, backend = _cruise_control(_partitions())
    before = backend.describe_partitions()
    res = cc.rebalance(dryrun=True)
    assert res.proposals, "skewed cluster must yield proposals"
    assert not res.executed
    assert backend.describe_partitions() == before
    assert res.optimizer_result.balancedness_after >= \
        res.optimizer_result.balancedness_before


def test_rebalance_executes_against_backend():
    cc, backend = _cruise_control(_partitions())
    res = cc.rebalance(dryrun=False)
    assert res.executed
    cc.executor.await_completion()
    after = backend.describe_partitions()
    applied = {(t, p): st.replicas for (t, p), st in after.items()}
    for pr in res.proposals:
        assert set(applied[(pr.topic, pr.partition)]) == set(pr.new_replicas)


def test_proposals_cache_hits_until_generation_changes():
    cc, _ = _cruise_control(_partitions())
    r1 = cc.proposals()
    assert r1.reason != "cached"
    r2 = cc.proposals()
    assert r2.reason == "cached"
    # New samples → new model generation → fresh computation.
    cc.load_monitor.task_runner.run_sampling_once(end_ms=10_000)
    assert cc.proposals().reason != "cached"


def test_remove_brokers_moves_all_replicas_off():
    cc, _ = _cruise_control(_partitions(brokers=(0, 1, 2, 3)))
    res = cc.remove_brokers([3], dryrun=True)
    for pr in res.proposals:
        assert 3 not in pr.new_replicas
    held = [pr for pr in res.proposals if 3 in pr.old_replicas]
    # Every partition broker 3 hosted must be moved away.
    parts_on_3 = [(t, p) for (t, p), st in
                  cc._admin.describe_partitions().items() if 3 in st.replicas]
    assert {(pr.topic, pr.partition) for pr in held} >= set(parts_on_3)


def test_add_brokers_routes_load_to_new_broker():
    partitions = _partitions(brokers=(0, 1, 2))
    backend = InMemoryAdminBackend(partitions.values())
    backend.revive_broker(4)          # empty new broker joins the cluster
    cfg = CruiseControlConfig({"partition.metrics.window.ms": 1000,
                               "num.partition.metrics.windows": 3,
                               "min.valid.partition.ratio": 0.0,
                               "max.solver.rounds": 40,
                               "failed.brokers.file.path": ""})
    caps = StaticCapacityResolver({}, {Resource.CPU: 100.0, Resource.DISK: 1e7,
                                       Resource.NW_IN: 1e6, Resource.NW_OUT: 1e6})
    monitor = LoadMonitor(cfg, backend, samplers=[SyntheticSampler()],
                          capacity_resolver=caps)
    cc = CruiseControl(cfg, backend, load_monitor=monitor,
                       executor=Executor(backend, synchronous=True))
    for k in range(1, 4):
        monitor.task_runner.run_sampling_once(end_ms=k * 1000)
    res = cc.add_brokers([4], dryrun=True)
    gained = [pr for pr in res.proposals if 4 in pr.new_replicas]
    assert gained, "new broker must receive replicas"


def test_demote_brokers_sheds_leadership_only():
    cc, _ = _cruise_control(_partitions())
    res = cc.demote_brokers([0], dryrun=True)
    for pr in res.proposals:
        assert set(pr.old_replicas) == set(pr.new_replicas), \
            "demotion must not move replicas"
        assert pr.new_leader != 0


def test_update_topic_replication_factor_grows_rack_aware():
    cc, _ = _cruise_control(_partitions(rf=2))
    # The fixture has 2 racks (r0/r1): growing to RF 3 must refuse without
    # the explicit opt-in (RunnableUtils.java:91-99) ...
    with pytest.raises(ValueError, match="skip_rack_awareness_check"):
        cc.update_topic_replication_factor(["t0"], 3, dryrun=True)
    # ... and RF above the alive-broker count is always impossible (:87-90).
    with pytest.raises(ValueError, match="alive broker"):
        cc.update_topic_replication_factor(["t0"], 5, dryrun=True,
                                           skip_rack_awareness_check=True)
    res = cc.update_topic_replication_factor(["t0"], 3, dryrun=True,
                                             skip_rack_awareness_check=True)
    assert res.proposals
    for pr in res.proposals:
        assert len(pr.new_replicas) == 3
        assert set(pr.old_replicas) <= set(pr.new_replicas)


def test_state_dashboard_sections():
    cc, _ = _cruise_control(_partitions())
    st = cc.state()
    assert {"MonitorState", "ExecutorState", "AnalyzerState",
            "AnomalyDetectorState"} <= set(st)
    assert st["MonitorState"]["numValidWindows"] >= 1
    only = cc.state(substates=["executor"])
    assert set(only) == {"ExecutorState"}


def test_self_healing_broker_failure_end_to_end():
    """Kill a broker → failure detector reports → manager consults notifier
    → fix = remove_brokers → executor applies → no replica remains on the
    dead broker (the reference's BrokerFailureDetectorTest + self-healing
    loop, collapsed into one synchronous pass)."""
    cc, backend = _cruise_control(
        _partitions(brokers=(0, 1, 2, 3)),
        extra_cfg={"self.healing.enabled": True,
                   "broker.failure.self.healing.threshold.ms": 0})
    cc._notifier._alert_threshold_ms = 0
    backend.kill_broker(3)
    # Re-sample so the model sees the dead broker.
    cc.load_monitor.task_runner.run_sampling_once(end_ms=5000)

    detector = [d for d, _i in cc.anomaly_detector._detectors
                if type(d).__name__ == "BrokerFailureDetector"][0]
    anomaly = detector.run_once()
    assert isinstance(anomaly, BrokerFailures) and 3 in anomaly.failed_brokers
    taken = cc.anomaly_detector._take(timeout_s=0.5)
    status = cc.anomaly_detector.handle_anomaly(taken)
    assert status == AnomalyStatus.FIX_STARTED
    cc.executor.await_completion()
    for st in backend.describe_partitions().values():
        assert 3 not in st.replicas


def test_config_excluded_topics_regex_holds_on_rebalance_path():
    """topics.excluded.from.partition.movement must bind the EXECUTING
    operations, not just dryrun previews: no proposal may touch a matching
    topic (KafkaCruiseControlUtils.excludedTopics contract)."""
    cc, backend = _cruise_control(
        _partitions(), extra_cfg={
            "topics.excluded.from.partition.movement": "t0"})
    res = cc.rebalance(dryrun=True)
    assert res.proposals, "t1 still needs rebalancing"
    assert not any(p.topic == "t0" for p in res.proposals), \
        [p.topic for p in res.proposals]
    # the cached-proposal path (PROPOSALS endpoint) honors it too
    res2 = cc.proposals()
    assert not any(p.topic == "t0" for p in res2.proposals)


def test_invalid_excluded_topics_regex_fails_fast():
    from cruise_control_tpu.config.configdef import ConfigException

    with pytest.raises(ConfigException, match="regex"):
        _cruise_control(_partitions(), extra_cfg={
            "topics.excluded.from.partition.movement": "[__"})


def test_background_proposal_precompute_warms_cache():
    """GoalOptimizer.java:152-203 parity: the precompute loop keeps cached
    proposals fresh so a PROPOSALS request hits a warm cache without ever
    computing inline."""
    import time as _time

    cc, _backend = _cruise_control(
        _partitions(), extra_cfg={"proposal.expiration.ms": 2000},
        synchronous_executor=True)
    cc.start_up(block_on_load=False)
    try:
        deadline = _time.time() + 20
        while _time.time() < deadline:
            with cc._proposal_lock:
                if cc._proposal_cache is not None:
                    break
            _time.sleep(0.2)
        with cc._proposal_lock:
            assert cc._proposal_cache is not None, \
                "precompute never populated the cache"
        res = cc.proposals()
        assert res.reason == "cached"
    finally:
        cc.shutdown()
