"""Digital-twin scenario harness (round 11).

Unit coverage: the simulated clock, seeded event-stream expansion, the
backend's topic-churn controls, synchronous detector driving on an
injected clock, and the facade's TTL'd removal/demotion history.
Integration coverage: seed-pinned canonical scenario regressions (exact
final-assignment digests, finite time-to-heal bounds, zero dead letters
on the non-chaos scenarios), byte-identical determinism of a full
>=100-tick broker-loss-under-drift replay, and the ``?what_if=``
time-dimension extension of the PROPOSALS dry run.
"""

import functools
import json

import pytest

from cruise_control_tpu.common.resources import Resource
from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
from cruise_control_tpu.detector.manager import AnomalyDetectorManager
from cruise_control_tpu.detector.notifier import (
    AnomalyNotificationAction, AnomalyNotificationResult,
)
from cruise_control_tpu.executor.admin import (
    InMemoryAdminBackend, PartitionState,
)
from cruise_control_tpu.executor.executor import Executor
from cruise_control_tpu.facade import CruiseControl
from cruise_control_tpu.monitor import LoadMonitor, StaticCapacityResolver
from cruise_control_tpu.monitor.sampling import SyntheticSampler
from cruise_control_tpu.testing.simulator import (
    CANONICAL_SCENARIOS, DriftSpec, DriftingSampler, SimClock, run_scenario,
)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@functools.lru_cache(maxsize=None)
def _run(name: str, seed: int):
    return run_scenario(name, seed=seed)


# ---------------------------------------------------------------------------
# Clock + event-stream determinism units
# ---------------------------------------------------------------------------

def test_sim_clock_advances_and_sleep_consumes_sim_time():
    clk = SimClock(start_s=5.0)
    assert clk() == 5.0 and clk.now_ms() == 5000
    clk.advance(2.5)
    clk.sleep(1.5)          # backoff sleeps burn sim time, never wall time
    assert clk.now_s() == 9.0


def test_expand_events_is_pure_in_seed_and_sorted():
    spec = CANONICAL_SCENARIOS["topic_churn_storm"]
    a = [e.as_dict() for e in spec.expand_events(seed=7)]
    b = [e.as_dict() for e in spec.expand_events(seed=7)]
    assert a == b, "generator streams must be pure in (seed, spec)"
    assert a == sorted(a, key=lambda d: d["tick"])
    c = [e.as_dict() for e in spec.expand_events(seed=8)]
    assert a != c, "different seeds must vary the stream"


def test_drifting_sampler_is_wall_clock_free_and_seeded():
    parts = {("t0", 0): PartitionState("t0", 0, (0, 1), 0, isr=(0, 1))}
    s1 = DriftingSampler(seed=3, drift=DriftSpec(amplitude=0.5))
    s2 = DriftingSampler(seed=3, drift=DriftSpec(amplitude=0.5))
    r1 = s1.get_samples(parts, 0, 60_000)
    r2 = s2.get_samples(parts, 0, 60_000)
    assert r1.partition_samples[0].values == r2.partition_samples[0].values
    # Drift is a function of the SIM timestamp handed in, nothing else.
    r3 = s1.get_samples(parts, 0, 90_000)
    assert r3.partition_samples[0].values != r1.partition_samples[0].values


# ---------------------------------------------------------------------------
# Backend topic-churn controls
# ---------------------------------------------------------------------------

def _backend(brokers=4, topics=1, parts=4):
    out = {}
    for t in range(topics):
        for p in range(parts):
            reps = (p % brokers, (p + 1) % brokers)
            out[(f"t{t}", p)] = PartitionState(f"t{t}", p, reps, reps[0],
                                               isr=reps)
    return InMemoryAdminBackend(out.values())


def test_create_delete_expand_topic_bump_metadata_generation():
    b = _backend()
    g0 = b.metadata_generation()
    b.create_topic("new", 6, rf=2)
    assert b.metadata_generation() > g0
    created = {k: st for k, st in b.describe_partitions().items()
               if k[0] == "new"}
    assert len(created) == 6
    assert all(len(set(st.replicas)) == 2 and st.leader in st.replicas
               for st in created.values())

    g1 = b.metadata_generation()
    assert b.expand_partitions("new", 9) == 3
    assert b.metadata_generation() > g1
    assert len([k for k in b.describe_partitions() if k[0] == "new"]) == 9

    g2 = b.metadata_generation()
    assert b.delete_topic("new") == 9
    assert b.metadata_generation() > g2
    assert not [k for k in b.describe_partitions() if k[0] == "new"]
    # Unknown-topic expansion is an error, not a silent create.
    with pytest.raises(ValueError):
        b.expand_partitions("nope", 4)


def test_expand_partitions_places_logdirs_on_jbod():
    b = _backend()
    b.enable_jbod({br: ["/d0", "/d1"] for br in range(4)})
    b.create_topic("j", 2, rf=2)
    b.expand_partitions("j", 4)
    placed = b.replica_logdirs()
    for p in range(2, 4):        # the EXPANDED partitions, same rule as
        st = b.describe_partitions()[("j", p)]   # create_topic's
        for br in st.replicas:
            assert placed.get(("j", p, br)) in ("/d0", "/d1"), \
                "expanded partitions must be visible to disk-health checks"


# ---------------------------------------------------------------------------
# Synchronous detector driving on the injected clock
# ---------------------------------------------------------------------------

class _CountingDetector:
    def __init__(self):
        self.runs = 0

    def run_once(self):
        self.runs += 1
        return []


def test_run_due_paces_detectors_on_injected_clock():
    clk = FakeClock()
    mgr = AnomalyDetectorManager(CruiseControlConfig(), clock=clk)
    det = _CountingDetector()
    mgr.add_detector(det, 10_000)   # 10 s interval
    # First sight only schedules (matches the scheduler thread's
    # wait-then-run pacing) — nothing runs at t=0.
    assert mgr.run_due() == 0 and det.runs == 0
    clk.advance(9.9)
    assert mgr.run_due() == 0
    clk.advance(0.2)
    assert mgr.run_due() == 1 and det.runs == 1
    clk.advance(5.0)
    assert mgr.run_due() == 0 and det.runs == 1
    clk.advance(5.0)
    assert mgr.run_due() == 1 and det.runs == 2


class _ScriptedNotifier:
    """First consult: re-check after 5 s; second: ignore."""

    def __init__(self):
        self.consults = 0

    def on_anomaly(self, anomaly):
        self.consults += 1
        if self.consults == 1:
            return AnomalyNotificationResult(
                AnomalyNotificationAction.CHECK, delay_ms=5_000)
        return AnomalyNotificationResult(AnomalyNotificationAction.IGNORE)

    def self_healing_enabled(self):
        return {}


def test_drain_anomalies_promotes_rechecks_on_sim_time():
    from cruise_control_tpu.detector.anomaly import Anomaly
    clk = FakeClock()
    notifier = _ScriptedNotifier()
    mgr = AnomalyDetectorManager(CruiseControlConfig(), notifier, clock=clk)
    mgr.report(Anomaly())
    assert mgr.drain_anomalies() == 1           # consult 1 -> parked
    assert notifier.consults == 1
    clk.advance(4.0)
    assert mgr.drain_anomalies() == 0           # not due yet on sim time
    clk.advance(2.0)
    assert mgr.drain_anomalies() == 1           # promoted + consulted again
    assert notifier.consults == 2


# ---------------------------------------------------------------------------
# Facade removal/demotion history: TTL on the injected clock
# ---------------------------------------------------------------------------

def test_removal_history_expires_on_injected_clock():
    clk = FakeClock()
    cc = CruiseControl(
        CruiseControlConfig({"failed.brokers.file.path": "",
                             "removal.history.retention.time.ms": 60_000,
                             "demotion.history.retention.time.ms": 30_000}),
        _backend(), clock=clk)
    cc._history_record(cc._removal_history, [3, 4])
    cc._history_record(cc._demotion_history, [5])
    assert cc.recently_removed_brokers == {3, 4}
    assert cc.recently_demoted_brokers == {5}
    clk.advance(31.0)       # demotion retention (30 s) lapses first
    assert cc.recently_demoted_brokers == set()
    assert cc.recently_removed_brokers == {3, 4}
    # Operator drop (the ADMIN drop_recently_* path) beats the TTL.
    cc.drop_recently_removed_brokers([3])
    assert cc.recently_removed_brokers == {4}
    clk.advance(30.0)       # removal retention (60 s) lapses
    assert cc.recently_removed_brokers == set()


def test_removal_history_rerecord_refreshes_stamp():
    clk = FakeClock()
    cc = CruiseControl(
        CruiseControlConfig({"failed.brokers.file.path": "",
                             "removal.history.retention.time.ms": 60_000}),
        _backend(), clock=clk)
    cc._history_record(cc._removal_history, [7])
    clk.advance(40.0)
    cc._history_record(cc._removal_history, [7])   # removed again
    clk.advance(40.0)       # 80 s after first stamp, 40 s after second
    assert cc.recently_removed_brokers == {7}


# ---------------------------------------------------------------------------
# Seed-pinned canonical scenario regressions
# ---------------------------------------------------------------------------

# (scenario, seed) -> exact expectations. These runs are fully
# deterministic: any drift here is a behavior change in the pipeline the
# twin drives, not noise.
PINNED = {
    ("broker_loss_drift", 0):
        dict(digest="b8ea3087", heal_p95=8, moves=16, bal_final=100.0),
    ("broker_loss_drift", 1):
        dict(digest="b8ea3087", heal_p95=8, moves=16, bal_final=100.0),
    ("multi_az_failure", 0):
        dict(digest="0d3c895b", heal_p95=6, moves=66, bal_final=100.0),
    ("multi_az_failure", 1):
        dict(digest="d1d3cfc2", heal_p95=6, moves=66, bal_final=100.0),
    ("topic_churn_storm", 0):
        dict(digest="035ad16a", heal_p95=None, moves=4, bal_final=62.264),
    ("topic_churn_storm", 1):
        dict(digest="556c9b4e", heal_p95=None, moves=20, bal_final=100.0),
}


@pytest.mark.slow  # ~75 s across params; CI's SCENARIO_MATRIX replays
# every canonical scenario per-PR, and the slow suite still runs these
# exact pins — tier-1 keeps the cheaper determinism tests above.
@pytest.mark.parametrize("name,seed", sorted(PINNED))
def test_seed_pinned_scenario_regression(name, seed):
    exp = PINNED[(name, seed)]
    r = _run(name, seed)
    d = r.score.as_dict()
    assert r.assignment_digest == exp["digest"]
    assert d["heal"]["p95Ticks"] == exp["heal_p95"]
    assert d["heal"]["unhealed"] == 0
    assert d["churn"]["replicaMoves"] == exp["moves"]
    assert d["balancedness"]["final"] == exp["bal_final"]
    assert d["deadLetters"] == 0, \
        "non-chaos scenarios must never dead-letter"
    assert d["sloViolations"] == []


def test_scenario_score_embeds_solver_flight_summary():
    """Round-12 satellite: the score carries the flight-recorder summary
    of the solves the scenario drove — the WHY behind a quality move
    (acceptance density, kill attribution, per-goal violation
    trajectories), wall-clock-free so determinism holds."""
    r = _run("broker_loss_drift", 0)
    sf = r.score.as_dict()["solverFlight"]
    assert sf is not None, "flight recorder is on by default"
    assert sf["passes"] >= 1, "self-healing must have driven solves"
    assert sf["movesApplied"] >= 1
    assert set(sf["killAttribution"]) == {
        "killedByPriorVeto", "killedByNonPositive", "killedByPerSourceReduce",
        "killedByDedupRecheck"}
    assert sf["byGoal"], "per-goal summaries expected"
    g = next(iter(sf["byGoal"].values()))
    assert "violationTrajectory" in g and "lastViolationAfter" in g


def test_broker_loss_time_to_heal_is_finite_and_bounded():
    r = _run("broker_loss_drift", 0)
    heals = r.score.heal_events
    assert heals, "the kill_broker event must open a heal measurement"
    for h in heals:
        assert h.ticks_to_heal is not None, "time-to-heal must be finite"
        assert 0 < h.ticks_to_heal <= 30   # the scenario.slo.heal.ticks SLO


def test_full_broker_loss_scenario_is_byte_identical_at_one_seed():
    """Acceptance: >=100 simulated ticks of broker loss + load drift,
    two runs at one seed -> byte-identical event streams, final
    assignments, and ScenarioScore JSON."""
    assert CANONICAL_SCENARIOS["broker_loss_drift"].ticks >= 100
    a = _run("broker_loss_drift", 0)
    b = run_scenario("broker_loss_drift", seed=0)   # fresh simulator
    assert json.dumps(a.events, sort_keys=True) \
        == json.dumps(b.events, sort_keys=True)
    assert a.final_assignment == b.final_assignment
    assert a.score.to_json() == b.score.to_json()
    assert a.assignment_digest == b.assignment_digest


def test_chaos_drift_converges_through_injected_faults():
    r = _run("chaos_drift", 0)
    d = r.score.as_dict()
    assert d["faultsInjected"] > 0, "chaos must actually fire"
    assert d["heal"]["unhealed"] == 0
    assert d["balancedness"]["final"] == 100.0
    assert d["sloViolations"] == []


def test_simulator_leaves_host_tracer_configuration_alone():
    """A ?what_if= replay (or any embedded twin) must not rewrite the
    serving process's tracing settings — the twin's facade is built with
    configure_observability=False."""
    from cruise_control_tpu.testing.simulator import ClusterSimulator
    from cruise_control_tpu.utils.tracing import TRACER
    with TRACER._lock:
        prev_enabled = TRACER._enabled
        prev_path = TRACER._jsonl_path
    sentinel = "/tmp/host-trace-sentinel.jsonl"
    TRACER.configure(enabled=False, jsonl_path=sentinel)
    try:
        ClusterSimulator(CANONICAL_SCENARIOS["broker_loss_drift"], seed=0)
        with TRACER._lock:
            assert TRACER._enabled is False
            assert TRACER._jsonl_path == sentinel
    finally:
        TRACER.configure(enabled=prev_enabled, jsonl_path=prev_path)


def test_ticks_override_truncates_and_unknown_scenario_raises():
    r = run_scenario("broker_loss_drift", seed=0, ticks=10)
    assert r.score.as_dict()["ticks"] == 10
    with pytest.raises(ValueError, match="unknown scenario"):
        run_scenario("definitely_not_a_scenario")


@pytest.mark.slow
@pytest.mark.parametrize("name,digest", [
    ("rolling_maintenance", "20978f4d"),
    ("capacity_heterogeneity", "265784f8"),
])
def test_slow_scenarios_seed_pinned(name, digest):
    r = _run(name, 0)
    d = r.score.as_dict()
    assert r.assignment_digest == digest
    assert d["balancedness"]["final"] == 100.0
    assert d["deadLetters"] == 0
    assert d["sloViolations"] == []


# ---------------------------------------------------------------------------
# ?what_if= — the time-dimension extension of the PROPOSALS dry run
# ---------------------------------------------------------------------------

@pytest.fixture()
def what_if_api():
    from cruise_control_tpu.api.server import CruiseControlApi
    backend = _backend(brokers=4, topics=2, parts=4)
    cfg = CruiseControlConfig({
        "partition.metrics.window.ms": 1000,
        "num.partition.metrics.windows": 3,
        "min.valid.partition.ratio": 0.0,
        "failed.brokers.file.path": ""})
    caps = StaticCapacityResolver({}, {Resource.CPU: 100.0,
                                       Resource.DISK: 1e7,
                                       Resource.NW_IN: 1e6,
                                       Resource.NW_OUT: 1e6})
    monitor = LoadMonitor(cfg, backend, samplers=[SyntheticSampler()],
                          capacity_resolver=caps)
    cc = CruiseControl(cfg, backend, load_monitor=monitor,
                       executor=Executor(backend, synchronous=True))
    api = CruiseControlApi(cc)
    api._async_wait_s = 300     # cover first-compile of the sim's shapes
    yield api, cc
    api.shutdown()


def test_what_if_returns_scored_trajectory_without_executing(what_if_api):
    api, cc = what_if_api
    before = cc.executor.execution_state()
    status, body, _ = api.handle(
        "GET", "/kafkacruisecontrol/proposals",
        "what_if=broker_loss_drift&what_if_ticks=30&what_if_seed=1")
    assert status == 200
    assert body["scenario"] == "broker_loss_drift"
    assert body["ticks"] == 30 and body["seed"] == 1
    assert body["dryrun"] is True and body["executed"] is False
    assert body["score"]["ticks"] == 30
    assert body["score"]["churn"]["replicaMoves"] >= 0
    assert body["finalAssignmentDigest"]
    assert body["events"] == [
        {"tick": 23, "kind": "kill_broker", "params": {"broker": 5}}]
    # The replay ran on its OWN twin: this cluster's executor state is
    # untouched and its partitions unmoved.
    assert cc.executor.execution_state() == before


def test_what_if_rejects_unknown_scenario(what_if_api):
    api, _cc = what_if_api
    status, body, _ = api.handle("GET", "/kafkacruisecontrol/proposals",
                                 "what_if=nope")
    assert status == 400
    assert "unknown what_if scenario" in json.dumps(body)


def test_what_if_rejects_requires_live_template(what_if_api):
    """A requires_live futures template (forecast_horizon) has no
    standalone replay spec — its content lives in the evaluator's live
    seam, so replaying its bare renamed BASE_SPEC would serve a
    meaningless trajectory under the template's name. 400, pointing at
    COMPARE_FUTURES (the surface that answers it)."""
    api, _cc = what_if_api
    status, body, _ = api.handle(
        "GET", "/kafkacruisecontrol/proposals",
        "what_if=random:forecast_horizon:0")
    assert status == 400
    assert "requires the live-cluster seam" in json.dumps(body)


def test_what_if_tick_cap_is_enforced():
    from cruise_control_tpu.api.server import CruiseControlApi
    backend = _backend()
    cfg = CruiseControlConfig({"failed.brokers.file.path": "",
                               "scenario.what.if.max.ticks": 5})
    cc = CruiseControl(cfg, backend)
    api = CruiseControlApi(cc)
    api._async_wait_s = 300
    try:
        status, body, _ = api.handle(
            "GET", "/kafkacruisecontrol/proposals",
            "what_if=broker_loss_drift&what_if_ticks=50")
        assert status == 200
        assert body["ticks"] == 5, "cap must bound requested ticks"
        assert body["score"]["ticks"] == 5
    finally:
        api.shutdown()


# ---------------------------------------------------------------------------
# staleness_s on the degraded-serving path (round 9's stale=true)
# ---------------------------------------------------------------------------

def test_stale_proposal_response_carries_staleness_age():
    backend = _backend(brokers=4, topics=2, parts=4)
    cfg = CruiseControlConfig({
        "partition.metrics.window.ms": 1000,
        "num.partition.metrics.windows": 3,
        "min.valid.partition.ratio": 0.0,
        "failed.brokers.file.path": ""})
    caps = StaticCapacityResolver({}, {Resource.CPU: 100.0,
                                       Resource.DISK: 1e7,
                                       Resource.NW_IN: 1e6,
                                       Resource.NW_OUT: 1e6})
    monitor = LoadMonitor(cfg, backend, samplers=[SyntheticSampler()],
                          capacity_resolver=caps)
    cc = CruiseControl(cfg, backend, load_monitor=monitor,
                       executor=Executor(backend, synchronous=True))
    for k in range(1, 4):
        monitor.task_runner.run_sampling_once(end_ms=k * 1000)
    good = cc.proposals()
    assert not good.extra.get("stale")

    def explode(*a, **k):
        raise RuntimeError("model build failed")

    cc._optimizer.optimizations = explode
    monitor.task_runner.run_sampling_once(end_ms=5000)
    res = cc.proposals()
    assert res.extra["stale"] is True
    age = res.extra["staleness_s"]
    assert isinstance(age, float) and age >= 0.0, \
        "degraded serving must expose its duration to the SLO scorer"
