"""Megastep dispatch (analyzer/chain.py round-10 machinery): donated
multi-round dispatches, async stats readback, deficit-aware count-goal
sizing.

The load-bearing contract is dispatch-boundary invariance: the bounded
megastep path must walk the BYTE-IDENTICAL trajectory of the per-round
bounded path and of the fused whole-chain kernel, for any dispatch budget
K, with async readback on or off, at any padded bucket size — only the
XLA-execution boundaries and readback timing may differ.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.analyzer.chain import (
    AdaptiveDispatch, DispatchStats, MegastepConfig, chain_optimize_rounds,
    deficit_sized_config, donation_enabled, optimize_chain,
    optimize_goal_in_chain, run_bounded_pass, strip_mutable,
)
from cruise_control_tpu.analyzer.constraint import BalancingConstraint
from cruise_control_tpu.analyzer.goals import (
    NetworkOutboundUsageDistributionGoal, PreferredLeaderElectionGoal,
    RackAwareGoal, ReplicaCapacityGoal, ReplicaDistributionGoal,
    TopicReplicaDistributionGoal,
)
from cruise_control_tpu.analyzer.search import ExclusionMasks, SearchConfig
from cruise_control_tpu.model.fixtures import random_cluster

CHAIN = (RackAwareGoal(), ReplicaCapacityGoal(),
         NetworkOutboundUsageDistributionGoal(), ReplicaDistributionGoal(),
         PreferredLeaderElectionGoal())
CFG = SearchConfig(num_sources=32, num_dests=8, moves_per_round=32,
                   max_rounds=60)
MEGA = MegastepConfig(donate=True, async_readback=True, deficit_moves_cap=0)


def _cluster(partition_bucket: int = 0):
    return random_cluster(num_brokers=12, num_topics=6, num_partitions=96,
                          rf=2, num_racks=3, seed=3, skew_to_first=2.0,
                          partition_bucket=partition_bucket)


def _run_chain(state, meta, masks, megastep, dispatch_rounds):
    infos = []
    for i in range(len(CHAIN)):
        state, info = optimize_goal_in_chain(
            state, CHAIN, i, BalancingConstraint(), CFG, meta.num_topics,
            masks, dispatch_rounds=dispatch_rounds, megastep=megastep,
            donate_input=infos and any(x["rounds"] > 0 for x in infos))
        infos.append(info)
    return state, infos


# The two pinned bucket sizes: 32 keeps P=96 unpadded, 128 pads to 128
# rows — the megastep path must be trajectory-exact on padded shapes too
# (pad partitions are masked, never moved).
@pytest.mark.parametrize("bucket", [32, 128])
def test_megastep_parity_per_round_vs_k_vs_fused(bucket):
    state, meta = _cluster(partition_bucket=bucket)
    masks = ExclusionMasks()
    # Reference: per-round dispatching (K=1, synchronous, no donation).
    ref_state, ref_infos = _run_chain(
        state, meta, masks,
        MegastepConfig(donate=False, async_readback=False), 1)
    # Fused whole-chain kernel.
    fused_state, _ = optimize_chain(state, CHAIN, BalancingConstraint(),
                                    CFG, meta.num_topics, masks)
    np.testing.assert_array_equal(np.asarray(fused_state.assignment),
                                  np.asarray(ref_state.assignment))
    # Megasteps at two K values, async readback + donation requested
    # (donation resolves to off on this CPU backend — the gate under test
    # in test_donation_gated_off_on_zero_copy_backend).
    for k in (4, 64):
        st, infos = _run_chain(state, meta, masks, MEGA, k)
        np.testing.assert_array_equal(np.asarray(st.assignment),
                                      np.asarray(ref_state.assignment))
        np.testing.assert_array_equal(np.asarray(st.leader_slot),
                                      np.asarray(ref_state.leader_slot))
        for a, b in zip(ref_infos, infos):
            assert a["moves_applied"] == b["moves_applied"], (k, a["goal"])
            assert a["succeeded"] == b["succeeded"], (k, a["goal"])


def test_deficit_sizing_invariant_across_dispatch_budgets():
    """Deficit-aware sizing reads only the goal's ENTRY violations, so the
    sized trajectory is identical for any dispatch-budget sequence."""
    state, meta = _cluster()
    masks = ExclusionMasks()
    mega = MegastepConfig(donate=False, async_readback=True,
                          deficit_moves_cap=256)
    st1, infos1 = _run_chain(state, meta, masks, mega, 1)
    st2, infos2 = _run_chain(state, meta, masks, mega, 16)
    np.testing.assert_array_equal(np.asarray(st1.assignment),
                                  np.asarray(st2.assignment))
    for a, b in zip(infos1, infos2):
        assert a["moves_applied"] == b["moves_applied"], a["goal"]


def test_on_device_early_exit_freezes_state():
    """A megastep dispatched on an already-converged state must run exactly
    ONE zero-apply round (the while_loop's early-exit flag) and return the
    state byte-identical — the guarantee the async pump's speculative
    post-convergence dispatch relies on."""
    state, meta = _cluster()
    masks = ExclusionMasks()
    constraint = BalancingConstraint()
    st = state
    for i in range(len(CHAIN)):
        st, _ = optimize_goal_in_chain(st, CHAIN, i, constraint, CFG,
                                       meta.num_topics, masks)
    before = np.asarray(st.assignment).copy()
    for i in range(len(CHAIN)):
        new_st, moves, rounds = chain_optimize_rounds(
            st, jnp.int32(i), jnp.asarray([j < i for j in range(len(CHAIN))]),
            CHAIN, constraint, CFG, meta.num_topics, masks,
            budget=jnp.int32(50))
        assert int(rounds) == 1, CHAIN[i].name
        assert int(moves) == 0, CHAIN[i].name
        np.testing.assert_array_equal(np.asarray(new_st.assignment), before)


def test_donation_gated_off_on_zero_copy_backend():
    """model/refresh.py's snapshot rule: on CPU, device arrays may alias
    host buffers the model pipeline still owns, so the megastep path must
    refuse donation there — the input state stays alive and readable after
    a full bounded run with donation REQUESTED."""
    assert jax.default_backend() == "cpu"
    assert not donation_enabled(MegastepConfig(donate=True))
    assert not donation_enabled(None)
    state, meta = _cluster()
    host_assignment = np.asarray(state.assignment).copy()
    st, _ = _run_chain(state, meta, ExclusionMasks(),
                       MegastepConfig(donate=True, async_readback=True), 4)
    # The ORIGINAL state must not have been donated/deleted or mutated.
    np.testing.assert_array_equal(np.asarray(state.assignment),
                                  host_assignment)


def test_strip_mutable_excludes_topology_from_donation_set():
    state, _meta = _cluster()
    rest = strip_mutable(state)
    assert rest.assignment.shape == (0, state.max_replication_factor)
    assert rest.leader_slot.shape == (0,)
    # Topology leaves are passed through UNTOUCHED (same arrays — they are
    # exactly the buffers the model cache shares across generations).
    assert rest.topic is state.topic
    assert rest.capacity is state.capacity
    merged = dataclasses.replace(rest, assignment=state.assignment,
                                 leader_slot=state.leader_slot)
    np.testing.assert_array_equal(np.asarray(merged.assignment),
                                  np.asarray(state.assignment))


class _Script:
    """Fake dispatch kernel: a pass that applies moves for ``work`` rounds
    then reaches its fixed point (every later round applies 0)."""

    def __init__(self, work: int):
        self.work = work
        self.done = 0
        self.enqueued: list[int] = []

    def __call__(self, st, budget: int):
        self.enqueued.append(budget)
        rounds = 0
        applied = 0
        remaining = max(0, self.work - self.done)
        if remaining == 0:
            rounds = 1          # the terminal zero-apply round re-runs
        else:
            rounds = min(budget, remaining)
            applied = rounds
            self.done += rounds
            if rounds < budget:
                rounds += 1     # the in-dispatch zero-apply round
                rounds = min(rounds, budget)
        return st + applied, applied, rounds, False, None


class _SpyController(AdaptiveDispatch):
    def __init__(self, k):
        super().__init__(k, target_s=0.0)
        self.events: list[tuple] = []

    def budget(self, remaining: int) -> int:
        b = super().budget(remaining)
        self.events.append(("budget", b))
        return b

    def observe(self, rounds_run, budget, elapsed_s):
        self.events.append(("observe", rounds_run, budget))
        super().observe(rounds_run, budget, elapsed_s)


def test_async_pump_one_behind_and_speculative_drain():
    """Async readback keeps one dispatch in flight: the controller observes
    dispatch N only AFTER dispatch N+1's budget was requested (the
    staleness contract), and the speculative post-convergence dispatch is
    drained WITHOUT touching the pass totals — it applies nothing and its
    round must not be counted, or the async path would burn cfg.max_rounds
    budget the synchronous path does not."""
    script = _Script(work=5)
    ctl = _SpyController(2)
    st, applied, rounds = run_bounded_pass(script, 0, 100, ctl,
                                           async_readback=True)
    assert st == 5 and applied == 5
    # 4 real dispatches (2+2+[1+zero round]+[terminal zero round]) + 1
    # speculative zero-apply re-run enqueued while the 4th was unread.
    assert script.enqueued == [2, 2, 2, 2, 2]
    # Pass totals match the sync path exactly: the speculative dispatch
    # contributes zero rounds.
    assert rounds == 2 + 2 + 2 + 1
    # One-behind: the first observe lands after the SECOND budget request.
    kinds = [e[0] for e in ctl.events]
    assert kinds[:3] == ["budget", "budget", "observe"]


def test_sync_pump_reads_before_enqueueing():
    script = _Script(work=5)
    ctl = _SpyController(2)
    st, applied, rounds = run_bounded_pass(script, 0, 100, ctl,
                                           async_readback=False)
    assert st == 5 and applied == 5
    assert script.enqueued == [2, 2, 2, 2]   # no speculative dispatch
    assert rounds == 2 + 2 + 2 + 1
    kinds = [e[0] for e in ctl.events]
    assert kinds[:3] == ["budget", "observe", "budget"]


def test_pump_never_overshoots_pass_cap():
    for async_rb in (False, True):
        script = _Script(work=1000)
        ctl = _SpyController(8)
        _st, applied, rounds = run_bounded_pass(script, 0, 20, ctl,
                                                async_readback=async_rb)
        assert applied == 20 and rounds == 20, async_rb
        assert sum(script.enqueued) <= 24     # ≤ cap + one in-flight budget


def test_deficit_sized_config_quantization():
    cfg = SearchConfig(num_sources=64, num_dests=16, moves_per_round=32,
                       max_rounds=100)
    # Small violations: no resize (the configured width already covers it).
    assert deficit_sized_config(cfg, 40.0, 2048) is cfg
    # ~50 moves needed -> next pow2 (64).
    sized = deficit_sized_config(cfg, 100.0, 2048)
    assert sized.moves_per_round == 64 and sized.num_sources == 64
    assert sized.num_dests == 16 and sized.max_rounds == 100
    # Huge imbalance: capped.
    sized = deficit_sized_config(cfg, 1_000_000.0, 2048)
    assert sized.moves_per_round == 2048 and sized.num_sources == 2048
    # Cap 0 disables via the caller gate; the function itself floors at cfg.
    assert deficit_sized_config(cfg, 10.0, 2048) is cfg
    # count_based is set on exactly the three count-distribution goals.
    assert ReplicaDistributionGoal().count_based
    assert TopicReplicaDistributionGoal().count_based
    assert not RackAwareGoal().count_based
    assert not NetworkOutboundUsageDistributionGoal().count_based


def test_dispatch_stats_accounting():
    s = DispatchStats()
    for r in (16, 2, 8):
        s.record("move", r)
    s.record("swap", 1, donated=True, speculative=True)
    d = s.as_dict()
    assert d["dispatch_count"] == 4
    assert d["rounds_per_dispatch_p50"] == 2.0   # lower median of [1,2,8,16]
    assert d["donated_dispatches"] == 1
    assert d["speculative_dispatches"] == 1


def test_optimizer_reports_dispatch_stats():
    from cruise_control_tpu.analyzer.optimizer import (
        GoalOptimizer, goals_by_priority,
    )
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    state, meta = random_cluster(num_brokers=12, num_topics=6,
                                 num_partitions=240, rf=2, num_racks=4,
                                 seed=3, target_utilization=0.5)
    cfg = CruiseControlConfig({"solver.fused.chain.max.brokers": "8",
                               "solver.dispatch.max.rounds": "4"})
    opt = GoalOptimizer(cfg)
    assert opt.last_dispatch_stats() == {}
    opt.optimizations(state, meta, goals=goals_by_priority(cfg))
    ds = opt.last_dispatch_stats()
    assert ds["dispatch_count"] > 0
    assert ds["rounds_per_dispatch_p50"] >= 1.0
    # Fused path records the whole chain as one dispatch.
    opt_fused = GoalOptimizer(CruiseControlConfig())
    opt_fused.optimizations(state, meta, goals=goals_by_priority(
        CruiseControlConfig()))
    assert opt_fused.last_dispatch_stats()["dispatch_count"] == 1
