"""Detector layer: anomaly taxonomy, notifier escalation, detectors, the
manager pipeline, and facade self-healing dispatch (reference parity:
detector/ + notifier/ — AnomalyDetectorManagerTest, SlowBrokerFinderTest,
BrokerFailureDetectorTest ideas re-expressed against the tensor stack)."""

import time

import numpy as np
import pytest

from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
from cruise_control_tpu.detector import (
    AnomalyDetectorManager, AnomalyStatus, AnomalyType, BrokerFailureDetector,
    BrokerFailures, DiskFailureDetector, GoalViolations, IdempotenceCache,
    InMemoryMaintenanceEventReader, MaintenanceEvent, MaintenanceEventDetector,
    MaintenanceEventType, MetricAnomaly, NoopNotifier,
    PercentileMetricAnomalyFinder, SelfHealingNotifier,
    SlackSelfHealingNotifier, SlowBrokerFinder, TopicAnomalyDetector,
)
from cruise_control_tpu.detector.notifier import AnomalyNotificationAction
from cruise_control_tpu.executor.admin import InMemoryAdminBackend, PartitionState
from cruise_control_tpu.metricdef.kafka_metric_def import (
    BrokerMetric, CommonMetric, KafkaMetricDef,
)
from cruise_control_tpu.monitor.aggregator.aggregator import MetricSampleAggregator
from cruise_control_tpu.monitor.sampling.samples import BrokerEntity


def _partitions(brokers=(0, 1, 2), n=4, rf=2):
    out = {}
    for p in range(n):
        reps = tuple(brokers[(p + i) % len(brokers)] for i in range(rf))
        out[("t0", p)] = PartitionState("t0", p, reps, reps[0], isr=reps)
    return out


class RecordingFacade:
    """Captures the self-healing operations an anomaly fix dispatches."""

    def __init__(self):
        self.calls = []

    def ready_for_self_healing(self):
        return True

    def alive_brokers(self):
        return set(getattr(self, "_alive", ()))

    def __getattr__(self, name):
        def record(*a, **kw):
            self.calls.append((name, a, kw))
        return record


# ---- notifier escalation -------------------------------------------------

def test_self_healing_notifier_broker_failure_escalation():
    now = [1_000_000]
    cfg = CruiseControlConfig({"self.healing.enabled": True,
                               "broker.failure.self.healing.threshold.ms": 1000})
    n = SelfHealingNotifier(cfg, now_ms=lambda: now[0])
    n._alert_threshold_ms = 500
    anomaly = BrokerFailures(failed_brokers={7: 1_000_000})
    # Fresh failure → re-check before alerting.
    r = n.on_anomaly(anomaly)
    assert r.action is AnomalyNotificationAction.CHECK and r.delay_ms == 500
    # Past alert threshold, before fix threshold → alert + re-check.
    now[0] += 600
    r = n.on_anomaly(anomaly)
    assert r.action is AnomalyNotificationAction.CHECK
    # Past the self-healing threshold → FIX.
    now[0] += 600
    assert n.on_anomaly(anomaly).action is AnomalyNotificationAction.FIX


def test_self_healing_notifier_respects_per_type_flags():
    cfg = CruiseControlConfig({"self.healing.enabled": True,
                               "self.healing.goal.violation.enabled": False})
    n = SelfHealingNotifier(cfg)
    r = n.on_anomaly(GoalViolations(fixable_goals=["G"]))
    assert r.action is AnomalyNotificationAction.IGNORE
    assert n.set_self_healing_for(AnomalyType.GOAL_VIOLATION, True) is False
    assert n.on_anomaly(GoalViolations(fixable_goals=["G"])).action \
        is AnomalyNotificationAction.FIX


def test_slack_notifier_posts_payload():
    posts = []
    cfg = CruiseControlConfig({"self.healing.enabled": True})
    n = SlackSelfHealingNotifier(cfg, webhook_url="http://hook",
                                 http_post=lambda url, payload: posts.append(
                                     (url, payload)) or 200)
    n.on_anomaly(GoalViolations(fixable_goals=["RackAwareGoal"]))
    (url, payload), = posts
    assert url == "http://hook" and "RackAwareGoal" in payload["text"]


# ---- broker failure detector --------------------------------------------

def test_broker_failure_detector_detects_and_persists(tmp_path):
    path = str(tmp_path / "failed_brokers.json")
    backend = InMemoryAdminBackend(_partitions().values())
    seen = []
    det = BrokerFailureDetector(backend, seen.append, path,
                                now_ms=lambda: 42_000)
    assert det.run_once() is None and not seen
    backend.kill_broker(2)
    anomaly = det.run_once()
    assert anomaly.failed_brokers == {2: 42_000}
    # A fresh detector (restart) remembers the original failure time.
    det2 = BrokerFailureDetector(backend, seen.append, path,
                                 now_ms=lambda: 99_000)
    assert det2.failed_brokers == {2: 42_000}
    # Revival clears the record.
    backend.revive_broker(2)
    assert det2.run_once() is None
    assert det2.failed_brokers == {}


# ---- disk failure detector ----------------------------------------------

def test_disk_failure_detector_reads_logdirs():
    backend = InMemoryAdminBackend(_partitions().values())
    backend.describe_logdirs = lambda: {0: {"/d0": True, "/d1": False},
                                        1: {"/d0": True}}
    seen = []
    det = DiskFailureDetector(backend, seen.append)
    anomaly = det.run_once()
    assert anomaly.failed_disks == {0: ["/d1"]}
    # Unchanged offline set is not re-reported.
    assert det.run_once() is None


# ---- metric anomaly finders ---------------------------------------------

def _broker_agg(num_windows=8):
    return MetricSampleAggregator(
        num_windows=num_windows, window_ms=1000, min_samples_per_window=1,
        metric_def=KafkaMetricDef.broker_metric_def())


def _fill_broker_windows(agg, values_by_broker, windows=7):
    # One extra window past the spike: the aggregator only reports STABLE
    # windows (the in-fill current window is excluded, reference semantics),
    # so the last series value must land in a stable window.
    mdef = KafkaMetricDef.broker_metric_def()
    m = mdef.num_metrics
    flush = mdef.metric_info(BrokerMetric.BROKER_LOG_FLUSH_TIME_MS_999TH.name).id
    bytes_in = mdef.metric_info(CommonMetric.LEADER_BYTES_IN.name).id
    for w in range(windows):
        for b, (flush_series, bin_rate) in values_by_broker.items():
            row = np.full(m, 1.0)
            row[flush] = flush_series[min(w, len(flush_series) - 1)]
            row[bytes_in] = bin_rate
            agg.add_sample(BrokerEntity(b), w * 1000 + 500, row)


def test_percentile_finder_flags_latest_window_outlier():
    agg = _broker_agg()
    # Broker 0 spikes in the latest window; broker 1 stays flat.
    _fill_broker_windows(agg, {0: ([10, 10, 10, 10, 10, 500], 1e5),
                               1: ([10] * 6, 1e5)})
    finder = PercentileMetricAnomalyFinder(CruiseControlConfig())
    anomalies = finder.find_anomalies(agg)
    assert any(a.broker_ids == [0] and "above" in a.description
               for a in anomalies)
    assert not any(a.broker_ids == [1] for a in anomalies)


def test_slow_broker_finder_escalates_demote_then_remove():
    finder = SlowBrokerFinder(CruiseControlConfig(), demote_score=2,
                              removal_score=4)
    demoted = removed = False
    for _round in range(6):
        agg = _broker_agg()
        _fill_broker_windows(agg, {0: ([10, 10, 10, 10, 10, 900], 1e6),
                                   1: ([10] * 6, 1e6),
                                   2: ([10] * 6, 1e6)})
        for a in finder.find_anomalies(agg):
            if a.fix_by_removal:
                removed = True
                assert a.broker_ids == [0]
            else:
                demoted = True
                assert a.broker_ids == [0]
    assert demoted and removed


# ---- topic anomaly -------------------------------------------------------

def test_topic_rf_anomaly_finder():
    backend = InMemoryAdminBackend(_partitions(rf=2).values())
    seen = []
    det = TopicAnomalyDetector(backend, seen.append, desired_rf=3)
    anomaly = det.run_once()
    assert anomaly.topics_by_desired_rf == {3: ["t0"]}


# ---- maintenance events --------------------------------------------------

def test_maintenance_event_idempotence_and_dispatch():
    reader = InMemoryMaintenanceEventReader()
    seen = []
    det = MaintenanceEventDetector(reader, seen.append)
    ev = MaintenanceEvent(event_type=MaintenanceEventType.REMOVE_BROKER,
                          broker_ids=[3])
    reader.submit(ev)
    reader.submit(MaintenanceEvent(
        event_type=MaintenanceEventType.REMOVE_BROKER, broker_ids=[3]))
    assert len(det.run_once()) == 1          # duplicate dropped
    facade = RecordingFacade()
    assert ev.fix(facade)
    (name, args, _kw), = facade.calls
    assert name == "remove_brokers" and args[0] == [3]


def test_idempotence_cache_expires():
    now = [0]
    cache = IdempotenceCache(retention_ms=100, now_ms=lambda: now[0])
    e = MaintenanceEvent(event_type=MaintenanceEventType.REBALANCE)
    assert not cache.is_duplicate(e)
    assert cache.is_duplicate(e)
    now[0] = 500
    assert not cache.is_duplicate(e)


# ---- anomaly fix dispatch ------------------------------------------------

def test_anomaly_fixes_dispatch_to_facade_methods():
    facade = RecordingFacade()
    BrokerFailures(failed_brokers={1: 0}).fix(facade)
    GoalViolations(fixable_goals=["G"]).fix(facade)
    MetricAnomaly(broker_ids=[2], fix_by_removal=False).fix(facade)
    names = [c[0] for c in facade.calls]
    assert names == ["remove_brokers", "rebalance", "demote_brokers"]


# ---- manager pipeline ----------------------------------------------------

def test_manager_priority_order_and_fix_pipeline():
    facade = RecordingFacade()
    cfg = CruiseControlConfig({"self.healing.enabled": True,
                               "broker.failure.self.healing.threshold.ms": 0})
    notifier = SelfHealingNotifier(cfg)
    notifier._alert_threshold_ms = 0
    mgr = AnomalyDetectorManager(cfg, notifier, facade=facade)
    # Goal violation reported first, broker failure second — broker failure
    # has higher priority and must be handled first.
    mgr.report(GoalViolations(fixable_goals=["G"]))
    mgr.report(BrokerFailures(failed_brokers={5: 0}))
    first = mgr._take(timeout_s=0.1)
    assert isinstance(first, BrokerFailures)
    assert mgr.handle_anomaly(first) == AnomalyStatus.FIX_STARTED
    second = mgr._take(timeout_s=0.1)
    assert isinstance(second, GoalViolations)
    assert mgr.handle_anomaly(second) == AnomalyStatus.FIX_STARTED
    assert [c[0] for c in facade.calls] == ["remove_brokers", "rebalance"]
    st = mgr.state()
    assert st["metrics"]["numSelfHealingStarted"] == 2
    assert {r["status"] for r in st["recentAnomalies"]} == {AnomalyStatus.FIX_STARTED}


def test_manager_check_with_delay_requeues():
    cfg = CruiseControlConfig({"self.healing.enabled": True,
                               "broker.failure.self.healing.threshold.ms": 10_000})
    notifier = SelfHealingNotifier(cfg)
    mgr = AnomalyDetectorManager(cfg, notifier, facade=RecordingFacade())
    anomaly = BrokerFailures(failed_brokers={1: int(time.time() * 1000)})
    mgr.report(anomaly)
    taken = mgr._take(timeout_s=0.1)
    assert mgr.handle_anomaly(taken) == AnomalyStatus.CHECK_WITH_DELAY
    # The recheck is scheduled in the future, so an immediate take times out.
    assert mgr._take(timeout_s=0.05) is None
    assert len(mgr._recheck) == 1


def test_manager_drops_stale_recheck_when_broker_recovers():
    cfg = CruiseControlConfig({"self.healing.enabled": True,
                               "broker.failure.self.healing.threshold.ms": 10_000})
    facade = RecordingFacade()
    mgr = AnomalyDetectorManager(cfg, SelfHealingNotifier(cfg), facade=facade)
    anomaly = BrokerFailures(failed_brokers={1: int(time.time() * 1000)})
    mgr.report(anomaly)
    assert mgr.handle_anomaly(mgr._take(timeout_s=0.1)) \
        == AnomalyStatus.CHECK_WITH_DELAY
    # Broker 1 recovers; force the recheck due and take again → dropped.
    facade._alive = {1}
    mgr._recheck = [(time.time() - 1, a) for _t, a in mgr._recheck]
    assert mgr._take(timeout_s=0.05) is None
    assert not mgr._recheck
    assert not facade.calls, "no fix may run for a recovered broker"


def test_manager_runs_detector_threads():
    cfg = CruiseControlConfig({"self.healing.enabled": True})

    class TickDetector:
        def __init__(self, report):
            self.report = report

        def run_once(self):
            self.report(GoalViolations(fixable_goals=["G"]))

    mgr = AnomalyDetectorManager(cfg, NoopNotifier(), facade=RecordingFacade())
    mgr.add_detector(TickDetector(mgr.report), interval_ms=20)
    mgr.start_detection()
    try:
        deadline = time.time() + 3
        while time.time() < deadline and not mgr.state()["recentAnomalies"]:
            time.sleep(0.02)
    finally:
        mgr.shutdown()
    assert mgr.state()["recentAnomalies"]


# ---- maintenance plan serde + topic reader ---------------------------------

def test_maintenance_plan_serde_round_trip():
    from cruise_control_tpu.detector.anomaly import (
        MaintenanceEvent, MaintenanceEventType,
    )
    from cruise_control_tpu.detector.maintenance_serde import (
        deserialize_plan, serialize_plan,
    )

    event = MaintenanceEvent(
        event_type=MaintenanceEventType.TOPIC_REPLICATION_FACTOR,
        broker_ids=[3, 1], topics_by_rf={3: ["t2", "t1"]})
    back = deserialize_plan(serialize_plan(event, time_ms=123))
    assert back.event_type is MaintenanceEventType.TOPIC_REPLICATION_FACTOR
    assert sorted(back.broker_ids) == [1, 3]
    assert back.topics_by_rf == {3: ["t1", "t2"]}


def test_maintenance_plan_serde_rejects_bad_envelopes():
    import json

    import pytest

    from cruise_control_tpu.detector.anomaly import (
        MaintenanceEvent, MaintenanceEventType,
    )
    from cruise_control_tpu.detector.maintenance_serde import (
        PlanSerdeError, deserialize_plan, serialize_plan,
    )

    good = serialize_plan(MaintenanceEvent(
        event_type=MaintenanceEventType.REMOVE_BROKER, broker_ids=[5]))
    d = json.loads(good)
    # Corrupt content: crc must catch it.
    d["content"]["brokers"] = [6]
    with pytest.raises(PlanSerdeError, match="crc"):
        deserialize_plan(json.dumps(d).encode())
    # Unsupported (future) version.
    d2 = json.loads(good)
    d2["version"] = 99
    with pytest.raises(PlanSerdeError, match="version"):
        deserialize_plan(json.dumps(d2).encode())
    # Unknown type.
    d3 = json.loads(good)
    d3["planType"] = "DESTROY_CLUSTER"
    with pytest.raises(PlanSerdeError, match="unknown"):
        deserialize_plan(json.dumps(d3).encode())


def test_topic_reader_feeds_detector_and_drops_corrupt_plans():
    """MaintenanceEventDetector consuming from the (fake) topic transport:
    good plans reported once (idempotence cache), corrupt ones skipped."""
    from cruise_control_tpu.detector.anomaly import (
        MaintenanceEvent, MaintenanceEventType,
    )
    from cruise_control_tpu.detector.maintenance import (
        MaintenanceEventDetector,
    )
    from cruise_control_tpu.detector.maintenance_serde import (
        TopicMaintenanceEventReader, serialize_plan,
    )

    class FakeTransport:
        def __init__(self):
            self.records = []

        def poll(self, start_ms, end_ms):
            out, self.records = self.records, []
            return out

    transport = FakeTransport()
    reader = TopicMaintenanceEventReader(transport)
    reported = []
    detector = MaintenanceEventDetector(reader, reported.append)

    plan = MaintenanceEvent(event_type=MaintenanceEventType.REMOVE_BROKER,
                            broker_ids=[7])
    transport.records = [serialize_plan(plan, time_ms=1),
                         b"not-json", serialize_plan(plan, time_ms=1)]
    out = detector.run_once()
    assert len(out) == 1
    assert reported[0].broker_ids == [7]
    # Same plan re-submitted within the idempotence window: dropped.
    transport.records = [serialize_plan(plan, time_ms=1)]
    assert detector.run_once() == []


def test_options_generator_merges_excluded_topics_regex():
    """topics.excluded.from.partition.movement must flow into the options
    the generator produces (KafkaCruiseControlUtils.excludedTopics)."""
    from cruise_control_tpu.analyzer.plugins import (
        DefaultOptimizationOptionsGenerator, options_generator_from_config,
    )

    cfg = CruiseControlConfig(
        {"topics.excluded.from.partition.movement": "__.*"})
    gen = options_generator_from_config(cfg)
    assert isinstance(gen, DefaultOptimizationOptionsGenerator)
    topics = ["__consumer_offsets", "orders", "__CruiseControlMetrics"]
    opts = gen.for_goal_violation_detection(topics, ("orders",), [1], [2])
    assert set(opts.excluded_topics) == {"__consumer_offsets", "orders",
                                         "__CruiseControlMetrics"}
    assert opts.excluded_brokers_for_leadership == (1,)
    assert opts.excluded_brokers_for_replica_move == (2,)
    assert opts.is_triggered_by_goal_violation
    cached = gen.for_cached_proposal_calculation(topics, ())
    assert set(cached.excluded_topics) == {"__consumer_offsets",
                                           "__CruiseControlMetrics"}
    assert cached.excluded_brokers_for_replica_move == ()


class _CollapseAzMapper:
    """rack id 'rack1-az2' -> 'rack1' (the canonical mapper use case)."""

    def apply(self, rack_id: str) -> str:
        return rack_id.split("-")[0]


def test_rack_id_mapper_is_config_swappable():
    from cruise_control_tpu.analyzer.plugins import (
        NoOpRackAwareGoalRackIdMapper, rack_id_mapper_from_config,
    )

    noop = rack_id_mapper_from_config(CruiseControlConfig())
    assert isinstance(noop, NoOpRackAwareGoalRackIdMapper)
    assert noop.apply("rack1-az2") == "rack1-az2"
    cfg = CruiseControlConfig({
        "rack.aware.goal.rack.id.mapper.class":
            f"{_CollapseAzMapper.__module__}.{_CollapseAzMapper.__qualname__}"})
    mapper = rack_id_mapper_from_config(cfg)
    assert mapper.apply("rack1-az2") == "rack1"
