"""Chain-shared kernel (analyzer/chain.py) vs the per-goal kernels.

The chain kernels must reproduce the per-goal search exactly when the
selection size matches (moves_per_round == num_sources makes the static
top-m identical across both paths), for every (active goal, prior set)
combination — that is the compile-once-run-for-every-goal contract.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from cruise_control_tpu.analyzer.chain import (
    chain_goal_stats, chain_optimize_rounds, optimize_chain,
    optimize_goal_in_chain,
)
from cruise_control_tpu.analyzer.constraint import BalancingConstraint
from cruise_control_tpu.analyzer.goals import (
    LeaderReplicaDistributionGoal, NetworkOutboundUsageDistributionGoal,
    PreferredLeaderElectionGoal, RackAwareGoal, ReplicaCapacityGoal,
    ReplicaDistributionGoal,
)
from cruise_control_tpu.analyzer.search import (
    ExclusionMasks, SearchConfig, optimize_goal, optimize_round,
)
from cruise_control_tpu.model.fixtures import random_cluster

CHAIN = (RackAwareGoal(), ReplicaCapacityGoal(),
         NetworkOutboundUsageDistributionGoal(),
         LeaderReplicaDistributionGoal(), PreferredLeaderElectionGoal())
# moves_per_round == num_sources ⇒ the old path's static top-m equals the
# chain path's max(moves_per_round, num_sources) for every goal.
CFG1 = SearchConfig(num_sources=32, num_dests=8, moves_per_round=32,
                    max_rounds=1)


def _cluster():
    return random_cluster(num_brokers=12, num_topics=6, num_partitions=96,
                          rf=2, num_racks=3, seed=3, skew_to_first=2.0)


def _prior(i):
    return jnp.asarray([j < i for j in range(len(CHAIN))])


@pytest.mark.parametrize("i", range(len(CHAIN)))
def test_single_round_matches_per_goal_kernel(i):
    state, meta = _cluster()
    constraint = BalancingConstraint()
    masks = ExclusionMasks()

    old_state, applied = optimize_round(
        state, CHAIN[i], CHAIN[:i], constraint, CFG1, meta.num_topics, masks)
    new_state, moves, rounds = chain_optimize_rounds(
        state, jnp.int32(i), _prior(i), CHAIN, constraint, CFG1,
        meta.num_topics, masks)

    assert int(rounds) == 1
    assert int(moves) == int(applied)
    np.testing.assert_array_equal(np.asarray(new_state.assignment),
                                  np.asarray(old_state.assignment))
    np.testing.assert_array_equal(np.asarray(new_state.leader_slot),
                                  np.asarray(old_state.leader_slot))


def test_full_chain_driver_matches_per_goal_outcome():
    """Same convergence config ⇒ the chain driver and the per-goal driver
    walk identical trajectories goal by goal."""
    state, meta = _cluster()
    constraint = BalancingConstraint()
    masks = ExclusionMasks()
    cfg = SearchConfig(num_sources=32, num_dests=8, moves_per_round=32,
                       max_rounds=60)

    st_old = state
    for i, g in enumerate(CHAIN):
        st_old, _ = optimize_goal(st_old, g, CHAIN[:i], constraint, cfg,
                                  meta.num_topics, masks)
    st_new = state
    for i in range(len(CHAIN)):
        st_new, _ = optimize_goal_in_chain(st_new, CHAIN, i, constraint, cfg,
                                           meta.num_topics, masks)
    np.testing.assert_array_equal(np.asarray(st_new.assignment),
                                  np.asarray(st_old.assignment))
    np.testing.assert_array_equal(np.asarray(st_new.leader_slot),
                                  np.asarray(st_old.leader_slot))


def test_fused_full_chain_matches_per_goal_chain():
    """chain_optimize_full (one dispatch for the whole chain) must walk the
    same trajectory as optimize_goal_in_chain called per goal, and report
    the same per-goal outcome stats."""
    state, meta = _cluster()
    constraint = BalancingConstraint()
    masks = ExclusionMasks()
    cfg = SearchConfig(num_sources=32, num_dests=8, moves_per_round=32,
                       max_rounds=60)

    st_seq = state
    seq_infos = []
    for i in range(len(CHAIN)):
        st_seq, info = optimize_goal_in_chain(st_seq, CHAIN, i, constraint,
                                              cfg, meta.num_topics, masks)
        seq_infos.append(info)

    st_fused, fused_infos = optimize_chain(state, CHAIN, constraint, cfg,
                                           meta.num_topics, masks)
    np.testing.assert_array_equal(np.asarray(st_fused.assignment),
                                  np.asarray(st_seq.assignment))
    np.testing.assert_array_equal(np.asarray(st_fused.leader_slot),
                                  np.asarray(st_seq.leader_slot))
    for seq, fused in zip(seq_infos, fused_infos):
        assert fused["goal"] == seq["goal"]
        assert fused["succeeded"] == seq["succeeded"]
        assert fused["moves_applied"] == seq["moves_applied"]
        assert fused["swaps_applied"] == seq["swaps_applied"]
        assert fused["residual_violation"] == pytest.approx(
            seq["residual_violation"], rel=1e-5, abs=1e-5)


def test_bounded_dispatch_matches_unbounded():
    """dispatch_rounds caps rounds per XLA execution (the TPU-tunnel
    watchdog mitigation); the host loop must walk the IDENTICAL trajectory
    to the unbounded driver — same final assignment, moves, and swaps."""
    state, meta = _cluster()
    constraint = BalancingConstraint()
    masks = ExclusionMasks()
    cfg = SearchConfig(num_sources=32, num_dests=8, moves_per_round=32,
                       max_rounds=60)

    st_unbounded = state
    infos_unbounded = []
    for i in range(len(CHAIN)):
        st_unbounded, info = optimize_goal_in_chain(
            st_unbounded, CHAIN, i, constraint, cfg, meta.num_topics, masks)
        infos_unbounded.append(info)

    for k in (1, 3):
        st_bounded = state
        infos_bounded = []
        for i in range(len(CHAIN)):
            st_bounded, info = optimize_goal_in_chain(
                st_bounded, CHAIN, i, constraint, cfg, meta.num_topics,
                masks, dispatch_rounds=k)
            infos_bounded.append(info)
        np.testing.assert_array_equal(np.asarray(st_bounded.assignment),
                                      np.asarray(st_unbounded.assignment))
        np.testing.assert_array_equal(np.asarray(st_bounded.leader_slot),
                                      np.asarray(st_unbounded.leader_slot))
        for a, b in zip(infos_unbounded, infos_bounded):
            assert a["moves_applied"] == b["moves_applied"], (k, a["goal"])
            assert a["swaps_applied"] == b["swaps_applied"], (k, a["goal"])
            assert a["succeeded"] == b["succeeded"]


def test_optimizer_switches_to_bounded_path_at_scale():
    """GoalOptimizer must route clusters above solver.fused.chain.max.brokers
    through the bounded per-goal path, with identical results."""
    from cruise_control_tpu.analyzer.optimizer import (
        GoalOptimizer, goals_by_priority,
    )
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.model.fixtures import Dist, random_cluster

    state, meta = random_cluster(num_brokers=12, num_topics=6,
                                 num_partitions=240, rf=2, num_racks=4,
                                 dist=Dist.EXPONENTIAL, seed=3,
                                 target_utilization=0.5)
    cfg_fused = CruiseControlConfig()
    cfg_bounded = CruiseControlConfig(
        {"solver.fused.chain.max.brokers": "8",
         "solver.dispatch.max.rounds": "4"})
    _, res_fused = GoalOptimizer(cfg_fused).optimizations(
        state, meta, goals=goals_by_priority(cfg_fused))
    _, res_bounded = GoalOptimizer(cfg_bounded).optimizations(
        state, meta, goals=goals_by_priority(cfg_bounded))
    assert sorted((p.topic, p.partition) for p in res_bounded.proposals) == \
        sorted((p.topic, p.partition) for p in res_fused.proposals)
    assert res_bounded.balancedness_after == pytest.approx(
        res_fused.balancedness_after)


def test_fused_chain_skips_satisfied_goals():
    """A goal with zero violations and no offline replicas on entry runs
    zero rounds in the fused kernel (the on-device fast path)."""
    state, meta = _cluster()
    constraint = BalancingConstraint()
    masks = ExclusionMasks()
    cfg = SearchConfig(num_sources=32, num_dests=8, moves_per_round=32,
                       max_rounds=60)
    # Converge once, then re-run on the balanced state: every goal that is
    # already satisfied must report 0 rounds.
    st, infos = optimize_chain(state, CHAIN, constraint, cfg,
                               meta.num_topics, masks)
    _st2, infos2 = optimize_chain(st, CHAIN, constraint, cfg,
                                  meta.num_topics, masks)
    for info in infos2:
        if info["residual_violation"] == 0.0:
            assert info["rounds"] == 0, info


def test_moves_per_round_caps_deduped_goals():
    """solver.moves.per.round is a true per-round accept cap for
    broker-deduped goals even though the static selection size is larger."""
    state, meta = _cluster()
    constraint = BalancingConstraint()
    masks = ExclusionMasks()
    cfg = SearchConfig(num_sources=64, num_dests=8, moves_per_round=3,
                       max_rounds=1)
    i = 2  # NetworkOutboundUsageDistributionGoal with two priors: deduped
    _st, moves, rounds = chain_optimize_rounds(
        state, jnp.int32(i), _prior(i), CHAIN, constraint, cfg,
        meta.num_topics, masks)
    assert int(rounds) == 1
    assert int(moves) <= 3


def test_chain_goal_stats_matches_eager():
    state, meta = _cluster()
    constraint = BalancingConstraint()
    masks = ExclusionMasks()
    from cruise_control_tpu.analyzer.derived import compute_derived

    derived = compute_derived(state)
    for i, g in enumerate(CHAIN):
        viol, obj, offline = chain_goal_stats(
            state, jnp.int32(i), CHAIN, constraint, meta.num_topics, masks)
        aux = g.prepare(state, derived, constraint, meta.num_topics)
        expect = float(g.broker_violations(state, derived, constraint,
                                           aux).sum())
        assert float(viol) == pytest.approx(expect, rel=1e-5, abs=1e-5)


def test_chain_satisfies_hard_goals_and_reduces_soft():
    state, meta = _cluster()
    constraint = BalancingConstraint()
    masks = ExclusionMasks()
    cfg = SearchConfig(num_sources=64, num_dests=8, moves_per_round=16,
                       max_rounds=120)
    chain = (RackAwareGoal(), ReplicaCapacityGoal(),
             ReplicaDistributionGoal(),
             NetworkOutboundUsageDistributionGoal())
    st = state
    infos = []
    for i in range(len(chain)):
        st, info = optimize_goal_in_chain(st, chain, i, constraint, cfg,
                                          meta.num_topics, masks)
        infos.append(info)
    assert all(info["succeeded"] for info in infos[:2])  # hard goals
    # Rack invariant: no partition has two replicas on the same rack when
    # racks >= rf (checked via the goal's own violation readback).
    viol, _obj, _ = chain_goal_stats(st, jnp.int32(0), chain, constraint,
                                     meta.num_topics, masks)
    assert float(viol) == 0.0


def test_adaptive_dispatch_sizing():
    """AdaptiveDispatch grows the round budget while full dispatches finish
    under target/2, shrinks above 2x target, never learns from a partial
    dispatch (a pass hitting its fixed point says nothing about cost), and
    never drops below the configured initial budget."""
    from cruise_control_tpu.analyzer.chain import AdaptiveDispatch

    d = AdaptiveDispatch(16, target_s=2.0)
    assert d.budget(1000) == 16
    d.observe(16, 16, 0.5)          # fast full dispatch -> double
    assert d.k == 32
    d.observe(32, 32, 0.5)
    assert d.k == 64
    d.observe(10, 64, 0.1)          # partial dispatch -> unchanged
    assert d.k == 64
    d.observe(64, 64, 5.0)          # overshoot -> halve
    assert d.k == 32
    d.observe(32, 32, 100.0)
    assert d.k == 16                # floors at the initial budget
    d.observe(16, 16, 100.0)
    assert d.k == 16
    assert d.budget(7) == 7         # remaining pass budget caps it
    # target 0 = adaptation disabled entirely.
    d0 = AdaptiveDispatch(8, target_s=0.0)
    d0.observe(8, 8, 0.0001)
    assert d0.k == 8


def test_adaptive_dispatch_trajectory_invariance():
    """The search trajectory must be identical for ANY dispatch-budget
    sequence: an aggressive controller (tiny target, max growth) walks the
    same rounds as fixed-size dispatches, only the XLA-execution boundaries
    differ."""
    from cruise_control_tpu.analyzer.chain import AdaptiveDispatch

    state, meta = _cluster()
    constraint = BalancingConstraint()
    masks = ExclusionMasks()
    cfg = SearchConfig(num_sources=32, num_dests=8, moves_per_round=32,
                       max_rounds=60)

    st_fixed = state
    infos_fixed = []
    for i in range(len(CHAIN)):
        st_fixed, info = optimize_goal_in_chain(
            st_fixed, CHAIN, i, constraint, cfg, meta.num_topics, masks,
            dispatch_rounds=2)
        infos_fixed.append(info)

    controller = AdaptiveDispatch(1, target_s=1e9)   # grows every dispatch
    st_adapt = state
    infos_adapt = []
    for i in range(len(CHAIN)):
        st_adapt, info = optimize_goal_in_chain(
            st_adapt, CHAIN, i, constraint, cfg, meta.num_topics, masks,
            dispatch_rounds=1, dispatch=controller)
        infos_adapt.append(info)
    assert controller.k > 1          # it did grow
    np.testing.assert_array_equal(np.asarray(st_adapt.assignment),
                                  np.asarray(st_fixed.assignment))
    # NOTE: the "rounds" counter is dispatch-boundary-DEPENDENT (the
    # terminal zero-apply round is re-run when a dispatch ends exactly at
    # the fixed point), so only state/moves/outcome are invariant.
    for a, b in zip(infos_fixed, infos_adapt):
        assert a["moves_applied"] == b["moves_applied"], a["goal"]
        assert a["succeeded"] == b["succeeded"], a["goal"]


def test_bounded_single_device_skips_satisfied_goals():
    """Parity with the fused kernel's per-goal fast path: a goal with zero
    violations and no offline replicas on entry reports 0 rounds on the
    bounded per-goal path too (no driver dispatches at all)."""
    state, meta = _cluster()
    constraint = BalancingConstraint()
    masks = ExclusionMasks()
    cfg = SearchConfig(num_sources=32, num_dests=8, moves_per_round=32,
                       max_rounds=60)
    st = state
    for i in range(len(CHAIN)):
        st, _ = optimize_goal_in_chain(st, CHAIN, i, constraint, cfg,
                                       meta.num_topics, masks,
                                       dispatch_rounds=4)
    before = np.asarray(st.assignment).copy()
    for i in range(len(CHAIN)):
        st, info = optimize_goal_in_chain(st, CHAIN, i, constraint, cfg,
                                          meta.num_topics, masks,
                                          dispatch_rounds=4)
        if info["residual_violation"] == 0.0:
            assert info["rounds"] == 0, info
    np.testing.assert_array_equal(np.asarray(st.assignment), before)


def test_wide_batch_config_derivation():
    """Goal.prefers_wide_batches widens the source grid only in regime:
    above solver.wide.batch.min.brokers, with a wide goal in the chain,
    floored at the base config, disabled by threshold 0."""
    from cruise_control_tpu.analyzer.goals import (
        RackAwareGoal, TopicReplicaDistributionGoal,
    )
    from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )

    assert TopicReplicaDistributionGoal().prefers_wide_batches
    # r4: RackAwareGoal joined the wide-batch class (validated at 1k:
    # rounds 145 -> 38, balancedness + violated set unchanged).
    assert RackAwareGoal().prefers_wide_batches
    from cruise_control_tpu.analyzer.goals import CpuCapacityGoal
    assert not CpuCapacityGoal().prefers_wide_batches
    opt = GoalOptimizer(CruiseControlConfig())
    base = SearchConfig(num_sources=256, num_dests=250, moves_per_round=500,
                        max_rounds=2000)
    chain = [RackAwareGoal(), TopicReplicaDistributionGoal()]
    wide = opt._wide_config(base, chain, num_brokers=1000)
    # r4: wide sources = min(2048, base x multiplier(8), B) — width beyond
    # ~B only inflates per-round cost (measured, optimizer._widen).
    assert wide.num_sources == 1000 and wide.moves_per_round == 1000
    assert wide.num_dests == base.num_dests
    assert opt._wide_config(base, chain, num_brokers=7000).num_sources == 2048
    # Below the regime threshold / no wide goal in the chain -> None.
    assert opt._wide_config(base, chain, num_brokers=100) is None
    assert opt._wide_config(base, [CpuCapacityGoal()], 1000) is None
    # An operator-raised base can never exceed the "wide" config.
    big = SearchConfig(num_sources=2048, num_dests=250, moves_per_round=4096,
                       max_rounds=2000)
    wide = opt._wide_config(big, chain, num_brokers=1000)
    assert wide.num_sources >= big.num_sources
    assert wide.moves_per_round >= big.moves_per_round
    # Threshold 0 disables wide batches entirely.
    opt_off = GoalOptimizer(CruiseControlConfig(
        {"solver.wide.batch.min.brokers": "0"}))
    assert opt_off._wide_config(base, chain, num_brokers=5000) is None
