"""Always-hot solver (round 18): warm-start seeds + quality fallback,
fingerprint goal skipping, and per-shape AOT prewarm.

The load-bearing contracts:

- fingerprint-skip ON vs OFF is BYTE-IDENTICAL at two padded bucket
  shapes (a violation-free goal applies nothing; the skip only removes
  its dispatches);
- a warm-seeded solve either matches the cold path's quality (sentry
  band) or demonstrably falls back to a cold solve — the served
  proposals are then the cold solve's, the fallback is counted, and the
  stale seed is dropped;
- the prewarm manager is idempotent and double-start safe, and its
  compiles hit the SAME jit cache keys the production paths use;
- the round-10 persistent dispatch controllers keep their (P, B, batch)
  keying across warm-seeded passes.
"""

import dataclasses
import tempfile

import numpy as np
import pytest

from cruise_control_tpu import warmstart
from cruise_control_tpu.analyzer.constraint import OptimizationOptions
from cruise_control_tpu.analyzer.optimizer import (
    GoalOptimizer, goals_by_priority,
)
from cruise_control_tpu.config.cruise_control_config import CruiseControlConfig
from cruise_control_tpu.model.fixtures import random_cluster
from cruise_control_tpu.utils.sensors import SENSORS


def _cluster(partition_bucket: int = 0):
    return random_cluster(num_brokers=12, num_topics=6, num_partitions=96,
                          rf=2, num_racks=3, seed=3, skew_to_first=2.0,
                          partition_bucket=partition_bucket)


def _optimizer(fingerprint: bool, **extra) -> GoalOptimizer:
    return GoalOptimizer(CruiseControlConfig({
        "solver.chain.fused": False,
        "max.solver.rounds": 60,
        "solver.fingerprint.skip.enabled": fingerprint,
        **extra}))


def _counter(name: str) -> float:
    return SENSORS._counters.get((name, ()), 0.0)


# ---------------------------------------------------------------------------
# Fingerprint goal skipping

# Two pinned padded bucket shapes: 32 keeps P=96 unpadded, 128 pads to
# 128 rows (the acceptance-criteria byte-parity pin).
@pytest.mark.parametrize("bucket", [32, 128])
def test_fingerprint_skip_byte_parity(bucket):
    state, meta = _cluster(partition_bucket=bucket)
    chain = goals_by_priority(CruiseControlConfig())
    opts = OptimizationOptions()
    f_on, r_on = _optimizer(True).optimizations(state, meta, chain, opts)
    f_off, r_off = _optimizer(False).optimizations(state, meta, chain, opts)
    np.testing.assert_array_equal(np.asarray(f_on.assignment),
                                  np.asarray(f_off.assignment))
    np.testing.assert_array_equal(np.asarray(f_on.leader_slot),
                                  np.asarray(f_off.leader_slot))
    assert [g.name for g in r_on.goal_results] \
        == [g.name for g in r_off.goal_results]
    for a, b in zip(r_on.goal_results, r_off.goal_results):
        assert (a.rounds, a.moves_applied, a.succeeded) \
            == (b.rounds, b.moves_applied, b.succeeded)
    assert r_on.violated_goals_after == r_off.violated_goals_after
    assert r_on.balancedness_after == r_off.balancedness_after


@pytest.mark.parametrize("bucket", [32, 128])
def test_fingerprint_skip_bounded_path_parity(bucket):
    """Same pin on the BOUNDED dispatch path (fused gate exceeded — the
    at-scale production path the skip was built for)."""
    state, meta = _cluster(partition_bucket=bucket)
    chain = goals_by_priority(CruiseControlConfig())
    opts = OptimizationOptions()
    f_on, _ = _optimizer(
        True, **{"solver.chain.fused": True,
                 "solver.fused.chain.max.brokers": 4}).optimizations(
        state, meta, chain, opts)
    f_off, _ = _optimizer(
        False, **{"solver.chain.fused": True,
                  "solver.fused.chain.max.brokers": 4}).optimizations(
        state, meta, chain, opts)
    np.testing.assert_array_equal(np.asarray(f_on.assignment),
                                  np.asarray(f_off.assignment))
    np.testing.assert_array_equal(np.asarray(f_on.leader_slot),
                                  np.asarray(f_off.leader_slot))


def test_fingerprint_skip_converged_state_costs_one_stats_program():
    """Re-solving an already-converged state: every satisfiable goal
    skips off the ONE batched snapshot — dispatch count collapses vs the
    skip-off arm, and the skipped goals are accounted."""
    state, meta = _cluster()
    chain = goals_by_priority(CruiseControlConfig())
    opt = _optimizer(True)
    final, res = opt.optimizations(state, meta, chain, OptimizationOptions())
    opt.optimizations(final, meta, chain, OptimizationOptions())
    with_skip = opt.last_dispatch_stats()
    opt_off = _optimizer(False)
    f2, _ = opt_off.optimizations(final, meta, chain, OptimizationOptions())
    without = opt_off.last_dispatch_stats()
    np.testing.assert_array_equal(np.asarray(f2.assignment),
                                  np.asarray(final.assignment))
    assert with_skip.get("goals_skipped", 0) > 0
    assert with_skip["dispatch_count"] <= without["dispatch_count"]
    assert "violation_fingerprint" in with_skip


def test_fingerprint_skip_megabatch_parity():
    """Batched twin: skip ON vs OFF is byte-identical per cluster at
    occupancy 2 (pad slot included)."""
    state, meta = _cluster()
    chain = goals_by_priority(CruiseControlConfig())
    items = [(state, meta, "a", None), (state, meta, "b", None)]
    out_on = _optimizer(True).optimizations_megabatch(
        items, goals=chain, width=4)
    out_off = _optimizer(False).optimizations_megabatch(
        items, goals=chain, width=4)
    for (fa, ra), (fb, rb) in zip(out_on, out_off):
        np.testing.assert_array_equal(np.asarray(fa.assignment),
                                      np.asarray(fb.assignment))
        assert ra.violated_goals_after == rb.violated_goals_after


@pytest.mark.slow  # ~18 s: full 5-tuple megabatch warm solve; the
# fingerprint-skip megabatch parity pin stays tier-1.
def test_megabatch_warm_item_diffs_and_reports_from_true_initial():
    """A 5-tuple megabatch item (warm-seeded state + true initial)
    solves from the seed but reports proposals AND the before picture
    from reality — matching the serial warm contract, via the one
    batched snapshot."""
    state, meta = _cluster()
    chain = goals_by_priority(CruiseControlConfig())
    opt = _optimizer(True)
    out = opt.optimizations_megabatch([(state, meta, "c", None)],
                                      goals=chain, width=2)
    final, res = out[0]
    out2 = opt.optimizations_megabatch(
        [(final, meta, "c", None, state)], goals=chain, width=2)
    final2, res2 = out2[0]
    np.testing.assert_array_equal(np.asarray(final2.assignment),
                                  np.asarray(final.assignment))
    assert len(res2.proposals) == len(res.proposals)
    assert res2.violated_goals_before       # reality's violations
    assert res2.balancedness_before < 100.0


def test_violation_fingerprint_stability():
    v = np.array([0.0, 3.0, 1.25], dtype=np.float32)
    assert warmstart.violation_fingerprint(v) \
        == warmstart.violation_fingerprint([0.0, 3.0, 1.25])
    assert warmstart.violation_fingerprint(v) \
        != warmstart.violation_fingerprint([0.0, 3.0, 1.5])
    # f32 noise below the rounding quantum cannot flap the fingerprint
    assert warmstart.violation_fingerprint([1.0 + 1e-9]) \
        == warmstart.violation_fingerprint([1.0])


# ---------------------------------------------------------------------------
# Warm-start seeds

def test_warm_seed_store_validity():
    state, meta = _cluster()
    chain = goals_by_priority(CruiseControlConfig())
    opt = _optimizer(True)
    final, res = opt.optimizations(state, meta, chain, OptimizationOptions())
    store = warmstart.WarmSeedStore()
    store.store(final, meta, res)
    assert store.match(state, meta) is not None
    # Different padded shape -> invalid (and dropped)
    state2, meta2 = _cluster(partition_bucket=128)
    assert store.match(state2, meta2) is None
    assert store.match(state, meta) is None  # dropped on mismatch
    # Different partition index -> invalid
    store.store(final, meta, res)
    meta3 = dataclasses.replace(
        meta, partition_index=list(reversed(meta.partition_index)))
    assert store.match(state, meta3) is None


def test_warm_seeded_solve_matches_cold_fixed_point():
    """Seeding from the accepted target re-reaches the SAME fixed point
    with far fewer dispatches, and proposals still diff from reality."""
    state, meta = _cluster()
    chain = goals_by_priority(CruiseControlConfig())
    opt = _optimizer(True)
    final, res = opt.optimizations(state, meta, chain, OptimizationOptions())
    cold = opt.last_dispatch_stats()
    store = warmstart.WarmSeedStore()
    store.store(final, meta, res)
    seed = store.match(state, meta)
    warm_state = warmstart.apply_seed(state, seed)
    final2, res2 = opt.optimizations(warm_state, meta, chain,
                                     OptimizationOptions(),
                                     initial_state=state)
    warm = opt.last_dispatch_stats()
    np.testing.assert_array_equal(np.asarray(final2.assignment),
                                  np.asarray(final.assignment))
    # proposals are moves from REALITY (state), not from the seed
    assert len(res2.proposals) == len(res.proposals)
    assert warm["dispatch_count"] < cold["dispatch_count"]
    assert warm.get("goals_skipped", 0) > 0
    # ... and so is the BEFORE picture: the skewed initial's violations,
    # not the near-clean seeded search start's.
    assert res2.violated_goals_before
    assert res2.balancedness_before < 100.0


def _facade_cluster(extra_cfg=None):
    from cruise_control_tpu.common.resources import Resource
    from cruise_control_tpu.executor.admin import (
        InMemoryAdminBackend, PartitionState,
    )
    from cruise_control_tpu.executor.executor import Executor
    from cruise_control_tpu.facade import CruiseControl
    from cruise_control_tpu.monitor import LoadMonitor, StaticCapacityResolver
    from cruise_control_tpu.monitor.sampling import SyntheticSampler
    partitions = {}
    for t in range(2):
        for p in range(6):
            reps = (0, 1 + (t + p) % 3)
            partitions[(f"t{t}", p)] = PartitionState(
                f"t{t}", p, reps, reps[0], isr=reps)
    backend = InMemoryAdminBackend(partitions.values())
    cfg = CruiseControlConfig({
        "partition.metrics.window.ms": 1000,
        "num.partition.metrics.windows": 3,
        "min.valid.partition.ratio": 0.0,
        "anomaly.detection.interval.ms": 60_000,
        "max.solver.rounds": 40,
        "failed.brokers.file.path": "",
        **(extra_cfg or {})})
    caps = StaticCapacityResolver({}, {Resource.CPU: 100.0,
                                       Resource.DISK: 1e7,
                                       Resource.NW_IN: 1e6,
                                       Resource.NW_OUT: 1e6})
    monitor = LoadMonitor(cfg, backend, samplers=[SyntheticSampler()],
                          capacity_resolver=caps,
                          broker_racks={b: f"r{b % 2}" for b in range(8)})
    executor = Executor(backend, synchronous=True)
    cc = CruiseControl(cfg, backend, load_monitor=monitor, executor=executor)
    for k in range(1, 4):
        monitor.task_runner.run_sampling_once(end_ms=k * 1000)
    return cc, backend


def test_facade_warm_start_seeds_and_serves_same_quality():
    cc, _ = _facade_cluster({"solver.warm.start.enabled": True})
    cc_cold, _ = _facade_cluster()
    r1 = cc.proposals()
    seeded0 = _counter("solver_warm_seeded")
    r2 = cc.proposals(ignore_proposal_cache=True)
    assert _counter("solver_warm_seeded") > seeded0
    cold = cc_cold.proposals(ignore_proposal_cache=True)
    # The warm-served result is quality-band-equal to the cold path's.
    assert r2.optimizer_result.violated_goals_after \
        == cold.optimizer_result.violated_goals_after
    assert abs(r2.optimizer_result.balancedness_after
               - cold.optimizer_result.balancedness_after) <= 0.05


def test_facade_warm_fallback_on_adversarial_seed():
    """A seed whose accepted quality the warm solve cannot re-reach (the
    adversarial drift step, simulated by doctoring the accepted band)
    triggers the counted cold fallback, drops the seed, and serves the
    cold solve's proposals. Pre-check OFF here: this test pins the
    POST-SOLVE gate specifically (the round-19 pre-check would catch
    the doctored seed before the attempt — covered by
    test_warm_precheck_skips_band_worse_seed)."""
    cc, _ = _facade_cluster({"solver.warm.start.enabled": True,
                             "solver.warm.start.precheck.enabled": False})
    cc.proposals()                       # stores the first seed
    seed = cc._warm_seeds._seed
    assert seed is not None
    # Adversarial: demand a balancedness no warm solve can reach.
    cc._warm_seeds._seed = dataclasses.replace(
        seed, balancedness_after=seed.balancedness_after + 50.0,
        violated_after=frozenset())
    fallbacks0 = _counter("solver_warm_fallbacks")
    r = cc.proposals(ignore_proposal_cache=True)
    assert _counter("solver_warm_fallbacks") == fallbacks0 + 1
    cc_cold, _ = _facade_cluster()
    cold = cc_cold.proposals()
    assert sorted((p.topic, p.partition, p.new_replicas)
                  for p in r.proposals) \
        == sorted((p.topic, p.partition, p.new_replicas)
                  for p in cold.proposals)
    # The post-fallback stored seed reflects the COLD solve's quality.
    assert cc._warm_seeds._seed.balancedness_after \
        == cold.optimizer_result.balancedness_after


def test_warm_precheck_skips_band_worse_seed():
    """Round 19 warm-band pre-check (ROADMAP 3a tail): a seed that
    scores band-worse against the CURRENT loads is skipped BEFORE the
    full warm chain — solver_warm_precheck_skips counts it, no warm
    attempt+fallback is paid — and the served proposals are byte-equal
    to the pre-check-off fallback path's (both serve the cold solve)."""
    overrides = {"solver.warm.start.enabled": True}
    cc_on, _ = _facade_cluster(overrides)
    cc_off, _ = _facade_cluster({**overrides,
                                 "solver.warm.start.precheck.enabled":
                                 False})
    for cc in (cc_on, cc_off):
        cc.proposals()                   # store the first seed
        seed = cc._warm_seeds._seed
        assert seed is not None
        # Adversarial seed: an accepted band no re-solve can reach —
        # the pre-check's entry snapshot sees the violated set beyond
        # the (empty) reference and skips; the post-solve gate would
        # pay attempt+fallback for the same verdict.
        cc._warm_seeds._seed = dataclasses.replace(
            seed, balancedness_after=seed.balancedness_after + 50.0,
            violated_after=frozenset())
    skips0 = _counter("solver_warm_precheck_skips")
    fallbacks0 = _counter("solver_warm_fallbacks")
    r_on = cc_on.proposals(ignore_proposal_cache=True)
    assert _counter("solver_warm_precheck_skips") == skips0 + 1
    assert _counter("solver_warm_fallbacks") == fallbacks0  # no attempt
    assert cc_on._warm_seeds._seed is not None  # cold result re-seeded
    r_off = cc_off.proposals(ignore_proposal_cache=True)
    assert _counter("solver_warm_fallbacks") == fallbacks0 + 1
    # Byte-equal served quality: pre-check skip == post-solve fallback.
    assert sorted((p.topic, p.partition, p.new_replicas, p.new_leader)
                  for p in r_on.proposals) \
        == sorted((p.topic, p.partition, p.new_replicas, p.new_leader)
                  for p in r_off.proposals)
    assert r_on.optimizer_result.balancedness_after \
        == r_off.optimizer_result.balancedness_after


def test_warm_precheck_passes_in_band_seed():
    """A seed still inside the band (the refresh case: unchanged model)
    is NOT skipped by the pre-check — the warm attempt proceeds and
    serves gate-equal quality."""
    cc, _ = _facade_cluster({"solver.warm.start.enabled": True})
    cc.proposals()
    skips0 = _counter("solver_warm_precheck_skips")
    seeded0 = _counter("solver_warm_seeded")
    r = cc.proposals(ignore_proposal_cache=True)
    assert _counter("solver_warm_seeded") > seeded0
    assert _counter("solver_warm_precheck_skips") == skips0
    assert r.optimizer_result is not None


def test_warm_reference_is_sticky_and_scoped_to_default_chain():
    """(a) Gate-passing warm solves may not lower the quality reference
    (no band-per-tick ratchet: only a cold solve re-anchors it); (b)
    non-default-chain operations neither consume nor store seeds (their
    solve classes are incomparable with the canonical precompute)."""
    cc, _ = _facade_cluster({"solver.warm.start.enabled": True})
    cc.proposals()
    ref0 = cc._warm_seeds._seed.balancedness_after
    # Inflate the reference within the band: the next warm solve passes
    # the gate but must NOT pull the reference down to its own result.
    seed = cc._warm_seeds._seed
    cc._warm_seeds._seed = dataclasses.replace(
        seed, balancedness_after=ref0 + 0.04)
    cc.proposals(ignore_proposal_cache=True)
    assert cc._warm_seeds._seed.balancedness_after >= ref0 + 0.04
    # Custom-chain / broker-scoped operations leave the seed untouched
    # and are never warm-seeded themselves.
    before = cc._warm_seeds._seed
    seeded0 = _counter("solver_warm_seeded")
    cc.rebalance(goals=["ReplicaDistributionGoal"], dryrun=True)
    assert cc._warm_seeds._seed is before
    assert _counter("solver_warm_seeded") == seeded0


def test_precompute_seams_carry_warm_seed_and_quality_gate():
    cc, _ = _facade_cluster({"solver.warm.start.enabled": True})
    out = cc.precompute_inputs()
    assert len(out) == 6 and out[5] is None   # cold: no initial
    chain, state, meta, options, gen = out[:5]
    final, result = cc.optimizer.optimizations(state, meta, chain, options)
    cc.store_precomputed(gen, result, final_state=final)
    with cc._proposal_lock:
        assert cc._proposal_cache is not None
    # Second round: seeded inputs carry the true initial separately.
    out2 = cc.precompute_inputs()
    assert out2[5] is not None
    # Quality gate: a below-band result is NOT stored; the cold re-solve
    # is stored instead and the fallback counted.
    bad = dataclasses.replace(result, balancedness_after=0.0)
    fallbacks0 = _counter("solver_warm_fallbacks")
    cc.store_precomputed(gen, bad, final_state=final)
    assert _counter("solver_warm_fallbacks") == fallbacks0 + 1
    with cc._proposal_lock:
        stored = cc._proposal_cache[2]
    assert stored.balancedness_after == result.balancedness_after


def test_controllers_persist_across_warm_seeded_passes():
    """Round 10's (P, B, batch) controller keying is unchanged by warm
    seeding: the warm pass reuses the SAME persistent AdaptiveDispatch
    pair its shape learned on the cold pass."""
    state, meta = _cluster()
    chain = goals_by_priority(CruiseControlConfig())
    opt = _optimizer(True, **{"solver.chain.fused": True,
                              "solver.fused.chain.max.brokers": 4})
    final, res = opt.optimizations(state, meta, chain, OptimizationOptions())
    keys = set(opt._controllers)
    pair_ids = {k: (id(v[0]), id(v[1])) for k, v in opt._controllers.items()}
    store = warmstart.WarmSeedStore()
    store.store(final, meta, res)
    warm_state = warmstart.apply_seed(state, store.match(state, meta))
    opt.optimizations(warm_state, meta, chain, OptimizationOptions(),
                      initial_state=state)
    assert set(opt._controllers) == keys
    assert {k: (id(v[0]), id(v[1]))
            for k, v in opt._controllers.items()} == pair_ids


# ---------------------------------------------------------------------------
# Prewarm

_SMALL_GOALS = "ReplicaDistributionGoal,PreferredLeaderElectionGoal"


def _prewarm_cfg(tmp, **extra):
    return CruiseControlConfig({
        "solver.prewarm.enabled": True,
        "solver.compile.cache.dir": tmp,
        "goals": _SMALL_GOALS,
        "hard.goals": "",
        "anomaly.detection.goals": _SMALL_GOALS,
        "self.healing.goals": "",
        "max.solver.rounds": 20,
        **extra})


def test_prewarm_records_shapes_and_is_idempotent():
    tmp = tempfile.mkdtemp()
    cfg = _prewarm_cfg(tmp)
    opt = GoalOptimizer(cfg)
    mgr = warmstart.ensure_prewarm(opt, cfg, start=False)
    assert mgr is not None
    state, meta = _cluster()
    chain = goals_by_priority(cfg)
    opt.optimizations(state, meta, chain, OptimizationOptions())
    entries = mgr.registry.entries()
    assert len(entries) == 1
    assert entries[0]["goals"] == _SMALL_GOALS.split(",")
    # Re-solving the same shape records nothing new.
    opt.optimizations(state, meta, chain, OptimizationOptions())
    assert len(mgr.registry.entries()) == 1
    # ensure_prewarm is one-manager-per-optimizer.
    assert warmstart.ensure_prewarm(opt, cfg, start=False) is mgr
    # Double-start safety: first start wins, the rest are no-ops.
    assert mgr.start() is True
    assert mgr.start() is False
    mgr.join(timeout=300)
    st = mgr.status_dict()
    assert st["state"] == "done"
    assert st["shapesDone"] == 1 and st["shapesFailed"] == 0
    assert mgr.start() is False          # done managers never re-run
    assert warmstart.prewarm_status(opt)["state"] == "done"


def test_prewarm_compiles_hit_production_cache_keys():
    """A prewarmed process's first real solve re-compiles NOTHING: the
    prewarm executions populate the exact jit cache entries the
    production path dispatches (verified via the module-level jit cache
    size, which is shared process-wide)."""
    from cruise_control_tpu.analyzer import chain as chainmod
    tmp = tempfile.mkdtemp()
    cfg = _prewarm_cfg(tmp, **{"solver.chain.fused": True,
                               "solver.fused.chain.max.brokers": 4})
    opt = GoalOptimizer(cfg)
    mgr = warmstart.ensure_prewarm(opt, cfg, start=False)
    state, meta = _cluster()
    chain = goals_by_priority(cfg)
    opt.optimizations(state, meta, chain, OptimizationOptions())

    def sizes():
        return (chainmod.chain_optimize_rounds._cache_size(),
                chainmod.chain_swap_rounds._cache_size(),
                chainmod.chain_goal_stats._cache_size(),
                chainmod.chain_all_goal_stats._cache_size())

    sizes0 = sizes()
    assert opt.prewarm_shape(mgr.registry.entries()[0]) is True
    sizes1 = sizes()
    # Prewarm re-used the solve's move-driver/stats programs exactly
    # (it may additionally warm kernels THIS solve skipped, e.g. the
    # swap driver of a swap-less chain — a superset, never a mismatch).
    assert sizes1[0] == sizes0[0]
    assert sizes1[2] == sizes0[2] and sizes1[3] == sizes0[3]
    # And after prewarm, a fresh solve of the shape compiles NOTHING.
    opt.optimizations(state, meta, chain, OptimizationOptions())
    assert sizes() == sizes1, "a post-prewarm solve still compiled"


def test_prewarm_skips_unknown_goal_entries():
    tmp = tempfile.mkdtemp()
    cfg = _prewarm_cfg(tmp)
    opt = GoalOptimizer(cfg)
    state, meta = _cluster()
    masks_entry = warmstart.shape_signature(
        state, meta.num_topics,
        goals_by_priority(cfg), _empty_masks(), 0)
    masks_entry["goals"] = ["NoSuchGoal"]
    assert opt.prewarm_shape(masks_entry) is False


def _empty_masks():
    from cruise_control_tpu.analyzer.search import ExclusionMasks
    return ExclusionMasks()


# ---------------------------------------------------------------------------
# Round-20 prewarm extensions: bound-state goal chains and mesh-sharded
# solvers (the two documented round-18 gaps).

def test_goal_spec_round_trips_bound_state():
    import json

    from cruise_control_tpu.analyzer.goals import (
        ALL_GOALS, BrokerSetAwareGoal, ReplicaDistributionGoal,
    )
    # Default-constructible goals keep the compact name-string spec.
    assert warmstart.goal_spec(ReplicaDistributionGoal()) \
        == "ReplicaDistributionGoal"
    assert warmstart.goal_from_spec("ReplicaDistributionGoal", ALL_GOALS) \
        == ReplicaDistributionGoal()
    # Bound state records a {"name", "state"} dict that survives the
    # registry's JSON persistence and rebuilds an EQUAL instance.
    bound = BrokerSetAwareGoal(broker_sets=(0, 0, 1, 1))
    spec = warmstart.goal_spec(bound)
    assert isinstance(spec, dict) and spec["name"] == "BrokerSetAwareGoal"
    spec = json.loads(json.dumps(spec))
    assert warmstart.goal_from_spec(spec, ALL_GOALS) == bound
    with pytest.raises(KeyError):
        warmstart.goal_from_spec("NoSuchGoal", ALL_GOALS)
    with pytest.raises(KeyError):
        warmstart.goal_from_spec({"name": "NoSuchGoal", "state": {}},
                                 ALL_GOALS)


def test_prewarm_covers_bound_broker_set_chains():
    """A chain carrying a BOUND BrokerSetAwareGoal (the round-18
    documented gap) records a reproducible signature and prewarms."""
    import json

    from cruise_control_tpu.analyzer.goals import BrokerSetAwareGoal
    tmp = tempfile.mkdtemp()
    cfg = _prewarm_cfg(tmp)
    opt = GoalOptimizer(cfg)
    state, meta = _cluster()
    chain = tuple(goals_by_priority(cfg)) + (
        BrokerSetAwareGoal(
            broker_sets=tuple(i % 2 for i in range(state.num_brokers))),)
    entry = warmstart.shape_signature(state, meta.num_topics, chain,
                                      _empty_masks(), 0)
    assert entry is not None
    assert any(isinstance(s, dict) for s in entry["goals"])
    # Through the registry's JSON persistence, as a fresh process would
    # load it.
    entry = json.loads(json.dumps(entry))
    assert opt.prewarm_shape(entry) is True


def test_prewarm_mesh_sharded_whole_chain():
    """A mesh optimizer prewarms the SHARDED chain program a production
    solve of the shape would run — the solve after prewarm builds no new
    program."""
    from cruise_control_tpu.parallel import chain_sharded, make_mesh
    tmp = tempfile.mkdtemp()
    cfg = _prewarm_cfg(tmp)
    opt = GoalOptimizer(cfg, mesh=make_mesh(8))
    state, meta = _cluster()               # 96 partitions: divides the mesh
    chain = goals_by_priority(cfg)
    entry = warmstart.shape_signature(state, meta.num_topics, chain,
                                      _empty_masks(), 0)
    assert opt.prewarm_shape(entry) is True
    programs = chain_sharded._make_chain_full.cache_info().currsize
    opt.optimizations(state, meta, chain, OptimizationOptions())
    assert chain_sharded._make_chain_full.cache_info().currsize \
        == programs, "post-prewarm mesh solve built a new chain program"
    # Megabatch entries stay single-device machinery under a mesh.
    assert opt.prewarm_shape(dict(entry, batch=4)) is False
    # A partition axis that does not divide the mesh falls back to the
    # single-device solver in _optimize — nothing to prewarm here.
    odd_state, odd_meta = random_cluster(num_brokers=12, num_topics=6,
                                         num_partitions=90, rf=2,
                                         num_racks=3, seed=3)
    odd = warmstart.shape_signature(odd_state, odd_meta.num_topics, chain,
                                    _empty_masks(), 0)
    assert opt.prewarm_shape(odd) is False


def test_prewarm_mesh_bounded_phase_kernels():
    """Past the fused-broker gate the mesh path dispatches per-goal phase
    kernels — the prewarm compiles that bounded set instead."""
    from cruise_control_tpu.parallel import chain_sharded, make_mesh
    cfg = _prewarm_cfg(tempfile.mkdtemp(),
                       **{"solver.fused.chain.max.brokers": 4})
    opt = GoalOptimizer(cfg, mesh=make_mesh(8))
    state, meta = _cluster()               # 12 brokers > the gate of 4
    entry = warmstart.shape_signature(state, meta.num_topics,
                                      goals_by_priority(cfg),
                                      _empty_masks(), 0)
    before = chain_sharded._make_chain_phase_kernels.cache_info().currsize
    assert opt.prewarm_shape(entry) is True
    assert chain_sharded._make_chain_phase_kernels.cache_info().currsize \
        == before + 1


def test_shape_registry_dedupes_and_persists():
    tmp = tempfile.mkdtemp()
    reg = warmstart.ShapeRegistry(f"{tmp}/shapes.json")
    entry = {"tensors": {"assignment": [[4, 2], "int32"]},
             "num_topics": 1, "goals": ["ReplicaDistributionGoal"],
             "mask_shapes": {}, "batch": 0}
    assert reg.record(entry) is True
    assert reg.record(dict(entry)) is False
    # A fresh registry object (fresh process) reloads the persisted set.
    reg2 = warmstart.ShapeRegistry(f"{tmp}/shapes.json")
    assert reg2.entries() == [entry]
    assert reg2.record(dict(entry)) is False


def test_facade_state_surfaces_prewarm_progress():
    tmp = tempfile.mkdtemp()
    cc, _ = _facade_cluster({"solver.prewarm.enabled": True,
                             "solver.compile.cache.dir": tmp,
                             "goals": _SMALL_GOALS,
                             "hard.goals": "",
                             "anomaly.detection.goals": _SMALL_GOALS,
                             "self.healing.goals": ""})
    try:
        cc.start_up(block_on_load=False, start_precompute=False)
        mgr = warmstart.prewarm_manager(cc.optimizer)
        assert mgr is not None
        mgr.join(timeout=300)
        body = cc.state(substates=("analyzer",))
        assert body["AnalyzerState"]["prewarm"]["state"] == "done"
    finally:
        cc.shutdown()


def test_pacer_defers_while_prewarm_running():
    from types import SimpleNamespace

    from cruise_control_tpu.fleet.scheduler import FleetScheduler
    tmp = tempfile.mkdtemp()
    cfg = _prewarm_cfg(tmp)
    opt = GoalOptimizer(cfg)
    mgr = warmstart.ensure_prewarm(opt, cfg, start=False)
    paced = []
    registry = SimpleNamespace(optimizer=opt, entries=lambda: paced)
    sched = FleetScheduler()
    sched.bind(registry)
    mgr._state = "running"
    assert sched.pace_once() == 0        # deferred, clusters untouched
    mgr._state = "done"
    assert sched.pace_once() == 0        # no clusters registered -> 0
