"""Futures engine (round 15): seeded scenario generation, batched
what-if evaluation, and the COMPARE_FUTURES serving surface.

The load-bearing contracts:

- Generator determinism: a sampled scenario is a pure function of
  ``(template, seed)`` — byte-identical event streams on re-sample.
- Batched == serial: a futures batch at ANY occupancy scores every
  future byte-identically to serial solves, and changing occupancy
  never compiles a new batched program (jit-cache-counter pinned).
- Ranked-answer determinism: the COMPARE_FUTURES body is byte-identical
  across repeated runs at one (templates, seed, ticks) request — no
  wall-clock-derived values anywhere in it.
- The endpoint is an async dry run: 202/200 + User-Task-ID semantics,
  never an execution, per-future flight passes on GET /solver.
"""

import json

import pytest

from cruise_control_tpu.analyzer.optimizer import GoalOptimizer
from cruise_control_tpu.futures.evaluator import (
    PRESENT, FutureSpec, compare_futures, evaluate_prepared, plan_futures,
    prepare_future, rank_results,
)
from cruise_control_tpu.futures.generator import (
    FUTURE_TEMPLATES, sample_future, sample_scenario,
)

TICKS = 6
WIDTH = 4


# ---------------------------------------------------------------------------
# Generator
# ---------------------------------------------------------------------------

def _event_stream(template: str, seed: int) -> str:
    spec = sample_scenario(template, seed)
    return json.dumps([e.as_dict() for e in spec.expand_events(0)],
                      sort_keys=True)


def test_templates_are_deterministic_and_seed_sensitive():
    for t in FUTURE_TEMPLATES:
        assert _event_stream(t, 3) == _event_stream(t, 3), t
        assert sample_scenario(t, 3).name == f"random:{t}:3"
    # Seeds actually change the sampled content somewhere.
    assert any(_event_stream(t, 1) != _event_stream(t, 2)
               for t in FUTURE_TEMPLATES)


def test_unknown_template_lists_valid_names():
    with pytest.raises(ValueError) as ei:
        sample_scenario("nope", 0)
    for t in FUTURE_TEMPLATES:
        assert t in str(ei.value)


def test_advance_events_rescale_and_filter_decision_content():
    cascade = sample_future("cascading_failures", 7)
    # Kills/revives are decision-point content for the evaluator: the
    # advance stream carries only load-shaping kinds.
    assert {e.kind for e in cascade.spec.events} \
        == {"kill_broker", "revive_broker"}
    assert cascade.advance_events(8) == ()
    assert len(cascade.remove_brokers) == 2
    churn = sample_future("churn_storm", 7)
    adv = churn.advance_events(8)
    assert adv, "churn must shape the advance"
    assert all(e.kind == "expand_partitions" for e in adv)
    assert all(0 <= e.tick < 8 for e in adv)


def test_plan_futures_round_robins_templates_and_seeds():
    plan = plan_futures(["load_ramp", "churn_storm"], 5, seed=4, ticks=TICKS)
    assert [(p.template, p.seed) for p in plan] == [
        ("load_ramp", 4), ("churn_storm", 4), ("load_ramp", 5),
        ("churn_storm", 5), ("load_ramp", 6)]
    with pytest.raises(ValueError, match="load_ramp"):
        plan_futures(["typo"], 2, 0, TICKS)
    # Duplicate template names dedupe (review finding: colliding future
    # ids would corrupt the ranked answer and double-solve).
    plan = plan_futures(["load_ramp", "load_ramp"], 2, seed=0, ticks=TICKS)
    assert [(p.template, p.seed) for p in plan] == [
        ("load_ramp", 0), ("load_ramp", 1)]
    assert len({p.future_id for p in plan}) == 2


def test_replay_spec_compresses_the_whole_story():
    """The bench's serial-replay baseline must see every sampled event
    inside the shortened horizon (plain truncation would drop late
    faults/maintenance and under-work the baseline)."""
    cascade = sample_future("cascading_failures", 7)
    spec = cascade.replay_spec(10)
    assert spec.ticks == 10
    assert {e.kind for e in spec.events} \
        == {e.kind for e in cascade.spec.events}
    assert len(spec.events) == len(cascade.spec.events)
    assert all(0 <= e.tick < 10 for e in spec.events)
    # Relative order of kill -> revive survives the compression.
    kills = [e.tick for e in spec.events if e.kind == "kill_broker"]
    revives = [e.tick for e in spec.events if e.kind == "revive_broker"]
    assert max(kills) <= min(revives)


# ---------------------------------------------------------------------------
# Batched evaluation: parity, occupancy, one program per shape
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def prepared_set():
    """Three futures + the present baseline, advanced once and shared by
    the parity tests (the twins are read-only inputs to the solves)."""
    specs = [FutureSpec("maintenance_plan", 1, TICKS),
             FutureSpec("load_ramp", 1, TICKS),
             FutureSpec("churn_storm", 1, TICKS),
             FutureSpec(PRESENT, 0, TICKS)]
    prepared = [prepare_future(fs) for fs in specs]
    optimizer = GoalOptimizer(prepared[0].config)
    return prepared, optimizer


def _scores(results) -> list[dict]:
    return [{"future": r.future_id, **r.score_dict()} for r in results]


@pytest.mark.slow  # ~20 s: serial-vs-batched at two occupancies; the
# batched-vs-serial decision parity stays tier-1 via the ranking tests.
def test_batched_matches_serial_at_two_occupancies_one_program(prepared_set):
    from cruise_control_tpu.analyzer.chain import megabatch_optimize_rounds
    prepared, optimizer = prepared_set
    serial = evaluate_prepared(prepared, optimizer, batched=False)
    full = evaluate_prepared(prepared, optimizer, width=WIDTH)
    cache_after_full = megabatch_optimize_rounds._cache_size()
    # Occupancy 1-of-4: one future only — inert pad slots fill the rest.
    padded = evaluate_prepared(prepared[:1], optimizer, width=WIDTH)
    # One compiled batched program per bucket shape serves BOTH
    # occupancies: the second run must not compile anything new.
    assert megabatch_optimize_rounds._cache_size() == cache_after_full
    assert _scores(full) == _scores(serial)
    assert _scores(padded) == _scores(serial)[:1]
    # The maintenance future's drained broker actually shaped its solve:
    # its per-future exclusion options rode the batched mask assembler.
    maint = full[0]
    assert maint.decision["removeBrokers"]
    assert maint.num_proposals > 0


def test_rank_is_deterministic_with_deltas(prepared_set):
    prepared, optimizer = prepared_set
    results = evaluate_prepared(prepared, optimizer, width=WIDTH)
    ranked = rank_results(results)
    assert [r.rank for r in ranked] == [1, 2, 3]
    assert all(r.future_id != PRESENT for r in ranked)
    # Ranked best-balancedness first (ties broken byte-stably).
    bals = [r.balancedness_after for r in ranked]
    assert bals == sorted(bals, reverse=True)
    for r in ranked:
        assert r.delta_vs_present is not None
        assert set(r.delta_vs_present) == {"balancednessAfter",
                                           "numProposals", "bytesToMoveMb"}


def test_compare_futures_body_is_byte_identical():
    kwargs = dict(templates=["maintenance_plan", "capacity_skew"],
                  num_futures=2, seed=1, ticks=TICKS, width=WIDTH)
    b1 = compare_futures(**kwargs)
    b2 = compare_futures(**kwargs)
    assert json.dumps(b1, sort_keys=True) == json.dumps(b2, sort_keys=True)
    assert b1["numFutures"] == 2
    assert [f["rank"] for f in b1["futures"]] == [1, 2]
    assert b1["present"]["future"] == PRESENT
    assert b1["dryrun"] is True and b1["executed"] is False
    # Every row is independently replayable:
    for f in b1["futures"]:
        assert f["future"] == f"{f['template']}:{f['seed']}"


# ---------------------------------------------------------------------------
# Serving surface: COMPARE_FUTURES + what_if=random:
# ---------------------------------------------------------------------------

@pytest.fixture()
def api_cc():
    from cruise_control_tpu.api.server import CruiseControlApi
    from cruise_control_tpu.common.resources import Resource
    from cruise_control_tpu.config.cruise_control_config import (
        CruiseControlConfig,
    )
    from cruise_control_tpu.executor.admin import (
        InMemoryAdminBackend, PartitionState,
    )
    from cruise_control_tpu.executor.executor import Executor
    from cruise_control_tpu.facade import CruiseControl
    from cruise_control_tpu.monitor import (
        LoadMonitor, StaticCapacityResolver,
    )
    from cruise_control_tpu.monitor.sampling import SyntheticSampler
    parts = {}
    for t in range(2):
        for p in range(6):
            reps = (0, 1 + (t + p) % 3)
            parts[(f"t{t}", p)] = PartitionState(f"t{t}", p, reps, reps[0],
                                                 isr=reps)
    backend = InMemoryAdminBackend(parts.values())
    cfg = CruiseControlConfig({
        "partition.metrics.window.ms": 1000,
        "num.partition.metrics.windows": 3,
        "min.valid.partition.ratio": 0.0,
        "failed.brokers.file.path": "",
        "futures.default.ticks": TICKS,
        "futures.max.count": 3,
        "futures.max.ticks": 20,
        "futures.batch.width": WIDTH})
    caps = StaticCapacityResolver({}, {Resource.CPU: 100.0,
                                       Resource.DISK: 1e7,
                                       Resource.NW_IN: 1e6,
                                       Resource.NW_OUT: 1e6})
    monitor = LoadMonitor(cfg, backend, samplers=[SyntheticSampler()],
                          capacity_resolver=caps)
    cc = CruiseControl(cfg, backend, load_monitor=monitor,
                       executor=Executor(backend, synchronous=True))
    for k in range(1, 4):
        monitor.task_runner.run_sampling_once(end_ms=k * 1000)
    api = CruiseControlApi(cc)
    api._async_wait_s = 300     # cover first-compile of the twin shapes
    yield api, cc
    api.shutdown()


def test_compare_futures_endpoint_serves_ranked_dry_run(api_cc):
    api, cc = api_cc
    before = cc.executor.execution_state()
    status, body, headers = api.handle(
        "GET", "/kafkacruisecontrol/compare_futures",
        f"templates=maintenance_plan,capacity_skew&num_futures=2"
        f"&seed=1&ticks={TICKS}")
    assert status == 200, body
    assert headers.get("User-Task-ID")
    assert body["numFutures"] == 2
    assert [f["rank"] for f in body["futures"]] == [1, 2]
    assert body["executed"] is False
    # A futures request never touches THIS cluster's executor.
    assert cc.executor.execution_state() == before
    # Per-future flight passes are addressable on GET /solver.
    fid = body["futures"][0]["future"]
    status, solver, _ = api.handle("GET", "/kafkacruisecontrol/solver",
                                   f"cluster=future:{fid}")
    assert status == 200
    assert solver["numPasses"] >= 1
    # Occupancy rode the futures_* sensors.
    from cruise_control_tpu.utils.sensors import SENSORS
    snap = SENSORS.histogram_snapshot("futures_batch_occupancy")
    assert snap is not None and sum(snap["counts"]) >= 1


def test_compare_futures_endpoint_rejects_unknown_template(api_cc):
    api, _cc = api_cc
    status, body, _ = api.handle(
        "GET", "/kafkacruisecontrol/compare_futures", "templates=nope")
    assert status == 400
    assert "maintenance_plan" in json.dumps(body)


def test_compare_futures_caps_are_enforced(api_cc):
    api, cc = api_cc
    status, body, _ = api.handle(
        "GET", "/kafkacruisecontrol/compare_futures",
        "templates=load_ramp&num_futures=500&ticks=10000&seed=0")
    assert status == 200, body
    assert body["numFutures"] <= cc.config.get_int("futures.max.count")
    assert body["ticks"] <= cc.config.get_int("futures.max.ticks")


def test_what_if_random_replays_sampled_scenario(api_cc):
    api, _cc = api_cc
    q = ("what_if=random:load_ramp:3&what_if_ticks=6&what_if_seed=1")
    status, b1, _ = api.handle("GET", "/kafkacruisecontrol/proposals", q)
    assert status == 200, b1
    assert b1["scenario"] == "random:load_ramp:3"
    assert b1["ticks"] == 6
    status, b2, _ = api.handle("GET", "/kafkacruisecontrol/proposals", q)
    assert json.dumps(b1["score"], sort_keys=True) \
        == json.dumps(b2["score"], sort_keys=True)


def test_what_if_random_unknown_template_is_400_listing_templates(api_cc):
    api, _cc = api_cc
    status, body, _ = api.handle("GET", "/kafkacruisecontrol/proposals",
                                 "what_if=random:nope:3")
    assert status == 400
    text = json.dumps(body)
    for t in FUTURE_TEMPLATES:
        assert t in text
    status, body, _ = api.handle("GET", "/kafkacruisecontrol/proposals",
                                 "what_if=random:load_ramp:abc")
    assert status == 400
    assert "not an integer" in json.dumps(body)


@pytest.mark.slow  # ~19 s: replays a capped-horizon scenario full-loop;
# the cap logic itself is a one-line clamp covered by the 400-path tests
def test_what_if_random_respects_tick_cap(api_cc):
    api, cc = api_cc
    cap = cc.config.get_int("scenario.what.if.max.ticks")
    status, body, _ = api.handle(
        "GET", "/kafkacruisecontrol/proposals",
        f"what_if=random:churn_storm:1&what_if_ticks={cap + 500}")
    assert status == 200, body
    assert body["ticks"] == cap


# ---------------------------------------------------------------------------
# Fleet coalescing: FuturesPayload through the MegabatchRunner
# ---------------------------------------------------------------------------

def test_futures_payload_rides_the_megabatch_runner(prepared_set):
    from concurrent.futures import Future
    from types import SimpleNamespace

    from cruise_control_tpu.fleet.megabatch import MegabatchRunner
    from cruise_control_tpu.futures.evaluator import FuturesPayload
    _prepared, optimizer = prepared_set
    runner = MegabatchRunner(optimizer, width=WIDTH)
    payload = FuturesPayload("c1", ["maintenance_plan", "load_ramp"], 2,
                             seed=1, ticks=TICKS)
    job = SimpleNamespace(payload=payload, future=Future())
    runner([job])
    body = job.future.result(timeout=0)
    assert body["numFutures"] == 2
    assert [f["rank"] for f in body["futures"]] == [1, 2]
    assert runner.stats()["clustersSolved"] >= 3  # 2 futures + present
    # The direct evaluator and the runner path agree byte-for-byte on
    # the ranked content (the runner's width differs only in padding).
    direct = compare_futures(templates=["maintenance_plan", "load_ramp"],
                             num_futures=2, seed=1, ticks=TICKS,
                             width=WIDTH)
    assert json.dumps(body["futures"], sort_keys=True) \
        == json.dumps(direct["futures"], sort_keys=True)


# ---------------------------------------------------------------------------
# Live-cluster seeding + the forecast_horizon template (round 19)
# ---------------------------------------------------------------------------

def test_forecast_horizon_excluded_from_default_expansion():
    """The live-only template must not change pinned default plans
    (bench ranked_order, the CI matrix): an empty templates request
    expands to the synthetic set only."""
    from cruise_control_tpu.futures.generator import DEFAULT_TEMPLATES
    assert "forecast_horizon" in FUTURE_TEMPLATES
    assert FUTURE_TEMPLATES["forecast_horizon"].requires_live
    assert "forecast_horizon" not in DEFAULT_TEMPLATES
    plan = plan_futures((), 12, seed=0, ticks=TICKS)
    assert all(p.template != "forecast_horizon" for p in plan)
    # Named explicitly it is valid.
    plan = plan_futures(["forecast_horizon"], 2, seed=0, ticks=TICKS)
    assert [p.template for p in plan] == ["forecast_horizon"] * 2


def test_forecast_horizon_requires_live_seam():
    with pytest.raises(ValueError, match="live"):
        prepare_future(FutureSpec("forecast_horizon", 0, TICKS))


def test_live_base_swaps_geometry_deterministically():
    """Samplers are pure in (template, seed, live geometry): the same
    live base yields byte-identical event streams, and the sampled spec
    carries the LIVE cluster's geometry, not BASE_SPEC's."""
    import dataclasses as _dc

    from cruise_control_tpu.futures.generator import BASE_SPEC
    live_base = _dc.replace(BASE_SPEC, num_brokers=4, num_topics=2,
                            partitions_per_topic=6, rf=2, num_racks=2)
    a = sample_future("cascading_failures", 5, base=live_base)
    b = sample_future("cascading_failures", 5, base=live_base)
    assert a.spec.num_brokers == 4 and a.spec.num_topics == 2
    assert json.dumps([e.as_dict() for e in a.spec.events]) \
        == json.dumps([e.as_dict() for e in b.spec.events])
    # Broker picks stay inside the live broker range.
    assert all(b_id < 4 for b_id in a.remove_brokers)
    # A different base geometry is a different (deterministic) sample.
    c = sample_future("cascading_failures", 5)
    assert c.spec.num_brokers == BASE_SPEC.num_brokers


def test_compare_futures_with_live_seed(api_cc):
    """End to end through the live seam: twins take the live cluster's
    geometry, forecast_horizon solves the live model under its (not
    ready here -> current) loads, and the body says liveSeeded."""
    from cruise_control_tpu.futures.evaluator import live_seed_from
    _api, cc = api_cc
    live = live_seed_from(cc)
    assert live is not None
    assert live.base.num_brokers == 4          # the fixture's cluster
    assert live.base.num_topics == 2
    body = compare_futures(
        templates=["forecast_horizon", "maintenance_plan"],
        num_futures=2, seed=0, ticks=TICKS, optimizer=cc.optimizer,
        width=WIDTH, live=live)
    assert body["liveSeeded"] is True
    futures = {f["future"]: f for f in body["futures"]}
    fh = futures["forecast_horizon:0"]
    # Engine off in this fixture: honest decision note, still ranked.
    assert fh["decision"]["forecastReady"] is False
    assert fh["rank"] in (1, 2)
    mp = futures["maintenance_plan:0"]
    assert all(b < 4 for b in mp["decision"]["removeBrokers"])
    # Disabled by config -> no live seam.
    cc.config._values["futures.live.seed.enabled"] = False
    try:
        assert live_seed_from(cc) is None
    finally:
        cc.config._values["futures.live.seed.enabled"] = True
